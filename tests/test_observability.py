"""Telemetry subsystem: metrics registry (host + device-resident),
span tracing / Chrome-trace export, exporters, engine stats, and the
bench JSONL schema."""

import json
import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import models, observability as obs, serving
from apex_tpu.observability import exporters


# -- host metrics ---------------------------------------------------------

def test_counter_gauge_basics():
    reg = obs.MetricsRegistry()
    c = reg.counter("c_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(7.0)
    assert g.value == 7.0
    # get-or-create returns the same object; kind clash raises
    assert reg.counter("c_total") is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("c_total")


def test_counter_labels_accumulate_separately():
    reg = obs.MetricsRegistry()
    c = reg.counter("bytes_total")
    c.labels(dtype="float32").inc(100)
    c.labels(dtype="bfloat16").inc(7)
    c.labels(dtype="float32").inc(1)
    assert c.labels(dtype="float32").value == 101
    assert c.labels(dtype="bfloat16").value == 7


def test_histogram_bucket_edges_le_semantics():
    """Prometheus ``le``: an observation exactly on an edge lands in
    that edge's bucket, strictly-greater goes to the next."""
    h = obs.Histogram("h", buckets=(1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.0000001, 2.0, 5.0, 5.1):
        h.observe(v)
    cum = h.cumulative()
    assert cum["1.0"] == 2          # 0.5 and exactly-1.0
    assert cum["2.0"] == 4          # + 1.0000001 and exactly-2.0
    assert cum["5.0"] == 5          # + exactly-5.0
    assert cum["+Inf"] == 6         # + 5.1 overflow
    assert h.count == 6
    assert h.sum == pytest.approx(14.6000001)
    s = h.summary()
    assert s["count"] == 6 and s["mean"] == pytest.approx(h.sum / 6)
    assert h.percentile(0.0) <= h.percentile(0.99) <= 5.0
    with pytest.raises(ValueError, match="increasing"):
        obs.Histogram("bad", buckets=(2.0, 1.0))


def test_histogram_empty_summary():
    h = obs.Histogram("h")
    assert h.summary() == {"count": 0, "sum": 0.0, "mean": None,
                           "p50": None, "p99": None}


def test_registry_thread_safety():
    reg = obs.MetricsRegistry()
    c = reg.counter("n")
    h = reg.histogram("lat", buckets=(0.5,))

    def work():
        for _ in range(1000):
            c.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000 and h.cumulative()["0.5"] == 8000


# -- device metrics -------------------------------------------------------

def test_device_counters_accumulate_under_jit_single_fetch(monkeypatch):
    dm = obs.DeviceMetrics(counters=("steps", "overflows"),
                           gauges=("scale",))
    st = dm.init()

    @jax.jit
    def step(st, ovf):
        st = dm.inc(st, "steps")
        st = dm.inc(st, "overflows", ovf)
        st = dm.set(st, "scale", 2.0 ** 10)
        return st

    for i in range(5):
        st = step(st, jnp.asarray(float(i == 2)))

    # counters stay on device until flush...
    assert all(isinstance(v, jax.Array) for v in st.values())
    # ...which is ONE device_get of the whole tree
    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda x: calls.append(1) or real(x))
    reg = obs.MetricsRegistry()
    vals = dm.flush(st, reg)
    assert len(calls) == 1
    assert vals["steps"] == 5.0 and vals["overflows"] == 1.0
    assert vals["scale"] == 2.0 ** 10
    # host registry now mirrors the device totals; repeated flushes are
    # idempotent (set_total, not +=)
    assert reg.counter("steps").value == 5.0
    dm.flush(st, reg)
    assert reg.counter("steps").value == 5.0


def test_device_metrics_jaxpr_is_host_transfer_free():
    dm = obs.DeviceMetrics(counters=("n",), histograms={"h": (1.0, 2.0)})
    st = dm.init()

    def step(st):
        st = dm.inc(st, "n", 3.0)
        st = dm.observe(st, "h", 1.5)
        return st

    jpr = jax.make_jaxpr(step)(st)
    prims = {e.primitive.name for e in jpr.jaxpr.eqns}
    assert not prims & {"pure_callback", "io_callback", "debug_callback",
                        "outfeed", "infeed", "device_put"}


def test_device_metrics_under_shard_map():
    """Per-device increments + an in-graph psum: the flushed counter is
    the global total, with the state replicated across the mesh."""
    dm = obs.DeviceMetrics(counters=("tokens",))
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    def step(st, x):
        return dm.inc(st, "tokens", lax.psum(jnp.sum(x), "data"))

    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False))
    st = dm.init()
    x = jnp.ones((8, 4), jnp.float32)
    for _ in range(3):
        st = mapped(st, x)
    assert dm.flush(st, obs.MetricsRegistry())["tokens"] == 3 * 32


def test_device_histogram_buckets():
    dm = obs.DeviceMetrics(histograms={"lat": (1.0, 2.0, 5.0)})
    st = dm.init()

    @jax.jit
    def step(st, v):
        return dm.observe(st, "lat", v)

    for v in (0.5, 1.0, 3.0, 100.0):
        st = step(st, jnp.asarray(v))
    reg = obs.MetricsRegistry()
    dm.flush(st, reg)
    h = reg.histogram("lat", buckets=(1.0, 2.0, 5.0))
    assert h.cumulative() == {"1.0": 2, "2.0": 2, "5.0": 3, "+Inf": 4}
    assert h.sum == pytest.approx(104.5)


def test_device_metrics_name_validation():
    dm = obs.DeviceMetrics(counters=("a",), gauges=("b",))
    st = dm.init()
    with pytest.raises(KeyError):
        dm.inc(st, "b")           # gauge is not a counter
    with pytest.raises(KeyError):
        dm.set(st, "nope", 1.0)
    with pytest.raises(ValueError, match="duplicate"):
        obs.DeviceMetrics(counters=("x",), gauges=("x",))


# -- tracing --------------------------------------------------------------

def test_chrome_trace_export_well_formed(tmp_path):
    rec = obs.SpanRecorder()
    with rec.span("outer", phase="test"):
        with rec.span("inner"):
            pass
    rec.event("mark", step=3)
    path = str(tmp_path / "trace.json")
    rec.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    assert [e["name"] for e in evs] == ["inner", "outer", "mark"]
    for e in evs:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], float) and e["ts"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    outer = evs[1]
    inner = evs[0]
    # nesting: inner lies within outer's span
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"phase": "test"}
    assert evs[2]["args"] == {"step": 3}


def test_jsonl_event_export(tmp_path):
    rec = obs.SpanRecorder()
    with rec.span("a"):
        pass
    rec.event("b")
    path = str(tmp_path / "events.jsonl")
    rec.export_jsonl(path)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert [ln["name"] for ln in lines] == ["a", "b"]
    rec.clear()
    assert rec.events() == []


def test_span_exception_safe():
    rec = obs.SpanRecorder()
    with pytest.raises(RuntimeError):
        with rec.span("boom"):
            raise RuntimeError("x")
    assert [e["name"] for e in rec.events()] == ["boom"]


# -- distributed-trace context (PR 6) --------------------------------------

def test_trace_ids_unique_and_span_ids_causal():
    ids = {obs.new_trace_id() for _ in range(100)}
    assert len(ids) == 100
    rec = obs.SpanRecorder()
    tid = obs.new_trace_id()
    with rec.span("root", trace_id=tid):
        with rec.span("child"):              # adopts ambient trace
            rec.event("leaf")
    evs = rec.trace(tid)
    assert [e["name"] for e in evs] == ["root", "child", "leaf"]
    root, child, leaf = evs
    # allocation order IS causal order: parent id < child id, and the
    # parent chain is exactly root <- child <- leaf
    assert root["span_id"] < child["span_id"] < leaf["span_id"]
    assert "parent_id" not in root
    assert child["parent_id"] == root["span_id"]
    assert leaf["parent_id"] == child["span_id"]
    assert all(e["trace_id"] == tid for e in evs)
    # trace() sorts causally even though the recorder appended the
    # parent's complete event AFTER its children
    raw = [e["name"] for e in rec.events()]
    assert raw == ["leaf", "child", "root"]


def test_explicit_trace_does_not_adopt_foreign_parent():
    """A new root with an explicit trace_id opened INSIDE another
    trace's span must stay parentless — adopting the ambient span
    would stitch two unrelated traces together."""
    rec = obs.SpanRecorder()
    with rec.span("outer", trace_id="trace-a"):
        with rec.span("rootb", trace_id="trace-b"):
            pass
    (b,) = rec.trace("trace-b")
    assert "parent_id" not in b
    # and events chained by explicit parent_id override the ambient
    with rec.span("outer2", trace_id="trace-a"):
        first = rec.event("e1", trace_id="trace-c")
        rec.event("e2", trace_id="trace-c", parent_id=first)
    e1, e2 = rec.trace("trace-c")
    assert "parent_id" not in e1
    assert e2["parent_id"] == e1["span_id"]


def test_span_parentage_thread_correct_under_pool():
    """Satellite 1 regression: spans emitted from ThreadPoolExecutor
    workers must parent on THEIR activated context, never on whatever
    span another worker has open concurrently (the ambient context is
    per-thread and reset on exit, so reused pool threads cannot
    inherit a stale parent)."""
    from concurrent.futures import ThreadPoolExecutor
    rec = obs.SpanRecorder()
    barrier = threading.Barrier(4, timeout=10)

    def work(k):
        tid = f"trace-{k}"
        root = rec.event("root", trace_id=tid)
        with rec.activate(tid, root):
            barrier.wait()               # all workers inside at once
            with rec.span("outer", item=k):
                with rec.span("inner", item=k):
                    rec.event("mark", item=k)
        return tid

    with ThreadPoolExecutor(max_workers=4) as pool:
        tids = list(pool.map(work, range(4)))
    for k, tid in enumerate(tids):
        evs = rec.trace(tid)
        assert [e["name"] for e in evs] == ["root", "outer", "inner",
                                            "mark"]
        ids = {e["span_id"] for e in evs}
        root, outer, inner, mark = evs
        # parentage stays inside the trace and follows the nesting
        assert outer["parent_id"] == root["span_id"]
        assert inner["parent_id"] == outer["span_id"]
        assert mark["parent_id"] == inner["span_id"]
        assert all(e.get("parent_id", root["span_id"]) in ids
                   for e in evs)
        assert all(e.get("args", {}).get("item", k) == k for e in evs)
    # pool threads are reused: after the activations exit, a span on
    # a reused worker has NO ambient trace
    with ThreadPoolExecutor(max_workers=1) as pool:
        pool.submit(lambda: None).result()
        assert pool.submit(obs.current_trace).result() is None


def test_maybe_span_gated_by_ambient_context():
    rec = obs.SpanRecorder()
    prev = obs.set_recorder(rec)
    try:
        with obs.maybe_span("hot"):          # no ambient: records nothing
            pass
        assert obs.maybe_event("tick") is None
        assert rec.events() == []
        with rec.activate("t-1", None):
            with obs.maybe_span("hot"):
                pass
            assert isinstance(obs.maybe_event("tick"), int)
        assert [e["name"] for e in rec.trace("t-1")] == ["hot", "tick"]
    finally:
        obs.set_recorder(prev)


def test_maybe_event_records_into_ambient_owner_recorder():
    """Span ids are PER-RECORDER: an ambient context minted by a
    private recorder must route maybe_span/maybe_event into THAT
    recorder — recording them into the default recorder would stamp a
    foreign parent id into its id space (dangling, or colliding with
    an unrelated span that happens to hold the same id)."""
    priv = obs.SpanRecorder()
    rec = obs.SpanRecorder()
    prev = obs.set_recorder(rec)
    try:
        rec.event("noise")                   # default id space advances
        with priv.span("outer", trace_id="t-priv"):
            sid = obs.maybe_event("inner")
        assert rec.events() == [  # default recorder: only its own noise
            e for e in rec.events() if e["name"] == "noise"]
        evs = priv.trace("t-priv")
        assert [e["name"] for e in evs] == ["outer", "inner"]
        inner = next(e for e in evs if e["name"] == "inner")
        outer = next(e for e in evs if e["name"] == "outer")
        assert inner["span_id"] == sid
        assert inner["parent_id"] == outer["span_id"]
        from apex_tpu.observability.exporters import (JsonlExporter,
                                                      validate_trace_record)
        assert validate_trace_record(
            JsonlExporter.enrich(priv.trace_record("t-priv"))) == []
        # and the default recorder's explicit event() never adopts a
        # foreign recorder's ambient parent
        with priv.span("outer2", trace_id="t-priv2"):
            rec.event("standalone")
        ev = [e for e in rec.events() if e["name"] == "standalone"][0]
        assert "parent_id" not in ev and "trace_id" not in ev
    finally:
        obs.set_recorder(prev)


def test_span_recorder_bounded_buffer():
    rec = obs.SpanRecorder(max_events=3)
    for i in range(10):
        rec.event(f"e{i}")
    assert [e["name"] for e in rec.events()] == ["e7", "e8", "e9"]
    # the process DEFAULT recorder is bounded too (flight-recorder
    # discipline: a fleet traces every request by default, and a
    # weeks-long process must hold the last N spans, not all of them)
    assert (obs.get_recorder()._events.maxlen
            == obs.tracing.DEFAULT_MAX_EVENTS)


# -- flight-recorder event ring (PR 6) -------------------------------------

def test_event_ring_bounded_seq_and_dump(tmp_path):
    ring = obs.EventRing(capacity=4)
    for i in range(7):
        ring.append("kind_a" if i % 2 == 0 else "kind_b", i=i)
    assert len(ring) == 4
    assert ring.total == 7 and ring.dropped == 3
    evs = ring.snapshot()
    # oldest-first, seq survives wraparound
    assert [e["seq"] for e in evs] == [3, 4, 5, 6]
    assert [e["i"] for e in ring.snapshot("kind_a")] == [4, 6]
    assert all(e["t"] >= 0 for e in evs)
    path = str(tmp_path / "flight.jsonl")
    ring.dump(path)
    with open(path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines[0] == {"kind": "flight_ring", "capacity": 4,
                        "total": 7, "dropped": 3}
    assert [ln["seq"] for ln in lines[1:]] == [3, 4, 5, 6]
    ring.clear()
    assert len(ring) == 0 and ring.total == 7    # seq keeps counting
    with pytest.raises(ValueError, match="capacity"):
        obs.EventRing(capacity=0)
    # process-default ring plumbing
    prev = obs.set_ring(obs.EventRing(capacity=2))
    try:
        from apex_tpu.observability import flightrec
        flightrec.record("x", a=1)
        assert obs.get_ring().snapshot()[0]["kind"] == "x"
    finally:
        obs.set_ring(prev)


def test_event_ring_thread_safe_appends():
    ring = obs.EventRing(capacity=10_000)
    def work():
        for i in range(500):
            ring.append("k", i=i)
    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ring.total == 4000
    assert sorted(e["seq"] for e in ring.snapshot()) == list(range(4000))


def test_amp_scaler_skip_lands_in_flight_ring():
    """A scaler skip (overflow -> step dropped) is a flight-recorder
    transition: record_scaler appends it to the process ring exactly
    once per newly observed skip."""
    from apex_tpu import amp, optimizers as opts
    from apex_tpu import nn

    class Lin(nn.Module):
        def init(self, key):
            return {"w": jnp.ones((4,), jnp.float32)}, ()

        def apply(self, p, x, state=(), train=False):
            return x * p["w"], state

    model, opt = amp.initialize(Lin(), opts.FusedAdam(1e-3),
                                opt_level="O2", half_dtype="float16",
                                verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    ring = obs.EventRing()
    prev = obs.set_ring(ring)
    try:
        reg = obs.MetricsRegistry()
        amp.record_scaler(ost, registry=reg, step=0)
        assert ring.snapshot("scaler_skip") == []      # no skip yet
        g = jax.tree_util.tree_map(
            lambda p: jnp.full(p.shape, jnp.inf, jnp.float32), params)
        _, ost2, _ = opt.step(params, ost, g)
        amp.record_scaler(ost2, registry=reg, step=1)
        (ev,) = ring.snapshot("scaler_skip")
        assert ev["steps_skipped"] == 1 and ev["step"] == 1
        assert ev["loss_scale"] == 2.0 ** 15
        # re-recording the SAME skip count appends nothing
        amp.record_scaler(ost2, registry=reg, step=2)
        assert len(ring.snapshot("scaler_skip")) == 1
        # a FRESH registry re-reports the cumulative total once — the
        # documented tradeoff: dedup is per registry, because any
        # process-global gate on totals would suppress a SECOND
        # optimizer's first skips (worse than a duplicate event)
        amp.record_scaler(ost2, registry=obs.MetricsRegistry(), step=3)
        evs = ring.snapshot("scaler_skip")
        assert len(evs) == 2 and evs[-1]["steps_skipped"] == 1
    finally:
        obs.set_ring(prev)


# -- step-time attribution (PR 6) ------------------------------------------

def test_steptime_attribution_decomposition_and_schema():
    """attribute_step on deterministic sleepers: the decomposition's
    internal identities (comm = step - compute, clamped; per-level
    times reassemble the isolated comm time; overlap in [0, 1]) hold
    and the resulting bench record passes the validator."""
    from apex_tpu.observability import steptime

    def sleeper(s):
        def fn():
            import time as _t
            _t.sleep(s)
            return jnp.ones((4,))
        return fn

    plan = [{"topology": "hierarchical", "comm_dtype": "float32",
             "ici_wire_bytes": 3000, "dcn_wire_bytes": 1000,
             "wire_bytes": 4000},
            {"topology": "flat", "wire_bytes": 4000}]
    att = steptime.attribute_step(sleeper(0.03), sleeper(0.018),
                                  sleeper(0.012), args=(), plan=plan,
                                  iters=2, warmup=0)
    for k in steptime.ATTRIBUTION_FIELDS:
        assert isinstance(att[k], float) and att[k] >= 0.0, k
    assert 0.0 <= att["overlap_fraction"] <= 1.0
    assert att["comm_ms"] == pytest.approx(
        max(att["step_ms"] - att["compute_ms"], 0.0), abs=2e-4)
    # the per-level split reassembles the isolated measurement and
    # follows the plan's byte weights (3000+4000 ici vs 1000 dcn);
    # fields are rounded to 4 decimals, hence the absolute tolerance
    assert att["ici_ms"] + att["dcn_ms"] == pytest.approx(
        att["comm_isolated_ms"], abs=2e-4)
    assert att["dcn_ms"] == pytest.approx(
        att["comm_isolated_ms"] * 1000 / 8000, abs=2e-4)
    assert len(att["buckets"]) == 2
    assert att["buckets"][1]["dcn_ms"] == 0.0    # flat bucket: all ici
    rec = exporters.JsonlExporter.enrich(
        {"metric": "train_step_attribution_hier", "value": att["step_ms"],
         "unit": "ms", "vs_baseline": None, "backend": "cpu", "ndev": 8,
         "arch": "cpu",
         **{k: att[k] for k in steptime.ATTRIBUTION_FIELDS},
         **{k: att[k] for k in steptime.OVERLAP_SCHEDULE_FIELDS}})
    assert exporters.validate_bench_record(rec) == []
    with pytest.raises(ValueError, match="iters"):
        steptime.blocked_time(sleeper(0.0), iters=0)


def test_attribution_measured_ici_step_zero_weight_level_folds():
    """A measured ici_step under a plan whose buckets carry no DCN
    bytes (single-fabric): the measured non-ici residue folds into the
    ici column instead of silently vanishing (a zero byte weight can't
    absorb time), so the record still reassembles comm_isolated_ms and
    passes the validator."""
    from apex_tpu.observability import steptime

    def sleeper(s):
        def fn():
            import time as _t
            _t.sleep(s)
            return jnp.ones((4,))
        return fn

    plan = [{"topology": "flat", "wire_bytes": 100}]
    att = steptime.attribute_step(sleeper(0.02), sleeper(0.012),
                                  sleeper(0.008), args=(), plan=plan,
                                  iters=2, warmup=0,
                                  ici_step=sleeper(0.003))
    assert att["dcn_ms"] == 0.0
    assert att["ici_ms"] == pytest.approx(att["comm_isolated_ms"],
                                          abs=2e-4)
    rec = exporters.JsonlExporter.enrich(
        {"metric": "train_step_attribution_flat", "value": att["step_ms"],
         "unit": "ms", "vs_baseline": None, "backend": "cpu", "ndev": 8,
         "arch": "cpu",
         **{k: att[k] for k in steptime.ATTRIBUTION_FIELDS},
         **{k: att[k] for k in steptime.OVERLAP_SCHEDULE_FIELDS}})
    assert exporters.validate_bench_record(rec) == []


def test_attribution_zero_weight_plan_still_reassembles():
    """A plan whose buckets carry NO recognized byte weight (no
    wire_bytes/bytes, or zero) can't label the per-level split — the
    fallback attributes everything to the ici column so ici+dcn still
    reassembles comm_isolated_ms and the record passes its own
    schema, instead of emitting ici=dcn=0 and failing it."""
    from apex_tpu.observability import steptime

    def sleeper(s):
        def fn():
            import time as _t
            _t.sleep(s)
            return jnp.ones((4,))
        return fn

    for plan in ([{"topology": "flat", "payload_bytes": 100}],
                 [{"topology": "flat", "wire_bytes": 0}]):
        att = steptime.attribute_step(sleeper(0.02), sleeper(0.012),
                                      sleeper(0.008), args=(),
                                      plan=plan, iters=2, warmup=0)
        assert att["dcn_ms"] == 0.0
        assert att["ici_ms"] == pytest.approx(att["comm_isolated_ms"],
                                              abs=2e-4)
        rec = exporters.JsonlExporter.enrich(
            {"metric": "train_step_attribution_flat",
             "value": att["step_ms"], "unit": "ms", "vs_baseline": None,
             "backend": "cpu", "ndev": 8, "arch": "cpu",
             **{k: att[k] for k in steptime.ATTRIBUTION_FIELDS},
             **{k: att[k]
                for k in steptime.OVERLAP_SCHEDULE_FIELDS}})
        assert exporters.validate_bench_record(rec) == []


def test_attribution_record_schema_mutations():
    """A record carrying overlap_fraction must be internally
    consistent: compute+comm reassemble the step, the level times
    reassemble the isolated comm, the fraction is a fraction."""
    base = exporters.JsonlExporter.enrich(
        {"metric": "train_step_attribution_flat", "value": 10.0,
         "unit": "ms", "vs_baseline": None, "backend": "cpu", "ndev": 8,
         "arch": "cpu", "step_ms": 10.0, "compute_ms": 6.0,
         "comm_ms": 4.0, "comm_isolated_ms": 5.0,
         "overlap_fraction": 0.2, "ici_ms": 4.0, "dcn_ms": 1.0,
         "overlap_mode": "reduce_after_backward", "n_stages": 1,
         "issue_order": [0]})
    assert exporters.validate_bench_record(base) == []
    bad = dict(base, overlap_fraction=1.5)
    assert any("overlap_fraction" in e
               for e in exporters.validate_bench_record(bad))
    bad = dict(base, comm_ms=-1.0)
    assert any(">= 0" in e for e in exporters.validate_bench_record(bad))
    bad = dict(base, compute_ms=1.0)       # 1 + 4 != 10
    assert any("inconsistent with step_ms" in e
               for e in exporters.validate_bench_record(bad))
    bad = dict(base, ici_ms=1.0)           # 1 + 1 != 5
    assert any("reassemble" in e
               for e in exporters.validate_bench_record(bad))
    missing = {k: v for k, v in base.items() if k != "dcn_ms"}
    assert any("dcn_ms" in e
               for e in exporters.validate_bench_record(missing))


def test_ddp_comm_enabled_compute_twin_is_collective_free():
    """comm_enabled=False (the step-time compute twin) elides every
    gradient collective while keeping the local average, so the twin
    graph is collective-free and its values are the local mean."""
    from apex_tpu import parallel
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    ddp = parallel.DistributedDataParallel()
    ddp.comm_enabled = False
    grads = {"a": jnp.ones((64,), jnp.float32)}

    def step(g):
        return ddp.allreduce_grads(g)

    mapped = jax.shard_map(step, mesh=mesh, in_specs=(P(),),
                           out_specs=P(), check_vma=False)
    txt = str(jax.make_jaxpr(mapped)(grads))
    assert not any(p in txt for p in ("psum", "all_gather",
                                      "reduce_scatter", "all_to_all",
                                      "ppermute")), txt
    out = jax.jit(mapped)(grads)
    # local gradient averaged by the axis size, no cross-replica sum
    assert float(out["a"][0]) == pytest.approx(1.0 / 8)
    assert ddp.last_comm_stats == []


def test_validate_trace_record_pins_causal_shape():
    """kind: trace records — the per-request flight record — must hold
    the causal invariants: unique positive span ids, parents strictly
    earlier, every span in the record's trace.  A violated parent
    order is exactly the worker-thread interleaving bug the schema
    exists to catch."""
    rec = obs.SpanRecorder()
    tid = obs.new_trace_id()
    root = rec.event("submit", trace_id=tid)
    with rec.activate(tid, root):
        with rec.span("dispatch"):
            rec.event("tick")
    good = exporters.JsonlExporter.enrich(rec.trace_record(tid))
    assert exporters.validate_trace_record(good) == []
    assert exporters.validate_telemetry_record(good) == []  # dispatch
    assert good["span_count"] == 3

    def bad(**mut):
        return exporters.validate_trace_record({**good, **mut})

    assert any("kind" in e for e in bad(kind="bench"))
    assert any("trace_id" in e for e in bad(trace_id=""))
    assert any("non-empty" in e for e in bad(spans=[], span_count=0))
    assert any("span_count" in e for e in bad(span_count=7))
    # a span whose parent is NOT causally earlier (the lost-chain bug)
    spans = [dict(s) for s in good["spans"]]
    spans[1]["parent_id"] = spans[2]["span_id"] + 5
    assert any("causally earlier" in e for e in bad(spans=spans))
    # duplicate span ids
    spans = [dict(s) for s in good["spans"]]
    spans[2]["span_id"] = spans[0]["span_id"]
    errs = bad(spans=spans)
    assert any("duplicate" in e or "causally" in e for e in errs)
    # a span smuggled in from another trace
    spans = [dict(s) for s in good["spans"]]
    spans[1]["trace_id"] = "other-trace"
    assert any("belongs to trace" in e for e in bad(spans=spans))
    spans = [dict(s) for s in good["spans"]]
    spans[0]["ph"] = "Z"
    assert any("ph" in e for e in bad(spans=spans))
    # the chain's head evicted (bounded recorder): the orphaned child
    # parents on a span that is NOT in the record — incomplete trace
    spans = [dict(s) for s in good["spans"][1:]]
    assert any("not in this record" in e
               for e in bad(spans=spans, span_count=len(spans)))
    assert exporters.validate_trace_record("nope") != []


def test_histogram_summary_cached_between_writes():
    """Satellite 2 pin: summary() memoizes until the next observation —
    a router reading Engine.stats() every tick pays the bucket-walk
    quantiles once per write, not once per read."""
    h = obs.Histogram("lat", buckets=(1.0, 2.0, 5.0))
    assert h._summary_computes == 0
    for v in (0.5, 1.5, 3.0):
        h.observe(v)
    first = h.summary()
    for _ in range(50):
        assert h.summary() == first
    assert h._summary_computes == 1          # 51 reads, ONE compute
    h.observe(4.0)                           # write invalidates
    s2 = h.summary()
    assert s2["count"] == 4 and s2 != first
    for _ in range(10):
        h.summary()
    assert h._summary_computes == 2
    # the cache returns copies — mutating a reader's dict is safe
    s2["p50"] = -1
    assert h.summary()["p50"] != -1
    assert h._summary_computes == 2
    # percentile() still answers directly (uncached path unchanged)
    assert h.percentile(0.5) == h.summary()["p50"]
    # _restore (DeviceMetrics flush) also invalidates
    h._restore([1, 0, 0, 0], 0.5)
    assert h.summary()["count"] == 1
    assert h._summary_computes == 3


# -- exporters ------------------------------------------------------------

def test_prometheus_text_exposition():
    reg = obs.MetricsRegistry()
    reg.counter("req_total", help="requests").inc(3)
    reg.gauge("depth").set(2)
    h = reg.histogram("lat", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    b = reg.counter("bytes_total")
    b.labels(dtype="float32").inc(64)
    text = exporters.prometheus_text(reg)
    assert "# HELP req_total requests" in text
    assert "# TYPE req_total counter" in text
    assert "req_total 3.0" in text
    assert "depth 2.0" in text
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="+Inf"} 2' in text
    assert "lat_count 2" in text
    assert 'bytes_total{dtype="float32"} 64.0' in text


def test_jsonl_exporter_enrich_and_emit(tmp_path):
    path = str(tmp_path / "out.jsonl")
    with exporters.JsonlExporter(path=path) as ex:
        line = ex.emit({"metric": "m", "value": 1.0, "unit": "x"})
        # replayed record keeps its own provenance
        replay = ex.emit({"metric": "m2", "value": 2.0, "stale": True,
                          "host": {"hostname": "cap", "pid": 1}})
    assert line["schema_version"] == exporters.SCHEMA_VERSION
    assert line["stale"] is False
    assert line["host"]["hostname"]
    assert replay["stale"] is True
    assert replay["host"] == {"hostname": "cap", "pid": 1}
    with open(path) as f:
        assert len(f.readlines()) == 2


def test_bench_record_schema_validation():
    good = exporters.JsonlExporter.enrich(
        {"metric": "m", "value": 1.5, "unit": "x", "vs_baseline": None,
         "backend": "cpu", "ndev": 8, "arch": "cpu"})
    assert exporters.validate_bench_record(good) == []
    # error lines (value null) are valid
    err_line = exporters.JsonlExporter.enrich(
        {"metric": "m", "value": None, "unit": None, "vs_baseline": None,
         "backend": "cpu", "ndev": 8, "arch": "cpu", "error": "boom"})
    assert exporters.validate_bench_record(err_line) == []
    # missing stale / wrong types are caught
    bad = dict(good)
    del bad["stale"]
    assert any("stale" in e for e in exporters.validate_bench_record(bad))
    bad = dict(good, value="fast")
    assert any("value" in e for e in exporters.validate_bench_record(bad))
    bad = dict(good, schema_version=0)
    assert any("schema_version" in e
               for e in exporters.validate_bench_record(bad))
    assert exporters.validate_bench_record([1, 2]) != []


def test_bench_record_schema_serving_decode_window_fields():
    """Fresh engine-decode lines must carry the decode-window fields
    (PR 2); stale replays of pre-window records and error lines stay
    valid without them."""
    base = {"metric": "gpt_tiny_engine_decode_throughput", "value": 9.0,
            "unit": "tokens/sec/chip", "vs_baseline": None,
            "backend": "cpu", "ndev": 8, "arch": "cpu",
            "kv_cache_bytes": 16384,    # required fresh at schema v3
            # required fresh at schema v8 (KV fragmentation pair)
            "kv_waste_bytes": 4096, "kv_utilization": 0.75,
            # required fresh at schema v10 (compile-plane triple)
            "cold_compile_ms": 350.0, "compiles_total": 2,
            "steady_state_retraces": 0,
            # required fresh at schema v12 (paged serving plane)
            "admission_mode": "fixed_slot"}
    good = exporters.JsonlExporter.enrich(
        dict(base, window=8, tokens_per_sync=7.5))
    assert exporters.validate_bench_record(good) == []
    # missing window on a fresh decode line is a schema violation
    missing = exporters.JsonlExporter.enrich(dict(base))
    assert any("window" in e
               for e in exporters.validate_bench_record(missing))
    # missing kv_cache_bytes on a fresh v3 decode line too (PR 8)
    nokv = {k: v for k, v in base.items() if k != "kv_cache_bytes"}
    assert any("kv_cache_bytes" in e
               for e in exporters.validate_bench_record(
                   exporters.JsonlExporter.enrich(dict(nokv, window=8))))
    # missing the fragmentation pair on a fresh v8 decode line (PR 13)
    for key in ("kv_waste_bytes", "kv_utilization"):
        nofrag = {k: v for k, v in base.items() if k != key}
        assert any(key in e
                   for e in exporters.validate_bench_record(
                       exporters.JsonlExporter.enrich(
                           dict(nofrag, window=8)))), key
    # ...but an archived v7 line without the pair stays valid at its
    # declared version, as does an archived v2 line without any of it
    v7 = exporters.JsonlExporter.enrich(
        dict({k: v for k, v in base.items()
              if k not in ("kv_waste_bytes", "kv_utilization")},
             window=8))
    v7["schema_version"] = 7
    assert exporters.validate_bench_record(v7) == []
    v2 = exporters.JsonlExporter.enrich(dict(nokv, window=8))
    v2["schema_version"] = 2
    assert exporters.validate_bench_record(v2) == []
    # wrong types / values are caught wherever the field appears
    for w in (0, -2, 1.5, True, "8"):
        bad = exporters.JsonlExporter.enrich(dict(base, window=w))
        assert any("window" in e
                   for e in exporters.validate_bench_record(bad)), w
    bad = exporters.JsonlExporter.enrich(
        dict(base, window=8, tokens_per_sync="lots"))
    assert any("tokens_per_sync" in e
               for e in exporters.validate_bench_record(bad))
    bad = exporters.JsonlExporter.enrich(
        dict(base, window=8, kv_cache_bytes=-5))
    assert any("kv_cache_bytes" in e
               for e in exporters.validate_bench_record(bad))
    bad = exporters.JsonlExporter.enrich(
        dict(base, window=8, kv_waste_bytes=999_999))   # > cache
    assert any("kv_waste_bytes" in e
               for e in exporters.validate_bench_record(bad))
    bad = exporters.JsonlExporter.enrich(
        dict(base, window=8, kv_utilization=1.2))
    assert any("kv_utilization" in e
               for e in exporters.validate_bench_record(bad))
    # a windowed line must report tokens/sec
    bad = exporters.JsonlExporter.enrich(
        dict(base, window=8, unit="steps/sec"))
    assert any("tokens/sec" in e
               for e in exporters.validate_bench_record(bad))
    # stale replay of an old (pre-window) record: exempt
    stale = exporters.JsonlExporter.enrich(dict(base), stale=True)
    assert exporters.validate_bench_record(stale) == []
    # error line for a hung decode config: exempt
    err = exporters.JsonlExporter.enrich(
        {"metric": "gpt_tiny_engine_decode_throughput", "value": None,
         "unit": None, "vs_baseline": None, "backend": "cpu",
         "ndev": 8, "arch": "cpu", "error": "config hung"})
    assert exporters.validate_bench_record(err) == []


def test_bench_emits_schema_valid_jsonl(tmp_path):
    """bench.py's emit/replay paths produce schema-valid lines: enrich a
    fresh line, save it to a record, and validate the stale replay."""
    import bench
    fresh = exporters.JsonlExporter.enrich(
        {"metric": bench.HEADLINE_METRIC, "value": 1830.0,
         "unit": "images/sec/chip", "vs_baseline": 11.7,
         "backend": "tpu", "ndev": 1, "arch": "TPU v5 lite",
         # schema-v3 cost-model fields every fresh train line carries
         "flops_per_step": 3.15e12, "achieved_tflops": 45.0,
         "mfu": 0.228, "peak_bytes": 9_000_000_000,
         # schema-v10 compile-plane triple (fresh train lines)
         "cold_compile_ms": 5400.0, "compiles_total": 1,
         "steady_state_retraces": 0})
    assert exporters.validate_bench_record(fresh) == []
    # the v3 requirement bites: a fresh train line without them flags
    bare = {k: v for k, v in fresh.items()
            if k not in ("flops_per_step", "achieved_tflops", "mfu",
                         "peak_bytes")}
    assert any("flops_per_step" in e
               for e in exporters.validate_bench_record(bare))
    # archived v2 train lines (and stale replays) stay valid
    v2 = dict(bare)
    v2["schema_version"] = 2
    assert exporters.validate_bench_record(v2) == []
    assert exporters.validate_bench_record(dict(bare, stale=True)) == []
    p = str(tmp_path / "rec.json")
    bench.save_tpu_record([fresh], path=p, now="2026-07-30T04:55:00Z")
    rec = bench.load_tpu_record(path=p)
    replayed = [exporters.JsonlExporter.enrich(ln)
                for ln in bench.stale_lines(rec)]
    assert exporters.validate_bench_jsonl(
        [json.dumps(ln) for ln in replayed]) == []
    assert replayed[-1]["stale"] is True
    assert replayed[-1]["metric"] == bench.HEADLINE_METRIC


def test_check_bench_schema_cli(tmp_path):
    """The tests/ci gate accepts a valid stream and rejects a broken
    one."""
    import subprocess
    import sys
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(root, "tests", "ci", "check_bench_schema.py")
    good = json.dumps(exporters.JsonlExporter.enrich(
        {"metric": "m", "value": 1.0, "unit": "x", "backend": "cpu",
         "ndev": 8, "arch": "cpu"}))
    r = subprocess.run([sys.executable, script], input=good + "\n",
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    r = subprocess.run([sys.executable, script],
                       input='{"metric": "m"}\n',
                       capture_output=True, text=True)
    assert r.returncode == 1


def _trend_round(tmp_path, name, lines):
    """One BENCH_r*.json runbook wrapper holding ``lines`` as its
    JSONL tail (what check_bench_trend.py parses)."""
    doc = {"n": name, "cmd": "python bench.py", "rc": 0,
           "tail": "\n".join(json.dumps(ln) for ln in lines)}
    with open(str(tmp_path / name), "w") as f:
        json.dump(doc, f)


def _run_trend(args):
    import subprocess
    import sys
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(root, "tests", "ci", "check_bench_trend.py")
    return subprocess.run([sys.executable, script] + args,
                          capture_output=True, text=True)


def test_check_bench_trend_gate(tmp_path):
    """The trend gate (acceptance pin): exit 0 on the real BENCH
    history (stale replays partitioned out, no fresh regression),
    nonzero on a synthetic history where a stale replay is presented
    as fresh progress OR a fresh accelerator metric regresses past
    tolerance — and 0 again when the same replay is properly marked
    ``stale: true``."""
    # the real r01-r05 history at the repo root must gate clean
    r = _run_trend([])
    assert r.returncode == 0, r.stderr
    assert "stale replays partitioned out" in r.stderr

    def tpu(value, **kw):
        return exporters.JsonlExporter.enrich(
            {"metric": "resnet18_fwd_bwd_throughput", "value": value,
             "unit": "images/sec/chip", "vs_baseline": None,
             "backend": "tpu", "ndev": 1, "arch": "TPU v5 lite", **kw})

    # replay presented as fresh progress: the wedge flag is in the
    # round but the replayed line lacks stale: true -> error
    d1 = tmp_path / "case1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [tpu(500.0)])
    _trend_round(d1, "BENCH_r02.json",
                 [exporters.JsonlExporter.enrich(
                     {"metric": ("TPU_TUNNEL_WEDGED_NO_FRESH_"
                                 "HARDWARE_NUMBERS"), "value": 1,
                      "unit": "flag", "vs_baseline": None,
                      "backend": "cpu", "ndev": 8, "arch": "cpu"}),
                  tpu(1830.0)])
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 1
    assert "replay presented as fresh" in r.stderr

    # byte-identical accelerator re-emission from an earlier round is
    # suspicious but not definitive (stable hardware can honestly
    # repeat a rounded value): WARNS without gating, and the line
    # stays out of the trend so it can't count as progress
    d2 = tmp_path / "case2"
    d2.mkdir()
    line = tpu(777.7)
    _trend_round(d2, "BENCH_r01.json", [line])
    _trend_round(d2, "BENCH_r02.json", [dict(line)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 0
    assert "byte-identical" in r.stderr and "WARNING" in r.stderr
    assert "1 fresh measurements counted" in r.stderr

    # fresh-vs-fresh accelerator regression past tolerance -> error
    d3 = tmp_path / "case3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [tpu(1000.0)])
    _trend_round(d3, "BENCH_r02.json", [tpu(600.0)])   # -40%
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 1 and "regressed" in r.stderr
    # ...within tolerance passes
    r = _run_trend(["--dir", str(d3), "--tol", "0.8"])
    assert r.returncode == 0
    # change is relative to the PREVIOUS value in both directions: a
    # 21% rate drop is under the 25% default tol and must not gate
    d3b = tmp_path / "case3b"
    d3b.mkdir()
    _trend_round(d3b, "BENCH_r01.json", [tpu(1000.0)])
    _trend_round(d3b, "BENCH_r02.json", [tpu(790.0)])  # -21%
    r = _run_trend(["--dir", str(d3b)])
    assert r.returncode == 0, r.stderr

    # the SAME replay properly marked stale: partitioned out, clean —
    # and it must NOT count as progress (no fresh line to compare)
    d4 = tmp_path / "case4"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json", [tpu(500.0)])
    _trend_round(d4, "BENCH_r02.json", [tpu(1830.0, stale=True)])
    r = _run_trend(["--dir", str(d4)])
    assert r.returncode == 0
    assert "1 stale replays partitioned out" in r.stderr

    # CPU smoke regressions warn but do not gate... unless --strict-cpu
    d5 = tmp_path / "case5"
    d5.mkdir()

    def cpu(value):
        return exporters.JsonlExporter.enrich(
            {"metric": "fused_lamb_step_time", "value": value,
             "unit": "ms", "vs_baseline": None, "backend": "cpu",
             "ndev": 8, "arch": "cpu"})
    _trend_round(d5, "BENCH_r01.json", [cpu(10.0)])
    _trend_round(d5, "BENCH_r02.json", [cpu(47.0)])
    r = _run_trend(["--dir", str(d5)])
    assert r.returncode == 0 and "WARNING" in r.stderr
    r = _run_trend(["--dir", str(d5), "--strict-cpu"])
    assert r.returncode == 1


def test_check_bench_trend_overlap_fields_gate(tmp_path):
    """The PR 14 trend columns: a fresh accelerator line whose
    overlap_fraction / measured_overlap_fraction DROPS past --tol (or
    whose comm_visible_ms GROWS past it) gates; CPU smoke warns; and a
    zero baseline — the reduce-after-backward world — never trends (no
    overlap yet means nothing to lose)."""
    def attr(backend, value, frac, visible):
        return exporters.JsonlExporter.enrich(
            {"metric": "train_step_attribution_overlap",
             "value": value, "unit": "ms", "vs_baseline": None,
             "backend": backend, "ndev": 8,
             "arch": "TPU v5 lite" if backend == "tpu" else "cpu",
             "overlap_fraction": frac, "comm_visible_ms": visible,
             "overlap_mode": "overlapped", "n_stages": 4,
             "issue_order": [3, 2, 1, 0]})

    # accelerator overlap_fraction drop past tol -> error
    d1 = tmp_path / "ovl1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [attr("tpu", 10.0, 0.8, 1.0)])
    _trend_round(d1, "BENCH_r02.json", [attr("tpu", 10.1, 0.3, 1.0)])
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 1
    assert "overlap_fraction dropped" in r.stderr
    # ...within tolerance passes
    r = _run_trend(["--dir", str(d1), "--tol", "0.7"])
    assert r.returncode == 0, r.stderr

    # accelerator comm_visible_ms growth past tol -> error
    d2 = tmp_path / "ovl2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [attr("tpu", 10.0, 0.8, 1.0)])
    _trend_round(d2, "BENCH_r02.json", [attr("tpu", 10.1, 0.8, 2.0)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 1
    assert "comm_visible_ms grew" in r.stderr

    # CPU smoke: warns only, unless --strict-cpu
    d3 = tmp_path / "ovl3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [attr("cpu", 10.0, 0.8, 1.0)])
    _trend_round(d3, "BENCH_r02.json", [attr("cpu", 10.1, 0.3, 1.0)])
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 0 and "WARNING" in r.stderr
    r = _run_trend(["--dir", str(d3), "--strict-cpu"])
    assert r.returncode == 1

    # zero baseline never trends: 0.0 -> 0.0 is today's world, and a
    # fraction appearing off zero is progress, not regression
    d4 = tmp_path / "ovl4"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json", [attr("tpu", 10.0, 0.0, 1.0)])
    _trend_round(d4, "BENCH_r02.json", [attr("tpu", 10.1, 0.0, 1.0)])
    _trend_round(d4, "BENCH_r03.json", [attr("tpu", 10.0, 0.6, 1.0)])
    r = _run_trend(["--dir", str(d4)])
    assert r.returncode == 0, r.stderr

    # ...but a LOWER-is-better time at 0 is the success state: comm
    # returning from fully hidden to measurably visible is the worst
    # regression the column exists for — gates even from a zero
    # baseline (rounding-noise returns under 0.05 ms do not)
    d4b = tmp_path / "ovl4b"
    d4b.mkdir()
    _trend_round(d4b, "BENCH_r01.json", [attr("tpu", 10.0, 0.9, 0.0)])
    _trend_round(d4b, "BENCH_r02.json", [attr("tpu", 10.1, 0.9, 4.0)])
    r = _run_trend(["--dir", str(d4b)])
    assert r.returncode == 1
    assert "returned from a zero baseline" in r.stderr
    d4c = tmp_path / "ovl4c"
    d4c.mkdir()
    _trend_round(d4c, "BENCH_r01.json", [attr("tpu", 10.0, 0.9, 0.0)])
    _trend_round(d4c, "BENCH_r02.json", [attr("tpu", 10.1, 0.9, 0.01)])
    r = _run_trend(["--dir", str(d4c)])
    assert r.returncode == 0, r.stderr

    # measured_overlap_fraction (profile metric lines) follows the
    # same policy
    def prof(value, frac):
        return exporters.JsonlExporter.enrich(
            {"metric": "comm_profile_overlap_comm_visible_ms",
             "value": value, "unit": "ms", "vs_baseline": None,
             "backend": "tpu", "ndev": 8, "arch": "TPU v5 lite",
             "measured_overlap_fraction": frac})
    d5 = tmp_path / "ovl5"
    d5.mkdir()
    _trend_round(d5, "BENCH_r01.json", [prof(1.0, 0.9)])
    _trend_round(d5, "BENCH_r02.json", [prof(1.05, 0.2)])
    r = _run_trend(["--dir", str(d5)])
    assert r.returncode == 1
    assert "measured_overlap_fraction dropped" in r.stderr


def test_check_bench_trend_memory_and_mfu_gate(tmp_path):
    """The PR 8 trend columns: peak-memory growth past --mem-tol gates
    on EVERY backend (the compiled plan is deterministic — CPU noise
    is no excuse), stale replays stay partitioned out, kind: memory
    records trend by entry point, and MFU drops follow the same
    accelerator-gates / CPU-warns policy as throughput."""

    def train(value, peak, mfu=None, backend="cpu", **kw):
        rec = {"metric": "resnet18_train_throughput", "value": value,
               "unit": "images/sec/chip", "vs_baseline": None,
               "backend": backend, "ndev": 8, "arch": backend,
               "peak_bytes": peak}
        if mfu is not None:
            rec["mfu"] = mfu
        return exporters.JsonlExporter.enrich({**rec, **kw})

    # peak-memory regression on a CPU backend: throughput noise warns,
    # but the 40% plan growth is an error
    d1 = tmp_path / "mem1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [train(100.0, 1_000_000)])
    _trend_round(d1, "BENCH_r02.json", [train(101.0, 1_400_000)])
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 1
    assert "peak memory grew 40%" in r.stderr
    # ...within a loosened --mem-tol it passes
    r = _run_trend(["--dir", str(d1), "--mem-tol", "0.5"])
    assert r.returncode == 0, r.stderr

    # a stale replay carrying a bigger peak is partitioned out
    d2 = tmp_path / "mem2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [train(100.0, 1_000_000)])
    _trend_round(d2, "BENCH_r02.json",
                 [train(100.0, 9_000_000, stale=True)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 0, r.stderr

    # kind: memory records trend by entry point
    def memrec(peak):
        return exporters.JsonlExporter.enrich(
            {"kind": "memory", "entry_point": "engine_step_k",
             "source": "compiled", "flops": 1e6, "backend": "cpu",
             "peak_bytes": peak})

    d3 = tmp_path / "mem3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [memrec(1_000_000)])
    _trend_round(d3, "BENCH_r02.json", [memrec(1_500_000)])
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 1 and "engine_step_k" in r.stderr
    # identical plans across rounds are the normal case: clean
    d3b = tmp_path / "mem3b"
    d3b.mkdir()
    _trend_round(d3b, "BENCH_r01.json", [memrec(1_000_000)])
    _trend_round(d3b, "BENCH_r02.json", [memrec(1_000_000)])
    assert _run_trend(["--dir", str(d3b)]).returncode == 0

    # MFU: accelerator drop past tol gates, CPU drop warns
    d4 = tmp_path / "mfu1"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json",
                 [train(1000.0, 1_000_000, mfu=0.20, backend="tpu",
                        arch="TPU v5 lite")])
    _trend_round(d4, "BENCH_r02.json",
                 [train(990.0, 1_000_000, mfu=0.10, backend="tpu",
                        arch="TPU v5 lite")])
    r = _run_trend(["--dir", str(d4)])
    assert r.returncode == 1 and "MFU regressed" in r.stderr
    d5 = tmp_path / "mfu2"
    d5.mkdir()
    _trend_round(d5, "BENCH_r01.json", [train(100.0, 1_000_000,
                                              mfu=0.02)])
    _trend_round(d5, "BENCH_r02.json", [train(99.0, 1_000_000,
                                              mfu=0.01)])
    r = _run_trend(["--dir", str(d5)])
    assert r.returncode == 0 and "MFU regressed" in r.stderr \
        and "WARNING" in r.stderr
    assert _run_trend(["--dir", str(d5), "--strict-cpu"]).returncode == 1


def test_check_bench_trend_zero_peak_memory_ratchet(tmp_path):
    """The ZeRO memory ratchet on the --comm zero legs: a stage
    landing DROPS the leg's compiled peak_bytes and the trend accepts
    the new floor without ceremony; the next round regressing back
    toward the unsharded peak gates at --mem-tol on EVERY backend —
    the compiled plan is deterministic, so CPU noise is no excuse
    (same policy as the replication-ledger gate)."""

    def zleg(peak, stage=3):
        return exporters.JsonlExporter.enrich(
            {"metric": f"ddp_mlp_zero{stage}_train_throughput",
             "value": 5000.0, "unit": "samples/sec/chip",
             "vs_baseline": None, "backend": "cpu", "ndev": 8,
             "arch": "cpu", "peak_bytes": peak, "zero_stage": stage,
             "flops_per_step": 1e6, "achieved_tflops": 0.001,
             "mfu": None, "cold_compile_ms": 10.0,
             "compiles_total": 1, "steady_state_retraces": 0})

    # ratchet DOWN: the stage-3 peak collapse vs last round is clean
    d1 = tmp_path / "zmem1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [zleg(151_000_000)])
    _trend_round(d1, "BENCH_r02.json", [zleg(128_000_000)])
    r = _run_trend(["--dir", str(d1), "--mem-tol", "0.05"])
    assert r.returncode == 0, r.stderr

    # ...and the ratcheted-down floor HOLDS: regressing back up past
    # --mem-tol gates, even on the CPU backend
    d2 = tmp_path / "zmem2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [zleg(128_000_000)])
    _trend_round(d2, "BENCH_r02.json", [zleg(145_000_000)])  # +13%
    r = _run_trend(["--dir", str(d2), "--mem-tol", "0.1"])
    assert r.returncode == 1
    assert "peak memory grew" in r.stderr
    # the same growth inside a loosened tolerance passes
    r = _run_trend(["--dir", str(d2), "--mem-tol", "0.25"])
    assert r.returncode == 0, r.stderr


def test_check_bench_trend_partitions_numerics_records(tmp_path):
    """kind: numerics gradient-health dumps (PR 9) are per-run
    diagnostics, not a cross-round trend: fresh ones pass through
    without entering the measurement trend, stale replays count
    toward the partition tally like every other record family."""
    def numrec(overflow, **kw):
        return exporters.JsonlExporter.enrich(
            {"kind": "numerics", "metric": "resnet18_o2_ddp_numerics",
             "steps": 10, "overflow_steps": overflow,
             "backend": "cpu",
             "layers": [{"name": "w", "nonfinite": 0, "abs_max": 1.0,
                         "grad_norm": 1.0,
                         "underflow_fraction": 0.0}], **kw})

    d = tmp_path / "num1"
    d.mkdir()
    _trend_round(d, "BENCH_r01.json", [numrec(0)])
    # a later round with MORE overflows must not read as a metric
    # regression — numerics records carry no trend value
    _trend_round(d, "BENCH_r02.json", [numrec(5),
                                       numrec(0, stale=True)])
    r = _run_trend(["--dir", str(d)])
    assert r.returncode == 0, r.stderr
    assert "0 fresh measurements counted" in r.stderr
    assert "1 stale replays partitioned out" in r.stderr


# -- engine telemetry -----------------------------------------------------

def _gpt(seed=0):
    m = models.GPT(models.GPTConfig(vocab_size=64, block_size=24,
                                    n_layer=2, n_head=4, n_embd=32,
                                    dropout=0.0, n_kv_head=2))
    params, _ = m.init(jax.random.PRNGKey(seed))
    return m, params


def test_engine_stats_enriched_fields():
    m, params = _gpt()
    eng = serving.Engine(m, params, slots=2, buf_len=24)
    rng = np.random.RandomState(0)
    rids = [eng.submit(list(rng.randint(0, 64, 5)), max_new_tokens=4)
            for _ in range(3)]                  # 3rd queues (2 slots)
    s = eng.stats()
    assert s["queue_depth"] == s["waiting"] == 1
    assert s["occupancy"] == 1.0 and s["slots"] == 2
    assert s["admitted"] == 2
    assert s["prefill_latency"]["count"] == 2
    while eng.live() or eng.stats()["waiting"]:
        eng.step()
    s = eng.stats()
    assert s["finished"] == 3 and s["admitted"] == 3
    assert s["tokens_generated"] == 12
    assert s["decode_steps"] == s["decode_step_latency"]["count"] > 0
    assert s["ttft"]["count"] == 3 and s["ttft"]["mean"] > 0
    assert s["request_tokens_per_sec"]["count"] == 3
    assert s["queue_wait"]["count"] == 3
    assert s["prefix_hits"] == 0 and s["prefix_hit_rate"] == 0.0
    for rid in rids:
        assert len(eng.result(rid)) == 4


def test_engine_stats_memory_fields():
    """Engine.stats() memory surface (PR 8): kv_cache_bytes recomputed
    from the live cache buffers, the live-array census, the
    engine_kv_cache_bytes gauge, and HBM fields None on a CPU-style
    backend (no fabricated occupancy)."""
    m, params = _gpt()
    eng = serving.Engine(m, params, slots=2, buf_len=24)
    s = eng.stats()
    expect_kv = sum(leaf.nbytes
                    for leaf in jax.tree_util.tree_leaves(eng.cache))
    assert s["kv_cache_bytes"] == expect_kv > 0
    assert eng.kv_cache_bytes() == expect_kv
    assert eng.metrics.gauge("engine_kv_cache_bytes").value == expect_kv
    # the census sees at least this engine's cache + params
    assert s["device_live_bytes"] >= expect_kv
    assert eng.metrics.gauge("device_live_bytes").value \
        == s["device_live_bytes"]
    # CPU backend reports no hardware memory stats — fields are None,
    # not a made-up ratio
    assert s["hbm_bytes_in_use"] is None
    assert s["hbm_bytes_limit"] is None
    assert s["hbm_occupancy"] is None
    # a prefix pool adds its rows to the engine's KV footprint
    pooled = serving.Engine(m, params, slots=2, buf_len=24,
                            prefix_pool=1)
    assert pooled.kv_cache_bytes() > expect_kv


def test_seq2seq_engine_stats_memory_fields():
    model = models.T5(models.T5Config(
        vocab_size=64, d_model=32, d_kv=8, d_ff=64, num_layers=1,
        num_heads=4, dropout_rate=0.0, relative_attention_num_buckets=8,
        relative_attention_max_distance=16))
    t5p, _ = model.init(jax.random.PRNGKey(0))
    eng = serving.Seq2SeqEngine(model, t5p, slots=2, src_len=8,
                                max_new_cap=8)
    s = eng.stats()
    expect = sum(leaf.nbytes
                 for leaf in jax.tree_util.tree_leaves(eng.state))
    assert s["kv_cache_bytes"] == expect > 0


def test_engine_stats_prefix_cache_hit_rate():
    m, params = _gpt(1)
    eng = serving.Engine(m, params, slots=2, buf_len=24, prefix_pool=1)
    rng = np.random.RandomState(1)
    pref = list(rng.randint(0, 64, 8))
    eng.register_prefix(pref)
    eng.add_request(pref + list(rng.randint(0, 64, 3)), max_new_tokens=2)
    eng.add_request(list(rng.randint(0, 64, 6)), max_new_tokens=2)
    while eng.live():
        eng.step()
    s = eng.stats()
    assert s["prefix_hits"] == 1 and s["admitted"] == 2
    assert s["prefix_hit_rate"] == 0.5
    assert eng.metrics.counter("engine_prefix_hits_total").value == 1


def test_engine_stats_rolling_mode():
    cfg = models.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=16,
        sliding_window=6, tie_word_embeddings=True)
    m = models.Llama(cfg)
    params, _ = m.init(jax.random.PRNGKey(0))
    eng = serving.Engine(m, params, slots=2, buf_len=16, rolling=True)
    rng = np.random.RandomState(0)
    eng.add_request(list(rng.randint(0, 64, 4)), max_new_tokens=3)
    while eng.live():
        eng.step()
    s = eng.stats()
    assert s["finished"] == 1 and s["tokens_generated"] == 3
    assert s["prefill_latency"]["count"] == 1
    assert s["ttft"]["count"] == 1


def test_seq2seq_engine_stats():
    cfg = models.T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                          num_layers=2, num_heads=4, dropout_rate=0.0,
                          relative_attention_num_buckets=8,
                          relative_attention_max_distance=16)
    m = models.T5(cfg)
    params, _ = m.init(jax.random.PRNGKey(0))
    eng = serving.Seq2SeqEngine(m, params, slots=1, src_len=8,
                                max_new_cap=4)
    eng.submit([3, 4, 5], max_new_tokens=3)
    eng.submit([6, 7], max_new_tokens=2)       # queues behind slot 0
    while eng.live() or eng.stats()["waiting"]:
        eng.step()
    s = eng.stats()
    assert s["finished"] == 2 and s["tokens_generated"] == 5
    assert s["ttft"]["count"] == 2
    assert s["queue_wait"]["count"] == 2
    # the queued request waited at least one decode tick
    assert s["queue_wait"]["sum"] > 0


def test_engine_custom_metrics_registry():
    m, params = _gpt(2)
    reg = obs.MetricsRegistry()
    eng = serving.Engine(m, params, slots=1, buf_len=24, metrics=reg)
    eng.add_request([1, 2, 3], max_new_tokens=2)
    while eng.live():
        eng.step()
    assert eng.metrics is reg
    assert reg.counter("engine_tokens_total").value == 2


# -- amp / optimizer / profiler satellites --------------------------------

def test_amp_scaler_introspection():
    from apex_tpu import amp, optimizers as opts
    from apex_tpu import nn

    class Lin(nn.Module):
        def init(self, key):
            return {"w": jnp.ones((4, 4), jnp.float32)}, ()

        def apply(self, p, x, state=(), train=False):
            return x @ p["w"], state

    model, opt = amp.initialize(Lin(), opts.FusedAdam(1e-3),
                                opt_level="O2", half_dtype="float16",
                                verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    assert amp.current_loss_scale(ost) == 2.0 ** 16
    assert amp.steps_skipped(ost) == 0
    st = amp.amp_stats(ost)
    assert st["num_losses"] == 1
    assert st["per_loss"][0]["loss_scale"] == 2.0 ** 16
    # overflow: scale halves, skip count exposed through the frontend
    g = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.inf, jnp.float32), params)
    _, ost2, info = opt.step(params, ost, g)
    assert amp.steps_skipped(ost2) == 1
    assert amp.current_loss_scale(ost2) == 2.0 ** 15
    # registry recording (loss-scale timeline point)
    reg = obs.MetricsRegistry()
    rec = obs.SpanRecorder()
    prev = obs.set_recorder(rec)
    try:
        amp.record_scaler(ost2, registry=reg, step=1, emit_event=True)
    finally:
        obs.set_recorder(prev)
    assert reg.gauge("amp_loss_scale").value == 2.0 ** 15
    assert reg.counter("amp_steps_skipped_total").value == 1
    ev = rec.events()[-1]
    assert ev["name"] == "amp_loss_scale" and ev["args"]["step"] == 1
    with pytest.raises(TypeError):
        amp.amp_stats({"not": "an opt state"})


def test_step_info_grad_norm():
    from apex_tpu import amp, optimizers as opts
    from apex_tpu import nn

    class Lin(nn.Module):
        def init(self, key):
            return {"w": jnp.ones((3,), jnp.float32)}, ()

        def apply(self, p, x, state=(), train=False):
            return x * p["w"], state

    model, opt = amp.initialize(Lin(), opts.FusedAdam(1e-3),
                                opt_level="O2", verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    g = {"w": jnp.asarray([3.0, 4.0, 0.0], jnp.bfloat16)}
    _, _, info = opt.step(params, ost, g)
    assert float(info["grad_norm"]) == pytest.approx(5.0, rel=1e-3)
    assert float(opts.global_grad_norm(
        {"a": jnp.asarray([3.0]), "b": jnp.asarray([4.0])})) == \
        pytest.approx(5.0)
    assert float(opts.global_grad_norm({})) == 0.0


def test_profiler_nesting_and_threads(monkeypatch):
    """Nested profile() must not stop the outer window; concurrent
    start/stop must produce exactly one start_trace/stop_trace pair."""
    from apex_tpu.utils import profiler
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    with profiler.profile("/tmp/x"):
        assert profiler.profiling_active()
        with profiler.profile("/tmp/x"):   # nested: must no-op cleanly
            assert calls == ["start"]
        assert calls == ["start"]          # inner exit didn't stop it
        assert profiler.profiling_active()
    assert calls == ["start", "stop"]
    assert not profiler.profiling_active()
    profiler.stop_profile()                # unmatched stop: no-op
    assert calls == ["start", "stop"]

    # hammer it from 8 threads: starts/stops stay balanced, never nested
    calls.clear()
    def work():
        for _ in range(50):
            with profiler.profile("/tmp/x"):
                pass
    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not profiler.profiling_active()
    assert calls.count("start") == calls.count("stop")
    depth = 0
    for c in calls:
        depth += 1 if c == "start" else -1
        assert depth in (0, 1)             # never two open windows
    assert depth == 0


def test_data_loader_records_wait_times():
    from apex_tpu.data import DataLoader
    reg = obs.MetricsRegistry()
    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 255, (16, 8, 8, 3)).astype(np.uint8)
    lbls = rng.randint(0, 10, 16)
    dl = DataLoader(imgs, lbls, batch_size=4, shuffle=False, native=False,
                    metrics=reg)
    for _ in range(3):
        dl.next_batch()
    s = dl.stats()
    assert s["batches"] == 3
    assert s["load_wait"]["count"] == 3 and s["load_wait"]["sum"] >= 0
    assert reg.counter("data_batches_total").value == 3


def test_ddp_comm_stats_recorded():
    from apex_tpu import parallel
    ddp = parallel.DistributedDataParallel(message_size=100)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    grads = {"a": jnp.ones((300,), jnp.float32),
             "b": jnp.ones((10,), jnp.bfloat16)}

    def step(g):
        return ddp.allreduce_grads(g)

    out = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False))(grads)
    assert float(out["a"][0]) == 1.0    # psum(1)*8 / world (averaged)
    by_dtype = {b["dtype"]: b for b in ddp.last_comm_stats}
    assert by_dtype["float32"]["cause"] == "chunked"
    assert by_dtype["float32"]["chunks"] == 3
    # TRUE on-wire bytes: the chunked path pads to chunks*message_size
    # (here 300 fits 3x100 exactly — padded_elements pins that)
    assert by_dtype["float32"]["bytes"] == 300 * 4
    assert by_dtype["float32"]["wire_elements"] == 300
    assert by_dtype["float32"]["padded_elements"] == 0
    assert by_dtype["float32"]["topology"] == "flat"
    assert by_dtype["bfloat16"]["cause"] == "single"
    assert by_dtype["bfloat16"]["bytes"] == 10 * 2
    # folded into the process registry under (dtype, cause) labels
    reg = obs.get_registry()
    c = reg.counter("ddp_allreduce_buckets_total")
    assert c.labels(dtype="float32", cause="chunked").value >= 1
    assert reg.counter("ddp_allreduce_bytes_total").labels(
        dtype="float32").value >= 1200
    # per-fabric-level accounting: flat psums count fully on both
    lvl = reg.counter("ddp_allreduce_level_bytes_total")
    assert lvl.labels(level="dcn", dtype="float32").value >= 1200
    assert lvl.labels(level="ici", dtype="float32").value >= 1200


def test_ddp_comm_stats_hierarchical_levels():
    """The hierarchical topology's trace-time stats split the wire
    bytes per fabric level, and the registry's level counter sees the
    DCN hop at 1/ici of the bucket."""
    from apex_tpu import parallel
    ddp = parallel.DistributedDataParallel(
        comm_topology="hierarchical", ici_size=4)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    grads = {"a": jnp.ones((400,), jnp.float32)}

    base = obs.get_registry().counter(
        "ddp_allreduce_level_bytes_total").labels(
        level="dcn", dtype="float32").value
    jax.jit(jax.shard_map(
        lambda g: ddp.allreduce_grads(g), mesh=mesh, in_specs=(P(),),
        out_specs=P(), check_vma=False))(grads)
    (b,) = ddp.last_comm_stats
    assert b["topology"] == "hierarchical"
    assert b["dcn_wire_bytes"] == 100 * 4          # 1/ici of the bucket
    assert b["ici_wire_bytes"] == 400 * 4 + 100 * 4
    assert b["bytes"] == b["ici_wire_bytes"] + b["dcn_wire_bytes"]
    after = obs.get_registry().counter(
        "ddp_allreduce_level_bytes_total").labels(
        level="dcn", dtype="float32").value
    assert after - base == 400


# -- Prometheus exposition conformance (PR 10, satellite) ------------------

def test_prometheus_text_escapes_and_roundtrips():
    """Exposition-format conformance: HELP/TYPE lines, label-value
    escaping (backslash / quote / newline), the +Inf histogram bucket
    — and the parser round-trip recovers the registry's exact label
    values and sample values."""
    reg = obs.MetricsRegistry()
    c = reg.counter("esc_total", help="counts with a \\ slash\nnewline")
    c.labels(path='/v1/"gen"\nx', shard="a\\b").inc(4)
    g = reg.gauge("esc_gauge")
    g.set(2.5)
    h = reg.histogram("esc_seconds", help="latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 9.0):
        h.observe(v)
    text = exporters.prometheus_text(reg)
    # conformance checker: no violations
    assert exporters.validate_prometheus_text(text) == []
    # HELP newline is escaped on the wire (single physical line)
    (help_line,) = [ln for ln in text.splitlines()
                    if ln.startswith("# HELP esc_total")]
    assert "\\n" in help_line and "\n" not in help_line[1:]
    # parser round-trip: the gnarly label values come back EXACTLY
    fams = exporters.parse_prometheus_text(text)
    assert fams["esc_total"]["type"] == "counter"
    (name, labels, value), = fams["esc_total"]["samples"]
    assert labels == {"path": '/v1/"gen"\nx', "shard": "a\\b"}
    assert value == 4.0
    assert fams["esc_total"]["help"].endswith("\\nnewline")
    # histogram: +Inf bucket present, cumulative counts monotone,
    # _count == +Inf, _sum == the observed sum
    hs = {n: (lab, v) for n, lab, v in fams["esc_seconds"]["samples"]}
    buckets = {lab["le"]: v for n, lab, v
               in fams["esc_seconds"]["samples"]
               if n == "esc_seconds_bucket"}
    assert buckets == {"0.1": 1.0, "1": 2.0, "+Inf": 3.0}
    assert hs["esc_seconds_count"][1] == 3.0
    assert hs["esc_seconds_sum"][1] == pytest.approx(9.55)


def test_prometheus_labeled_histogram_exposition_roundtrips():
    """PR 16 satellite: labeled HISTOGRAM children expose correctly —
    each label set's buckets merge the child labels with ``le=``, keep
    their own cumulative +Inf/_count invariants, and user-supplied
    tenant label values (quotes, backslashes, newlines) survive the
    escape round-trip on every bucket line."""
    reg = obs.MetricsRegistry()
    h = reg.histogram("tenant_wait_seconds", help="queue wait",
                      buckets=(0.1, 1.0))
    nasty = 'acme "prod"\nv\\2'
    h.labels(tenant="batch").observe(0.05)
    h.labels(tenant="batch").observe(0.5)
    h.labels(tenant=nasty).observe(9.0)
    text = exporters.prometheus_text(reg)
    assert exporters.validate_prometheus_text(text) == []
    fams = exporters.parse_prometheus_text(text)
    f = fams["tenant_wait_seconds"]
    assert f["type"] == "histogram"
    # untouched parent suppressed: every sample carries the tenant
    assert f["samples"] and all("tenant" in lab
                                for _, lab, _ in f["samples"])
    per = {}
    for name, lab, value in f["samples"]:
        s = per.setdefault(lab["tenant"], {})
        if name.endswith("_bucket"):
            s[lab["le"]] = value
        else:
            s[name.rsplit("_", 1)[-1]] = value
    # per-label-set cumulative buckets, each with its own +Inf==_count
    assert per["batch"] == {"0.1": 1.0, "1": 2.0, "+Inf": 2.0,
                            "sum": pytest.approx(0.55), "count": 2.0}
    # the gnarly tenant value came back EXACTLY, buckets intact
    assert per[nasty]["+Inf"] == 1.0 and per[nasty]["count"] == 1.0
    assert per[nasty]["sum"] == 9.0
    # a parent observed DIRECTLY as well exposes both series
    h.observe(0.05)
    fams = exporters.parse_prometheus_text(
        exporters.prometheus_text(reg))
    bare = [lab for n, lab, _ in fams["tenant_wait_seconds"]["samples"]
            if n.endswith("_count") and "tenant" not in lab]
    assert bare == [{}]
    assert exporters.validate_prometheus_text(
        exporters.prometheus_text(reg)) == []


def test_registry_label_cardinality_cap_folds_and_counts():
    """PR 16 tentpole guard: a metric flooded with more distinct label
    values than ``max_label_sets`` stays bounded — overflow folds into
    the shared ``other`` child, the fold is counted on
    ``labels_dropped``, totals are conserved, and the exposition stays
    conformant mid-fold."""
    from apex_tpu.observability.metrics import (DEFAULT_MAX_LABEL_SETS,
                                                OVERFLOW_LABEL_VALUE)
    reg = obs.MetricsRegistry()
    c = reg.counter("flood_total")
    assert c.max_label_sets == DEFAULT_MAX_LABEL_SETS
    c.max_label_sets = 3
    for i in range(8):
        c.labels(tenant=f"t{i}").inc()
    kids = c.children()
    assert {dict(k)["tenant"] for k in kids} == \
        {"t0", "t1", "t2", OVERFLOW_LABEL_VALUE}
    assert c.labels_dropped == 5
    # conserved: the folded increments landed on the overflow child
    assert c.labels(tenant=OVERFLOW_LABEL_VALUE).value == 5
    assert sum(ch.value for ch in kids.values()) == 8
    # a REPEATED over-cap id keeps folding (per-call drop accounting)
    c.labels(tenant="t7").inc()
    assert c.labels_dropped == 6
    assert c.labels(tenant=OVERFLOW_LABEL_VALUE).value == 6
    # an id that got under the cap is unaffected
    assert c.labels(tenant="t1").value == 1
    assert exporters.validate_prometheus_text(
        exporters.prometheus_text(reg)) == []


def test_validate_prometheus_text_catches_violations():
    # missing +Inf bucket
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="1"} 2\nh_sum 1.0\nh_count 2\n')
    assert any("+Inf" in e
               for e in exporters.validate_prometheus_text(bad))
    # non-monotone cumulative buckets
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\n'
           "h_sum 1.0\nh_count 3\n")
    assert any("decrease" in e
               for e in exporters.validate_prometheus_text(bad))
    # _count disagreeing with the +Inf bucket
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="+Inf"} 3\nh_sum 1.0\nh_count 4\n')
    assert any("_count" in e
               for e in exporters.validate_prometheus_text(bad))
    # sample with no TYPE declaration
    assert any("no # TYPE" in e
               for e in exporters.validate_prometheus_text("x 1.0\n"))
    # negative counter
    bad = "# TYPE c counter\nc -1.0\n"
    assert any("negative" in e
               for e in exporters.validate_prometheus_text(bad))
    # unparseable line
    assert exporters.validate_prometheus_text("{broken 1.0\n")
    # labeled-histogram invariants hold PER label set: one tenant's
    # series missing its +Inf (or disagreeing with _count) is caught
    # even when a sibling series is clean
    bad = ("# TYPE h histogram\n"
           'h_bucket{tenant="ok",le="+Inf"} 2\n'
           'h_sum{tenant="ok"} 1.0\nh_count{tenant="ok"} 2\n'
           'h_bucket{tenant="sick",le="1"} 1\n'
           'h_sum{tenant="sick"} 0.5\nh_count{tenant="sick"} 1\n')
    errs = exporters.validate_prometheus_text(bad)
    assert any("+Inf" in e and "sick" in e for e in errs)
    assert not any("'ok'" in e for e in errs)
    bad = ("# TYPE h histogram\n"
           'h_bucket{tenant="a",le="+Inf"} 3\n'
           'h_sum{tenant="a"} 1.0\nh_count{tenant="a"} 4\n')
    assert any("_count" in e
               for e in exporters.validate_prometheus_text(bad))


# -- EventRing.dump under concurrent appends (PR 10, satellite) -----------

def test_event_ring_dump_consistent_under_concurrent_appends(tmp_path):
    """dump() taken WHILE writers hammer the ring must be internally
    consistent: the header's drop accounting is exact for the snapshot
    it describes, retained events are a contiguous seq window in order
    (timestamps non-decreasing with seq — the clock is read under the
    lock), and no event is torn or duplicated."""
    ring = obs.EventRing(capacity=64)
    stop = threading.Event()

    def writer(wid):
        i = 0
        while not stop.is_set():
            ring.append("w", wid=wid, i=i)
            i += 1

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(4)]
    for t in threads:
        t.start()
    try:
        for k in range(20):
            path = str(tmp_path / f"dump_{k}.jsonl")
            ring.dump(path)
            with open(path) as f:
                lines = [json.loads(ln) for ln in f]
            header, events = lines[0], lines[1:]
            assert header["kind"] == "flight_ring"
            assert header["capacity"] == 64
            # exact accounting FOR THIS snapshot
            assert header["dropped"] == header["total"] - len(events)
            assert len(events) <= 64
            seqs = [e["seq"] for e in events]
            # contiguous window ending at total-1, oldest first
            assert seqs == list(range(header["total"] - len(events),
                                      header["total"]))
            # time order can never disagree with seq order
            ts = [e["t"] for e in events]
            assert ts == sorted(ts)
            # no torn event: every record carries its full payload
            assert all("wid" in e and "i" in e for e in events)
    finally:
        stop.set()
        for t in threads:
            t.join()
    # quiesced: final dump's total equals appended count exactly
    final = str(tmp_path / "final.jsonl")
    ring.dump(final)
    with open(final) as f:
        header = json.loads(f.readline())
    assert header["total"] == ring.total
    assert header["dropped"] == ring.total - len(ring)


# -- kind: run records (PR 10) --------------------------------------------

def test_validate_run_record_edges():
    def rec(**kw):
        base = {"kind": "run", "run": "r", "verdict": "ok",
                "observations": 5, "watermark": 4,
                "anomaly_counts": {"stall": 0, "nan": 0},
                "anomalies": [],
                "loss": {"last": 1.0, "ewma": 1.0},
                "checkpoints": 0, "duration_s": 1.5}
        base.update(kw)
        return exporters.JsonlExporter.enrich(base)

    assert exporters.validate_run_record(rec()) == []
    # null watermark (nothing observed yet) is legal
    assert exporters.validate_run_record(rec(watermark=None)) == []
    # verdict/count consistency both ways
    assert any("inconsistent" in e for e in exporters.
               validate_run_record(rec(verdict="attention")))
    assert any("inconsistent" in e for e in exporters.
               validate_run_record(rec(anomaly_counts={"nan": 2})))
    # unknown anomaly kind
    assert any("unknown kind" in e for e in exporters.
               validate_run_record(rec(anomaly_counts={"gremlin": 1},
                                       verdict="attention")))
    # detail list exceeding its count
    assert any("can never exceed" in e for e in exporters.
               validate_run_record(rec(
                   verdict="attention",
                   anomaly_counts={"nan": 1},
                   anomalies=[{"kind": "nan", "observation": 1},
                              {"kind": "nan", "observation": 2}])))
    # NaN smuggled into the loss summary
    assert any("finite" in e for e in exporters.validate_run_record(
        rec(loss={"last": float("nan")})))
    # bad verdict / run / observations
    assert exporters.validate_run_record(rec(verdict="fine"))
    assert exporters.validate_run_record(rec(run=""))
    assert exporters.validate_run_record(rec(observations=-1))
    assert exporters.validate_run_record(rec(duration_s=-2))


def test_check_bench_trend_partitions_run_records(tmp_path):
    """kind: run supervisor verdicts are per-run diagnostics, not a
    cross-round trend: a later round's anomalous run must not read as
    a regression, stale replays count toward the partition tally —
    while the run_supervisor_overhead METRIC lines do trend."""
    def runrec(n_nan, **kw):
        return exporters.JsonlExporter.enrich(
            {"kind": "run", "run": "resnet18_o2_ddp",
             "verdict": "attention" if n_nan else "ok",
             "observations": 10, "watermark": 9,
             "anomaly_counts": {"nan": n_nan}, "anomalies": [],
             "backend": "cpu", **kw})

    d = tmp_path / "run1"
    d.mkdir()
    _trend_round(d, "BENCH_r01.json", [runrec(0)])
    _trend_round(d, "BENCH_r02.json", [runrec(5),
                                       runrec(0, stale=True)])
    r = _run_trend(["--dir", str(d)])
    assert r.returncode == 0, r.stderr
    assert "1 stale replays partitioned out" in r.stderr

    # the overhead metric lines DO trend (tpu backend gates)
    def ov(value, **kw):
        return exporters.JsonlExporter.enrich(
            {"metric": "run_supervisor_overhead_o2", "value": value,
             "unit": "ms", "vs_baseline": None, "backend": "tpu",
             "ndev": 1, "arch": "TPU v5 lite",
             "step_ms_on": 10.0 + value, "step_ms_off": 10.0, **kw})

    d2 = tmp_path / "run2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [ov(1.0)])
    _trend_round(d2, "BENCH_r02.json", [ov(2.0)])   # 100% worse (ms)
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 1
    assert "regressed" in r.stderr


def test_v5_requirements_gate_on_declared_version():
    """Schema v5's run_supervisor_overhead both-sides requirement (and
    the run-record family itself) gate on the record's DECLARED
    schema_version — archived v4-and-earlier streams re-validate
    clean."""
    line = {"metric": "run_supervisor_overhead_o2", "value": 1.0,
            "unit": "ms", "vs_baseline": None, "backend": "cpu",
            "ndev": 8, "arch": "cpu"}
    # fresh v5 line WITHOUT the on/off pair: error
    v5 = exporters.JsonlExporter.enrich(dict(line))
    assert v5["schema_version"] >= 5
    errs = exporters.validate_bench_record(v5)
    assert any("step_ms_on" in e for e in errs)
    # the same line declaring v4 (an archived pre-supervisor stream):
    # clean — v4 never defined the metric, so no requirement applies
    v4 = exporters.JsonlExporter.enrich(
        {**line, "schema_version": 4})
    assert exporters.validate_bench_record(v4) == []
    # and the complete v5 line is clean
    full = exporters.JsonlExporter.enrich(
        {**line, "step_ms_on": 11.0, "step_ms_off": 10.0})
    assert exporters.validate_bench_record(full) == []
    # v4 numerics_overhead contract unchanged by the bump
    num = exporters.JsonlExporter.enrich(
        {"metric": "numerics_overhead_o2", "value": 1.0, "unit": "ms",
         "vs_baseline": None, "backend": "cpu", "ndev": 8,
         "arch": "cpu", "schema_version": 4})
    assert any("step_ms_on" in e
               for e in exporters.validate_bench_record(num))


def test_v7_requirements_gate_on_declared_version():
    """Schema v7: fresh chaos_preempt* lines must carry the resume
    they measured (mttr_s / resume_overhead_s / resumed_step);
    recovery records validate cause/preempted/data_state whenever
    present.  Archived v6-and-earlier streams re-validate clean."""
    line = {"metric": "chaos_preempt_resume", "value": 0.01,
            "unit": "s", "vs_baseline": None, "backend": "cpu",
            "ndev": 1, "arch": "cpu"}
    v7 = exporters.JsonlExporter.enrich(dict(line))
    assert v7["schema_version"] >= 7
    errs = exporters.validate_bench_record(v7)
    assert any("mttr_s" in e for e in errs)
    assert any("resumed_step" in e for e in errs)
    # the same line declaring v6 (an archived pre-preemption stream):
    # clean — v6 never defined the metric
    v6 = exporters.JsonlExporter.enrich({**line, "schema_version": 6})
    assert exporters.validate_bench_record(v6) == []
    # and the complete v7 line is clean
    full = exporters.JsonlExporter.enrich(
        {**line, "mttr_s": 0.02, "resume_overhead_s": 0.01,
         "resumed_step": 7})
    assert exporters.validate_bench_record(full) == []

    # recovery-record preemption fields, validated whenever present
    base = {"kind": "recovery", "role": "training", "subject": "run",
            "episodes": 0, "actions_total": 0,
            "max_actions_in_episode": 0, "actions": [],
            "mttr_s": {"last": None, "mean": None, "count": 0},
            "in_flight": False, "duration_s": 1.0}
    ok = exporters.JsonlExporter.enrich(
        {**base, "cause": "preemption", "preempted": True,
         "data_state": {"samples_consumed": 80, "epoch": 1,
                        "cursor": 16, "shard_id": 0,
                        "num_shards": 4}})
    assert exporters.validate_recovery_record(ok) == []
    bad_cause = exporters.JsonlExporter.enrich(
        {**base, "cause": "cosmic_rays"})
    assert any("cause" in e for e in
               exporters.validate_recovery_record(bad_cause))
    bad_ds = exporters.JsonlExporter.enrich(
        {**base, "data_state": {"samples_consumed": -1}})
    assert any("samples_consumed" in e for e in
               exporters.validate_recovery_record(bad_ds))
    bad_shard = exporters.JsonlExporter.enrich(
        {**base, "data_state": {"shard_id": 5, "num_shards": 4}})
    assert any("shard_id" in e for e in
               exporters.validate_recovery_record(bad_shard))
    bad_pre = exporters.JsonlExporter.enrich(
        {**base, "preempted": "yes"})
    assert any("preempted" in e for e in
               exporters.validate_recovery_record(bad_pre))
    # the new action kind is known to the validator
    act = exporters.JsonlExporter.enrich(
        {**base, "episodes": 1, "actions_total": 1,
         "max_actions_in_episode": 1,
         "actions": [{"kind": "preempt_snapshot", "episode": 1,
                      "t_s": 0.5}]})
    assert exporters.validate_recovery_record(act) == []


def test_v8_profile_records_and_version_gating():
    """Schema v8: ``kind: profile`` records dispatch to their own
    validator, and the engine-decode kv-fragmentation requirement
    gates on the DECLARED version — archived v7-and-earlier streams
    re-validate clean (the full archived-stream sweep rides
    test_check_bench_trend_gate's real BENCH_r*.json files through
    check_bench_schema)."""
    prof = exporters.JsonlExporter.enrich(
        {"kind": "profile", "metric": "resnet18_o2_ddp_flat_profile",
         "span_ms": 10.0, "device_busy_ms": 8.0, "compute_ms": 7.0,
         "collective_ms": 3.0, "gap_ms": 2.0, "overlap_ms": 2.0,
         "measured_overlap_fraction": 0.6667, "kernel_count": 42,
         "lane_count": 8, "steps": 3,
         "top_kernels": [{"name": "all-reduce", "kind": "collective",
                          "count": 24, "total_ms": 3.0}]})
    assert prof["schema_version"] >= 8
    assert exporters.validate_profile_record(prof) == []
    # the dispatcher routes on kind — the same record through the
    # telemetry validator hits the profile schema, not the bench one
    assert exporters.validate_telemetry_record(prof) == []
    broken = dict(prof, device_busy_ms=99.0)
    assert exporters.validate_telemetry_record(broken) != []
    # a mixed stream with a profile line stays check_bench_schema clean
    bench_line = exporters.JsonlExporter.enrich(
        {"metric": "m", "value": 1.0, "unit": "x", "vs_baseline": None,
         "backend": "cpu", "ndev": 8, "arch": "cpu"})
    assert exporters.validate_telemetry_jsonl(
        [json.dumps(prof), json.dumps(bench_line)]) == []


def test_check_bench_trend_partitions_profile_records(tmp_path):
    """kind: profile device-timeline attributions are per-capture
    stories, not a cross-round trend: a later round's worse split
    must not read as a metric regression, and stale replays count
    toward the partition tally (the numerics/run/recovery rule)."""
    def profrec(busy, **kw):
        return exporters.JsonlExporter.enrich(
            {"kind": "profile", "metric": "resnet18_o2_ddp_profile",
             "backend": "cpu", "span_ms": busy + 1.0,
             "device_busy_ms": busy, "compute_ms": busy,
             "collective_ms": 0.0, "gap_ms": 1.0, "overlap_ms": 0.0,
             "measured_overlap_fraction": 0.0, **kw})

    d = tmp_path / "prof1"
    d.mkdir()
    _trend_round(d, "BENCH_r01.json", [profrec(5.0)])
    _trend_round(d, "BENCH_r02.json", [profrec(50.0),
                                       profrec(5.0, stale=True)])
    r = _run_trend(["--dir", str(d)])
    assert r.returncode == 0, r.stderr
    assert "0 fresh measurements counted" in r.stderr
    assert "1 stale replays partitioned out" in r.stderr


# -- PR 15: the compilation plane ------------------------------------------

def test_v10_compile_fields_and_version_gating():
    """Schema v10 (the compilation plane): fresh train-throughput and
    engine-decode lines must carry the compile-plane triple
    (cold_compile_ms / compiles_total / steady_state_retraces); the
    fields are value-checked wherever they appear; archived v1-v9
    streams re-validate clean at their declared versions."""
    assert exporters.SCHEMA_VERSION >= 10
    base = {"metric": "resnet18_o2_train_throughput", "value": 100.0,
            "unit": "images/sec/chip", "vs_baseline": None,
            "backend": "tpu", "ndev": 1, "arch": "TPU v5 lite",
            "flops_per_step": 1e12, "achieved_tflops": 10.0,
            "mfu": 0.1, "peak_bytes": 1_000_000,
            "cold_compile_ms": 1234.5, "compiles_total": 1,
            "steady_state_retraces": 0}
    assert exporters.validate_bench_record(
        exporters.JsonlExporter.enrich(dict(base))) == []
    # fresh v10 train line missing any of the triple flags
    for key in exporters.COMPILE_FIELDS:
        rec = exporters.JsonlExporter.enrich(
            {k: v for k, v in base.items() if k != key})
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), key
    # ...but the same line DECLARING v9 (an archived stream) is valid
    v9 = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items()
         if k not in exporters.COMPILE_FIELDS})
    v9["schema_version"] = 9
    assert exporters.validate_bench_record(v9) == []
    # stale replays and error lines stay exempt
    stale = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items()
         if k not in exporters.COMPILE_FIELDS}, stale=True)
    assert exporters.validate_bench_record(stale) == []
    err = exporters.JsonlExporter.enrich(
        {"metric": "resnet18_o2_train_throughput", "value": None,
         "unit": None, "vs_baseline": None, "backend": "tpu",
         "ndev": 1, "arch": "TPU v5 lite", "error": "hung"})
    assert exporters.validate_bench_record(err) == []
    # field VALUES are checked wherever the fields appear (any metric)
    plain = {"metric": "m", "value": 1.0, "unit": "x",
             "vs_baseline": None, "backend": "cpu", "ndev": 8,
             "arch": "cpu"}
    for key, bad in (("cold_compile_ms", -1.0),
                     ("cold_compile_ms", "slow"),
                     ("compiles_total", -1),
                     ("compiles_total", 1.5),
                     ("compiles_total", True),
                     ("steady_state_retraces", -2),
                     ("steady_state_retraces", "none")):
        rec = exporters.JsonlExporter.enrich(dict(plain, **{key: bad}))
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), \
            (key, bad)
    # a nonzero steady-state retrace count is schema-VALID (the record
    # is honest about it) — gating it is the trend checker's job
    assert exporters.validate_bench_record(
        exporters.JsonlExporter.enrich(
            dict(plain, steady_state_retraces=3))) == []


def test_compile_fields_pinned_to_compilation_module():
    """exporters.COMPILE_FIELDS is the stdlib-side duplicate of
    compilation.BENCH_COMPILE_FIELDS (both modules must stay
    importable without jax) — pinned equal so the two cannot drift."""
    from apex_tpu.observability import compilation
    assert exporters.COMPILE_FIELDS == compilation.BENCH_COMPILE_FIELDS


def test_check_bench_trend_compile_gate(tmp_path):
    """The compile-plane trend gates: a fresh line with a nonzero
    steady_state_retraces errors on EVERY backend (the ledger count is
    deterministic — the timed loop included a recompile), and
    cold_compile_ms growth past --tol gates on accelerators / warns on
    CPU smoke like every timing-derived column."""
    def line(backend, value, cold_ms, retraces=0):
        return exporters.JsonlExporter.enrich(
            {"metric": "gpt_tiny_engine_decode_throughput",
             "value": value, "unit": "tokens/sec/chip",
             "vs_baseline": None, "backend": backend, "ndev": 8,
             "arch": "TPU v5 lite" if backend == "tpu" else "cpu",
             "window": 8, "tokens_per_sync": 7.5,
             "admission_mode": "fixed_slot",
             "kv_cache_bytes": 16384, "kv_waste_bytes": 4096,
             "kv_utilization": 0.75,
             "cold_compile_ms": cold_ms, "compiles_total": 2,
             "steady_state_retraces": retraces})

    # nonzero steady-state retraces: error even on CPU smoke
    d1 = tmp_path / "comp1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [line("cpu", 100.0, 300.0,
                                             retraces=2)])
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 1
    assert "steady-state retrace" in r.stderr
    # accelerator cold_compile_ms growth past tol: error
    d2 = tmp_path / "comp2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [line("tpu", 100.0, 1000.0)])
    _trend_round(d2, "BENCH_r02.json", [line("tpu", 100.0, 2000.0)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 1
    assert "cold_compile_ms" in r.stderr
    # the same growth on CPU smoke: warning only (strict-cpu gates)
    d3 = tmp_path / "comp3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [line("cpu", 100.0, 1000.0)])
    _trend_round(d3, "BENCH_r02.json", [line("cpu", 100.0, 2000.0)])
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 0 and "cold_compile_ms" in r.stderr
    r = _run_trend(["--dir", str(d3), "--strict-cpu"])
    assert r.returncode == 1
    # growth inside tol, zero retraces: clean
    d4 = tmp_path / "comp4"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json", [line("tpu", 100.0, 1000.0)])
    _trend_round(d4, "BENCH_r02.json", [line("tpu", 101.0, 1100.0)])
    r = _run_trend(["--dir", str(d4)])
    assert r.returncode == 0, r.stderr
    # a STALE replay carrying old compile fields never trends
    d5 = tmp_path / "comp5"
    d5.mkdir()
    _trend_round(d5, "BENCH_r01.json", [line("tpu", 100.0, 1000.0)])
    _trend_round(d5, "BENCH_r02.json",
                 [dict(line("tpu", 100.0, 9000.0, retraces=5),
                       stale=True)])
    r = _run_trend(["--dir", str(d5)])
    assert r.returncode == 0, r.stderr


def test_v11_tenant_fields_and_version_gating():
    """Schema v11 (the tenant plane): fresh per-tenant goodput lines
    must carry ``tenant`` + ``slo_attainment``, the parity line its
    token counts (arithmetically consistent); archived v10 streams
    re-validate clean at their declared version; TENANT_COUNTS is
    pinned to the SLO tracker's actual bucket keys so the validator
    and the producer cannot drift."""
    assert exporters.SCHEMA_VERSION >= 11
    from apex_tpu.fleet import slo as fleet_slo
    assert exporters.TENANT_COUNTS == tuple(
        k for k in fleet_slo._new_tenant_bucket()
        if k not in ("t_first", "t_last", "tenant"))

    tline = {"metric": "gpt_tiny_fleet2_tenant_interactive_goodput",
             "value": 42.0, "unit": "tokens/sec", "vs_baseline": None,
             "backend": "cpu", "ndev": 1, "arch": "cpu",
             "tenant": "interactive", "slo_attainment": 1.0}
    assert exporters.validate_bench_record(
        exporters.JsonlExporter.enrich(dict(tline))) == []
    # fresh v11 tenant-goodput line missing either required field
    for key in ("tenant", "slo_attainment"):
        rec = exporters.JsonlExporter.enrich(
            {k: v for k, v in tline.items() if k != key})
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), key
    # ...but the same line DECLARING v10 (archived) is valid
    v10 = exporters.JsonlExporter.enrich(
        {k: v for k, v in tline.items()
         if k not in ("tenant", "slo_attainment")})
    v10["schema_version"] = 10
    assert exporters.validate_bench_record(v10) == []
    # null attainment (no deadlined request resolved) is valid
    assert exporters.validate_bench_record(exporters.JsonlExporter
        .enrich(dict(tline, slo_attainment=None))) == []
    # field VALUES checked wherever they appear
    for key, bad in (("slo_attainment", 1.5),
                     ("slo_attainment", -0.1),
                     ("tenant", ""), ("tenant", 7)):
        rec = exporters.JsonlExporter.enrich(dict(tline, **{key: bad}))
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), \
            (key, bad)

    pline = {"metric": "gpt_tiny_fleet2_tenant_parity", "value": 1.0,
             "unit": "ratio", "vs_baseline": None, "backend": "cpu",
             "ndev": 1, "arch": "cpu",
             "tenants_goodput_tokens": 120, "tokens_within_slo": 120}
    assert exporters.validate_bench_record(
        exporters.JsonlExporter.enrich(dict(pline))) == []
    # the ratio must reassemble from its own counts
    assert any("tenants_goodput_tokens" in e or "reassemble" in e
               for e in exporters.validate_bench_record(
                   exporters.JsonlExporter.enrich(
                       dict(pline, value=0.9))))
    # fresh v11 parity line missing its counts
    for key in ("tenants_goodput_tokens", "tokens_within_slo"):
        rec = exporters.JsonlExporter.enrich(
            {k: v for k, v in pline.items() if k != key})
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), key
    # archived v10 parity-free streams unaffected; stale exempt
    stale = exporters.JsonlExporter.enrich(
        {k: v for k, v in pline.items()
         if k not in ("tenants_goodput_tokens", "tokens_within_slo")},
        stale=True)
    assert exporters.validate_bench_record(stale) == []


def test_check_bench_trend_tenant_gate(tmp_path):
    """The tenant-plane trend gates: a fresh parity line off 1.0 by
    more than 1% errors on EVERY backend (exact token accounting — the
    leg tags every request), while a per-tenant slo_attainment drop
    past --tol follows the accelerator-gates / CPU-warns policy like
    every timing-derived column; stale replays never trend."""
    def tline(backend, attain):
        return exporters.JsonlExporter.enrich(
            {"metric": "gpt_tiny_fleet2_tenant_interactive_goodput",
             "value": 50.0, "unit": "tokens/sec", "vs_baseline": None,
             "backend": backend, "ndev": 1,
             "arch": "TPU v5 lite" if backend == "tpu" else "cpu",
             "tenant": "interactive", "slo_attainment": attain})

    def parity(backend, value, tg, tw):
        return exporters.JsonlExporter.enrich(
            {"metric": "gpt_tiny_fleet2_tenant_parity", "value": value,
             "unit": "ratio", "vs_baseline": None, "backend": backend,
             "ndev": 1,
             "arch": "TPU v5 lite" if backend == "tpu" else "cpu",
             "tenants_goodput_tokens": tg, "tokens_within_slo": tw})

    # parity off 1.0: error even on CPU smoke, first round
    d1 = tmp_path / "ten1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [parity("cpu", 0.9, 90, 100)])
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 1
    assert "parity" in r.stderr
    # accelerator attainment drop past tol: error
    d2 = tmp_path / "ten2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [tline("tpu", 1.0)])
    _trend_round(d2, "BENCH_r02.json", [tline("tpu", 0.5)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 1
    assert "slo_attainment" in r.stderr
    # same drop on CPU smoke: warning only (strict-cpu gates)
    d3 = tmp_path / "ten3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [tline("cpu", 1.0)])
    _trend_round(d3, "BENCH_r02.json", [tline("cpu", 0.5)])
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 0 and "slo_attainment" in r.stderr
    r = _run_trend(["--dir", str(d3), "--strict-cpu"])
    assert r.returncode == 1
    # steady attainment + exact parity: clean
    d4 = tmp_path / "ten4"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json",
                 [tline("tpu", 1.0), parity("tpu", 1.0, 100, 100)])
    _trend_round(d4, "BENCH_r02.json",
                 [tline("tpu", 1.0), parity("tpu", 1.0, 120, 120)])
    r = _run_trend(["--dir", str(d4)])
    assert r.returncode == 0, r.stderr
    # a STALE replay with broken parity / cratered attainment: ignored
    d5 = tmp_path / "ten5"
    d5.mkdir()
    _trend_round(d5, "BENCH_r01.json", [tline("tpu", 1.0)])
    _trend_round(d5, "BENCH_r02.json",
                 [dict(tline("tpu", 0.1), stale=True),
                  dict(parity("tpu", 0.5, 50, 100), stale=True)])
    r = _run_trend(["--dir", str(d5)])
    assert r.returncode == 0, r.stderr


def test_v12_block_pool_fields_and_version_gating():
    """Schema v12 (the paged serving plane): fresh engine-decode lines
    must say which allocator produced them (``admission_mode``), paged
    lines must expose the block pool, field VALUES are checked
    wherever they appear, and archived v11 streams re-validate clean
    at their declared version."""
    assert exporters.SCHEMA_VERSION >= 12
    assert exporters.ADMISSION_MODES == ("fixed_slot", "paged")
    from apex_tpu import serving
    assert serving.Engine.admission_mode in exporters.ADMISSION_MODES
    assert serving.PagedEngine.admission_mode in exporters.ADMISSION_MODES

    base = {"metric": "gpt_tiny_engine_decode_paged_throughput",
            "value": 9.0, "unit": "tokens/sec/chip",
            "vs_baseline": None, "backend": "cpu", "ndev": 8,
            "arch": "cpu", "window": 8, "tokens_per_sync": 7.5,
            "kv_cache_bytes": 16384, "kv_waste_bytes": 4096,
            "kv_utilization": 0.75, "cold_compile_ms": 350.0,
            "compiles_total": 2, "steady_state_retraces": 0,
            "admission_mode": "paged", "block_size": 8,
            "blocks_total": 16, "blocks_free": 5}
    assert exporters.validate_bench_record(
        exporters.JsonlExporter.enrich(dict(base))) == []
    # fresh v12 engine line without admission_mode
    rec = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items() if k != "admission_mode"})
    assert any("admission_mode" in e
               for e in exporters.validate_bench_record(rec))
    # a fixed-slot line needs no block fields
    fixed = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items()
         if k not in ("block_size", "blocks_total", "blocks_free")}
        | {"admission_mode": "fixed_slot"})
    assert exporters.validate_bench_record(fixed) == []
    # ...but a paged line missing any of them fails
    for key in ("block_size", "blocks_total", "blocks_free"):
        rec = exporters.JsonlExporter.enrich(
            {k: v for k, v in base.items() if k != key})
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), key
    # archived v11 stream without any of it: valid at its version
    v11 = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items()
         if k not in ("admission_mode", "block_size", "blocks_total",
                      "blocks_free")})
    v11["schema_version"] = 11
    assert exporters.validate_bench_record(v11) == []
    # field VALUES checked wherever they appear
    for key, bad in (("admission_mode", "slab"), ("admission_mode", 3),
                     ("block_size", 0), ("block_size", 8.5),
                     ("blocks_total", -1), ("blocks_free", True)):
        rec = exporters.JsonlExporter.enrich(dict(base, **{key: bad}))
        assert any(key in e
                   for e in exporters.validate_bench_record(rec)), \
            (key, bad)
    # blocks_free beyond the pool is an accounting bug
    rec = exporters.JsonlExporter.enrich(dict(base, blocks_free=99))
    assert any("blocks_free" in e
               for e in exporters.validate_bench_record(rec))
    # stale replay of a pre-paged record: exempt
    stale = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items()
         if k not in ("admission_mode", "block_size", "blocks_total",
                      "blocks_free")}, stale=True)
    assert exporters.validate_bench_record(stale) == []


def test_check_bench_trend_kv_gate(tmp_path):
    """The KV-plane trend gates: kv_waste_bytes growth past --tol
    errors on accelerators / warns on CPU smoke (the sampled waste is
    timing-adjacent), waste returning from a ZERO baseline gates like
    comm coming back onto the critical path, waste dropping (the paged
    engine's whole purpose) is clean, and the v12 field contract —
    fresh engine lines must carry admission_mode — gates on every
    backend while archived v11 rounds stay exempt."""
    def kline(backend, waste, **kw):
        return exporters.JsonlExporter.enrich(
            {"metric": "gpt_tiny_engine_decode_throughput",
             "value": 100.0, "unit": "tokens/sec/chip",
             "vs_baseline": None, "backend": backend, "ndev": 8,
             "arch": "TPU v5 lite" if backend == "tpu" else "cpu",
             "window": 8, "tokens_per_sync": 7.5,
             "admission_mode": "fixed_slot",
             "kv_cache_bytes": 16384, "kv_waste_bytes": waste,
             "kv_utilization": 0.75, "cold_compile_ms": 300.0,
             "compiles_total": 2, "steady_state_retraces": 0, **kw})

    # accelerator waste growth past tol: error
    d1 = tmp_path / "kv1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json", [kline("tpu", 4096)])
    _trend_round(d1, "BENCH_r02.json", [kline("tpu", 9000)])
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 1
    assert "kv_waste_bytes" in r.stderr
    # the same growth on CPU smoke: warning only (strict-cpu gates)
    d2 = tmp_path / "kv2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [kline("cpu", 4096)])
    _trend_round(d2, "BENCH_r02.json", [kline("cpu", 9000)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 0 and "kv_waste_bytes" in r.stderr
    r = _run_trend(["--dir", str(d2), "--strict-cpu"])
    assert r.returncode == 1
    # waste DROPPING (the paged win) is clean
    d3 = tmp_path / "kv3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [kline("tpu", 4096)])
    _trend_round(d3, "BENCH_r02.json", [kline("tpu", 128)])
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 0, r.stderr
    # waste returning from a zero baseline: the leak signature
    d4 = tmp_path / "kv4"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json", [kline("tpu", 0)])
    _trend_round(d4, "BENCH_r02.json", [kline("tpu", 2048)])
    r = _run_trend(["--dir", str(d4)])
    assert r.returncode == 1
    assert "zero baseline" in r.stderr
    # fresh v12 line without admission_mode: error on every backend
    d5 = tmp_path / "kv5"
    d5.mkdir()
    noam = kline("cpu", 4096)
    del noam["admission_mode"]
    _trend_round(d5, "BENCH_r01.json", [noam])
    r = _run_trend(["--dir", str(d5)])
    assert r.returncode == 1
    assert "admission_mode" in r.stderr
    # a paged line missing its block fields: error
    d6 = tmp_path / "kv6"
    d6.mkdir()
    _trend_round(d6, "BENCH_r01.json",
                 [kline("cpu", 4096, admission_mode="paged")])
    r = _run_trend(["--dir", str(d6)])
    assert r.returncode == 1
    assert "block" in r.stderr
    # ...but an archived round DECLARING v11 is exempt, and a stale
    # replay with cratered waste never trends
    d7 = tmp_path / "kv7"
    d7.mkdir()
    old = kline("tpu", 4096)
    del old["admission_mode"]
    old["schema_version"] = 11
    _trend_round(d7, "BENCH_r01.json", [old])
    _trend_round(d7, "BENCH_r02.json",
                 [dict(kline("tpu", 999999), stale=True)])
    r = _run_trend(["--dir", str(d7)])
    assert r.returncode == 0, r.stderr


def _ledger_rec(entry_point="ddp_resnet18_o2", repl=7000, **kw):
    """A schema-complete v13 replication-ledger record (what bench.py
    --graph-lint and the --sharding CLI emit)."""
    arg = 1000
    return exporters.JsonlExporter.enrich({
        "kind": "sharding", "entry_point": entry_point,
        "source": "jaxpr", "world": 8, "mesh_axes": {"data": 8},
        "shard_maps": 1, "argument_bytes": arg,
        "unique_bytes": 8 * arg - repl, "replicated_bytes": repl,
        "replicated_bytes_by_dtype": {"float32": repl} if repl else {},
        "replicated_fraction": repl / (8 * arg),
        "top_replicated": [], "resharding_eqns": {}, **kw})


def test_v13_sharding_records_and_version_gating():
    """Schema v13 (the sharding plane): ``kind: sharding`` records
    dispatch to their own validator, the ledger identity must
    reassemble, and archived streams declaring v1..v12 — which never
    carry the kind — re-validate clean at their declared versions."""
    assert exporters.SCHEMA_VERSION >= 13
    good = _ledger_rec()
    assert exporters.validate_sharding_record(good) == []
    assert exporters.validate_telemetry_record(good) == []
    # the identity every record must satisfy:
    # unique + replicated == world * argument
    assert any("reassemble" in e for e in
               exporters.validate_sharding_record(
                   dict(good, replicated_bytes=6999,
                        replicated_bytes_by_dtype={"float32": 6999})))
    # archived pre-v13 records of every enveloped kind stay valid at
    # their declared version after the bump
    old_kinds = [
        exporters.JsonlExporter.enrich(
            {"metric": "m", "value": 1.0, "unit": "x",
             "backend": "cpu", "ndev": 8, "arch": "cpu"}),
        exporters.JsonlExporter.enrich(
            {"kind": "graph_lint", "rule": "donation",
             "severity": "error", "entry_point": "e", "message": "m"}),
    ]
    for rec in old_kinds:
        for v in range(1, 13):
            archived = dict(rec, schema_version=v)
            assert exporters.validate_telemetry_record(archived) == [], v


def test_check_bench_trend_sharding_gate(tmp_path):
    """The replication-ledger trend gate (schema v13): duplicate-bytes
    growth past --mem-tol gates on EVERY backend (the ledger is
    statically derived, the peak_bytes rule), a zero baseline
    returning to nonzero is the un-sharded signature, shrinkage (the
    ZeRO direction) is clean, and stale replays partition out."""
    # growth past mem-tol on CPU smoke still errors — no noise excuse
    d1 = tmp_path / "sh1"
    d1.mkdir()
    _trend_round(d1, "BENCH_r01.json",
                 [_ledger_rec(repl=7000, backend="cpu")])
    _trend_round(d1, "BENCH_r02.json",
                 [_ledger_rec(repl=7900, backend="cpu")])  # +13%
    r = _run_trend(["--dir", str(d1)])
    assert r.returncode == 0, r.stderr          # within default 25%
    r = _run_trend(["--dir", str(d1), "--mem-tol", "0.1"])
    assert r.returncode == 1
    assert "replicated_bytes" in r.stderr
    # shrinking the duplicate bytes (a ZeRO shard landing) is clean
    d2 = tmp_path / "sh2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [_ledger_rec(repl=7000)])
    _trend_round(d2, "BENCH_r02.json", [_ledger_rec(repl=1000)])
    r = _run_trend(["--dir", str(d2), "--mem-tol", "0.1"])
    assert r.returncode == 0, r.stderr
    # a fully-sharded (zero) baseline returning to replication gates
    d3 = tmp_path / "sh3"
    d3.mkdir()
    _trend_round(d3, "BENCH_r01.json", [_ledger_rec(repl=0)])
    _trend_round(d3, "BENCH_r02.json", [_ledger_rec(repl=2048)])
    r = _run_trend(["--dir", str(d3)])
    assert r.returncode == 1
    assert "zero baseline" in r.stderr
    # distinct entry points trend independently; a stale replay with
    # inflated bytes never enters the trend
    d4 = tmp_path / "sh4"
    d4.mkdir()
    _trend_round(d4, "BENCH_r01.json",
                 [_ledger_rec("ep_a", 7000), _ledger_rec("ep_b", 100)])
    _trend_round(d4, "BENCH_r02.json",
                 [_ledger_rec("ep_a", 7000),
                  dict(_ledger_rec("ep_b", 999999), stale=True)])
    r = _run_trend(["--dir", str(d4), "--mem-tol", "0.01"])
    assert r.returncode == 0, r.stderr
    assert "stale replays partitioned" in r.stderr


def test_v15_zero_stage_records_and_version_gating():
    """Schema v15 (the ZeRO weight-update plane): fresh zero
    train-throughput lines and zero-EP sharding ledgers must carry
    ``zero_stage`` in {1, 2, 3}; the field is value-checked wherever
    it appears; archived v1..v14 streams re-validate clean at their
    declared versions."""
    assert exporters.SCHEMA_VERSION == 15
    base = {"metric": "ddp_resnet18_o2_zero3_train_throughput",
            "value": 100.0, "unit": "images/sec/chip",
            "vs_baseline": None, "backend": "cpu", "ndev": 8,
            "arch": "cpu", "flops_per_step": 1e12,
            "achieved_tflops": 10.0, "mfu": None,
            "peak_bytes": 1_000_000, "cold_compile_ms": 10.0,
            "compiles_total": 1, "steady_state_retraces": 0,
            "zero_stage": 3}
    assert exporters.validate_bench_record(
        exporters.JsonlExporter.enrich(dict(base))) == []
    # fresh v15 zero line without the stage tag gates
    rec = exporters.JsonlExporter.enrich(
        {k: v for k, v in base.items() if k != "zero_stage"})
    assert any("zero_stage" in e for e in
               exporters.validate_bench_record(rec))
    # ...but the same record declaring v14 rolls back clean
    v14 = dict(rec, schema_version=14)
    assert exporters.validate_bench_record(v14) == []
    # non-zero train lines never need the tag
    plain = exporters.JsonlExporter.enrich(
        dict({k: v for k, v in base.items() if k != "zero_stage"},
             metric="ddp_resnet18_o2_train_throughput"))
    assert exporters.validate_bench_record(plain) == []
    # the stage is value-checked wherever it appears (any metric)
    for bad in (0, 4, True, "3", 2.0):
        rec = exporters.JsonlExporter.enrich(
            {"metric": "m", "value": 1.0, "unit": "x",
             "vs_baseline": None, "backend": "cpu", "ndev": 8,
             "arch": "cpu", "zero_stage": bad})
        assert any("zero_stage" in e for e in
                   exporters.validate_bench_record(rec)), bad

    # sharding plane: fresh v15 ledgers for zero EPs carry the stage
    zled = _ledger_rec("ddp_resnet18_o2_zero2", zero_stage=2)
    assert exporters.validate_sharding_record(zled) == []
    missing = {k: v for k, v in zled.items() if k != "zero_stage"}
    assert any("zero_stage" in e for e in
               exporters.validate_sharding_record(missing))
    archived = dict(missing, schema_version=14)
    assert exporters.validate_sharding_record(archived) == []
    assert any("zero_stage" in e for e in
               exporters.validate_sharding_record(
                   dict(zled, zero_stage=7)))
    # non-zero EPs stay exempt at v15
    assert exporters.validate_sharding_record(_ledger_rec()) == []


def test_check_bench_trend_skips_twin_anomaly_overlap_records(tmp_path):
    """A record whose attribution flagged its own compute twin as
    slower than the step (compute_twin_excess_ms > 0) carries CLAMPED
    perfect-overlap numbers (comm_ms=0, overlap_fraction=1.0) — it
    must not seed the overlap trend, or the next HEALTHY round gates
    as a phantom regression."""
    def attr(frac, visible, **kw):
        return exporters.JsonlExporter.enrich(
            {"metric": "train_step_attribution_overlap",
             "value": 5.0, "unit": "ms", "vs_baseline": None,
             "backend": "tpu", "ndev": 8, "arch": "TPU v5 lite",
             "overlap_fraction": frac, "comm_visible_ms": visible,
             "overlap_mode": "overlapped", "n_stages": 4,
             "issue_order": [3, 2, 1, 0], **kw})

    d = tmp_path / "twin1"
    d.mkdir()
    # round 1: the twin anomaly (clamped to perfect overlap)
    _trend_round(d, "BENCH_r01.json",
                 [attr(1.0, 0.0, compute_twin_excess_ms=2.5)])
    # round 2: a healthy real measurement — must NOT gate against the
    # clamped 1.0/0.0 baseline
    _trend_round(d, "BENCH_r02.json", [attr(0.5, 1.2)])
    r = _run_trend(["--dir", str(d)])
    assert r.returncode == 0, r.stderr
    # sanity: without the anomaly marker the same pair DOES gate
    d2 = tmp_path / "twin2"
    d2.mkdir()
    _trend_round(d2, "BENCH_r01.json", [attr(1.0, 0.0)])
    _trend_round(d2, "BENCH_r02.json", [attr(0.5, 1.2)])
    r = _run_trend(["--dir", str(d2)])
    assert r.returncode == 1
