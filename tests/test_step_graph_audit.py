"""Trace-level audit of the hot graphs, driven by ``apex_tpu.analysis``.

The round-3 perf campaign showed the headline cost lives in the conv
backward + optimizer; PR 1 added device-resident telemetry and PR 2 the
donated decode window.  The properties that keep those wins — bf16 MXU
operands under O2, transpose-free channels-last, zero host transfers in
jitted hot graphs, every KV buffer aliased, the exact DDP/TP collective
pattern — are now pinned by the static-analysis framework: these tests
run the SAME rules over the SAME entry-point registry as the CI gate
(tests/ci/graph_lint.py) and the CLI (``python -m apex_tpu.analysis``),
so there is exactly one implementation of every invariant.

Mutation coverage (each rule demonstrably catches its broken-graph
counterpart) lives in tests/test_analysis.py; this file asserts the
clean repo is clean, plus the runtime host-sync arithmetic no jaxpr
can express.  Jaxpr properties are backend-independent, so the guard
runs on the CPU mesh while asserting what the TPU executable will see.
"""

import numpy as np
import pytest
import jax

from apex_tpu import analysis


def _findings(name, rules=None):
    return analysis.analyze_entry_point(analysis.get(name), rules=rules)


def _assert_clean(name, rules=None):
    found = _findings(name, rules=rules)
    assert not found, "\n".join(str(f) for f in found)


# -- amp dtype policy: the O2 train step keeps bf16 on the MXU ------------

def test_o2_step_convs_all_bf16():
    """Under amp O2 every convolution in the jitted DDP train step —
    forward, dgrad, and wgrad — consumes bf16 operands (a policy or
    cast bug that upcasts one conv family to fp32 would double its time
    on the MXU and halve effective HBM bandwidth).  The amp-dtype rule
    carries a >= 40 conv floor, so this cannot pass vacuously."""
    _assert_clean("ddp_resnet18_o2", rules=["amp-dtype"])
    # the rule really saw the full fwd+bwd conv population
    g = analysis.get("ddp_resnet18_o2").graph()
    assert len(analysis.conv_eqns(g.jaxpr)) >= 40


@pytest.mark.parametrize("lvl", ["o0", "o1", "o3"])
def test_other_opt_levels_match_policy(lvl):
    """O0 stays pure fp32 (accuracy baseline); O1/O3 put bf16 on the
    MXU — each level's traced step matches amp.compute_dtype."""
    _assert_clean(f"ddp_resnet18_{lvl}", rules=["amp-dtype"])


# -- layout: channels-last steps stay transpose-free ----------------------

def test_o2_nhwc_step_transpose_free():
    _assert_clean("ddp_resnet18_o2_nhwc", rules=["layout"])


def test_o2_s2d_nhwc_step_convs_bf16_and_transpose_free():
    """space_to_depth keeps its single sanctioned 6-D block rearrange
    (forward-only — the input is a constant, so no gradient flows back
    through it); anything else is a layout leak, and the convs stay
    bf16."""
    _assert_clean("ddp_resnet18_o2_nhwc_s2d", rules=["layout",
                                                     "amp-dtype"])
    ep = analysis.get("ddp_resnet18_o2_nhwc_s2d")
    six_d = [e for e in analysis.transpose_eqns(
        ep.graph().jaxpr, ep.expect["layout"]["min_activation_elems"])
        if e.invars[0].aval.ndim == 6]
    assert len(six_d) <= 1


# -- telemetry: device-resident metrics add zero host transfers -----------

def test_telemetry_step_adds_zero_host_transfers():
    """Enabling DeviceMetrics telemetry on the jitted DDP+amp-O2 train
    step must add ZERO host transfers: the counters/gauges accumulate
    as jnp scalars in the step carry and only flush() (outside the
    step) touches the host.  A callback- or outfeed-based metrics
    implementation would turn every train step into a host round-trip —
    the regression this guard exists to catch."""
    _assert_clean("ddp_resnet18_o2_telemetry", rules=["host-transfer"])
    _assert_clean("ddp_resnet18_o2", rules=["host-transfer"])
    # the instrumented graph keeps the same conv population — telemetry
    # reads existing step outputs (found_inf, loss scale, grad norm)
    # instead of perturbing the compute
    base = analysis.get("ddp_resnet18_o2").graph()
    tele = analysis.get("ddp_resnet18_o2_telemetry").graph()
    assert len(analysis.conv_eqns(tele.jaxpr)) == \
        len(analysis.conv_eqns(base.jaxpr))


# -- numerics instrumentation: free when on, absent when off --------------

def test_numerics_step_zero_host_transfers_and_plan_exact_collectives():
    """The numerics-instrumented O2 step (per-layer grad health +
    per-bucket stats + divergence digest threaded through the carry)
    adds ZERO host-transfer primitives and EXACTLY the plan-derived
    collective delta: the digest's one psum over the (L, 2) fp32
    checksum, nothing else.  The collective rule's expectations are
    folded from allreduce_comm_plan + numerics.digest_comm_plan, so a
    bucketing change moves plan and graph together while a smuggled
    collective still flags."""
    _assert_clean("ddp_resnet18_o2_numerics",
                  rules=["numerics", "host-transfer", "collective"])
    from collections import Counter
    base = analysis.get("ddp_resnet18_o2").graph()
    inst = analysis.get("ddp_resnet18_o2_numerics").graph()
    got = Counter(e.primitive.name
                  for e in analysis.collective_eqns(inst.jaxpr))
    base_counts = Counter(e.primitive.name
                          for e in analysis.collective_eqns(base.jaxpr))
    assert got["psum"] == base_counts["psum"] + 1      # the digest
    assert analysis.host_transfer_eqns(inst.jaxpr) == []
    # the payload delta is exactly the digest plan's bytes
    want = analysis.get("ddp_resnet18_o2_numerics").expect["numerics"]
    delta = (sum(analysis.eqn_payload_bytes(e)
                 for e in analysis.collective_eqns(inst.jaxpr))
             - sum(analysis.eqn_payload_bytes(e)
                   for e in analysis.collective_eqns(base.jaxpr)))
    assert delta == want["extra_payload_bytes"]
    # same conv population: the accounting reads grads, never
    # perturbs the compute
    assert len(analysis.conv_eqns(inst.jaxpr)) == \
        len(analysis.conv_eqns(base.jaxpr))


def test_numerics_disabled_step_is_byte_identical():
    """The SAME step code with a disabled NumericsMonitor must lower
    to a graph with no numerics residue: the monitor state is an empty
    pytree and every mutator an identity, so the traced jaxpr is
    byte-for-byte the uninstrumented step's."""
    _assert_clean("ddp_resnet18_o2_numerics_off", rules=["numerics"])
    base = analysis.get("ddp_resnet18_o2").graph()
    off = analysis.get("ddp_resnet18_o2_numerics_off").graph()
    assert str(off.jaxpr) == str(base.jaxpr)


def test_supervised_step_is_byte_identical_both_ways():
    """The operational-plane contract (PR 10): a run-supervised train
    step is the UNSUPERVISED step to the byte — the supervisor
    consumes host-side flush points only, so RunSupervisor.wrap_step
    must be an identity whether the supervisor is enabled or
    disabled.  Unlike the numerics monitor there is no planned
    collective delta: zero host transfers, zero extra eqns, the
    identical jaxpr string, in BOTH directions."""
    base = analysis.get("ddp_resnet18_o2").graph()
    for name in ("ddp_resnet18_o2_supervised",
                 "ddp_resnet18_o2_supervised_off"):
        _assert_clean(name, rules=["supervisor", "host-transfer",
                                   "collective"])
        g = analysis.get(name).graph()
        assert str(g.jaxpr) == str(base.jaxpr), name
        assert analysis.host_transfer_eqns(g.jaxpr) == []


# -- collective accounting: the comm pattern is what DDP assumes ----------

def test_ddp_collective_accounting():
    """Exact psum census for the O2 step: one psum per
    allreduce_comm_plan bucket (fp32 batchnorm stash + chunked bf16
    bulk) + the axis-size scalar + the loss pmean — and the on-wire
    bytes match the plan to the byte (chunk padding included)."""
    _assert_clean("ddp_resnet18_o2", rules=["collective"])
    want = analysis.get("ddp_resnet18_o2").expect["collectives"]
    assert want["counts"]["psum"] == 4        # 2 buckets + 2 scalars
    g = analysis.get("ddp_resnet18_o2").graph()
    total = sum(analysis.eqn_payload_bytes(e)
                for e in analysis.collective_eqns(g.jaxpr))
    assert total == want["payload_bytes"]


def test_hier_ddp_collective_accounting():
    """The hierarchical O2 step carries the exact two-level pattern the
    plan derives: per grad bucket one in-slice reduce_scatter, the DCN
    reduce on the 1/ici shard (a psum, or a second bf16 all_gather in
    the compressed variant), and the in-slice all_gather back — with
    the per-primitive payload split pinning that the DCN hop really is
    1/ici of the flat payload (a bucket sneaking a full-size DCN psum
    is the mutation tests/test_analysis.py proves the rule catches)."""
    _assert_clean("ddp_resnet18_o2_hier", rules=["collective"])
    _assert_clean("ddp_resnet18_o2_hier_bf16", rules=["collective"])
    want = analysis.get("ddp_resnet18_o2_hier").expect["collectives"]
    nbuckets = want["counts"]["reduce_scatter"]
    assert nbuckets >= 1
    assert want["counts"]["all_gather"] == nbuckets
    assert want["counts"]["psum"] == nbuckets + 2   # DCN hops + scalars
    # DCN bytes (the bucket psums minus the two 4-byte scalars) are
    # EXACTLY 1/ici of the full bucket payload — which is what the
    # in-slice reduce_scatter carries (what a flat unchunked psum
    # would put on DCN)
    dcn = want["payload_bytes_by_primitive"]["psum"] - 8
    full = want["payload_bytes_by_primitive"]["reduce_scatter"]
    ici = 4
    assert dcn * ici == full
    # compressed: the DCN hop halves again, moving to the bf16 gather
    wantc = analysis.get(
        "ddp_resnet18_o2_hier_bf16").expect["collectives"]
    assert wantc["counts"]["psum"] == 2              # scalars only
    assert wantc["counts"]["all_gather"] == 2 * nbuckets


def test_tp_collective_accounting():
    """The DPxTP ParallelMLP step carries exactly the Megatron comm
    pattern: one row-parallel forward psum over the model axis plus
    the DDP grad bucket (+ axis-size scalar) over data."""
    _assert_clean("tp_mlp_train_step")


# -- transformer families -------------------------------------------------

def test_gpt_o2_step_large_dots_bf16():
    """Every activation/param-sized matmul in the GPT O2 train step —
    qkv/attention/MLP/fused-head, fwd and bwd — must run on bf16
    operands (fp32 stays in accumulators via preferred_element_type;
    an operand upcast would halve MXU rate and double HBM traffic).
    The rule's >= 10 dot floor keeps it non-vacuous."""
    _assert_clean("gpt_o2_train_step", rules=["amp-dtype"])


def test_llama_o2_step_large_dots_bf16():
    _assert_clean("llama_o2_train_step", rules=["amp-dtype"])


# -- serving decode window ------------------------------------------------

def test_serving_window_step_zero_host_transfers():
    """The jitted K-tick decode window must contain ZERO host-transfer
    primitives: the whole point of the window is that the host touches
    the device once per K tokens."""
    _assert_clean("engine_step_k", rules=["host-transfer"])


def test_serving_window_step_cache_buffers_donated():
    """The big mutated decode-window inputs — ids, the KV cache tree,
    the RNG keys — must be DONATED (input/output aliased in the lowered
    module); the per-slot length vector (cur_len) is on the permanent
    donation blocklist (donating it corrupts executables reloaded from
    the persistent XLA:CPU compile cache — serving.DONATION_BLOCKLIST).
    Admission-path mutators donate too (cache scattered in place)."""
    _assert_clean("engine_step_k", rules=["donation"])
    _assert_clean("engine_prefill_slot", rules=["donation"])
    # every donated buffer really got a tf.aliasing_output attribute
    g = analysis.get("engine_step_k").graph()
    n_cache = len(jax.tree_util.tree_leaves(g.example_args[2]))
    assert analysis.aliased_output_count(g.stablehlo) == n_cache + 2
    gp = analysis.get("engine_prefill_slot").graph()
    assert analysis.aliased_output_count(gp.stablehlo) == n_cache + 1
    # and the blocklisted length vector is NOT among the donated args
    donated, _ = analysis.donated_arg_names(g.lowered, g.arg_names)
    assert "cur_len" not in donated


def test_seq2seq_window_step_donation():
    _assert_clean("seq2seq_step_k")


# -- the acceptance pin: the clean repo lints clean -----------------------

def test_full_registry_zero_findings():
    """`python -m apex_tpu.analysis` must report zero findings on the
    clean repo — same registry, same rules, same implementation (the
    graphs are already traced and cached by the tests above, so this
    is cheap)."""
    findings = analysis.analyze()
    assert not findings, "\n".join(str(f) for f in findings)


# -- runtime host-sync arithmetic (not expressible as a jaxpr property) ---

def test_serving_window_host_syncs_per_token():
    """The acceptance number: with window=K the engine pays <= 1/K
    host syncs per generated token (pinned via the engine metrics),
    while ``engine_decode_steps_total`` keeps counting device
    dispatches and the decode histogram observes PER-TOKEN latency."""
    from apex_tpu import models, serving
    m = models.GPT(models.GPTConfig(vocab_size=64, block_size=32,
                                    n_layer=2, n_head=4, n_embd=32,
                                    dropout=0.0, n_kv_head=2))
    params, _ = m.init(jax.random.PRNGKey(0))
    eng = serving.Engine(m, params, slots=2, buf_len=32, window=8)
    prompt = list(np.random.RandomState(5).randint(0, 64, 4))
    rid = eng.add_request(prompt, max_new_tokens=16)
    while eng.live():
        eng.step()
    s = eng.stats()
    assert len(eng.result(rid)) == 16
    assert s["host_syncs"] == 2                 # ceil(16 / 8) windows
    assert s["host_syncs"] / s["tokens_generated"] <= 1 / 8
    assert s["tokens_per_sync"] == 8.0
    assert s["window"] == 8
    assert s["decode_steps"] == 2               # dispatches, not ticks
    assert s["decode_step_latency"]["count"] == 2
    assert eng.metrics.counter("engine_host_syncs_total").value == 2
    assert eng.metrics.counter("engine_decode_steps_total").value == 2
    assert eng.metrics.gauge("engine_window_size").value == 8.0
    # one live slot, full windows: utilization pinned at 1.0
    assert eng.metrics.gauge("engine_window_utilization").value == 1.0
    assert s["window_utilization"] == 1.0
