"""Trace-level audit of the hot training step (perf regression guards).

The round-3 perf campaign showed the headline cost lives in the conv
backward + optimizer (PERF_NOTES_r3.md); these tests pin the properties
that keep that cost minimal and that a silent regression would destroy:

  * under amp O2 every convolution in the jitted train step — forward,
    dgrad, and wgrad — consumes bf16 operands (a policy or cast bug
    that upcasts one conv family to fp32 would double its time on the
    MXU and halve effective HBM bandwidth);
  * the channels-last (NHWC input_format) step stays transpose-free on
    activation-sized tensors (the whole point of the layout mode —
    reference-side analogue: --channels-last in
    examples/imagenet/main_amp.py).

Jaxpr properties are backend-independent, so the guard runs on the CPU
mesh while asserting what the TPU executable will see.
"""

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu import amp, observability, optimizers, parallel, models
from apex_tpu.nn import functional as F


def _traced_step(channels_last=False, input_format="NCHW", stem="conv7",
                 B=8, image=32, telemetry=False):
    """Trace the REAL DDP train step — shard_map over the 8-device CPU
    mesh with the grad allreduce inside — so the audit covers the same
    graph bench.py's headline and the imagenet example execute.

    ``telemetry=True`` threads an observability.DeviceMetrics state
    through the step carry (step/overflow counters, loss-scale and
    grad-norm gauges) — the fully-instrumented shape of the hot loop."""
    from jax.sharding import Mesh, PartitionSpec as P

    model, opt = amp.initialize(
        models.resnet18(num_classes=10, channels_last=channels_last,
                        input_format=input_format, stem=stem),
        optimizers.FusedAdam(1e-3), opt_level="O2", verbosity=0)
    ddp = parallel.DistributedDataParallel(model)
    params, bn = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    rng = np.random.RandomState(0)
    shape = (B, 3, image, image) if input_format == "NCHW" \
        else (B, image, image, 3)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, B), jnp.int32)
    dm = observability.DeviceMetrics(
        counters=("steps", "overflows"),
        gauges=("loss_scale", "grad_norm")) if telemetry else None

    def step(state, batch):
        if telemetry:
            params, bn, ost, tele = state
        else:
            params, bn, ost = state
        xb, yb = batch

        def loss_fn(p):
            out, nb = model.apply(p, xb, state=bn, train=True)
            return F.cross_entropy(out, yb), nb

        loss, nb, g = amp.scaled_grad(loss_fn, params, ost, has_aux=True)
        g = ddp.allreduce_grads(g)
        params, ost2, info = opt.step(params, ost, g)
        if telemetry:
            tele = dm.inc(tele, "steps")
            tele = dm.inc(tele, "overflows", info["found_inf"])
            tele = dm.set(tele, "loss_scale", info["loss_scale"])
            tele = dm.set(tele, "grad_norm", info["grad_norm"])
            return (params, nb, ost2, tele), jax.lax.pmean(loss, "data")
        return (params, nb, ost2), jax.lax.pmean(loss, "data")

    state = (params, bn, ost) + ((dm.init(),) if telemetry else ())
    mesh = Mesh(np.array(jax.devices()), ("data",))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), (P("data"), P("data"))),
                           out_specs=(P(), P()), check_vma=False)
    return jax.make_jaxpr(mapped)(state, (x, y))


def _walk(jaxpr):
    """Yield every eqn in a (closed) jaxpr, recursing into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in jax.tree_util.tree_leaves(
                    v, is_leaf=lambda x: isinstance(
                        x, (jax.extend.core.Jaxpr, jax.extend.core.ClosedJaxpr))):
                if isinstance(sub, jax.extend.core.ClosedJaxpr):
                    yield from _walk(sub.jaxpr)
                elif isinstance(sub, jax.extend.core.Jaxpr):
                    yield from _walk(sub)


def test_o2_step_convs_all_bf16():
    jpr = _traced_step()
    convs = [e for e in _walk(jpr.jaxpr)
             if e.primitive.name == "conv_general_dilated"]
    # resnet18 fwd has 20 convs (incl. 3 downsample); backward adds
    # dgrad+wgrad per conv minus the input dgrad -> sanity-floor only
    assert len(convs) >= 40, f"expected fwd+bwd convs, got {len(convs)}"
    bad = [(e.invars[0].aval.dtype, e.invars[1].aval.dtype)
           for e in convs
           if not (e.invars[0].aval.dtype == jnp.bfloat16
                   and e.invars[1].aval.dtype == jnp.bfloat16)]
    assert not bad, f"non-bf16 convs in O2 step: {bad[:5]} (+{len(bad)} total)"


def test_o2_nhwc_step_transpose_free():
    jpr = _traced_step(channels_last=True, input_format="NHWC")
    big_transposes = [e for e in _walk(jpr.jaxpr)
                      if e.primitive.name == "transpose"
                      and np.prod(e.invars[0].aval.shape) >= 4 * 3 * 32 * 32]
    assert not big_transposes, (
        "activation-sized transposes in the NHWC step: "
        f"{[(e.invars[0].aval.shape, e.params) for e in big_transposes[:4]]}")


def test_o2_s2d_nhwc_step_convs_bf16_and_transpose_free():
    jpr = _traced_step(channels_last=True, input_format="NHWC",
                       stem="space_to_depth")
    convs = [e for e in _walk(jpr.jaxpr)
             if e.primitive.name == "conv_general_dilated"]
    bad = [e for e in convs if e.invars[0].aval.dtype != jnp.bfloat16
           or e.invars[1].aval.dtype != jnp.bfloat16]
    assert not bad
    # the 6-D block rearrange inside F.space_to_depth is the ONE
    # legitimate activation transpose (forward-only: the input is a
    # constant, so no gradient flows back through it); anything else
    # would be a layout leak
    big_transposes = [e for e in _walk(jpr.jaxpr)
                      if e.primitive.name == "transpose"
                      and np.prod(e.invars[0].aval.shape) >= 4 * 3 * 32 * 32
                      and e.invars[0].aval.ndim != 6]
    assert not big_transposes
    s2d_rearranges = [e for e in _walk(jpr.jaxpr)
                      if e.primitive.name == "transpose"
                      and e.invars[0].aval.ndim == 6]
    assert len(s2d_rearranges) <= 1, (
        f"s2d rearrange should appear once (forward), got "
        f"{len(s2d_rearranges)}")


# -- telemetry ------------------------------------------------------------

# primitives that move data across the host boundary: any of these inside
# the step jaxpr means a per-iteration host sync — the exact cost the
# device-resident scaler (and now the device-resident telemetry) exists
# to avoid
_HOST_TRANSFER_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                        "callback", "outfeed", "infeed", "device_put"}


def _host_transfers(jpr):
    return [e.primitive.name for e in _walk(jpr.jaxpr)
            if e.primitive.name in _HOST_TRANSFER_PRIMS]


def test_telemetry_step_adds_zero_host_transfers():
    """Enabling DeviceMetrics telemetry on the jitted DDP+amp-O2 train
    step must add ZERO host transfers: the counters/gauges accumulate as
    jnp scalars in the step carry and only flush() (outside the step)
    touches the host.  A callback- or outfeed-based metrics
    implementation would turn every train step into a host round-trip —
    the regression this guard exists to catch."""
    base = _traced_step()
    tele = _traced_step(telemetry=True)
    assert _host_transfers(tele) == _host_transfers(base) == []
    # the instrumented graph keeps the same conv population — telemetry
    # reads existing step outputs (found_inf, loss scale, grad norm)
    # instead of perturbing the compute
    def convs(j):
        return len([e for e in _walk(j.jaxpr)
                    if e.primitive.name == "conv_general_dilated"])
    assert convs(tele) == convs(base)


# -- transformer families ------------------------------------------------

def _transformer_step_jaxpr(family):
    """Trace the real O2 DDP train step (fused-head loss) for a tiny
    transformer config over the 8-device CPU mesh."""
    from jax.sharding import Mesh, PartitionSpec as P

    if family == "gpt":
        net = models.GPT(models.GPTConfig(
            vocab_size=97, block_size=16, n_layer=2, n_head=4,
            n_embd=32, dropout=0.0))
    else:
        net = models.Llama(models.LlamaConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=16,
            tie_word_embeddings=True))
    model, opt = amp.initialize(net, optimizers.FusedAdam(1e-3),
                                opt_level="O2", verbosity=0)
    ddp = parallel.DistributedDataParallel(model)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 97, (8, 16)))

    def step(state, batch):
        params, ost = state
        (ids_b,) = batch

        def loss_fn(p):
            return model.loss(p, ids_b), ()

        loss, _, g = amp.scaled_grad(loss_fn, params, ost, has_aux=True)
        g = ddp.allreduce_grads(g)
        params, ost2, _ = opt.step(params, ost, g)
        return (params, ost2), jax.lax.pmean(loss, "data")

    mesh = Mesh(np.array(jax.devices()), ("data",))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), (P("data"),)),
                           out_specs=(P(), P()), check_vma=False)
    return jax.make_jaxpr(mapped)((params, ost), (ids,))


def _large_dots(jpr, min_elems=256):
    return [e for e in _walk(jpr.jaxpr)
            if e.primitive.name == "dot_general"
            and all(int(np.prod(v.aval.shape)) >= min_elems
                    for v in e.invars)]


def _assert_dots_bf16(jpr):
    dots = _large_dots(jpr)
    assert len(dots) >= 10, f"expected fwd+bwd dots, got {len(dots)}"
    bad = [tuple(v.aval.dtype for v in e.invars) for e in dots
           if not all(v.aval.dtype == jnp.bfloat16 for v in e.invars)]
    assert not bad, (f"non-bf16 large dots in O2 step: {bad[:6]} "
                     f"(+{len(bad)} total); fp32 accumulation belongs "
                     f"in preferred_element_type, not operand upcasts")


def test_gpt_o2_step_large_dots_bf16():
    """Every activation/param-sized matmul in the GPT O2 train step —
    qkv/attention/MLP/fused-head, fwd and bwd — must run on bf16
    operands (fp32 stays in accumulators via preferred_element_type;
    an operand upcast would halve MXU rate and double HBM traffic)."""
    _assert_dots_bf16(_transformer_step_jaxpr("gpt"))


def test_llama_o2_step_large_dots_bf16():
    _assert_dots_bf16(_transformer_step_jaxpr("llama"))


# -- serving decode window ------------------------------------------------

def _window_engine(window=8):
    from apex_tpu import serving
    m = models.GPT(models.GPTConfig(vocab_size=64, block_size=32,
                                    n_layer=2, n_head=4, n_embd=32,
                                    dropout=0.0, n_kv_head=2))
    params, _ = m.init(jax.random.PRNGKey(0))
    eng = serving.Engine(m, params, slots=2, buf_len=32, window=window)
    return eng, m, params


def _window_args(eng):
    return (eng.ids, eng.cur_len, eng.cache, eng._slot_keys,
            eng._slot_temp, eng.limit, eng._eos)


def test_serving_window_step_zero_host_transfers():
    """The jitted K-tick decode window must contain ZERO host-transfer
    primitives: the whole point of the window is that the host touches
    the device once per K tokens — a callback/outfeed smuggled into the
    scan would reintroduce the per-token sync tax."""
    eng, _, _ = _window_engine(window=8)
    jpr = jax.make_jaxpr(eng._step_k)(*_window_args(eng))
    assert _host_transfers(jpr) == []


def test_serving_window_step_cache_buffers_donated():
    """The big mutated decode-window inputs — ids, the KV cache tree,
    the RNG keys — must be DONATED (input/output aliased in the
    lowered module): without donation XLA keeps a second copy of the
    multi-GB cache alive across every dispatch.  The per-slot length
    vector (cur_len) is deliberately NOT donated — donating that
    argnum class corrupts executables reloaded from the persistent
    XLA:CPU compilation cache (serving.py's _sstep note).  The
    lowering emits one ``tf.aliasing_output`` attribute per donated
    buffer."""
    eng, _, _ = _window_engine(window=8)
    txt = eng._step_k.lower(*_window_args(eng)).as_text()
    n_cache = len(jax.tree_util.tree_leaves(eng.cache))
    want = n_cache + 2              # + ids, slot keys
    got = txt.count("tf.aliasing_output")
    assert got == want, (
        f"expected {want} donated buffers (cache {n_cache} + ids + "
        f"keys), lowering aliases {got}")
    # admission-path mutators donate too (cache scattered in place)
    ptxt = eng._prefill_slot.lower(
        eng.ids, eng.cache, None, 0,
        jnp.zeros((32,), jnp.int32)).as_text()
    assert ptxt.count("tf.aliasing_output") == n_cache + 1  # + ids


def test_serving_window_host_syncs_per_token():
    """The acceptance number: with window=K the engine pays <= 1/K
    host syncs per generated token (pinned via the engine metrics),
    while ``engine_decode_steps_total`` keeps counting device
    dispatches and the decode histogram observes PER-TOKEN latency."""
    eng, _, _ = _window_engine(window=8)
    prompt = list(np.random.RandomState(5).randint(0, 64, 4))
    rid = eng.add_request(prompt, max_new_tokens=16)
    while eng.live():
        eng.step()
    s = eng.stats()
    assert len(eng.result(rid)) == 16
    assert s["host_syncs"] == 2                 # ceil(16 / 8) windows
    assert s["host_syncs"] / s["tokens_generated"] <= 1 / 8
    assert s["tokens_per_sync"] == 8.0
    assert s["window"] == 8
    assert s["decode_steps"] == 2               # dispatches, not ticks
    assert s["decode_step_latency"]["count"] == 2
    assert eng.metrics.counter("engine_host_syncs_total").value == 2
    assert eng.metrics.counter("engine_decode_steps_total").value == 2
    assert eng.metrics.gauge("engine_window_size").value == 8.0
    # one live slot, full windows: utilization pinned at 1.0
    assert eng.metrics.gauge("engine_window_utilization").value == 1.0
    assert s["window_utilization"] == 1.0
