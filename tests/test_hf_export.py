"""Round-trip checkpoint portability: an apex_tpu-trained Llama tree
exports to a transformers state_dict that loads cleanly and produces
IDENTICAL logits — users can leave as easily as they arrive."""

import numpy as np
import jax
import jax.numpy as jnp

from apex_tpu.models import Llama, LlamaConfig


def test_llama_roundtrip_through_hf():
    import torch
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM
    from apex_tpu.utils import hf_interop

    cfg = LlamaConfig(vocab_size=151, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=48,
                      tie_word_embeddings=False)
    m = Llama(cfg)
    params, _ = m.init(jax.random.PRNGKey(0))

    # ...pretend we trained; export to HF and load
    sd = hf_interop.llama_to_hf(cfg, params)
    hf = LlamaForCausalLM(HFConfig(
        vocab_size=151, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=48,
        tie_word_embeddings=False, attn_implementation="eager")).eval()
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    assert not unexpected, unexpected
    # rotary inv_freq buffers may appear as missing; no weights may
    assert all("rotary" in k or "inv_freq" in k for k in missing), missing

    ids = np.random.RandomState(0).randint(0, 151, (2, 20))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    ours = np.asarray(m(params, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    # and back again: from_hf of the exported model is bit-identical
    cfg2, params2 = hf_interop.llama_from_hf(hf)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
