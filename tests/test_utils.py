"""apex_tpu.utils: profiler range shims and AverageMeter."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu.utils import (range_push, range_pop, nvtx_range, annotate,
                            AverageMeter)
from apex_tpu.analysis import lowered_text


def test_range_push_pop_balanced():
    assert range_push("outer") == 1
    assert range_push("inner") == 2
    assert range_pop() == 1
    assert range_pop() == 0


def test_range_pop_unbalanced_raises():
    with pytest.raises(RuntimeError, match="range_pop"):
        range_pop()


def test_nvtx_range_inside_jit_names_hlo():
    @jax.jit
    def f(x):
        with nvtx_range("my_hot_section"):
            return x * 2.0

    x = jnp.ones((4,))
    np.testing.assert_allclose(np.asarray(f(x)), 2.0)
    # lowered_text papers over the as_text(debug_info=) API drift
    # (jax 0.4.x wants get_asm(enable_debug_info=True))
    hlo = lowered_text(f.lower(x), debug_info=True)
    assert "my_hot_section" in hlo


def test_annotate_decorator():
    @annotate("scaled_add")
    def g(a, b):
        return a + 2 * b

    assert float(g(jnp.ones(()), jnp.ones(()))) == 3.0
    assert g.__name__ == "g"


def test_average_meter():
    m = AverageMeter()
    m.update(1.0)
    m.update(3.0)
    assert m.avg == 2.0 and m.val == 3.0 and m.count == 2
    m.update(5.0, n=2)
    assert m.count == 4 and m.avg == pytest.approx(3.5)
    m.reset()
    assert m.count == 0 and m.avg == 0.0


def test_syncbn_emits_named_scope():
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.parallel import SyncBatchNorm
    from apex_tpu import nn

    bn = SyncBatchNorm(4)
    params, state = bn.init(jax.random.PRNGKey(0))
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def fwd(p, x):
        out, _ = nn.apply(bn, p, x, state=state, train=True)
        return out

    x = jnp.ones((4, 4, 2, 2))
    lowered = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P("data")), out_specs=P("data"),
        check_vma=False)).lower(params, x)
    assert "sync_bn_stats" in lowered_text(lowered, debug_info=True)
