"""Training-run supervisor: every seeded anomaly detected within a
bounded observation count, episode-deduped ring events, schema-clean
``kind: run`` records, the checkpoint-fed progress watermark, and the
host-side-only contract (the graph side of which is audit-pinned in
tests/test_step_graph_audit.py).

The supervisor is deterministic over its observation feed, so each
anomaly scenario seeds exactly one pathology into an otherwise healthy
signal stream and asserts the detector fires AT the expected
observation — not just eventually."""

import json
import math

import pytest

from apex_tpu.observability import (EventRing, MetricsRegistry,
                                    RunSupervisor, SupervisorConfig,
                                    exporters)
from apex_tpu.observability.supervisor import ANOMALY_KINDS


def _sup(**kw):
    kw.setdefault("ring", EventRing(capacity=64))
    kw.setdefault("registry", MetricsRegistry())
    kw.setdefault("config", SupervisorConfig(stall_observations=4,
                                             warmup_observations=3))
    return RunSupervisor("t", **kw)


def _healthy(sup, n, start_step=0, loss=1.0, dt=0.01):
    for i in range(n):
        assert sup.observe_step(step=start_step + i, loss=loss,
                                step_time_s=dt) == []


# -- the seeded anomalies, each within a bounded count --------------------

def test_stall_detected_within_bound():
    """A frozen step counter fires the stall after EXACTLY
    stall_observations observations without progress — and once per
    episode, with recovery re-arming the detector."""
    sup = _sup()
    _healthy(sup, 3)
    fired_at = None
    for k in range(1, 10):
        found = sup.observe_step(step=2, loss=1.0, step_time_s=0.01)
        if found:
            assert fired_at is None, "stall must fire once per episode"
            fired_at = k
            assert found[0]["kind"] == "stall"
    assert fired_at == sup.config.stall_observations
    assert sup.verdict == "attention"
    ok, detail = sup.health_check()
    assert not ok and "stalled" in detail
    # progress ends the episode and restores liveness...
    assert sup.observe_step(step=3, loss=1.0) == []
    assert sup.health_check()[0]
    # ...and a second stall is a fresh episode that fires again
    for _ in range(sup.config.stall_observations):
        found = sup.observe_step(step=3, loss=1.0)
    assert found and found[0]["kind"] == "stall"
    assert sup._counts["stall"] == 2
    assert [e["kind"] for e in sup.ring.snapshot(kind="run_stall")] \
        == ["run_stall", "run_stall"]


def test_loss_spike_detected_and_episode_deduped():
    sup = _sup()
    _healthy(sup, 6, loss=1.0)
    found = sup.observe_step(step=6, loss=10.0, step_time_s=0.01)
    assert [a["kind"] for a in found] == ["loss_spike"]
    assert found[0]["factor"] > sup.config.loss_spike_factor
    # still spiked: same episode, no refire; the EWMA must NOT have
    # chased the spike
    assert sup.observe_step(step=7, loss=10.0) == []
    assert sup.status()["loss"]["ewma"] == pytest.approx(1.0)
    # recovery closes the episode; a later spike is a new anomaly
    assert sup.observe_step(step=8, loss=1.0) == []
    found = sup.observe_step(step=9, loss=8.0)
    assert [a["kind"] for a in found] == ["loss_spike"]
    assert sup._counts["loss_spike"] == 2


def test_seeded_nan_loss_detected_immediately():
    sup = _sup()
    _healthy(sup, 2)
    found = sup.observe_step(step=2, loss=float("nan"))
    assert [a["kind"] for a in found] == ["nan"]
    ok, detail = sup.health_check()
    assert not ok and "nan" in detail
    evs = sup.ring.snapshot(kind="run_nan")
    assert len(evs) == 1 and evs[0]["run"] == "t"
    # a loss that STAYS nonfinite is one episode: no refire, no ring
    # flood (the shed-episode rule) — but liveness stays unhealthy
    assert sup.observe_step(step=3, loss=float("inf")) == []
    assert len(sup.ring.snapshot(kind="run_nan")) == 1
    assert not sup.health_check()[0]
    # recovery restores liveness (a past anomaly degrades the verdict,
    # never the probe — an orchestrator must not kill a healed run)
    assert sup.observe_step(step=4, loss=1.0) == []
    assert sup.health_check()[0]
    assert sup.verdict == "attention"
    # a SECOND nonfinite excursion is a fresh episode and fires again
    found = sup.observe_step(step=5, loss=float("inf"))
    assert [a["kind"] for a in found] == ["nan"]
    assert sup._counts["nan"] == 2


def test_seeded_nan_via_numerics_flush_names_culprit():
    """The numerics-side NaN path: a flushed NumericsMonitor summary
    with new overflow steps raises a nan anomaly carrying the culprit
    layer — the same attribution the flight ring's scaler_skip event
    names (PR 9), now surfaced as a run verdict."""
    sup = _sup()
    _healthy(sup, 2)
    flushed = {"overflow_steps": 1, "culprit": "layer1/conv/kernel",
               "culprit_nonfinite": 7, "loss_scale": 32768.0}
    found = sup.observe_step(step=2, loss=1.0, numerics=flushed)
    assert [a["kind"] for a in found] == ["nan"]
    assert found[0]["culprit"] == "layer1/conv/kernel"
    assert found[0]["culprit_nonfinite"] == 7
    # the SAME cumulative total does not re-fire (flush-delta dedup)
    assert sup.observe_step(step=3, loss=1.0, numerics=flushed) == []
    # a new overflow does
    flushed2 = dict(flushed, overflow_steps=2)
    assert [a["kind"] for a in
            sup.observe_step(step=4, loss=1.0, numerics=flushed2)] \
        == ["nan"]


def test_throughput_regression_detected():
    sup = _sup()
    _healthy(sup, 6, dt=0.010)
    found = sup.observe_step(step=6, loss=1.0, step_time_s=0.05)
    assert [a["kind"] for a in found] == ["throughput_regression"]
    assert found[0]["factor"] > sup.config.throughput_regression_factor
    # sustained slowness: one episode
    assert sup.observe_step(step=7, loss=1.0, step_time_s=0.05) == []
    # the EWMA did not absorb the regressed samples
    assert sup.status()["step_time_s"]["ewma"] == pytest.approx(0.010)


def test_one_replica_divergence_detected():
    """A flushed divergence digest whose desync counter advanced is
    the one-replica-drifted signal; the anomaly names the worst
    leaf."""
    sup = _sup()
    _healthy(sup, 3)
    insync = {"divergence": {"max_rel_dev": 1e-9, "desync_steps": 0,
                             "in_sync": True, "worst_leaf": None}}
    assert sup.observe_step(step=3, loss=1.0, numerics=insync) == []
    div = {"divergence": {"max_rel_dev": 0.3, "desync_steps": 2,
                          "in_sync": False,
                          "worst_leaf": "blocks/0/w"}}
    found = sup.observe_step(step=4, loss=1.0, numerics=div)
    assert [a["kind"] for a in found] == ["replica_divergence"]
    assert found[0]["worst_leaf"] == "blocks/0/w"
    assert found[0]["max_rel_dev"] == pytest.approx(0.3)
    # same cumulative desync count: no refire
    assert sup.observe_step(step=5, loss=1.0, numerics=div) == []
    evs = sup.ring.snapshot(kind="run_replica_divergence")
    assert len(evs) == 1


# -- progress watermark consumes checkpoint_saved -------------------------

def test_checkpoint_event_advances_watermark():
    """A run writing checkpoints is making durable progress: the
    checkpoint_saved flight event (utils/checkpoint emits it) holds
    the stall watchdog off even when the caller has no step counter
    to report."""
    ring = EventRing(capacity=64)
    sup = _sup(ring=ring)
    stall_n = sup.config.stall_observations
    for i in range(3 * stall_n):
        if i % 2 == 0:
            ring.append("checkpoint_saved", step=i, bytes=128,
                        path="/tmp/x", async_save=False)
        assert sup.observe_step(loss=1.0) == []   # no step= at all
    assert sup.status()["checkpoint"]["count"] == 3 * stall_n // 2
    # checkpoints stop -> the stall fires within the bound (the last
    # consumed checkpoint re-anchored the watermark one observation
    # after its append, hence the +1)
    fired = []
    for _ in range(stall_n + 1):
        fired += sup.observe_step(loss=1.0)
    assert [a["kind"] for a in fired] == ["stall"]


def test_real_npz_checkpoint_feeds_watermark(tmp_path):
    """End to end through utils/checkpoint: save_checkpoint emits the
    checkpoint_saved event onto the ring the supervisor consumes, and
    the save/restore telemetry lands in the registry."""
    import numpy as np
    from apex_tpu.observability import flightrec
    from apex_tpu.utils import checkpoint as ckpt

    ring = EventRing(capacity=64)
    reg = MetricsRegistry()
    prev_ring = flightrec.set_ring(ring)
    try:
        from apex_tpu.observability import metrics as obs_metrics
        prev_reg = obs_metrics.set_registry(reg)
        try:
            sup = _sup(ring=ring, registry=reg)
            tree = {"w": np.ones((4, 4), np.float32)}
            ckpt.save_checkpoint(str(tmp_path), 7, tree)
            assert sup.observe_step(loss=1.0) == []
            assert sup.status()["checkpoint"] == {"count": 1,
                                                  "last_step": 7}
            ckpt.restore_checkpoint(str(tmp_path), tree)
        finally:
            obs_metrics.set_registry(prev_reg)
    finally:
        flightrec.set_ring(prev_ring)
    evs = ring.snapshot(kind="checkpoint_saved")
    assert len(evs) == 1 and evs[0]["step"] == 7
    assert evs[0]["bytes"] == 64
    assert reg.get("checkpoint_save_seconds").count == 1
    assert reg.get("checkpoint_restore_seconds").count == 1
    assert reg.get("checkpoint_snapshot_bytes").value == 64.0
    assert reg.get("checkpoint_saves_total").value == 1


# -- records / reports / contract ----------------------------------------

def test_run_record_validates_and_reflects_anomalies():
    sup = _sup()
    _healthy(sup, 6)
    sup.observe_step(step=6, loss=50.0)            # spike
    sup.observe_step(step=7, loss=float("nan"))    # nan
    rec = exporters.JsonlExporter.enrich(
        sup.record(metric="unit_run"))
    assert exporters.validate_run_record(rec) == []
    assert exporters.validate_telemetry_record(rec) == []
    assert rec["verdict"] == "attention"
    assert rec["anomaly_counts"]["loss_spike"] == 1
    assert rec["anomaly_counts"]["nan"] == 1
    assert {a["kind"] for a in rec["anomalies"]} == {"loss_spike",
                                                     "nan"}
    # every anomaly detail names a known kind and its observation
    for a in rec["anomalies"]:
        assert a["kind"] in ANOMALY_KINDS
        assert a["observation"] >= 1


def test_healthy_run_record_is_ok():
    sup = _sup()
    _healthy(sup, 10)
    rec = exporters.JsonlExporter.enrich(sup.record())
    assert exporters.validate_run_record(rec) == []
    assert rec["verdict"] == "ok"
    assert rec["watermark"] == 9
    assert sum(rec["anomaly_counts"].values()) == 0


def test_write_report_artifact(tmp_path):
    sup = _sup()
    _healthy(sup, 3)
    sup.observe_step(step=3, loss=float("nan"))
    path = sup.write_report(str(tmp_path / "run_report.json"))
    with open(path) as f:
        rep = json.load(f)
    assert rep["record"]["verdict"] == "attention"
    assert rep["status"]["anomaly_counts"]["nan"] == 1
    # the persisted record still validates once enriched
    rec = exporters.JsonlExporter.enrich(rep["record"])
    assert exporters.validate_run_record(rec) == []


def test_disabled_supervisor_is_inert():
    sup = _sup(enabled=False)
    assert sup.observe_step(step=0, loss=float("nan")) == []
    assert sup.verdict == "ok"
    assert sup.ring.snapshot(kind="run_nan") == []
    step = object()
    assert sup.wrap_step(step) is step


def test_wrap_step_is_identity_when_enabled():
    """The graph-side contract (the audit pins the jaxpr identity;
    this pins the object identity the audit relies on)."""
    sup = _sup(enabled=True)
    step = object()
    assert sup.wrap_step(step) is step


def test_registry_and_scaler_tap():
    reg = MetricsRegistry()
    sup = _sup(registry=reg)
    _healthy(sup, 3)
    sup.observe_step(step=5, loss=2.0, step_time_s=0.02,
                     comm_stats=[{"wire_bytes": 1024},
                                 {"wire_bytes": 512}])
    sup.observe_scaler({"loss_scale": 4096.0, "steps_skipped": 2,
                        "num_losses": 1, "per_loss": []})
    st = sup.status()
    assert st["comm"] == {"buckets": 2, "wire_bytes": 1536}
    assert st["scaler"]["loss_scale"] == 4096.0
    assert reg.get("run_progress_watermark") is not None
    anom = reg.get("run_anomalies_total")
    assert anom is None or anom.value == 0   # no anomaly fired yet


def test_amp_record_scaler_supervisor_kwarg():
    """amp.record_scaler(supervisor=) is the amp-side tap: the scaler
    snapshot reaches the supervisor's status page."""
    import jax
    from apex_tpu import amp, nn, optimizers

    model, opt = amp.initialize(nn.Linear(4, 2),
                                optimizers.FusedAdam(1e-3),
                                opt_level="O2", verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    sup = _sup()
    stats = amp.record_scaler(ost, registry=MetricsRegistry(),
                              supervisor=sup)
    assert sup.status()["scaler"]["loss_scale"] == stats["loss_scale"]


def test_supervisor_config_validation():
    with pytest.raises(ValueError):
        SupervisorConfig(stall_observations=0)
    with pytest.raises(ValueError):
        SupervisorConfig(loss_spike_factor=1.0)
    with pytest.raises(ValueError):
        SupervisorConfig(loss_alpha=0.0)
    with pytest.raises(ValueError):
        RunSupervisor("")


def test_anomaly_detail_list_is_bounded_counts_exact():
    cfg = SupervisorConfig(stall_observations=2,
                           warmup_observations=1, max_anomalies=3)
    sup = _sup(config=cfg)
    # 8 distinct nan EPISODES (each closed by a finite recovery) —
    # consecutive nonfinite observations inside one episode would
    # count once by design
    for i in range(8):
        sup.observe_step(step=2 * i, loss=float("nan"))
        sup.observe_step(step=2 * i + 1, loss=1.0)
    assert sup._counts["nan"] == 8
    rec = sup.record()
    assert len(rec["anomalies"]) == 3           # bounded details
    assert rec["anomaly_counts"]["nan"] == 8    # exact counts
    assert exporters.validate_run_record(
        exporters.JsonlExporter.enrich(rec)) == []


def test_nonfinite_ewma_guard():
    """A nonfinite step time must not poison the EWMA (NaN would make
    every later comparison silently false)."""
    sup = _sup()
    _healthy(sup, 4)
    sup.observe_step(step=4, loss=1.0, step_time_s=float("nan"))
    assert math.isfinite(sup.status()["step_time_s"]["ewma"])


def test_recovering_is_degraded_but_live(tmp_path):
    """PR 11: while a recovery controller is handling the run, the
    supervisor reports the distinct degraded-but-live 'recovering'
    state — /healthz must not 503 an orchestrator into a restart loop
    on a run that is already being fixed — and returns to honest
    sickness reporting the moment the recovery ends."""
    sup = _sup()
    # drive the run into a NaN episode: health goes 503-worthy
    sup.observe_step(step=0, loss=1.0)
    sup.observe_step(step=1, loss=float("nan"))
    ok, detail = sup.health_check()
    assert not ok and "nan" in detail
    # a recovery in flight supersedes the sickness: live, distinct
    sup.begin_recovery("rollback to step 0")
    assert sup.recovering
    ok, detail = sup.health_check()
    assert ok and detail.startswith("recovering:")
    assert "rollback to step 0" in detail
    st = sup.status()
    assert st["recovering"] == "rollback to step 0"
    assert st["recoveries"] == 1
    # recovery ends with the run still sick -> 503 again (honesty)
    sup.end_recovery()
    assert not sup.recovering
    ok, _ = sup.health_check()
    assert not ok
    # ... and a clean post-recovery observation recovers liveness
    sup.observe_step(step=2, loss=1.0)
    ok, _ = sup.health_check()
    assert ok
    kinds = [ev["kind"] for ev in sup.ring.snapshot()]
    assert "run_recovery_begin" in kinds
    assert "run_recovery_end" in kinds
    # the record still validates with the recovery fields around
    rec = exporters.JsonlExporter.enrich(sup.record())
    assert exporters.validate_run_record(rec) == []


# -- PR 15: recompilation storm -------------------------------------------

def _retrace(ring, entry="engine._step_k", cause="shape",
             culprit="ids", before="i32[4,32]", after="i32[4,48]"):
    ring.append("xla_retrace", entry=entry, cause=cause,
                culprit=culprit, before=before, after=after)


def test_recompilation_storm_detected_with_culprit():
    """storm_retraces signature-change retraces of ONE entry within
    the observation window fire EXACTLY one recompilation_storm whose
    detail carries the differ's verdict (entry, cause, culprit arg,
    before/after signatures) — episode-latched, re-arming once the
    window drains."""
    ring = EventRing(capacity=128)
    sup = _sup(ring=ring,
               config=SupervisorConfig(storm_retraces=3,
                                       storm_window_observations=6))
    _healthy(sup, 3)
    # two retraces: below threshold, no anomaly
    _retrace(ring)
    _retrace(ring)
    assert sup.observe_step(step=3, loss=1.0) == []
    # the third within the window fires the storm, naming the culprit
    _retrace(ring, after="i32[4,64]")
    found = sup.observe_step(step=4, loss=1.0)
    assert len(found) == 1
    ev = found[0]
    assert ev["kind"] == "recompilation_storm"
    assert ev["entry"] == "engine._step_k"
    assert ev["retraces_in_window"] == 3
    assert ev["cause"] == "shape"
    assert ev["culprit"] == "ids"
    assert ev["before"] == "i32[4,32]" and ev["after"] == "i32[4,64]"
    # episode latch: staying in the storm does not re-fire
    _retrace(ring)
    assert sup.observe_step(step=5, loss=1.0) == []
    assert sup.status()["recompilation"]["entries_in_storm"] == \
        ["engine._step_k"]
    # a storm degrades the verdict but never liveness (it is a
    # performance pathology, not a dead run)
    ok, _ = sup.health_check()
    assert ok and sup.verdict == "attention"
    # the window drains -> episode closes -> a fresh burst re-fires
    for i in range(8):
        assert sup.observe_step(step=6 + i, loss=1.0) == []
    assert sup.status()["recompilation"]["entries_in_storm"] == []
    for _ in range(3):
        _retrace(ring, cause="dtype", culprit="cache",
                 before="bf16[4,8]", after="f32[4,8]")
    found = sup.observe_step(step=20, loss=1.0)
    assert len(found) == 1 and found[0]["culprit"] == "cache"
    assert sup._counts["recompilation_storm"] == 2
    # the ring carries the run_* event and the record validates
    assert any(e["kind"] == "run_recompilation_storm"
               for e in ring.snapshot())
    rec = exporters.JsonlExporter.enrich(sup.record())
    assert exporters.validate_run_record(rec) == []


def test_storm_counts_per_entry_not_globally():
    """Retraces spread across DIFFERENT entries never pool into one
    storm — three entries retracing once each is churn, not a storm
    of any one of them."""
    ring = EventRing(capacity=64)
    sup = _sup(ring=ring,
               config=SupervisorConfig(storm_retraces=3,
                                       storm_window_observations=10))
    _healthy(sup, 2)
    for entry in ("a", "b", "c"):
        _retrace(ring, entry=entry)
    assert sup.observe_step(step=2, loss=1.0) == []
    assert sup._counts["recompilation_storm"] == 0


def test_storm_window_is_observation_counted():
    """Retraces older than storm_window_observations fall out of the
    window: a slow drip below the rate never fires."""
    ring = EventRing(capacity=64)
    sup = _sup(ring=ring,
               config=SupervisorConfig(storm_retraces=3,
                                       storm_window_observations=4))
    _healthy(sup, 2)
    for i in range(6):
        _retrace(ring)
        # 5 observations between retraces: each falls out before the
        # next arrives
        for j in range(5):
            assert sup.observe_step(step=2 + i * 5 + j,
                                    loss=1.0) == []
    assert sup._counts["recompilation_storm"] == 0


def test_storm_config_validation():
    with pytest.raises(ValueError, match="storm_retraces"):
        SupervisorConfig(storm_retraces=0)
    with pytest.raises(ValueError, match="storm_window"):
        SupervisorConfig(storm_window_observations=0)
    assert "recompilation_storm" in ANOMALY_KINDS


def test_storm_threshold_above_default_log_bound():
    """A threshold past the default 64-event retention still fires:
    the per-entry log is sized from the config, so a high-threshold
    detector cannot be silently capped below its own trigger."""
    ring = EventRing(capacity=256)
    sup = _sup(ring=ring,
               config=SupervisorConfig(storm_retraces=100,
                                       storm_window_observations=500))
    _healthy(sup, 2)
    for _ in range(100):
        _retrace(ring)
    found = sup.observe_step(step=2, loss=1.0)
    assert [a["kind"] for a in found] == ["recompilation_storm"]
    assert found[0]["retraces_in_window"] == 100
