"""GPT causal LM: shapes, causality, training descent, fixed-buffer
generation, and tensor-parallel parity."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import nn, models, optimizers
from conftest import assert_trees_close


def tiny_cfg(**kw):
    d = dict(vocab_size=64, block_size=16, n_layer=2, n_head=4,
             n_embd=32, dropout=0.0)
    d.update(kw)
    return models.GPTConfig(**d)


def test_forward_shapes_and_loss():
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 10)))
    logits = model(params, ids)
    assert logits.shape == (2, 10, 64)
    loss = model.loss(params, ids)
    assert np.isfinite(float(loss))
    # block_size guard
    with pytest.raises(ValueError, match="block_size"):
        model(params, jnp.zeros((1, 17), jnp.int32))


def test_causality():
    """Changing a future token must not change past logits."""
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 64, (1, 8))
    ids2 = ids.copy()
    ids2[0, 6] = (ids2[0, 6] + 1) % 64
    l1 = np.asarray(model(params, jnp.asarray(ids)))
    l2 = np.asarray(model(params, jnp.asarray(ids2)))
    np.testing.assert_array_equal(l1[0, :6], l2[0, :6])
    assert np.abs(l1[0, 6:] - l2[0, 6:]).max() > 0


def test_padding_mask_ignored_in_loss():
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(2))
    rng = np.random.RandomState(2)
    ids = jnp.asarray(rng.randint(0, 64, (2, 12)))
    amask = jnp.asarray((np.arange(12)[None, :] < [[8], [5]]).astype(
        np.int32))
    # garbage in the padding must not move the loss
    ids_garbage = jnp.where(amask == 0, 63, ids)
    l1 = float(model.loss(params, ids, amask))
    l2 = float(model.loss(params, ids_garbage, amask))
    # padding keys are masked out of attention and padding labels out of
    # the loss; the embedding of a pad position only feeds its own
    # (ignored) prediction
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_training_descends():
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(3))
    opt = optimizers.FusedAdam(lr=2e-3)
    opt_state = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(3).randint(0, 64, (4, 16)))

    @jax.jit
    def step(p, os):
        loss, g = jax.value_and_grad(lambda pp: model.loss(pp, ids))(p)
        p, os = opt.update(g, os, p)
        return p, os, loss

    l0 = None
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < l0 * 0.7, (l0, float(loss))


def test_generate_greedy_deterministic_and_prefix_preserving():
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(4))
    rng = np.random.RandomState(4)
    S = 16
    prompt = rng.randint(0, 64, (2, 4))
    buf = np.zeros((2, S), np.int32)
    buf[:, :4] = prompt
    gen = jax.jit(lambda p, b, n: model.generate(p, b, 4, n))
    ids1, len1 = gen(params, jnp.asarray(buf), 6)
    ids2, _ = gen(params, jnp.asarray(buf), 6)
    np.testing.assert_array_equal(np.asarray(ids1), np.asarray(ids2))
    np.testing.assert_array_equal(np.asarray(ids1)[:, :4], prompt)
    assert list(np.asarray(len1)) == [10, 10]
    # the continuation equals teacher-forced greedy next-token choices
    step1 = np.asarray(ids1)[0, 4]
    amask = jnp.asarray((np.arange(S) < 4).astype(np.int32))[None, :]
    logits = model(params, jnp.asarray(buf[:1]), amask)
    np.testing.assert_array_equal(
        step1, int(jnp.argmax(logits[0, 3])))


def test_generate_sampling_needs_rng_and_varies():
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(5))
    buf = jnp.zeros((1, 16), jnp.int32)
    with pytest.raises(ValueError, match="rng"):
        model.generate(params, buf, 1, 3, temperature=1.0)
    ids1, _ = model.generate(params, buf, 1, 8, temperature=2.0,
                             rng=jax.random.PRNGKey(0))
    ids2, _ = model.generate(params, buf, 1, 8, temperature=2.0,
                             rng=jax.random.PRNGKey(1))
    assert np.any(np.asarray(ids1) != np.asarray(ids2))


# tier-1 budget (PR 2): slowest tests by --durations carry the slow
# marker so a cold `-m 'not slow'` run fits the 870 s timeout
@pytest.mark.slow
def test_gpt_tensor_parallel_matches_unmapped():
    from apex_tpu.parallel import tensor_parallel as tp
    model = models.GPT(tiny_cfg(tp_axis="model"))
    params, _ = model.init(jax.random.PRNGKey(6))
    specs = tp.partition_specs(model, params)
    assert specs["wte"]["weight"] == P("model", None)
    mesh = Mesh(np.array(jax.devices()[:4]), ("model",))
    ids = jnp.asarray(np.random.RandomState(6).randint(0, 64, (2, 12)))

    def loss(p):
        return model.loss(p, ids)

    l_tp = jax.jit(jax.shard_map(
        loss, mesh=mesh, in_specs=(specs,), out_specs=P(),
        check_vma=False))(params)
    np.testing.assert_allclose(float(l_tp), float(loss(params)),
                               atol=1e-5)
    g_tp = jax.jit(jax.shard_map(
        jax.grad(loss), mesh=mesh, in_specs=(specs,), out_specs=specs,
        check_vma=False))(params)
    assert_trees_close(g_tp, jax.grad(loss)(params), atol=5e-5)


def test_generate_saturates_at_block_size():
    """prompt_len + max_new past block_size: the buffer fills and then
    stays frozen — no re-decoding over the final slot."""
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(7))
    rng = np.random.RandomState(7)
    S = 16
    buf = np.zeros((1, S), np.int32)
    buf[0, :12] = rng.randint(0, 64, 12)
    ids_exact, len_exact = model.generate(params, jnp.asarray(buf), 12, 4)
    ids_over, len_over = model.generate(params, jnp.asarray(buf), 12, 9)
    np.testing.assert_array_equal(np.asarray(ids_exact),
                                  np.asarray(ids_over))
    assert int(len_over[0]) == S == int(len_exact[0])


def test_generate_cached_matches_uncached_greedy():
    """KV-cached decoding must produce EXACTLY the uncached greedy
    continuation (and the prompt must survive untouched) for ragged
    per-row prompt lengths."""
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(8))
    rng = np.random.RandomState(8)
    S = 16
    buf = np.zeros((2, S), np.int32)
    buf[0, :5] = rng.randint(0, 64, 5)
    buf[1, :3] = rng.randint(0, 64, 3)
    plen = jnp.asarray([5, 3])
    ids_u, len_u = jax.jit(
        lambda p, b: model.generate(p, b, plen, 7))(params,
                                                    jnp.asarray(buf))
    ids_c, len_c = jax.jit(
        lambda p, b: model.generate_cached(p, b, plen, 7))(
        params, jnp.asarray(buf))
    np.testing.assert_array_equal(np.asarray(len_u), np.asarray(len_c))
    # compare only the live region of each row (beyond final_len the
    # uncached path leaves zeros and the cached path may too)
    for r in range(2):
        n = int(np.asarray(len_u)[r])
        np.testing.assert_array_equal(np.asarray(ids_u)[r, :n],
                                      np.asarray(ids_c)[r, :n])


def test_decode_step_matches_full_forward():
    """Single decode_step logits == full-forward logits at that row."""
    model = models.GPT(tiny_cfg())
    params, _ = model.init(jax.random.PRNGKey(9))
    rng = np.random.RandomState(9)
    ids = jnp.asarray(rng.randint(0, 64, (2, 6)))
    cache = model.init_cache(2)
    for t in range(6):
        logits_t, cache = model.decode_step(params, ids[:, t], t, cache)
    amask = jnp.ones((2, 6), jnp.int32)
    full = model(params, ids, amask)
    np.testing.assert_allclose(np.asarray(logits_t),
                               np.asarray(full[:, -1]), atol=2e-5)


@pytest.mark.slow
def test_gpt_sequence_parallel_matches_unmapped():
    """sp_axis: tokens sharded over the mesh, ring attention, global
    positions, cross-shard label shift — loss equals the full-sequence
    computation, and grads (pmean'd over sp like a data axis) match."""
    cfg = tiny_cfg(sp_axis="sp", block_size=16)
    model = models.GPT(cfg)
    params, _ = model.init(jax.random.PRNGKey(10))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    ids = jnp.asarray(np.random.RandomState(10).randint(0, 64, (2, 16)))

    def sp_loss(p, i):
        return model.loss(p, i)

    l_sp = jax.jit(jax.shard_map(
        sp_loss, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(), check_vma=False))(params, ids)

    # unmapped reference: same model object, full sequence, standard
    # shifted loss (sp code path inert outside the mesh)
    l_ref = model.loss(params, ids)
    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=2e-6)

    # grads: the sp axis behaves like a data axis — average over it
    def sp_grad(p, i):
        g = jax.grad(sp_loss)(p, i)
        return jax.tree_util.tree_map(
            lambda t: jax.lax.pmean(t, "sp"), g)

    g_sp = jax.jit(jax.shard_map(
        sp_grad, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(), check_vma=False))(params, ids)
    g_ref = jax.grad(lambda p: model.loss(p, ids))(params)
    assert_trees_close(g_sp, g_ref, atol=5e-5)


def test_gpt_sp_long_sequence_trains():
    """Train a few steps at a global length that each device only ever
    sees a quarter of; loss must descend."""
    from apex_tpu import amp
    cfg = tiny_cfg(sp_axis="sp", block_size=64)
    model, opt = amp.initialize(models.GPT(cfg),
                                optimizers.FusedAdam(lr=3e-3),
                                opt_level="O2", verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(11))
    opt_state = opt.init(params)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    pat = np.tile(np.arange(8), 8)
    ids = jnp.asarray(np.stack([np.roll(pat, r) for r in range(4)]))

    def step(p, os, i):
        def loss_fn(pp):
            return model.loss(pp, i), ()
        loss, _, g = amp.scaled_grad(loss_fn, p, os, has_aux=True)
        g = jax.tree_util.tree_map(lambda t: jax.lax.pmean(t, "sp"), g)
        p, os, _ = opt.step(p, os, g)
        return p, os, loss

    train = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(P(), P(), P(None, "sp")),
        out_specs=(P(), P(), P()), check_vma=False))
    l0 = None
    for _ in range(30):
        params, opt_state, loss = train(params, opt_state, ids)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < l0 * 0.5, (l0, float(loss))


def test_gpt_sp_mask_rejected_and_dropout_active():
    cfg = tiny_cfg(sp_axis="sp", block_size=16, dropout=0.3)
    model = models.GPT(cfg)
    params, _ = model.init(jax.random.PRNGKey(12))
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    ids = jnp.asarray(np.random.RandomState(12).randint(0, 64, (2, 16)))
    amask = jnp.ones((2, 4), jnp.int32)

    with pytest.raises(NotImplementedError, match="attention_mask"):
        jax.jit(jax.shard_map(
            lambda p, i, m: model.loss(p, i, m), mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp")), out_specs=P(),
            check_vma=False))(params, ids, amask)

    # train-mode dropout is live on the sp path: two rngs differ
    def fwd(p, i, key):
        out, _ = nn.apply(model, p, i, train=True,
                          rng=jax.random.PRNGKey(key))
        return out

    run = jax.jit(jax.shard_map(
        lambda p, i: fwd(p, i, 0) - fwd(p, i, 1), mesh=mesh,
        in_specs=(P(), P(None, "sp")), out_specs=P(None, "sp"),
        check_vma=False), static_argnums=())
    diff = run(params, ids)
    assert np.abs(np.asarray(diff)).max() > 1e-4


@pytest.mark.parametrize("ol", ["O1", "O3"])
def test_gpt_trains_under_other_opt_levels(ol):
    """The decoder family rides the amp opt-level matrix like the
    reference models: O1 (policy-patched ops, fp32 params) and O3
    (pure half) both train."""
    from apex_tpu import amp
    model, opt = amp.initialize(models.GPT(tiny_cfg()),
                                optimizers.FusedAdam(lr=2e-3),
                                opt_level=ol, verbosity=0,
                                hard_override=True)
    params, _ = model.init(jax.random.PRNGKey(13))
    leaves = jax.tree_util.tree_leaves(params)
    if ol == "O1":
        assert all(l.dtype == jnp.float32 for l in leaves)
    else:
        assert any(l.dtype == jnp.bfloat16 for l in leaves)
    opt_state = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(13).randint(0, 64, (4, 16)))

    @jax.jit
    def step(p, os):
        loss, _, g = amp.scaled_grad(
            lambda pp: (model.loss(pp, ids), ()), p, os, has_aux=True)
        p, os, _ = opt.step(p, os, g)
        return p, os, loss

    l0 = None
    for _ in range(20):
        params, opt_state, loss = step(params, opt_state)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < l0 * 0.8, (ol, l0, float(loss))


def test_gqa_cached_decode_matches_uncached():
    from apex_tpu.models import GPT, GPTConfig
    """GQA (n_kv_head < n_head): the compact grouped-cache decode is
    greedy-identical to the uncached forward path (which expands KV to
    full heads), and the cache is n_kv_head-sized; int8 cache composes."""
    cfg = GPTConfig(vocab_size=101, block_size=24, n_layer=2, n_head=4,
                    n_embd=32, dropout=0.0, n_kv_head=2)
    m = GPT(cfg)
    params, _ = m.init(jax.random.PRNGKey(0))
    assert params["h"]["0"]["attn"]["qkv"]["weight"].shape == \
        ((4 + 2 * 2) * 8, 32)
    assert m.init_cache(2)["0"]["k"].shape == (2, 2, 24, 8)

    rng = np.random.RandomState(0)
    buf = jnp.zeros((2, 24), jnp.int32).at[:, :5].set(
        jnp.asarray(rng.randint(0, 101, (2, 5))))
    out_u, n_u = m.generate(params, buf, 5, 8)
    out_c, n_c = m.generate_cached(params, buf, 5, 8)
    np.testing.assert_array_equal(np.asarray(out_u), np.asarray(out_c))
    np.testing.assert_array_equal(np.asarray(n_u), np.asarray(n_c))

    out_q, _ = m.generate_cached(params, buf, 5, 8, cache_dtype=jnp.int8)
    assert out_q.shape == (2, 24)


def test_gqa_full_heads_is_mha_parity():
    """Checkpoint compatibility: the fused qkv slice order is pinned
    DIRECTLY (a crafted fused tensor with distinguishable q/k/v blocks
    must split in the documented [q; k; v] order — a layout regression
    would pass a model-vs-itself comparison), and n_kv_head == n_head
    accepts the default config's params unchanged.  (The [q; k; v] row
    order vs real GPT-2 checkpoints is independently pinned by
    test_gpt2_matches_transformers.)"""
    from apex_tpu.models import GPT, GPTConfig
    from apex_tpu.models.gpt import GPTSelfAttention

    kw = dict(vocab_size=97, block_size=16, n_layer=1, n_head=4,
              n_embd=32, dropout=0.0)
    attn = GPTSelfAttention(GPTConfig(n_kv_head=2, **kw))
    H, Hkv, D = 4, 2, 8
    fused = jnp.concatenate([jnp.full((1, 1, H * D), 1.0),
                             jnp.full((1, 1, Hkv * D), 2.0),
                             jnp.full((1, 1, Hkv * D), 3.0)], axis=-1)
    q, k, v = attn._split_qkv(fused, 1, 1)
    assert q.shape == (1, H, 1, D) and float(q[0, 0, 0, 0]) == 1.0
    assert k.shape == (1, Hkv, 1, D) and float(k[0, 0, 0, 0]) == 2.0
    assert v.shape == (1, Hkv, 1, D) and float(v[0, 0, 0, 0]) == 3.0

    m_def = GPT(GPTConfig(**kw))
    m_gqa = GPT(GPTConfig(n_kv_head=4, **kw))
    params, _ = m_def.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 97, (2, 16)))
    np.testing.assert_array_equal(
        np.asarray(m_def(params, ids)), np.asarray(m_gqa(params, ids)))


def test_gqa_trains():
    from apex_tpu.models import GPT, GPTConfig
    """GQA model trains through amp O2 (loss decreases)."""
    from apex_tpu import amp, optimizers
    cfg = GPTConfig(vocab_size=64, block_size=16, n_layer=2, n_head=4,
                    n_embd=32, dropout=0.0, n_kv_head=1)
    model, opt = amp.initialize(GPT(cfg), optimizers.FusedAdam(lr=3e-3),
                                opt_level="O2", verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (4, 16)))

    @jax.jit
    def step(params, ost):
        def loss_fn(p):
            return model.loss(p, ids), ()
        loss, _, g = amp.scaled_grad(loss_fn, params, ost, has_aux=True)
        params, ost, _ = opt.step(params, ost, g)
        return params, ost, loss

    losses = [None, None]
    for i in range(40):
        params, ost, loss = step(params, ost)
        if i == 0:
            losses[0] = float(loss)
    losses[1] = float(loss)
    assert losses[1] < losses[0], losses


@pytest.mark.slow
def test_gpt_gqa_tensor_parallel_matches_unmapped():
    """GQA + TP: compact K/V projections shard over the model axis
    (n_kv_head % tp == 0); loss and grads match the unmapped model."""
    from apex_tpu.parallel import tensor_parallel as tp
    model = models.GPT(tiny_cfg(tp_axis="model", n_kv_head=2))
    params, _ = model.init(jax.random.PRNGKey(11))
    specs = tp.partition_specs(model, params)
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    ids = jnp.asarray(np.random.RandomState(11).randint(0, 64, (2, 12)))

    def loss(p):
        return model.loss(p, ids)

    l_tp = jax.jit(jax.shard_map(
        loss, mesh=mesh, in_specs=(specs,), out_specs=P(),
        check_vma=False))(params)
    np.testing.assert_allclose(float(l_tp), float(loss(params)),
                               atol=1e-5)
    g_tp = jax.jit(jax.shard_map(
        jax.grad(loss), mesh=mesh, in_specs=(specs,), out_specs=specs,
        check_vma=False))(params)
    assert_trees_close(g_tp, jax.grad(loss)(params), atol=5e-5)


def test_generate_under_tp_matches_unmapped():
    """Serving under TP: generate() inside shard_map must emit GLOBAL
    token ids (vocab-sharded logits take a cross-shard argmax) and
    reproduce the unmapped greedy output token-for-token."""
    from apex_tpu.parallel import tensor_parallel as tp
    model = models.GPT(tiny_cfg(tp_axis="model"))
    params, _ = model.init(jax.random.PRNGKey(12))
    specs = tp.partition_specs(model, params)
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    rng = np.random.RandomState(12)
    buf = np.zeros((2, 16), np.int32)
    buf[0, :5] = rng.randint(0, 64, 5)
    buf[1, :7] = rng.randint(0, 64, 7)
    ids, plen = jnp.asarray(buf), jnp.asarray([5, 7])

    out_tp, n_tp = jax.jit(jax.shard_map(
        lambda p, i, pl: model.generate(p, i, pl, 6),
        mesh=mesh, in_specs=(specs, P(), P()), out_specs=(P(), P()),
        check_vma=False))(params, ids, plen)
    out_ref, n_ref = model.generate(params, ids, plen, 6)
    np.testing.assert_array_equal(np.asarray(n_tp), np.asarray(n_ref))
    np.testing.assert_array_equal(np.asarray(out_tp),
                                  np.asarray(out_ref))
