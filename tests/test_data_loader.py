"""Native prefetching DataLoader tests (apex_tpu/_native apex_loader_* +
apex_tpu.data.DataLoader): parity with the numpy fallback, epoch coverage
under shuffle, ordered delivery, and prefetch-depth stress — the input-
pipeline analogue of the reference's extension-vs-Python L1 comparisons."""

import numpy as np
import pytest

from apex_tpu import _native
from apex_tpu.data import DataLoader

N, H, W, C = 64, 6, 5, 3


def _dataset():
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (N, H, W, C), np.uint8)
    labels = np.arange(N, dtype=np.int32)  # label == sample index
    return images, labels


def test_native_library_available():
    assert _native.available(), "native runtime failed to build/load"


def test_loader_uses_native_path():
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=8, shuffle=False)
    assert dl.native
    dl.close()


def test_noshuffle_matches_python_fallback_exactly():
    images, labels = _dataset()
    nat = DataLoader(images, labels, batch_size=8, shuffle=False)
    py = DataLoader(images, labels, batch_size=8, shuffle=False,
                    native=False)
    assert nat.native and not py.native
    for _ in range(2 * (N // 8)):  # two epochs
        ia, la, ba = nat.next_batch()
        ib, lb, bb = py.next_batch()
        assert ba == bb
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_allclose(ia, ib, rtol=1e-6, atol=1e-5)
    nat.close()


def test_normalization_matches_manual():
    images, labels = _dataset()
    mean, std = (10.0, 20.0, 30.0), (2.0, 3.0, 4.0)
    dl = DataLoader(images, labels, batch_size=4, shuffle=False,
                    mean=mean, std=std)
    imgs, lbls, _ = dl.next_batch()
    ref = np.moveaxis(
        (images[:4].astype(np.float32) - np.asarray(mean, np.float32))
        / np.asarray(std, np.float32), -1, 1)
    np.testing.assert_allclose(imgs, ref, rtol=1e-6, atol=1e-5)
    dl.close()


def test_shuffle_covers_every_sample_once_per_epoch():
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=8, shuffle=True, seed=7)
    for epoch in range(2):
        seen = []
        for _ in range(N // 8):
            _, lbls, _ = dl.next_batch()
            seen.extend(int(v) for v in lbls)
        assert sorted(seen) == list(range(N)), f"epoch {epoch}"
    dl.close()


def test_shuffle_differs_between_epochs_and_from_identity():
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=N, shuffle=True, seed=3)
    _, e0, _ = dl.next_batch()
    e0 = e0.copy()
    _, e1, _ = dl.next_batch()
    assert not np.array_equal(e0, np.arange(N))
    assert not np.array_equal(e0, e1)
    dl.close()


def test_ordered_delivery_under_stress():
    """Many batches through a tiny ring with many workers: indices must
    arrive 0,1,2,... regardless of fill completion order (the race the
    reference's ddp_race_condition_test guards, applied to the loader)."""
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=4, shuffle=True,
                    prefetch=2, workers=6, seed=1)
    for expect in range(200):
        _, _, b = dl.next_batch()
        assert b == expect
    dl.close()


def test_zero_copy_slot_lifetime():
    """zero_copy=True returns views into the ring: the view must hold the
    right data at delivery, stay stable until the next call, and get
    recycled once the ring wraps past it."""
    images, labels = _dataset()
    zc = DataLoader(images, labels, batch_size=8, shuffle=False,
                    prefetch=2, workers=2, zero_copy=True)
    ref = DataLoader(images, labels, batch_size=8, shuffle=False,
                     native=False)
    imgs0, lbls0, _ = zc.next_batch()
    rimgs0, rlbls0, _ = ref.next_batch()
    np.testing.assert_array_equal(lbls0, rlbls0)
    np.testing.assert_allclose(imgs0, rimgs0, rtol=1e-6, atol=1e-5)
    lbl_snapshot = lbls0.copy()
    # advance past the ring depth: the old view's slot must be recycled
    # with different (later-batch) labels — proving views really alias
    # the ring and documenting the hazard the default copy mode avoids
    for _ in range(4):
        zc.next_batch()
    assert not np.array_equal(lbls0, lbl_snapshot)
    zc.close()


def test_copy_mode_batches_are_owned():
    """Default mode: delivered arrays are unaffected by later calls."""
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=8, shuffle=False,
                    prefetch=2, workers=2)
    imgs0, lbls0, _ = dl.next_batch()
    snap_i, snap_l = imgs0.copy(), lbls0.copy()
    for _ in range(6):
        dl.next_batch()
    np.testing.assert_array_equal(imgs0, snap_i)
    np.testing.assert_array_equal(lbls0, snap_l)
    dl.close()


def test_rejects_non_uint8_images():
    images, labels = _dataset()
    with pytest.raises(TypeError, match="uint8"):
        DataLoader(images.astype(np.float32), labels, batch_size=8)


def test_validation_errors():
    images, labels = _dataset()
    with pytest.raises(ValueError):
        DataLoader(images[:4], labels[:4], batch_size=8)
    with pytest.raises(ValueError):
        DataLoader(images, labels[:10], batch_size=8)
    with pytest.raises(ValueError):
        DataLoader(images, labels, batch_size=8, mean=(1.0,), std=(1.0,))


@pytest.mark.parametrize("native", [True, False])
def test_loader_nhwc_delivery_matches_nchw(native):
    """data_format='NHWC' must deliver the same normalized pixels as the
    NCHW default, transposed — native path and python fallback."""
    if native and not _native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (12, 6, 5, 3), dtype=np.uint8)
    labels = np.arange(12, dtype=np.int64)
    kw = dict(batch_size=4, shuffle=False, prefetch=2, workers=2,
              native=native)
    a = DataLoader(images, labels, **kw)
    b = DataLoader(images, labels, data_format="NHWC", **kw)
    try:
        for _ in range(3):
            ia, la, _ = a.next_batch()
            ib, lb, _ = b.next_batch()
            assert ia.shape == (4, 3, 6, 5)
            assert ib.shape == (4, 6, 5, 3)
            np.testing.assert_allclose(ib.transpose(0, 3, 1, 2), ia,
                                       rtol=1e-6, atol=1e-6)
            np.testing.assert_array_equal(la, lb)
    finally:
        a.close()
        b.close()
