"""Native prefetching DataLoader tests (apex_tpu/_native apex_loader_* +
apex_tpu.data.DataLoader): parity with the numpy fallback, epoch coverage
under shuffle, ordered delivery, and prefetch-depth stress — the input-
pipeline analogue of the reference's extension-vs-Python L1 comparisons."""

import numpy as np
import pytest

from apex_tpu import _native
from apex_tpu.data import DataLoader

N, H, W, C = 64, 6, 5, 3


def _dataset():
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (N, H, W, C), np.uint8)
    labels = np.arange(N, dtype=np.int32)  # label == sample index
    return images, labels


def test_native_library_available():
    assert _native.available(), "native runtime failed to build/load"


def test_loader_uses_native_path():
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=8, shuffle=False)
    assert dl.native
    dl.close()


def test_noshuffle_matches_python_fallback_exactly():
    images, labels = _dataset()
    nat = DataLoader(images, labels, batch_size=8, shuffle=False)
    py = DataLoader(images, labels, batch_size=8, shuffle=False,
                    native=False)
    assert nat.native and not py.native
    for _ in range(2 * (N // 8)):  # two epochs
        ia, la, ba = nat.next_batch()
        ib, lb, bb = py.next_batch()
        assert ba == bb
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_allclose(ia, ib, rtol=1e-6, atol=1e-5)
    nat.close()


def test_normalization_matches_manual():
    images, labels = _dataset()
    mean, std = (10.0, 20.0, 30.0), (2.0, 3.0, 4.0)
    dl = DataLoader(images, labels, batch_size=4, shuffle=False,
                    mean=mean, std=std)
    imgs, lbls, _ = dl.next_batch()
    ref = np.moveaxis(
        (images[:4].astype(np.float32) - np.asarray(mean, np.float32))
        / np.asarray(std, np.float32), -1, 1)
    np.testing.assert_allclose(imgs, ref, rtol=1e-6, atol=1e-5)
    dl.close()


def test_shuffle_covers_every_sample_once_per_epoch():
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=8, shuffle=True, seed=7)
    for epoch in range(2):
        seen = []
        for _ in range(N // 8):
            _, lbls, _ = dl.next_batch()
            seen.extend(int(v) for v in lbls)
        assert sorted(seen) == list(range(N)), f"epoch {epoch}"
    dl.close()


def test_shuffle_differs_between_epochs_and_from_identity():
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=N, shuffle=True, seed=3)
    _, e0, _ = dl.next_batch()
    e0 = e0.copy()
    _, e1, _ = dl.next_batch()
    assert not np.array_equal(e0, np.arange(N))
    assert not np.array_equal(e0, e1)
    dl.close()


def test_ordered_delivery_under_stress():
    """Many batches through a tiny ring with many workers: indices must
    arrive 0,1,2,... regardless of fill completion order (the race the
    reference's ddp_race_condition_test guards, applied to the loader)."""
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=4, shuffle=True,
                    prefetch=2, workers=6, seed=1)
    for expect in range(200):
        _, _, b = dl.next_batch()
        assert b == expect
    dl.close()


def test_zero_copy_slot_lifetime():
    """zero_copy=True returns views into the ring: the view must hold the
    right data at delivery, stay stable until the next call, and get
    recycled once the ring wraps past it."""
    images, labels = _dataset()
    zc = DataLoader(images, labels, batch_size=8, shuffle=False,
                    prefetch=2, workers=2, zero_copy=True)
    ref = DataLoader(images, labels, batch_size=8, shuffle=False,
                     native=False)
    imgs0, lbls0, _ = zc.next_batch()
    rimgs0, rlbls0, _ = ref.next_batch()
    np.testing.assert_array_equal(lbls0, rlbls0)
    np.testing.assert_allclose(imgs0, rimgs0, rtol=1e-6, atol=1e-5)
    lbl_snapshot = lbls0.copy()
    # advance past the ring depth: the old view's slot must be recycled
    # with different (later-batch) labels — proving views really alias
    # the ring and documenting the hazard the default copy mode avoids
    for _ in range(4):
        zc.next_batch()
    assert not np.array_equal(lbls0, lbl_snapshot)
    zc.close()


def test_copy_mode_batches_are_owned():
    """Default mode: delivered arrays are unaffected by later calls."""
    images, labels = _dataset()
    dl = DataLoader(images, labels, batch_size=8, shuffle=False,
                    prefetch=2, workers=2)
    imgs0, lbls0, _ = dl.next_batch()
    snap_i, snap_l = imgs0.copy(), lbls0.copy()
    for _ in range(6):
        dl.next_batch()
    np.testing.assert_array_equal(imgs0, snap_i)
    np.testing.assert_array_equal(lbls0, snap_l)
    dl.close()


def test_rejects_non_uint8_images():
    images, labels = _dataset()
    with pytest.raises(TypeError, match="uint8"):
        DataLoader(images.astype(np.float32), labels, batch_size=8)


def test_validation_errors():
    images, labels = _dataset()
    with pytest.raises(ValueError):
        DataLoader(images[:4], labels[:4], batch_size=8)
    with pytest.raises(ValueError):
        DataLoader(images, labels[:10], batch_size=8)
    with pytest.raises(ValueError):
        DataLoader(images, labels, batch_size=8, mean=(1.0,), std=(1.0,))


class TestCheckpointableState:
    """The state protocol (PR 12): state_dict/load_state_dict round-
    trip the portable stream's cursor, deterministic per-replica
    sharding re-derives exactly-once delivery across an elastic world
    shrink, corrupt records are quarantined (never a crashed step),
    and the census is scrapeable from stats()."""

    def _loader(self, **kw):
        images, labels = _dataset()
        kw.setdefault("batch_size", 8)
        kw.setdefault("shuffle", True)
        kw.setdefault("seed", 5)
        kw.setdefault("native", False)
        return DataLoader(images, labels, **kw)

    def test_state_roundtrip_resumes_bitwise(self):
        a = self._loader()
        for _ in range(5):
            a.next_batch()
        sd = a.state_dict()
        tail_a = [a.next_batch() for _ in range(12)]   # crosses epochs
        b = self._loader()
        b.load_state_dict(sd)
        tail_b = [b.next_batch() for _ in range(12)]
        for (ia, la, ba), (ib, lb, bb) in zip(tail_a, tail_b):
            np.testing.assert_array_equal(la, lb)
            np.testing.assert_array_equal(ia, ib)   # bitwise pixels
            assert ba == bb

    def test_state_dict_fields_and_json(self):
        import json
        dl = self._loader()
        dl.next_batch()
        sd = dl.state_dict()
        for key in ("seed", "epoch", "cursor", "samples_consumed",
                    "shard_id", "num_shards"):
            assert key in sd, key
        assert sd["samples_consumed"] == 8 and sd["cursor"] == 8
        json.dumps(sd)                   # checkpoint-blob contract

    def test_load_state_dict_rejects_wrong_stream(self):
        dl = self._loader()
        sd = dl.state_dict()
        other = self._loader(seed=6)
        with pytest.raises(ValueError, match="seed"):
            other.load_state_dict(sd)
        noshuf = self._loader(shuffle=False)
        with pytest.raises(ValueError, match="shuffle"):
            noshuf.load_state_dict(sd)
        with pytest.raises(ValueError, match="missing"):
            dl.load_state_dict({"seed": 5})

    def test_state_protocol_raises_on_native_path(self):
        if not _native.available():
            pytest.skip("native lib unavailable")
        images, labels = _dataset()
        dl = DataLoader(images, labels, batch_size=8)
        assert dl.native
        with pytest.raises(RuntimeError, match="native=False"):
            dl.state_dict()
        with pytest.raises(RuntimeError, match="native=False"):
            dl.load_state_dict({})
        dl.close()

    def test_sharded_delivery_partitions_each_global_batch(self):
        """At a fixed world, the shards of one global step cover the
        permutation slice exactly once, in shard order."""
        images = np.random.RandomState(0).randint(
            0, 256, (96, 6, 5, 3), np.uint8)
        labels = np.arange(96, dtype=np.int32)
        loaders = [DataLoader(images, labels, batch_size=4,
                              shuffle=True, seed=9, shard_id=s,
                              num_shards=8, native=False)
                   for s in range(8)]
        assert all(not dl.native for dl in loaders)
        perm = np.random.RandomState(9 + 0).permutation(96)
        step0 = []
        for dl in loaders:
            _, lbls, _ = dl.next_batch()
            step0.extend(int(v) for v in lbls)
        np.testing.assert_array_equal(step0, perm[:32])

    def test_census_exactly_once_across_8_to_4_shrink(self):
        """The acceptance pin: consume one global step at world 8,
        re-derive the shards at world 4 from the SAME exported cursor,
        finish the epoch — every usable sample is delivered exactly
        once across the world change."""
        images = np.random.RandomState(0).randint(
            0, 256, (96, 6, 5, 3), np.uint8)
        labels = np.arange(96, dtype=np.int32)

        def shards(num):
            return [DataLoader(images, labels, batch_size=4,
                               shuffle=True, seed=9, shard_id=s,
                               num_shards=num, native=False)
                    for s in range(num)]

        delivered = []
        world8 = shards(8)
        for dl in world8:                 # one global step at world 8
            _, lbls, _ = dl.next_batch()
            delivered.extend(int(v) for v in lbls)
        sd = world8[0].state_dict()
        assert sd["cursor"] == 32 and sd["samples_consumed"] == 32

        world4 = shards(4)                # the elastic shrink
        for dl in world4:
            dl.load_state_dict(sd)        # cursor is world-independent
        # drive the epoch dry (the roll itself happens lazily at the
        # next draw — cursor == n means this epoch is exhausted)
        while world4[0].stats()["cursor"] < 96:
            for dl in world4:
                _, lbls, _ = dl.next_batch()
                delivered.extend(int(v) for v in lbls)
        # 96 % 32 == 96 % 16 == 0: the whole epoch is usable, and the
        # census must be a perfect partition — exactly once each
        assert len(delivered) == 96
        assert sorted(delivered) == list(range(96))
        assert world4[0].stats()["samples_consumed"] == 96

    def test_quarantine_skips_bad_records_without_crashing(self):
        from apex_tpu.observability import EventRing, MetricsRegistry
        images, labels = _dataset()
        bad = {5, 17}
        ring = EventRing(64)
        reg = MetricsRegistry()
        dl = DataLoader(images, labels, batch_size=8, shuffle=False,
                        native=False, bad_record_fn=lambda i: i in bad,
                        ring=ring, metrics=reg)
        seen = []
        for _ in range(N // 8):           # one epoch, never a crash
            _, lbls, _ = dl.next_batch()
            seen.extend(int(v) for v in lbls)
        # the bad records never reach training; their slots carry the
        # first good sample of the same batch (static batch shape)
        assert bad.isdisjoint(seen)
        assert seen.count(0) == 2 and seen.count(16) == 2
        assert dl.stats()["samples_quarantined"] == 2
        assert reg.get("data_samples_quarantined_total").value == 2
        evs = ring.snapshot("data_sample_quarantined")
        assert [ev["index"] for ev in evs] == [5, 17]
        assert [ev["replaced_with"] for ev in evs] == [0, 16]

    def test_quarantine_all_bad_batch_substitutes_only_good(self):
        # a fully-poisoned batch falls back to the first record the
        # check ACCEPTS (never a flagged one); a fully-poisoned
        # dataset is loud
        from apex_tpu.observability import EventRing, MetricsRegistry
        images, labels = _dataset()
        bad = set(range(8)) | {0}         # batch 0 entirely bad
        dl = DataLoader(images, labels, batch_size=8, shuffle=False,
                        native=False,
                        bad_record_fn=lambda i: i in bad,
                        ring=EventRing(64), metrics=MetricsRegistry())
        _, lbls, _ = dl.next_batch()
        assert set(int(v) for v in lbls) == {8}   # first good record
        hopeless = DataLoader(images, labels, batch_size=8,
                              shuffle=False, native=False,
                              bad_record_fn=lambda i: True,
                              ring=EventRing(64),
                              metrics=MetricsRegistry())
        with pytest.raises(RuntimeError, match="every record"):
            hopeless.next_batch()

    def test_stats_census_consistent_through_save_restore(self):
        from apex_tpu.observability import MetricsRegistry
        reg = MetricsRegistry()
        a = self._loader(metrics=reg)
        for _ in range(3):
            a.next_batch()
        sd = a.state_dict()
        for _ in range(2):
            a.next_batch()
        st = a.stats()
        assert st["samples_consumed"] == 40 and st["epoch"] == 0
        assert st["shard_id"] == 0 and st["num_shards"] == 1
        assert reg.get("data_samples_consumed").value == 40
        a.load_state_dict(sd)             # rewind to the snapshot
        st = a.stats()
        assert st["samples_consumed"] == 24 and st["cursor"] == 24
        # the /statusz gauge follows the restored census immediately
        assert reg.get("data_samples_consumed").value == 24

    def test_shard_validation(self):
        images, labels = _dataset()
        with pytest.raises(ValueError, match="shard_id"):
            DataLoader(images, labels, batch_size=8, shard_id=2,
                       num_shards=2)
        with pytest.raises(ValueError, match="num_shards"):
            DataLoader(images, labels, batch_size=8, num_shards=0)
        with pytest.raises(ValueError, match="global batch"):
            DataLoader(images, labels, batch_size=8, num_shards=16)


@pytest.mark.parametrize("native", [True, False])
def test_loader_nhwc_delivery_matches_nchw(native):
    """data_format='NHWC' must deliver the same normalized pixels as the
    NCHW default, transposed — native path and python fallback."""
    if native and not _native.available():
        pytest.skip("native lib unavailable")
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (12, 6, 5, 3), dtype=np.uint8)
    labels = np.arange(12, dtype=np.int64)
    kw = dict(batch_size=4, shuffle=False, prefetch=2, workers=2,
              native=native)
    a = DataLoader(images, labels, **kw)
    b = DataLoader(images, labels, data_format="NHWC", **kw)
    try:
        for _ in range(3):
            ia, la, _ = a.next_batch()
            ib, lb, _ = b.next_batch()
            assert ia.shape == (4, 3, 6, 5)
            assert ib.shape == (4, 6, 5, 3)
            np.testing.assert_allclose(ib.transpose(0, 3, 1, 2), ia,
                                       rtol=1e-6, atol=1e-6)
            np.testing.assert_array_equal(la, lb)
    finally:
        a.close()
        b.close()
