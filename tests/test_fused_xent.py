"""Chunked fused linear+cross-entropy head (nn.fused_xent).

Parity oracle: dense ``logits -> fp32 log_softmax -> gather`` — the
reference-shaped path this op replaces (apex-era models materialize
logits and call the fp32 loss; see SURVEY §2.1 amp lists: losses are
blacklist/fp32).  The fused path must match it to fp32 round-off,
including grads, the non-divisible tail chunk, and through GPT.loss in
both the default and ``head_chunk=None`` modes.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu.nn.fused_xent import linear_cross_entropy


def _dense_nll(h, W, y):
    logp = jax.nn.log_softmax((h @ W.T).astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]


@pytest.mark.parametrize("V,chunk", [(1003, 128), (512, 128), (96, 200)])
def test_fwd_parity_incl_tail(V, chunk):
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.randn(37, 64), jnp.float32)
    W = jnp.asarray(rng.randn(V, 64) * 0.05, jnp.float32)
    y = jnp.asarray(rng.randint(0, V, 37), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(linear_cross_entropy(h, W, y, chunk)),
        np.asarray(_dense_nll(h, W, y)), rtol=1e-5, atol=1e-5)


def test_grad_parity_fp32():
    rng = np.random.RandomState(1)
    h = jnp.asarray(rng.randn(29, 48), jnp.float32)
    W = jnp.asarray(rng.randn(777, 48) * 0.05, jnp.float32)
    y = jnp.asarray(rng.randint(0, 777, 29), jnp.int32)
    # weighted mean (exercises non-uniform per-row cotangents, the
    # ignore_index masking shape)
    w = jnp.asarray(rng.rand(29), jnp.float32)

    def mk(fn):
        return jax.grad(lambda h, W: jnp.sum(fn(h, W) * w) / w.sum(),
                        argnums=(0, 1))

    gd = mk(lambda h, W: _dense_nll(h, W, y))(h, W)
    gf = mk(lambda h, W: linear_cross_entropy(h, W, y, 100))(h, W)
    for a, b in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_bf16_inputs_fp32_accumulation():
    """bf16 activations/table (the amp O2 shape): fused and dense paths
    agree within bf16 matmul tolerance, and the returned nll is fp32."""
    rng = np.random.RandomState(2)
    h = jnp.asarray(rng.randn(16, 32), jnp.bfloat16)
    W = jnp.asarray(rng.randn(300, 32) * 0.05, jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 300, 16), jnp.int32)
    out = linear_cross_entropy(h, W, y, 64)
    assert out.dtype == jnp.float32
    ref = _dense_nll(h.astype(jnp.float32), W.astype(jnp.float32), y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    # grad dtypes mirror the primals (what amp O2 + the optimizer expect)
    gh, gw = jax.grad(lambda h, W: linear_cross_entropy(h, W, y, 64).mean(),
                      argnums=(0, 1))(h, W)
    assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16


@pytest.mark.slow
def test_gpt_loss_fused_matches_dense():
    """GPT.loss default (fused head) == head_chunk=None (dense oracle),
    value and grads, including ignore_index masking via attention_mask."""
    from apex_tpu import models

    kw = dict(vocab_size=311, block_size=32, n_layer=2, n_head=4,
              n_embd=32, dropout=0.0)
    m_f = models.GPT(models.GPTConfig(head_chunk=128, **kw))
    m_d = models.GPT(models.GPTConfig(head_chunk=None, **kw))
    params, _ = m_f.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 311, (2, 32)), jnp.int32)
    mask = jnp.asarray(rng.rand(2, 32) > 0.2, jnp.int32)

    lf = m_f.loss(params, ids, attention_mask=mask)
    ld = m_d.loss(params, ids, attention_mask=mask)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5, atol=1e-5)

    gf = jax.grad(lambda p: m_f.loss(p, ids, attention_mask=mask))(params)
    gd = jax.grad(lambda p: m_d.loss(p, ids, attention_mask=mask))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-5, atol=5e-5)


# tier-1 budget (PR 2): slowest tests by --durations carry the slow
# marker so a cold `-m 'not slow'` run fits the 870 s timeout
@pytest.mark.slow
def test_bert_mlm_loss_fused_matches_dense():
    """BertForPretraining.loss default (fused MLM head) ==
    head_chunk=None dense oracle, value and grads."""
    from apex_tpu import models

    kw = dict(vocab_size=259, hidden_size=32, num_hidden_layers=2,
              num_attention_heads=4, intermediate_size=64,
              max_position_embeddings=32, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0)
    m_f = models.BertForPretraining(models.BertConfig(head_chunk=64, **kw))
    m_d = models.BertForPretraining(models.BertConfig(head_chunk=None, **kw))
    params, _ = m_f.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 259, (2, 16)), jnp.int32)
    mlm = jnp.where(jnp.asarray(rng.rand(2, 16) < 0.15),
                    jnp.asarray(rng.randint(0, 259, (2, 16))), -100)
    nsp = jnp.asarray(rng.randint(0, 2, 2), jnp.int32)

    def run(m, p):
        return m.loss(p, ids, mlm, nsp)

    np.testing.assert_allclose(float(run(m_f, params)),
                               float(run(m_d, params)),
                               rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda p: run(m_f, p))(params)
    gd = jax.grad(lambda p: run(m_d, p))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.slow
def test_gpt_o2_memorizes_through_fused_head():
    """End-to-end training correctness of the fused head: a tiny GPT
    under amp O2 + FusedAdam must memorize a fixed batch (loss -> ~0),
    which a wrong backward would prevent (one-step grad parity can miss
    accumulation/scale bugs that only show over a trajectory)."""
    from apex_tpu import amp, models, optimizers

    cfg = models.GPTConfig(vocab_size=64, block_size=16, n_layer=2,
                           n_head=2, n_embd=32, dropout=0.0,
                           head_chunk=32)
    model, opt = amp.initialize(models.GPT(cfg),
                                optimizers.FusedAdam(lr=3e-3),
                                opt_level="O2", verbosity=0)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)

    @jax.jit
    def step(params, ost):
        def loss_fn(p):
            return model.loss(p, ids), ()
        loss, _, g = amp.scaled_grad(loss_fn, params, ost, has_aux=True)
        params, ost, _ = opt.step(params, ost, g)
        return params, ost, loss

    first = None
    for i in range(300):
        params, ost, loss = step(params, ost)
        if first is None:
            first = float(loss)
    assert float(loss) < 0.15, (first, float(loss))


@pytest.mark.parametrize("seed", range(4))
def test_fuzz_shapes_and_chunks(seed):
    """Kernel-fuzz discipline (reference: test_multi_tensor_scale's
    size sweep): random (N, D, V, chunk) incl. chunk > V, chunk == V,
    ragged tails, single-row N — fwd and grads match dense."""
    rng = np.random.RandomState(seed)
    N = int(rng.randint(1, 40))
    D = int(rng.choice([8, 24, 64]))
    V = int(rng.randint(3, 600))
    chunk = int(rng.choice([1, 7, 64, V, V + 13, 4096]))
    h = jnp.asarray(rng.randn(N, D), jnp.float32)
    W = jnp.asarray(rng.randn(V, D) * 0.1, jnp.float32)
    y = jnp.asarray(rng.randint(0, V, N), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(linear_cross_entropy(h, W, y, chunk)),
        np.asarray(_dense_nll(h, W, y)), rtol=2e-5, atol=2e-5)
    gd = jax.grad(lambda h, W: _dense_nll(h, W, y).sum(),
                  argnums=(0, 1))(h, W)
    gf = jax.grad(lambda h, W: linear_cross_entropy(h, W, y, chunk).sum(),
                  argnums=(0, 1))(h, W)
    for a, b in zip(gd, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)
