"""Trainee for the cross-process TENSOR-PARALLEL parity test.

Runs a tiny GPT with tp_axis="model" — Megatron column/row sharding,
vocab-parallel embedding + cross-entropy — for a fixed number of SGD
steps on deterministic data, printing the loss trajectory bit-exactly
(float.hex) plus a psum-reduced param summary.

The test runs this two ways and asserts identical output:
  1. single process, 2-device virtual CPU mesh
  2. under `python -m apex_tpu.parallel.multiproc --nprocs 2 --backend
     cpu` — the f/g conjugate collectives and the vocab-parallel loss
     psums cross a REAL process boundary via jax.distributed.
"""

import os
import sys

_repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
if _repo not in sys.path:
    sys.path.insert(0, _repo)

from apex_tpu.parallel import multiproc

rank = multiproc.init_process_group()

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import models
from apex_tpu.parallel import tensor_parallel as tp


def main():
    ndev = len(jax.devices())
    assert ndev == 2, f"parity trainee expects a 2-device world, got {ndev}"

    model = models.GPT(models.GPTConfig(
        vocab_size=64, block_size=16, n_layer=2, n_head=4, n_embd=32,
        dropout=0.0, tp_axis="model"))
    params, _ = model.init(jax.random.PRNGKey(0))
    specs = tp.partition_specs(model, params)
    mesh = Mesh(np.array(jax.devices()), ("model",))

    def step(p, ids):
        loss, g = jax.value_and_grad(
            lambda pp: model.loss(pp, ids))(p)
        p = jax.tree_util.tree_map(lambda w, gg: w - 0.05 * gg, p, g)
        # deterministic param summary crossing every shard: psum of
        # per-leaf sums (replicated leaves count axis_size times in
        # BOTH runs, so the comparison stays apples-to-apples)
        summ = jax.lax.psum(
            sum(jnp.sum(x.astype(jnp.float32))
                for x in jax.tree_util.tree_leaves(p)), "model")
        return p, loss, summ

    train = jax.jit(jax.shard_map(
        step, mesh=mesh, in_specs=(specs, P()),
        out_specs=(specs, P(), P()), check_vma=False))

    rng = np.random.RandomState(0)
    summ = None
    for i in range(6):
        ids = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)
        params, loss, summ = train(params, ids)
        if jax.process_index() == 0:
            print(f"traj {i} {float(loss).hex()}", flush=True)
    if jax.process_index() == 0:
        print(f"param summary {float(summ).hex()}", flush=True)
        print(f"world {jax.process_count()} processes "
              f"{len(jax.devices())} devices", flush=True)


if __name__ == "__main__":
    main()
