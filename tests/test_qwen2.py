"""Qwen2 = Llama + Q/K/V projection biases (+ optional sliding
window): HF parity incl. generation through the biased decode path."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu.models import Llama, LlamaConfig


def _pair(tie=False):
    import torch
    from transformers import Qwen2Config as HFConfig, Qwen2ForCausalLM
    from apex_tpu.utils import hf_interop

    hf_cfg = HFConfig(vocab_size=151, hidden_size=64,
                      intermediate_size=128, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=48,
                      tie_word_embeddings=tie,
                      attn_implementation="eager")
    torch.manual_seed(0)
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    cfg, params = hf_interop.qwen2_from_hf(hf)
    assert cfg.attention_bias
    return hf, Llama(cfg), params


def test_qwen2_logits_match_transformers():
    import torch

    hf, m, params = _pair()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 151, (2, 24))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    out = np.asarray(m(params, jnp.asarray(ids)))
    np.testing.assert_allclose(out, ref, rtol=3e-4, atol=3e-4)


def test_qwen2_greedy_generation_matches_transformers():
    import torch

    hf, m, params = _pair()
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 151, (2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(prompt), max_new_tokens=10,
                          do_sample=False).numpy()
    buf = jnp.zeros((2, 48), jnp.int32).at[:, :6].set(jnp.asarray(prompt))
    out, n = m.generate_cached(params, buf, 6, 10)
    assert int(n[0]) == 16
    np.testing.assert_array_equal(np.asarray(out[:, :16]), ref)


def test_attention_bias_params_exist_and_train():
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=1,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=16,
                      tie_word_embeddings=True, attention_bias=True)
    m = Llama(cfg)
    params, _ = m.init(jax.random.PRNGKey(0))
    at = params["layers"]["0"]["self_attn"]
    assert "bias" in at["q_proj"] and "bias" in at["k_proj"]
    assert "bias" not in at["o_proj"]       # Qwen2: no output bias
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 97, (2, 16)))
    g = jax.grad(lambda p: m.loss(p, ids))(params)
    assert np.abs(np.asarray(
        g["layers"]["0"]["self_attn"]["q_proj"]["bias"])).sum() > 0


def test_attention_bias_rejects_tp():
    with pytest.raises(NotImplementedError, match="attention_bias"):
        LlamaConfig(vocab_size=97, hidden_size=32,
                    intermediate_size=64, num_hidden_layers=1,
                    num_attention_heads=4, num_key_value_heads=2,
                    max_position_embeddings=16, attention_bias=True,
                    tp_axis="model")


def test_qwen2_mixed_sliding_window_refused():
    """HF gates SWA per layer (max_window_layers); a mixed config must
    raise, not silently band every layer (code-review finding)."""
    import torch
    from transformers import Qwen2Config as HFConfig, Qwen2ForCausalLM
    from apex_tpu.utils import hf_interop

    hf_cfg = HFConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=4,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=32,
                      use_sliding_window=True, sliding_window=8,
                      max_window_layers=2)
    torch.manual_seed(0)
    hf = Qwen2ForCausalLM(hf_cfg).eval()
    with pytest.raises(ValueError, match="per-layer sliding window"):
        hf_interop.qwen2_from_hf(hf)
