"""Device-timeline attribution (PR 13): the stdlib chrome-trace parser
on hand-built synthetic traces (overlap / gap / collective
classification pinned without a capture), the real-capture path on the
8-virtual-device CPU mesh (jax.profiler writes it, we parse it), the
steptime differencing-vs-measurement consistency pin, the unique
per-capture directory contract, and the ``kind: profile`` record
schema."""

import gzip
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.observability import exporters, steptime, timeline
from apex_tpu.utils import profiler


# -- synthetic-trace unit suite (no capture needed) ------------------------

def _trace(events):
    """A minimal chrome-trace document: the given X events plus the
    host-frame noise a real capture interleaves (python tracer events
    without hlo_op, metadata rows) that the parser must drop."""
    noise = [
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 999.0,
         "name": "$builtins isinstance"},          # no args at all
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 999.0,
         "name": "host frame", "args": {"not_hlo": 1}},
        {"ph": "i", "pid": 1, "tid": 1, "ts": 5.0, "name": "instant",
         "args": {"hlo_op": "ignored"}},           # wrong phase
    ]
    return {"displayTimeUnit": "ns", "traceEvents": noise + events}


def _kernel(name, ts, dur, tid=2, module="jit_step", op=None):
    return {"ph": "X", "pid": 7, "tid": tid, "ts": ts, "dur": dur,
            "name": name,
            "args": {"hlo_op": op or name, "hlo_module": module}}


def test_classify_kernel_patterns():
    for name in ("all-reduce.1", "all-gather.3", "reduce-scatter",
                 "collective-permute.2", "all-to-all",
                 "fused-all-reduce-start.1"):
        assert timeline.classify_kernel(name) == "collective", name
    for name in ("dot.3", "fusion.12", "tanh", "reduce-window",
                 "convolution.1", "copy"):
        assert timeline.classify_kernel(name) == "compute", name
    # the exporters validator duplicates the field tuple (stdlib CI
    # loader discipline) — pin the pairs equal so they cannot drift
    assert exporters.PROFILE_TIME_FIELDS + (
        "measured_overlap_fraction",) == timeline.PROFILE_FIELDS


def test_merge_and_overlap_primitives():
    merged = timeline.merge_intervals(
        [(0, 10), (5, 15), (20, 30), (30, 31), (40, 40)])
    assert merged == [(0, 15), (20, 31)]
    assert timeline.overlap_us([(0, 10), (20, 30)],
                               [(5, 25)]) == pytest.approx(10.0)
    assert timeline.overlap_us([], [(0, 5)]) == 0.0


def test_synthetic_full_overlap():
    """A collective fully hidden under compute: overlap == collective,
    measured fraction 1.0."""
    doc = _trace([
        _kernel("dot.1", ts=0.0, dur=100.0),
        _kernel("all-reduce.1", ts=20.0, dur=50.0, tid=3),
    ])
    att = timeline.attribute_timeline(timeline.device_events(doc))
    assert att["span_ms"] == pytest.approx(0.1)
    assert att["device_busy_ms"] == pytest.approx(0.1)   # union
    assert att["compute_ms"] == pytest.approx(0.1)
    assert att["collective_ms"] == pytest.approx(0.05)
    assert att["overlap_ms"] == pytest.approx(0.05)
    assert att["measured_overlap_fraction"] == pytest.approx(1.0)
    assert att["gap_ms"] == 0.0
    assert att["kernel_count"] == 2 and att["lane_count"] == 2


def test_synthetic_no_overlap_reduce_after_backward():
    """The reduce-after-backward shape: compute then collective,
    disjoint — fraction 0.0, exactly today's baseline."""
    doc = _trace([
        _kernel("fusion.1", ts=0.0, dur=100.0),
        _kernel("all-reduce.1", ts=100.0, dur=40.0),
    ])
    att = timeline.attribute_timeline(timeline.device_events(doc))
    assert att["measured_overlap_fraction"] == 0.0
    assert att["overlap_ms"] == 0.0
    assert att["collective_ms"] == pytest.approx(0.04)
    assert att["device_busy_ms"] == pytest.approx(0.14)
    assert att["gap_ms"] == 0.0


def test_synthetic_gap_and_partial_overlap():
    """Gap = span minus busy; overlap counts only the covered part of
    the collective."""
    doc = _trace([
        _kernel("dot.1", ts=0.0, dur=100.0),
        # idle 100..200, then a collective whose first half overlaps
        # the next compute kernel
        _kernel("all-reduce.2", ts=200.0, dur=100.0, tid=3),
        _kernel("fusion.7", ts=200.0, dur=50.0),
    ])
    att = timeline.attribute_timeline(timeline.device_events(doc))
    assert att["span_ms"] == pytest.approx(0.3)
    assert att["device_busy_ms"] == pytest.approx(0.2)
    assert att["gap_ms"] == pytest.approx(0.1)
    assert att["overlap_ms"] == pytest.approx(0.05)
    assert att["measured_overlap_fraction"] == pytest.approx(0.5)
    # the record built from it is schema-valid
    rec = exporters.JsonlExporter.enrich(
        timeline.profile_record(att, metric="synthetic"))
    assert exporters.validate_profile_record(rec) == []
    assert exporters.validate_telemetry_record(rec) == []


def test_synthetic_module_filter_and_topk():
    doc = _trace([
        _kernel("dot.1", ts=0.0, dur=10.0),
        _kernel("dot.2", ts=10.0, dur=30.0),
        _kernel("tanh.1", ts=40.0, dur=5.0),
        _kernel("sum.1", ts=0.0, dur=500.0, module="jit__multi_slice"),
    ])
    ev = timeline.device_events(doc, modules=("jit_step",))
    assert {e["name"] for e in ev} == {"dot.1", "dot.2", "tanh.1"}
    att = timeline.attribute_timeline(ev, top_k=1)
    # ``.N`` instance suffixes aggregate: dot.1 + dot.2 -> one line
    assert att["top_kernels"] == [
        {"name": "dot", "kind": "compute", "count": 2,
         "total_ms": pytest.approx(0.04)}]
    # no collectives at all: fraction pins to 0.0, not NaN
    assert att["measured_overlap_fraction"] == 0.0
    # empty event list attributes to all-zeros (a capture of an idle
    # process must produce a valid record, /profilez relies on it)
    empty = timeline.attribute_timeline([])
    rec = exporters.JsonlExporter.enrich(
        timeline.profile_record(empty, metric="idle"))
    assert exporters.validate_profile_record(rec) == []
    assert empty["span_ms"] == empty["device_busy_ms"] == 0.0


def test_load_trace_plain_and_gz(tmp_path):
    doc = _trace([_kernel("dot.1", ts=0.0, dur=10.0)])
    plain = tmp_path / "a.trace.json"
    plain.write_text(json.dumps(doc))
    with gzip.open(str(tmp_path / "b.trace.json.gz"), "wt") as f:
        json.dump(doc, f)
    for p in (str(plain), str(tmp_path / "b.trace.json.gz")):
        loaded = timeline.load_trace(p)
        assert len(timeline.device_events(loaded)) == 1
    bad = tmp_path / "c.trace.json"
    bad.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError, match="traceEvents"):
        timeline.load_trace(str(bad))


def test_find_trace_file_resolves_newest_session(tmp_path):
    """The jax layout (plugins/profile/<session>/host.trace.json.gz)
    resolves; with two sessions the newest wins; a missing capture
    raises FileNotFoundError instead of parsing stale garbage."""
    with pytest.raises(FileNotFoundError):
        timeline.find_trace_file(str(tmp_path))
    s1 = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    s1.mkdir(parents=True)
    p1 = s1 / "host.trace.json.gz"
    with gzip.open(str(p1), "wt") as f:
        json.dump(_trace([]), f)
    assert timeline.find_trace_file(str(tmp_path)) == str(p1)
    s2 = tmp_path / "plugins" / "profile" / "2026_01_02_00_00_00"
    s2.mkdir(parents=True)
    p2 = s2 / "host.trace.json"
    p2.write_text(json.dumps(_trace([])))
    os.utime(str(p1), (1, 1))              # force p2 newer
    assert timeline.find_trace_file(str(tmp_path)) == str(p2)
    # a direct file path passes through
    assert timeline.find_trace_file(str(p2)) == str(p2)


def test_profile_record_schema_mutations():
    """validate_profile_record catches the hand-built-record
    mistakes: busy above span, gap not reassembling, overlap escaping
    its intersection bound, fraction inconsistent with its own sides,
    unknown kernel kinds, and bad KV fields."""
    att = timeline.attribute_timeline(timeline.device_events(_trace([
        _kernel("dot.1", ts=0.0, dur=100.0),
        _kernel("all-reduce.1", ts=50.0, dur=100.0, tid=3),
    ])))
    good = exporters.JsonlExporter.enrich(timeline.profile_record(
        att, metric="m", kv_cache_bytes=1000, kv_waste_bytes=400,
        kv_utilization=0.6))
    assert exporters.validate_profile_record(good) == []
    assert any("kind" in e for e in exporters.validate_profile_record(
        {**good, "kind": "bench"}))
    assert any("metric" in e[:40] or "entry_point" in e
               for e in exporters.validate_profile_record(
                   {k: v for k, v in good.items() if k != "metric"}))
    assert any("device_busy_ms" in e
               for e in exporters.validate_profile_record(
                   {**good, "device_busy_ms": good["span_ms"] + 5.0}))
    assert any("gap_ms" in e
               for e in exporters.validate_profile_record(
                   {**good, "gap_ms": good["gap_ms"] + 3.0}))
    assert any("overlap_ms" in e
               for e in exporters.validate_profile_record(
                   {**good, "overlap_ms": good["collective_ms"] + 1.0}))
    assert any("measured_overlap_fraction" in e
               for e in exporters.validate_profile_record(
                   {**good, "measured_overlap_fraction": 0.0}))
    assert any("collective_ms" in e
               for e in exporters.validate_profile_record(
                   {**good, "collective_ms": -1.0}))
    assert any("top_kernels" in e
               for e in exporters.validate_profile_record(
                   {**good, "top_kernels": [
                       {"name": "dot", "kind": "magic", "count": 1,
                        "total_ms": 1.0}]}))
    assert any("kv_waste_bytes" in e
               for e in exporters.validate_profile_record(
                   {**good, "kv_waste_bytes": 2000}))   # > cache
    assert any("kv_utilization" in e
               for e in exporters.validate_profile_record(
                   {**good, "kv_utilization": 1.5}))
    assert any("steps" in e for e in exporters.validate_profile_record(
        {**good, "steps": 0}))


# -- real captures on the CPU mesh ----------------------------------------

def _psum_step():
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    def step(x):
        y = jnp.tanh(x @ x.T)
        return jax.lax.psum(y.sum(), "data")

    return jax.jit(jax.shard_map(step, mesh=mesh,
                                 in_specs=(P("data"),), out_specs=P(),
                                 check_vma=False))


def test_real_capture_parses_with_collectives(tmp_path):
    """One jitted psum step captured under profile(): the parser finds
    the trace jax actually wrote, the all-reduce classifies as a
    collective, and the per-step attribution is schema-valid."""
    f = _psum_step()
    x = jnp.ones((8 * 16, 16))
    f(x).block_until_ready()               # compile outside the window
    att = timeline.capture(f, x, iters=2, logdir=str(tmp_path),
                           modules=("jit_step",))
    assert att["steps"] == 2
    assert att["trace_path"].startswith(str(tmp_path))
    assert att["kernel_count"] > 0
    assert att["device_busy_ms"] > 0
    names = {k["name"] for k in att["top_kernels"]}
    assert any(k["kind"] == "collective" for k in att["top_kernels"]), \
        names
    rec = exporters.JsonlExporter.enrich(
        timeline.profile_record(att, metric="psum_step"))
    assert exporters.validate_profile_record(rec) == []


def test_steptime_timeline_consistency_pin(tmp_path):
    """The ISSUE's consistency test: attribute_step's differenced
    comm/compute split, pinned against the measured device-timeline
    split within the stated tolerance.  The step is compute-dominated
    (a real matmul) with a small collective, so BOTH methods must see
    a small comm share — an absolute 0.6 tolerance on the fraction is
    loose enough for a noisy shared CPU host (under full-suite load
    the 8 device threads' psum rendezvous waits inflate the MEASURED
    collective share to ~0.38-0.52 while differencing reads 0 —
    observed flakes at the old 0.35 and 0.5 tolerances under suite
    load) and tight enough to catch the methodology
    inverting (a twin that elides compute would push the differenced
    share toward 1.0, an abs_diff of ~0.9)."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))

    def make(comm):
        def step(x):
            y = jnp.tanh(x @ x.T).sum()
            # the compute twin's unreplicated scalar under out_specs
            # P() is fine with check_vma=False — the same discipline
            # bench's comm_enabled=False twin uses
            return jax.lax.psum(y, "data") if comm else y
        return jax.jit(jax.shard_map(
            step, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
            check_vma=False))

    comm_only = jax.jit(jax.shard_map(
        lambda x: jax.lax.psum(x[0, 0], "data"), mesh=mesh,
        in_specs=(P("data"),), out_specs=P(), check_vma=False))
    x = jnp.ones((8 * 32, 64))
    att = steptime.attribute_step(
        make(True), make(False), comm_only, args=(x,), iters=4,
        warmup=2, capture_timeline=True, capture_dir=str(tmp_path),
        timeline_modules=("jit_step",), consistency_tol=0.6)
    assert "timeline" in att
    tl = att["timeline"]
    assert tl["kernel_count"] > 0
    assert 0.0 <= att["measured_overlap_fraction"] <= 1.0
    c = att["consistency"]
    assert set(c) == {"differenced_comm_fraction",
                      "measured_comm_fraction", "abs_diff", "tol",
                      "consistent"}
    assert c["tol"] == 0.6
    assert c["consistent"], c
    # and the differencing-side schema contract still holds untouched
    for k in steptime.ATTRIBUTION_FIELDS:
        assert k in att


def test_timeline_consistency_flags_inverted_split():
    """A methodology inversion (differencing says all-comm, the
    timeline says none) fails the pin — the check is not a tautology."""
    att = {"step_ms": 10.0, "comm_ms": 9.0}
    tl = {"span_ms": 10.0, "collective_ms": 0.0, "overlap_ms": 0.0}
    c = steptime.timeline_consistency(att, tl, tol=0.35)
    assert not c["consistent"]
    assert c["differenced_comm_fraction"] == pytest.approx(0.9)
    assert c["measured_comm_fraction"] == 0.0
    # agreeing splits pass
    tl2 = {"span_ms": 10.0, "collective_ms": 9.5, "overlap_ms": 0.7}
    assert steptime.timeline_consistency(att, tl2,
                                         tol=0.35)["consistent"]


def test_profiler_unique_capture_dirs(tmp_path):
    """The capture-reuse fix: repeated captures into ONE logdir land
    in distinct subdirectories, each holding its own trace file —
    start_trace names sessions by wall-clock second, so two captures
    in one second used to overwrite each other."""
    f = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((16, 16))
    f(x).block_until_ready()
    dirs = []
    for _ in range(2):
        with profiler.profile(str(tmp_path)) as cap:
            assert profiler.current_capture_dir() == cap
            f(x).block_until_ready()
        dirs.append(cap)
    assert dirs[0] != dirs[1]
    assert all(d.startswith(str(tmp_path)) for d in dirs)
    assert profiler.current_capture_dir() is None
    assert profiler.last_capture_dir() == dirs[1]
    # both captures kept their own trace file — nothing overwritten
    traces = [timeline.find_trace_file(d) for d in dirs]
    assert traces[0] != traces[1]
    for t in traces:
        assert timeline.load_trace(t)["traceEvents"] is not None
    # nested profile() joins the outer window: same dir, refcount
    # semantics preserved (the existing nesting test monkeypatches the
    # trace calls; this one exercises the real window)
    with profiler.profile(str(tmp_path)) as outer:
        with profiler.profile(str(tmp_path / "inner")) as inner:
            assert inner == outer
            assert profiler.profiling_active()
        assert profiler.profiling_active()
    assert not profiler.profiling_active()


def test_failed_start_trace_leaves_no_orphan_dir(tmp_path, monkeypatch):
    """A foreign trace already active makes start_trace raise; the
    pre-created unique capture dir must not be left behind (a monitor
    retrying /profilez would otherwise grow one orphan per attempt) and
    the refcount must stay clean."""
    def boom(d):
        raise RuntimeError("Only one profile may be run at a time.")
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    with pytest.raises(RuntimeError, match="one profile"):
        profiler.start_profile(str(tmp_path))
    assert os.listdir(str(tmp_path)) == []
    assert not profiler.profiling_active()
