"""Sequence/context-parallel attention parity tests.

Ring attention and Ulysses all-to-all attention over a faked sp mesh axis
must reproduce the single-device dense attention on the gathered sequence
exactly (up to fp32 reassociation) — the SP analogue of the reference's
two-GPU-vs-full-batch SyncBN parity test
(tests/distributed/synced_batchnorm/two_gpu_unit_test.py).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.transformer import (dot_product_attention, ring_attention,
                                  ulysses_attention, MultiheadAttention)

B, H, T, D = 2, 4, 32, 8
SP = 4


def _qkv(key, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (B, H, T, D), dtype) for k in ks)


def _dense_reference(q, k, v, causal):
    mask = None
    if causal:
        pos = jnp.arange(T)
        mask = pos[:, None] >= pos[None, :]
    return dot_product_attention(q, k, v, mask=mask,
                                 scale=1.0 / math.sqrt(D))


def _sp_run(attn_fn, q, k, v, causal):
    devs = np.array(jax.devices()[:SP])
    mesh = Mesh(devs, ("sp",))

    def local(q, k, v):
        return attn_fn(q, k, v, axis_name="sp", causal=causal)

    f = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, "sp"),) * 3,
        out_specs=P(None, None, "sp"), check_vma=False))
    return f(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    ref = _dense_reference(q, k, v, causal)
    out = _sp_run(ring_attention, q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    q, k, v = _qkv(jax.random.PRNGKey(1))
    ref = _dense_reference(q, k, v, causal)
    out = _sp_run(ulysses_attention, q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_matches_ulysses_bf16():
    q, k, v = _qkv(jax.random.PRNGKey(2), jnp.bfloat16)
    ring = _sp_run(ring_attention, q, k, v, True).astype(jnp.float32)
    uly = _sp_run(ulysses_attention, q, k, v, True).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(uly),
                               rtol=2e-2, atol=2e-2)


def test_ulysses_rejects_indivisible_heads():
    devs = np.array(jax.devices()[:SP])
    mesh = Mesh(devs, ("sp",))
    q = jnp.ones((B, 2, T, D))  # 2 heads, sp=4

    def local(q):
        return ulysses_attention(q, q, q, axis_name="sp")

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(jax.shard_map(local, mesh=mesh,
                              in_specs=(P(None, None, "sp"),),
                              out_specs=P(None, None, "sp"),
                              check_vma=False))(q)


def test_ring_grad_matches_dense_grad():
    """d(loss)/d(q,k,v) through the ring must equal the dense gradient —
    the online-softmax rematerialization is exact."""
    q, k, v = _qkv(jax.random.PRNGKey(3))

    def dense_loss(qkv):
        q, k, v = qkv
        return jnp.sum(_dense_reference(q, k, v, True) ** 2)

    def ring_loss(qkv):
        q, k, v = qkv
        return jnp.sum(_sp_run(ring_attention, q, k, v, True) ** 2)

    g_ref = jax.grad(dense_loss)((q, k, v))
    g_ring = jax.grad(ring_loss)((q, k, v))
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_multihead_attention_module():
    model = MultiheadAttention(embed_dim=16, num_heads=4)
    params, _ = model.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, 16))
    out, _ = model.apply(params, x)
    assert out.shape == (B, T, 16)
    assert jnp.all(jnp.isfinite(out))


def test_causal_cross_length_decode_mask():
    """causal=True with Tq < Tk (decode): the last query sees all keys,
    the first query sees the first Tk-Tq+1 keys."""
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    Tq, Tk = 4, 12
    q = jax.random.normal(ks[0], (1, 2, Tq, 8))
    k = jax.random.normal(ks[1], (1, 2, Tk, 8))
    v = jax.random.normal(ks[2], (1, 2, Tk, 8))
    out = dot_product_attention(q, k, v, causal=True)
    qpos = Tk - Tq + jnp.arange(Tq)
    mask = qpos[:, None] >= jnp.arange(Tk)[None, :]
    ref = dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_causal_with_padding_mask_keeps_causality():
    """Regression (round-1 advisor, medium): causal=True combined with an
    explicit mask must AND the two constraints — the old code silently
    dropped causality whenever any mask was passed."""
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (1, 2, T, 8)) for kk in ks)
    valid_len = T - 7
    pad = (jnp.arange(T) < valid_len)[None, :]        # key padding mask
    out = dot_product_attention(q, k, v, mask=pad, causal=True)

    pos = jnp.arange(T)
    combined = jnp.logical_and(pos[:, None] >= pos[None, :], pad)
    ref = dot_product_attention(q, k, v, mask=combined)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    # and it must differ from the padding-only result (proves the AND)
    wrong = dot_product_attention(q, k, v, mask=pad)
    assert not np.allclose(np.asarray(out), np.asarray(wrong))


def test_multihead_key_padding_mask():
    """torch convention: True = ignore.  Must equal an explicit validity
    mask, and masked key positions must not influence valid outputs."""
    from apex_tpu.transformer import MultiheadAttention
    from apex_tpu import nn
    mha = MultiheadAttention(16, 2)
    params, _ = mha.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 16))
    kpm = jnp.zeros((2, 12), bool).at[1, 8:].set(True)   # ignore tail

    out, _ = nn.apply(mha, params, x, key_padding_mask=kpm)
    valid4 = jnp.logical_not(kpm)[:, None, None, :]
    ref, _ = nn.apply(mha, params, x, mask=valid4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    # perturbing an ignored key's row must not change any output
    x2 = x.at[1, 10].add(100.0)
    out2, _ = nn.apply(mha, params, x2, key_padding_mask=kpm)
    # row 10 of batch 1 is itself a query, so compare only other rows
    np.testing.assert_allclose(np.asarray(out2[1, :8]),
                               np.asarray(out[1, :8]),
                               rtol=1e-5, atol=1e-5)


def test_dot_product_attention_segment_ids_paths_agree(monkeypatch):
    """segment_ids on the dense path == the flash path (forced pallas)."""
    from apex_tpu.transformer import dot_product_attention
    B, H, T, D = 2, 2, 64, 16
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q, k, v = (jax.random.normal(kk, (B, H, T, D)) for kk in ks)
    seg = jnp.asarray(np.repeat([0, 1], T // 2)[None, :].repeat(B, 0),
                      jnp.int32)

    # pin the baseline to the dense path explicitly — on a TPU host the
    # ambient default would route both calls through the flash kernel
    monkeypatch.setenv("APEX_TPU_DISABLE_PALLAS", "1")
    dense = dot_product_attention(q, k, v, causal=True, segment_ids=seg)
    monkeypatch.setenv("APEX_TPU_FORCE_PALLAS", "1")
    monkeypatch.delenv("APEX_TPU_DISABLE_PALLAS", raising=False)
    flash = dot_product_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)
    with pytest.raises(ValueError, match="requires"):
        dot_product_attention(q[0], k[0], v[0], segment_ids=seg)


def test_attention_path_hook(monkeypatch):
    """set_path_hook reports which backend dispatch resolved to (ADVICE
    r3: parity harnesses need to pin the compiled path)."""
    # pin dispatch: without this the assertion depends on the ambient
    # APEX_TPU_FORCE_PALLAS / backend, which kernel-parity runs set
    monkeypatch.setenv("APEX_TPU_DISABLE_PALLAS", "1")
    from apex_tpu.transformer import attention
    seen = []
    attention.set_path_hook(seen.append)
    try:
        q = jnp.asarray(np.random.RandomState(0).randn(2, 2, 16, 8),
                        jnp.float32)
        attention.dot_product_attention(q, q, q, causal=True)
    finally:
        attention.set_path_hook(None)
    assert seen == ["dense"]
