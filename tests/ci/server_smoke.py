#!/usr/bin/env python
"""CI gate: boot the introspection server on an ephemeral port and
scrape it end to end.

Loads ``apex_tpu.observability``'s server stack WITHOUT importing the
apex_tpu package (pure stdlib — same loader discipline as
check_bench_schema.py: a smoke gate that pulls in jax + the model zoo
would cost ~15s per CI invocation for nothing), builds a registry /
flight ring / span recorder / run supervisor with representative
content — including label values that NEED exposition escaping — then:

1. starts :class:`ObservabilityServer` on ``127.0.0.1:0``;
2. scrapes ``/healthz`` ``/metricsz`` ``/statusz`` ``/flightz``
   ``/tracez`` (and ``/tracez?trace_id=``) over real HTTP, the
   ``/profilez`` no-capture shape — with no profiler hook attached
   (the jax-free deployment) the endpoint must answer 404, never 500 —
   and ``/compilez`` against a jax-free compilation ledger seeded with
   a shape retrace, whose differ verdict (culprit argument) must be on
   the snapshot, and ``/tenantz`` in both deployment shapes — with no
   tenant source attached it must serve the valid empty rollup (200,
   never an error: the jax-free process has no fleet), with a seeded
   tenant source it must serve the per-tenant block, isolate a raising
   source, filter with ``?tenant=`` and 404 an unknown tenant;
3. validates ``/metricsz`` against the exposition-format conformance
   checker (``validate_prometheus_text``: TYPE/HELP lines, label
   escaping round-trip, +Inf buckets, cumulative monotonicity);
4. validates the JSON endpoints' shapes — ``/healthz`` status + check
   map, ``/statusz`` source isolation, ``/flightz`` seq-ordered events
   with exact drop accounting, ``/tracez?trace_id=`` as a schema-clean
   ``kind: trace`` record — and that the supervisor's sick verdict
   flips ``/healthz`` to 503.

Exit 0 = every scrape valid; 1 = any violation (each printed).
Wired into tier-1 by tests/test_server.py (subprocess), like the
check_bench_trend gate.
"""

import importlib.util
import json
import os
import sys
import urllib.error
import urllib.request

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))


def _load_obs():
    """Load the jax-free observability submodules the server needs,
    without importing the apex_tpu package."""
    pkg_dir = os.path.join(_ROOT, "apex_tpu", "observability")
    spec = importlib.util.spec_from_file_location(
        "_obs_smoke", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    pkg = importlib.util.module_from_spec(spec)
    sys.modules["_obs_smoke"] = pkg
    mods = {}
    for sub in ("metrics", "exporters", "flightrec", "tracing",
                "supervisor", "compilation", "server"):
        sspec = importlib.util.spec_from_file_location(
            f"_obs_smoke.{sub}", os.path.join(pkg_dir, sub + ".py"))
        mod = importlib.util.module_from_spec(sspec)
        sys.modules[f"_obs_smoke.{sub}"] = mod
        sspec.loader.exec_module(mod)
        mods[sub] = mod
    return mods


def _get(url, want_status=200):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.headers.get("Content-Type", ""), r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), e.read()


def main(argv):
    errs = []
    mods = _load_obs()
    metrics, exporters = mods["metrics"], mods["exporters"]
    flightrec, tracing = mods["flightrec"], mods["tracing"]
    supervisor, server = mods["supervisor"], mods["server"]
    compilation = mods["compilation"]

    # representative content, incl. escape-needing label values
    reg = metrics.MetricsRegistry()
    reg.counter("smoke_requests_total",
                help="requests with a \\ backslash in help").labels(
        route='/v1/"generate"\npath', shard="a\\b").inc(5)
    reg.gauge("smoke_occupancy").set(0.75)
    h = reg.histogram("smoke_latency_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 5.0):
        h.observe(v)
    ring = flightrec.EventRing(capacity=4)
    for i in range(5):                  # overflow: exact drop accounting
        ring.append("smoke_event", i=i)
    # tenant-stamped events for the ?tenant= filter: one per-request
    # stamp, one aggregate tenants list (both must match)
    ring.append("shed", queue_depth=4, max_queue=4, tenant="acme")
    ring.append("failover", replica=0, tenants=["acme", "zeta"])
    rec = tracing.SpanRecorder()
    tid = tracing.new_trace_id("smoke")
    root = rec.event("submit", trace_id=tid)
    rec.event("dispatch", trace_id=tid, parent_id=root)
    sup = supervisor.RunSupervisor("smoke_run", registry=reg, ring=ring)
    sup.observe_step(step=0, loss=1.0, step_time_s=0.01)

    # a jax-free compilation ledger with a seeded retrace: one entry
    # traced twice at different shapes, so /compilez must show the
    # differ's culprit argument (the endpoint's whole point)
    led = compilation.CompilationLedger(registry=reg, ring=ring)
    led.record_trace("engine._step_k",
                     {"ids": {"leaves": [["int32", [4, 32]]]},
                      "cur_len": {"leaves": [["int32", [4]]]}},
                     closure_id=0)
    led.record_trace("engine._step_k",
                     {"ids": {"leaves": [["int32", [4, 48]]]},
                      "cur_len": {"leaves": [["int32", [4]]]}},
                     closure_id=0)

    srv = server.ObservabilityServer(
        registry=reg, ring=ring, recorder=rec, ledger=led,
        status={"run": sup.status,
                "boom": lambda: (_ for _ in ()).throw(
                    RuntimeError("seeded source failure"))},
        health={"run": sup.health_check}).start()
    base = srv.url
    print(f"server_smoke: serving on {base}")

    try:
        # /healthz — healthy run, 200 + check map
        code, ctype, body = _get(base + "/healthz")
        hz = json.loads(body)
        if code != 200 or hz.get("status") != "ok":
            errs.append(f"/healthz expected 200/ok, got {code}/"
                        f"{hz.get('status')!r}")
        if hz.get("checks", {}).get("run", {}).get("ok") is not True:
            errs.append(f"/healthz run check not ok: {hz.get('checks')}")

        # /metricsz — exposition conformance
        code, ctype, body = _get(base + "/metricsz")
        if code != 200 or not ctype.startswith("text/plain"):
            errs.append(f"/metricsz expected 200 text/plain, got "
                        f"{code} {ctype!r}")
        text = body.decode("utf-8")
        for e in exporters.validate_prometheus_text(text):
            errs.append(f"/metricsz exposition: {e}")
        fams = exporters.parse_prometheus_text(text)
        labels = fams["smoke_requests_total"]["samples"][0][1]
        if labels.get("route") != '/v1/"generate"\npath' \
                or labels.get("shard") != "a\\b":
            errs.append(f"/metricsz label escaping did not round-trip: "
                        f"{labels}")

        # /statusz — source content + error isolation
        code, _, body = _get(base + "/statusz")
        st = json.loads(body)
        if code != 200 or st.get("run", {}).get("run") != "smoke_run":
            errs.append(f"/statusz missing run source: {code}")
        if "error" not in st.get("boom", {}):
            errs.append("/statusz did not isolate the raising source")

        # /flightz — seq-ordered window, exact drop accounting
        code, _, body = _get(base + "/flightz")
        fz = json.loads(body)
        seqs = [e["seq"] for e in fz.get("events", [])]
        if code != 200 or seqs != sorted(seqs):
            errs.append(f"/flightz events not seq-ordered: {seqs}")
        if fz.get("total", 0) != fz.get("dropped", -1) + len(seqs):
            errs.append(f"/flightz drop accounting inexact: {fz}")

        # /flightz?tenant= — one tenant's story: the per-request
        # ``tenant`` stamp AND the aggregate ``tenants`` list match
        code, _, body = _get(base + "/flightz?tenant=acme")
        fzt = json.loads(body)
        kinds = sorted(e["kind"] for e in fzt.get("events", []))
        if code != 200 or kinds != ["failover", "shed"]:
            errs.append(f"/flightz?tenant=acme expected the shed + "
                        f"failover events, got {kinds}")
        code, _, body = _get(base + "/flightz?tenant=nobody")
        if json.loads(body).get("events"):
            errs.append("/flightz?tenant=nobody returned events for "
                        "an unknown tenant")

        # /tracez — index, then one schema-clean kind: trace record
        code, _, body = _get(base + "/tracez")
        tz = json.loads(body)
        if code != 200 or tid not in tz.get("traces", []):
            errs.append(f"/tracez index missing {tid}: {tz.get('traces')}")
        code, _, body = _get(base + f"/tracez?trace_id={tid}")
        trec = json.loads(body)
        for e in exporters.validate_trace_record(trec):
            errs.append(f"/tracez record: {e}")
        code, _, _ = _get(base + "/tracez?trace_id=nope")
        if code != 404:
            errs.append(f"/tracez unknown trace expected 404, got {code}")

        # /profilez — no profiler hook attached (this loader is
        # jax-free by design): 404 with a JSON error, not a 500
        code, _, body = _get(base + "/profilez")
        if code != 404:
            errs.append(f"/profilez with no hook expected 404, got "
                        f"{code}")
        else:
            pz = json.loads(body)
            if "error" not in pz:
                errs.append(f"/profilez 404 body carries no error: "
                            f"{pz}")
        code, _, _ = _get(base + "/profilez?duration_ms=bogus")
        if code != 400:
            errs.append(f"/profilez with bad duration expected 400, "
                        f"got {code}")

        # /compilez — the ledger snapshot with the seeded retrace's
        # differ verdict (jax-free: record_trace is pure host python)
        code, _, body = _get(base + "/compilez")
        cz = json.loads(body)
        if code != 200 or cz.get("kind") != "compilation":
            errs.append(f"/compilez expected 200 kind=compilation, "
                        f"got {code} {cz.get('kind')!r}")
        ent = cz.get("entries", {}).get("engine._step_k", {})
        if ent.get("traces") != 2 or ent.get("retraces") != 1:
            errs.append(f"/compilez entry counts wrong: {ent}")
        lr = ent.get("last_retrace") or {}
        if lr.get("cause") != "shape" or lr.get("culprit") != "ids":
            errs.append(f"/compilez last_retrace must name the shape "
                        f"culprit 'ids', got {lr}")
        if cz.get("totals", {}).get("traces") != 2:
            errs.append(f"/compilez totals wrong: {cz.get('totals')}")
        code, _, body = _get(base + "/compilez?entry=engine._step_k")
        fz1 = json.loads(body)
        if code != 200 or list(fz1.get("entries", {})) != \
                ["engine._step_k"]:
            errs.append(f"/compilez ?entry= filter broken: {code}")
        code, _, _ = _get(base + "/compilez?entry=nope")
        if code != 404:
            errs.append(f"/compilez unknown entry expected 404, got "
                        f"{code}")

        # /tenantz — no tenant source attached: the valid empty shape
        # (200, never an error — this loader is the jax-free
        # deployment, exactly the process with no fleet)
        code, _, body = _get(base + "/tenantz")
        tz0 = json.loads(body)
        if (code != 200 or tz0.get("kind") != "tenants"
                or tz0.get("tenant_names") != []
                or tz0.get("class_names") != []
                or tz0.get("by_source") != {}):
            errs.append(f"/tenantz empty shape wrong: {code} {tz0}")
        code, _, _ = _get(base + "/tenantz?tenant=acme")
        if code != 404:
            errs.append(f"/tenantz?tenant= with no source expected "
                        f"404, got {code}")
        code, _, _ = _get(base + "/tenantz?class=interactive")
        if code != 404:
            errs.append(f"/tenantz?class= with no source expected "
                        f"404, got {code}")

        # /tenantz — seeded tenant source + a raising one: per-tenant
        # block served, per-source error isolation, ?tenant= filter
        bucket = {"submitted": 3, "finished": 2, "failed": 0,
                  "shed": 1, "deadline_exceeded": 0, "slo_misses": 0,
                  "goodput_tokens": 32, "with_deadline": 2,
                  "within_deadline": 2, "slo_attainment": 1.0,
                  "goodput_tokens_per_s": 12.5}
        srv.add_tenant_source("fleet", lambda: {
            "tenants": {"acme": dict(bucket), "zeta": dict(bucket)},
            "tenants_dropped": 0, "label_sets_dropped": {}})
        srv.add_tenant_source("boomfleet", lambda: (
            _ for _ in ()).throw(RuntimeError("seeded tenant source "
                                              "failure")))
        code, _, body = _get(base + "/tenantz")
        tz = json.loads(body)
        if code != 200 or tz.get("tenant_names") != ["acme", "zeta"]:
            errs.append(f"/tenantz tenant_names wrong: {code} "
                        f"{tz.get('tenant_names')}")
        acme = tz.get("by_source", {}).get("fleet", {}) \
                 .get("tenants", {}).get("acme")
        if acme != bucket:
            errs.append(f"/tenantz fleet source bucket wrong: {acme}")
        if "error" not in tz.get("by_source", {}).get("boomfleet", {}):
            errs.append("/tenantz did not isolate the raising tenant "
                        "source")
        code, _, body = _get(base + "/tenantz?tenant=acme")
        tzf = json.loads(body)
        fl_t = tzf.get("by_source", {}).get("fleet", {})
        if (code != 200 or tzf.get("filter") != "acme"
                or list(fl_t.get("tenants", {})) != ["acme"]):
            errs.append(f"/tenantz?tenant=acme filter broken: {code} "
                        f"{fl_t.get('tenants')}")
        code, _, _ = _get(base + "/tenantz?tenant=nope")
        if code != 404:
            errs.append(f"/tenantz unknown tenant expected 404, got "
                        f"{code}")

        # /tenantz?class= — a QoS-aware source adds a per-class
        # ``classes`` rollup (schema v14) next to its tenants; the
        # filter narrows it per source, 404s only when NO source
        # knows the class, and composes with ?tenant=
        cbucket = dict(bucket, preempted=1, queue_depth=0,
                       queue_cap=8, weight=8, preemptible=False)
        srv.add_tenant_source("qosfleet", lambda: {
            "tenants": {"acme": dict(bucket)},
            "classes": {"interactive": dict(cbucket),
                        "batch": dict(cbucket, weight=1,
                                      preemptible=True)},
            "tenants_dropped": 0, "preemptions": 2})
        code, _, body = _get(base + "/tenantz")
        tzc = json.loads(body)
        if (code != 200
                or tzc.get("class_names") != ["batch", "interactive"]):
            errs.append(f"/tenantz class_names wrong: {code} "
                        f"{tzc.get('class_names')}")
        code, _, body = _get(base + "/tenantz?class=interactive")
        tzc = json.loads(body)
        qf = tzc.get("by_source", {}).get("qosfleet", {})
        if (code != 200 or tzc.get("class_filter") != "interactive"
                or list(qf.get("classes", {})) != ["interactive"]
                or qf["classes"]["interactive"] != cbucket):
            errs.append(f"/tenantz?class=interactive filter broken: "
                        f"{code} {qf.get('classes')}")
        # the class filter must leave class-less sources intact (the
        # plain fleet source has no classes block) and compose with
        # the tenant filter
        code, _, body = _get(
            base + "/tenantz?tenant=acme&class=batch")
        tzb = json.loads(body)
        qf = tzb.get("by_source", {}).get("qosfleet", {})
        if (code != 200 or list(qf.get("classes", {})) != ["batch"]
                or list(qf.get("tenants", {})) != ["acme"]):
            errs.append(f"/tenantz tenant+class compose broken: "
                        f"{code} {qf}")
        code, _, body = _get(base + "/tenantz?class=nope")
        if code != 404:
            errs.append(f"/tenantz unknown class expected 404, got "
                        f"{code}")
        else:
            czerr = json.loads(body)
            if "class" not in str(czerr.get("error", "")):
                errs.append(f"/tenantz 404 body must name the class: "
                            f"{czerr}")

        # sick supervisor flips /healthz to 503
        sup.observe_step(step=1, loss=float("nan"))
        code, _, body = _get(base + "/healthz")
        hz = json.loads(body)
        if code != 503 or hz.get("status") != "unhealthy":
            errs.append(f"/healthz expected 503/unhealthy after NaN, "
                        f"got {code}/{hz.get('status')!r}")
    finally:
        srv.stop()

    for e in errs:
        print(f"server_smoke: {e}", file=sys.stderr)
    if errs:
        return 1
    print("server_smoke: all 8 endpoints OK (exposition conformant, "
          "schemas valid, profilez no-capture 404, compilez retrace "
          "differ verdict served, tenantz empty shape + per-tenant "
          "rollup + per-class ?class= filter + 404, sick-run 503)")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
