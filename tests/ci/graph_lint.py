#!/usr/bin/env python
"""CI gate: run the static graph analyzer over every registered hot
entry point and fail on ANY finding.

This is the mechanical enforcement of the invariants the repo has paid
to learn: no host syncs in jitted hot graphs, every donated KV buffer
actually aliased (and the per-slot length vectors NEVER donated — the
PR 2 compile-cache corruption), conv/matmul operand dtypes matching
the O-level policy, transpose-free channels-last steps, and the exact
collective pattern DDP/TP assume — plus, since the sharding plane
landed, shard_map specs consistent with their mesh (and every
replicated-out-spec divergence declared) and every placement-changing
collective explained by the comm plan or a declared budget
(resharding census).  New rules registered in apex_tpu.analysis.rules
are picked up here automatically: this gate runs the full RULES
registry via the module CLI.  Usage:

    python tests/ci/graph_lint.py                      # full registry
    python tests/ci/graph_lint.py --tags serving       # subset
    python tests/ci/graph_lint.py --entry paged        # substring
    python tests/ci/graph_lint.py | \\
        python tests/ci/check_bench_schema.py          # schema-check it

Stdout is pure schema-versioned JSONL (findings + a summary record);
progress goes to stderr.  Exit 0 = clean, 1 = any finding.  Unlike the
module CLI (``python -m apex_tpu.analysis``), warnings also fail here:
CI has no one to read them.
"""

import json
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))


def main(argv):
    sys.path.insert(0, _ROOT)
    import io
    from apex_tpu.analysis.__main__ import main as lint_main

    args = argv[1:]
    buf = io.StringIO()
    real = sys.stdout
    sys.stdout = buf
    try:
        rc = lint_main(args)
    finally:
        sys.stdout = real
    out = buf.getvalue()
    sys.stdout.write(out)
    sys.stdout.flush()

    # promote warnings to failures by reading the run's own
    # graph_lint_summary record — from the --out file when the stream
    # was redirected there (--out appends, so scan from the end);
    # argparse accepts both "--out PATH" and "--out=PATH"
    out_path = None
    for i, a in enumerate(args):
        if a == "--out" and i + 1 < len(args):
            out_path = args[i + 1]
        elif a.startswith("--out="):
            out_path = a.split("=", 1)[1]
    lines = out.splitlines()
    if out_path:
        with open(out_path) as f:
            lines = f.read().splitlines()
    n_warn = 0
    for ln in reversed(lines):
        if ln.strip():
            rec = json.loads(ln)
            if rec.get("kind") == "graph_lint_summary":
                n_warn = rec.get("warnings", 0)
                break
    if rc == 0 and n_warn:
        print(f"graph_lint: {n_warn} warning(s) — CI treats warnings "
              f"as failures", file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv))
