#!/bin/bash
# jax version-drift matrix — the TPU-native analogue of the reference's
# docker_extension_builds (reference tests/docker_extension_builds/run.sh
# builds its CUDA extensions across 7 torch/cuda images to catch API
# drift before users do).
#
# apex_tpu's drift surface is the jax API instead of the torch C++ ABI.
# Since r5 the package uses NO jax._src private symbols (grep gate
# below); the remaining drift risks are behavioral contracts pinned by
# tests:
#   * lax.axis_index's NameError-on-unbound-axis contract
#     (tests/test_syncbn.py::test_axis_scope_probe) — beneath SyncBN,
#     TP/PP/EP guards, and the ZeRO path;
#   * jax.closure_convert residual extraction order
#     (parallel/pipeline.py 1F1B stash);
#   * shard_map/check_vma, Pallas, and optimizer-state pytree layouts.
#
# Usage:  tests/ci/version_matrix.sh [jax==X.Y.Z ...]
#   with no args: the pinned version (sanity) + the latest release.
#   Requires network access for pip; in the air-gapped build image this
#   script is documentation + the grep gate only (run with NO_PIP=1).
set -euo pipefail
cd "$(dirname "$0")/../.."

echo "== private-API gate (runs everywhere, no network needed) =="
if grep -rn --include='*.py' -E 'from jax\._src|jax\._src\.[a-z]' \
        apex_tpu/ | grep -v '``jax\._src``'; then
    echo "FAIL: jax._src private-API use found in apex_tpu/" >&2
    exit 1
fi
echo "ok: no jax._src use in apex_tpu/"

PINNED=$(python -c "import jax; print(jax.__version__)")
echo "== pinned jax: $PINNED =="

if [ "${NO_PIP:-0}" = "1" ]; then
    echo "NO_PIP=1: running the suite on the pinned version only"
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -x
    exit 0
fi

VERSIONS=("$@")
[ ${#VERSIONS[@]} -eq 0 ] && VERSIONS=("jax==$PINNED" "jax")

for spec in "${VERSIONS[@]}"; do
    name=$(echo "$spec" | tr '=<>~' '_')
    venv=".ci_venv_$name"
    echo "== matrix leg: $spec =="
    python -m venv --system-site-packages "$venv"
    # Pin jaxlib to the jax spec (ADVICE r5): an UNPINNED jaxlib
    # resolves to the latest wheel, which a pinned older jax may not
    # support — the leg would then fail on a jax/jaxlib skew that has
    # nothing to do with our code.  jax[cpu]==X pulls the exactly
    # matching jaxlib; a bare "jax" (latest) keeps the extra so both
    # packages ride the same release.
    case "$spec" in
        jax==*) pipspec="jax[cpu]==${spec#jax==}" ;;
        jax)    pipspec="jax[cpu]" ;;
        *)      pipspec="$spec" ;;
    esac
    # --ignore-installed so the venv's jax/jaxlib shadow the system pin
    "$venv/bin/pip" install -q --ignore-installed "$pipspec"
    "$venv/bin/python" -c "import jax; print('  jax', jax.__version__)"
    JAX_PLATFORMS=cpu "$venv/bin/python" -m pytest tests/ -q -x \
        || { echo "FAIL on $spec" >&2; exit 1; }
    rm -rf "$venv"
done
echo "== version matrix green =="
