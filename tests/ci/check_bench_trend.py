#!/usr/bin/env python
"""CI gate: machine-check the BENCH_r*.json trajectory.

The per-round bench artifacts wrap a JSONL ``tail`` of schema-versioned
records (tests/ci/check_bench_schema.py validates each record's shape;
THIS gate validates the trend ACROSS rounds).  Two failure classes:

1. **Unmarked replay.**  A wedged TPU tunnel makes bench replay the
   last known hardware record with ``stale: true`` — by design those
   lines must never read as fresh progress.  A line that carries a
   definitive replay fingerprint (the ``TPU_TUNNEL_WEDGED...`` flag in
   the same round, or a "STALE REPLAY" note) but is NOT marked
   ``stale: true`` is a replay presented as a fresh measurement:
   error.  A byte-identical accelerator record from an earlier round
   is only *suspicious* — stable hardware can honestly reproduce a
   rounded value — so it WARNS instead of gating (and still never
   counts as an improvement over the earlier line, which it equals).
2. **Fresh regression.**  Consecutive FRESH measurements of the same
   (metric, backend) that got worse by more than ``--tol`` (default
   25%): error on accelerator backends.  CPU-smoke lines live on a
   shared noisy container where run-to-run swings of several x are
   routine (fused_lamb_step_time moved 4.7x between r03 and r04 with
   no code change on that path), so CPU regressions are REPORTED as
   warnings but do not gate — the byte/plan fields and the tier-1
   suite are the portable CPU signals, hardware lines are the timing
   signal.  ``--strict-cpu`` promotes them to errors.
3. **Comm-overlap regression** (schema v9 overlap fields).  Fresh
   metric lines carrying ``overlap_fraction`` /
   ``measured_overlap_fraction`` (step-time attribution and profile
   lines from ``bench.py --comm`` / ``--profile``) trend per
   (metric, backend, field): a fraction that DROPS past ``--tol``
   after the overlap work drove it off zero is comm sliding back onto
   the critical path — error on accelerator backends, warning on CPU
   smoke (virtual devices share one host; measured overlap there
   reflects thread scheduling).  A ``comm_visible_ms`` field that
   GROWS past ``--tol`` follows the same policy.  A zero baseline cuts
   both ways: a FRACTION at 0 (the reduce-after-backward world) never
   trends — there is no overlap to lose yet — but a
   ``comm_visible_ms`` of 0 is the success state, and comm returning
   from fully hidden to measurably visible gates as the worst
   regression the column exists for.
4. **Peak-memory / MFU regression** (schema v3 cost-model fields).
   ``peak_bytes`` — on train-throughput lines and ``kind: memory``
   records — is a property of the COMPILED executable, deterministic
   on any backend, so growth past ``--mem-tol`` (default 25%) gates
   even on CPU: a step that suddenly plans 30% more device memory
   regressed no matter how noisy the host clock is (ROADMAP item 4's
   "pin peak-memory in bench").  ``mfu`` is timing-derived, so its
   regressions follow the same accelerator-gates / CPU-warns policy
   as throughput.  Stale replays are partitioned out of both trends
   exactly like throughput lines.

5. **Compile-plane regression** (schema v10 compile fields).  A fresh
   line carrying ``steady_state_retraces`` > 0 is an ERROR on every
   backend: the compilation ledger saw a jit re-trace DURING the timed
   loop, so the trended rate includes a recompile — that is a
   deterministic contract violation, not timing noise (the zero-
   retrace steady state is tier-1-pinned; a bench line breaking it
   means the measured configuration regressed the contract).
   ``cold_compile_ms`` growth past ``--tol`` follows the
   accelerator-gates / CPU-warns policy like MFU — compile time is
   wall-clock, but a 2x jump on hardware is a real compile-plane
   regression (a new shape family, a cache stopped hitting).

6. **Tenant-plane regression** (schema v11 tenant fields).  Per-tenant
   goodput lines from the ``bench.py --fleet`` two-tenant leg trend
   through the ordinary (metric, backend) path — the tenant is part of
   the metric name — and their ``slo_attainment`` field trends as its
   own column: an attainment drop past ``--tol`` follows the
   accelerator-gates / CPU-warns policy (attainment is timing-derived
   on a noisy host).  The ``*_tenant_parity`` line is NOT timing: the
   leg tags every request, so the sum of per-tenant goodput tokens
   over the fleet total must be 1.0 — a fresh parity off 1.0 by more
   than 1% means the tenant split lost or double-counted tokens, a
   deterministic accounting bug that gates on every backend (the
   steady-state-retrace rule, not the MFU rule).

7. **KV-plane regression** (schema v12 block-pool fields).  Fresh
   engine lines carry the PR 13 fragmentation ledger
   (``kv_waste_bytes``), and the paged allocator exists to drive it
   DOWN — so waste trends as a lower-is-better column per
   (metric, backend): growth past ``--tol`` errors on accelerator
   backends and warns on CPU smoke (the sampled waste depends on
   where in the admit/finish cycle the snapshot lands, which is
   timing on a noisy host).  A zero baseline is the success state —
   waste returning from 0 to measurably nonzero gates like comm
   coming back onto the critical path.  Separately, the v12 FIELD
   contract is deterministic and gates on every backend: a fresh
   ``engine_decode`` line whose round declares ``schema_version``
   >= 12 must carry ``admission_mode``, and a paged line must carry
   ``block_size``/``blocks_total``/``blocks_free`` — archived rounds
   that declare an older version are exempt (they were valid when
   written).

8. **Sharding-plane regression** (schema v13 ``kind: sharding``
   records from ``bench.py --graph-lint`` /
   ``python -m apex_tpu.analysis --sharding``).  The replication
   ledger's ``replicated_bytes`` is derived statically from the traced
   jaxpr — deterministic on every backend, exactly like
   ``peak_bytes`` — so growth past ``--mem-tol`` gates per
   (entry_point, backend) even on CPU smoke: a train step that
   suddenly duplicates more world bytes un-sharded something (a ZeRO
   shard silently re-replicated, an optimizer state that stopped
   partitioning).  Shrinkage is the ROADMAP item 2 direction and never
   gates.  Stale replays are partitioned out like everything else.

9. **QoS-plane regression** (schema v14 fields from the ``bench.py
   --fleet`` QoS leg).  Per-class goodput lines carry ``qos_class`` +
   ``slo_attainment``; attainment trends per (metric, backend) like
   the tenant column (timing-derived: accelerator gates, CPU warns),
   and the ``*_qos_aggregate_goodput`` line's ``vs_baseline`` — the
   QoS-tagged pass over the untagged baseline — dropping below 0.95
   follows the same policy (the WFQ plane is allowed ~5% overhead,
   not more).  The ``*_preemption_parity`` line is NOT timing: a
   preempted-then-readmitted request's tokens must equal an
   undisturbed run token-for-token, so a fresh parity off 1.0 by more
   than 1% is a deterministic exactness violation that gates on every
   backend (the steady-state-retrace rule — and the line's own
   ``steady_state_retraces`` must be 0, enforced by the v10 gate).

Stale replays are partitioned out of the trend entirely: a replay can
neither regress nor improve a metric (r04/r05's 1830 img/s replays do
not count as beating r02's fresh 508.6 — the tunnel was wedged, nobody
measured anything).  Error lines (``value: null`` + ``error``) and
flag/summary records are likewise excluded, as are per-run
``kind: numerics`` gradient-health dumps (schema v4), per-run
``kind: run`` supervisor verdicts (schema v5), per-run
``kind: recovery`` controller snapshots (schema v6), per-capture
``kind: profile`` device-timeline attributions (schema v8) and
per-run ``kind: fleet`` snapshots (whose v11 per-tenant blocks
describe one run's traffic mix, not a cross-round trend) — their
stale replays still count toward the partition tally.  The ``run_supervisor_overhead``
and ``fleet_goodput`` *metric* lines from ``bench.py --run`` are
ordinary measurements and DO trend (accelerator gates, CPU warns).

Usage::

    python tests/ci/check_bench_trend.py                 # repo root
    python tests/ci/check_bench_trend.py --dir /path     # other history
    python tests/ci/check_bench_trend.py --tol 0.4
    python tests/ci/check_bench_trend.py --mem-tol 0.1
    python tests/ci/check_bench_trend.py --strict-cpu

Exit 0 = trend clean (warnings allowed), 1 = any error.  Pure stdlib —
importable from CI without jax.
"""

import argparse
import glob
import json
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))

WEDGE_FLAG = "TPU_TUNNEL_WEDGED_NO_FRESH_HARDWARE_NUMBERS"
REPLAY_NOTE_MARKERS = ("STALE REPLAY", "stale replay", "replayed because")
# units where a LOWER value is better (times); anything else is a
# rate/ratio where higher is better
LOWER_IS_BETTER_UNITS = {"ms", "s", "us", "ns", "seconds"}


def load_rounds(directory):
    """[(round_name, [records])] in round order.  Each BENCH_r*.json is
    the runbook wrapper {n, cmd, rc, tail, parsed}; ``tail`` holds the
    run's last stdout bytes, so its FIRST line may be truncated —
    unparseable lines are skipped, complete JSONL records kept."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trend: cannot read {path}: {e}", file=sys.stderr)
            continue
        recs = []
        for ln in str(doc.get("tail", "")).splitlines():
            ln = ln.strip()
            if not ln.startswith("{"):
                continue            # stderr chatter / '# buffered:'
            try:
                rec = json.loads(ln)
            except ValueError:
                continue            # truncated head of the tail
            if isinstance(rec, dict):
                recs.append(rec)
        rounds.append((os.path.basename(path), recs))
    return rounds


def is_measurement(rec):
    """Fresh-or-stale numeric metric line (what the trend is made of):
    excludes error lines, flags, and non-metric kinds (fleet / trace /
    graph_lint records interleave in the same streams)."""
    if "kind" in rec and rec.get("kind") not in (None, "bench"):
        return False
    v = rec.get("value")
    return (isinstance(rec.get("metric"), str)
            and rec["metric"] != WEDGE_FLAG
            and isinstance(v, (int, float))
            and not isinstance(v, bool)
            and "error" not in rec
            and rec.get("unit") != "flag")


def is_stale(rec):
    return rec.get("stale") is True


def is_cpu(rec):
    # pre-envelope records (r01/r02) carry no backend field; they were
    # fresh measurements on whatever ran — treat unknown as gating
    # (nothing in the real history compares across the unknown key)
    return rec.get("backend") == "cpu"


def _replay_fingerprint(rec, round_has_wedge_flag, earlier_lines):
    """(kind, why) when this line looks like a replay, else None.
    kind "error" = definitive fingerprint (gates); kind "warning" =
    byte-identical re-emission, which stable hardware can honestly
    produce at rounded precision, so it only warns."""
    note = str(rec.get("note", ""))
    for marker in REPLAY_NOTE_MARKERS:
        if marker in note:
            return "error", f"note contains {marker!r}"
    if round_has_wedge_flag and not is_cpu(rec):
        return "error", f"round carries the {WEDGE_FLAG} flag"
    if not is_cpu(rec):
        # the replay path re-emits the record verbatim; a fresh
        # re-measurement USUALLY differs in its timed value, but can
        # legitimately repeat at 1-decimal rounding.  (CPU smoke lines
        # repeat all the time and are exempt.)
        key = json.dumps({k: v for k, v in rec.items()
                          if k not in ("stale", "schema_version",
                                       "host")}, sort_keys=True)
        if key in earlier_lines:
            return "warning", ("byte-identical to an earlier round's "
                               "record")
    return None


def direction(rec):
    unit = str(rec.get("unit", ""))
    if unit in LOWER_IS_BETTER_UNITS:
        return "lower"
    return "higher"


def _mem_subject(rec):
    """Trend key for a peak-bytes / mfu carrier: the bench metric or
    the analysis entry point (``kind: memory`` records from
    ``python -m apex_tpu.analysis --memory``)."""
    s = rec.get("metric") or rec.get("entry_point")
    return s if isinstance(s, str) and s else None


def check(directory, tol=0.25, strict_cpu=False, mem_tol=0.25,
          out=sys.stderr):
    rounds = load_rounds(directory)
    if not rounds:
        print(f"trend: no BENCH_r*.json under {directory}", file=out)
        return 1
    errors, warnings = [], []
    # (metric, backend) -> (round_name, value, unit) of last FRESH line
    last_fresh = {}
    # (subject, backend) -> (round_name, value) of the cost-model trends
    last_mem = {}
    last_mfu = {}
    # (subject, backend, field) -> (round_name, value) of the
    # comm-overlap trends (schema v9 fields on attribution/profile
    # metric lines)
    last_overlap = {}
    # (metric, backend) -> (round_name, cold_compile_ms) of the
    # compile-plane trend (schema v10)
    last_compile = {}
    # (metric, backend) -> (round_name, slo_attainment) of the
    # per-tenant attainment trend (schema v11)
    last_attain = {}
    # (metric, backend) -> (round_name, kv_waste_bytes) of the
    # KV-plane trend (schema v12)
    last_waste = {}
    # (metric, backend) -> (round_name, slo_attainment) of the
    # per-class attainment trend (schema v14)
    last_class_attain = {}
    # (entry_point, backend) -> (round_name, replicated_bytes) of the
    # replication-ledger trend (schema v13)
    last_repl = {}
    earlier_lines = set()
    n_fresh = n_stale = 0

    def track_cost_fields(rname, rec):
        """Peak-memory and MFU trends for one fresh record (bench line
        or ``kind: memory`` dump).  peak_bytes is compiled-plan
        deterministic -> gates on every backend; mfu is timing ->
        follows the CPU-warns policy."""
        subject = _mem_subject(rec)
        if subject is None:
            return
        key = (subject, rec.get("backend"))
        peak = rec.get("peak_bytes")
        if isinstance(peak, (int, float)) and not isinstance(peak, bool) \
                and peak > 0:
            prev = last_mem.get(key)
            if prev is not None:
                pname, pval = prev
                growth = (peak - pval) / pval
                if growth > mem_tol:
                    errors.append(
                        f"{rname}: {subject} "
                        f"[{rec.get('backend') or '?'}] peak memory "
                        f"grew {growth * 100:.0f}% vs {pname} "
                        f"({pval} -> {peak} bytes, mem-tol "
                        f"{mem_tol * 100:.0f}%) — the compiled plan "
                        f"reserves more device memory")
            last_mem[key] = (rname, float(peak))
        mfu = rec.get("mfu")
        if isinstance(mfu, (int, float)) and not isinstance(mfu, bool) \
                and mfu > 0:
            prev = last_mfu.get(key)
            if prev is not None:
                pname, pval = prev
                drop = (pval - mfu) / pval
                if drop > tol:
                    msg = (f"{rname}: {subject} "
                           f"[{rec.get('backend') or '?'}] MFU "
                           f"regressed {drop * 100:.0f}% vs {pname} "
                           f"({pval:.4g} -> {mfu:.4g}, tol "
                           f"{tol * 100:.0f}%)")
                    if is_cpu(rec) and not strict_cpu:
                        warnings.append(msg + " [cpu smoke: warning "
                                        "only]")
                    else:
                        errors.append(msg)
            last_mfu[key] = (rname, float(mfu))

    def track_overlap_fields(rname, rec):
        """Comm-overlap trends for one fresh metric line: the overlap
        fractions (higher is better — the whole point of ROADMAP
        item 2) and the visible-comm time (lower is better).  Both are
        timing-derived, so they follow the accelerator-gates /
        CPU-warns policy like MFU; a zero baseline never trends (no
        overlap yet = nothing to lose)."""
        subject = rec.get("metric")
        if not isinstance(subject, str) or not subject:
            return
        ctx = rec.get("compute_twin_excess_ms")
        if isinstance(ctx, (int, float)) and not isinstance(ctx, bool) \
                and ctx > 0:
            # the attribution flagged its own compute twin as slower
            # than the full step (oversubscribed-host rendezvous
            # staggering): the clamp forces comm_ms=0 /
            # overlap_fraction=1.0 on that record, and seeding the
            # baseline with those perfect-overlap numbers would gate
            # the NEXT healthy round as a phantom regression
            return
        for field, better in (("overlap_fraction", "higher"),
                              ("measured_overlap_fraction", "higher"),
                              ("comm_visible_ms", "lower")):
            val = rec.get(field)
            if (not isinstance(val, (int, float))
                    or isinstance(val, bool) or val < 0):
                continue
            key = (subject, rec.get("backend"), field)
            prev = last_overlap.get(key)
            last_overlap[key] = (rname, float(val))
            if prev is None:
                continue
            pname, pval = prev
            if pval <= 0:
                # a zero baseline means opposite things per direction:
                # a FRACTION at 0 is today's no-overlap world — nothing
                # to lose, never trends.  A lower-is-better TIME at 0
                # is the success state, and comm returning from fully
                # hidden to visibly on the critical path is the WORST
                # regression this column exists for — gate it (0.05 ms
                # absorbs the 4-decimal rounding noise of a true zero).
                if better == "lower" and val > 0.05:
                    msg = (f"{rname}: {subject} "
                           f"[{rec.get('backend') or '?'}] {field} "
                           f"returned from a zero baseline to "
                           f"{val:.4g} vs {pname} — comm is back on "
                           f"the critical path")
                    if is_cpu(rec) and not strict_cpu:
                        warnings.append(msg + " [cpu smoke: warning "
                                        "only]")
                    else:
                        errors.append(msg)
                continue
            if better == "higher":
                change = (pval - val) / pval   # + = less overlap
                verb = "dropped"
            else:
                change = (val - pval) / pval   # + = more visible comm
                verb = "grew"
            if change > tol:
                msg = (f"{rname}: {subject} "
                       f"[{rec.get('backend') or '?'}] {field} {verb} "
                       f"{change * 100:.0f}% vs {pname} "
                       f"({pval:.4g} -> {val:.4g}, tol "
                       f"{tol * 100:.0f}%) — comm is sliding back "
                       f"onto the critical path")
                if is_cpu(rec) and not strict_cpu:
                    warnings.append(msg + " [cpu smoke: warning only]")
                else:
                    errors.append(msg)

    def track_compile_fields(rname, rec):
        """Compile-plane gates for one fresh metric line (schema v10).
        A nonzero steady-state retrace count gates on EVERY backend —
        the ledger counting traces during the timed loop is
        deterministic, so there is no noise excuse; cold_compile_ms
        growth is wall-clock and follows the accelerator-gates /
        CPU-warns policy."""
        subject = rec.get("metric")
        if not isinstance(subject, str) or not subject:
            return
        ssr = rec.get("steady_state_retraces")
        if isinstance(ssr, int) and not isinstance(ssr, bool) and ssr > 0:
            errors.append(
                f"{rname}: {subject} [{rec.get('backend') or '?'}] "
                f"measured {ssr} steady-state retrace(s) — the timed "
                f"loop re-traced a jit entry, so the trended rate "
                f"includes a recompile (the zero-retrace contract "
                f"this line must hold; see /compilez for the culprit "
                f"signature)")
        cc = rec.get("cold_compile_ms")
        if (not isinstance(cc, (int, float)) or isinstance(cc, bool)
                or cc <= 0):
            return
        key = (subject, rec.get("backend"))
        prev = last_compile.get(key)
        last_compile[key] = (rname, float(cc))
        if prev is None:
            return
        pname, pval = prev
        if pval <= 0:
            return
        growth = (cc - pval) / pval
        if growth > tol:
            msg = (f"{rname}: {subject} "
                   f"[{rec.get('backend') or '?'}] cold_compile_ms "
                   f"grew {growth * 100:.0f}% vs {pname} "
                   f"({pval:.4g} -> {cc:.4g} ms, tol "
                   f"{tol * 100:.0f}%) — the compile plane regressed "
                   f"(new shape family, persistent cache stopped "
                   f"hitting, or a slower lowering)")
            if is_cpu(rec) and not strict_cpu:
                warnings.append(msg + " [cpu smoke: warning only]")
            else:
                errors.append(msg)

    def track_tenant_fields(rname, rec):
        """Tenant-plane gates for one fresh metric line (schema v11).
        Per-tenant goodput trends through the ordinary (metric,
        backend) path — the tenant is in the metric name — so this
        adds the two tenant-specific columns: ``slo_attainment``
        (timing-derived, so a drop past ``--tol`` follows the
        accelerator-gates / CPU-warns policy) and the parity check
        (exact token accounting: the two-tenant leg tags every
        request, so a parity off 1.0 is a deterministic split bug —
        gates on every backend, the steady-state-retrace rule)."""
        subject = rec.get("metric")
        if not isinstance(subject, str) or not subject:
            return
        if subject.endswith("_tenant_parity"):
            val = rec.get("value")
            if (isinstance(val, (int, float))
                    and not isinstance(val, bool)
                    and abs(val - 1.0) > 0.01):
                errors.append(
                    f"{rname}: {subject} "
                    f"[{rec.get('backend') or '?'}] tenant parity is "
                    f"{val:.4g}, not 1.0 — the per-tenant split lost "
                    f"or double-counted goodput tokens (every request "
                    f"in the leg is tagged, so the sums must agree "
                    f"exactly)")
            return
        if "tenant" not in rec:
            return
        att = rec.get("slo_attainment")
        if (not isinstance(att, (int, float)) or isinstance(att, bool)
                or not (0.0 <= att <= 1.0)):
            return
        key = (subject, rec.get("backend"))
        prev = last_attain.get(key)
        last_attain[key] = (rname, float(att))
        if prev is None:
            return
        pname, pval = prev
        if pval <= 0:
            return
        drop = (pval - att) / pval
        if drop > tol:
            msg = (f"{rname}: {subject} "
                   f"[{rec.get('backend') or '?'}] slo_attainment "
                   f"dropped {drop * 100:.0f}% vs {pname} "
                   f"({pval:.4g} -> {att:.4g}, tol "
                   f"{tol * 100:.0f}%) — this tenant's deadlines "
                   f"stopped holding")
            if is_cpu(rec) and not strict_cpu:
                warnings.append(msg + " [cpu smoke: warning only]")
            else:
                errors.append(msg)

    def track_qos_fields(rname, rec):
        """QoS-plane gates for one fresh metric line (schema v14).
        Three columns: the preemption-parity check (exact token
        equality of a preempted-then-readmitted request vs an
        undisturbed run — deterministic, gates on every backend, the
        steady-state-retrace rule), the per-class ``slo_attainment``
        trend (timing-derived: accelerator gates, CPU warns, the
        tenant rule), and the aggregate-goodput overhead bound (the
        QoS pass's ``vs_baseline`` vs the untagged pass must stay
        >= 0.95 — timing-derived, same policy)."""
        subject = rec.get("metric")
        if not isinstance(subject, str) or not subject:
            return
        if subject.endswith("_preemption_parity"):
            val = rec.get("value")
            if (isinstance(val, (int, float))
                    and not isinstance(val, bool)
                    and abs(val - 1.0) > 0.01):
                errors.append(
                    f"{rname}: {subject} "
                    f"[{rec.get('backend') or '?'}] preemption parity "
                    f"is {val:.4g}, not 1.0 — a preempted request's "
                    f"replayed tokens diverged from the undisturbed "
                    f"run (eviction perturbed decode state: blocks "
                    f"not recycled cleanly, or the sampling stream "
                    f"is not request-intrinsic); exactness is "
                    f"deterministic, so this gates on every backend")
            return
        if subject.endswith("_qos_aggregate_goodput"):
            vb = rec.get("vs_baseline")
            if (isinstance(vb, (int, float))
                    and not isinstance(vb, bool) and vb < 0.95):
                msg = (f"{rname}: {subject} "
                       f"[{rec.get('backend') or '?'}] QoS aggregate "
                       f"goodput is {vb:.3g}x the untagged baseline "
                       f"(bound 0.95) — the WFQ plane is taxing total "
                       f"throughput beyond its ~5% allowance")
                if is_cpu(rec) and not strict_cpu:
                    warnings.append(msg + " [cpu smoke: warning only]")
                else:
                    errors.append(msg)
            return
        if "qos_class" not in rec:
            return
        att = rec.get("slo_attainment")
        if (not isinstance(att, (int, float)) or isinstance(att, bool)
                or not (0.0 <= att <= 1.0)):
            return
        key = (subject, rec.get("backend"))
        prev = last_class_attain.get(key)
        last_class_attain[key] = (rname, float(att))
        if prev is None:
            return
        pname, pval = prev
        if pval <= 0:
            return
        drop = (pval - att) / pval
        if drop > tol:
            msg = (f"{rname}: {subject} "
                   f"[{rec.get('backend') or '?'}] slo_attainment "
                   f"dropped {drop * 100:.0f}% vs {pname} "
                   f"({pval:.4g} -> {att:.4g}, tol "
                   f"{tol * 100:.0f}%) — this priority class's "
                   f"deadlines stopped holding (did the flood start "
                   f"starving it?)")
            if is_cpu(rec) and not strict_cpu:
                warnings.append(msg + " [cpu smoke: warning only]")
            else:
                errors.append(msg)

    def track_sharding_fields(rname, rec):
        """Replication-ledger gate for one fresh ``kind: sharding``
        record (schema v13).  ``replicated_bytes`` is statically
        derived from the traced jaxpr — deterministic on every
        backend, the peak_bytes rule, not the MFU rule — so growth
        past ``--mem-tol`` gates per (entry_point, backend)
        everywhere; shrinkage is the ZeRO direction and never
        gates."""
        subject = rec.get("entry_point")
        if not isinstance(subject, str) or not subject:
            return
        repl = rec.get("replicated_bytes")
        if (not isinstance(repl, (int, float)) or isinstance(repl, bool)
                or repl < 0):
            return
        key = (subject, rec.get("backend"))
        prev = last_repl.get(key)
        last_repl[key] = (rname, float(repl))
        if prev is None:
            return
        pname, pval = prev
        if pval <= 0:
            # nothing replicated is the fully-sharded success state;
            # duplicate bytes returning from 0 is the regression the
            # ledger exists to catch
            if repl > 0:
                errors.append(
                    f"{rname}: {subject} "
                    f"[{rec.get('backend') or '?'}] replicated_bytes "
                    f"returned from a zero baseline to {repl:,.0f} vs "
                    f"{pname} — something un-sharded (the ledger is "
                    f"static, so this is a real graph change)")
            return
        growth = (repl - pval) / pval
        if growth > mem_tol:
            errors.append(
                f"{rname}: {subject} "
                f"[{rec.get('backend') or '?'}] replicated_bytes grew "
                f"{growth * 100:.0f}% vs {pname} ({pval:,.0f} -> "
                f"{repl:,.0f} bytes, mem-tol {mem_tol * 100:.0f}%) — "
                f"more world bytes are duplicate copies (a ZeRO shard "
                f"re-replicated, or optimizer state stopped "
                f"partitioning); the ledger is deterministic, so this "
                f"gates on every backend")

    def track_kv_fields(rname, rec):
        """KV-plane gates for one fresh metric line (schema v12).
        Two halves: the ``kv_waste_bytes`` trend (lower is better —
        the paged allocator's whole purpose; growth past ``--tol``
        follows the accelerator-gates / CPU-warns policy because the
        sampled waste depends on where in the admit/finish cycle the
        snapshot lands) and the block-pool FIELD contract, which is
        deterministic: a fresh engine_decode line in a round that
        declares schema_version >= 12 without ``admission_mode`` —
        or a paged line without its block fields — gates on every
        backend (archived rounds declaring an older version are
        exempt; they were valid when written)."""
        subject = rec.get("metric")
        if not isinstance(subject, str) or not subject:
            return
        sv = rec.get("schema_version")
        declared_v12 = isinstance(sv, int) and not isinstance(sv, bool) \
            and sv >= 12
        if declared_v12 and "engine_decode" in subject:
            mode = rec.get("admission_mode")
            if mode is None:
                errors.append(
                    f"{rname}: {subject} "
                    f"[{rec.get('backend') or '?'}] declares schema "
                    f"v{sv} but carries no admission_mode — every "
                    f"fresh v12 engine line must say which allocator "
                    f"(fixed_slot | paged) produced it")
            elif mode == "paged":
                missing = [f for f in ("block_size", "blocks_total",
                                       "blocks_free")
                           if not isinstance(rec.get(f), int)
                           or isinstance(rec.get(f), bool)]
                if missing:
                    errors.append(
                        f"{rname}: {subject} "
                        f"[{rec.get('backend') or '?'}] is a paged "
                        f"engine line missing {missing} — v12 paged "
                        f"lines must expose the block pool")
        waste = rec.get("kv_waste_bytes")
        if (not isinstance(waste, (int, float))
                or isinstance(waste, bool) or waste < 0):
            return
        key = (subject, rec.get("backend"))
        prev = last_waste.get(key)
        last_waste[key] = (rname, float(waste))
        if prev is None:
            return
        pname, pval = prev
        if pval <= 0:
            # zero waste is the success state (a well-sized block
            # pool); waste returning from 0 to measurably nonzero is
            # the regression this column exists to catch
            if waste > 0:
                msg = (f"{rname}: {subject} "
                       f"[{rec.get('backend') or '?'}] kv_waste_bytes "
                       f"returned from a zero baseline to "
                       f"{waste:.4g} vs {pname} — the KV pool is "
                       f"fragmenting again (block_size too large, or "
                       f"blocks leaking)")
                if is_cpu(rec) and not strict_cpu:
                    warnings.append(msg + " [cpu smoke: warning only]")
                else:
                    errors.append(msg)
            return
        growth = (waste - pval) / pval
        if growth > tol:
            msg = (f"{rname}: {subject} "
                   f"[{rec.get('backend') or '?'}] kv_waste_bytes "
                   f"grew {growth * 100:.0f}% vs {pname} "
                   f"({pval:.4g} -> {waste:.4g} bytes, tol "
                   f"{tol * 100:.0f}%) — KV fragmentation is trending "
                   f"the wrong way (block_size too large, or blocks "
                   f"leaking)")
            if is_cpu(rec) and not strict_cpu:
                warnings.append(msg + " [cpu smoke: warning only]")
            else:
                errors.append(msg)

    for rname, recs in rounds:
        wedged = any(r.get("metric") == WEDGE_FLAG for r in recs)
        for rec in recs:
            # ``kind: memory`` records are not throughput measurements
            # but carry the peak-bytes trend; stale replays stay out
            if isinstance(rec, dict) and rec.get("kind") == "memory":
                if is_stale(rec):
                    n_stale += 1
                elif "error" not in rec:
                    track_cost_fields(rname, rec)
                continue
            # ``kind: sharding`` records carry the replication-ledger
            # trend (schema v13); stale replays stay out as ever
            if isinstance(rec, dict) and rec.get("kind") == "sharding":
                if is_stale(rec):
                    n_stale += 1
                elif "error" not in rec:
                    track_sharding_fields(rname, rec)
                continue
            # ``kind: numerics`` records (gradient-health dumps from
            # bench --numerics) describe one run's numerics, not a
            # cross-round trend; stale replays partition out as ever.
            # ``kind: run`` records (supervisor verdicts from bench
            # --run, schema v5) likewise describe one run — its
            # anomaly counts are that run's story, not a regression
            # against an earlier round's run.  ``kind: recovery``
            # records (controller snapshots from bench --chaos,
            # schema v6) are the same shape of story: the METRIC
            # lines next to them (chaos_mttr*, chaos_spike*) carry
            # the cross-round trend.  ``kind: profile`` records
            # (device-timeline attributions from bench --profile /
            # /profilez, schema v8) likewise describe one capture —
            # the profile_* metric lines next to them trend.
            # ``kind: fleet`` snapshots (and their v11 per-tenant
            # blocks) describe one run's traffic mix — the tenant
            # metric lines next to them trend.
            if isinstance(rec, dict) and rec.get("kind") in ("numerics",
                                                             "run",
                                                             "recovery",
                                                             "profile",
                                                             "fleet"):
                if is_stale(rec):
                    n_stale += 1
                continue
            if not is_measurement(rec):
                continue
            if is_stale(rec):
                n_stale += 1
                continue              # replays never enter the trend
            fp = _replay_fingerprint(rec, wedged, earlier_lines)
            if fp is not None:
                kind, why = fp
                msg = (f"{rname}: {rec['metric']}={rec['value']} is a "
                       f"replay presented as fresh ({why}) — replays "
                       f"must carry stale: true and never count as "
                       f"progress")
                if kind == "error":
                    errors.append(msg)
                else:
                    warnings.append(msg + " [suspicious, not "
                                    "definitive: warning only]")
                # either way the line never enters the trend — a
                # byte-identical repeat cannot count as progress (it
                # equals the earlier line) and must not reset the
                # fresh baseline if it IS a replay
                continue
            n_fresh += 1
            track_cost_fields(rname, rec)
            track_overlap_fields(rname, rec)
            track_compile_fields(rname, rec)
            track_tenant_fields(rname, rec)
            track_kv_fields(rname, rec)
            track_qos_fields(rname, rec)
            key = (rec["metric"], rec.get("backend"))
            prev = last_fresh.get(key)
            if prev is not None:
                pname, pval, _ = prev
                val = float(rec["value"])
                if pval > 0 and val > 0:
                    # relative-to-previous in BOTH directions, so the
                    # printed percent is the actual worsening and the
                    # effective tolerance doesn't depend on whether
                    # the metric is a time or a rate
                    if direction(rec) == "lower":
                        change = (val - pval) / pval  # + = slower = worse
                    else:
                        change = (pval - val) / pval  # + = less = worse
                    if change > tol:
                        msg = (f"{rname}: {rec['metric']} "
                               f"[{rec.get('backend') or '?'}] "
                               f"regressed {change * 100:.0f}% vs "
                               f"{pname} ({pval} -> {val} "
                               f"{rec.get('unit')}, tol "
                               f"{tol * 100:.0f}%)")
                        if is_cpu(rec) and not strict_cpu:
                            warnings.append(msg + " [cpu smoke: "
                                            "warning only]")
                        else:
                            errors.append(msg)
            last_fresh[key] = (rname, float(rec["value"]),
                               rec.get("unit"))
        # rounds are ordered: everything in THIS round is "earlier"
        # for the next one
        for rec in recs:
            if is_measurement(rec):
                earlier_lines.add(json.dumps(
                    {k: v for k, v in rec.items()
                     if k not in ("stale", "schema_version", "host")},
                    sort_keys=True))
    for w in warnings:
        print(f"trend WARNING: {w}", file=out)
    for e in errors:
        print(f"trend ERROR: {e}", file=out)
    print(f"trend: {len(rounds)} rounds, {n_fresh} fresh measurements "
          f"counted, {n_stale} stale replays partitioned out, "
          f"{len(warnings)} warnings, {len(errors)} errors", file=out)
    return 1 if errors else 0


def main(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=_ROOT,
                    help="directory holding BENCH_r*.json "
                         "(default: repo root)")
    ap.add_argument("--tol", type=float, default=0.25,
                    help="fresh-vs-fresh regression tolerance "
                         "(fraction, default 0.25)")
    ap.add_argument("--strict-cpu", action="store_true",
                    help="gate CPU-smoke regressions too (default: "
                         "warn only — the shared CPU host is noisy)")
    ap.add_argument("--mem-tol", type=float, default=0.25,
                    help="peak-memory growth tolerance (fraction, "
                         "default 0.25; gates on every backend — the "
                         "compiled plan is deterministic)")
    args = ap.parse_args(argv[1:])
    if args.tol < 0:
        ap.error(f"--tol must be >= 0, got {args.tol}")
    if args.mem_tol < 0:
        ap.error(f"--mem-tol must be >= 0, got {args.mem_tol}")
    return check(args.dir, tol=args.tol, strict_cpu=args.strict_cpu,
                 mem_tol=args.mem_tol)


if __name__ == "__main__":
    sys.exit(main(sys.argv))
