#!/usr/bin/env python
"""CI gate: validate bench.py's stdout against the telemetry schema.

Every stdout line bench emits must be a JSON object carrying
``schema_version``, the capture host, and a boolean ``stale`` field
(apex_tpu/observability/exporters.py::validate_bench_record).  Fresh
serving decode lines (metric containing ``engine_decode``) must also
carry the decode-window fields: ``window`` (int >= 1, in-graph decode
ticks per host sync) and a tokens/sec unit — the w1-vs-wK comparison
is meaningless without them — and, at schema v10, the compile-plane
triple (``cold_compile_ms`` / ``compiles_total`` /
``steady_state_retraces``), which fresh ``*_train_throughput`` lines
must carry too: a timed rate is only a steady-state claim if its
compile time was separated out and the timed loop provably re-traced
nothing.
Gradient-allreduce comm microbench lines (``bench.py --comm``) carry
``comm_topology`` and must then state the per-level wire bytes
(``ici_wire_bytes`` / ``dcn_wire_bytes`` / ``wire_bytes``), the
``compress`` flag and the ``ici_size`` / ``dcn_size`` level widths —
the flat-vs-hierarchical comparison is meaningless without them; fresh
``grad_allreduce_*`` metrics must carry the topology fields at all.
Graph-lint records (``kind:
graph_lint`` / ``graph_lint_summary``, from ``python -m
apex_tpu.analysis``, ``bench.py --graph-lint`` or
tests/ci/graph_lint.py) are validated against the lint schema
(``validate_lint_record``), fleet snapshots (``kind: fleet``,
from ``bench.py --fleet N`` / ``Fleet.record()``) against the fleet
schema (``validate_fleet_record``), and cost-model dumps (``kind:
memory``, from ``python -m apex_tpu.analysis --memory`` or the
per-train-config records bench emits) against the memory schema
(``validate_memory_record``, incl. the peak_bytes reassembly
arithmetic), and gradient-health dumps (``kind: numerics``, from
``bench.py --numerics``) against the numerics schema
(``validate_numerics_record``: per-layer health fields, culprit
cross-checks, divergence consistency), and training-run supervisor
verdicts (``kind: run``, from ``bench.py --run`` /
``RunSupervisor.record``) against the run schema
(``validate_run_record``: known anomaly kinds, verdict-vs-counts
consistency), and device-timeline attributions (``kind: profile``,
from ``bench.py --profile`` / ``/profilez``) against the profile
schema (``validate_profile_record``: interval arithmetic — busy
within span, overlap inside both class unions, the measured fraction
equal to its own sides); at schema v3 fresh train-throughput lines
must carry the MFU fields and fresh engine-decode lines
``kv_cache_bytes``, at v4 fresh ``numerics_overhead_*`` lines the
on/off step times, at v5 fresh ``run_supervisor_overhead*`` lines the
same on/off pair, ``kind: fleet`` records may carry the SLO/goodput +
deadline-sweep fields (validated whenever present), at v8 fresh
engine-decode lines the KV fragmentation pair (``kv_waste_bytes`` /
``kv_utilization``), and at v11 the tenant plane: fresh ``kind:
fleet`` records must carry the per-tenant rollup (``tenants`` — the
TENANT_COUNTS tallies per tenant, internally consistent and summing
within the fleet totals — plus ``tenants_dropped``), fresh
``*_tenant_*_goodput`` lines from the two-tenant leg must carry
``tenant`` + ``slo_attainment``, and the ``*_tenant_parity`` line
must carry (and arithmetically match) the token counts its ratio
came from.  At schema v13 the sharding plane joins the stream:
replication-ledger records (``kind: sharding``, from ``python -m
apex_tpu.analysis --sharding`` or ``bench.py --graph-lint``) are
validated against the sharding schema (``validate_sharding_record``:
the mesh must multiply out to the world, the per-dtype duplicate
split must sum, and the ledger identity ``unique + replicated ==
world x argument_bytes`` must reassemble — a ledger that cannot
re-derive its own totals proves nothing about ZeRO).  All
record families may interleave in one stream.  Usage:

    python bench.py | python tests/ci/check_bench_schema.py
    python bench.py --fleet 2 | python tests/ci/check_bench_schema.py
    python bench.py --comm --graph-lint \
        | python tests/ci/check_bench_schema.py
    python bench.py --run | python tests/ci/check_bench_schema.py
    python bench.py --profile | python tests/ci/check_bench_schema.py
    python tests/ci/check_bench_schema.py bench_output.jsonl
    python -m apex_tpu.analysis | python tests/ci/check_bench_schema.py
    python -m apex_tpu.analysis --sharding \
        | python tests/ci/check_bench_schema.py

Exit status 0 = every record valid; 1 = any schema violation (each is
printed).  Stderr chatter must not be piped in — bench keeps stdout
pure JSONL by contract.
"""

import importlib.util
import os
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))


def _load_exporters():
    """Load observability.exporters WITHOUT importing the apex_tpu
    package: the validator is pure stdlib, and a schema gate that pulls
    in jax + the full model zoo would cost ~15s per CI invocation for
    nothing."""
    pkg_dir = os.path.join(_ROOT, "apex_tpu", "observability")
    spec = importlib.util.spec_from_file_location(
        "_obs_schema", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    pkg = importlib.util.module_from_spec(spec)
    sys.modules["_obs_schema"] = pkg
    for sub in ("metrics", "exporters"):
        sspec = importlib.util.spec_from_file_location(
            f"_obs_schema.{sub}", os.path.join(pkg_dir, sub + ".py"))
        mod = importlib.util.module_from_spec(sspec)
        sys.modules[f"_obs_schema.{sub}"] = mod
        sspec.loader.exec_module(mod)
    return sys.modules["_obs_schema.exporters"]


def main(argv):
    validate_telemetry_jsonl = _load_exporters().validate_telemetry_jsonl
    if len(argv) > 1:
        with open(argv[1]) as f:
            lines = f.readlines()
    else:
        lines = sys.stdin.readlines()
    errs = validate_telemetry_jsonl(lines)
    for e in errs:
        print(f"check_bench_schema: {e}", file=sys.stderr)
    if errs:
        return 1
    n = sum(1 for ln in lines if ln.strip())
    print(f"check_bench_schema: {n} records OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
