#!/usr/bin/env python
"""CI gate: run the serving + fleet suites TWICE against ONE
persistent compile-cache dir — and MEASURE that run 2 reloaded.

Why twice: the PR 2 donation gotcha.  On jax 0.4.37's XLA:CPU,
donating the wrong argnum class (the per-slot length vectors,
``serving.DONATION_BLOCKLIST``) produces executables that work when
freshly compiled but decode garbage when RELOADED from the persistent
compilation cache — so a single green run proves nothing about the
next warm one.  Run 1 populates a dedicated cache dir; run 2 executes
the very same jitted mutators from AOT-reloaded executables.  Both
must pass.  The static donation rule (apex_tpu/analysis) pins the
blocklist structurally; this gate pins the runtime behavior.

The compilation ledger turns "both runs green" from an
absence-of-garbage check into a POSITIVE measurement: each run dumps
its ledger at session end (conftest's
``APEX_TPU_COMPILATION_LEDGER_DUMP`` hook), and this gate asserts run
2's serving entries (``engine.*`` / ``seq2seq.*``) compiled with
**zero persistent-cache misses and at least one hit** — i.e. the warm
run really executed AOT-reloaded executables rather than silently
recompiling everything fresh (which would also "pass" while proving
nothing about the reload path).  ``APEX_TPU_COMPILE_CACHE_MIN_S=0``
makes every compile cacheable so sub-threshold toy compiles cannot
spoil the measurement.  When NEITHER run saw a single cache event
(jax.monitoring's cache events unavailable on the backend/version —
the condition the pytest suite skips on), the measurement is reported
as unavailable and only the behavioral both-runs-green gate applies.

Usage:

    python tests/ci/double_run.py             # temp cache dir
    python tests/ci/double_run.py /some/dir   # persistent across CI runs
    python tests/ci/double_run.py --keep      # leave the temp dir behind

Extra pytest args go after ``--``:

    python tests/ci/double_run.py -- -x -q

Exit status 0 = both runs green AND run 2 ledger-measured cache-HIT;
nonzero otherwise.
"""

import json
import os
import shutil
import subprocess
import sys
import tempfile

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))

# the suites exercising every donated cache mutator: the engines
# directly, and the fleet driving many engine instances (each with its
# own jit closures -> its own cache entries)
SUITES = ["tests/test_serving.py", "tests/test_fleet.py"]

# ledger entries owned by the serving engines (the donated mutators
# this gate exists for) — fleet/bench helpers and model-level jits
# outside the engines are not part of the reload contract
SERVING_ENTRY_PREFIXES = ("engine.", "seq2seq.")

# the paged engine's jitted executables get their own named assertion:
# they are the newest donated mutators (block-pool KV, schema v12) and
# the exact class the PR 2 reload regression bites — run 2 must reload
# them from the persistent cache, not merely "some serving entry"
PAGED_ENTRIES = ("engine._paged_step_k", "engine._paged_admit")


def _serving_cache_counts(dump_path):
    """(hits, misses, uncached, entries, per_entry) summed over the
    serving entries of one run's ledger dump; None when the dump is
    missing or unreadable (reported by the caller)."""
    try:
        with open(dump_path) as f:
            snap = json.load(f)
    except (OSError, ValueError) as e:
        print(f"double_run: cannot read ledger dump {dump_path}: {e}",
              file=sys.stderr)
        return None
    hits = misses = uncached = 0
    names = []
    per = {}
    for name, st in snap.get("entries", {}).items():
        if not name.startswith(SERVING_ENTRY_PREFIXES):
            continue
        cache = st.get("cache", {})
        h = int(cache.get("hit", 0))
        m = int(cache.get("miss", 0))
        u = int(cache.get("uncached", 0))
        hits += h
        misses += m
        uncached += u
        names.append(name)
        per[name] = (h, m, u)
    return hits, misses, uncached, sorted(names), per


def check_cache_hits(run1_dump, run2_dump):
    """The positive reload measurement: run 2's serving compiles must
    be persistent-cache HITS — zero misses, at least one hit.  Returns
    a list of problems (empty = measured clean)."""
    errs = []
    c1 = _serving_cache_counts(run1_dump)
    c2 = _serving_cache_counts(run2_dump)
    if c1 is None or c2 is None:
        return ["ledger dump missing — conftest's "
                "APEX_TPU_COMPILATION_LEDGER_DUMP hook did not fire"]
    h1, m1, u1, names1, _ = c1
    h2, m2, u2, names2, per2 = c2
    if not names2:
        return ["run 2 ledger recorded no serving entries — the "
                "engines' jits are no longer instrumented?"]
    if h1 == m1 == 0 and h2 == m2 == 0 and (u1 or u2):
        # NEITHER run saw a single cache event: jax.monitoring's
        # /jax/compilation_cache/* events are not firing on this
        # backend/version (the same condition the pytest suite
        # skips on).  That is "measurement unavailable", not "cache
        # missed" — both runs still passed, which is the original
        # absence-of-garbage gate; warn instead of going
        # permanently red on an environment drift.
        print("double_run: WARNING — no persistent-cache "
              "attribution in either run (jax.monitoring cache "
              "events unavailable?); the run-2 cache-HIT "
              "measurement was skipped, the behavioral double-run "
              "gate still passed", file=sys.stderr)
        return []
    if u2:
        errs.append(f"run 2 had {u2} serving compile(s) with no "
                    f"cache attribution — is the persistent cache "
                    f"disabled? (run 1: hits={h1} misses={m1} "
                    f"uncached={u1})")
    if m2 > 0:
        errs.append(f"run 2 had {m2} serving cache MISS(es) — the "
                    f"warm run recompiled instead of reloading "
                    f"(entries: {names2}); the AOT-reload gate "
                    f"measured nothing for those executables")
    if m2 == 0 and h2 == 0:
        errs.append("run 2 recorded serving compiles but zero cache "
                    "hits and zero misses — attribution is broken")
    # the paged executables by name: each must be present in run 2 and
    # reload as pure hits (>=1 hit, 0 misses) — the aggregate check
    # above could be satisfied by the fixed-slot engine alone
    for pname in PAGED_ENTRIES:
        if pname not in per2:
            errs.append(f"run 2 ledger has no entry for {pname} — "
                        f"the paged engine's jit is no longer "
                        f"instrumented or the suites stopped "
                        f"exercising it")
            continue
        ph, pm, pu = per2[pname]
        if pm > 0 or (ph == 0 and pu > 0):
            errs.append(f"run 2: {pname} compiled with hits={ph} "
                        f"misses={pm} uncached={pu} — the paged "
                        f"executable did not reload from the "
                        f"persistent cache")
    if not errs:
        print(f"double_run: run 2 serving suite ledger-measured "
              f"cache-HIT ({h2} hits, 0 misses over "
              f"{len(names2)} entries; run 1 populated with "
              f"{m1} misses)")
    return errs


def main(argv):
    args = argv[1:]
    extra = []
    if "--" in args:
        split = args.index("--")
        args, extra = args[:split], args[split + 1:]
    keep = "--keep" in args
    args = [a for a in args if a != "--keep"]
    if args:
        cache_dir, made_tmp = os.path.abspath(args[0]), False
        os.makedirs(cache_dir, exist_ok=True)
    else:
        cache_dir = tempfile.mkdtemp(prefix="apex_tpu_double_run_")
        made_tmp = True

    env = dict(os.environ)
    env["APEX_TPU_COMPILE_CACHE_DIR"] = cache_dir
    env.pop("APEX_TPU_NO_COMPILE_CACHE", None)
    # every compile cacheable: the run-2 HIT assertion must not be
    # spoiled by toy compiles under the default 0.5s write threshold
    env["APEX_TPU_COMPILE_CACHE_MIN_S"] = "0"
    dumps = {run: os.path.join(cache_dir, f"ledger_run{run}.json")
             for run in (1, 2)}

    status = 0
    try:
        for run in (1, 2):
            label = ("cold (populates the cache)" if run == 1
                     else "warm (AOT-reloaded executables)")
            print(f"double_run: run {run}/2 — {label}; cache dir "
                  f"{cache_dir}", flush=True)
            env["APEX_TPU_COMPILATION_LEDGER_DUMP"] = dumps[run]
            proc = subprocess.run(
                [sys.executable, "-m", "pytest", *SUITES, "-q",
                 *(extra or ["-x"])],
                cwd=_ROOT, env=env)
            if proc.returncode != 0:
                print(f"double_run: run {run}/2 FAILED "
                      f"(exit {proc.returncode})"
                      + ("" if run == 1 else
                         " — executables reloaded from the persistent "
                         "compile cache misbehaved; suspect a donation "
                         "change (see serving.DONATION_BLOCKLIST)"),
                      file=sys.stderr)
                status = proc.returncode
                break
        else:
            errs = check_cache_hits(dumps[1], dumps[2])
            for e in errs:
                print(f"double_run: {e}", file=sys.stderr)
            if errs:
                status = 1
            else:
                print("double_run: both runs green — donated "
                      "executables survive the AOT cache round trip, "
                      "and run 2 measurably RELOADED them")
    finally:
        if made_tmp and not keep:
            shutil.rmtree(cache_dir, ignore_errors=True)
    return status


if __name__ == "__main__":
    sys.exit(main(sys.argv))
