#!/usr/bin/env python
"""CI gate: run the serving + fleet suites TWICE against ONE
persistent compile-cache dir.

Why twice: the PR 2 donation gotcha.  On jax 0.4.37's XLA:CPU,
donating the wrong argnum class (the per-slot length vectors,
``serving.DONATION_BLOCKLIST``) produces executables that work when
freshly compiled but decode garbage when RELOADED from the persistent
compilation cache — so a single green run proves nothing about the
next warm one.  Run 1 populates a dedicated cache dir; run 2 executes
the very same jitted mutators from AOT-reloaded executables.  Both
must pass.  The static donation rule (apex_tpu/analysis) pins the
blocklist structurally; this gate pins the runtime behavior.

Usage:

    python tests/ci/double_run.py             # temp cache dir
    python tests/ci/double_run.py /some/dir   # persistent across CI runs
    python tests/ci/double_run.py --keep      # leave the temp dir behind

Extra pytest args go after ``--``:

    python tests/ci/double_run.py -- -x -q

Exit status 0 = both runs green; the failing run's status otherwise.
"""

import os
import shutil
import subprocess
import sys
import tempfile

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))

# the suites exercising every donated cache mutator: the engines
# directly, and the fleet driving many engine instances (each with its
# own jit closures -> its own cache entries)
SUITES = ["tests/test_serving.py", "tests/test_fleet.py"]


def main(argv):
    args = argv[1:]
    extra = []
    if "--" in args:
        split = args.index("--")
        args, extra = args[:split], args[split + 1:]
    keep = "--keep" in args
    args = [a for a in args if a != "--keep"]
    if args:
        cache_dir, made_tmp = os.path.abspath(args[0]), False
        os.makedirs(cache_dir, exist_ok=True)
    else:
        cache_dir = tempfile.mkdtemp(prefix="apex_tpu_double_run_")
        made_tmp = True

    env = dict(os.environ)
    env["APEX_TPU_COMPILE_CACHE_DIR"] = cache_dir
    env.pop("APEX_TPU_NO_COMPILE_CACHE", None)

    status = 0
    try:
        for run in (1, 2):
            label = ("cold (populates the cache)" if run == 1
                     else "warm (AOT-reloaded executables)")
            print(f"double_run: run {run}/2 — {label}; cache dir "
                  f"{cache_dir}", flush=True)
            proc = subprocess.run(
                [sys.executable, "-m", "pytest", *SUITES, "-q",
                 *(extra or ["-x"])],
                cwd=_ROOT, env=env)
            if proc.returncode != 0:
                print(f"double_run: run {run}/2 FAILED "
                      f"(exit {proc.returncode})"
                      + ("" if run == 1 else
                         " — executables reloaded from the persistent "
                         "compile cache misbehaved; suspect a donation "
                         "change (see serving.DONATION_BLOCKLIST)"),
                      file=sys.stderr)
                status = proc.returncode
                break
        else:
            print("double_run: both runs green — donated executables "
                  "survive the AOT cache round trip")
    finally:
        if made_tmp and not keep:
            shutil.rmtree(cache_dir, ignore_errors=True)
    return status


if __name__ == "__main__":
    sys.exit(main(sys.argv))
