#!/usr/bin/env python
"""CI gate: the self-healing controllers under a seeded fault schedule.

Runs a short deterministic chaos scenario — replica DEATH, silent
STALL, and a traffic SPIKE — through jax-light stub replicas (no model,
no tracing, no device work: the fleet/controller layer is pure host
orchestration) plus a stub elastic-training run, and asserts the
telemetry→action loop CONVERGES:

1. the serving SLO controller's actuation is bounded per overload
   episode (``max_actions_in_episode <= config.max_actions_per_episode``
   — the no-oscillation contract) and the controlled run's deadline
   attainment beats the no-controller baseline on the identical seeded
   workload with goodput no worse;
2. the fleet survives the death and the stall (every surviving request
   resolves; MTTR — failover to first post-recovery progress of
   reclaimed work — is measured and non-negative, in the injected
   tick clock's units);
3. the training controller survives a mid-step replica death AND a
   torn snapshot: it shrinks the world, skips the torn write, resumes
   from the previous durable snapshot, and finishes the run;
4. a PLANNED preemption (the ``TrainingFaults.preemption`` window
   firing into a ``PreemptionGuard``, the programmatic twin of the
   real SIGTERM) is honored at the next step boundary: coordinated
   emergency snapshot (numpy tree + DataLoader cursor under one
   checksum), clean ``preempted`` verdict, and a fresh trainer +
   fresh loader resume to a loss trajectory and consumed-sample-index
   sequence IDENTICAL to an undisturbed run (exactly-once accounting
   across the preemption);
5. every ``kind: recovery`` record the controllers emit — and every
   ``kind: fleet`` record with the new ``mttr`` aggregate — validates
   against the schema (``exporters.validate_telemetry_record``).

Exit 0 = converged and schema-clean; 1 = any violation (each printed).
Wired into tier-1 by tests/test_autoscale.py (subprocess), like the
server_smoke and check_bench_trend gates.
"""

import os
import sys
import tempfile

_ROOT = os.path.abspath(os.path.join(os.path.dirname(
    os.path.abspath(__file__)), os.pardir, os.pardir))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from apex_tpu.data import DataLoader  # noqa: E402
from apex_tpu.fleet import (AutoscaleConfig, ElasticConfig,  # noqa: E402
                            ElasticTrainer, FaultyReplica, Fleet,
                            FleetOverloaded, PreemptionGuard,
                            RetryPolicy, SloController, TrainingFaults)
from apex_tpu.observability.exporters import (  # noqa: E402
    JsonlExporter, validate_telemetry_record)

VIOLATIONS = []


def check(ok, msg):
    status = "ok" if ok else "VIOLATION"
    print(f"chaos_smoke: [{status}] {msg}")
    if not ok:
        VIOLATIONS.append(msg)


def check_record(rec, label):
    errs = validate_telemetry_record(JsonlExporter.enrich(rec))
    check(not errs, f"{label} record schema-clean"
          + (f": {errs}" if errs else ""))


class StubReplica:
    """Deterministic scheduler-surface replica (test_fleet discipline):
    request k's j-th token is ``100*len(prompt)+j``; one token per live
    request per step.  ``set_window`` exists so the controller's
    decode-window actuator has a real target."""

    def __init__(self, slots=2, window=4):
        self.slots = slots
        self.window = window
        self.base_window = window
        self._free = list(range(slots))
        self._live = {}
        self._waiting = []
        self._finished = {}
        self._next_rid = 0

    def set_window(self, k):
        self.window = int(k)

    def _admit(self, rid, prompt, max_new):
        self._free.pop()
        self._live[rid] = [list(prompt), max_new, []]

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    seed=None, temperature=None):
        if not self._free:
            raise RuntimeError("no free slot")
        rid = self._next_rid
        self._next_rid += 1
        self._admit(rid, prompt, max_new_tokens)
        return rid

    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               seed=None, temperature=None):
        if self._free and not self._waiting:
            return self.add_request(prompt, max_new_tokens)
        rid = self._next_rid
        self._next_rid += 1
        self._waiting.append((rid, list(prompt), max_new_tokens))
        return rid

    def step(self):
        out = {}
        for rid, rec in list(self._live.items()):
            prompt, max_new, got = rec
            tok = 100 * len(prompt) + len(got)
            got.append(tok)
            out[rid] = [tok]
            if len(got) >= max_new:
                del self._live[rid]
                self._free.append(0)
                self._finished[rid] = got
        while self._free and self._waiting:
            rid, prompt, max_new = self._waiting.pop(0)
            self._admit(rid, prompt, max_new)
        return out

    def live(self):
        return len(self._live)

    def free_slots(self):
        return len(self._free)

    def queue_depth(self):
        return len(self._waiting)

    def is_finished(self, rid):
        return rid in self._finished

    def result(self, rid):
        return list(self._finished[rid])

    def cancel(self, rid):
        for i, item in enumerate(self._waiting):
            if item[0] == rid:
                del self._waiting[i]
                return True
        if rid in self._live:
            del self._live[rid]
            self._free.append(0)
            return True
        return False

    def take_waiting(self):
        taken, self._waiting = self._waiting, []
        return taken

    def stats(self):
        return {"occupancy": len(self._live) / self.slots,
                "queue_depth": len(self._waiting)}


class Tick:
    t = 0.0


def clock():
    return Tick.t


# ---------------------------------------------------------------------------
# serving: seeded spike + death + stall, baseline vs controller
# ---------------------------------------------------------------------------

MAX_NEW = 8
DEADLINE = 16.0
# seeded schedule: steady trickle + one 24-request spike at tick 10;
# the death (replica 0 raises from tick 20) and the stall (replica 1
# goes silent on live work, ticks 44-56) land mid-run
WAVES = {t: 1 for t in range(0, 70, 6)}
WAVES[10] = WAVES.get(10, 0) + 24


def drive(fl, controller=None, ticks=90):
    rng = np.random.RandomState(0)
    rids, shed = [], 0
    for tick in range(ticks):
        for _ in range(WAVES.get(tick, 0)):
            try:
                rids.append(fl.submit(
                    list(rng.randint(0, 100, 4)),
                    max_new_tokens=MAX_NEW, deadline=DEADLINE))
            except FleetOverloaded:
                shed += 1
        fl.step()
        Tick.t += 1.0
        if controller is not None and tick % 2 == 1:
            controller.tick()
    guard = 0
    while fl.live() and guard < 500:
        fl.step()
        Tick.t += 1.0
        if controller is not None:
            controller.tick()
        guard += 1
    check(not fl.live(), "fleet drained to completion")
    return rids, shed


def build_fleet(with_faults):
    reps = [StubReplica(slots=2), StubReplica(slots=2)]
    if with_faults:
        reps[0] = FaultyReplica(reps[0], raise_on_step=(20, 24))
        reps[1] = FaultyReplica(reps[1], stall=(44, 56))
    return Fleet(reps, policy="least_loaded", max_queue=64,
                 retry=RetryPolicy(max_attempts=8), step_workers=1,
                 clock=clock)


def serving_scenario():
    cfg = AutoscaleConfig(target_attainment=0.9, min_queue=4,
                          backlog_factor=2.0, cooldown_ticks=1,
                          relax_after_ticks=6,
                          max_actions_per_episode=6)

    Tick.t = 0.0
    base = build_fleet(with_faults=True)
    drive(base)
    rec_b = base.record()
    check_record(rec_b, "baseline fleet")

    Tick.t = 0.0
    fl = build_fleet(with_faults=True)
    ctrl = SloController(fl, cfg, clock=clock)
    drive(fl, controller=ctrl)
    rec_c = fl.record()
    check_record(rec_c, "controlled fleet")
    rec_ctrl = ctrl.record()
    check_record(rec_ctrl, "serving controller recovery")

    # convergence: bounded actuation per episode, episode closed
    check(rec_ctrl["max_actions_in_episode"]
          <= cfg.max_actions_per_episode,
          f"actuation bounded per episode "
          f"({rec_ctrl['max_actions_in_episode']} <= "
          f"{cfg.max_actions_per_episode})")
    check(not rec_ctrl["in_flight"],
          "controller episode closed by end of run")
    check(rec_ctrl["episodes"] >= 1,
          f"controller saw the overload "
          f"({rec_ctrl['episodes']} episode(s))")

    # the death + stall were survived on both sides; MTTR measured
    for label, rec in (("baseline", rec_b), ("controlled", rec_c)):
        check(rec["failovers"] >= 1,
              f"{label}: failover happened "
              f"({rec['failovers']} reclaims)")
        m = rec["mttr"]
        check(m["count"] >= 1 and m["last"] is not None
              and m["last"] >= 0,
              f"{label}: MTTR measured ({m})")

    # the SLO verdict: attainment up, goodput no worse (identical
    # seeded workload, deterministic stub service times)
    att_b, att_c = rec_b["slo_attainment"], rec_c["slo_attainment"]
    check(att_b is not None and att_c is not None
          and att_c > att_b,
          f"controller holds attainment above baseline "
          f"({att_b if att_b is None else round(att_b, 3)} -> "
          f"{att_c if att_c is None else round(att_c, 3)})")
    gp_b, gp_c = (rec_b["goodput_tokens_per_s"],
                  rec_c["goodput_tokens_per_s"])
    check(gp_c >= 0.95 * gp_b,
          f"goodput no worse under control "
          f"({round(gp_b, 3)} -> {round(gp_c, 3)} tokens/tick)")


# ---------------------------------------------------------------------------
# training: stub elastic run — death + torn snapshot, world shrink
# ---------------------------------------------------------------------------

def training_scenario():
    with tempfile.TemporaryDirectory() as d:
        # a "training run" whose step is plain numpy (the controller
        # never looks inside the step; jax enters only through the
        # npz checkpointer, which traces nothing)
        def build_step(world):
            def step(state, batch):
                w = state["w"] - 0.1 * (state["w"] - batch)
                loss = float(np.sum((w - batch) ** 2)) + 1.0 / world
                return {"w": w, "steps": state["steps"] + 1}, loss
            return step

        faults = TrainingFaults(replica_death=(5, 6),
                                torn_checkpoint=(4, 5), seed=0)
        trainer = ElasticTrainer(
            build_step,
            {"w": np.zeros(4, np.float32), "steps": np.int32(0)},
            world=4, ckpt_dir=d, faults=faults,
            config=ElasticConfig(checkpoint_every=2, min_world=1,
                                 max_recoveries=3),
            run="chaos_smoke")
        rng = np.random.RandomState(1)
        batches = [rng.randn(4).astype(np.float32)
                   for _ in range(12)]
        history = trainer.run(10, lambda i: batches[i])
        check(trainer.world == 2,
              f"world shrank 4 -> {trainer.world} on replica death")
        check(trainer.recoveries == 1,
              f"exactly one recovery ({trainer.recoveries})")
        # the snapshot at step 4 was torn (observed-step window 4 is
        # the save after committed step 4): resume fell back to the
        # previous durable snapshot at step 2
        check(trainer.resumed_step == 2,
              f"torn snapshot skipped, resumed at step "
              f"{trainer.resumed_step} (durable), not 4 (torn)")
        check(len(faults.torn_paths) == 1,
              f"the torn-write fault fired ({faults.torn_paths})")
        steps_seen = [row[0] for row in trainer.history]
        check(trainer.history[-1][0] == 9 and len(history) >= 10,
              f"run completed through step 9 (saw {steps_seen})")
        rec = trainer.record()
        check_record(rec, "training controller recovery")
        m = rec["mttr_s"]
        check(m["count"] == 1 and m["last"] is not None
              and m["last"] >= 0,
              f"training MTTR measured ({m})")


# ---------------------------------------------------------------------------
# training: planned preemption — emergency snapshot, deterministic resume
# ---------------------------------------------------------------------------

def preemption_scenario():
    rng = np.random.RandomState(7)
    images = rng.randint(0, 256, (64, 4, 4, 3), np.uint8)
    labels = np.arange(64, dtype=np.int32)
    total_steps = 12

    def make_loader():
        # the portable (checkpointable) stream — jax-light like the
        # rest of this gate; only the npz checkpointer touches jax
        return DataLoader(images, labels, batch_size=8, shuffle=True,
                          seed=11, native=False)

    def build_step(world):
        def step(state, batch):
            imgs, lbls = batch
            g = imgs.mean(axis=(0, 2, 3)).astype(np.float32)
            w = state["w"] - 0.1 * (state["w"] - g)
            loss = float(np.mean((w - g) ** 2)) + 1.0 / world
            return {"w": w}, loss
        return step

    def run_one(d, loader, log, *, guard=None, faults=None,
                resume=False, name="preempt"):
        def data_fn(i):
            imgs, lbls, _ = loader.next_batch()
            log.append([int(v) for v in lbls])
            return imgs, lbls
        tr = ElasticTrainer(
            build_step, {"w": np.zeros(3, np.float32)}, world=4,
            ckpt_dir=d, data=loader, guard=guard, faults=faults,
            resume=resume,
            # keep the numpy step in numpy after a restore (the
            # checkpointer hands back jnp leaves)
            from_host=lambda tree, w: {
                k: np.asarray(v) for k, v in tree.items()},
            config=ElasticConfig(checkpoint_every=4, min_world=1),
            run=name)
        tr.run(total_steps, data_fn)
        return tr

    with tempfile.TemporaryDirectory() as d_und, \
            tempfile.TemporaryDirectory() as d_pre:
        und_log, pre_log = [], []
        und = run_one(d_und, make_loader(), und_log, name="und")
        guard = PreemptionGuard(grace_s=60.0)
        faults = TrainingFaults(preemption=(6, 7), seed=0)
        pre = run_one(d_pre, make_loader(), pre_log, guard=guard,
                      faults=faults, name="preempted")
        check(pre.verdict == "preempted",
              f"preemption honored at the step boundary "
              f"(verdict {pre.verdict!r})")
        check(len(pre.history) == 7,
              f"step 6 still committed before the exit "
              f"({[r[0] for r in pre.history]})")
        rec = pre.record()
        check_record(rec, "preempted trainer recovery")
        check(rec.get("cause") == "preemption"
              and rec.get("preempted") is True,
              f"record names the cause (cause={rec.get('cause')!r})")
        check(rec.get("data_state", {}).get(
            "samples_consumed") == 7 * 8,
            f"record carries the data census "
            f"({rec.get('data_state')})")

        res = run_one(d_pre, make_loader(), pre_log, resume=True,
                      name="resumed")
        check(res.resumed_step == 7,
              f"resumed from the emergency snapshot "
              f"(step {res.resumed_step})")
        res_losses = [l for _, l, _ in pre.history + res.history]
        und_losses = [l for _, l, _ in und.history]
        check(res_losses == und_losses,
              "preempt-resume loss trajectory identical to the "
              "undisturbed run")
        check(pre_log == und_log,
              "consumed-sample-index sequence identical (exactly-once "
              "across the preemption)")
        check(res.resume_overhead_s is not None
              and res.resume_overhead_s >= 0,
              f"resume overhead accounted "
              f"({res.resume_overhead_s})")
        check_record(res.record(), "resumed trainer recovery")


def main():
    serving_scenario()
    training_scenario()
    preemption_scenario()
    if VIOLATIONS:
        print(f"chaos_smoke: {len(VIOLATIONS)} violation(s)")
        return 1
    print("chaos_smoke: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
