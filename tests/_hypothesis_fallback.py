"""Minimal stand-in for the slice of hypothesis tests/test_properties.py
uses, for environments without the real package (this container bakes
its deps and tier-1 must still COLLECT AND RUN the property suite, not
skip it).

Faithful where it matters, deliberately small everywhere else:

- ``given(**kwargs)`` draws ``max_examples`` pseudo-random examples per
  test from a fixed seed (deterministic across runs — a property
  failure reproduces) and reports the failing example like hypothesis
  does;
- strategies implement only ``integers``, ``booleans``, ``lists``,
  ``sampled_from`` — the combinators the suite needs;
- no shrinking, no database, no deadline machinery (``settings`` only
  honors ``max_examples``).

If the real hypothesis is installed it wins (see the import guard in
test_properties.py); this module never shadows it.
"""

import numpy as np

__all__ = ["given", "settings", "strategies"]

_DEFAULT_MAX_EXAMPLES = 20
_SEED = 0xA9E7  # fixed: failures must reproduce run-to-run


class _Strategy:
    def __init__(self, draw):
        self._draw = draw

    def example(self, rng):
        return self._draw(rng)


class strategies:
    """Namespace mirroring ``hypothesis.strategies`` (imported
    ``as st``)."""

    @staticmethod
    def integers(min_value, max_value):
        return _Strategy(
            lambda rng: int(rng.randint(min_value, max_value + 1)))

    @staticmethod
    def booleans():
        return _Strategy(lambda rng: bool(rng.randint(0, 2)))

    @staticmethod
    def sampled_from(options):
        options = list(options)
        return _Strategy(lambda rng: options[rng.randint(len(options))])

    @staticmethod
    def lists(elements, min_size=0, max_size=10):
        def draw(rng):
            n = int(rng.randint(min_size, max_size + 1))
            return [elements.example(rng) for _ in range(n)]
        return _Strategy(draw)


def settings(max_examples=_DEFAULT_MAX_EXAMPLES, deadline=None, **_):
    """Decorator: stash the example budget on the (given-wrapped)
    test."""
    def deco(fn):
        fn._fallback_max_examples = max_examples
        return fn
    return deco


def given(**strats):
    def deco(fn):
        # NOT functools.wraps: that sets __wrapped__, which makes
        # pytest resolve the ORIGINAL signature and demand fixtures
        # named like the strategy kwargs — the wrapper must present a
        # zero-arg test
        def wrapper():
            n = getattr(wrapper, "_fallback_max_examples",
                        _DEFAULT_MAX_EXAMPLES)
            rng = np.random.RandomState(_SEED)
            for i in range(n):
                drawn = {k: s.example(rng) for k, s in strats.items()}
                try:
                    fn(**drawn)
                except Exception as e:
                    raise AssertionError(
                        f"falsifying example ({i + 1}/{n}): "
                        f"{drawn!r}") from e
        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__module__ = fn.__module__
        return wrapper
    return deco
