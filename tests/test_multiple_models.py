"""Multiple models x optimizers x losses with fault injection — the
apex_tpu analogue of the reference's flagship 762-line
tests/L0/run_amp/test_multiple_models_optimizers_losses.py: the cross
product of {opt levels} x {planted inf at iter 0/1} x {loss_id}, asserting
(a) half-precision runs track an fp32 reference trajectory, (b) an
overflowed loss skips exactly that optimizer's step and halves exactly
that scaler, and (c) per-loss scalers evolve independently."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp, nn, optimizers
from apex_tpu.nn import functional as F


def _models():
    return [nn.Sequential([nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 3)]),
            nn.Sequential([nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 3)])]


X = np.random.RandomState(0).randn(16, 6).astype(np.float32)
Y = np.random.RandomState(1).randn(16, 3).astype(np.float32)


def _run(opt_level, iters=6, inf_iter=None, half_dtype=None, target=0):
    """Train two models with two optimizers; optionally plant an inf into
    model[target]'s loss at iteration ``inf_iter``.  Returns (params list,
    scale list, trajectories)."""
    models, opts = amp.initialize(
        _models(), [optimizers.FusedAdam(lr=1e-2) for _ in range(2)],
        opt_level=opt_level, half_dtype=half_dtype, verbosity=0,
        hard_override=True)
    x, y = jnp.asarray(X), jnp.asarray(Y)
    ps = [m.init(jax.random.PRNGKey(i))[0] for i, m in enumerate(models)]
    oss = [o.init(p) for o, p in zip(opts, ps)]
    trajs = [[], []]
    for it in range(iters):
        for k in range(2):
            bad = (inf_iter is not None and it == inf_iter and k == target)

            def loss_fn(p, k=k, bad=bad):
                out, _ = models[k].apply(p, x)
                loss = F.mse_loss(out.astype(jnp.float32), y)
                return loss * jnp.float32(np.inf) if bad else loss

            loss, grads = amp.scaled_grad(loss_fn, ps[k], oss[k])
            ps[k], oss[k], info = opts[k].step(ps[k], oss[k], grads)
            trajs[k].append(float(loss) if np.isfinite(float(loss))
                            else None)
    scales = [float(o.scalers[0].loss_scale) for o in oss]
    return ps, scales, trajs


# tier-1 budget (PR 2): slowest tests by --durations carry the slow
# marker so a cold `-m 'not slow'` run fits the 870 s timeout
@pytest.mark.slow
@pytest.mark.parametrize("opt_level", ["O1", "O2", "O3"])
def test_half_tracks_fp32_reference(opt_level):
    ref_ps, _, ref_traj = _run("O0")
    tst_ps, _, tst_traj = _run(opt_level)
    # loss trajectories agree to half-precision tolerance
    for rt, tt in zip(ref_traj, tst_traj):
        np.testing.assert_allclose(rt, tt, rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("inf_iter", [0, 1])
@pytest.mark.parametrize("target", [0, 1])
def test_inf_skips_only_target_optimizer(inf_iter, target):
    """Planted inf must (a) halve only the target's scaler, (b) leave the
    target's params equal to a run where that iteration never happened,
    (c) not disturb the other model at all."""
    init_scale = 2.0 ** 8
    loss_scale = "dynamic"

    def run(n_iters, inf_at):
        models, opts = amp.initialize(
            _models(), [optimizers.FusedAdam(lr=1e-2) for _ in range(2)],
            opt_level="O2", half_dtype="float16", loss_scale=loss_scale,
            verbosity=0, hard_override=True)
        x, y = jnp.asarray(X), jnp.asarray(Y)
        ps = [m.init(jax.random.PRNGKey(i))[0]
              for i, m in enumerate(models)]
        oss = [o.init(p) for o, p in zip(opts, ps)]
        for it in range(n_iters):
            for k in range(2):
                bad = (it == inf_at and k == target)

                def loss_fn(p, k=k, bad=bad):
                    out, _ = models[k].apply(p, x)
                    loss = F.mse_loss(out.astype(jnp.float32), y)
                    return loss * jnp.float32(np.inf) if bad else loss

                _, grads = amp.scaled_grad(loss_fn, ps[k], oss[k])
                ps[k], oss[k], _ = opts[k].step(ps[k], oss[k], grads)
        return ps, oss

    ps_inf, oss_inf = run(3, inf_iter)
    ps_ref, oss_ref = run(3, None)

    # target scaler halved exactly once, the other untouched
    s_t = float(oss_inf[target].scalers[0].loss_scale)
    s_o = float(oss_inf[1 - target].scalers[0].loss_scale)
    s_ref = float(oss_ref[0].scalers[0].loss_scale)
    assert s_t == s_ref / 2
    assert s_o == s_ref

    # the non-target model is bit-identical to the clean run
    for a, b in zip(jax.tree_util.tree_leaves(ps_inf[1 - target]),
                    jax.tree_util.tree_leaves(ps_ref[1 - target])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the target model differs from clean (it skipped one update) but has
    # finite params
    assert all(np.all(np.isfinite(np.asarray(l, np.float32)))
               for l in jax.tree_util.tree_leaves(ps_inf[target]))
    diff = max(float(jnp.max(jnp.abs(a.astype(jnp.float32) -
                                     b.astype(jnp.float32))))
               for a, b in zip(jax.tree_util.tree_leaves(ps_inf[target]),
                               jax.tree_util.tree_leaves(ps_ref[target])))
    assert diff > 0


def test_skipped_step_params_unchanged():
    """iter-0 inf: params after the skipped step == initial params."""
    models, opts = amp.initialize(
        _models()[:1], [optimizers.FusedAdam(lr=1e-2)],
        opt_level="O2", half_dtype="float16", loss_scale="dynamic",
        verbosity=0, hard_override=True)
    model, opt = models[0], opts[0]
    x, y = jnp.asarray(X), jnp.asarray(Y)
    params, _ = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)

    def inf_loss(p):
        out, _ = model.apply(p, x)
        return F.mse_loss(out.astype(jnp.float32), y) * jnp.float32(np.inf)

    _, grads = amp.scaled_grad(inf_loss, params, opt_state)
    new_params, opt_state, info = opt.step(params, opt_state, grads)
    assert float(info["found_inf"]) == 1.0
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(new_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_two_losses_one_optimizer_independent_scalers():
    """num_losses=2: each loss_id owns a scaler; overflow in loss 1 must
    not touch scaler 0 (reference scale_loss(loss_id=...) semantics)."""
    model, opt = amp.initialize(
        _models()[0], optimizers.FusedAdam(lr=1e-2), opt_level="O2",
        half_dtype="float16", loss_scale="dynamic", num_losses=2,
        verbosity=0, hard_override=True)
    x, y = jnp.asarray(X), jnp.asarray(Y)
    params, _ = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    assert len(opt_state.scalers) == 2
    s0 = float(opt_state.scalers[0].loss_scale)

    def inf_loss(p):
        out, _ = model.apply(p, x)
        return F.mse_loss(out.astype(jnp.float32), y) * jnp.float32(np.inf)

    _, grads = amp.scaled_grad(inf_loss, params, opt_state, loss_id=1)
    params, opt_state, _ = opt.step(params, opt_state, grads, loss_id=1)
    assert float(opt_state.scalers[1].loss_scale) == s0 / 2
    assert float(opt_state.scalers[0].loss_scale) == s0
