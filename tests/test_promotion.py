"""Promotion-semantics + full-lists coverage tests — the apex_tpu port of
the reference's tests/L0/run_amp/test_promotion.py plus a value-sanity
sweep over every op name the O1 tables classify (round-2 VERDICT item 8:
the tables must not name ops that don't exist)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu.amp import lists
from apex_tpu.amp import policy as P
from apex_tpu.nn import functional as F


@pytest.fixture(autouse=True)
def reset_policy():
    yield
    P.set_policy(P.NoPolicy())


def o1(half=jnp.float16):
    return P.use_policy(P.CastPolicy(half))


def test_every_listed_op_exists():
    """The table/implementation gap the round-2 VERDICT flagged: every
    classified name must resolve to a callable on nn.functional (the
    framework's op surface) or the transformer package."""
    from apex_tpu import transformer
    for table in (lists.FP16_FUNCS, lists.FP32_FUNCS, lists.PROMOTE_FUNCS,
                  lists.SEQUENCE_PROMOTE_FUNCS, lists.BANNED_FUNCS):
        for name in table:
            assert (hasattr(F, name) or hasattr(transformer, name)), \
                f"amp.lists names unimplemented op {name!r}"


# -- out-of-place promotion: widest type wins (test_promotion.py) -----------

@pytest.mark.parametrize("op,args", [
    ("sub", 2), ("div", 2), ("atan2", 2), ("fmod", 2), ("remainder", 2),
    ("addcdiv", 3), ("addcmul", 3),
])
def test_mixed_dtype_promotes_widest(op, args):
    xs16 = [jnp.ones((4,), jnp.float16) * (i + 1) for i in range(args - 1)]
    x32 = jnp.ones((4,), jnp.float32) * 3
    with o1():
        out = getattr(F, op)(x32, *xs16)
        out2 = getattr(F, op)(*xs16, x32)
    assert out.dtype == jnp.float32, op
    assert out2.dtype == jnp.float32, op


def test_same_half_dtype_stays_half():
    a = jnp.ones((4,), jnp.float16)
    b = jnp.ones((4,), jnp.float16)
    with o1():
        assert F.sub(a, b).dtype == jnp.float16
        assert F.min(a, b).dtype == jnp.float16
        assert F.max(a, b).dtype == jnp.float16


def test_comparisons_promote_inputs_return_bool():
    a = jnp.ones((4,), jnp.float16)
    b = jnp.ones((4,), jnp.float32)
    with o1():
        for op in ("eq", "ne", "lt", "gt", "le", "ge"):
            out = getattr(F, op)(a, b)
            assert out.dtype == jnp.bool_, op


def test_sequence_promote_mixed_cat():
    a = jnp.ones((2,), jnp.float16)
    b = jnp.ones((2,), jnp.float32)
    with o1():
        assert F.cat([a, b]).dtype == jnp.float32
        assert F.concatenate([a, b]).dtype == jnp.float32
        assert F.stack([a, a]).dtype == jnp.float16


# -- whitelist ops: half execution -------------------------------------------

def test_gemm_family_casts_to_half():
    with o1(jnp.bfloat16):
        assert F.mm(jnp.ones((2, 3)), jnp.ones((3, 2))).dtype == jnp.bfloat16
        assert F.mv(jnp.ones((2, 3)), jnp.ones((3,))).dtype == jnp.bfloat16
        assert F.bmm(jnp.ones((2, 2, 3)),
                     jnp.ones((2, 3, 2))).dtype == jnp.bfloat16
        assert F.addmm(jnp.ones((2, 2)), jnp.ones((2, 3)),
                       jnp.ones((3, 2))).dtype == jnp.bfloat16
        assert F.baddbmm(jnp.ones((2, 2, 2)), jnp.ones((2, 2, 3)),
                         jnp.ones((2, 3, 2))).dtype == jnp.bfloat16


def test_gemm_family_values():
    a = jnp.asarray(np.arange(6).reshape(2, 3), jnp.float32)
    b = jnp.asarray(np.arange(6).reshape(3, 2), jnp.float32)
    c = jnp.ones((2, 2), jnp.float32)
    np.testing.assert_allclose(np.asarray(F.mm(a, b)), np.arange(6).reshape(2, 3) @ np.arange(6).reshape(3, 2))
    np.testing.assert_allclose(np.asarray(F.addmm(c, a, b, beta=2.0, alpha=0.5)),
                               2.0 + 0.5 * (np.arange(6).reshape(2, 3) @ np.arange(6).reshape(3, 2)))
    np.testing.assert_allclose(np.asarray(F.addbmm(c, jnp.stack([a, a]), jnp.stack([b, b]))),
                               1.0 + 2 * (np.arange(6).reshape(2, 3) @ np.arange(6).reshape(3, 2)))
    np.testing.assert_allclose(np.asarray(F.addr(jnp.zeros((2, 2)), jnp.asarray([1.0, 2.0]), jnp.asarray([3.0, 4.0]))),
                               np.outer([1, 2], [3, 4]))


def test_conv_family_shapes_and_half():
    x1 = jnp.ones((2, 3, 16), jnp.float32)
    w1 = jnp.ones((5, 3, 3), jnp.float32)
    x3 = jnp.ones((1, 2, 4, 6, 6), jnp.float32)
    w3 = jnp.ones((4, 2, 2, 3, 3), jnp.float32)
    with o1(jnp.bfloat16):
        y1 = F.conv1d(x1, w1, padding=1)
        y3 = F.conv3d(x3, w3)
    assert y1.shape == (2, 5, 16) and y1.dtype == jnp.bfloat16
    assert y3.shape == (1, 4, 3, 4, 4) and y3.dtype == jnp.bfloat16
    # conv_tbc: (T, B, C) in, kernel (kW, Cin, Cout)
    xt = jnp.ones((10, 2, 3), jnp.float32)
    wt = jnp.ones((3, 3, 5), jnp.float32)
    yt = F.conv_tbc(xt, wt, None, pad=1)
    assert yt.shape == (10, 2, 5)
    # transposed 1d inverts conv1d stride-2 shape
    xtr = jnp.ones((2, 5, 8), jnp.float32)
    wtr = jnp.ones((5, 3, 4), jnp.float32)
    assert F.conv_transpose1d(xtr, wtr, stride=2).shape == (2, 3, 18)


def test_prelu_values_and_half():
    x = jnp.asarray([[-2.0, 3.0]], jnp.float32)
    w = jnp.asarray([0.5, 0.5], jnp.float32)
    np.testing.assert_allclose(np.asarray(F.prelu(x, w)), [[-1.0, 3.0]])
    with o1():
        assert F.prelu(x, w).dtype == jnp.float16


# -- blacklist ops: fp32 execution on half inputs -----------------------------

def test_transcendentals_force_fp32():
    x = jnp.ones((4,), jnp.float16)
    with o1():
        for name in ("exp", "log", "log2", "log10", "log1p", "expm1",
                     "reciprocal", "rsqrt", "cosh", "sinh", "tan", "erf",
                     "softplus", "cumsum", "cumprod"):
            out = getattr(F, name)(x * 0.5)
            assert out.dtype == jnp.float32, name
        assert F.pow(x, 2.0).dtype == jnp.float32
        assert F.acos(x * 0.1).dtype == jnp.float32
        assert F.asin(x * 0.1).dtype == jnp.float32
        assert F.erfinv(x * 0.1).dtype == jnp.float32


@pytest.mark.slow
def test_reductions_force_fp32():
    x = jnp.ones((3, 4), jnp.float16)
    with o1():
        for name in ("sum", "mean", "prod", "std", "var", "logsumexp",
                     "norm", "softmin"):
            out = getattr(F, name)(x)
            assert out.dtype == jnp.float32, name
        assert F.dist(x, 2 * x).dtype == jnp.float32
        assert F.normalize(x).dtype == jnp.float32
        assert F.cosine_similarity(x, x).dtype == jnp.float32
        assert F.pdist(x).dtype == jnp.float32
        assert F.renorm(x, 2.0, 0, 1.0).dtype == jnp.float32


def test_reduction_values():
    x = jnp.asarray([[3.0, 4.0]], jnp.float32)
    np.testing.assert_allclose(float(F.norm(x)), 5.0)
    np.testing.assert_allclose(float(F.dist(x, jnp.zeros_like(x))), 5.0)
    np.testing.assert_allclose(np.asarray(F.pdist(jnp.asarray(
        [[0.0, 0.0], [3.0, 4.0]]))), [5.0])
    r = F.renorm(jnp.asarray([[3.0, 4.0], [0.3, 0.4]]), 2.0, 0, 1.0)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(r), axis=1),
                               [1.0, 0.5], rtol=1e-5)


def test_norm_layers_fp32_and_values():
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 3, 3), jnp.float16)
    with o1():
        g = F.group_norm(x, 2)
        i = F.instance_norm(x)
        b = F.batch_norm(x, None, None, training=True)
    assert g.dtype == i.dtype == jnp.float32
    gn = np.asarray(g).reshape(2, 2, -1)
    np.testing.assert_allclose(gn.mean(-1), 0.0, atol=1e-3)
    np.testing.assert_allclose(gn.std(-1), 1.0, atol=1e-2)


def test_losses_fp32_and_values():
    x = jnp.asarray([0.0, 2.0], jnp.float16)
    t = jnp.asarray([0.0, 0.0], jnp.float16)
    with o1():
        assert F.smooth_l1_loss(x, t).dtype == jnp.float32
        assert F.kl_div(x, jnp.abs(t) + 0.5).dtype == jnp.float32
        assert F.soft_margin_loss(x, jnp.sign(t + 1)).dtype == jnp.float32
        assert F.poisson_nll_loss(x, jnp.abs(t)).dtype == jnp.float32
    np.testing.assert_allclose(
        float(F.smooth_l1_loss(jnp.asarray([0.5, 2.0]),
                               jnp.asarray([0.0, 0.0]))),
        (0.5 * 0.25 + 1.5) / 2)
    # margin family values vs hand math
    np.testing.assert_allclose(float(F.margin_ranking_loss(
        jnp.asarray([1.0]), jnp.asarray([2.0]), jnp.asarray([1.0]),
        margin=0.5)), 1.5)
    np.testing.assert_allclose(float(F.hinge_embedding_loss(
        jnp.asarray([0.3]), jnp.asarray([-1]), margin=1.0)), 0.7, rtol=1e-6)
    np.testing.assert_allclose(float(F.cosine_embedding_loss(
        jnp.asarray([[1.0, 0.0]]), jnp.asarray([[1.0, 0.0]]),
        jnp.asarray([1]))), 0.0, atol=1e-6)
    np.testing.assert_allclose(float(F.triplet_margin_loss(
        jnp.asarray([[0.0]]), jnp.asarray([[0.5]]), jnp.asarray([[3.0]]),
        margin=1.0)), 0.0)


def test_multi_margin_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype(np.float32)
    t = rng.randint(0, 5, (4,))
    ref = torch.nn.functional.multi_margin_loss(
        torch.tensor(x), torch.tensor(t)).item()
    np.testing.assert_allclose(
        float(F.multi_margin_loss(jnp.asarray(x), jnp.asarray(t))), ref,
        rtol=1e-5)


def test_multilabel_margin_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.asarray([[0.1, 0.2, 0.4, 0.8]], np.float32)
    t = np.asarray([[3, 0, -1, 1]], np.int64)
    ref = torch.nn.functional.multilabel_margin_loss(
        torch.tensor(x), torch.tensor(t)).item()
    np.testing.assert_allclose(
        float(F.multilabel_margin_loss(jnp.asarray(x), jnp.asarray(t))),
        ref, rtol=1e-5)


def test_bilinear_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(1)
    x1 = rng.randn(3, 4).astype(np.float32)
    x2 = rng.randn(3, 5).astype(np.float32)
    w = rng.randn(2, 4, 5).astype(np.float32)
    b = rng.randn(2).astype(np.float32)
    ref = torch.nn.functional.bilinear(
        torch.tensor(x1), torch.tensor(x2), torch.tensor(w),
        torch.tensor(b)).numpy()
    np.testing.assert_allclose(
        np.asarray(F.bilinear(jnp.asarray(x1), jnp.asarray(x2),
                              jnp.asarray(w), jnp.asarray(b))),
        ref, rtol=1e-4, atol=1e-5)


def test_conv_family_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 10).astype(np.float32)
    w = rng.randn(4, 3, 3).astype(np.float32)
    ref = torch.nn.functional.conv1d(torch.tensor(x), torch.tensor(w),
                                     padding=1).numpy()
    np.testing.assert_allclose(np.asarray(F.conv1d(
        jnp.asarray(x), jnp.asarray(w), padding=1)), ref, rtol=1e-4,
        atol=1e-4)
    xt = rng.randn(6, 2, 3).astype(np.float32)
    wt = rng.randn(3, 3, 4).astype(np.float32)
    bt = rng.randn(4).astype(np.float32)
    ref = torch.conv_tbc(torch.tensor(xt), torch.tensor(wt),
                         torch.tensor(bt), pad=1).numpy()
    np.testing.assert_allclose(np.asarray(F.conv_tbc(
        jnp.asarray(xt), jnp.asarray(wt), jnp.asarray(bt), pad=1)), ref,
        rtol=1e-4, atol=1e-4)
