"""Serving SLO-feedback controller (fleet/autoscale.py) + the shared
recovery bookkeeping (fleet/recovery.py RecoveryLog) + fleet MTTR.

All jax-free: the controller reads tracker deltas and actuates host
knobs, so a stub replica + an injected tick clock make every timeline
exact — the same discipline as the breaker/retry tests in
test_fleet.py.  The end-to-end seeded chaos schedule (death + stall +
spike, baseline vs controller, plus the stub elastic-training run)
lives in tests/ci/chaos_smoke.py and is wired into tier-1 here by
subprocess, like the server_smoke gate."""

import os
import subprocess
import sys

import numpy as np
import pytest

from apex_tpu.fleet import (DEAD, AutoscaleConfig, FaultyReplica,
                            Fleet, FleetOverloaded, HealthConfig,
                            RecoveryLog, RetryPolicy, SloController)
from apex_tpu.fleet.recovery import (RECOVERY_ACTION_KINDS,
                                     RECOVERY_CAUSES, RECOVERY_ROLES)
from apex_tpu import observability as obs
from apex_tpu.observability import exporters
from apex_tpu.observability.exporters import (JsonlExporter,
                                              validate_recovery_record,
                                              validate_fleet_record,
                                              validate_telemetry_record)


class _Stub:
    """Scheduler-surface stub: one deterministic token per live
    request per step (test_fleet discipline) + the duck-typed
    ``set_window`` the controller's window actuator targets."""

    def __init__(self, slots=2, window=8):
        self.slots = slots
        self.window = window
        self.base_window = window
        self._free = list(range(slots))
        self._live = {}
        self._waiting = []
        self._finished = {}
        self._next_rid = 0

    def set_window(self, k):
        self.window = int(k)

    @staticmethod
    def expected_tokens(plen, max_new):
        return [100 * plen + j for j in range(max_new)]

    def _admit(self, rid, prompt, max_new):
        self._free.pop()
        self._live[rid] = [list(prompt), max_new, []]

    def add_request(self, prompt, max_new_tokens, eos_token_id=None,
                    seed=None, temperature=None):
        if not self._free:
            raise RuntimeError("no free slot")
        rid = self._next_rid
        self._next_rid += 1
        self._admit(rid, prompt, max_new_tokens)
        return rid

    def submit(self, prompt, max_new_tokens, eos_token_id=None,
               seed=None, temperature=None):
        if self._free and not self._waiting:
            return self.add_request(prompt, max_new_tokens)
        rid = self._next_rid
        self._next_rid += 1
        self._waiting.append((rid, list(prompt), max_new_tokens))
        return rid

    def step(self):
        out = {}
        for rid, rec in list(self._live.items()):
            prompt, max_new, got = rec
            tok = 100 * len(prompt) + len(got)
            got.append(tok)
            out[rid] = [tok]
            if len(got) >= max_new:
                del self._live[rid]
                self._free.append(0)
                self._finished[rid] = got
        while self._free and self._waiting:
            rid, prompt, max_new = self._waiting.pop(0)
            self._admit(rid, prompt, max_new)
        return out

    def live(self):
        return len(self._live)

    def free_slots(self):
        return len(self._free)

    def queue_depth(self):
        return len(self._waiting)

    def is_finished(self, rid):
        return rid in self._finished

    def result(self, rid):
        return list(self._finished[rid])

    def cancel(self, rid):
        for i, item in enumerate(self._waiting):
            if item[0] == rid:
                del self._waiting[i]
                return True
        if rid in self._live:
            del self._live[rid]
            self._free.append(0)
            return True
        return False

    def take_waiting(self):
        taken, self._waiting = self._waiting, []
        return taken

    def stats(self):
        return {"occupancy": len(self._live) / self.slots,
                "queue_depth": len(self._waiting)}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _fleet(n=2, slots=2, max_queue=64, clock=None, window=8, **kw):
    reps = [_Stub(slots=slots, window=window) for _ in range(n)]
    fl = Fleet(reps, policy="least_loaded", max_queue=max_queue,
               retry=RetryPolicy(max_attempts=8), step_workers=1,
               clock=clock, **kw)
    return fl, reps


def _drive(fl, ctrl, clock, *, waves, ticks, deadline=None,
           ctrl_every=2, max_new=4):
    """Seeded workload: ``waves[tick]`` submissions per tick; one
    controller tick every ``ctrl_every`` fleet steps; the clock
    advances exactly one unit per fleet step."""
    shed = 0
    rids = []
    for tick in range(ticks):
        for _ in range(waves.get(tick, 0)):
            try:
                rids.append(fl.submit([1, 2, 3],
                                      max_new_tokens=max_new,
                                      deadline=deadline))
            except FleetOverloaded:
                shed += 1
        fl.step()
        clock.t += 1.0
        if ctrl is not None and tick % ctrl_every == ctrl_every - 1:
            ctrl.tick()
    guard = 0
    while fl.live() and guard < 300:
        fl.step()
        clock.t += 1.0
        if ctrl is not None:
            ctrl.tick()
        guard += 1
    assert not fl.live()
    return rids, shed


# -- constants pinned across the stdlib/package boundary -----------------

def test_action_kinds_pinned_to_exporters():
    assert RECOVERY_ACTION_KINDS == exporters.RECOVERY_ACTION_KINDS
    assert RECOVERY_ROLES == exporters.RECOVERY_ROLES
    assert RECOVERY_CAUSES == exporters.RECOVERY_CAUSES


def test_recovery_log_rejects_negative_t_s_at_append():
    """The PR 11 gotcha guarded AT THE SOURCE: a log whose t0 predates
    the current clock (fleet/controller built before an injected tick
    clock was reset) fails at action() time with the remedy, instead
    of the finished record failing validate_recovery_record later."""
    t = {"v": 100.0}
    log = RecoveryLog("serving", "clockskew", clock=lambda: t["v"])
    t["v"] = 10.0                       # clock reset AFTER construction
    with pytest.raises(ValueError, match="[Rr]eset the clock"):
        log.action("undrain")
    # a healthy clock still appends
    t["v"] = 101.0
    ev = log.action("undrain")
    assert ev["t_s"] == pytest.approx(1.0)


# -- RecoveryLog bookkeeping ---------------------------------------------

def test_recovery_log_episode_action_mttr_accounting():
    clk = _Clock()
    ring = obs.EventRing(64)
    log = RecoveryLog("serving", "t", clock=clk, ring=ring)
    assert not log.in_flight
    log.open_episode("spike")
    log.open_episode("spike again")      # idempotent while open
    assert log.episodes == 1
    log.action("admission_tighten", max_queue_from=8, max_queue_to=4)
    clk.t = 3.0
    log.close_episode(mttr_s=3.0)
    assert not log.in_flight
    # relax OUTSIDE the episode: counted in the total, excluded from
    # the per-episode oscillation bound
    log.action("admission_relax", max_queue_from=4, max_queue_to=8)
    assert log.actions_total == 2
    assert log.max_actions_in_episode == 1
    assert log.mttr() == {"last": 3.0, "mean": 3.0, "count": 1}
    with pytest.raises(ValueError):
        log.action("reboot_the_universe")
    with pytest.raises(ValueError):
        RecoveryLog("mystery", "t")
    kinds = [ev["kind"] for ev in ring.snapshot()]
    assert kinds == ["recovery_started", "recovery_action",
                     "recovery_done", "recovery_action"]
    rec = JsonlExporter.enrich(log.record())
    assert validate_recovery_record(rec) == []
    assert validate_telemetry_record(rec) == []


def test_recovery_record_validator_rejects_mutations():
    log = RecoveryLog("training", "r")
    log.open_episode("death")
    log.action("world_shrink", world_from=8, world_to=4)
    log.close_episode(mttr_s=0.5)
    good = JsonlExporter.enrich(log.record(world=4, recoveries=1))
    assert validate_recovery_record(good) == []
    cases = {
        "unknown role": {"role": "parking"},
        "empty subject": {"subject": ""},
        "negative episodes": {"episodes": -1},
        "details exceed total": {"actions_total": 0},
        "max exceeds total": {"max_actions_in_episode": 99},
        "bad world": {"world": 0},
        "mttr inconsistent": {"mttr_s": {"last": None, "mean": None,
                                         "count": 3}},
        "mttr nan": {"mttr_s": {"last": float("nan"), "mean": 0.5,
                                "count": 1}},
    }
    for label, patch in cases.items():
        bad = {**good, **patch}
        assert validate_recovery_record(bad), label
    bad_action = dict(good)
    bad_action["actions"] = [dict(good["actions"][0], kind="reboot")]
    assert validate_recovery_record(bad_action)
    bad_ep = dict(good)
    bad_ep["actions"] = [dict(good["actions"][0], episode=7)]
    assert validate_recovery_record(bad_ep)


# -- controller behavior --------------------------------------------------

def test_stable_load_no_actuation():
    clk = _Clock()
    fl, _ = _fleet(clock=clk)
    ctrl = SloController(fl, AutoscaleConfig(), clock=clk)
    waves = {t: 1 for t in range(0, 40, 6)}     # well under capacity
    _drive(fl, ctrl, clk, waves=waves, ticks=48, deadline=30.0)
    rec = ctrl.record()
    assert rec["episodes"] == 0
    assert rec["actions_total"] == 0
    assert fl.max_queue == ctrl.base_max_queue
    assert JsonlExporter.enrich(rec) and \
        validate_recovery_record(JsonlExporter.enrich(rec)) == []


def test_spike_tightens_admission_then_relaxes_back():
    clk = _Clock()
    fl, _ = _fleet(max_queue=64, clock=clk)
    cfg = AutoscaleConfig(min_queue=4, backlog_factor=2.0,
                          cooldown_ticks=1, relax_after_ticks=4,
                          max_actions_per_episode=6)
    ctrl = SloController(fl, cfg, clock=clk)
    waves = {0: 1, 10: 30}                       # the spike
    _drive(fl, ctrl, clk, waves=waves, ticks=80, deadline=12.0)
    rec = ctrl.record()
    kinds = [a["kind"] for a in rec["actions"]]
    assert "admission_tighten" in kinds
    assert "admission_relax" in kinds
    # converged: bounded per episode, episode closed, admission back
    # at its base once the spike drained and health held
    assert rec["max_actions_in_episode"] <= cfg.max_actions_per_episode
    assert not rec["in_flight"]
    assert fl.max_queue == ctrl.base_max_queue
    assert validate_recovery_record(JsonlExporter.enrich(rec)) == []


def test_controller_beats_baseline_on_seeded_spike():
    """The acceptance pin at the unit level: identical seeded TWO-wave
    spike, deterministic stub service times — the controller must hold
    attainment above the no-controller baseline.  Wave 1 is absorbed
    by both (already admitted before any feedback can act); wave 2 is
    where feedback pays: it hits the pre-tightened admission bound and
    the doomed tail sheds at the door instead of expiring as misses.
    min_queue is sized to the makeable backlog (deadline / per-request
    service x slots), so goodput stays within a whisker of the
    baseline — the exact-parity pin under saturation lives in
    bench --chaos and tests/ci/chaos_smoke.py."""
    waves = {t: 1 for t in range(0, 90, 6)}
    waves[10] = waves.get(10, 0) + 24
    waves[50] = waves.get(50, 0) + 24

    def run(with_ctrl):
        clk = _Clock()
        fl, _ = _fleet(max_queue=64, clock=clk)
        ctrl = (SloController(
            fl, AutoscaleConfig(min_queue=12, backlog_factor=2.0,
                                cooldown_ticks=1,
                                relax_after_ticks=10,
                                max_actions_per_episode=6),
            clock=clk) if with_ctrl else None)
        _drive(fl, ctrl, clk, waves=waves, ticks=110, deadline=24.0,
               max_new=8)
        return fl.record()

    base, ctrl = run(False), run(True)
    assert base["slo_attainment"] is not None
    assert ctrl["slo_attainment"] > base["slo_attainment"]
    assert (ctrl["goodput_tokens_per_s"]
            >= 0.9 * base["goodput_tokens_per_s"])
    for rec in (base, ctrl):
        assert validate_fleet_record(JsonlExporter.enrich(rec)) == []


def test_undrain_is_first_resort_under_backlog():
    clk = _Clock()
    fl, _ = _fleet(n=3, clock=clk)
    fl.drain(2)
    assert fl.states()[2] == "drained"
    ctrl = SloController(fl, AutoscaleConfig(backlog_factor=1.0,
                                             cooldown_ticks=1),
                         clock=clk)
    # pile a backlog: 20 queued against 4 steppable slots
    for _ in range(20):
        fl.submit([1, 2, 3], max_new_tokens=4)
    fl.step()
    clk.t += 1.0
    acts = ctrl.tick()
    assert [a["kind"] for a in acts] == ["undrain"]
    assert fl.states()[2] == "healthy"
    # capacity came back BEFORE any admission tightening
    assert fl.max_queue == ctrl.base_max_queue


def test_cooldown_shortened_for_open_breaker_under_pressure():
    clk = _Clock()
    reps = [_Stub(slots=2), _Stub(slots=2)]
    sick = FaultyReplica(reps[0], raise_on_step=(0, None))
    fl = Fleet([sick, reps[1]], policy="least_loaded", max_queue=64,
               retry=RetryPolicy(max_attempts=8),
               health=HealthConfig(cooldown_steps=32,
                                   dead_consecutive=2),
               step_workers=1, clock=clk)
    ctrl = SloController(fl, AutoscaleConfig(backlog_factor=1.0,
                                             cooldown_ticks=1,
                                             probe_cooldown_steps=1),
                         clock=clk)
    for _ in range(12):
        fl.submit([1, 2, 3], max_new_tokens=4)
    # step until the breaker opens on the sick replica
    for _ in range(4):
        fl.step()
        clk.t += 1.0
    h = fl.health[0]
    assert h.circuit == "open" and h.cooldown_left > 1
    acts = ctrl.tick()
    assert any(a["kind"] == "cooldown_shorten" for a in acts)
    assert h.cooldown_left == 1
    ring_kinds = [ev["kind"] for ev in fl.ring.snapshot()]
    assert "cooldown_set" in ring_kinds


def test_window_actuated_when_other_knobs_exhausted():
    clk = _Clock()
    fl, reps = _fleet(max_queue=16, clock=clk, window=8)
    cfg = AutoscaleConfig(min_queue=16, backlog_factor=1.0,
                          cooldown_ticks=1, relax_after_ticks=2,
                          window_bounds=(2, 8),
                          max_actions_per_episode=8)
    ctrl = SloController(fl, cfg, clock=clk)
    # max_queue already at min (== min_queue), nothing drained, no
    # breaker open: the only knob left under backlog is the decode
    # window.  16 submits leave 8 queued past the 4 slots + 4
    # replica-queue seats after one dispatch tick.
    for _ in range(16):
        fl.submit([1, 2, 3], max_new_tokens=4)
    fl.step()
    clk.t += 1.0
    acts = ctrl.tick()
    assert [a["kind"] for a in acts] == ["window_shrink"]
    assert reps[0].window == 4
    # recovery grows it back toward the base window
    while fl.live():
        fl.step()
        clk.t += 1.0
    for _ in range(6):
        clk.t += 1.0
        ctrl.tick()
    assert any(a["kind"] == "window_grow"
               for a in ctrl.record()["actions"])
    assert reps[0].window == 8


def test_bounded_actuation_under_persistent_overload():
    """A hopeless overload (capacity can never meet the deadline) must
    not make the controller thrash: one episode, at most
    max_actions_per_episode actuations, then it stops and leaves the
    episode for a human."""
    clk = _Clock()
    fl, _ = _fleet(n=1, slots=1, max_queue=64, clock=clk)
    cfg = AutoscaleConfig(min_queue=2, backlog_factor=1.0,
                          cooldown_ticks=1, relax_after_ticks=50,
                          max_actions_per_episode=3)
    ctrl = SloController(fl, cfg, clock=clk)
    waves = {t: 3 for t in range(0, 60, 2)}     # 3x capacity forever
    _drive(fl, ctrl, clk, waves=waves, ticks=60, deadline=4.0)
    rec = ctrl.record()
    assert rec["episodes"] >= 1
    assert rec["max_actions_in_episode"] <= 3
    assert validate_recovery_record(JsonlExporter.enrich(rec)) == []


# -- fleet MTTR accounting ------------------------------------------------

def test_fleet_mttr_measures_failover_to_reclaimed_progress():
    clk = _Clock()
    stub = _Stub(slots=2)
    sick = FaultyReplica(stub, raise_on_step=(2, 3))
    fl = Fleet([sick, _Stub(slots=2)], policy="round_robin",
               max_queue=16, retry=RetryPolicy(max_attempts=8),
               step_workers=1, clock=clk)
    rids = [fl.submit([1, 2, 3], max_new_tokens=4) for _ in range(4)]
    assert fl.mttr() == {"last": None, "mean": None, "count": 0}
    guard = 0
    while fl.live() and guard < 100:
        fl.step()
        clk.t += 1.0
        guard += 1
    m = fl.mttr()
    assert m["count"] == 1
    # deterministic timeline: failover at the fault tick, re-dispatch
    # next tick into the survivor's (full) slots, first reclaimed
    # token one tick later -> exactly 2 ticks
    assert m["last"] == 2.0
    for r in rids:
        assert fl.result(r) == _Stub.expected_tokens(3, 4)
    kinds = [ev["kind"] for ev in fl.ring.snapshot()]
    assert "failover" in kinds and "recovery_done" in kinds
    rec = JsonlExporter.enrich(fl.record())
    assert validate_fleet_record(rec) == []
    assert rec["mttr"]["count"] == 1


def test_fleet_record_mttr_field_validated():
    good = {"kind": "fleet", "trace_id": "t", "replicas": 1,
            "policy": "p", "healthy": 1, "degraded": 0, "dead": 0,
            "queue_depth": 0, "submitted": 0, "finished": 0,
            "failed": 0, "shed": 0, "retries": 0, "failovers": 0,
            "drains": 0, "tokens": 0, "deadline_exceeded": 0,
            "tenants": {}, "tenants_dropped": 0,  # required fresh at v11
            "classes": {}, "preemptions": 0,      # required fresh at v14
            "mttr": {"last": None, "mean": None, "count": 0}}
    assert validate_fleet_record(JsonlExporter.enrich(good)) == []
    bad = dict(good, mttr={"last": -1.0, "mean": 1.0, "count": 1})
    assert validate_fleet_record(JsonlExporter.enrich(bad))
    bad2 = dict(good, mttr="fast")
    assert validate_fleet_record(JsonlExporter.enrich(bad2))


# -- recovering is degraded-but-live on /healthz --------------------------

def test_healthz_reports_recovering_not_503_during_world_shrink():
    clk = _Clock()
    reps = [FaultyReplica(_Stub(), raise_on_step=(0, None))]
    fl = Fleet(reps, step_workers=1, clock=clk,
               health=HealthConfig(dead_consecutive=1))
    fl.submit([1, 2], max_new_tokens=2)
    for _ in range(3):
        fl.step()
        clk.t += 1.0
    assert fl.states() == [DEAD]
    srv = obs.server.serve(fleet=fl, start=False)
    code, payload = srv.healthz()
    assert code == 503                      # dead fleet, no recovery
    fl.begin_recovery("intentional world shrink")
    code, payload = srv.healthz()
    assert code == 200                      # degraded-but-LIVE
    assert "recovering" in payload["checks"]["replicas"]["detail"]
    kinds = [ev["kind"] for ev in fl.ring.snapshot()]
    assert "fleet_recovery_begin" in kinds
    fl.end_recovery()
    code, _ = srv.healthz()
    assert code == 503                      # still dead, not handled
    assert fl.stats()["recovery_in_flight"] is False


# -- the tier-1 chaos gate ------------------------------------------------

def test_chaos_smoke_gate():
    script = os.path.join(os.path.dirname(__file__), "ci",
                          "chaos_smoke.py")
    proc = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, (proc.stdout + proc.stderr)
    assert "all checks passed" in proc.stdout
