"""Gemma on the Llama backbone: decoupled head_dim, GeGLU, (1+w)
RMSNorm, sqrt(hidden) embedding scale — HF logits and greedy
generation parity."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu.models import Llama, LlamaConfig


def _pair():
    import torch
    from transformers import GemmaConfig as HFConfig, GemmaForCausalLM
    from apex_tpu.utils import hf_interop

    hf_cfg = HFConfig(vocab_size=151, hidden_size=48,
                      intermediate_size=96, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      head_dim=20,            # decoupled: 4*20 != 48
                      max_position_embeddings=48,
                      attn_implementation="eager")
    torch.manual_seed(0)
    hf = GemmaForCausalLM(hf_cfg).eval()
    cfg, params = hf_interop.gemma_from_hf(hf)
    assert cfg.head_dim == 20 and cfg.rms_unit_offset \
        and cfg.embed_scale and cfg.mlp_act == "gelu_tanh"
    return hf, Llama(cfg), params


# tier-1 budget (PR 2): slowest tests by --durations carry the slow
# marker so a cold `-m 'not slow'` run fits the 870 s timeout
@pytest.mark.slow
def test_gemma_logits_match_transformers():
    import torch

    hf, m, params = _pair()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 151, (2, 24))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    out = np.asarray(m(params, jnp.asarray(ids)))
    np.testing.assert_allclose(out, ref, rtol=4e-4, atol=4e-4)


def test_gemma_greedy_generation_matches_transformers():
    import torch

    hf, m, params = _pair()
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 151, (2, 6))
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(prompt), max_new_tokens=10,
                          do_sample=False).numpy()
    buf = jnp.zeros((2, 48), jnp.int32).at[:, :6].set(jnp.asarray(prompt))
    out, n = m.generate_cached(params, buf, 6, 10)
    assert int(n[0]) == 16
    np.testing.assert_array_equal(np.asarray(out[:, :16]), ref)


def test_gemma_cache_uses_head_dim():
    _, m, params = _pair()
    cache = m.init_cache(2)
    assert cache["0"]["k"].shape == (2, 2, 48, 20)


def test_gemma_knob_validation():
    kw = dict(vocab_size=97, hidden_size=32, intermediate_size=64,
              num_hidden_layers=1, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=16)
    with pytest.raises(ValueError, match="mlp_act"):
        LlamaConfig(mlp_act="relu", **kw)
    with pytest.raises(NotImplementedError, match="head_dim"):
        LlamaConfig(head_dim=16, tp_axis="model", **kw)
