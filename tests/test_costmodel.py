"""Cost-model observability (PR 8): the analytic FLOPs/bytes model is
pinned against XLA's own counts on the real hot graphs, the memory
plans/liveness/live-array census behave, and the MFU + peak-TFLOPs
surface is consistent.

The acceptance pin lives here: analytic FLOPs match XLA within 5% on
the resnet18 O2 and GPT O2 entry points.  The cross-check runs at the
``Lowered`` stage (pre-optimization HLO, structurally 1:1 with the
jaxpr — jax's own DCE applied on both sides) AND against
``Compiled.cost_analysis()`` on a fwd+bwd core the way test_remat.py
consumes it.  Post-optimization counts on flat-optimizer graphs are
deliberately NOT compared: XLA's fused-producer duplication bills the
11M-element Adam update once per param-leaf slice there (~8x over —
see the costmodel module docstring)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu import amp, models, optimizers
from apex_tpu.nn import functional as F
from apex_tpu.observability import costmodel, memory, exporters


def _lower_jaxpr(closed):
    """Re-stage a traced jaxpr for XLA cost analysis (same trick
    analysis.Graph.compiled uses for trace-only entry points)."""
    args = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
            for v in closed.jaxpr.invars]
    fn = jax.jit(lambda *xs: jax.core.eval_jaxpr(
        closed.jaxpr, closed.consts, *xs))
    return fn.lower(*args)


# -- acceptance: analytic vs XLA on the real entry points ------------------

@pytest.mark.parametrize("ep_name", ["ddp_resnet18_o2",
                                     "gpt_o2_train_step"])
def test_analytic_flops_match_xla_on_entry_points(ep_name):
    """THE acceptance pin: the analytic model prices the full DDP train
    step — convs (valid-position counting incl. dgrad dilation), dots,
    elementwise, reductions, collectives, the optimizer cond — within
    5% of XLA's HloCostAnalysis on the same graph.  Actual agreement
    is ~0.1%; 5% is the contract."""
    from apex_tpu import analysis
    ep = analysis.get(ep_name)
    cost = costmodel.jaxpr_cost(ep.graph().jaxpr, xla_parity=True)
    xla = costmodel.xla_cost(_lower_jaxpr(ep.graph().jaxpr))
    assert xla["flops"] > 0
    rel = abs(cost.flops - xla["flops"]) / xla["flops"]
    assert rel < 0.05, (ep_name, cost.flops, xla["flops"], rel)
    # transcendentals ride the same ledger split XLA uses
    if xla["transcendentals"]:
        rel_t = (abs(cost.transcendentals - xla["transcendentals"])
                 / xla["transcendentals"])
        assert rel_t < 0.05
    # the cached surface returns the honest-mode count, once
    assert ep.cost() is ep.cost()
    assert ep.cost().flops > 0


def test_analytic_matches_compiled_cost_analysis():
    """Cross-validation against ``Compiled.cost_analysis()`` the way
    tests/test_remat.py consumes it — on a dot-dominated MLP fwd+bwd
    where XLA's post-fusion counter has no duplicated producers to
    overbill (the flat-optimizer / BN-heavy graphs are cross-checked
    at the Lowered stage instead; see the costmodel docstring)."""
    w1 = jnp.ones((256, 512), jnp.bfloat16)
    w2 = jnp.ones((512, 256), jnp.bfloat16)
    x = jnp.ones((64, 256), jnp.bfloat16)

    def loss(w1, w2):
        h = jnp.maximum(x @ w1, 0)
        return (h @ w2).astype(jnp.float32).sum()

    def fwdbwd(w1, w2):
        return jax.grad(loss, argnums=(0, 1))(w1, w2)

    cost = costmodel.jaxpr_cost(jax.make_jaxpr(fwdbwd)(w1, w2),
                                xla_parity=True)
    compiled = jax.jit(fwdbwd).lower(w1, w2).compile()
    xla = costmodel.xla_cost(compiled)
    rel = abs(cost.flops - xla["flops"]) / xla["flops"]
    assert rel < 0.05, (cost.flops, xla["flops"], rel)
    # dot-dominated: the matmul family carries nearly all the work.
    # 4 dots survive DCE: fwd h = x@w1 (kept for dw2), then dh = g@w2^T,
    # dw2 = h^T@g, dw1 = x^T@dh — the fwd OUTPUT dot h@w2 is dead under
    # grad-of-sum (cotangent is ones) and neither ledger counts it
    assert cost.matmul_flops > 0.9 * cost.flops
    one_dot = 2 * 64 * 256 * 512
    assert cost.matmul_flops == pytest.approx(4 * one_dot, rel=0.01)


def test_conv_flops_valid_position_counting():
    """The conv formula is XLA's: padding taps don't count, and the
    dgrad of a strided conv (dilated input) costs the same as its
    forward — NOT kernel-size times more."""
    x = jnp.ones((1, 64, 8, 8), jnp.bfloat16)
    w = jnp.ones((64, 64, 3, 3), jnp.bfloat16)

    def conv(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (2, 2), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    fwd = jax.make_jaxpr(conv)(x, w)
    (conv_eqn,) = [e for e in fwd.jaxpr.eqns
                   if e.primitive.name == "conv_general_dilated"]
    f_fwd = costmodel.conv_flops(conv_eqn)
    assert f_fwd == costmodel.xla_cost(_lower_jaxpr(fwd))["flops"]

    dgrad = jax.make_jaxpr(
        lambda x, w: jax.grad(
            lambda x: conv(x, w).astype(jnp.float32).sum())(x))(x, w)
    bwd_convs = [e for e in dgrad.jaxpr.eqns
                 if e.primitive.name == "conv_general_dilated"]
    # the dgrad conv (dilated lhs) prices like the forward
    dg = [e for e in bwd_convs if e.params.get("lhs_dilation",
                                               (1, 1)) != (1, 1)]
    assert dg and costmodel.conv_flops(dg[0]) == f_fwd
    # naive out*cin*k^2 counting would claim stride^2 = 4x more
    naive = 2 * 1 * 64 * 8 * 8 * 64 * 9
    assert costmodel.conv_flops(dg[0]) < naive / 2


def test_scan_honest_vs_parity_and_dce():
    """Honest mode multiplies scan bodies by trip count (a K-tick
    decode window costs K ticks); parity mode counts once like XLA's
    while lowering.  Dead eqns never count in either mode."""
    def stepped(x):
        def body(c, _):
            dead = jnp.tanh(c) * 3.0          # unused: DCE fodder
            del dead
            return c * 2.0 + 1.0, ()
        return jax.lax.scan(body, x, None, length=8)[0]

    closed = jax.make_jaxpr(stepped)(jnp.ones((100,)))
    honest = costmodel.jaxpr_cost(closed)
    parity = costmodel.jaxpr_cost(closed, xla_parity=True)
    assert honest.flops == 8 * parity.flops == 8 * 200
    assert honest.transcendentals == 0        # tanh chain is dead

    xla = costmodel.xla_cost(_lower_jaxpr(closed))
    # XLA's while lowering adds a couple of loop-counter flops
    assert abs(parity.flops - xla["flops"]) <= 8


def test_fp32_matmul_fraction():
    def mixed(a16, b16, a32, b32):
        return (a16 @ b16).astype(jnp.float32).sum() + (a32 @ b32).sum()

    a16 = jnp.ones((32, 32), jnp.bfloat16)
    a32 = jnp.ones((32, 32), jnp.float32)
    c = costmodel.jaxpr_cost(jax.make_jaxpr(mixed)(a16, a16, a32, a32))
    assert c.fp32_matmul_fraction() == pytest.approx(0.5)
    assert c.dominant_matmul_dtype in ("bfloat16", "float32")
    c16 = costmodel.jaxpr_cost(
        jax.make_jaxpr(lambda a, b: a @ b)(a16, a16))
    assert c16.fp32_matmul_fraction() == 0.0
    assert c16.dominant_matmul_dtype == "bfloat16"


def test_peak_flops_table_and_mfu():
    """Documented peak table: the v5-lite bf16 entry is the 197
    TFLOP/s the ROOFLINE_r5 headline was derived against; unknown
    hardware yields mfu None (absent beats fabricated)."""
    assert costmodel.peak_flops("TPU v5 lite", "bfloat16") == 197e12
    assert costmodel.peak_flops("cpu", "float32") == 100e9
    assert costmodel.peak_flops("warp drive", "bfloat16") is None

    m = costmodel.mfu(1.97e12, 1.0, "TPU v5 lite", "bfloat16")
    assert m["achieved_tflops"] == pytest.approx(1.97)
    assert m["mfu"] == pytest.approx(0.01)
    assert m["peak_tflops"] == pytest.approx(197.0)
    unknown = costmodel.mfu(1e9, 1.0, "warp drive", "bfloat16")
    assert unknown["mfu"] is None and unknown["peak_tflops"] is None
    assert unknown["achieved_tflops"] > 0


def test_roofline_r5_flops_accounting_corrected():
    """The promoted ROOFLINE_r5 math, now machine-checked — and
    CORRECTED: the hand-rolled roofline priced a resnet50 224^2
    forward at "4.1 GFLOP/img", which is the published ~4.1 GMACs
    quoted in the 2-flops-per-MAC convention the peak table uses, so
    the real forward is ~7.9 GFLOP (XLA agrees to 0.01%).  The
    hand-derived 11.4%-MFU headline divided MAC-counted work by a
    FLOP-counted peak — the measured step was actually ~2x that MFU.
    This is exactly the class of folklore error the analytic model
    exists to kill."""
    model = models.resnet50()
    params, bn = model.init(jax.random.PRNGKey(0))
    x = jnp.ones((1, 3, 224, 224))

    def fwd(p):
        out, _ = model.apply(p, x, state=bn, train=False)
        return out.sum()

    closed = jax.make_jaxpr(fwd)(params)
    c = costmodel.jaxpr_cost(closed, xla_parity=True)
    assert 7.5e9 < c.flops < 8.5e9            # ~2x the MAC count
    assert c.matmul_flops > 0.95 * c.flops
    xla = costmodel.xla_cost(_lower_jaxpr(closed))
    assert abs(c.flops - xla["flops"]) / xla["flops"] < 0.01


# -- memory plans and liveness --------------------------------------------

def test_memory_plan_fields_and_reassembly():
    f = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
    compiled = f.lower(jnp.ones((64, 64)), jnp.ones((64, 64))).compile()
    plan = memory.memory_plan(compiled)
    for key in memory.MEMORY_PLAN_FIELDS:
        assert plan[key] >= 0
    assert plan["argument_bytes"] == 2 * 64 * 64 * 4
    assert plan["peak_bytes"] == (
        plan["argument_bytes"] + plan["output_bytes"]
        + plan["temp_bytes"] + plan["generated_code_bytes"]
        - plan["alias_bytes"])


def test_memory_plan_donation_alias_credit():
    """A donated buffer's output shares its argument's storage: the
    alias credit shows up and lowers the peak."""
    def bump(c):
        return jax.tree_util.tree_map(lambda x: x + 1.0, c)

    cache = {"k": jnp.zeros((64, 64)), "v": jnp.zeros((64, 64))}
    plain = jax.jit(bump).lower(cache).compile()
    donated = jax.jit(bump, donate_argnums=(0,)).lower(cache).compile()
    p0 = memory.memory_plan(plain)
    p1 = memory.memory_plan(donated)
    assert p0["alias_bytes"] == 0
    assert p1["alias_bytes"] == 2 * 64 * 64 * 4
    assert p1["peak_bytes"] < p0["peak_bytes"]


def test_jaxpr_live_bytes_sees_through_shard_map_and_finds_peak():
    from jax.sharding import Mesh, PartitionSpec as P

    def body(x):
        big = jnp.concatenate([x, x, x])      # 3x temp, then reduced
        return big.sum(keepdims=True)

    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    mapped = jax.shard_map(body, mesh=mesh, in_specs=(P("data"),),
                           out_specs=P("data"), check_vma=False)
    lb = memory.jaxpr_live_bytes(
        jax.make_jaxpr(mapped)(jnp.ones((8, 1024))))
    # per-device: 1024-elem arg + the 3072-elem concat temp
    assert lb["argument_bytes"] == 1024 * 4
    assert lb["peak_temp_bytes"] >= 3 * 1024 * 4
    assert lb["peak_temp_bytes_by_dtype"]["float32"] \
        == lb["peak_temp_bytes"]


def test_jaxpr_live_bytes_fp32_upcast_doubles_fp32_temps():
    """The static signal MemoryBudgetRule's upcast mutation rides: the
    same pipeline with an fp32 upcast multiplies fp32 temp bytes while
    the bf16 version keeps them near zero."""
    w = jnp.ones((256, 256), jnp.bfloat16)

    def clean(x):
        h = jnp.maximum(x @ w, 0)
        return (h @ w).astype(jnp.float32).sum()

    def upcast(x):
        h = jnp.maximum((x.astype(jnp.float32) @ w.astype(jnp.float32)),
                        0)
        return (h @ w.astype(jnp.float32)).sum()

    x = jnp.ones((64, 256), jnp.bfloat16)
    lb_clean = memory.jaxpr_live_bytes(jax.make_jaxpr(clean)(x))
    lb_up = memory.jaxpr_live_bytes(jax.make_jaxpr(upcast)(x))
    f32_clean = lb_clean["peak_temp_bytes_by_dtype"].get("float32", 0)
    f32_up = lb_up["peak_temp_bytes_by_dtype"].get("float32", 0)
    assert f32_up > 2 * max(f32_clean, 1)


def test_live_array_census_and_gauges():
    from apex_tpu.observability import MetricsRegistry
    keep = jnp.ones((1024,), jnp.float32)     # noqa: F841 — stays live
    census = memory.live_array_bytes()
    assert census["bytes"] >= 4096 and census["arrays"] >= 1
    reg = MetricsRegistry()
    out = memory.record_live_arrays(reg)
    assert reg.gauge("device_live_bytes").value == out["bytes"]
    assert reg.gauge("device_live_arrays").value == out["arrays"]
    del keep


# -- entry-point surface + records ----------------------------------------

def test_entry_point_memory_plan_and_record_schema():
    """engine_prefill_slot (real lowering, donation) gives a memory
    plan with a non-zero alias credit, and the shared record builder
    emits a schema-valid ``kind: memory`` record."""
    from apex_tpu import analysis
    ep = analysis.get("engine_prefill_slot")
    plan = ep.memory_plan()
    assert plan["alias_bytes"] > 0            # donated cache aliases
    assert plan["peak_bytes"] > 0
    assert plan["analytic_live_bytes"] > 0
    assert ep.memory_plan() is plan           # cached per process

    rec = exporters.JsonlExporter.enrich(
        analysis.entry_point_memory_record(ep))
    assert exporters.validate_memory_record(rec) == []
    assert exporters.validate_telemetry_record(rec) == []
    assert rec["entry_point"] == "engine_prefill_slot"
    assert rec["flops"] > 0
