"""Chunked prefill (one full-buffer forward seeding the KV cache) must
be token-for-token interchangeable with stepping the prompt position
by position — ragged prompts, bf16 and int8 caches, both families."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu import models


def _models():
    gpt = models.GPT(models.GPTConfig(vocab_size=64, block_size=24,
                                      n_layer=2, n_head=4, n_embd=32,
                                      dropout=0.0, n_kv_head=2))
    llama = models.Llama(models.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=24,
        tie_word_embeddings=True))
    return {"gpt": gpt, "llama": llama}


@pytest.mark.parametrize("family", ["gpt", "llama"])
@pytest.mark.parametrize("cache_dtype", [None, jnp.int8])
def test_chunked_prefill_matches_step_mode(family, cache_dtype):
    m = _models()[family]
    params, _ = m.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    buf = np.zeros((3, 24), np.int32)
    for i, n in enumerate((9, 4, 12)):       # ragged prompts
        buf[i, :n] = rng.randint(0, 64, n)
    ids = jnp.asarray(buf)
    plen = jnp.asarray([9, 4, 12])
    out_c, n_c = m.generate_cached(params, ids, plen, 8,
                                   cache_dtype=cache_dtype,
                                   prefill_mode="chunked")
    out_s, n_s = m.generate_cached(params, ids, plen, 8,
                                   cache_dtype=cache_dtype,
                                   prefill_mode="step")
    np.testing.assert_array_equal(np.asarray(n_c), np.asarray(n_s))
    np.testing.assert_array_equal(np.asarray(out_c), np.asarray(out_s))


def test_prefill_mode_validation():
    m = _models()["gpt"]
    params, _ = m.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="prefill_mode"):
        m.generate_cached(params, jnp.zeros((1, 24), jnp.int32), 4, 2,
                          prefill_mode="lazy")
