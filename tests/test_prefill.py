"""Chunked prefill (one full-buffer forward seeding the KV cache) must
be token-for-token interchangeable with stepping the prompt position
by position — ragged prompts, bf16 and int8 caches, both families."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu import models


def _models():
    gpt = models.GPT(models.GPTConfig(vocab_size=64, block_size=24,
                                      n_layer=2, n_head=4, n_embd=32,
                                      dropout=0.0, n_kv_head=2))
    llama = models.Llama(models.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=24,
        tie_word_embeddings=True))
    return {"gpt": gpt, "llama": llama}


@pytest.mark.parametrize("family", ["gpt", "llama"])
@pytest.mark.parametrize("cache_dtype", [None, jnp.int8])
def test_chunked_prefill_matches_step_mode(family, cache_dtype):
    m = _models()[family]
    params, _ = m.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    buf = np.zeros((3, 24), np.int32)
    for i, n in enumerate((9, 4, 12)):       # ragged prompts
        buf[i, :n] = rng.randint(0, 64, n)
    ids = jnp.asarray(buf)
    plen = jnp.asarray([9, 4, 12])
    out_c, n_c = m.generate_cached(params, ids, plen, 8,
                                   cache_dtype=cache_dtype,
                                   prefill_mode="chunked")
    out_s, n_s = m.generate_cached(params, ids, plen, 8,
                                   cache_dtype=cache_dtype,
                                   prefill_mode="step")
    np.testing.assert_array_equal(np.asarray(n_c), np.asarray(n_s))
    np.testing.assert_array_equal(np.asarray(out_c), np.asarray(out_s))


def test_prefill_mode_validation():
    m = _models()["gpt"]
    params, _ = m.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="prefill_mode"):
        m.generate_cached(params, jnp.zeros((1, 24), jnp.int32), 4, 2,
                          prefill_mode="lazy")


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_decode_chunk_int8_matches_sequential_int8(family):
    """Chunked decode over an int8 cache must reproduce the
    single-token int8 walk exactly (identical per-position amax/127
    quantization)."""
    m = _models()[family]
    params, _ = m.init(jax.random.PRNGKey(5))
    toks = jnp.asarray(np.random.RandomState(5).randint(0, 64, (2, 10)),
                       jnp.int32)

    cache = m.init_cache(2, dtype=jnp.int8)
    hs = []
    for i in range(10):
        h, cache = m._decode_hidden(params, toks[:, i], i, cache)
        hs.append(h[:, 0])
    seq_h = jnp.stack(hs, 1)

    cache = m.init_cache(2, dtype=jnp.int8)
    for i in range(4):
        _, cache = m._decode_hidden(params, toks[:, i], i, cache)
    ch_h, ch_cache = m.decode_chunk(params, toks[:, 4:],
                                    jnp.asarray([4, 4]), cache)
    np.testing.assert_allclose(np.asarray(seq_h[:, 4:]),
                               np.asarray(ch_h), rtol=2e-5, atol=2e-5)


# tier-1 budget (PR 2): slowest tests by --durations carry the slow
# marker so a cold `-m 'not slow'` run fits the 870 s timeout
@pytest.mark.slow
def test_engine_int8_cache_matches_solo():
    from apex_tpu import serving
    m = _models()["gpt"]
    params, _ = m.init(jax.random.PRNGKey(6))
    prompt = list(np.random.RandomState(6).randint(0, 64, 6))
    eng = serving.Engine(m, params, slots=2, buf_len=24,
                         cache_dtype=jnp.int8)
    rid = eng.add_request(prompt, max_new_tokens=6)
    while eng.live():
        eng.step()
    buf = jnp.zeros((1, 24), jnp.int32).at[0, :6].set(jnp.asarray(prompt))
    solo, flen = m.generate_cached(params, buf, 6, 6,
                                   cache_dtype=jnp.int8)
    assert eng.result(rid) == list(np.asarray(solo[0, 6:int(flen[0])]))
