"""bench.py stale-record mechanism (VERDICT r3 item 6): a TPU run
persists its lines; a wedged run replays them with ``stale: true`` and
provenance, headline last, so the round artifact degrades to "last known
hardware number" instead of a CPU smoke that reads as a regression."""

import json
import re
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench


def _lines():
    return [
        {"metric": "resnet50_amp_o2_ddp_train_throughput", "value": 1830.0,
         "unit": "images/sec/chip", "vs_baseline": 11.712,
         "backend": "tpu", "ndev": 1, "arch": "TPU v5 lite"},
        {"metric": "ddp_allreduce_bandwidth", "value": 12.0,
         "unit": "GB/s/chip", "vs_baseline": None, "backend": "tpu",
         "ndev": 1, "arch": "TPU v5 lite", "note": "chunked-psum path"},
    ]


def test_save_load_roundtrip(tmp_path):
    p = str(tmp_path / "rec.json")
    bench.save_tpu_record(_lines(), path=p, now="2026-07-30T04:55:00Z")
    rec = bench.load_tpu_record(path=p)
    assert rec["recorded_at"] == "2026-07-30T04:55:00Z"
    assert rec["lines"] == [
        {**ln, "recorded_at": "2026-07-30T04:55:00Z"} for ln in _lines()]


def test_partial_run_merges_without_clobbering_headline(tmp_path):
    """A later partial run (headline config hung) must not evict the
    previous headline from the record — else a wedge replay ends on the
    wrong metric."""
    p = str(tmp_path / "rec.json")
    bench.save_tpu_record(_lines(), path=p, now="2026-07-30T04:55:00Z")
    bench.save_tpu_record(
        [{"metric": "ddp_allreduce_bandwidth", "value": 14.0,
          "unit": "GB/s/chip", "vs_baseline": None, "backend": "tpu",
          "ndev": 1, "arch": "TPU v5 lite"}],
        path=p, now="2026-07-31T08:00:00Z")
    rec = bench.load_tpu_record(path=p)
    by_metric = {ln["metric"]: ln for ln in rec["lines"]}
    # headline carried over with its ORIGINAL timestamp
    head = by_metric[bench.HEADLINE_METRIC]
    assert head["value"] == 1830.0
    assert head["recorded_at"] == "2026-07-30T04:55:00Z"
    # updated metric replaced, stamped with the new time
    assert by_metric["ddp_allreduce_bandwidth"]["value"] == 14.0
    assert (by_metric["ddp_allreduce_bandwidth"]["recorded_at"]
            == "2026-07-31T08:00:00Z")
    # replay still ends on the headline, with per-line provenance
    stale = bench.stale_lines(rec)
    assert stale[-1]["metric"] == bench.HEADLINE_METRIC
    assert stale[-1]["stale_recorded_at"] == "2026-07-30T04:55:00Z"
    assert stale[0]["stale_recorded_at"] == "2026-07-31T08:00:00Z"


def test_save_empty_is_noop(tmp_path):
    p = str(tmp_path / "rec.json")
    bench.save_tpu_record([], path=p)
    assert not os.path.exists(p)
    assert bench.load_tpu_record(path=p) is None


def test_load_garbage_returns_none(tmp_path):
    p = str(tmp_path / "rec.json")
    with open(p, "w") as f:
        f.write("{not json")
    assert bench.load_tpu_record(path=p) is None


def test_stale_lines_annotate_and_order_headline_last(tmp_path):
    p = str(tmp_path / "rec.json")
    bench.save_tpu_record(_lines(), path=p, now="2026-07-30T04:55:00Z")
    out = bench.stale_lines(bench.load_tpu_record(path=p))
    assert [ln["metric"] for ln in out] == [
        "ddp_allreduce_bandwidth", bench.HEADLINE_METRIC]
    for ln in out:
        assert ln["stale"] is True
        assert ln["stale_recorded_at"] == "2026-07-30T04:55:00Z"
        assert ln["note"].startswith(
            "STALE REPLAY — NOT A FRESH MEASUREMENT")
        assert re.search(r"captured \d+d ago", ln["note"])
        assert json.loads(json.dumps(ln)) == ln    # JSON-serializable
    # original note preserved after the stale prefix
    assert "chunked-psum path" in out[0]["note"]
    # values untouched — this is a replay, not a new measurement
    assert out[1]["value"] == 1830.0
    assert out[1]["vs_baseline"] == 11.712


def test_comm_bench_record_schema():
    """The --comm microbench record contract: a record carrying
    ``comm_topology`` must state the per-level wire bytes, compression
    flag and level widths, and fresh ``grad_allreduce_*`` metrics must
    carry the topology fields at all (tests/ci/check_bench_schema.py
    rides the same validator)."""
    from apex_tpu.observability import exporters
    good = exporters.JsonlExporter.enrich({
        "metric": "grad_allreduce_hier_step_time", "value": 31.0,
        "unit": "ms", "vs_baseline": None, "backend": "cpu", "ndev": 8,
        "arch": "cpu", "comm_topology": "hierarchical",
        "compress": False, "ici_size": 4, "dcn_size": 2,
        "wire_bytes": 6_000_000, "ici_wire_bytes": 5_000_000,
        "dcn_wire_bytes": 1_000_000})
    assert exporters.validate_bench_record(good) == []
    # a grad_allreduce line with no topology fields is invalid fresh...
    bare = {k: v for k, v in good.items()
            if k not in ("comm_topology", "compress", "ici_size",
                         "dcn_size", "wire_bytes", "ici_wire_bytes",
                         "dcn_wire_bytes")}
    assert any("comm_topology" in e
               for e in exporters.validate_bench_record(bare))
    # ...but a stale replay of a pre-topology record is exempt
    assert exporters.validate_bench_record(dict(bare, stale=True)) == []
    # bad values flag field-by-field
    assert any("comm_topology" in e for e in
               exporters.validate_bench_record(
                   dict(good, comm_topology="diagonal")))
    assert any("dcn_wire_bytes" in e for e in
               exporters.validate_bench_record(
                   dict(good, dcn_wire_bytes=-1)))
    assert any("compress" in e for e in
               exporters.validate_bench_record(
                   dict(good, compress="yes")))
    assert any("ici_size" in e for e in
               exporters.validate_bench_record(dict(good, ici_size=0)))


def test_committed_record_is_valid():
    """The repo ships a seeded record (r3's manual pre-wedge measurement)
    so even a whole round of wedge leaves a hardware line."""
    rec = bench.load_tpu_record()
    assert rec is not None
    stale = bench.stale_lines(rec)
    assert stale[-1]["metric"] == bench.HEADLINE_METRIC
    assert stale[-1]["backend"] == "tpu"


def test_zero_leg_device_gate_is_bare_runtime_error():
    """The --comm ZeRO legs skip (not fail) on a 1-ambient-device host:
    the gate raises a BARE RuntimeError — the same skippable class the
    graph-lint entry points use — which bench catches with an exact
    type check so real failures still propagate."""
    import pytest
    with pytest.raises(RuntimeError, match="no shard split") as ei:
        bench.require_shard_devices(1)
    assert type(ei.value) is RuntimeError      # bare, not a subclass
    # 2+ devices pass straight through
    bench.require_shard_devices(2)
    bench.require_shard_devices(8)
