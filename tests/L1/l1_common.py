"""Shared L1 runner: train ResNet-18 under one amp config and record the
exact loss trajectory + a final-parameter digest.

The apex_tpu analogue of the reference's instrumented L1 trainer
(tests/L1/common/main_amp.py: run_info_dict of per-iteration Loss/Speed,
keyed by config) — same discipline, TPU-shaped: one deterministic synthetic
dataset, two dispatch paths (Pallas kernels vs pure jnp), bitwise
comparison where dtypes make it meaningful (compare.py:35-64).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

import numpy as np


def train_one(opt_level: str, loss_scale: Optional[str],
              keep_bn: Optional[str], pallas: bool, iters: int = 100,
              batch: int = 16, image: int = 32, arch: str = "resnet18",
              lr: float = 1e-3, nbatches: int = 10):
    """Returns (loss_trajectory float32 array, sha256 of final params)."""
    import jax
    import jax.numpy as jnp
    from apex_tpu import amp, models, optimizers
    from apex_tpu.nn import functional as F

    # "prod" reproduces the production TPU dispatch (fused optimizer /
    # multi-tensor / flash kernels Pallas, BN jnp) rather than the
    # parity-test-only FORCE=1 mode: Adam turns any sub-ulp grad
    # difference near zero into a full ±lr step, so bitwise trajectories
    # require the fwd/bwd to be the *same* XLA program in both runs
    old = {k: os.environ.pop(k, None)
           for k in ("APEX_TPU_FORCE_PALLAS", "APEX_TPU_DISABLE_PALLAS")}
    if pallas:
        os.environ["APEX_TPU_FORCE_PALLAS"] = "prod"
    else:
        os.environ["APEX_TPU_DISABLE_PALLAS"] = "1"
    env_key = ("APEX_TPU_FORCE_PALLAS" if pallas
               else "APEX_TPU_DISABLE_PALLAS")
    try:
        model, optimizer = amp.initialize(
            getattr(models, arch)(num_classes=10),
            optimizers.FusedAdam(lr=lr), opt_level=opt_level,
            loss_scale=loss_scale, keep_batchnorm_fp32=keep_bn,
            verbosity=0, hard_override=True)
        params, bn_state = model.init(jax.random.PRNGKey(0))
        opt_state = optimizer.init(params)

        rng = np.random.RandomState(0)
        xs = jnp.asarray(rng.randn(nbatches, batch, 3, image, image),
                         jnp.float32)
        ys = jnp.asarray(rng.randint(0, 10, (nbatches, batch)), jnp.int32)

        def step(params, bn_state, opt_state, x, y):
            def loss_fn(p):
                out, s = model.apply(p, x, state=bn_state, train=True)
                return F.cross_entropy(out, y), s

            loss, new_bn, grads = amp.scaled_grad(loss_fn, params,
                                                  opt_state, has_aux=True)
            params, opt_state, info = optimizer.step(params, opt_state,
                                                     grads)
            return params, new_bn, opt_state, loss

        jstep = jax.jit(step)
        traj = np.zeros((iters,), np.float32)
        for i in range(iters):
            params, bn_state, opt_state, loss = jstep(
                params, bn_state, opt_state, xs[i % nbatches],
                ys[i % nbatches])
            traj[i] = np.float32(float(loss))
        digest = hashlib.sha256()
        for leaf in jax.tree_util.tree_leaves(params):
            digest.update(np.asarray(leaf).tobytes())
        return traj, digest.hexdigest()
    finally:
        os.environ.pop(env_key, None)
        for k, v in old.items():
            if v is not None:
                os.environ[k] = v


# the reference driver's matrix (tests/L1/common/run_test.sh:64-135):
# {O0..O3} x {default, 1.0, 128.0, dynamic} x {keep_batchnorm_fp32 unset/
# True/False}
FULL_MATRIX = [
    (ol, ls, kbn)
    for ol in ("O0", "O1", "O2", "O3")
    for ls in (None, "1.0", "128.0", "dynamic")
    for kbn in (None, "True", "False")
]


def is_fp32_config(opt_level: str) -> bool:
    """Configs whose whole numeric path is fp32 — where the reference
    demands bitwise equality between extension and Python paths."""
    return opt_level == "O0"
