"""Full L1 cross-product driver — the apex_tpu port of the reference's
tests/L1/common/run_test.sh + compare.py.

Runs ResNet-18 for >=100 deterministic iterations under the full
{O0..O3} x {loss-scale} x {keep_batchnorm_fp32} matrix, once with Pallas
kernels and once with the pure-jnp fallback, then asserts:

- **bitwise-equal** loss trajectories and final-parameter digests for the
  fp32 configs (compare.py:35-64's discipline), and
- tolerance-tier agreement for half configs (bf16/fp16 kernels reassociate
  reductions; bitwise is unattainable there, documented in SURVEY §7).

Meant to run compiled on TPU (~fast steps, compile-dominated); works on
the CPU mesh with --iters/--configs trimmed.  Writes a JSON log for the
round artifacts.

  python tests/L1/run_l1.py --iters 100 --out artifacts/L1_r3.json
"""

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])

from tests.L1.l1_common import FULL_MATRIX, is_fp32_config, train_one


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--image", type=int, default=32)
    ap.add_argument("--configs", type=int, default=0,
                    help="run only the first N configs (0 = all 48)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    matrix = FULL_MATRIX[:args.configs] if args.configs else FULL_MATRIX
    results, failures = [], []
    for (ol, ls, kbn) in matrix:
        key = f"{ol}_ls{ls}_kbn{kbn}"
        t0 = time.time()
        ref_traj, ref_dig = train_one(ol, ls, kbn, pallas=False,
                                      iters=args.iters, batch=args.batch,
                                      image=args.image)
        tst_traj, tst_dig = train_one(ol, ls, kbn, pallas=True,
                                      iters=args.iters, batch=args.batch,
                                      image=args.image)
        bitwise = (ref_traj.tobytes() == tst_traj.tobytes()
                   and ref_dig == tst_dig)
        maxdiff = float(np.max(np.abs(ref_traj - tst_traj)))
        ok = True
        if is_fp32_config(ol) and not bitwise:
            ok = False
            failures.append(f"{key}: fp32 config not bitwise "
                            f"(maxdiff {maxdiff})")
        if not bitwise and maxdiff > 2e-2 * max(1.0, abs(ref_traj).max()):
            ok = False
            failures.append(f"{key}: trajectories diverge (max {maxdiff})")
        if not np.all(np.isfinite(ref_traj)):
            ok = False
            failures.append(f"{key}: non-finite losses")
        if args.iters >= 50 and ref_traj[-1] >= ref_traj[0]:
            ok = False
            failures.append(f"{key}: no training progress")
        results.append({"config": key, "bitwise": bitwise,
                        "max_traj_diff": maxdiff, "ok": ok,
                        "loss_first": float(ref_traj[0]),
                        "loss_last": float(ref_traj[-1]),
                        "wall_s": round(time.time() - t0, 1)})
        print(json.dumps(results[-1]), flush=True)

    summary = {"total": len(results),
               "bitwise": sum(r["bitwise"] for r in results),
               "ok": sum(r["ok"] for r in results),
               "failures": failures}
    print(json.dumps(summary))
    if args.out:
        import os
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            json.dump({"results": results, "summary": summary}, f,
                      indent=1)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
