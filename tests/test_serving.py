"""Continuous-batching engine: staggered arrivals must be token-for-
token what generate_cached produces for each request alone; EOS frees
slots that are then reclaimed."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from apex_tpu import models, serving


def _gpt(seed=0):
    m = models.GPT(models.GPTConfig(vocab_size=64, block_size=24,
                                    n_layer=2, n_head=4, n_embd=32,
                                    dropout=0.0, n_kv_head=2))
    params, _ = m.init(jax.random.PRNGKey(seed))
    return m, params


def _solo(m, params, prompt, n):
    buf = jnp.zeros((1, 24), jnp.int32).at[0, :len(prompt)].set(
        jnp.asarray(prompt))
    out, flen = m.generate_cached(params, buf, len(prompt), n)
    return list(np.asarray(out[0, len(prompt):int(flen[0])]))


def test_staggered_requests_match_solo_decoding():
    m, params = _gpt()
    eng = serving.Engine(m, params, slots=3, buf_len=24)
    rng = np.random.RandomState(0)
    pa = list(rng.randint(0, 64, 6))
    pb = list(rng.randint(0, 64, 4))
    pc = list(rng.randint(0, 64, 9))

    ra = eng.add_request(pa, max_new_tokens=8)
    for _ in range(3):
        eng.step()                       # A runs alone for 3 steps
    rb = eng.add_request(pb, max_new_tokens=10)
    for _ in range(2):
        eng.step()
    rc = eng.add_request(pc, max_new_tokens=5)
    while eng.live():
        eng.step()

    assert eng.result(ra) == _solo(m, params, pa, 8)
    assert eng.result(rb) == _solo(m, params, pb, 10)
    assert eng.result(rc) == _solo(m, params, pc, 5)


def test_eos_frees_slot_and_reuse_is_clean():
    m, params = _gpt(1)
    rng = np.random.RandomState(1)
    pa = list(rng.randint(0, 64, 5))
    # find what token A emits first, use it as A's EOS
    first = _solo(m, params, pa, 1)[0]

    eng = serving.Engine(m, params, slots=1, buf_len=24)
    ra = eng.add_request(pa, max_new_tokens=8, eos_token_id=first)
    out = eng.step()
    assert out == {ra: [first]}
    assert eng.live() == 0               # EOS -> slot freed
    assert eng.result(ra) == [first]

    # slot reuse: a fresh request on the recycled slot matches solo
    pb = list(rng.randint(0, 64, 7))
    rb = eng.add_request(pb, max_new_tokens=6)
    while eng.live():
        eng.step()
    assert eng.result(rb) == _solo(m, params, pb, 6)


def test_capacity_and_validation():
    m, params = _gpt(2)
    eng = serving.Engine(m, params, slots=1, buf_len=24)
    eng.add_request([1, 2, 3], max_new_tokens=4)
    with pytest.raises(RuntimeError, match="no free slot"):
        eng.add_request([4, 5], max_new_tokens=4)
    with pytest.raises(ValueError, match="prompt length"):
        serving.Engine(m, params, slots=1, buf_len=8).add_request(
            list(range(8)), max_new_tokens=2)


def test_llama_engine_smoke():
    m = models.Llama(models.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=24,
        tie_word_embeddings=True))
    params, _ = m.init(jax.random.PRNGKey(3))
    eng = serving.Engine(m, params, slots=2, buf_len=24)
    prompt = list(np.random.RandomState(4).randint(0, 64, 5))
    rid = eng.add_request(prompt, max_new_tokens=6)
    while eng.live():
        eng.step()
    assert eng.result(rid) == _solo(m, params, prompt, 6)


def test_engine_rejects_droppy_moe_and_defaults_cache_dtype():
    from apex_tpu.models import Mixtral, MixtralConfig
    kw = dict(vocab_size=64, hidden_size=32, intermediate_size=64,
              num_hidden_layers=1, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=16,
              tie_word_embeddings=True, num_local_experts=4,
              num_experts_per_tok=2)
    droppy = Mixtral(MixtralConfig(capacity_factor=2.0, **kw))
    dparams, _ = droppy.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="dropless"):
        serving.Engine(droppy, dparams, slots=2, buf_len=16)
    # dropless Mixtral is admitted
    ok = Mixtral(MixtralConfig(capacity_factor=4.0, **kw))
    oparams, _ = ok.init(jax.random.PRNGKey(0))
    serving.Engine(ok, oparams, slots=2, buf_len=16)

    # cache dtype follows the params (generate_cached's default)
    m, params = _gpt(7)
    bf16 = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if x.dtype == jnp.float32 else x, params)
    eng = serving.Engine(m, bf16, slots=1, buf_len=24)
    assert eng.cache["0"]["k"].dtype == jnp.bfloat16


def test_speculative_engine_matches_solo_decoding():
    """Continuous batching + speculative decoding composed: staggered
    arrivals, every request token-for-token equal to its solo greedy
    decode, advancing up to gamma+1 tokens per tick."""
    m, params = _gpt(10)
    draft, dparams = _gpt(11)        # different weights, same vocab
    eng = serving.Engine(m, params, slots=2, buf_len=24,
                         draft=draft, draft_params=dparams, gamma=3)
    rng = np.random.RandomState(10)
    pa = list(rng.randint(0, 64, 6))
    pb = list(rng.randint(0, 64, 4))
    ra = eng.add_request(pa, max_new_tokens=9)
    eng.step()
    rb = eng.add_request(pb, max_new_tokens=7)
    steps = 0
    while eng.live():
        eng.step()
        steps += 1
        assert steps < 40
    assert eng.result(ra) == _solo(m, params, pa, 9)
    assert eng.result(rb) == _solo(m, params, pb, 7)


def test_speculative_engine_perfect_draft_advances_fast():
    """Draft == target: every proposal accepted, so a request finishes
    in ~ceil(new/(gamma+1)) ticks instead of `new` ticks."""
    m, params = _gpt(12)
    eng = serving.Engine(m, params, slots=1, buf_len=24,
                         draft=m, draft_params=params, gamma=3)
    prompt = list(np.random.RandomState(12).randint(0, 64, 5))
    rid = eng.add_request(prompt, max_new_tokens=8)
    ticks = 0
    while eng.live():
        eng.step()
        ticks += 1
    assert ticks <= 3                # 8 tokens / (gamma+1)=4 -> 2-3
    assert eng.result(rid) == _solo(m, params, prompt, 8)


def test_speculative_engine_eos_mid_chunk():
    """EOS crossed inside an accepted run truncates the request at the
    EOS token even though the chunk carried tokens past it."""
    m, params = _gpt(13)
    prompt = list(np.random.RandomState(13).randint(0, 64, 5))
    solo = _solo(m, params, prompt, 8)
    eos = solo[1]                    # second greedy token as EOS
    eng = serving.Engine(m, params, slots=1, buf_len=24,
                         draft=m, draft_params=params, gamma=4)
    rid = eng.add_request(prompt, max_new_tokens=8, eos_token_id=eos)
    while eng.live():
        eng.step()
    assert eng.result(rid) == solo[:2]


def test_submit_queue_and_sampled_engine():
    m, params = _gpt(14)
    eng = serving.Engine(m, params, slots=1, buf_len=24)
    rng = np.random.RandomState(14)
    pa = list(rng.randint(0, 64, 5))
    pb = list(rng.randint(0, 64, 4))
    ra = eng.submit(pa, max_new_tokens=4)   # takes the slot
    rb = eng.submit(pb, max_new_tokens=3)   # queues
    assert eng.live() == 1
    while eng.live() or eng._waiting:
        eng.step()
    assert eng.result(ra) == _solo(m, params, pa, 4)
    assert eng.result(rb) == _solo(m, params, pb, 3)

    # sampled engine: tokens vary with rng, stay in-range, finite run
    se = serving.Engine(m, params, slots=2, buf_len=24,
                        temperature=1.0, top_k=8,
                        rng=jax.random.PRNGKey(5))
    r1 = se.add_request(pa, max_new_tokens=5)
    while se.live():
        se.step()
    toks = se.result(r1)
    assert len(toks) == 5 and all(0 <= t < 64 for t in toks)
    with pytest.raises(NotImplementedError, match="speculative"):
        serving.Engine(m, params, slots=1, buf_len=24,
                       temperature=0.5, draft=m, draft_params=params)


def test_prefix_sharing_matches_solo_decoding():
    """Prefix pool: requests sharing a registered prefix admit via KV
    splice + suffix-only prefill and must still be token-for-token
    equal to their solo decode; non-matching prompts take the full
    prefill path untouched."""
    m, params = _gpt(31)
    rng = np.random.RandomState(31)
    sys_prefix = list(rng.randint(0, 64, 7))
    eng = serving.Engine(m, params, slots=3, buf_len=24, prefix_pool=2,
                         prefix_chunk=4)
    eng.register_prefix(sys_prefix)

    prompts = [sys_prefix + list(rng.randint(0, 64, k))
               for k in (1, 3, 6)]            # shared prefix, suffixes
    prompts.append(list(rng.randint(0, 64, 5)))   # no match
    prompts.append(list(sys_prefix))              # exact-match prompt
    rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    while eng.live() or eng._waiting:
        eng.step()
    for rid, p in zip(rids, prompts):
        assert eng.result(rid) == _solo(m, params, p, 6), p
    assert eng.prefix_hits == 4               # all but the non-match

    # slot reuse after a spliced request stays clean (stale pool KV
    # beyond prompt_len must never leak into a later occupant)
    extra = list(rng.randint(0, 64, 9))
    r2 = eng.submit(extra, max_new_tokens=5)
    while eng.live():
        eng.step()
    assert eng.result(r2) == _solo(m, params, extra, 5)


@pytest.mark.slow
def test_prefix_sharing_with_speculative_engine():
    """The splice covers BOTH caches (target + draft): a speculative
    engine with a registered prefix must stay exactly solo-greedy."""
    m, params = _gpt(33)
    draft, dparams = _gpt(34)
    eng = serving.Engine(m, params, slots=2, buf_len=24, draft=draft,
                         draft_params=dparams, gamma=3, prefix_pool=1,
                         prefix_chunk=4)
    rng = np.random.RandomState(33)
    pref = list(rng.randint(0, 64, 6))
    eng.register_prefix(pref)
    pa = pref + list(rng.randint(0, 64, 3))
    pb = pref + list(rng.randint(0, 64, 1))
    ra = eng.submit(pa, max_new_tokens=7)
    rb = eng.submit(pb, max_new_tokens=5)
    steps = 0
    while eng.live():
        eng.step()
        steps += 1
        assert steps < 40
    assert eng.prefix_hits == 2
    assert eng.result(ra) == _solo(m, params, pa, 7)
    assert eng.result(rb) == _solo(m, params, pb, 5)


def test_prefix_sharing_with_int8_kv_cache():
    """The splice tree_maps over whatever the cache holds — including
    int8 buffers plus their scale sidecars; parity vs the solo int8
    decode must hold."""
    m, params = _gpt(35)
    rng = np.random.RandomState(35)
    pref = list(rng.randint(0, 64, 6))
    eng = serving.Engine(m, params, slots=2, buf_len=24,
                         cache_dtype=jnp.int8, prefix_pool=1,
                         prefix_chunk=4)
    eng.register_prefix(pref)
    prompts = [pref + list(rng.randint(0, 64, k)) for k in (2, 5)]
    rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
    while eng.live():
        eng.step()
    assert eng.prefix_hits == 2
    for rid, p in zip(rids, prompts):
        buf = jnp.zeros((1, 24), jnp.int32).at[0, :len(p)].set(
            jnp.asarray(p))
        out, fl = m.generate_cached(params, buf, len(p), 6,
                                    cache_dtype=jnp.int8)
        solo = list(np.asarray(out[0, len(p):int(fl[0])]))
        assert eng.result(rid) == solo, p


def test_sampled_requests_are_batch_independent():
    """Per-request RNG streams: the same request (same ``seed``, same
    base rng) must draw the same tokens whether it runs alone or shares
    the engine with other traffic and arrives late through the queue —
    the shared-stream caveat the r4 advisor flagged is gone."""
    m, params = _gpt(36)
    rng = np.random.RandomState(36)
    px = list(rng.randint(0, 64, 5))

    ea = serving.Engine(m, params, slots=2, buf_len=24,
                        temperature=1.0, top_k=16,
                        rng=jax.random.PRNGKey(3))
    ra = ea.add_request(px, max_new_tokens=6, seed=7)
    while ea.live():
        ea.step()

    eb = serving.Engine(m, params, slots=2, buf_len=24,
                        temperature=1.0, top_k=16,
                        rng=jax.random.PRNGKey(3))
    # different co-tenants + delayed queued admission for X
    eb.submit(list(rng.randint(0, 64, 8)), max_new_tokens=9, seed=1)
    eb.submit(list(rng.randint(0, 64, 3)), max_new_tokens=4, seed=2)
    rx = eb.submit(px, max_new_tokens=6, seed=7)     # queues
    steps = 0
    while eb.live() or eb.stats()["waiting"]:
        eb.step()
        steps += 1
        assert steps < 60
    assert eb.result(rx) == ea.result(ra)
    # seed rejected where it is meaningless (validated at submission,
    # not deferred into a later step()'s queue drain)
    from apex_tpu.models import T5, T5Config
    t5 = T5(T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                     num_layers=1, num_heads=4, dropout_rate=0.0,
                     relative_attention_num_buckets=8,
                     relative_attention_max_distance=16))
    t5p, _ = t5.init(jax.random.PRNGKey(0))
    s2s = serving.Seq2SeqEngine(t5, t5p, slots=1, src_len=8,
                                max_new_cap=4)
    with pytest.raises(ValueError, match="seed"):
        s2s.submit([3, 4], max_new_tokens=2, seed=1)


def test_per_request_temperature_override():
    """On a sampled engine: a temperature=0.0 request decodes greedily
    (== its solo greedy decode) while sampled co-tenants keep drawing;
    greedy/speculative engines reject the override at submission."""
    m, params = _gpt(38)
    # sharp logits (cf. test_mixtral.py::_model): with the realistic
    # flat 0.02-init logits the Gumbel noise dominates every
    # temperature, making T indistinguishable — the override's effect
    # needs real decision margins to show
    params = dict(params)
    params["wte"] = {"weight": params["wte"]["weight"] / 0.02}
    rng = np.random.RandomState(38)
    pg = list(rng.randint(0, 64, 5))
    eng = serving.Engine(m, params, slots=2, buf_len=24,
                         temperature=1.0, top_k=16,
                         rng=jax.random.PRNGKey(4))
    rg = eng.add_request(pg, max_new_tokens=6, temperature=0.0)
    rs = eng.add_request(list(rng.randint(0, 64, 4)),
                         max_new_tokens=8)
    while eng.live():
        eng.step()
    assert eng.result(rg) == _solo(m, params, pg, 6)   # greedy row
    toks = eng.result(rs)
    assert len(toks) == 8 and all(0 <= t < 64 for t in toks)
    # same seed, near-greedy vs scorching temperature: the sharp
    # logits make T=0.05 track the argmax while T=50 flattens the
    # top-k to near-uniform — the sequences must diverge
    r1 = eng.add_request(pg, max_new_tokens=6, seed=9,
                         temperature=0.05)
    while eng.live():
        eng.step()
    r2 = eng.add_request(pg, max_new_tokens=6, seed=9,
                         temperature=50.0)
    while eng.live():
        eng.step()
    assert eng.result(r1) != eng.result(r2)

    greedy_eng = serving.Engine(m, params, slots=1, buf_len=24)
    with pytest.raises(ValueError, match="temperature"):
        greedy_eng.add_request(pg, max_new_tokens=2, temperature=0.5)
    with pytest.raises(ValueError, match="temperature must be"):
        eng.add_request(pg, max_new_tokens=2, temperature=-1.0)


def test_prefix_splice_boundary_lengths():
    """Edges of the splice arithmetic: prompt at buf_len-1 (max legal),
    suffix exactly one chunk, suffix of 1 token, and a prefix whose
    length is not a chunk multiple (slide-back overlap recompute)."""
    m, params = _gpt(37)
    eng = serving.Engine(m, params, slots=2, buf_len=24, prefix_pool=1,
                         prefix_chunk=4)
    rng = np.random.RandomState(37)
    pref = list(rng.randint(0, 64, 10))       # not a multiple of 4
    eng.register_prefix(pref)
    cases = [
        pref + list(rng.randint(0, 64, 13)),  # prompt = 23 = buf-1
        pref + list(rng.randint(0, 64, 4)),   # suffix == one chunk
        pref + list(rng.randint(0, 64, 1)),   # suffix == 1
    ]
    rids = [eng.submit(p, max_new_tokens=2) for p in cases]
    while eng.live() or eng.stats()["waiting"]:
        eng.step()
    assert eng.prefix_hits == 3
    for rid, p in zip(rids, cases):
        assert eng.result(rid) == _solo(m, params, p, 2), len(p)


def test_prefix_pool_validation_and_longest_match():
    m, params = _gpt(32)
    eng = serving.Engine(m, params, slots=1, buf_len=24, prefix_pool=1)
    with pytest.raises(RuntimeError, match="prefix_pool=0"):
        serving.Engine(m, params, slots=1, buf_len=24).register_prefix(
            [1, 2])
    eng.register_prefix([5, 6, 7])
    with pytest.raises(RuntimeError, match="pool full"):
        eng.register_prefix([1])
    with pytest.raises(ValueError, match="prefix_chunk"):
        serving.Engine(m, params, slots=1, buf_len=24, prefix_pool=1,
                       prefix_chunk=0)
    # longest-match selection among registered prefixes
    e2 = serving.Engine(m, params, slots=1, buf_len=24, prefix_pool=2)
    e2.register_prefix([5, 6])
    e2.register_prefix([5, 6, 7, 8])
    assert e2._match_prefix([5, 6, 7, 8, 9]) == (1, 4)
    assert e2._match_prefix([5, 6, 9]) == (0, 2)
    assert e2._match_prefix([9, 5, 6]) == (None, 0)


def test_rolling_engine_matches_solo_rolling_decode():
    """Sliding-window serving with O(window) KV memory: the engine's
    ring caches must reproduce the solo rolling decode token-for-token
    — prompts longer than the window, generation crossing several
    wrap-arounds, staggered arrivals."""
    from apex_tpu.models import Llama, LlamaConfig
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=32, sliding_window=5,
                      tie_word_embeddings=True)
    m = Llama(cfg)
    params, _ = m.init(jax.random.PRNGKey(40))
    # tie-free argmax margins for the parity assertion (cf. _model in
    # test_mixtral.py)
    params["embed_tokens"] = {
        "weight": params["embed_tokens"]["weight"] / 0.02}
    eng = serving.Engine(m, params, slots=2, buf_len=32, rolling=True)
    # the memory claim is real: ring width == window, not buf_len
    assert jax.tree_util.tree_leaves(eng.cache)[0].shape[2] == 5

    rng = np.random.RandomState(40)
    pa = list(rng.randint(0, 97, 9))       # prompt > window
    pb = list(rng.randint(0, 97, 3))       # prompt < window
    ra = eng.add_request(pa, max_new_tokens=12)
    eng.step()
    rb = eng.add_request(pb, max_new_tokens=14)
    while eng.live():
        eng.step()

    def solo(p, n):
        buf = jnp.zeros((1, 32), jnp.int32).at[0, :len(p)].set(
            jnp.asarray(p))
        out, fl = m.generate_cached(params, buf, len(p), n,
                                    rolling_cache=True)
        return list(np.asarray(out[0, len(p):int(fl[0])]))

    assert eng.result(ra) == solo(pa, 12)
    assert eng.result(rb) == solo(pb, 14)


def test_rolling_engine_validation():
    from apex_tpu.models import Llama, LlamaConfig
    m, params = _gpt(41)
    with pytest.raises(ValueError, match="sliding_window"):
        serving.Engine(m, params, slots=1, buf_len=24, rolling=True)
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=32, sliding_window=5,
                      tie_word_embeddings=True)
    lm = Llama(cfg)
    lp, _ = lm.init(jax.random.PRNGKey(41))
    with pytest.raises(NotImplementedError, match="prefix_pool"):
        serving.Engine(lm, lp, slots=1, buf_len=32, rolling=True,
                       prefix_pool=1)
    with pytest.raises(NotImplementedError, match="speculative"):
        serving.Engine(lm, lp, slots=1, buf_len=32, rolling=True,
                       draft=lm, draft_params=lp)
    with pytest.raises(NotImplementedError, match="int8"):
        serving.Engine(lm, lp, slots=1, buf_len=32, rolling=True,
                       cache_dtype=jnp.int8)


@pytest.mark.slow
def test_seq2seq_engine_matches_solo_t5_generate():
    """Encoder-decoder continuous batching: each request's tokens must
    equal T5.generate run for it alone (its own source, its own
    attention mask), under staggered arrivals, mixed source lengths,
    and slot reuse."""
    from apex_tpu.models import T5, T5Config
    cfg = T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                   num_layers=2, num_heads=4, dropout_rate=0.0,
                   relative_attention_num_buckets=8,
                   relative_attention_max_distance=16)
    m = T5(cfg)
    params, _ = m.init(jax.random.PRNGKey(50))
    eng = serving.Seq2SeqEngine(m, params, slots=2, src_len=12,
                                max_new_cap=10)
    rng = np.random.RandomState(50)

    def solo(src, n):
        ids = jnp.zeros((1, 12), jnp.int32).at[0, :len(src)].set(
            jnp.asarray(src))
        mask = (jnp.arange(12) < len(src)).astype(
            jnp.float32)[None, :]
        out = m.generate(params, ids, n, attention_mask=mask)
        return list(np.asarray(out[0]))

    pa = list(rng.randint(2, 64, 11))
    pb = list(rng.randint(2, 64, 4))
    pc = list(rng.randint(2, 64, 7))
    ra = eng.add_request(pa, max_new_tokens=9)
    eng.step()
    rb = eng.add_request(pb, max_new_tokens=5)     # staggered
    rc = eng.submit(pc, max_new_tokens=7)          # queues (2 slots)
    steps = 0
    while eng.live() or eng._waiting:
        eng.step()
        steps += 1
        assert steps < 40
    assert eng.result(ra) == solo(pa, 9)
    assert eng.result(rb) == solo(pb, 5)
    assert eng.result(rc) == solo(pc, 7)           # reused slot
    assert eng.stats()["finished"] == 3

    # per-request EOS frees the slot early and is recorded
    first = solo(pa, 1)[0]
    r4 = eng.add_request(pa, max_new_tokens=8, eos_token_id=first)
    out = eng.step()
    assert out[r4] == [first] and eng.live() == 0
    with pytest.raises(ValueError, match="source length"):
        eng.add_request(list(range(13)), max_new_tokens=2)


# tier-1 budget (PR 2): slowest tests by --durations carry the slow
# marker so a cold `-m 'not slow'` run fits the 870 s timeout
@pytest.mark.slow
def test_queue_stress_arrivals_exceed_slots_fifo_fair():
    """VERDICT r4 item 6: arrivals >> slots.  20 requests of mixed
    lengths through 3 slots — every result must still equal its solo
    decode (batch-independence under heavy churn), the queue must fully
    drain, and admission must be FIFO: no request may start decoding
    before an earlier-submitted one (fairness — a later short request
    must not jump a waiting long one)."""
    m, params = _gpt(21)
    eng = serving.Engine(m, params, slots=3, buf_len=24)
    rng = np.random.RandomState(21)
    reqs = []                    # rid -> (prompt, n)
    for i in range(20):
        prompt = list(rng.randint(0, 64, int(rng.randint(2, 10))))
        n = int(rng.randint(1, 8))
        rid = eng.submit(prompt, max_new_tokens=n)
        reqs.append((rid, prompt, n))
    assert eng.live() == 3 and len(eng._waiting) == 17

    first_emit = {}
    for step_no in range(500):
        out = eng.step()
        for rid in out:
            first_emit.setdefault(rid, step_no)
        if not eng.live() and not eng._waiting:
            break
    else:
        pytest.fail("queue did not drain in 500 steps")

    # FIFO fairness: first-token step is monotone in submission order
    order = [first_emit[rid] for rid, _, _ in reqs]
    assert order == sorted(order), (
        f"later request started before an earlier one: {order}")
    # correctness under churn: every result == its solo decode
    for rid, prompt, n in reqs:
        assert eng.result(rid) == _solo(m, params, prompt, n), rid


# -- decode window (PR 2): K in-graph ticks per host round trip -----------

def test_windowed_engine_matches_solo_and_k1():
    """The decode window must be invisible to results: K in-graph ticks
    per host fetch, staggered arrivals admitted at window boundaries,
    max-token freeze mid-window — every request token-for-token equal
    to generate_cached AND to the K=1 engine under the same schedule."""
    m, params = _gpt(60)
    rng = np.random.RandomState(60)
    pa = list(rng.randint(0, 64, 6))
    pb = list(rng.randint(0, 64, 4))
    pc = list(rng.randint(0, 64, 9))

    def run(window):
        eng = serving.Engine(m, params, slots=3, buf_len=24,
                             window=window)
        ra = eng.add_request(pa, max_new_tokens=8)
        eng.step()                      # A runs alone for one window
        rb = eng.add_request(pb, max_new_tokens=10)  # window boundary
        eng.step()
        rc = eng.add_request(pc, max_new_tokens=5)   # finishes mid-win
        while eng.live():
            eng.step()
        return [eng.result(r) for r in (ra, rb, rc)]

    want = [_solo(m, params, pa, 8), _solo(m, params, pb, 10),
            _solo(m, params, pc, 5)]
    k1 = run(1)
    assert k1 == want
    for K in (4, 8):
        assert run(K) == k1 == want, K


def test_windowed_engine_mid_window_eos_frees_and_reuses():
    """EOS hit at an interior tick of the window: the slot freezes
    in-graph, the host sees exactly the tokens up to and including
    EOS, and the freed slot is clean for its next occupant."""
    m, params = _gpt(61)
    rng = np.random.RandomState(61)
    pa = list(rng.randint(0, 64, 5))
    solo = _solo(m, params, pa, 8)
    eos = solo[2]                       # EOS lands mid-window (K=8)
    want = solo[:solo.index(eos) + 1]
    eng = serving.Engine(m, params, slots=1, buf_len=24, window=8)
    ra = eng.add_request(pa, max_new_tokens=8, eos_token_id=eos)
    out = eng.step()
    assert out == {ra: want}
    assert eng.live() == 0              # slot freed at window boundary
    pb = list(rng.randint(0, 64, 7))
    rb = eng.add_request(pb, max_new_tokens=6)
    while eng.live():
        eng.step()
    assert eng.result(rb) == _solo(m, params, pb, 6)


def test_windowed_engine_queue_admits_between_windows():
    """A request arriving through submit() while the engine is full is
    admitted at the next window boundary and still decodes exactly as
    its solo run (the mid-window freeze never leaks into it)."""
    m, params = _gpt(63)
    rng = np.random.RandomState(63)
    pa = list(rng.randint(0, 64, 5))
    pb = list(rng.randint(0, 64, 7))
    eng = serving.Engine(m, params, slots=1, buf_len=24, window=4)
    ra = eng.submit(pa, max_new_tokens=6)     # takes the slot
    rb = eng.submit(pb, max_new_tokens=9)     # queues
    assert eng.live() == 1
    steps = 0
    while eng.live() or eng.stats()["waiting"]:
        eng.step()
        steps += 1
        assert steps < 30
    assert eng.result(ra) == _solo(m, params, pa, 6)
    assert eng.result(rb) == _solo(m, params, pb, 9)
    # 6 then 9 tokens through K=4 windows: 2 + 3 dispatches
    assert eng.stats()["host_syncs"] == 5


def test_windowed_sampled_mode_matches_k1_with_explicit_seeds():
    """Sampled windowed decode: per-request streams advance once per
    OWN token (frozen slots hold their key), so an explicitly seeded
    request draws identical tokens at any window size and under any
    co-tenancy/arrival pattern."""
    m, params = _gpt(62)
    rng = np.random.RandomState(62)
    pa = list(rng.randint(0, 64, 5))
    pb = list(rng.randint(0, 64, 7))

    def run(window, stagger):
        eng = serving.Engine(m, params, slots=2, buf_len=24,
                             temperature=1.0, top_k=8,
                             rng=jax.random.PRNGKey(9), window=window)
        ra = eng.add_request(pa, max_new_tokens=9, seed=3)
        if stagger:
            eng.step()
        rb = eng.add_request(pb, max_new_tokens=6, seed=4)
        while eng.live():
            eng.step()
        return eng.result(ra), eng.result(rb)

    base = run(1, False)
    assert run(4, False) == base
    assert run(4, True) == base         # arrival timing-independent
    a, b = base
    assert len(a) == 9 and len(b) == 6
    assert all(0 <= t < 64 for t in a + b)


@pytest.mark.slow
def test_windowed_rolling_engine_matches_solo():
    """window > 1 composes with the O(window-KV) rolling mode: the
    scanned ring writes stay exact across wrap-arounds."""
    from apex_tpu.models import Llama, LlamaConfig
    cfg = LlamaConfig(vocab_size=97, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=32, sliding_window=5,
                      tie_word_embeddings=True)
    m = Llama(cfg)
    params, _ = m.init(jax.random.PRNGKey(64))
    params["embed_tokens"] = {
        "weight": params["embed_tokens"]["weight"] / 0.02}
    eng = serving.Engine(m, params, slots=2, buf_len=32, rolling=True,
                         window=4)
    rng = np.random.RandomState(64)
    pa = list(rng.randint(0, 97, 9))        # prompt > window
    ra = eng.add_request(pa, max_new_tokens=12)
    eng.step()
    pb = list(rng.randint(0, 97, 3))
    rb = eng.add_request(pb, max_new_tokens=14)
    while eng.live():
        eng.step()

    def solo(p, n):
        buf = jnp.zeros((1, 32), jnp.int32).at[0, :len(p)].set(
            jnp.asarray(p))
        out, fl = m.generate_cached(params, buf, len(p), n,
                                    rolling_cache=True)
        return list(np.asarray(out[0, len(p):int(fl[0])]))

    assert eng.result(ra) == solo(pa, 12)
    assert eng.result(rb) == solo(pb, 14)


def test_windowed_engine_validation():
    m, params = _gpt(65)
    with pytest.raises(ValueError, match="window"):
        serving.Engine(m, params, slots=1, buf_len=24, window=0)
    with pytest.raises(NotImplementedError, match="speculative"):
        serving.Engine(m, params, slots=1, buf_len=24, window=4,
                       draft=m, draft_params=params)
    from apex_tpu.models import T5, T5Config
    t5 = T5(T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                     num_layers=1, num_heads=4, dropout_rate=0.0,
                     relative_attention_num_buckets=8,
                     relative_attention_max_distance=16))
    t5p, _ = t5.init(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="window"):
        serving.Seq2SeqEngine(t5, t5p, slots=1, src_len=8,
                              max_new_cap=4, window=0)


def test_seq2seq_windowed_matches_solo_and_k1():
    """Seq2SeqEngine gets the same windowed loop: staggered arrivals,
    mid-window EOS, and slot reuse all token-for-token equal to
    T5.generate and to the K=1 seq2seq engine."""
    from apex_tpu.models import T5, T5Config
    cfg = T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                   num_layers=2, num_heads=4, dropout_rate=0.0,
                   relative_attention_num_buckets=8,
                   relative_attention_max_distance=16)
    m = T5(cfg)
    params, _ = m.init(jax.random.PRNGKey(66))
    rng = np.random.RandomState(66)

    def solo(src, n):
        ids = jnp.zeros((1, 12), jnp.int32).at[0, :len(src)].set(
            jnp.asarray(src))
        mask = (jnp.arange(12) < len(src)).astype(jnp.float32)[None, :]
        return list(np.asarray(m.generate(params, ids, n,
                                          attention_mask=mask)[0]))

    pa = list(rng.randint(2, 64, 11))
    pb = list(rng.randint(2, 64, 4))

    def run(window):
        eng = serving.Seq2SeqEngine(m, params, slots=2, src_len=12,
                                    max_new_cap=10, window=window)
        ra = eng.add_request(pa, max_new_tokens=9)
        eng.step()
        rb = eng.add_request(pb, max_new_tokens=5)
        while eng.live():
            eng.step()
        return [eng.result(ra), eng.result(rb)]

    want = [solo(pa, 9), solo(pb, 5)]
    assert run(1) == want
    assert run(4) == want

    # mid-window EOS on the windowed engine frees the slot cleanly
    eng = serving.Seq2SeqEngine(m, params, slots=1, src_len=12,
                                max_new_cap=10, window=4)
    sol = solo(pa, 6)
    eos = sol[1]
    want_eos = sol[:sol.index(eos) + 1]
    r4 = eng.add_request(pa, max_new_tokens=6, eos_token_id=eos)
    out = eng.step()
    assert out == {r4: want_eos} and eng.live() == 0
    r5 = eng.add_request(pb, max_new_tokens=5)
    while eng.live():
        eng.step()
    assert eng.result(r5) == solo(pb, 5)


# -- KV fragmentation ledger (PR 13) ---------------------------------------
# ``kv_cache_bytes`` said what the engine allocated; these pin what it
# WASTES — the number ROADMAP item 1's paged allocator must drive down
# — and that the ``engine_kv_waste_bytes`` / ``engine_kv_utilization``
# gauges track stats() through submit/step/cancel/eos exactly the way
# the fleet tests pin ``engine_queue_depth``.

def _kv_gauges(eng):
    return (eng.metrics.gauge("engine_kv_waste_bytes").value,
            eng.metrics.gauge("engine_kv_utilization").value)


def _assert_kv_pinned(eng):
    """Gauges (set at the last mutation) must equal a fresh ledger AND
    the stats() fields — read the gauges FIRST so a lazy stats()-only
    refresh would be caught."""
    g_waste, g_util = _kv_gauges(eng)
    frag = eng.kv_fragmentation()
    assert g_waste == frag["kv_waste_bytes"]
    assert g_util == frag["kv_utilization"]
    s = eng.stats()
    assert s["kv_waste_bytes"] == frag["kv_waste_bytes"]
    assert s["kv_utilization"] == frag["kv_utilization"]
    assert 0.0 <= s["kv_utilization"] <= 1.0
    assert 0 <= s["kv_waste_bytes"] <= s["kv_cache_bytes"]
    # the ledger reassembles: used + waste == allocated, and the
    # per-slot entries sum to the used side (up to the total clamp)
    assert frag["kv_used_bytes"] + frag["kv_waste_bytes"] \
        == frag["kv_cache_bytes"]
    return frag


def test_kv_fragmentation_through_lifecycle():
    """Empty engine: all waste.  Admission: waste drops by the prompt's
    KV rows.  Decode: waste shrinks token by token.  EOS/cancel: the
    slot's rows return to waste.  Gauge == stats() at every stage."""
    m, params = _gpt(7)
    eng = serving.Engine(m, params, slots=2, buf_len=24)
    frag = _assert_kv_pinned(eng)
    total = frag["kv_cache_bytes"]
    assert total > 0
    # nothing admitted: the whole allocation is waste
    assert frag["kv_waste_bytes"] == total
    assert frag["kv_utilization"] == 0.0
    assert [e["used_positions"] for e in frag["slots"]] == [0, 0]

    rng = np.random.RandomState(7)
    pa = list(rng.randint(0, 64, 6))
    ra = eng.add_request(pa, max_new_tokens=4)
    frag = _assert_kv_pinned(eng)
    waste_after_admit = frag["kv_waste_bytes"]
    assert waste_after_admit < total            # the prompt occupies rows
    by_slot = {e["rid"]: e for e in frag["slots"]}
    assert by_slot[ra]["used_positions"] == 6
    assert by_slot[None]["used_positions"] == 0  # the free slot
    # per-slot waste: capacity minus used, and the free slot wastes
    # its whole row
    assert by_slot[ra]["kv_waste_bytes"] < by_slot[None]["kv_waste_bytes"]

    eng.step()                                   # one decode token
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] < waste_after_admit

    while eng.live():
        eng.step()                               # budget exhausts (eos-
    frag = _assert_kv_pinned(eng)                # equivalent finish)
    assert frag["kv_waste_bytes"] == total       # rows back to waste
    assert frag["kv_utilization"] == 0.0

    # cancel of a live request returns its rows to waste immediately
    rb = eng.add_request(pa, max_new_tokens=4)
    assert _assert_kv_pinned(eng)["kv_waste_bytes"] < total
    assert eng.cancel(rb)
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] == total


def test_kv_fragmentation_counts_prefix_pool_and_draft():
    """The pool rows and draft cache are allocation too: a registered
    prefix occupies its pool row's positions, an empty pool row is all
    waste; a speculative engine's draft cache doubles the per-slot
    bytes and the used fraction tracks both caches."""
    m, params = _gpt(8)
    eng = serving.Engine(m, params, slots=1, buf_len=24, prefix_pool=2)
    frag = _assert_kv_pinned(eng)
    total = frag["kv_cache_bytes"]
    assert len(frag["pools"]) == 2
    assert all(p["used_positions"] == 0 for p in frag["pools"])
    assert frag["kv_waste_bytes"] == total

    pref = [1, 2, 3, 4, 5]
    eng.register_prefix(pref)
    frag = eng.kv_fragmentation()
    assert frag["pools"][0]["used_positions"] == len(pref)
    assert frag["pools"][1]["used_positions"] == 0
    assert frag["kv_waste_bytes"] < total

    # draft engine: two cache trees share the position axis
    draft = models.GPT(models.GPTConfig(vocab_size=64, block_size=24,
                                        n_layer=1, n_head=2, n_embd=16,
                                        dropout=0.0))
    dparams, _ = draft.init(jax.random.PRNGKey(9))
    spec = serving.Engine(m, params, slots=2, buf_len=24, draft=draft,
                          draft_params=dparams)
    frag0 = _assert_kv_pinned(spec)
    spec.add_request([1, 2, 3, 4], max_new_tokens=3)
    frag1 = _assert_kv_pinned(spec)
    assert frag1["kv_used_bytes"] > 0
    assert frag1["kv_waste_bytes"] < frag0["kv_waste_bytes"]
    while spec.live():
        spec.step()
        _assert_kv_pinned(spec)


def test_kv_fragmentation_windowed_partial_fill_nonzero():
    """The acceptance shape: a partially-filled windowed engine has
    NONZERO waste (free slots + capacity beyond cur_len), utilization
    strictly between 0 and 1, and the gauges stay pinned across whole
    windows."""
    m, params = _gpt(10)
    eng = serving.Engine(m, params, slots=4, buf_len=24, window=4)
    eng.add_request([1, 2, 3], max_new_tokens=8)
    eng.add_request([4, 5, 6, 7], max_new_tokens=8)
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] > 0
    assert 0.0 < frag["kv_utilization"] < 1.0
    eng.step()
    frag2 = _assert_kv_pinned(eng)
    assert frag2["kv_waste_bytes"] < frag["kv_waste_bytes"]


def test_kv_fragmentation_rolling_ring_capacity():
    """A rolling engine's slot capacity is the RING (W positions), not
    buf_len: a prompt longer than W fully uses its row — utilization
    1.0 on a single fully-live slot, never >1."""
    from apex_tpu.models import Llama, LlamaConfig
    cfg = LlamaConfig(vocab_size=64, hidden_size=32,
                      intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=24, sliding_window=6,
                      tie_word_embeddings=True)
    m = Llama(cfg)
    params, _ = m.init(jax.random.PRNGKey(11))
    eng = serving.Engine(m, params, slots=1, buf_len=24, rolling=True)
    rng = np.random.RandomState(11)
    eng.add_request(list(rng.randint(0, 64, 10)), max_new_tokens=2)
    frag = _assert_kv_pinned(eng)
    # prompt (10) exceeds the ring (6): the row is fully used
    assert frag["slots"][0]["capacity_positions"] == 6
    assert frag["slots"][0]["used_positions"] == 6
    assert frag["kv_utilization"] == 1.0
    assert frag["kv_waste_bytes"] == 0


def test_kv_fragmentation_seq2seq_two_residents():
    """Seq2seq slots hold two residents (cross K/V over src_len, a
    decoder cache over max_new_cap): admission uses the source share,
    decode grows the decoder share, finish returns both to waste."""
    from apex_tpu.models import T5, T5Config
    cfg = T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                   num_layers=2, num_heads=4, dropout_rate=0.0,
                   relative_attention_num_buckets=8,
                   relative_attention_max_distance=16)
    m = T5(cfg)
    params, _ = m.init(jax.random.PRNGKey(12))
    eng = serving.Seq2SeqEngine(m, params, slots=2, src_len=12,
                                max_new_cap=10)
    frag = _assert_kv_pinned(eng)
    total = frag["kv_cache_bytes"]
    assert frag["kv_waste_bytes"] == total
    rng = np.random.RandomState(12)
    eng.add_request(list(rng.randint(2, 64, 9)), max_new_tokens=4)
    frag = _assert_kv_pinned(eng)
    after_admit = frag["kv_waste_bytes"]
    assert after_admit < total
    by_slot = {e["rid"]: e for e in frag["slots"]}
    live = next(e for rid, e in by_slot.items() if rid is not None)
    assert live["used_positions"] == 9           # source only so far
    eng.step()
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] < after_admit  # decoder share grew
    while eng.live():
        eng.step()
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] == total


# -- PR 15: the compilation plane ------------------------------------------
# The zero-retrace steady-state contract, pinned like the host-transfer
# audit: a warmed engine's decode loop never re-traces.  All deltas are
# against the PROCESS ledger (compilation is process-wide), so the pins
# are order-independent under the suite.


def test_engine_warmup_compiles_exactly_the_census():
    """warmup() traces exactly the admission+decode entries of the
    expected-closure census, and a second warmup adds zero traces —
    the per-instance re-jit is paid once, up front."""
    from apex_tpu.observability import compilation
    m, params = _gpt()
    led = compilation.get_ledger()
    eng = serving.Engine(m, params, slots=2, buf_len=24, window=2)
    census = eng.compile_census()
    assert census == {"engine._prefill_slot": "admission",
                      "engine._step_k": "decode"}
    warm_entries = {e for e, stage in census.items()
                    if stage in ("admission", "decode")}
    before = led.counts()
    t0 = led.total_traces()
    eng.warmup()
    after = led.counts()
    # every warm-stage census entry traced exactly once, nothing else
    assert led.total_traces() - t0 == len(warm_entries)
    for e in warm_entries:
        assert after.get(e, 0) - before.get(e, 0) == 1, e
    # idempotent: the closures are warm, a second pass traces nothing
    t1 = led.total_traces()
    eng.warmup()
    assert led.total_traces() == t1


def test_engine_zero_retrace_steady_state():
    """THE acceptance pin: after warmup, N decode windows with MIXED
    eos / admission / queue-drain traffic add exactly 0 traces to the
    compilation ledger.  Everything that varies between requests
    (prompt length, budget, eos id, arrival timing) is buffer VALUES,
    never abstract signatures — the static-shape serving contract,
    now machine-checked instead of assumed."""
    from apex_tpu.observability import compilation
    m, params = _gpt()
    eng = serving.Engine(m, params, slots=3, buf_len=24, window=2)
    eng.warmup()
    rng = np.random.RandomState(0)
    pa = list(rng.randint(0, 64, 5))
    eos_a = _solo(m, params, pa, 1)[0]     # an EOS that actually fires
    led = compilation.get_ledger()
    t0 = led.total_traces()
    ra = eng.submit(pa, max_new_tokens=6, eos_token_id=eos_a)
    rb = eng.submit(list(rng.randint(0, 64, 9)), max_new_tokens=4)
    rc = eng.submit(list(rng.randint(0, 64, 3)), max_new_tokens=8)
    rd = eng.submit(list(rng.randint(0, 64, 7)), max_new_tokens=3)
    windows = 0
    while eng.live() or eng.queue_depth():
        eng.step()
        windows += 1
        assert windows < 50
    assert windows >= 3                    # a real steady-state run
    for r in (ra, rb, rc, rd):
        assert eng.is_finished(r)
    assert eng.result(ra) == [eos_a]       # the eos path really ran
    assert led.total_traces() - t0 == 0    # zero retraces, pinned


def test_engine_zero_retrace_covers_prefix_splice():
    """The prefix-sharing admission path compiles at its census stages
    (register_prefix + first splice), then goes zero-retrace too: a
    second spliced admission with a different prompt adds nothing."""
    from apex_tpu.observability import compilation
    m, params = _gpt(3)
    eng = serving.Engine(m, params, slots=2, buf_len=24,
                         prefix_pool=1, prefix_chunk=4)
    eng.warmup()
    rng = np.random.RandomState(3)
    pref = list(rng.randint(0, 64, 6))
    eng.register_prefix(pref)
    # first spliced admission compiles the splice closures...
    r1 = eng.add_request(pref + list(rng.randint(0, 64, 3)),
                         max_new_tokens=2)
    led = compilation.get_ledger()
    assert set(eng.compile_census()) <= set(led.counts())
    # ...and from here the whole engine is steady-state
    t0 = led.total_traces()
    r2 = eng.add_request(pref + list(rng.randint(0, 64, 5)),
                         max_new_tokens=3)
    while eng.live():
        eng.step()
    assert eng.is_finished(r1) and eng.is_finished(r2)
    assert eng.stats()["prefix_hits"] == 2
    assert led.total_traces() - t0 == 0


def test_seq2seq_zero_retrace_steady_state():
    """The same pin for the encoder-decoder engine: warmed
    Seq2SeqEngine runs mixed decode windows (staggered sources, eos,
    queue admissions) with ledger delta == 0."""
    from apex_tpu.models import T5, T5Config
    from apex_tpu.observability import compilation
    t5 = T5(T5Config(vocab_size=64, d_model=32, d_kv=8, d_ff=64,
                     num_layers=1, num_heads=4, dropout_rate=0.0,
                     relative_attention_num_buckets=8,
                     relative_attention_max_distance=16))
    t5p, _ = t5.init(jax.random.PRNGKey(0))
    eng = serving.Seq2SeqEngine(t5, t5p, slots=2, src_len=8,
                                max_new_cap=6, window=2)
    eng.warmup()
    assert set(eng.compile_census()) == {"seq2seq._seed",
                                         "seq2seq._step_k"}
    led = compilation.get_ledger()
    t0 = led.total_traces()
    rng = np.random.RandomState(0)
    rids = [eng.submit(list(rng.randint(2, 64, n)),
                       max_new_tokens=b, eos_token_id=e)
            for n, b, e in ((3, 4, None), (8, 2, None), (5, 6, 1))]
    windows = 0
    while eng.live() or eng.queue_depth():
        eng.step()
        windows += 1
        assert windows < 50
    for r in rids:
        assert eng.is_finished(r)
    assert led.total_traces() - t0 == 0


def test_warmup_requires_idle_engine():
    m, params = _gpt()
    eng = serving.Engine(m, params, slots=1, buf_len=24)
    eng.add_request([1, 2], max_new_tokens=2)
    with pytest.raises(RuntimeError, match="idle"):
        eng.warmup()


# -- PR 17: paged KV + continuous batching ---------------------------------
# The block-pool engine must be INVISIBLE to results: every request
# token-for-token equal to generate_cached and to the fixed-slot engine
# under the same schedule, with blocks recycling in-graph the moment a
# request dies and admission landing at ITERATION boundaries (not
# window boundaries) — all of it zero-retrace after warmup.


def _paged(m, params, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("buf_len", 24)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.PagedEngine(m, params, **kw)


def test_paged_engine_matches_solo_and_fixed():
    """Greedy parity across mixed prompt lengths and staggered
    arrivals: paged == fixed-slot == generate_cached, at window 1 and
    a real window, under block-pool pressure (num_blocks < the
    worst-case sum, so admissions genuinely contend for blocks)."""
    m, params = _gpt(70)
    rng = np.random.RandomState(70)
    prompts = [list(rng.randint(0, 64, k)) for k in (6, 4, 9, 2)]
    buds = [8, 10, 5, 7]

    def run(make):
        eng = make()
        ra = eng.submit(prompts[0], max_new_tokens=buds[0])
        eng.step()                       # A alone for one dispatch
        rb = eng.submit(prompts[1], max_new_tokens=buds[1])
        rc = eng.submit(prompts[2], max_new_tokens=buds[2])
        eng.step()
        rd = eng.submit(prompts[3], max_new_tokens=buds[3])
        steps = 0
        while eng.live() or eng.queue_depth():
            eng.step()
            steps += 1
            assert steps < 60
        return [eng.result(r) for r in (ra, rb, rc, rd)]

    want = [_solo(m, params, p, b) for p, b in zip(prompts, buds)]
    fixed = run(lambda: serving.Engine(m, params, slots=3, buf_len=24))
    assert fixed == want
    for window in (1, 4):
        got = run(lambda: _paged(m, params, num_blocks=7,
                                 window=window))
        assert got == fixed == want, window


def test_paged_engine_nondivisor_block_size():
    """block_size that does NOT divide buf_len: the table pads to
    ceil(buf_len / block_size) blocks and the dense gather masks the
    overhang — parity must be bit-exact anyway."""
    m, params = _gpt(71)
    rng = np.random.RandomState(71)
    pa = list(rng.randint(0, 64, 7))
    eng = _paged(m, params, slots=2, block_size=5, window=2)
    ra = eng.add_request(pa, max_new_tokens=9)
    while eng.live():
        eng.step()
    assert eng.result(ra) == _solo(m, params, pa, 9)


def test_paged_engine_mid_window_eos_recycles_blocks_in_graph():
    """EOS at an interior tick: the host sees exactly the tokens up to
    and including EOS, and the dead request's blocks are back on the
    free stack at the SAME window's fetch — in-graph recycling, not a
    host-side cleanup on the next dispatch."""
    m, params = _gpt(72)
    rng = np.random.RandomState(72)
    pa = list(rng.randint(0, 64, 5))
    solo = _solo(m, params, pa, 8)
    eos = solo[2]                        # fires mid-window (K=8)
    want = solo[:solo.index(eos) + 1]
    eng = _paged(m, params, slots=1, window=8)
    total = eng.stats()["blocks_total"]
    ra = eng.add_request(pa, max_new_tokens=8, eos_token_id=eos)
    assert eng.stats()["blocks_free"] < total
    out = eng.step()
    assert out == {ra: want}
    assert eng.live() == 0
    assert eng.stats()["blocks_free"] == total   # all blocks recycled
    # the recycled blocks are clean for the next occupant
    pb = list(rng.randint(0, 64, 7))
    rb = eng.add_request(pb, max_new_tokens=6)
    while eng.live():
        eng.step()
    assert eng.result(rb) == _solo(m, params, pb, 6)


def test_paged_engine_midwindow_admission_is_exact():
    """THE continuous-batching claim: on a full engine, a queued
    request is admitted at the iteration where blocks free up — INSIDE
    the window — and still decodes exactly as its solo run.  The
    stats counter proves the in-window path (not the window-boundary
    drain) actually served it."""
    m, params = _gpt(73)
    rng = np.random.RandomState(73)
    pa = list(rng.randint(0, 64, 5))
    pb = list(rng.randint(0, 64, 7))
    eng = _paged(m, params, slots=1, window=16)
    ra = eng.submit(pa, max_new_tokens=4)    # takes the slot
    rb = eng.submit(pb, max_new_tokens=6)    # queues
    assert eng.live() == 1
    out = eng.step()                         # ONE window serves both
    assert sorted(out) == sorted([ra, rb])
    assert eng.live() == 0 and eng.queue_depth() == 0
    assert eng.stats()["midwindow_admissions"] == 1
    assert eng.stats()["host_syncs"] == 1
    assert eng.result(ra) == _solo(m, params, pa, 4)
    assert eng.result(rb) == _solo(m, params, pb, 6)


def test_paged_sampled_matches_fixed_with_explicit_seeds():
    """Seeded-sampled parity: per-request streams are derived from
    (rid | seed) exactly like the fixed engine's, and advance once per
    OWN decode tick — so explicit seeds draw identical tokens on both
    engines, at any window, under any arrival pattern."""
    m, params = _gpt(74)
    rng = np.random.RandomState(74)
    pa = list(rng.randint(0, 64, 5))
    pb = list(rng.randint(0, 64, 7))

    def run(make, stagger):
        eng = make()
        ra = eng.add_request(pa, max_new_tokens=9, seed=3)
        if stagger:
            eng.step()
        rb = eng.add_request(pb, max_new_tokens=6, seed=4)
        while eng.live():
            eng.step()
        return eng.result(ra), eng.result(rb)

    kw = dict(temperature=1.0, top_k=8, rng=jax.random.PRNGKey(9))
    base = run(lambda: serving.Engine(m, params, slots=2, buf_len=24,
                                      **kw), False)
    for window in (1, 4):
        for stagger in (False, True):
            got = run(lambda: _paged(m, params, slots=2,
                                     window=window, **kw), stagger)
            assert got == base, (window, stagger)
    a, b = base
    assert len(a) == 9 and len(b) == 6
    assert all(0 <= t < 64 for t in a + b)


def test_paged_engine_int8_kv_matches_solo():
    """int8 KV composes with the block pool (the quantized buffers and
    their scale sidecars page identically): parity vs the solo int8
    decode and the fixed-slot int8 engine."""
    m, params = _gpt(75)
    rng = np.random.RandomState(75)
    prompts = [list(rng.randint(0, 64, k)) for k in (4, 8)]

    def solo8(p, n):
        buf = jnp.zeros((1, 24), jnp.int32).at[0, :len(p)].set(
            jnp.asarray(p))
        out, fl = m.generate_cached(params, buf, len(p), n,
                                    cache_dtype=jnp.int8)
        return list(np.asarray(out[0, len(p):int(fl[0])]))

    fixed = serving.Engine(m, params, slots=2, buf_len=24,
                           cache_dtype=jnp.int8)
    paged = _paged(m, params, slots=2, cache_dtype=jnp.int8, window=2)
    rids_f = [fixed.add_request(p, max_new_tokens=6) for p in prompts]
    rids_p = [paged.add_request(p, max_new_tokens=6) for p in prompts]
    while fixed.live():
        fixed.step()
    while paged.live():
        paged.step()
    for rf, rp, p in zip(rids_f, rids_p, prompts):
        want = solo8(p, 6)
        assert fixed.result(rf) == want, p
        assert paged.result(rp) == want, p


def test_paged_engine_prefix_affinity_cross_check():
    """Prefix-affinity splice cross-check: prompts sharing a system
    prefix through the FIXED engine's splice path and through the
    plain paged engine must produce identical tokens — the splice is
    an admission-latency lever, never a numerics one, so the paged
    engine (which re-prefills the shared prefix chunked) agrees
    token-for-token."""
    m, params = _gpt(76)
    rng = np.random.RandomState(76)
    pref = list(rng.randint(0, 64, 7))
    prompts = [pref + list(rng.randint(0, 64, k)) for k in (1, 3, 6)]
    fixed = serving.Engine(m, params, slots=3, buf_len=24,
                           prefix_pool=1, prefix_chunk=4)
    fixed.register_prefix(pref)
    paged = _paged(m, params, window=2)
    rids_f = [fixed.submit(p, max_new_tokens=6) for p in prompts]
    rids_p = [paged.submit(p, max_new_tokens=6) for p in prompts]
    while fixed.live() or fixed.queue_depth():
        fixed.step()
    while paged.live() or paged.queue_depth():
        paged.step()
    assert fixed.stats()["prefix_hits"] == 3     # the splice ran
    for rf, rp, p in zip(rids_f, rids_p, prompts):
        want = _solo(m, params, p, 6)
        assert fixed.result(rf) == want, p
        assert paged.result(rp) == want, p


def test_paged_admission_control_and_cancel_release_blocks():
    """add_request on a slot-free but block-starved engine fails loud
    (submit() is the queueing path); cancel() of a live request
    releases its blocks eagerly so the next admission fits."""
    m, params = _gpt(77)
    # 4 blocks of 8: one 20-position request (3 blocks) starves the
    # pool for anything needing 2+
    eng = _paged(m, params, slots=2, num_blocks=4)
    ra = eng.add_request([1] * 16, max_new_tokens=8)     # 3 blocks
    assert eng.stats()["blocks_free"] == 1
    with pytest.raises(RuntimeError, match="no free KV blocks"):
        eng.add_request([2] * 8, max_new_tokens=8)       # needs 2
    rb = eng.add_request([3] * 4, max_new_tokens=4)      # 1 block fits
    assert eng.stats()["blocks_free"] == 0
    assert eng.cancel(rb)
    assert eng.stats()["blocks_free"] == 1
    assert eng.cancel(ra)
    assert eng.stats()["blocks_free"] == 4
    # queueing path: submit() holds the request until blocks recycle
    rc = eng.submit([4] * 16, max_new_tokens=6)
    rd = eng.submit([5] * 16, max_new_tokens=6)          # can't fit yet
    assert eng.live() == 1 and eng.queue_depth() == 1
    while eng.live() or eng.queue_depth():
        eng.step()
    assert eng.is_finished(rc) and eng.is_finished(rd)
    assert eng.stats()["blocks_free"] == 4


def test_paged_kv_fragmentation_block_accounting():
    """Per-BLOCK ledger: an empty pool is all waste, a live request
    wastes only the unfilled tail of its last block-set (not the whole
    buf_len row), decode shrinks the waste, and finish returns every
    block.  Gauges == ledger == stats() at each stage, plus the paged
    blocks_free gauge."""
    m, params = _gpt(78)
    eng = _paged(m, params, slots=2)
    frag = _assert_kv_pinned(eng)
    total = frag["kv_cache_bytes"]
    assert frag["kv_waste_bytes"] == total
    assert frag["kv_utilization"] == 0.0

    rng = np.random.RandomState(78)
    pa = list(rng.randint(0, 64, 6))
    ra = eng.add_request(pa, max_new_tokens=4)   # 10 pos -> 2 blocks
    frag = _assert_kv_pinned(eng)
    by_slot = {e["rid"]: e for e in frag["slots"]}
    assert by_slot[ra]["blocks_held"] == 2
    assert by_slot[ra]["used_positions"] == 6
    # block granularity: the live slot's waste is its block-tail, far
    # less than a fixed-slot engine's whole-row reservation would be
    assert by_slot[ra]["capacity_positions"] == 16
    waste_admit = frag["kv_waste_bytes"]
    assert waste_admit < total
    g_free = eng.metrics.gauge("engine_kv_blocks_free").value
    assert g_free == eng.stats()["blocks_free"]

    eng.step()
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] < waste_admit
    while eng.live():
        eng.step()
    frag = _assert_kv_pinned(eng)
    assert frag["kv_waste_bytes"] == total       # all blocks returned
    assert eng.stats()["blocks_free"] == eng.stats()["blocks_total"]


def test_paged_warmup_compiles_exactly_the_census():
    """warmup() traces exactly the two paged entries (the decode
    window's ONE graph covers chunked prefill, decode, in-window
    admission and recycling as cond branches / masked lanes), and a
    second warmup adds zero traces."""
    from apex_tpu.observability import compilation
    m, params = _gpt()
    led = compilation.get_ledger()
    eng = _paged(m, params, window=2)
    census = eng.compile_census()
    assert census == {"engine._paged_admit": "admission",
                      "engine._paged_step_k": "decode"}
    before = led.counts()
    t0 = led.total_traces()
    eng.warmup()
    after = led.counts()
    assert led.total_traces() - t0 == len(census)
    for e in census:
        assert after.get(e, 0) - before.get(e, 0) == 1, e
    t1 = led.total_traces()
    eng.warmup()
    assert led.total_traces() == t1


def test_paged_zero_retrace_steady_state_with_midwindow_admission():
    """THE acceptance pin for the paged plane: after warmup, N mixed
    windows — staggered arrivals, an eos that fires, queue drains AND
    a mid-window admission — add exactly 0 traces.  Everything that
    varies (prompt length, block counts, budgets, arrival timing) is
    buffer values, never abstract signatures."""
    from apex_tpu.observability import compilation
    m, params = _gpt()
    eng = _paged(m, params, slots=2, window=8)
    eng.warmup()
    rng = np.random.RandomState(0)
    pa = list(rng.randint(0, 64, 5))
    eos_a = _solo(m, params, pa, 1)[0]
    led = compilation.get_ledger()
    t0 = led.total_traces()
    ra = eng.submit(pa, max_new_tokens=6, eos_token_id=eos_a)
    rb = eng.submit(list(rng.randint(0, 64, 9)), max_new_tokens=4)
    rc = eng.submit(list(rng.randint(0, 64, 3)), max_new_tokens=8)
    rd = eng.submit(list(rng.randint(0, 64, 7)), max_new_tokens=3)
    windows = 0
    while eng.live() or eng.queue_depth():
        eng.step()
        windows += 1
        assert windows < 50
    for r in (ra, rb, rc, rd):
        assert eng.is_finished(r)
    assert eng.result(ra) == [eos_a]           # the eos path ran
    assert eng.stats()["midwindow_admissions"] >= 1
    assert led.total_traces() - t0 == 0        # zero retraces, pinned
    # the census is the whole compiled surface: nothing outside it
    assert set(eng.compile_census()) <= set(led.counts())
