"""Expert-parallel MoE parity: the all_to_all dispatch over an 'expert'
mesh axis must match running the same per-shard routing math locally —
outputs and gradients — and the aux loss must be finite and O(1)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import expert_parallel as ep
from conftest import assert_trees_close


def moe_and_params(E=8, d=8, h=16, seed=12, cap=2.0):
    moe = ep.ExpertParallelMLP(d, h, E, capacity_factor=cap)
    params, _ = moe.init(jax.random.PRNGKey(seed))
    return moe, params


def _ref_sharded(moe, params, x, n_shards):
    """Reference: each token shard routed independently (ep=1 path,
    outside any mesh), concatenated — the exact per-shard capacity
    semantics of the sharded run."""
    outs = [moe(params, xs) for xs in np.split(np.asarray(x), n_shards)]
    return jnp.concatenate([jnp.asarray(o) for o in outs])


def specs_of(moe, params):
    from apex_tpu.parallel import tensor_parallel as tp
    s = tp.partition_specs(moe, params)
    assert s["w_in"] == P("expert", None, None)
    assert s["router"] == P()
    return s


def test_moe_forward_matches_per_shard_reference():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe, params = moe_and_params()
    specs = specs_of(moe, params)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)

    y = jax.jit(jax.shard_map(
        lambda p, xb: moe(p, xb), mesh=mesh,
        in_specs=(specs, P("expert")), out_specs=P("expert"),
        check_vma=False))(params, x)
    y_ref = _ref_sharded(moe, params, x, 4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               atol=2e-5)


def test_moe_capacity_drops_tokens():
    """capacity_factor small enough forces drops: output rows for
    dropped tokens are zero, and nothing NaNs."""
    moe, params = moe_and_params(cap=0.25)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)
    y = moe(params, x)
    assert np.isfinite(np.asarray(y)).all()
    zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
    assert zero_rows > 0          # with C=ceil(0.25*16/8)=1 some drop


def test_moe_gradients_match_per_shard_reference():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe, params = moe_and_params()
    specs = specs_of(moe, params)
    x = jnp.asarray(np.random.RandomState(2).randn(16, 8), jnp.float32)

    def sharded_grad(p, xb):
        g = jax.grad(lambda pp: jnp.sum(jnp.square(moe(pp, xb))))(p)
        # the router is data-parallel over the expert axis (each device
        # routed only its token shard): sum its grad like DDP would
        g["router"] = lax.psum(g["router"], "expert")
        return g

    g_tp = jax.jit(jax.shard_map(
        sharded_grad, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=specs, check_vma=False))(params, x)

    def ref_loss(p):
        return jnp.sum(jnp.square(_ref_sharded(moe, p, x, 4)))

    assert_trees_close(g_tp, jax.grad(ref_loss)(params), atol=3e-5)


def test_moe_aux_loss():
    moe, params = moe_and_params()
    x = jnp.asarray(np.random.RandomState(3).randn(16, 8), jnp.float32)
    y, aux = moe(params, x, return_aux_loss=True)
    # Switch aux: >= 1 (perfect balance) and modest for random routing
    assert 0.9 < float(aux) < 8.0
    assert y.shape == x.shape


def test_moe_expert_divisibility_check():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe, params = moe_and_params(E=6)     # 6 experts, ep=4
    x = jnp.zeros((8, 8))
    # replicated params so shard_map's own shape check doesn't fire
    # first — the module's divisibility error is the one users see
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(jax.shard_map(
            lambda p, xb: moe(p, xb), mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                      P("expert")),
            out_specs=P("expert"), check_vma=False))(params, x)


# -- top-k (Mixtral-shape) routing ---------------------------------------

def _loop_moe(moe, params, x):
    """Per-token loop oracle: choice-major capacity queueing (all first
    choices enqueue before any second choice), renormalized gates,
    SwiGLU or plain experts."""
    import math
    x2 = np.asarray(x)
    T, d = x2.shape
    E, k = moe.n_experts, moe.top_k
    C = max(1, math.ceil(moe.capacity_factor * T / E))
    logits = x2 @ np.asarray(params["router"])
    z = np.exp(logits - logits.max(1, keepdims=True))
    probs = z / z.sum(1, keepdims=True)
    top = np.argsort(-probs, axis=1, kind="stable")[:, :k]
    gates = np.take_along_axis(probs, top, 1)
    if k > 1:
        gates = gates / gates.sum(1, keepdims=True)
    counts = np.zeros(E, np.int64)
    y = np.zeros_like(x2)
    wi = np.asarray(params["w_in"])
    wo = np.asarray(params["w_out"])
    wg = np.asarray(params.get("w_gate")) if "w_gate" in params else None
    for c in range(k):
        for t in range(T):
            e = top[t, c]
            if counts[e] >= C:
                continue
            counts[e] += 1
            if wg is not None:
                h = x2[t] @ wg[e]
                h = h / (1.0 + np.exp(-h)) * (x2[t] @ wi[e])
            else:
                h = x2[t] @ wi[e]
                h = 0.5 * h * (1.0 + np.tanh(
                    np.sqrt(2.0 / np.pi) * (h + 0.044715 * h ** 3)))
            y[t] += gates[t, c] * (h @ wo[e])
    return y


@pytest.mark.parametrize("cap", [2.0, 0.5])
def test_moe_top2_swiglu_matches_loop_oracle(cap):
    """top_k=2 + SwiGLU experts vs the per-token loop — including
    tight capacity (cap=0.5 forces drops, and the oracle's choice-major
    queue checks that second choices drop first)."""
    moe = ep.ExpertParallelMLP(8, 16, 8, capacity_factor=cap,
                               top_k=2, expert_type="swiglu")
    params, _ = moe.init(jax.random.PRNGKey(5))
    x = jnp.asarray(np.random.RandomState(5).randn(24, 8), jnp.float32)
    y = moe(params, x)
    np.testing.assert_allclose(np.asarray(y), _loop_moe(moe, params, x),
                               rtol=2e-4, atol=2e-5)


def test_moe_top2_sharded_matches_per_shard_reference():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe = ep.ExpertParallelMLP(8, 16, 8, capacity_factor=2.0,
                               top_k=2, expert_type="swiglu")
    params, _ = moe.init(jax.random.PRNGKey(6))
    specs = specs_of(moe, params)
    assert specs["w_gate"] == P("expert", None, None)
    x = jnp.asarray(np.random.RandomState(6).randn(16, 8), jnp.float32)

    y = jax.jit(jax.shard_map(
        lambda p, xb: moe(p, xb), mesh=mesh,
        in_specs=(specs, P("expert")), out_specs=P("expert"),
        check_vma=False))(params, x)
    y_ref = _ref_sharded(moe, params, x, 4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               atol=2e-5)


def test_moe_top2_gradients_match_per_shard_reference():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe = ep.ExpertParallelMLP(8, 16, 8, capacity_factor=2.0,
                               top_k=2, expert_type="swiglu")
    params, _ = moe.init(jax.random.PRNGKey(7))
    specs = specs_of(moe, params)
    x = jnp.asarray(np.random.RandomState(7).randn(16, 8), jnp.float32)

    def sharded_grad(p, xb):
        g = jax.grad(lambda pp: jnp.sum(jnp.square(moe(pp, xb))))(p)
        g["router"] = lax.psum(g["router"], "expert")
        return g

    g_tp = jax.jit(jax.shard_map(
        sharded_grad, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=specs, check_vma=False))(params, x)

    def ref_loss(p):
        return jnp.sum(jnp.square(_ref_sharded(moe, p, x, 4)))

    assert_trees_close(g_tp, jax.grad(ref_loss)(params), atol=3e-5)


def test_moe_top2_gates_renormalized():
    """Combine weights for an un-dropped token sum to 1 (Mixtral
    renormalization), not to the raw top-2 softmax mass."""
    moe = ep.ExpertParallelMLP(8, 16, 4, capacity_factor=8.0, top_k=2)
    params, _ = moe.init(jax.random.PRNGKey(8))
    x = jnp.asarray(np.random.RandomState(8).randn(8, 8), jnp.float32)
    _, combine, _ = moe._dispatch(
        x, params["router"], capacity=16)
    np.testing.assert_allclose(np.asarray(combine).sum((1, 2)),
                               np.ones(8), rtol=1e-5)


def test_moe_top_k_validation():
    with pytest.raises(ValueError, match="top_k"):
        ep.ExpertParallelMLP(8, 16, 4, top_k=5)
    with pytest.raises(ValueError, match="expert_type"):
        ep.ExpertParallelMLP(8, 16, 4, expert_type="dense")
