"""Expert-parallel MoE parity: the all_to_all dispatch over an 'expert'
mesh axis must match running the same per-shard routing math locally —
outputs and gradients — and the aux loss must be finite and O(1)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import expert_parallel as ep
from conftest import assert_trees_close


def moe_and_params(E=8, d=8, h=16, seed=12, cap=2.0):
    moe = ep.ExpertParallelMLP(d, h, E, capacity_factor=cap)
    params, _ = moe.init(jax.random.PRNGKey(seed))
    return moe, params


def _ref_sharded(moe, params, x, n_shards):
    """Reference: each token shard routed independently (ep=1 path,
    outside any mesh), concatenated — the exact per-shard capacity
    semantics of the sharded run."""
    outs = [moe(params, xs) for xs in np.split(np.asarray(x), n_shards)]
    return jnp.concatenate([jnp.asarray(o) for o in outs])


def specs_of(moe, params):
    from apex_tpu.parallel import tensor_parallel as tp
    s = tp.partition_specs(moe, params)
    assert s["w_in"] == P("expert", None, None)
    assert s["router"] == P()
    return s


def test_moe_forward_matches_per_shard_reference():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe, params = moe_and_params()
    specs = specs_of(moe, params)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)

    y = jax.jit(jax.shard_map(
        lambda p, xb: moe(p, xb), mesh=mesh,
        in_specs=(specs, P("expert")), out_specs=P("expert"),
        check_vma=False))(params, x)
    y_ref = _ref_sharded(moe, params, x, 4)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               atol=2e-5)


def test_moe_capacity_drops_tokens():
    """capacity_factor small enough forces drops: output rows for
    dropped tokens are zero, and nothing NaNs."""
    moe, params = moe_and_params(cap=0.25)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)
    y = moe(params, x)
    assert np.isfinite(np.asarray(y)).all()
    zero_rows = np.sum(np.all(np.asarray(y) == 0.0, axis=-1))
    assert zero_rows > 0          # with C=ceil(0.25*16/8)=1 some drop


def test_moe_gradients_match_per_shard_reference():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe, params = moe_and_params()
    specs = specs_of(moe, params)
    x = jnp.asarray(np.random.RandomState(2).randn(16, 8), jnp.float32)

    def sharded_grad(p, xb):
        g = jax.grad(lambda pp: jnp.sum(jnp.square(moe(pp, xb))))(p)
        # the router is data-parallel over the expert axis (each device
        # routed only its token shard): sum its grad like DDP would
        g["router"] = lax.psum(g["router"], "expert")
        return g

    g_tp = jax.jit(jax.shard_map(
        sharded_grad, mesh=mesh, in_specs=(specs, P("expert")),
        out_specs=specs, check_vma=False))(params, x)

    def ref_loss(p):
        return jnp.sum(jnp.square(_ref_sharded(moe, p, x, 4)))

    assert_trees_close(g_tp, jax.grad(ref_loss)(params), atol=3e-5)


def test_moe_aux_loss():
    moe, params = moe_and_params()
    x = jnp.asarray(np.random.RandomState(3).randn(16, 8), jnp.float32)
    y, aux = moe(params, x, return_aux_loss=True)
    # Switch aux: >= 1 (perfect balance) and modest for random routing
    assert 0.9 < float(aux) < 8.0
    assert y.shape == x.shape


def test_moe_expert_divisibility_check():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    moe, params = moe_and_params(E=6)     # 6 experts, ep=4
    x = jnp.zeros((8, 8))
    # replicated params so shard_map's own shape check doesn't fire
    # first — the module's divisibility error is the one users see
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(jax.shard_map(
            lambda p, xb: moe(p, xb), mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                      P("expert")),
            out_specs=P("expert"), check_vma=False))(params, x)
