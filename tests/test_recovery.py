"""Elastic fault-tolerant training (fleet/recovery.py): a replica can
die mid-step and the job continues — shrink the data axis, re-jit on
the survivors, redistribute ZeRO-1 shards, resume from the last
checksum-durable snapshot.

The acceptance pin: the post-recovery loss trajectory must match an
undisturbed run at the shrunk world size, both resumed from the same
snapshot — same restored state, same batches, same re-jitted step, so
the documented tolerance is float round-off (rtol 1e-6; empirically
bitwise on the CPU mesh).  Fault timelines use the seeded
half-open-window harness (fleet/faults.py TrainingFaults), so every
death/tear lands at an exact observed step."""

import os
import signal
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import amp, nn, optimizers, parallel
from apex_tpu import observability as obs
from apex_tpu.data import DataLoader
from apex_tpu.fleet import (ElasticConfig, ElasticTrainer,
                            PreemptionGuard, RecoveryError,
                            TrainingFaults, reshard_flat_state)
from apex_tpu.nn import functional as F
from apex_tpu.observability.exporters import (JsonlExporter,
                                              validate_recovery_record)
from apex_tpu.utils import checkpoint as ckpt


def _np_tree(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


# -- plain-DDP elastic run (replicated state, SGD) -----------------------

def _ddp_build_step(model, ddp, lr=0.05):
    def build_step(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))

        def step(state, batch):
            params, nan_steps = state
            xb, yb = batch

            def loss_fn(p):
                out, _ = model.apply(p, xb, train=True)
                return F.cross_entropy(out, yb)

            loss, g = jax.value_and_grad(loss_fn)(params)
            g = ddp.allreduce_grads(g)
            params = jax.tree_util.tree_map(
                lambda p, gg: p - lr * gg, params, g)
            loss = lax.pmean(loss, "data")
            # in-graph numerics residue: counts the nonfinite losses
            # this state has EVER trained through — the "no stale
            # pre-fault numerics state" probe (a rolled-back state
            # must not remember the poisoned step)
            nan_steps = nan_steps + (
                ~jnp.isfinite(loss)).astype(jnp.int32)
            return (params, nan_steps), loss

        return jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), (P("data"), P("data"))),
            out_specs=(P(), P()), check_vma=False))
    return build_step


def _mlp():
    net = nn.Sequential([nn.Flatten(), nn.Linear(24, 16), nn.ReLU(),
                         nn.Linear(16, 10)])
    params, _ = net.init(jax.random.PRNGKey(0))
    return net, params


def _batches(n, b=16, d=24, seed=3):
    rng = np.random.RandomState(seed)
    return [(jnp.asarray(rng.randn(b, d), jnp.float32),
             jnp.asarray(rng.randint(0, 10, b), jnp.int32))
            for _ in range(n)]


def test_replica_death_shrinks_world_and_matches_undisturbed(tmp_path):
    model, params = _mlp()
    ddp = parallel.DistributedDataParallel(model)
    build = _ddp_build_step(model, ddp)
    state0 = (params, jnp.zeros((), jnp.int32))
    batches = _batches(12)
    ring = obs.EventRing(256)
    sup = obs.RunSupervisor("elastic_test", ring=ring,
                            registry=obs.MetricsRegistry())

    faults = TrainingFaults(replica_death=(5, 6), seed=0, ring=ring)
    trainer = ElasticTrainer(
        build, state0, world=8, ckpt_dir=str(tmp_path),
        to_host=_np_tree, supervisor=sup, faults=faults,
        config=ElasticConfig(checkpoint_every=2, min_world=1),
        ring=ring, registry=obs.MetricsRegistry(), run="elastic_test")
    trainer.run(10, lambda i: batches[i])

    assert trainer.world == 4
    assert trainer.recoveries == 1
    assert trainer.resumed_step == 4          # last durable snapshot
    # run completed: committed steps 0..9, with 4..9 replayed/continued
    # on the shrunk world
    assert trainer.history[-1][0] == 9
    post = [(s, loss) for s, loss, w in trainer.history if w == 4]
    assert [s for s, _ in post] == list(range(4, 10))

    # undisturbed shrunk-world run from the SAME snapshot: restore the
    # step-4 snapshot, re-jit at world 4, run the same batches —
    # trajectories must match within the documented tolerance
    template = _np_tree(state0)
    restored = ckpt.restore_checkpoint(str(tmp_path), template, step=4)
    step4 = build(4)
    st = restored
    undisturbed = []
    for i in range(4, 10):
        st, loss = step4(st, batches[i])
        undisturbed.append(float(loss))
    np.testing.assert_allclose([loss for _, loss in post],
                               undisturbed, rtol=1e-6)

    # MTTR + record + ring story
    rec = JsonlExporter.enrich(trainer.record())
    assert validate_recovery_record(rec) == []
    assert rec["world"] == 4 and rec["recoveries"] == 1
    assert rec["mttr_s"]["count"] == 1 and rec["mttr_s"]["last"] >= 0
    kinds = [ev["kind"] for ev in ring.snapshot()]
    for k in ("fault_injected", "recovery_started", "recovery_action",
              "recovery_done", "run_recovery_begin",
              "run_recovery_end"):
        assert k in kinds, k
    acts = [a["kind"] for a in rec["actions"]]
    assert acts == ["world_shrink", "resume"]
    # the supervisor exits recovery LIVE (no 503 flap mid-shrink)
    ok, detail = sup.health_check()
    assert ok
    assert sup.status()["recoveries"] == 1


def test_torn_snapshot_skipped_falls_back_to_durable(tmp_path):
    model, params = _mlp()
    ddp = parallel.DistributedDataParallel(model)
    build = _ddp_build_step(model, ddp)
    state0 = (params, jnp.zeros((), jnp.int32))
    batches = _batches(12)
    ring = obs.EventRing(256)
    # checkpoint_saved telemetry goes to the PROCESS ring (the
    # supervisor watermark contract) — point it at this test's ring
    # so the whole story lands in one place
    prev_ring = obs.get_ring()
    obs.set_ring(ring)

    # snapshot cadence 2 -> snapshots at observed steps 2 and 4; the
    # torn window [4, 5) corrupts the step-4 write AFTER its atomic
    # rename (out-of-band tear), the death at 5 forces a resume: the
    # controller must skip the torn snapshot and fall back to step 2
    faults = TrainingFaults(replica_death=(5, 6),
                            torn_checkpoint=(4, 5), seed=0, ring=ring)
    trainer = ElasticTrainer(
        build, state0, world=8, ckpt_dir=str(tmp_path),
        to_host=_np_tree, faults=faults,
        config=ElasticConfig(checkpoint_every=2, min_world=1),
        ring=ring, registry=obs.MetricsRegistry(), run="torn")
    try:
        trainer.run(8, lambda i: batches[i])
    finally:
        obs.set_ring(prev_ring)
    assert trainer.resumed_step == 2
    assert trainer.world == 4
    assert trainer.history[-1][0] == 7

    events = ring.snapshot()
    skipped = [ev for ev in events if ev["kind"] == "snapshot_skipped"]
    assert [ev["step"] for ev in skipped] == [4]
    # every checkpoint_saved event named a snapshot that verified at
    # durability time (the tear happened out-of-band AFTER the atomic
    # rename); the replay past step 4 re-saved it, healing the file —
    # so by end of run every on-disk snapshot verifies again and the
    # step-4 path carries TWO save events (the torn original + the
    # healing re-save after the fallback resume)
    saved = [ev["path"] for ev in events
             if ev["kind"] == "checkpoint_saved"]
    assert faults.torn_paths and set(faults.torn_paths) <= set(saved)
    assert saved.count(faults.torn_paths[0]) == 2
    for step in ckpt.available_steps(str(tmp_path)):
        ckpt.verify_checkpoint(str(tmp_path), step)
    assert ckpt.latest_durable_step(str(tmp_path)) \
        == max(ckpt.available_steps(str(tmp_path)))


def test_nan_verdict_rolls_back_with_no_stale_numerics(tmp_path):
    model, params = _mlp()
    ddp = parallel.DistributedDataParallel(model)
    build = _ddp_build_step(model, ddp)
    state0 = (params, jnp.zeros((), jnp.int32))
    batches = _batches(12)
    poisoned = {"done": False}

    def data_fn(i):
        x, y = batches[i]
        if i == 6 and not poisoned["done"]:
            # one-shot poison: the first visit to step 6 trains
            # through a NaN batch; the post-rollback replay is clean
            poisoned["done"] = True
            return x.at[0, 0].set(jnp.nan), y
        return x, y

    sup = obs.RunSupervisor("nan_rollback", ring=obs.EventRing(128),
                            registry=obs.MetricsRegistry())
    trainer = ElasticTrainer(
        build, state0, world=8, ckpt_dir=str(tmp_path),
        to_host=_np_tree, supervisor=sup,
        config=ElasticConfig(checkpoint_every=2, min_world=1),
        registry=obs.MetricsRegistry(), run="nan_rollback")
    trainer.run(10, data_fn)

    # the verdict triggered a rollback at the SAME world (a NaN is
    # numerics, not hardware)
    assert trainer.world == 8
    assert trainer.recoveries == 1
    assert trainer.resumed_step == 6
    rec = trainer.record()
    assert [a["kind"] for a in rec["actions"]] == ["rollback"]
    # the NaN was observed once (history keeps the honest record) ...
    nan_rows = [row for row in trainer.history
                if not np.isfinite(row[1])]
    assert len(nan_rows) == 1 and nan_rows[0][0] == 6
    # ... but the final state carries NO stale pre-fault numerics:
    # the in-graph nonfinite counter of the committed state is 0 —
    # the rolled-back state never trained through the poison
    _, nan_steps = trainer._state
    assert int(nan_steps) == 0
    assert float(trainer.history[-1][1]) == pytest.approx(
        float(trainer.history[-1][1]))  # finite (not NaN)
    assert np.isfinite(trainer.history[-1][1])
    assert sup.status()["anomaly_counts"]["nan"] == 1
    ok, _ = sup.health_check()
    assert ok


# -- ZeRO-1 shard redistribution -----------------------------------------

def test_zero1_shards_redistribute_onto_survivors(tmp_path):
    net = nn.Sequential([nn.Flatten(), nn.Linear(24, 10)])
    model, optimizer = amp.initialize(
        net, optimizers.FusedAdam(lr=1e-2), opt_level="O2",
        verbosity=0, hard_override=True)
    params, _ = model.init(jax.random.PRNGKey(0))
    ospecs = amp.zero_optimizer_specs(optimizer, params, "data")
    total = optimizer.init(params).masters.buf.size
    batches = _batches(10)

    def build_step(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))

        def step(state, batch):
            p, ost = state
            xb, yb = batch

            def loss_fn(pp):
                out, _ = model.apply(pp, xb, train=True)
                return F.cross_entropy(out, yb)

            loss, g = amp.scaled_grad(loss_fn, p, ost)
            # no pre-allreduce: ZeRO-1 reduce-scatters inside step()
            p, ost, _ = optimizer.step(p, ost, g)
            return (p, ost), lax.pmean(loss, "data")

        return jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=((P(), ospecs), (P("data"), P("data"))),
            out_specs=((P(), ospecs), P()), check_vma=False))

    def init_state(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
        opt0 = jax.jit(jax.shard_map(
            lambda pp: optimizer.init(pp, zero_axis="data"),
            mesh=mesh, in_specs=(P(),), out_specs=ospecs,
            check_vma=False))(params)
        return (params, opt0)

    def to_host(state):
        # canonical = world-independent: slice the flat shard buffers
        # back to their logical length (pad-for-world-1 == unpadded);
        # the padding world is inferred from the buffer length
        p, ost = _np_tree(state)
        buf_len = ost.masters.buf.shape[0]
        old_world = next(w for w in (8, 4, 2, 1)
                         if buf_len == total + (-total) % w)
        return (p, reshard_flat_state(ost, total, old_world, 1))

    def from_host(tree, world):
        p, ost = tree
        return (p, reshard_flat_state(ost, total, 1, world))

    faults = TrainingFaults(replica_death=(3, 4), seed=0)
    trainer = ElasticTrainer(
        build_step, init_state(8), world=8, ckpt_dir=str(tmp_path),
        to_host=to_host, from_host=from_host, faults=faults,
        config=ElasticConfig(checkpoint_every=1, min_world=1),
        registry=obs.MetricsRegistry(), run="zero_elastic")
    trainer.run(7, lambda i: batches[i])

    assert trainer.world == 4
    assert trainer.resumed_step == 3
    # the flat optimizer shards were REDISTRIBUTED: the live state's
    # master buffer is padded for the 4-survivor world, not the
    # original 8
    _, ost = trainer._state
    assert ost.masters.buf.shape[0] == total + (-total) % 4
    assert trainer.history[-1][0] == 6

    # undisturbed shrunk-world run from the same snapshot
    template = to_host(init_state(8))
    restored = ckpt.restore_checkpoint(str(tmp_path), template, step=3)
    st = from_host(restored, 4)
    step4 = build_step(4)
    undisturbed = []
    for i in range(3, 7):
        st, loss = step4(st, batches[i])
        undisturbed.append(float(loss))
    post = [loss for s, loss, w in trainer.history if w == 4]
    np.testing.assert_allclose(post, undisturbed, rtol=1e-6)


def _retree(ost, specs):
    # transplant the state's leaves into the spec tree's treedef: the
    # ZeRO-2/3 layout (zero_ici) is FlatMasters aux data, so a state
    # resharded for a different world must also carry the new world's
    # layout before shard_map will accept it against the new specs
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(specs),
        jax.tree_util.tree_leaves(ost))


def _dedup_slices(ost, padded, dcn):
    # stage-2/3 host view is the device-concat over the FULL axis: the
    # padded in-slice concat repeated dcn times (slices hold bitwise
    # identical shards after the DCN reduce) — keep one copy
    def fix(a):
        if getattr(a, "ndim", 0) == 1 and a.shape[0] == dcn * padded:
            return a[:padded]
        return a
    return jax.tree_util.tree_map(fix, ost)


def _tile_slices(ost, padded, dcn):
    # inverse of _dedup_slices: rebuild the device-concat global by
    # repeating the slice concat across the DCN dimension
    def fix(a):
        if getattr(a, "ndim", 0) == 1 and a.shape[0] == padded:
            return np.concatenate([np.asarray(a)] * dcn)
        return a
    return jax.tree_util.tree_map(fix, ost)


def test_zero2_shards_redistribute_onto_survivors_hierarchical(tmp_path):
    # 8 -> 4 world shrink where the ICI slice shrinks with it (4 -> 2):
    # stage-2 shards live on the slice, so the redistribution population
    # is layout.zero_ici, not the world — reshard_flat_state gets
    # (old_ici, new_ici) and the state is re-treed onto the new layout
    net = nn.Sequential([nn.Flatten(), nn.Linear(24, 10)])
    model, optimizer = amp.initialize(
        net, optimizers.FusedAdam(lr=1e-2), opt_level="O2",
        verbosity=0, hard_override=True)
    params, _ = model.init(jax.random.PRNGKey(0))
    total = optimizer.init(params).masters.buf.size
    batches = _batches(10)

    def ici_of(world):
        return max(world // 2, 1)

    def ospecs_for(world):
        return amp.zero_optimizer_specs(
            optimizer, params, "data", zero_stage=2,
            zero_ici_size=ici_of(world))

    def build_step(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
        ospecs = ospecs_for(world)

        def step(state, batch):
            p, ost = state
            xb, yb = batch

            def loss_fn(pp):
                out, _ = model.apply(pp, xb, train=True)
                return F.cross_entropy(out, yb)

            loss, g = amp.scaled_grad(loss_fn, p, ost)
            # stage 2 reduce-scatters in-slice + DCN-reduces inside
            p, ost, _ = optimizer.step(p, ost, g)
            return (p, ost), lax.pmean(loss, "data")

        return jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=((P(), ospecs), (P("data"), P("data"))),
            out_specs=((P(), ospecs), P()), check_vma=False))

    def init_state(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
        opt0 = jax.jit(jax.shard_map(
            lambda pp: optimizer.init(
                pp, zero_axis="data", zero_stage=2,
                zero_ici_size=ici_of(world)),
            mesh=mesh, in_specs=(P(),), out_specs=ospecs_for(world),
            check_vma=False))(params)
        return (params, opt0)

    def to_host(state):
        # canonical form: population-1 buffers + the ici=1 layout, so
        # snapshots taken at any world share one treedef
        p, ost = _np_tree(state)
        buf_len = ost.masters.buf.shape[0]
        old_ici = next(i for i in (4, 2, 1)
                       if buf_len == 2 * (total + (-total) % i))
        padded = buf_len // 2
        ost = _dedup_slices(ost, padded, 2)
        ost = reshard_flat_state(ost, total, old_ici, 1)
        return (p, _retree(ost, ospecs_for(2)))

    def from_host(tree, world):
        p, ost = tree
        ici = ici_of(world)
        ost = reshard_flat_state(ost, total, 1, ici)
        ost = _tile_slices(ost, total + (-total) % ici, world // ici)
        return (p, _retree(ost, ospecs_for(world)))

    faults = TrainingFaults(replica_death=(3, 4), seed=0)
    trainer = ElasticTrainer(
        build_step, init_state(8), world=8, ckpt_dir=str(tmp_path),
        to_host=to_host, from_host=from_host, faults=faults,
        config=ElasticConfig(checkpoint_every=1, min_world=2),
        registry=obs.MetricsRegistry(), run="zero2_elastic")
    trainer.run(7, lambda i: batches[i])

    assert trainer.world == 4
    assert trainer.resumed_step == 3
    # shards were redistributed for the SHRUNK slice: the global view
    # is dcn(2) copies of the concat padded for ici 2, not ici 4
    _, ost = trainer._state
    assert ost.masters.buf.shape[0] == 2 * (total + (-total) % ici_of(4))
    assert ost.masters.layout.zero_ici == ici_of(4)
    assert trainer.history[-1][0] == 6

    # undisturbed shrunk-world run from the same snapshot
    template = to_host(init_state(8))
    restored = ckpt.restore_checkpoint(str(tmp_path), template, step=3)
    st = from_host(restored, 4)
    step4 = build_step(4)
    undisturbed = []
    for i in range(3, 7):
        st, loss = step4(st, batches[i])
        undisturbed.append(float(loss))
    post = [loss for s, loss, w in trainer.history if w == 4]
    np.testing.assert_allclose(post, undisturbed, rtol=1e-6)


def test_zero3_torn_snapshot_falls_back_and_reshards(tmp_path):
    # ZeRO-3: the master shard IS the parameter store, so the elastic
    # snapshot carries the whole model inside the flat shard buffers —
    # a torn snapshot must fall back to the previous durable one and
    # the fallback state must reshard 8 -> 4 (ici 4 -> 2) cleanly
    net = nn.Sequential([nn.Flatten(), nn.Linear(24, 10)])
    model, optimizer = amp.initialize(
        net, optimizers.FusedAdam(lr=1e-2), opt_level="O2",
        verbosity=0, hard_override=True)
    params, _ = model.init(jax.random.PRNGKey(0))
    total = optimizer.init(params).masters.buf.size
    batches = _batches(10)

    def ici_of(world):
        return max(world // 2, 1)

    def ospecs_for(world):
        return amp.zero_optimizer_specs(
            optimizer, params, "data", zero_stage=3,
            zero_ici_size=ici_of(world))

    def build_step(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
        ospecs = ospecs_for(world)

        def step(ost, batch):
            xb, yb = batch

            def loss_fn(m):
                # just-in-time gather: no replicated params in the state
                pp = amp.zero_gather_params(m)
                out, _ = model.apply(pp, xb, train=True)
                return F.cross_entropy(out, yb)

            loss, g = amp.scaled_grad(loss_fn, ost.masters, ost)
            _, ost, _ = optimizer.step((), ost, g)
            return ost, lax.pmean(loss, "data")

        return jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(ospecs, (P("data"), P("data"))),
            out_specs=(ospecs, P()), check_vma=False))

    def init_state(world):
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
        return jax.jit(jax.shard_map(
            lambda pp: optimizer.init(
                pp, zero_axis="data", zero_stage=3,
                zero_ici_size=ici_of(world)),
            mesh=mesh, in_specs=(P(),), out_specs=ospecs_for(world),
            check_vma=False))(params)

    def to_host(ost):
        ost = _np_tree(ost)
        buf_len = ost.masters.buf.shape[0]
        old_ici = next(i for i in (4, 2, 1)
                       if buf_len == 2 * (total + (-total) % i))
        padded = buf_len // 2
        ost = _dedup_slices(ost, padded, 2)
        ost = reshard_flat_state(ost, total, old_ici, 1)
        return _retree(ost, ospecs_for(2))

    def from_host(ost, world):
        ici = ici_of(world)
        ost = reshard_flat_state(ost, total, 1, ici)
        ost = _tile_slices(ost, total + (-total) % ici, world // ici)
        return _retree(ost, ospecs_for(world))

    ring = obs.EventRing(256)
    prev_ring = obs.get_ring()
    obs.set_ring(ring)
    faults = TrainingFaults(replica_death=(5, 6),
                            torn_checkpoint=(4, 5), seed=0, ring=ring)
    trainer = ElasticTrainer(
        build_step, init_state(8), world=8, ckpt_dir=str(tmp_path),
        to_host=to_host, from_host=from_host, faults=faults,
        config=ElasticConfig(checkpoint_every=2, min_world=2),
        ring=ring, registry=obs.MetricsRegistry(), run="zero3_torn")
    try:
        trainer.run(8, lambda i: batches[i])
    finally:
        obs.set_ring(prev_ring)

    # torn step-4 snapshot skipped -> durable step-2 fallback, and the
    # restored stage-3 state landed resharded on the survivor slice
    assert trainer.resumed_step == 2
    assert trainer.world == 4
    assert trainer.history[-1][0] == 7
    skipped = [ev for ev in ring.snapshot()
               if ev["kind"] == "snapshot_skipped"]
    assert [ev["step"] for ev in skipped] == [4]
    ost = trainer._state
    assert ost.masters.buf.shape[0] == 2 * (total + (-total) % ici_of(4))
    assert ost.masters.layout.zero_ici == ici_of(4)

    # trajectory parity vs an undisturbed world-4 replay from step 2
    template = to_host(init_state(8))
    restored = ckpt.restore_checkpoint(str(tmp_path), template, step=2)
    st = from_host(restored, 4)
    step4 = build_step(4)
    undisturbed = []
    for i in range(2, 8):
        st, loss = step4(st, batches[i])
        undisturbed.append(float(loss))
    post = [loss for s, loss, w in trainer.history if w == 4]
    np.testing.assert_allclose(post, undisturbed, rtol=1e-6)


def test_reshard_flat_state_pads_and_slices_exactly():
    total = 10
    base = np.arange(total, dtype=np.float32)
    padded8 = np.pad(base, (0, 6))            # 16 = pad to 8
    tree = {"buf": padded8, "scalar": np.float32(3.0),
            "other": np.ones((3, 3), np.float32)}
    out = reshard_flat_state(tree, total, 8, 4)
    assert out["buf"].shape == (12,)          # pad to 4
    np.testing.assert_array_equal(out["buf"][:total], base)
    assert not out["buf"][total:].any()
    assert out["scalar"] == 3.0               # scalars untouched
    assert out["other"].shape == (3, 3)       # non-flat untouched
    with pytest.raises(ValueError):
        reshard_flat_state(tree, total, 0, 4)


# -- preemption-safe deterministic resume (PR 12) ------------------------

def _uint8_dataset(n=64):
    rng = np.random.RandomState(5)
    images = rng.randint(0, 256, (n, 4, 4, 3), np.uint8)
    labels = np.arange(n, dtype=np.int32)   # label == sample index
    return images, labels


def test_preempt_resume_matches_undisturbed(tmp_path):
    """THE acceptance pin: preempt a run mid-training (coordinated
    emergency snapshot at the step boundary, clean ``preempted``
    exit), resume in a fresh trainer with a fresh loader — the loss
    trajectory AND the consumed-sample-index sequence are identical
    to an undisturbed run."""
    images, labels = _uint8_dataset()
    net = nn.Sequential([nn.Flatten(), nn.Linear(48, 32), nn.ReLU(),
                         nn.Linear(32, 64)])
    params, _ = net.init(jax.random.PRNGKey(0))
    ddp = parallel.DistributedDataParallel(net)
    build = _ddp_build_step(net, ddp)
    state0 = (params, jnp.zeros((), jnp.int32))

    def make_loader():
        # the portable (checkpointable) stream; batch 16 splits over
        # the world-8 data axis
        return DataLoader(images, labels, batch_size=16, shuffle=True,
                          seed=7, native=False)

    def run_trainer(d, loader, log, **kw):
        def data_fn(i):
            imgs, lbls, _ = loader.next_batch()
            log.append(tuple(int(v) for v in lbls))
            return jnp.asarray(imgs), jnp.asarray(lbls)
        tr = ElasticTrainer(
            build, state0, world=8, ckpt_dir=str(d),
            to_host=_np_tree, data=loader,
            config=ElasticConfig(checkpoint_every=3, min_world=1),
            registry=obs.MetricsRegistry(), **kw)
        tr.run(10, data_fn)
        return tr

    und_log = []
    und = run_trainer(tmp_path / "und", make_loader(), und_log,
                      run="preempt_und")
    assert und.verdict == "completed"
    und_losses = [loss for _, loss, _ in und.history]

    ring = obs.EventRing(256)
    sup = obs.RunSupervisor("preempt_test", ring=ring,
                            registry=obs.MetricsRegistry())
    guard = PreemptionGuard(grace_s=60.0, ring=ring,
                            registry=obs.MetricsRegistry())
    faults = TrainingFaults(preemption=(4, 5), seed=0, ring=ring)
    pre_log = []
    pre = run_trainer(tmp_path / "pre", make_loader(), pre_log,
                      guard=guard, faults=faults, supervisor=sup,
                      ring=ring, run="preempt_run")
    # the notice was honored at the NEXT step boundary: step 4 (where
    # the fault fired) still committed, then snapshot + clean exit
    assert pre.verdict == "preempted" and pre.cause == "preemption"
    assert [s for s, _, _ in pre.history] == list(range(5))
    assert faults.guard is guard          # auto-wired by the trainer
    kinds = [ev["kind"] for ev in ring.snapshot()]
    for k in ("preemption_requested", "preempted", "run_preempted"):
        assert k in kinds, k
    acts = [a["kind"] for a in pre.record()["actions"]]
    assert acts == ["preempt_snapshot"]
    # the supervisor reports the clean, LIVE preempted state
    assert sup.preempted
    ok, detail = sup.health_check()
    assert ok and "preempted" in detail
    assert sup.status()["preempted_step"] == 5

    rec = JsonlExporter.enrich(pre.record())
    assert validate_recovery_record(rec) == []
    assert rec["cause"] == "preemption" and rec["preempted"] is True
    assert rec["data_state"]["samples_consumed"] == 5 * 16

    # resume: fresh trainer, fresh loader — the snapshot's data_state
    # positions the stream, resume_overhead is accounted
    res = run_trainer(tmp_path / "pre", make_loader(), pre_log,
                      resume=True, run="preempt_resumed")
    assert res.resumed_step == 5 and res.verdict == "completed"
    assert res.resume_overhead_s is not None \
        and res.resume_overhead_s >= 0
    assert [s for s, _, _ in res.history] == list(range(5, 10))

    res_losses = [loss for _, loss, _ in pre.history + res.history]
    np.testing.assert_allclose(res_losses, und_losses, rtol=1e-6)
    assert pre_log == und_log             # exact index sequence


def test_replica_death_with_loader_rewinds_data_exactly_once(
        tmp_path):
    """The kill half of the pin, with a real data pipeline: a replica
    death mid-step abandons a drawn batch; recovery restores the
    snapshot's data_state alongside the tree, so the loader rewinds
    WITH the model and every committed step consumes its sample slice
    exactly once — no drift from the abandoned draw, across the 8→4
    shrink."""
    images, labels = _uint8_dataset()
    net = nn.Sequential([nn.Flatten(), nn.Linear(48, 32), nn.ReLU(),
                         nn.Linear(32, 64)])
    params, _ = net.init(jax.random.PRNGKey(0))
    ddp = parallel.DistributedDataParallel(net)
    build = _ddp_build_step(net, ddp)
    state0 = (params, jnp.zeros((), jnp.int32))

    loader = DataLoader(images, labels, batch_size=16, shuffle=True,
                        seed=7, native=False)
    faults = TrainingFaults(replica_death=(5, 6), seed=0)
    trainer = ElasticTrainer(
        build, state0, world=8, ckpt_dir=str(tmp_path),
        to_host=_np_tree, data=loader, faults=faults,
        config=ElasticConfig(checkpoint_every=2, min_world=1),
        registry=obs.MetricsRegistry(), run="death_loader")
    trainer.run(10)                      # data= feeds the run
    assert trainer.world == 4 and trainer.resumed_step == 4

    # exactly-once: 10 committed steps = 10 global batches, despite
    # the abandoned draw at the death (its consumption was rewound
    # with the snapshot's data_state)
    assert loader.stats()["samples_consumed"] == 10 * 16

    # the post-shrink trajectory matches an undisturbed world-4 run
    # resumed from the SAME snapshot with a FRESH loader positioned
    # by the snapshot's data_state
    template = _np_tree(state0)
    restored = ckpt.restore_checkpoint(str(tmp_path), template, step=4)
    ds = ckpt.load_data_state(str(tmp_path), step=4)
    assert ds["samples_consumed"] == 4 * 16
    loader2 = DataLoader(images, labels, batch_size=16, shuffle=True,
                         seed=7, native=False)
    loader2.load_state_dict(ds)
    step4 = build(4)
    st, undisturbed = restored, []
    for i in range(4, 10):
        imgs, lbls, _ = loader2.next_batch()
        st, loss = step4(st, (jnp.asarray(imgs), jnp.asarray(lbls)))
        undisturbed.append(float(loss))
    post = [loss for s, loss, w in trainer.history if w == 4]
    np.testing.assert_allclose(post, undisturbed, rtol=1e-6)


def test_preemption_guard_sigterm_handler():
    """The real entry point: SIGTERM lands in the installed guard's
    handler; uninstall restores the previous handler."""
    ring = obs.EventRing(16)
    guard = PreemptionGuard(grace_s=5.0, ring=ring,
                            registry=obs.MetricsRegistry())
    prev = signal.getsignal(signal.SIGTERM)
    with guard:
        os.kill(os.getpid(), signal.SIGTERM)
        for _ in range(400):              # delivery is async-ish
            if guard.requested:
                break
            time.sleep(0.005)
        assert guard.requested
        assert "signal" in guard.reason
    assert signal.getsignal(signal.SIGTERM) is prev
    # double install is idempotent: uninstall still restores the
    # ORIGINAL handler, not the guard's own
    guard2 = PreemptionGuard(registry=obs.MetricsRegistry())
    guard2.install()
    guard2.install()
    guard2.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev
    (ev,) = ring.snapshot("preemption_requested")
    assert ev["grace_s"] == 5.0
    # idempotent: a second notice does not restart the grace clock
    t0 = guard.requested_at
    guard.preempt("again")
    assert guard.requested_at == t0 and "signal" in guard.reason


def test_preemption_with_exhausted_grace_skips_snapshot(tmp_path):
    """Grace already gone when the boundary arrives: exit WITHOUT
    starting a write — the last durable snapshot stays the resume
    point, and nothing is torn."""
    def build(world):
        return lambda st, b: ({"w": st["w"] + 1}, 1.0)

    ring = obs.EventRing(64)
    guard = PreemptionGuard(grace_s=0.0, ring=ring,
                            registry=obs.MetricsRegistry())
    faults = TrainingFaults(preemption=(2, 3), seed=0, ring=ring)
    trainer = ElasticTrainer(
        build, {"w": np.zeros(2, np.float32)}, world=4,
        ckpt_dir=str(tmp_path), guard=guard, faults=faults,
        config=ElasticConfig(checkpoint_every=5, min_world=1),
        ring=ring, registry=obs.MetricsRegistry(), run="nograce")
    trainer.run(6, lambda i: None)
    assert trainer.verdict == "preempted"
    # only the step-0 fallback snapshot exists — no emergency write
    assert ckpt.available_steps(str(tmp_path)) == [0]
    kinds = [ev["kind"] for ev in ring.snapshot()]
    assert "preemption_grace_exhausted" in kinds
    assert "preempt_snapshot" not in [
        a["kind"] for a in trainer.record()["actions"]]
    # a resumed trainer falls back to the durable step-0 snapshot
    res = ElasticTrainer(
        build, {"w": np.zeros(2, np.float32)}, world=4,
        ckpt_dir=str(tmp_path), resume=True,
        registry=obs.MetricsRegistry(), run="nograce_res")
    assert res.resumed_step == 0


def test_legacy_snapshot_without_data_state_is_loud(tmp_path):
    """A pipeline is attached but the snapshot cannot say where the
    stream stood: RecoveryError, not a silent divergence."""
    images, labels = _uint8_dataset()
    ckpt.save_checkpoint(str(tmp_path), 0,
                         {"w": np.zeros(2, np.float32)})

    def build(world):
        return lambda st, b: ({"w": st["w"] + 1}, 1.0)

    loader = DataLoader(images, labels, batch_size=16, shuffle=True,
                        native=False)
    with pytest.raises(RecoveryError, match="data_state"):
        ElasticTrainer(
            build, {"w": np.zeros(2, np.float32)}, world=4,
            ckpt_dir=str(tmp_path), data=loader, resume=True,
            registry=obs.MetricsRegistry(), run="legacy")


# -- recovery failure paths (loud, not loops) ----------------------------

def test_recovery_error_when_no_survivors(tmp_path):
    def build(world):
        return lambda st, b: ({"w": st["w"] + 1}, 1.0)

    faults = TrainingFaults(replica_death=(2, 3), seed=0)
    trainer = ElasticTrainer(
        build, {"w": np.zeros(2, np.float32)}, world=1,
        ckpt_dir=str(tmp_path), faults=faults,
        config=ElasticConfig(min_world=1),
        registry=obs.MetricsRegistry(), run="floor")
    with pytest.raises(RecoveryError, match="no survivors"):
        trainer.run(6, lambda i: None)


def test_recovery_error_when_budget_exhausted(tmp_path):
    def build(world):
        return lambda st, b: ({"w": st["w"] + 1}, 1.0)

    faults = TrainingFaults(replica_death=(2, None), seed=0)
    trainer = ElasticTrainer(
        build, {"w": np.zeros(2, np.float32)}, world=64,
        ckpt_dir=str(tmp_path), faults=faults,
        config=ElasticConfig(min_world=1, max_recoveries=2),
        registry=obs.MetricsRegistry(), run="budget")
    with pytest.raises(RecoveryError, match="budget"):
        trainer.run(20, lambda i: None)
    assert trainer.recoveries == 2


def test_recovery_error_when_no_durable_snapshot(tmp_path):
    def build(world):
        return lambda st, b: ({"w": st["w"] + 1}, 1.0)

    # tear EVERY snapshot (window [0, None)); the death then finds no
    # durable resume point
    faults = TrainingFaults(replica_death=(3, 4),
                            torn_checkpoint=(0, None),
                            seed=0)
    trainer = ElasticTrainer(
        build, {"w": np.zeros(2, np.float32)}, world=4,
        ckpt_dir=str(tmp_path), faults=faults,
        config=ElasticConfig(checkpoint_every=1, min_world=1),
        registry=obs.MetricsRegistry(), run="nodurable")
    with pytest.raises(RecoveryError, match="durable"):
        trainer.run(6, lambda i: None)
