"""LARC: layer-wise adaptive rate control, as an optimizer wrapper.

Parity with apex.parallel.LARC (LARC.py:68-97): per-parameter adaptive lr

    adaptive_lr = trust_coefficient * ||p|| / (||g|| + wd*||p|| + eps)

applied by rescaling gradients in place before the wrapped optimizer runs;
``clip=True`` caps the effective lr at the base lr
(``min(adaptive_lr/lr, 1)``), ``clip=False`` scales grads by adaptive_lr
directly.  Weight decay is absorbed into the gradient and zeroed on the
inner optimizer, exactly like the reference mutates param_groups.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..optimizers.base import Optimizer, resolve_lr

__all__ = ["LARC"]


class LARC(Optimizer):
    def __init__(self, optimizer: Optimizer, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps
        # absorb the inner weight decay (reference LARC.py:81-95 zeroes the
        # group's wd after folding it into the grad)
        self.weight_decay = float(getattr(optimizer, "weight_decay", 0.0))
        if self.weight_decay:
            optimizer.weight_decay = 0.0

    def __getattr__(self, name):
        return getattr(self.optim, name)

    def init(self, params: Any):
        return self.optim.init(params)

    def update(self, grads: Any, state: Any, params: Any):
        step = getattr(state, "step", jnp.zeros((), jnp.int32))
        lr = resolve_lr(self.optim.lr, step)
        wd = self.weight_decay
        tc = self.trust_coefficient
        eps = self.eps

        def rescale(p, g):
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            adaptive_lr = tc * p_norm / (g_norm + wd * p_norm + eps)
            # parameters with zero norm take the base lr (reference guards
            # p_norm/g_norm != 0, LARC.py:88)
            adaptive_lr = jnp.where((p_norm > 0) & (g_norm > 0),
                                    adaptive_lr, 1.0 if self.clip else 1.0)
            if self.clip:
                adaptive_lr = jnp.minimum(adaptive_lr / lr, 1.0)
            new_g = g32 + wd * p32
            return (new_g * adaptive_lr).astype(g.dtype)

        scaled = jax.tree_util.tree_map(rescale, params, grads)
        return self.optim.update(scaled, state, params)
