"""Adasum: adaptive gradient summation for data-parallel training.

From the retrieved technique paper "Scaling Distributed Training with
Adaptive Summation" (Maleki et al., arXiv:2006.02924): summing (or
averaging) replica gradients treats them as if computed at the same
point, which degrades once per-replica batches pull in conflicting
directions at large scale.  Adasum combines a pair instead as

    adasum(a, b) = (1 - a.b / (2 |a|^2)) a + (1 - a.b / (2 |b|^2)) b

— orthogonal gradients ADD (full step), identical gradients AVERAGE
(no double step), and anti-correlated components are damped; the
N-replica reduction applies the rule over a fixed binary tree.

TPU-native mapping: the reference implementation rides MPI's
recursive-halving allreduce; here the same fixed XOR butterfly is
``log2(N)`` ``lax.ppermute`` exchange stages over the mesh axis, each
stage combining two half-block values with the (symmetric) rule above
— every rank converges to the same result because the pairwise
combine inputs are identical within each half block AND combined in a
canonical low-block-first operand order (see the in-function comment
on FMA asymmetry).  The combiner is intentionally NOT associative; the
tree shape (XOR pairing) is fixed so the result is deterministic, and
is pinned bitwise against a host-side recursion of the same tree in
tests/test_adasum.py.

Dot products / norms are per-LEAF in fp32 (the paper's per-layer
granularity) — a whole-model dot would let one giant layer mask
conflicts in small ones.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["adasum_pair", "adasum_grads", "adasum_comm_plan"]


def adasum_pair(a: jax.Array, b: jax.Array) -> jax.Array:
    """The two-operand Adasum rule for one leaf, fp32 internals.
    Zero-norm operands degrade to plain addition (a zero gradient
    contributes nothing and must not zero the other side)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    na = jnp.vdot(af, af)
    nb = jnp.vdot(bf, bf)
    ca = jnp.where(na > 0, 1.0 - dot / (2.0 * jnp.where(na > 0, na, 1.0)),
                   1.0)
    cb = jnp.where(nb > 0, 1.0 - dot / (2.0 * jnp.where(nb > 0, nb, 1.0)),
                   1.0)
    return (ca * af + cb * bf).astype(a.dtype)


def adasum_grads(grads: Any, axis_name: str = "data",
                 ici_size: int = 1) -> Any:
    """Adasum-combine ``grads`` across the mapped ``axis_name``
    (replacing the plain psum/pmean of a DDP allreduce).  Call inside
    ``shard_map``.  Returns the combined tree, identical on every rank.

    ``ici_size > 1`` is the hierarchical composition (the paper's
    average-within-node recipe mapped to the ICI/DCN split, matching
    ``comm_topology='hierarchical'``'s rank layout): gradients are
    plain-AVERAGED within each consecutive ``ici_size``-rank ICI slice
    — replicas of one host see near-identical data distributions, where
    averaging is the right combine and the fast fabric makes it cheap —
    and the adaptive-summation butterfly runs ACROSS slices only, so
    each ppermute stage crosses DCN once with the per-slice mean.  The
    slice mean divides by ``ici_size`` exactly once and the butterfly
    never divides, so no double-averaging across levels.  The number of
    slices must be a power of two (the fixed XOR tree); ``ici_size=1``
    is the flat butterfly over all ranks.

    **Bandwidth cost** (the side the VERDICT "justify Adasum"
    experiment weighs): every one of the ``log2(S)`` butterfly stages
    (``S`` = slices) exchanges the FULL fp32 flat buffer — each rank
    must see its partner's entire gradient to form the per-leaf dot
    products — so one rank puts ``log2(S) * 4n`` bytes on the wire
    (plus the in-slice pmean's ``4n`` when ``ici_size > 1``).  The
    plain psum the butterfly replaces costs ``~2n`` elements (``~8n``
    bytes fp32) per rank under recursive halving (reduce-scatter +
    all-gather), and MPI Adasum rides that same recursive-halving
    shape by combining *half-blocks* per stage; the XOR butterfly
    trades that bandwidth for one collective per stage and a
    deterministic tree on the mesh axis.  At ``S = 8`` that is
    ``12n`` bytes — ``1.5x`` the fp32 psum traffic, ``3x`` a
    bf16-compressed wire — and the gap widens by ``4n`` bytes per
    doubling of ``S``.  :func:`adasum_comm_plan` states the exact
    exchanged bytes so comm accounting can price it."""
    n = lax.axis_size(axis_name)
    ici = int(ici_size)
    if ici < 1 or n % ici:
        raise ValueError(f"ici_size {ici} must be >= 1 and divide the "
                         f"axis size {n}")
    n_slices = n // ici
    if n_slices & (n_slices - 1):
        raise ValueError(f"adasum needs a power-of-two number of "
                         f"slices, got {n_slices} ({n} ranks / "
                         f"ici_size {ici}) on axis {axis_name!r}")
    idx = lax.axis_index(axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    sizes = [l.size for l in leaves]
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    offs = np.concatenate([[0], np.cumsum(sizes)]).tolist()
    # ONE fp32 exchange buffer per stage instead of one ppermute per
    # leaf — log2(N) collectives total, not log2(N) x num_leaves tiny
    # ones (the flat_dist_call lesson applied here); the Adasum dots
    # stay PER-LEAF on segment views.
    flat = jnp.concatenate(
        [l.astype(jnp.float32).ravel() for l in leaves])
    if ici > 1:
        from .topology import hierarchical_axis_groups
        ici_groups, _ = hierarchical_axis_groups(n, ici)
        flat = lax.pmean(flat, axis_name, axis_index_groups=ici_groups)
    # butterfly over the slice index: rank h*ici + j pairs with its
    # same-offset peer (h ^ stride)*ici + j in the partner slice
    sid = idx // ici
    stages = n_slices.bit_length() - 1
    for s in range(stages):
        stride = 1 << s
        perm = [(h * ici + j, (h ^ stride) * ici + j)
                for h in range(n_slices) for j in range(ici)]
        theirs = lax.ppermute(flat, axis_name, perm)
        # canonical low-block-first operand order: mathematically the
        # pair rule is symmetric, but XLA's FMA fusion is not — in
        # ca*a + cb*b one product is fused into the add and the other
        # is rounded separately, so partners combining in swapped
        # operand order drift by ulps and the butterfly's
        # consistent-within-block invariant decays stage by stage
        # (observed on the CPU backend; pinned by the cross-rank
        # bitwise-equality test).  The block test runs on the SLICE
        # index, so the hierarchical and flat trees agree rank-for-rank
        # when ici_size == 1.
        low = (sid & stride) == 0
        a = jnp.where(low, flat, theirs)
        b = jnp.where(low, theirs, flat)
        flat = jnp.concatenate(
            [adasum_pair(a[offs[i]:offs[i + 1]],
                         b[offs[i]:offs[i + 1]])
             for i in range(len(leaves))])
    out = [flat[offs[i]:offs[i + 1]].reshape(shapes[i]).astype(
        dtypes[i]) for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, out)


def adasum_comm_plan(grads: Any, world: int,
                     ici_size: int = 1) -> List[Dict[str, Any]]:
    """Static wire accounting of :func:`adasum_grads` — the Adasum twin
    of ``parallel.allreduce_comm_plan``, computed from shapes alone.

    One plan bucket for the whole flat fp32 exchange buffer:
    ``log2(world / ici_size)`` ppermute stages of the full ``4n``-byte
    buffer (each stage crosses slices, i.e. DCN under the hierarchical
    layout) plus, when ``ici_size > 1``, the in-slice pmean (one psum
    eqn, ICI).  ``eqns`` / ``eqn_payload_bytes`` fold through
    ``plan_collective_expectations`` like any DDP bucket, and
    ``wire_bytes`` is what ``DistributedDataParallel``'s adasum branch
    now records — the exchanged-byte cost side of the VERDICT item-5
    "justify or demote Adasum" experiment."""
    leaves = jax.tree_util.tree_leaves(grads)
    n = sum(int(np.prod(getattr(l, "shape", ()) or (1,)))
            for l in leaves)
    ici = int(ici_size)
    world = int(world)
    if ici < 1 or world % ici:
        raise ValueError(f"ici_size {ici} must be >= 1 and divide the "
                         f"axis size {world}")
    n_slices = world // ici
    if n_slices & (n_slices - 1):
        raise ValueError(f"adasum needs a power-of-two number of "
                         f"slices, got {n_slices}")
    stages = n_slices.bit_length() - 1
    buf_bytes = n * 4                       # fp32 exchange buffer
    dcn_bytes = stages * buf_bytes          # butterfly crosses slices
    ici_bytes = buf_bytes if ici > 1 else 0  # in-slice pmean
    eqns: Dict[str, int] = {}
    payload: Dict[str, int] = {}
    if stages:
        eqns["ppermute"] = stages
        payload["ppermute"] = dcn_bytes
    if ici > 1:
        eqns["psum"] = 1                    # pmean traces as psum + div
        payload["psum"] = ici_bytes
    total = dcn_bytes + ici_bytes
    return [{
        "dtype": "float32", "comm_dtype": "float32",
        "leaves": len(leaves), "elements": n, "chunks": 1,
        "cause": "adasum",
        "topology": "hierarchical" if ici > 1 else "flat",
        "ici_size": ici, "dcn_size": n_slices, "stages": stages,
        "wire_elements": n, "padded_elements": 0,
        "bytes": total, "wire_bytes": total,
        # flat convention matches _bucket_wire_accounting: with no
        # level split every byte is charged to both fabrics
        "ici_wire_bytes": ici_bytes if ici > 1 else total,
        "dcn_wire_bytes": dcn_bytes if ici > 1 else total,
        "dcn_comm_dtype": "float32",
        "eqns": eqns, "eqn_payload_bytes": payload}]
