"""Multi-process launcher + process-group bootstrap.

The reference ships a legacy one-process-per-GPU spawner
(apex/parallel/multiproc.py:12-35: read WORLD_SIZE, fork
``main.py --rank i`` per device, wait on children).  The TPU-native
equivalent is one process per *host*, wired together with
``jax.distributed.initialize`` so XLA collectives span hosts over DCN and
every process sees the global device set.

Two pieces:

- ``init_process_group()`` — called by the *trainee* script; reads the
  env wiring (ours or the standard JAX_* names) and brings up the
  distributed runtime. On a single process it is a no-op, mirroring the
  reference's world_size==1 passthrough paths.
- ``python -m apex_tpu.parallel.multiproc [--nprocs N] script.py args...``
  — the *launcher*: spawns N local processes with the wiring set, streams
  their output, and exits non-zero if any child fails (killing the
  survivors, which would otherwise block in distributed init). With
  ``--backend cpu`` each child runs on host-platform devices, giving a
  real multi-process collective runtime on one machine — the analogue of
  the reference's single-node ``torch.distributed.launch
  --nproc_per_node=2`` test setup
  (tests/L1/cross_product_distributed/run.sh).  The default ``auto``
  inherits the environment's platform; on a host with a single TPU,
  multiple children would contend for it — pass ``--backend cpu`` there
  (the launcher warns).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import Optional

ENV_RANK = "APEX_TPU_RANK"
ENV_WORLD = "APEX_TPU_WORLD_SIZE"
ENV_COORD = "APEX_TPU_COORDINATOR"


def init_process_group(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> int:
    """Bring up ``jax.distributed`` from explicit args or env wiring.

    Returns the process id (rank). No-op (rank 0) when unwired, so scripts
    run unmodified both standalone and under the launcher.
    """
    coordinator_address = coordinator_address or os.environ.get(ENV_COORD)
    if num_processes is None:
        num_processes = int(os.environ.get(ENV_WORLD, "1"))
    if process_id is None:
        process_id = int(os.environ.get(ENV_RANK, "0"))
    if num_processes <= 1 or coordinator_address is None:
        return 0
    import jax
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return process_id


def _probe_free_port() -> int:
    """Ask the kernel for a free TCP port for the coordinator.  The
    reference's launcher hardcodes 29500 (and so did round 1 here,
    parallel/multiproc.py:72) — two concurrent groups on one host then
    collide; an OS-assigned ephemeral port cannot."""
    import socket
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.parallel.multiproc",
        description="spawn N local processes wired into one jax.distributed "
                    "process group")
    p.add_argument("--nprocs", type=int,
                   default=int(os.environ.get("WORLD_SIZE", "2")))
    p.add_argument("--port", type=int, default=0,
                   help="coordinator port; 0 probes for a free one "
                        "(default; a fixed 29500 collides with any other "
                        "group on the host)")
    p.add_argument("--backend", choices=["auto", "cpu"], default="auto",
                   help="cpu forces host-platform devices in the children")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="host-platform device count per child (cpu backend)")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    port = args.port or _probe_free_port()
    coord = f"127.0.0.1:{port}"
    if (args.backend == "auto" and args.nprocs > 1
            and os.environ.get("JAX_PLATFORMS", "") != "cpu"):
        print("[multiproc] warning: --backend auto inherits the "
              "environment's platform; if this host has a single "
              f"accelerator, all {args.nprocs} children will contend for "
              "it — pass --backend cpu for local multi-process runs",
              file=sys.stderr)
    children = []
    for rank in range(args.nprocs):
        env = dict(os.environ)
        env[ENV_RANK] = str(rank)
        env[ENV_WORLD] = str(args.nprocs)
        env[ENV_COORD] = coord
        # reference-compatible names so unmodified scripts can read them
        env["RANK"] = str(rank)
        env["WORLD_SIZE"] = str(args.nprocs)
        if args.backend == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env.pop("PALLAS_AXON_POOL_IPS", None)  # detach any TPU plugin
            # REPLACE any inherited device-count flag: a parent test
            # process runs on an 8-device virtual mesh, and inheriting
            # that would give each child 8 devices instead of
            # devices_per_proc (world 16, not nprocs)
            flags = [f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f]
            flags.append("--xla_force_host_platform_device_count="
                         f"{args.devices_per_proc}")
            env["XLA_FLAGS"] = " ".join(flags)
        children.append(subprocess.Popen(
            [sys.executable, args.script, *args.script_args], env=env))

    # wait on children like the reference's final loop, but poll so one
    # crashed rank kills the others instead of deadlocking the group
    # (a failed rank leaves the survivors blocked in distributed init)
    import time
    rc = 0
    try:
        while True:
            codes = [c.poll() for c in children]
            failed = [code for code in codes if code not in (None, 0)]
            if failed:
                rc = failed[0]
                break
            if all(code is not None for code in codes):
                break
            time.sleep(0.2)
    finally:
        for c in children:
            if c.poll() is None:
                c.kill()
                c.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
