"""apex_tpu.parallel — data parallelism, SyncBatchNorm, LARC
(reference: apex/parallel/__init__.py).
"""

from typing import List, Optional, Tuple

from . import multiproc
from .topology import (make_mesh, mesh_info, hierarchical_axis_groups,
                       default_ici_size, auto_comm_topology,
                       overlap_issue_order, collective_rank_groups)
from .distributed import (DistributedDataParallel, Reducer,
                          allreduce_grads_tree, allreduce_comm_plan,
                          plan_collective_expectations,
                          plan_resharding_expectations,
                          zero_update_comm_plan,
                          predivide_factors, flat_dist_call,
                          staged_grads, overlap_comm_schedule,
                          overlap_schedule_fields,
                          overlap_collective_expectations, OVERLAP_MODES)
from .sync_batchnorm import SyncBatchNorm
from .LARC import LARC
from . import tensor_parallel
from .tensor_parallel import (ColumnParallelLinear, RowParallelLinear,
                              ParallelMLP, ParallelSelfAttention)
from . import pipeline
from . import expert_parallel
from .adasum import adasum_grads, adasum_pair, adasum_comm_plan
from .expert_parallel import ExpertParallelMLP


class ReduceOp:
    """Shim mirroring torch.distributed.ReduceOp (parallel/__init__.py:3-8)."""
    SUM = "psum"
    MAX = "pmax"
    MIN = "pmin"
    MEAN = "pmean"


def convert_syncbn_model(module, process_group=None, channel_last=False):
    """Recursively replace BatchNorm2d children with SyncBatchNorm,
    preserving hyperparameters (reference parallel/__init__.py:21-53).

    Because apex_tpu params live outside the module tree and SyncBatchNorm
    has the identical param/state schema, existing params trees stay valid
    — the stats-copy dance of the reference is unnecessary.  Returns the
    (mutated) module for call-shape parity.
    """
    from ..nn.layers import BatchNorm2d

    def maybe_convert(mod):
        if type(mod) is BatchNorm2d:
            new = SyncBatchNorm(
                mod.num_features, eps=mod.eps, momentum=mod.momentum,
                affine=mod.affine,
                track_running_stats=mod.track_running_stats,
                process_group=process_group, channel_last=channel_last,
                channel_axis=mod.channel_axis)
            return new
        return None

    converted = maybe_convert(module)
    if converted is not None:
        return converted
    stack = [module]
    while stack:
        mod = stack.pop()
        for name, child in list(mod.named_children()):
            new = maybe_convert(child)
            if new is not None:
                mod._replace_child(name, new)
            else:
                stack.append(child)
    return module


def create_syncbn_process_group(group_size: int,
                                world_size: Optional[int] = None,
                                axis_name: str = "data"
                                ) -> Tuple[str, List[List[int]]]:
    """Partition the axis into groups of ``group_size`` for grouped BN stat
    sync (reference parallel/__init__.py:55-92).  Returns a
    ``(axis_name, axis_index_groups)`` pair to pass as
    ``SyncBatchNorm(process_group=...)``; group 0 contains ranks
    [0, group_size), etc.
    """
    import jax
    if world_size is None:
        world_size = jax.device_count()
    if group_size == 0 or group_size >= world_size:
        return (axis_name, None)
    if world_size % group_size != 0:
        raise ValueError(
            f"world_size {world_size} must be divisible by group_size "
            f"{group_size}")
    groups = [list(range(i, i + group_size))
              for i in range(0, world_size, group_size)]
    return (axis_name, groups)
