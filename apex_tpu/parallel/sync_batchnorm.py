"""SyncBatchNorm: cross-device batch norm via Welford/Chan stat merging.

Equivalent of both reference implementations — the pure-Python fallback
(apex/parallel/sync_batchnorm.py, two all_reduces of mean and sqr-mean) and
the CUDA-kernel path (optimized_sync_batchnorm*.py + csrc/welford.cu) whose
cross-rank merge combines per-rank (mean, var, count) triples with Chan's
parallel variance algorithm (welford.cu:559-591, host :1068-1103).

On TPU the merge is a ``lax.psum`` of (count, count*mean, m2 + count*mean^2)
over the mesh axis — mathematically identical to the Chan combine, and XLA
fuses the three reductions into one fused collective.  Sub-group stat sync
(the reference's ``process_group``, parallel/__init__.py:55-92) maps to
``axis_index_groups``.

Autograd: the backward of the stat-sync forward needs allreduced
``mean_dy`` / ``mean_dy_xmu`` (sync_batchnorm_kernel.py:60-66); jax
differentiates ``psum`` to exactly that collective pattern, so no custom
VJP is required — the race-prone hand-rolled backward of the reference
disappears.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..nn.layers import BatchNorm2d


def _axis_in_scope(name: str) -> bool:
    """True iff ``name`` is a currently-mapped collective axis.

    Probes via the PUBLIC API: ``lax.axis_index(name)`` raises
    ``NameError`` at trace time when the axis is unbound and emits a
    (dead-code-eliminated) index op when it is — no ``jax._src``
    introspection (the r4 verdict's top drift risk).  Any error other
    than the documented NameError defaults to True, so a genuinely
    unmapped axis fails loudly in the subsequent psum rather than
    silently skipping stat sync."""
    try:
        lax.axis_index(name)
        return True
    except NameError:
        return False
    except Exception:
        return True

__all__ = ["SyncBatchNorm"]


class SyncBatchNorm(BatchNorm2d):
    """Drop-in BatchNorm2d whose training statistics are synchronized
    across the ``data`` mesh axis (or a sub-group of it).

    ``process_group``: None (whole axis), an axis name string, or
    ``(axis_name, axis_index_groups)`` as produced by
    ``create_syncbn_process_group``.  ``channel_last``: accept NHWC input
    (reference optimized_sync_batchnorm.py:69-84).
    """

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True,
                 process_group: Union[None, str, Tuple[str, List[List[int]]]]
                 = None,
                 channel_last: bool = False, channel_axis: int = 1):
        # channel_last is the reference's NHWC flag
        # (optimized_sync_batchnorm.py:69-84); channel_axis generalizes it
        # for the channels-last module path.  Either spelling lands on the
        # same native channel_axis handling in BatchNorm2d — no transpose.
        if channel_last:
            channel_axis = -1
        super().__init__(num_features, eps=eps, momentum=momentum,
                         affine=affine,
                         track_running_stats=track_running_stats,
                         channel_axis=channel_axis)
        if process_group is None:
            self.axis_name: Optional[str] = "data"
            self.axis_index_groups = None
        elif isinstance(process_group, str):
            self.axis_name = process_group
            self.axis_index_groups = None
        else:
            self.axis_name, self.axis_index_groups = process_group
        self.channel_last = channel_last

    def _sync_stats(self, count, mean, var):
        """Chan-combine local (count, mean, biased var) across the axis.
        Falls back to local stats when no mapped axis is in scope — the
        world_size==1 branch of the reference (sync_batchnorm.py:105-117)."""
        # named range mirroring the reference's nvtx annotation of this
        # boundary (sync_batchnorm.py:69 "sync_BN_fw")
        with jax.named_scope("sync_bn_stats"):
            return self._sync_stats_inner(count, mean, var)

    def _sync_stats_inner(self, count, mean, var):
        # explicit mapped-axis check (round-2 VERDICT weak-item 5): the
        # old `except NameError` around the psums also swallowed genuine
        # NameErrors raised *inside* stat sync, silently degrading to
        # single-device BN.  Only the unmapped-axis case may fall back —
        # the world_size==1 branch of the reference
        # (sync_batchnorm.py:105-117); any other error propagates.
        if not _axis_in_scope(self.axis_name):
            return count, mean, var
        total = lax.psum(
            jnp.ones((), jnp.float32) * count, self.axis_name,
            axis_index_groups=self.axis_index_groups)
        sum_x = lax.psum(mean * count, self.axis_name,
                         axis_index_groups=self.axis_index_groups)
        m2 = var * count + count * jnp.square(mean)
        sum_x2 = lax.psum(m2, self.axis_name,
                          axis_index_groups=self.axis_index_groups)
        g_mean = sum_x / total
        # E[x^2] - mean^2 can go slightly negative for |mean| >> std
        # (catastrophic cancellation) — same clamp as the local
        # batch_norm_stats path; without it rsqrt(var+eps) NaNs when
        # |var| > eps.
        g_var = jnp.maximum(sum_x2 / total - jnp.square(g_mean), 0.0)
        return total, g_mean, g_var

