"""Expert parallelism: Switch-style Mixture-of-Experts over a mesh axis.

The reference predates MoE entirely; this completes apex_tpu's
parallelism surface (dp/tp/pp/sp/ep).  The design is the GShard/Switch
SPMD pattern in shard_map form:

- the ``expert`` mesh axis shards BOTH the tokens (data-style) and the
  expert homes: device d holds tokens-shard d and experts
  ``[d*E/ep, (d+1)*E/ep)``;
- each device routes its local tokens (replicated router weights),
  builds a capacity-bounded dispatch tensor, and one ``all_to_all``
  ships every token to the device owning its expert; the expert MLPs
  run as one vmapped batch; the reverse ``all_to_all`` brings results
  home, where the gate-weighted combine reads them back;
- tokens over an expert's capacity are DROPPED (contribute zero), the
  standard Switch behavior — size everything with ``capacity_factor``.

Communication per layer: two all_to_alls (forward) — their transposes
are all_to_alls again, so backward needs no f/g correction the way
psum-based TP does.

Router: top-1 (Switch) by default; ``top_k=2`` with
``expert_type="swiglu"`` gives the Mixtral shape (renormalized gate
weights, SwiGLU experts).  The auxiliary load-balancing loss
(Switch eq. 4: E * sum_e f_e * P_e, fraction counted over all k
assignments) is returned by ``forward`` when ``return_aux_loss`` — add
``aux_weight * aux`` to the task loss.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.module import Module
from ..nn import functional as F
from .sync_batchnorm import _axis_in_scope

__all__ = ["ExpertParallelMLP", "allreduce_replicated_grads"]

DEFAULT_AXIS = "expert"


class ExpertParallelMLP(Module):
    """Top-k routed MoE MLP; experts sharded over ``axis_name``.

    Params: ``router`` (d, E) replicated; ``w_in`` (E, d, hidden) and
    ``w_out`` (E, hidden, d) sharded on the expert dim (see
    ``param_specs``); gated experts add ``w_gate`` (E, d, hidden).
    Call inside shard_map with tokens sharded over the same axis;
    outside any mesh all experts run locally.

    ``top_k=1`` is Switch (gate = raw top-1 prob).  ``top_k>1`` is the
    GShard/Mixtral shape: each token goes to its k best experts, gate
    weights renormalized to sum 1 over the chosen k; capacity slots are
    assigned first-choice-first (every token's first choice queues
    before any token's second), so under pressure second choices drop
    first.  ``expert_type="swiglu"`` makes each expert the Llama MLP
    ``(silu(x@w_gate) * (x@w_in)) @ w_out`` (Mixtral's expert).
    """

    def __init__(self, embed_dim: int, hidden_dim: int, n_experts: int,
                 capacity_factor: float = 1.25,
                 activation: str = "gelu",
                 axis_name: str = DEFAULT_AXIS,
                 top_k: int = 1,
                 expert_type: str = "mlp"):
        super().__init__()
        if not 1 <= top_k <= n_experts:
            raise ValueError(f"top_k={top_k} not in [1, {n_experts}]")
        if expert_type not in ("mlp", "swiglu"):
            raise ValueError(f"unknown expert_type {expert_type!r}")
        self.embed_dim = embed_dim
        self.hidden_dim = hidden_dim
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.axis_name = axis_name
        self.top_k = top_k
        self.expert_type = expert_type

    def create_params(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        d, h, E = self.embed_dim, self.hidden_dim, self.n_experts
        s_in = (2.0 / d) ** 0.5
        s_out = (2.0 / h) ** 0.5
        p = {
            "router": jax.random.normal(k1, (d, E), jnp.float32) * 0.02,
            "w_in": jax.random.normal(k2, (E, d, h), jnp.float32) * s_in,
            "w_out": jax.random.normal(k3, (E, h, d), jnp.float32) * s_out,
        }
        if self.expert_type == "swiglu":
            p["w_gate"] = (jax.random.normal(k4, (E, d, h), jnp.float32)
                           * s_in)
        return p

    def param_specs(self) -> Dict[str, P]:
        s = {"router": P(),
             "w_in": P(self.axis_name, None, None),
             "w_out": P(self.axis_name, None, None)}
        if self.expert_type == "swiglu":
            s["w_gate"] = P(self.axis_name, None, None)
        return s

    # -- routing ----------------------------------------------------------
    def _dispatch(self, x2d: jax.Array, router: jax.Array, capacity: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(dispatch (T,E,C) one-hot, combine (T,E,C) gate-weighted,
        aux load-balance loss) for the local token block."""
        T = x2d.shape[0]
        E, k = self.n_experts, self.top_k
        logits = x2d.astype(jnp.float32) @ router
        probs = jax.nn.softmax(logits, axis=-1)
        gates, experts = lax.top_k(probs, k)                   # (T,k)
        if k > 1:
            # Mixtral: gate weights renormalized over the chosen k
            gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
        onehot = jax.nn.one_hot(experts, E, dtype=jnp.float32)  # (T,k,E)
        # queue positions, choice-major: every token's 1st choice is
        # enqueued before any token's 2nd, so overflow drops 2nd picks
        ohf = jnp.swapaxes(onehot, 0, 1).reshape(k * T, E)
        pos = jnp.cumsum(ohf, axis=0) * ohf - 1.0              # (kT,E)
        keep = (pos >= 0) & (pos < capacity)
        disp = ohf * keep                                      # (kT,E)
        posc = jax.nn.one_hot(
            jnp.sum(pos * ohf, -1).astype(jnp.int32), capacity,
            dtype=jnp.float32)                                 # (kT,C)
        per_choice = (disp[:, :, None]
                      * posc[:, None, :]).reshape(k, T, E, capacity)
        # slots are disjoint across choices, so the union is a sum
        dispatch = jnp.sum(per_choice, axis=0)                 # (T,E,C)
        combine = jnp.einsum("ktec,tk->tec", per_choice, gates)
        # Switch aux loss (eq. 4), fraction over all k assignments:
        # f_e x mean prob P_e, scaled E; reduces to Switch at k=1
        f_e = jnp.mean(jnp.sum(onehot, axis=1), axis=0) / k
        p_e = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(f_e * p_e)
        return dispatch, combine, aux

    def _expert_mlp(self, params, xe):
        """xe: (E_local, S, d) -> (E_local, S, d), vmapped over experts."""
        act = getattr(F, self.activation)

        if self.expert_type == "swiglu":
            def one(w_gate, w_in, w_out, t):
                return (F.silu(t @ w_gate.astype(t.dtype))
                        * (t @ w_in.astype(t.dtype))
                        ) @ w_out.astype(t.dtype)

            return jax.vmap(one)(params["w_gate"], params["w_in"],
                                 params["w_out"], xe)

        def one(w_in, w_out, t):
            return act(t @ w_in.astype(t.dtype)) @ w_out.astype(t.dtype)

        return jax.vmap(one)(params["w_in"], params["w_out"], xe)

    def forward(self, params, x, return_aux_loss: bool = False):
        *lead, d = x.shape
        x2d = x.reshape(-1, d)
        T = x2d.shape[0]
        E = self.n_experts
        ep = (lax.axis_size(self.axis_name)
              if _axis_in_scope(self.axis_name) else 1)
        if E % ep:
            raise ValueError(f"n_experts={E} not divisible by expert-"
                             f"parallel size {ep}")
        capacity = max(1, math.ceil(self.capacity_factor * T / E))
        dispatch, combine, aux = self._dispatch(x2d, params["router"],
                                                capacity)
        # (T,E,C) x (T,d) -> (E,C,d): the local contribution per expert
        sent = jnp.einsum("tec,td->ecd", dispatch.astype(x2d.dtype), x2d)
        if ep > 1:
            e_loc = E // ep
            # (E,C,d) -> (ep, e_loc, C, d) -all_to_all-> every device
            # ends up with ITS experts' queues from all source devices
            sent = sent.reshape(ep, e_loc, capacity, d)
            recv = lax.all_to_all(sent, self.axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
            # (ep_src, e_loc, C, d) -> (e_loc, ep_src*C, d)
            xe = jnp.moveaxis(recv, 0, 1).reshape(e_loc, ep * capacity, d)
            ye = self._expert_mlp(params, xe)
            back = jnp.moveaxis(
                ye.reshape(e_loc, ep, capacity, d), 1, 0)
            got = lax.all_to_all(back, self.axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
            got = got.reshape(E, capacity, d)
        else:
            got = self._expert_mlp(params, sent)
        y2d = jnp.einsum("tec,ecd->td", combine.astype(got.dtype), got)
        y = y2d.reshape(*lead, d)
        return (y, aux) if return_aux_loss else y


def allreduce_replicated_grads(grads, specs, axis_name: str):
    """DDP-style psum over ``axis_name`` for the REPLICATED leaves only.

    With experts sharded over the token/data axis (DeepSpeed-MoE
    style), expert-sharded leaves (their spec mentions ``axis_name``)
    hold that device's own experts' grads — a blanket psum would be
    wrong for them, while router/attention/norm grads are data-parallel
    and need the usual sum.  ``specs`` is the
    ``tensor_parallel.partition_specs(model)`` tree.
    """
    def names_in(spec):
        out = set()
        for part in spec:
            if part is None:
                continue
            out.update(part if isinstance(part, tuple) else (part,))
        return out

    def red(g, s):
        return g if axis_name in names_in(s) else lax.psum(g, axis_name)

    return jax.tree_util.tree_map(
        red, grads, specs,
        is_leaf=lambda x: isinstance(x, P))
