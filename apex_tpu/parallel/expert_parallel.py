"""Expert parallelism: Switch-style Mixture-of-Experts over a mesh axis.

The reference predates MoE entirely; this completes apex_tpu's
parallelism surface (dp/tp/pp/sp/ep).  The design is the GShard/Switch
SPMD pattern in shard_map form:

- the ``expert`` mesh axis shards BOTH the tokens (data-style) and the
  expert homes: device d holds tokens-shard d and experts
  ``[d*E/ep, (d+1)*E/ep)``;
- each device routes its local tokens (replicated router weights),
  builds a capacity-bounded dispatch tensor, and one ``all_to_all``
  ships every token to the device owning its expert; the expert MLPs
  run as one vmapped batch; the reverse ``all_to_all`` brings results
  home, where the gate-weighted combine reads them back;
- tokens over an expert's capacity are DROPPED (contribute zero), the
  standard Switch behavior — size everything with ``capacity_factor``.

Communication per layer: two all_to_alls (forward) — their transposes
are all_to_alls again, so backward needs no f/g correction the way
psum-based TP does.

Router: top-1 (Switch).  The auxiliary load-balancing loss
(Switch eq. 4: E * sum_e f_e * P_e) is returned by ``forward`` when
``return_aux_loss`` — add ``aux_weight * aux`` to the task loss.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.module import Module
from ..nn import functional as F
from .sync_batchnorm import _axis_in_scope

__all__ = ["ExpertParallelMLP"]

DEFAULT_AXIS = "expert"


class ExpertParallelMLP(Module):
    """Top-1 routed MoE MLP; experts sharded over ``axis_name``.

    Params: ``router`` (d, E) replicated; ``w_in`` (E, d, hidden) and
    ``w_out`` (E, hidden, d) sharded on the expert dim (see
    ``param_specs``).  Call inside shard_map with tokens sharded over
    the same axis; outside any mesh all experts run locally.
    """

    def __init__(self, embed_dim: int, hidden_dim: int, n_experts: int,
                 capacity_factor: float = 1.25,
                 activation: str = "gelu",
                 axis_name: str = DEFAULT_AXIS):
        super().__init__()
        self.embed_dim = embed_dim
        self.hidden_dim = hidden_dim
        self.n_experts = n_experts
        self.capacity_factor = capacity_factor
        self.activation = activation
        self.axis_name = axis_name

    def create_params(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        d, h, E = self.embed_dim, self.hidden_dim, self.n_experts
        s_in = (2.0 / d) ** 0.5
        s_out = (2.0 / h) ** 0.5
        return {
            "router": jax.random.normal(k1, (d, E), jnp.float32) * 0.02,
            "w_in": jax.random.normal(k2, (E, d, h), jnp.float32) * s_in,
            "w_out": jax.random.normal(k3, (E, h, d), jnp.float32) * s_out,
        }

    def param_specs(self) -> Dict[str, P]:
        return {"router": P(),
                "w_in": P(self.axis_name, None, None),
                "w_out": P(self.axis_name, None, None)}

    # -- routing ----------------------------------------------------------
    def _dispatch(self, x2d: jax.Array, router: jax.Array, capacity: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(dispatch (T,E,C) one-hot, combine (T,E,C) gate-weighted,
        aux load-balance loss) for the local token block."""
        T = x2d.shape[0]
        E = self.n_experts
        logits = x2d.astype(jnp.float32) @ router
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                    # (T,)
        gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T,E)
        # position of each token within its expert's queue (prefix count)
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0        # (T,E)
        keep = (pos >= 0) & (pos < capacity)
        disp = onehot * keep                                   # (T,E)
        posc = jax.nn.one_hot(
            jnp.sum(pos * onehot, -1).astype(jnp.int32), capacity,
            dtype=jnp.float32)                                 # (T,C)
        dispatch = disp[:, :, None] * posc[:, None, :]         # (T,E,C)
        combine = dispatch * gate[:, None, None]
        # Switch aux loss: fraction routed f_e x mean prob P_e, scaled E
        f_e = jnp.mean(onehot, axis=0)
        p_e = jnp.mean(probs, axis=0)
        aux = E * jnp.sum(f_e * p_e)
        return dispatch, combine, aux

    def _expert_mlp(self, params, xe):
        """xe: (E_local, S, d) -> (E_local, S, d), vmapped over experts."""
        act = getattr(F, self.activation)

        def one(w_in, w_out, t):
            return act(t @ w_in.astype(t.dtype)) @ w_out.astype(t.dtype)

        return jax.vmap(one)(params["w_in"], params["w_out"], xe)

    def forward(self, params, x, return_aux_loss: bool = False):
        *lead, d = x.shape
        x2d = x.reshape(-1, d)
        T = x2d.shape[0]
        E = self.n_experts
        ep = (lax.axis_size(self.axis_name)
              if _axis_in_scope(self.axis_name) else 1)
        if E % ep:
            raise ValueError(f"n_experts={E} not divisible by expert-"
                             f"parallel size {ep}")
        capacity = max(1, math.ceil(self.capacity_factor * T / E))
        dispatch, combine, aux = self._dispatch(x2d, params["router"],
                                                capacity)
        # (T,E,C) x (T,d) -> (E,C,d): the local contribution per expert
        sent = jnp.einsum("tec,td->ecd", dispatch.astype(x2d.dtype), x2d)
        if ep > 1:
            e_loc = E // ep
            # (E,C,d) -> (ep, e_loc, C, d) -all_to_all-> every device
            # ends up with ITS experts' queues from all source devices
            sent = sent.reshape(ep, e_loc, capacity, d)
            recv = lax.all_to_all(sent, self.axis_name, split_axis=0,
                                  concat_axis=0, tiled=False)
            # (ep_src, e_loc, C, d) -> (e_loc, ep_src*C, d)
            xe = jnp.moveaxis(recv, 0, 1).reshape(e_loc, ep * capacity, d)
            ye = self._expert_mlp(
                {"w_in": params["w_in"], "w_out": params["w_out"]}, xe)
            back = jnp.moveaxis(
                ye.reshape(e_loc, ep, capacity, d), 1, 0)
            got = lax.all_to_all(back, self.axis_name, split_axis=0,
                                 concat_axis=0, tiled=False)
            got = got.reshape(E, capacity, d)
        else:
            got = self._expert_mlp(
                {"w_in": params["w_in"], "w_out": params["w_out"]}, sent)
        y2d = jnp.einsum("tec,ecd->td", combine.astype(got.dtype), got)
        y = y2d.reshape(*lead, d)
        return (y, aux) if return_aux_loss else y
