"""DistributedDataParallel for device meshes.

The reference DDP (apex/parallel/distributed.py:129-512) overlaps NCCL
allreduce with backward by hooking per-param grad accumulators, assembling
flat dtype-split buckets in backward arrival order, and draining them on a
dedicated reduction stream.  On TPU/XLA none of that machinery is needed or
desirable (SURVEY.md §7 hard parts): collectives are compiler-scheduled, so
overlap comes from XLA's latency-hiding scheduler.  What *is* preserved is
every observable option of the reference wrapper:

- ``message_size``        — bucket granularity (elements) for chunked psum,
                            letting XLA interleave collectives with the
                            backward's tail (distributed.py:162-171),
- ``delay_allreduce``     — one fused allreduce after backward (:148-158),
- ``allreduce_always_fp32`` — upcast half grads before the collective
                            (:383-396),
- ``gradient_average``    — divide by world size after (:391-393),
- ``gradient_predivide_factor`` — pre/post divide split for fp16 range
                            control (:386-393),
- ``retain_allreduce_buffers`` — expose the flat reduced buckets.

Usage inside a shard_map/pmap'd step over axis ``data``::

    ddp = DistributedDataParallel(model)          # wrapper parity
    ...
    grads = ddp.allreduce_grads(grads)            # inside the mapped fn

or functionally via ``allreduce_grads_tree(grads, axis_name='data')``.
``DistributedDataParallel.make_step`` builds a whole shard_map'd train step
over a 1-D mesh for the common data-parallel case.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["DistributedDataParallel", "Reducer", "allreduce_grads_tree",
           "allreduce_comm_plan", "flat_dist_call"]


def _axis_size(axis_name: str) -> jax.Array:
    return lax.psum(jnp.ones((), jnp.float32), axis_name)


def _path_str(path) -> str:
    """'/'-joined readable key path for a tree_flatten_with_path entry."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def allreduce_grads_tree(grads: Any, axis_name: str = "data",
                         message_size: int = 10_000_000,
                         allreduce_always_fp32: bool = False,
                         gradient_average: bool = True,
                         gradient_predivide_factor: float = 1.0,
                         delay_allreduce: bool = False,
                         axis_index_groups: Optional[List[List[int]]] = None,
                         retain_buffers: Optional[list] = None,
                         trigger_paths: Optional[set] = None,
                         comm_stats: Optional[list] = None) -> Any:
    """Bucketed gradient allreduce with the reference's semantics
    (allreduce_bucket, distributed.py:378-398).  Must run inside a context
    where ``axis_name`` is a mapped mesh axis.

    ``trigger_paths``: the reference's ``allreduce_trigger_params``
    (distributed.py:162-171) — user-chosen params whose grad readiness
    fires a bucket flush, overriding message_size.  Arrival order doesn't
    exist under XLA, so the faithful mapping is: the listed leaves mark
    *bucket boundaries* in tree order; each bucket is one psum the
    scheduler can overlap independently.  Paths are '/'-joined key paths
    (e.g. 'layer1/conv/weight'); unknown paths raise.

    ``comm_stats``: observability out-param — one dict per reduced
    bucket ({dtype, comm_dtype, leaves, elements, bytes, cause, chunks})
    appended at TRACE time (like ``retain_buffers``), i.e. once per
    compiled step, describing what every execution of that step
    communicates.  ``cause`` records why the bucket flushed: a trigger
    boundary, ``delay_allreduce``, fitting under ``message_size``
    (``single``), or the chunked-psum path."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    paths = None
    if trigger_paths:
        flat_paths = jax.tree_util.tree_flatten_with_path(grads)[0]
        paths = [_path_str(p) for p, _ in flat_paths]
        unknown = set(trigger_paths) - set(paths)
        if unknown:
            raise ValueError(
                f"allreduce_trigger_params paths not found in the gradient "
                f"tree: {sorted(unknown)}; available: {paths[:8]}...")

    # dtype-split buckets, like split_half_float_double (distributed.py:51-58)
    groups: Dict[Any, List[int]] = {}
    for i, g in enumerate(leaves):
        groups.setdefault(jnp.dtype(g.dtype), []).append(i)

    world = _axis_size(axis_name)
    if axis_index_groups is not None:
        world = jnp.asarray(float(len(axis_index_groups[0])), jnp.float32)

    new_leaves: List[Any] = [None] * len(leaves)
    for dt, idxs in groups.items():
        # trigger params split the group into separately-reduced buckets
        if trigger_paths:
            buckets, cur = [], []
            for i in idxs:
                cur.append(i)
                if paths[i] in trigger_paths:
                    buckets.append(cur)
                    cur = []
            if cur:
                buckets.append(cur)
        else:
            buckets = [idxs]

        for bucket in buckets:
            flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
            comm = flat.astype(jnp.float32) if allreduce_always_fp32 else flat
            if gradient_predivide_factor != 1.0:
                comm = comm / jnp.asarray(gradient_predivide_factor,
                                          comm.dtype)

            n = comm.shape[0]
            nchunks = 1
            if delay_allreduce or trigger_paths or n <= message_size:
                cause = ("trigger" if trigger_paths
                         else "delay" if delay_allreduce else "single")
                reduced = lax.psum(comm, axis_name,
                                   axis_index_groups=axis_index_groups)
            else:
                # chunked psum: XLA schedules the pieces independently —
                # the compiler-native form of the reference's bucket overlap
                cause = "chunked"
                nchunks = math.ceil(n / message_size)
                pad = nchunks * message_size - n
                padded = jnp.pad(comm, (0, pad))
                chunks = padded.reshape(nchunks, message_size)
                reduced = lax.psum(chunks, axis_name,
                                   axis_index_groups=axis_index_groups)
                reduced = reduced.reshape(-1)[:n]

            if comm_stats is not None:
                comm_stats.append({
                    "dtype": str(dt), "comm_dtype": str(comm.dtype),
                    "leaves": len(bucket), "elements": int(n),
                    "bytes": int(n) * jnp.dtype(comm.dtype).itemsize,
                    "cause": cause, "chunks": nchunks})

            if gradient_average:
                post = world / gradient_predivide_factor if \
                    gradient_predivide_factor != 1.0 else world
                reduced = reduced / post.astype(reduced.dtype)
            reduced = reduced.astype(dt)
            if retain_buffers is not None:
                retain_buffers.append(reduced)
            off = 0
            for i in bucket:
                sz = leaves[i].size
                new_leaves[i] = reduced[off:off + sz].reshape(leaves[i].shape)
                off += sz
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def allreduce_comm_plan(grads: Any, message_size: int = 10_000_000,
                        allreduce_always_fp32: bool = False,
                        delay_allreduce: bool = False,
                        trigger_paths: Optional[set] = None
                        ) -> List[dict]:
    """Static twin of :func:`allreduce_grads_tree`'s bucketing: what the
    comm pattern of one allreduce WILL be, computed from shapes alone
    (no tracing).  One dict per bucket::

        {dtype, comm_dtype, leaves, elements, chunks, cause,
         wire_elements, wire_bytes}

    ``wire_elements`` includes chunk padding — the bytes a psum of this
    bucket actually moves per replica.  Each bucket is exactly one psum
    eqn in the traced step (the chunked path reshapes into one
    ``(chunks, message_size)`` psum), so ``len(plan)`` is the expected
    grad-psum count.  ``apex_tpu.analysis``'s collective-accounting rule
    derives its DDP expectations from this plan: if the bucketing
    algorithm changes, the plan and the traced graph move together,
    while an accidental extra/missing/fatter collective still flags."""
    leaves = jax.tree_util.tree_leaves(grads)
    plan: List[dict] = []
    if not leaves:
        return plan
    paths = None
    if trigger_paths:
        flat_paths = jax.tree_util.tree_flatten_with_path(grads)[0]
        paths = [_path_str(p) for p, _ in flat_paths]
        unknown = set(trigger_paths) - set(paths)
        if unknown:
            # mirror allreduce_grads_tree: a plan for a comm pattern
            # the real step would refuse to trace is not a plan
            raise ValueError(
                f"allreduce_trigger_params paths not found in the "
                f"gradient tree: {sorted(unknown)}; available: "
                f"{paths[:8]}...")

    groups: Dict[Any, List[int]] = {}
    for i, g in enumerate(leaves):
        groups.setdefault(jnp.dtype(g.dtype), []).append(i)

    for dt, idxs in groups.items():
        if trigger_paths:
            buckets, cur = [], []
            for i in idxs:
                cur.append(i)
                if paths[i] in trigger_paths:
                    buckets.append(cur)
                    cur = []
            if cur:
                buckets.append(cur)
        else:
            buckets = [idxs]
        for bucket in buckets:
            n = sum(int(leaves[i].size) for i in bucket)
            comm_dt = jnp.dtype(jnp.float32) if allreduce_always_fp32 \
                else dt
            if delay_allreduce or trigger_paths or n <= message_size:
                cause = ("trigger" if trigger_paths
                         else "delay" if delay_allreduce else "single")
                chunks, wire = 1, n
            else:
                cause = "chunked"
                chunks = math.ceil(n / message_size)
                wire = chunks * message_size
            plan.append({
                "dtype": str(dt), "comm_dtype": str(comm_dt),
                "leaves": len(bucket), "elements": n, "chunks": chunks,
                "cause": cause, "wire_elements": wire,
                "wire_bytes": wire * comm_dt.itemsize})
    return plan


def _broadcast0(flat: jax.Array, axis_name: str,
                axis_index_groups=None) -> jax.Array:
    """Broadcast from rank 0 expressed as a masked psum (XLA lowers this
    to a collective-broadcast-shaped pattern over ICI).  psum runs in the
    leaf's own dtype — an fp32 round-trip would corrupt integer leaves
    beyond 2^24 (e.g. PRNG keys)."""
    comm = flat.astype(jnp.int32) if flat.dtype == jnp.bool_ else flat
    src = jnp.where(lax.axis_index(axis_name) == 0, comm,
                    jnp.zeros_like(comm))
    return lax.psum(src, axis_name,
                    axis_index_groups=axis_index_groups).astype(flat.dtype)


def flat_dist_call(tree: Any, axis_name: str = "data", op: str = "psum",
                   axis_index_groups=None) -> Any:
    """apply_flat_dist_call parity (distributed.py:36-49): one collective
    per dtype group over the flattened tree."""
    reducer = {"psum": lax.psum, "pmean": lax.pmean, "pmax": lax.pmax,
               "pmin": lax.pmin, "broadcast": _broadcast0}[op]
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: Dict[Any, List[int]] = {}
    for i, g in enumerate(leaves):
        groups.setdefault(jnp.dtype(g.dtype), []).append(i)
    out: List[Any] = [None] * len(leaves)
    for dt, idxs in groups.items():
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        red = reducer(flat, axis_name, axis_index_groups=axis_index_groups)
        off = 0
        for i in idxs:
            sz = leaves[i].size
            out[i] = red[off:off + sz].reshape(leaves[i].shape)
            off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


class DistributedDataParallel:
    """Model wrapper with the reference's constructor surface
    (distributed.py:129-171)."""

    def __init__(self, module=None, message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 shared_param: Optional[bool] = None,
                 allreduce_trigger_params: Optional[list] = None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name: str = "data",
                 adasum: bool = False):
        if shared_param is not None:
            raise ValueError("shared_param is deprecated (reference "
                             "distributed.py:176-180)")
        self.module = module
        self.message_size = int(message_size)
        self.delay_allreduce = delay_allreduce
        self.allreduce_trigger_params = allreduce_trigger_params
        self.retain_allreduce_buffers = retain_allreduce_buffers
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.axis_name = axis_name
        # adasum=True swaps the psum for the adaptive-summation
        # butterfly (parallel/adasum.py, arXiv:2006.02924) — a
        # beyond-reference combiner for conflict-aware large-batch DP.
        # It REPLACES the sum-then-average pipeline wholesale, so the
        # psum-shaping knobs are meaningless with it: reject loudly
        # instead of silently ignoring them.
        self.adasum = adasum
        if adasum:
            clashes = [name for name, bad in (
                ("delay_allreduce", delay_allreduce),
                ("allreduce_trigger_params",
                 bool(allreduce_trigger_params)),
                ("retain_allreduce_buffers", retain_allreduce_buffers),
                ("allreduce_always_fp32", allreduce_always_fp32),
                ("gradient_average=False", not gradient_average),
                ("gradient_predivide_factor",
                 gradient_predivide_factor != 1.0)) if bad]
            if clashes:
                raise ValueError(
                    f"adasum=True replaces the psum pipeline; these "
                    f"options have no effect with it: {clashes}")
        self.allreduce_buffers: list = []
        # trace-time comm accounting (observability): one record per
        # bucket of the most recently traced allreduce — see
        # allreduce_grads_tree(comm_stats=...)
        self.last_comm_stats: list = []

    # -- forward passthrough (wrapper parity) ------------------------------
    def __call__(self, *args, **kwargs):
        return self.module(*args, **kwargs)

    def apply(self, *args, **kwargs):
        return self.module.apply(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.module, name)

    # -- the hot path ------------------------------------------------------
    def allreduce_grads(self, grads: Any,
                        axis_index_groups: Optional[List[List[int]]] = None
                        ) -> Any:
        if self.adasum:
            from .adasum import adasum_grads
            if axis_index_groups is not None:
                raise NotImplementedError(
                    "adasum over axis_index_groups is not wired")
            leaves = jax.tree_util.tree_leaves(grads)
            self.last_comm_stats = [{
                "dtype": str(jnp.dtype(l.dtype)),
                "comm_dtype": str(jnp.dtype(l.dtype)),
                "leaves": 1, "elements": int(l.size),
                "bytes": int(l.size) * jnp.dtype(l.dtype).itemsize,
                "cause": "adasum", "chunks": 1} for l in leaves]
            self._record_comm_stats()
            return adasum_grads(grads, self.axis_name)
        retain = [] if self.retain_allreduce_buffers else None
        triggers = (set(self.allreduce_trigger_params)
                    if self.allreduce_trigger_params else None)
        comm_stats: list = []
        out = allreduce_grads_tree(
            grads, axis_name=self.axis_name, message_size=self.message_size,
            allreduce_always_fp32=self.allreduce_always_fp32,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            delay_allreduce=self.delay_allreduce,
            axis_index_groups=axis_index_groups,
            retain_buffers=retain, trigger_paths=triggers,
            comm_stats=comm_stats)
        if retain is not None:
            self.allreduce_buffers = retain
        self.last_comm_stats = comm_stats
        self._record_comm_stats()
        return out

    def _record_comm_stats(self):
        """Fold the per-bucket accounting into the process observability
        registry: per-(dtype, cause) bucket counts and per-dtype bytes.
        Runs at TRACE time — totals count compiled traces, not executed
        steps (per-step totals = these x steps on that executable); the
        adaptive-summation / cross-replica sharding comm work in
        PAPERS.md plans against exactly this per-bucket record."""
        from ..observability import get_registry
        reg = get_registry()
        buckets = reg.counter(
            "ddp_allreduce_buckets_total",
            help="gradient allreduce buckets per compiled trace")
        bts = reg.counter(
            "ddp_allreduce_bytes_total",
            help="one replica's communicated gradient bytes per trace")
        for b in self.last_comm_stats:
            buckets.labels(dtype=b["comm_dtype"], cause=b["cause"]).inc()
            bts.labels(dtype=b["comm_dtype"]).inc(b["bytes"])

    def broadcast_params(self, params: Any) -> Any:
        """Rank-0 parameter broadcast (reference DDP does this at
        construction, distributed.py:234).  Under shard_map replicated
        in_specs make it implicit; call this explicitly when ranks may
        have diverged (e.g. after independent init under multi-process)."""
        return flat_dist_call(params, self.axis_name, "broadcast")

    # -- whole-step builder for the common 1-D data-parallel mesh ---------
    def make_step(self, step_fn: Callable, mesh: Optional[Mesh] = None,
                  donate_state: bool = True,
                  steps_per_call: int = 1,
                  state_specs: Any = None) -> Callable:
        """shard_map ``step_fn(state..., batch) -> (state..., aux)`` over a
        1-D mesh: replicated state, batch sharded on axis 0.  ``step_fn``
        runs per-device and should call ``self.allreduce_grads`` on its
        gradient tree (param broadcast from rank 0 is implicit: replicated
        inputs to shard_map stay replicated, the analogue of the init-time
        broadcast at distributed.py:234).

        ``state_specs``: PartitionSpec pytree for the state when parts of
        it are NOT replicated — e.g. a ZeRO-sharded optimizer state
        (``(P(), P(), amp.zero_optimizer_specs(...))``) or TP-sharded
        params (``tensor_parallel.partition_specs``).  Defaults to fully
        replicated (``P()``), the plain-DDP contract.

        ``steps_per_call > 1`` wraps ``step_fn`` in a ``lax.scan`` over a
        leading micro-batch axis (batch shaped ``(K, per_step...)``) so
        one dispatch runs K optimizer steps — amortizes host→device
        dispatch latency, which on tunneled TPU runtimes is ~ms-scale.
        The aux output then carries the K per-step values."""
        if mesh is None:
            mesh = Mesh(jax.devices(), (self.axis_name,))
        an = self.axis_name
        K = int(steps_per_call)
        if K < 1:
            raise ValueError(f"steps_per_call must be >= 1, got {K}")
        if state_specs is None:
            state_specs = P()

        if K == 1:
            wrapped = step_fn
        else:
            def wrapped(state, batch):
                lead = {l.shape[0] for l in jax.tree_util.tree_leaves(batch)}
                if lead != {K}:
                    raise ValueError(
                        f"steps_per_call={K} needs every batch leaf shaped "
                        f"(K, per_step...); got leading dims {sorted(lead)}")
                return lax.scan(step_fn, state, batch)

        # batch sharded on the data axis: micro-batch axis (if any) first
        bspec = P(an) if K == 1 else P(None, an)
        mapped = jax.shard_map(
            wrapped, mesh=mesh,
            in_specs=(state_specs, bspec),
            out_specs=(state_specs, P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0,) if donate_state else ())


class Reducer:
    """Manual allreduce helper, parity with apex.parallel.Reducer
    (distributed.py:89-126): call ``reduce(tree)`` inside a mapped context
    to sum (and average) a pytree across the axis, and
    ``broadcast_params(tree)`` for the construction-time rank-0 parameter
    broadcast the reference performs (distributed.py:100-104) — in the
    functional world construction has no params in hand, so the broadcast
    is an explicit call at the top of the first step (or skipped when
    params are replicated by shard_map, which is the common case)."""

    def __init__(self, module_or_tree=None, axis_name: str = "data",
                 gradient_average: bool = True):
        self.module = module_or_tree
        self.axis_name = axis_name
        self.gradient_average = gradient_average

    def reduce(self, tree: Any) -> Any:
        red = flat_dist_call(tree, self.axis_name, "psum")
        if self.gradient_average:
            world = _axis_size(self.axis_name)
            red = jax.tree_util.tree_map(
                lambda x: x / world.astype(x.dtype), red)
        return red

    def broadcast_params(self, tree: Any) -> Any:
        """Every rank gets rank 0's values (reference init broadcast,
        distributed.py:100-104 / DDP :234)."""
        return flat_dist_call(tree, self.axis_name, "broadcast")
