"""DistributedDataParallel for device meshes.

The reference DDP (apex/parallel/distributed.py:129-512) overlaps NCCL
allreduce with backward by hooking per-param grad accumulators, assembling
flat dtype-split buckets in backward arrival order, and draining them on a
dedicated reduction stream.  On TPU/XLA none of that machinery is needed or
desirable (SURVEY.md §7 hard parts): collectives are compiler-scheduled, so
overlap comes from XLA's latency-hiding scheduler.  What *is* preserved is
every observable option of the reference wrapper:

- ``message_size``        — bucket granularity (elements) for chunked psum,
                            letting XLA interleave collectives with the
                            backward's tail (distributed.py:162-171),
- ``delay_allreduce``     — one fused allreduce after backward (:148-158),
- ``allreduce_always_fp32`` — upcast half grads before the collective
                            (:383-396),
- ``gradient_average``    — divide by world size after (:391-393),
- ``gradient_predivide_factor`` — pre/post divide split for fp16 range
                            control (:386-393),
- ``retain_allreduce_buffers`` — expose the flat reduced buckets.

Beyond the reference, ``comm_topology=`` makes the allreduce
topology-aware: ``"hierarchical"`` reduce-scatters each bucket within
the ICI slice, crosses DCN on the 1/ici_size shard, and all_gathers
back (arXiv:2004.13336's placement applied to the ICI/DCN split), with
optional bf16 compression of the DCN hop
(``allreduce_compress_bf16=``); ``"auto"`` engages it when the data
axis spans processes.  See docs/parallel.md §Topology-aware gradient
communication.

Usage inside a shard_map/pmap'd step over axis ``data``::

    ddp = DistributedDataParallel(model)          # wrapper parity
    ...
    grads = ddp.allreduce_grads(grads)            # inside the mapped fn

or functionally via ``allreduce_grads_tree(grads, axis_name='data')``.
``DistributedDataParallel.make_step`` builds a whole shard_map'd train step
over a 1-D mesh for the common data-parallel case.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import topology as _topology

__all__ = ["DistributedDataParallel", "Reducer", "allreduce_grads_tree",
           "allreduce_comm_plan", "plan_collective_expectations",
           "plan_resharding_expectations", "zero_update_comm_plan",
           "predivide_factors", "flat_dist_call", "staged_grads",
           "overlap_comm_schedule", "overlap_schedule_fields",
           "overlap_collective_expectations", "OVERLAP_MODES"]

# where the gradient bytes travel: "flat" is one psum over the whole
# axis (every byte crosses the slowest link in it), "hierarchical" is
# psum_scatter within the ICI slice -> cross-slice reduce over DCN on
# the 1/ici shard -> in-slice all_gather (arXiv:2004.13336's
# reduce-scatter placement applied to the ICI/DCN split), "auto" picks
# per topology.auto_comm_topology (hierarchical iff the axis spans
# processes).
COMM_TOPOLOGIES = ("flat", "hierarchical", "auto")

# when the gradient bytes travel, relative to the backward that makes
# them: "reduce_after_backward" is the classic schedule (every bucket's
# collective trails the whole backward — today's measured
# overlap_fraction ~ 0.0 baseline), "overlapped" is the staged schedule
# where bucket i's reduction is ISSUED while bucket i-1's gradients are
# still being computed (the reference DDP's arrival-order bucket drain,
# expressed as jaxpr program order so XLA's latency-hiding scheduler —
# and the collective lint rule — can see it).
OVERLAP_MODES = ("overlapped", "reduce_after_backward")


def _axis_size(axis_name: str) -> jax.Array:
    return lax.psum(jnp.ones((), jnp.float32), axis_name)


def predivide_factors(world, gradient_predivide_factor: float = 1.0):
    """The reference's pre/post division split (distributed.py:386-393)
    in ONE audited place: gradients are divided by ``pre`` BEFORE the
    collective (fp16 range control) and by ``post`` after it when
    ``gradient_average`` is on, with ``pre * post == world`` by
    construction — the mean is taken exactly once, no matter how the
    split is chosen, whether the reduction runs over the full axis or
    ``axis_index_groups`` (``world`` is the *averaging* population:
    group size when grouped), or how many fabric levels carry the sum
    (the hierarchical path divides once on the final result, never
    per level)."""
    f = float(gradient_predivide_factor)
    if f == 1.0:
        return 1.0, world
    return f, world / f


def _validate_topology_knobs(comm_topology: str,
                             allreduce_compress_bf16: bool):
    """The one place the knob rules live — shared by the runtime, the
    static plan, and the DDP constructor (which validates eagerly so a
    typo fails at construction, not at first trace).  Explicit ``flat``
    + compression is rejected: there is no inner level to keep full
    precision, quantizing the only collective would just lose bits."""
    if comm_topology not in COMM_TOPOLOGIES:
        raise ValueError(
            f"comm_topology must be one of {COMM_TOPOLOGIES}, got "
            f"{comm_topology!r}")
    if allreduce_compress_bf16 and comm_topology == "flat":
        raise ValueError(
            "allreduce_compress_bf16 compresses the DCN hop of the "
            "hierarchical reduction; comm_topology='flat' has no inner "
            "level to keep full precision (use 'hierarchical' or "
            "'auto')")


def _resolve_topology(comm_topology: str, allreduce_compress_bf16: bool,
                      nproc: Optional[int] = None):
    """Validate the knobs and return ``(topology, compress)`` with
    ``auto`` resolved.  ``auto`` that resolves to flat drops
    compression silently, since a single-process axis has no DCN hop
    to shrink."""
    _validate_topology_knobs(comm_topology, allreduce_compress_bf16)
    topo = comm_topology
    if topo == "auto":
        topo = _topology.auto_comm_topology(nproc)
    return topo, (allreduce_compress_bf16 and topo == "hierarchical")


def _bucket_wire_accounting(n: int, comm_dt, topo: str, ici: int,
                            compress: bool, message_size: int,
                            delay_allreduce: bool, triggered: bool
                            ) -> Dict[str, Any]:
    """Per-bucket on-wire accounting, shared by the runtime
    ``comm_stats`` records and the static :func:`allreduce_comm_plan`
    so the two can never disagree.  All byte counts are TRUE wire
    bytes — chunk/shard padding included — and match what
    ``analysis.eqn_payload_bytes`` reads off the traced collectives:

    - flat: one psum; ``chunked`` pads to ``chunks * message_size``.
    - hierarchical: one ``reduce_scatter`` (full padded bucket, ICI),
      the DCN reduce on the 1/ici shard (a psum, or a bf16 all_gather
      when compressed), and the in-slice ``all_gather`` back.

    ``ici_wire_bytes`` / ``dcn_wire_bytes`` split the total by fabric
    level; for flat both equal the full payload (a flat psum over a
    DCN-spanning axis drags every byte across the slow link — the
    asymmetry the hierarchical path exists to fix)."""
    isz = jnp.dtype(comm_dt).itemsize
    if topo == "hierarchical":
        cause = ("trigger" if triggered else
                 "delay" if delay_allreduce else "single")
        n_pad = n + ((-n) % ici)
        m = n_pad // ici
        dcn_dt = jnp.dtype(jnp.bfloat16) if compress else jnp.dtype(comm_dt)
        dcn_bytes = m * dcn_dt.itemsize
        ici_bytes = n_pad * isz + m * isz        # scatter + gather back
        eqns = {"reduce_scatter": 1,
                "all_gather": 2 if compress else 1}
        payload = {"reduce_scatter": n_pad * isz,
                   "all_gather": m * isz + (dcn_bytes if compress else 0)}
        if not compress:
            eqns["psum"] = 1
            payload["psum"] = dcn_bytes
        return {"cause": cause, "chunks": 1, "topology": "hierarchical",
                "wire_elements": n_pad, "padded_elements": n_pad - n,
                "bytes": ici_bytes + dcn_bytes,
                "ici_wire_bytes": ici_bytes, "dcn_wire_bytes": dcn_bytes,
                "dcn_comm_dtype": str(dcn_dt),
                "eqns": eqns, "eqn_payload_bytes": payload}
    if delay_allreduce or triggered or n <= message_size:
        cause = ("trigger" if triggered
                 else "delay" if delay_allreduce else "single")
        chunks, wire = 1, n
    else:
        cause = "chunked"
        chunks = math.ceil(n / message_size)
        wire = chunks * message_size
    b = wire * isz
    return {"cause": cause, "chunks": chunks, "topology": "flat",
            "wire_elements": wire, "padded_elements": wire - n,
            "bytes": b, "ici_wire_bytes": b, "dcn_wire_bytes": b,
            "dcn_comm_dtype": str(jnp.dtype(comm_dt)),
            "eqns": {"psum": 1}, "eqn_payload_bytes": {"psum": b}}


def _hierarchical_reduce(comm: jax.Array, axis_name: str,
                         ici_groups, dcn_groups,
                         compress: bool, want_error: bool = False
                         ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Two-level sum of one flat bucket: ``psum_scatter`` within the
    ICI slice (the fast fabric carries the full payload and does the
    wide accumulation), cross-slice reduce over DCN on the 1/ici
    shard, in-slice ``all_gather`` back.  ``compress=True`` quantizes
    ONLY the DCN hop to bf16 and reduces it as all_gather + local sum
    in the communication dtype — the wire is half, the accumulation
    is not (the fp32-accumulate contract of allreduce_always_fp32
    survives compression).

    Returns ``(reduced, compression_sq_error)``: with ``want_error``
    (numerics observability, PR 9) the second element is the squared
    quantization error of THIS replica's own 1/ici shard on the bf16
    DCN hop — local elementwise math, no extra collectives, and
    ``None`` otherwise so the uninstrumented graph is unchanged."""
    n = comm.shape[0]
    shard, err = _hier_scatter_reduce(comm, axis_name, ici_groups,
                                      dcn_groups, compress, want_error)
    return _hier_gather(shard, axis_name, ici_groups, n), err


def _hier_scatter_reduce(comm: jax.Array, axis_name: str,
                         ici_groups, dcn_groups, compress: bool,
                         want_error: bool = False
                         ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """The scatter half of :func:`_hierarchical_reduce`: pad to the
    slice size, ``psum_scatter`` within ICI, DCN-reduce the 1/ici
    shard — and STOP.  This is exactly the ZeRO-2 gradient reduction
    (arXiv:2004.13336's reduce-scatter placement with the gather-back
    deleted): the caller that owns only the matching 1/ici optimizer
    shard never needs the full gradient, so the in-slice all_gather of
    grads is replaced by an all_gather of *updated params* after the
    shard update (:func:`_hier_gather`, same payload, same fabric
    level)."""
    ici = len(ici_groups[0])
    pad = (-comm.shape[0]) % ici
    if pad:
        comm = jnp.pad(comm, (0, pad))
    shard = lax.psum_scatter(comm, axis_name, scatter_dimension=0,
                             axis_index_groups=ici_groups, tiled=True)
    err = None
    if compress:
        q = shard.astype(jnp.bfloat16)
        if want_error:
            d = (shard.astype(jnp.float32)
                 - q.astype(jnp.float32))
            err = jnp.sum(d * d)
        wire = lax.all_gather(q, axis_name,
                              axis_index_groups=dcn_groups)
        shard = jnp.sum(wire.astype(shard.dtype), axis=0)
    else:
        shard = lax.psum(shard, axis_name, axis_index_groups=dcn_groups)
    return shard, err


def _hier_gather(shard: jax.Array, axis_name: str, ici_groups,
                 n: int) -> jax.Array:
    """The gather half: in-slice ``all_gather`` of a 1/ici shard back
    to the full (unpadded) buffer."""
    full = lax.all_gather(shard, axis_name,
                          axis_index_groups=ici_groups, tiled=True)
    return full[:n] if full.shape[0] != n else full


def _path_str(path) -> str:
    """'/'-joined readable key path for a tree_flatten_with_path entry."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def allreduce_grads_tree(grads: Any, axis_name: str = "data",
                         message_size: int = 10_000_000,
                         allreduce_always_fp32: bool = False,
                         gradient_average: bool = True,
                         gradient_predivide_factor: float = 1.0,
                         delay_allreduce: bool = False,
                         axis_index_groups: Optional[List[List[int]]] = None,
                         retain_buffers: Optional[list] = None,
                         trigger_paths: Optional[set] = None,
                         comm_stats: Optional[list] = None,
                         comm_topology: str = "flat",
                         allreduce_compress_bf16: bool = False,
                         ici_size: Optional[int] = None,
                         numerics_out: Optional[list] = None,
                         world_scalar: Optional[jax.Array] = None) -> Any:
    """Bucketed gradient allreduce with the reference's semantics
    (allreduce_bucket, distributed.py:378-398).  Must run inside a context
    where ``axis_name`` is a mapped mesh axis.

    ``trigger_paths``: the reference's ``allreduce_trigger_params``
    (distributed.py:162-171) — user-chosen params whose grad readiness
    fires a bucket flush, overriding message_size.  Arrival order doesn't
    exist under XLA, so the faithful mapping is: the listed leaves mark
    *bucket boundaries* in tree order; each bucket is one psum the
    scheduler can overlap independently.  Paths are '/'-joined key paths
    (e.g. 'layer1/conv/weight'); unknown paths raise.

    ``comm_topology``: where the bytes travel.  ``"flat"`` (default)
    reduces every bucket with one psum over the whole axis — on a
    multi-host mesh that drags the full payload across DCN, the slowest
    link.  ``"hierarchical"`` runs each bucket as psum_scatter within
    the ICI slice, a cross-slice reduce over DCN on the 1/ici_size
    shard, and an in-slice all_gather back — DCN carries 1/ici_size of
    the traffic, the sum is unchanged up to reduction-order round-off
    (pinned in tests/test_ddp.py like the ZeRO-1 psum_scatter-vs-psum
    ordering).  ``"auto"`` picks hierarchical iff the axis spans
    processes (topology.auto_comm_topology).  ``ici_size`` is the
    inner-level width (consecutive ranks per slice, make_mesh's
    multi-host ordering); it defaults to axis_size / process_count.
    Hierarchical within explicit ``axis_index_groups`` is not wired.
    ``message_size`` does NOT sub-chunk hierarchical buckets: each
    bucket is one reduce_scatter whose per-member shards XLA already
    schedules independently — the in-bucket psum chunking is a
    flat-path overlap device (its ``chunked`` cause never appears
    under hierarchical; bucket *boundaries* from triggers/dtypes still
    apply).

    ``allreduce_compress_bf16``: quantize the DCN hop to bf16 — on-wire
    payload halves; the ICI reduce-scatter and the per-slice
    accumulation stay in the communication dtype, so it composes with
    ``allreduce_always_fp32`` (fp32 adds, bf16 wire).  Hierarchical
    only.

    ``comm_stats``: observability out-param — one dict per reduced
    bucket ({dtype, comm_dtype, leaves, elements, bytes, cause, chunks,
    topology, wire_elements, padded_elements, ici_wire_bytes,
    dcn_wire_bytes, ...}) appended at TRACE time (like
    ``retain_buffers``), i.e. once per compiled step, describing what
    every execution of that step communicates.  ``bytes`` is true
    on-wire traffic (chunk/shard padding included, all levels summed);
    ``cause`` records why the bucket flushed: a trigger boundary,
    ``delay_allreduce``, fitting under ``message_size`` (``single``),
    or the chunked-psum path.

    ``numerics_out``: numerics observability out-param (PR 9) — one
    dict per bucket, in the same order as the comm plan, carrying the
    static bucket identity plus DEVICE scalars (``nonfinite`` /
    ``abs_max`` / ``sq_sum`` of the pre-divide communication buffer,
    and ``compression_sq_error`` of this replica's shard on the bf16
    DCN hop when compressed).  Unlike ``comm_stats`` these are traced
    values: thread them into the step carry in the SAME trace (e.g.
    ``NumericsMonitor.update(bucket_stats=...)``).  All stats are
    local elementwise math — the collective census and host-transfer
    audit of the step are unchanged.

    ``world_scalar``: the traced axis-size scalar to average by,
    computed ONCE by a caller that reduces several stage subtrees in
    one step (``DistributedDataParallel.staged_allreduce_grads``) —
    without it every per-stage call would psum its own 4-byte scalar
    and the step's collective census would grow by the stage count.
    ``None`` (the default) keeps the classic behavior: this call psums
    the scalar itself."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    topo, compress = _resolve_topology(comm_topology,
                                       allreduce_compress_bf16)
    ici_groups = dcn_groups = None
    ici = 1
    if topo == "hierarchical":
        if axis_index_groups is not None:
            raise NotImplementedError(
                "comm_topology='hierarchical' over explicit "
                "axis_index_groups is not wired — the hierarchy defines "
                "its own ICI/DCN groups")
        world_static = int(lax.axis_size(axis_name))
        ici = (int(ici_size) if ici_size is not None
               else _topology.default_ici_size(world_static))
        ici_groups, dcn_groups = _topology.hierarchical_axis_groups(
            world_static, ici)
    paths = None
    if trigger_paths:
        flat_paths = jax.tree_util.tree_flatten_with_path(grads)[0]
        paths = [_path_str(p) for p, _ in flat_paths]
        unknown = set(trigger_paths) - set(paths)
        if unknown:
            raise ValueError(
                f"allreduce_trigger_params paths not found in the gradient "
                f"tree: {sorted(unknown)}; available: {paths[:8]}...")

    # dtype-split buckets, like split_half_float_double (distributed.py:51-58)
    groups: Dict[Any, List[int]] = {}
    for i, g in enumerate(leaves):
        groups.setdefault(jnp.dtype(g.dtype), []).append(i)

    world = world_scalar if world_scalar is not None \
        else _axis_size(axis_name)
    if axis_index_groups is not None:
        world = jnp.asarray(float(len(axis_index_groups[0])), jnp.float32)

    new_leaves: List[Any] = [None] * len(leaves)
    for dt, idxs in groups.items():
        # trigger params split the group into separately-reduced buckets
        if trigger_paths:
            buckets, cur = [], []
            for i in idxs:
                cur.append(i)
                if paths[i] in trigger_paths:
                    buckets.append(cur)
                    cur = []
            if cur:
                buckets.append(cur)
        else:
            buckets = [idxs]

        for bucket in buckets:
            flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
            comm = flat.astype(jnp.float32) if allreduce_always_fp32 else flat
            nstat = None
            if numerics_out is not None:
                # bucket health on the pre-divide comm buffer: what
                # actually goes on the wire, before the predivide
                # shifts magnitudes.  Nonfinite masked out of the
                # magnitude stats so one inf doesn't erase them.
                x = comm.astype(jnp.float32)
                fin = jnp.isfinite(x)
                ax = jnp.abs(jnp.where(fin, x, 0.0))
                nstat = {"dtype": str(dt),
                         "comm_dtype": str(comm.dtype),
                         "leaves": len(bucket),
                         "elements": int(flat.shape[0]),
                         "nonfinite": jnp.sum(~fin).astype(jnp.float32),
                         "abs_max": jnp.max(ax, initial=0.0),
                         "sq_sum": jnp.sum(ax * ax)}
            pre, post = predivide_factors(world,
                                          gradient_predivide_factor)
            if pre != 1.0:
                comm = comm / jnp.asarray(pre, comm.dtype)

            n = comm.shape[0]
            acct = _bucket_wire_accounting(
                n, comm.dtype, topo, ici, compress, message_size,
                delay_allreduce, bool(trigger_paths))
            if topo == "hierarchical":
                reduced, comp_err = _hierarchical_reduce(
                    comm, axis_name, ici_groups, dcn_groups, compress,
                    want_error=numerics_out is not None)
                if nstat is not None and comp_err is not None:
                    nstat["compression_sq_error"] = comp_err
            elif acct["chunks"] == 1:
                reduced = lax.psum(comm, axis_name,
                                   axis_index_groups=axis_index_groups)
            else:
                # chunked psum: XLA schedules the pieces independently —
                # the compiler-native form of the reference's bucket overlap
                nchunks = acct["chunks"]
                pad = nchunks * message_size - n
                padded = jnp.pad(comm, (0, pad))
                chunks = padded.reshape(nchunks, message_size)
                reduced = lax.psum(chunks, axis_name,
                                   axis_index_groups=axis_index_groups)
                reduced = reduced.reshape(-1)[:n]

            if comm_stats is not None:
                comm_stats.append({
                    "dtype": str(dt), "comm_dtype": str(comm.dtype),
                    "leaves": len(bucket), "elements": int(n),
                    **{k: v for k, v in acct.items()
                       if k not in ("eqns", "eqn_payload_bytes")}})
            if nstat is not None:
                numerics_out.append(nstat)

            if gradient_average:
                reduced = reduced / post.astype(reduced.dtype)
            reduced = reduced.astype(dt)
            if retain_buffers is not None:
                retain_buffers.append(reduced)
            off = 0
            for i in bucket:
                sz = leaves[i].size
                new_leaves[i] = reduced[off:off + sz].reshape(leaves[i].shape)
                off += sz
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def allreduce_comm_plan(grads: Any, message_size: int = 10_000_000,
                        allreduce_always_fp32: bool = False,
                        delay_allreduce: bool = False,
                        trigger_paths: Optional[set] = None,
                        comm_topology: str = "flat",
                        allreduce_compress_bf16: bool = False,
                        ici_size: Optional[int] = None,
                        world: Optional[int] = None,
                        nproc: Optional[int] = None) -> List[dict]:
    """Static twin of :func:`allreduce_grads_tree`'s bucketing: what the
    comm pattern of one allreduce WILL be, computed from shapes alone
    (no tracing).  One dict per bucket::

        {dtype, comm_dtype, leaves, elements, chunks, cause, topology,
         ici_size, dcn_size, wire_elements, padded_elements, wire_bytes,
         ici_wire_bytes, dcn_wire_bytes, dcn_comm_dtype,
         eqns, eqn_payload_bytes}

    ``wire_elements`` includes chunk/shard padding — the elements the
    bucket's first collective actually moves per replica; ``wire_bytes``
    is the TRUE total on-wire traffic summed over every fabric level
    (for the flat topology that is the one psum; for the hierarchical
    topology the ICI reduce_scatter + the DCN reduce + the ICI
    all_gather), split per level as ``ici_wire_bytes`` /
    ``dcn_wire_bytes``.  ``eqns`` / ``eqn_payload_bytes`` give the
    exact per-primitive collective census of the bucket, matching what
    ``analysis.eqn_payload_bytes`` reads off the traced graph.
    ``apex_tpu.analysis``'s collective-accounting rule derives its DDP
    expectations from this plan (see
    :func:`plan_collective_expectations`): if the bucketing or topology
    algorithm changes, the plan and the traced graph move together,
    while an accidental extra/missing/fatter collective still flags.

    The topology knobs mirror the runtime: for ``"hierarchical"`` (or
    ``"auto"`` resolving there — ``nproc`` defaults to
    ``jax.process_count()``) the static axis size must be supplied as
    ``world=`` since there is no mapped axis to read it from."""
    leaves = jax.tree_util.tree_leaves(grads)
    plan: List[dict] = []
    if not leaves:
        return plan
    topo, compress = _resolve_topology(comm_topology,
                                       allreduce_compress_bf16, nproc)
    ici = dcn = 1
    if topo == "hierarchical":
        if world is None:
            raise ValueError(
                "a hierarchical comm plan needs world= (the static "
                "axis size); the runtime reads it from the mapped axis")
        ici = (int(ici_size) if ici_size is not None
               else _topology.default_ici_size(int(world), nproc))
        # validates divisibility the same way the runtime does
        _topology.hierarchical_axis_groups(int(world), ici)
        dcn = int(world) // ici
    paths = None
    if trigger_paths:
        flat_paths = jax.tree_util.tree_flatten_with_path(grads)[0]
        paths = [_path_str(p) for p, _ in flat_paths]
        unknown = set(trigger_paths) - set(paths)
        if unknown:
            # mirror allreduce_grads_tree: a plan for a comm pattern
            # the real step would refuse to trace is not a plan
            raise ValueError(
                f"allreduce_trigger_params paths not found in the "
                f"gradient tree: {sorted(unknown)}; available: "
                f"{paths[:8]}...")

    groups: Dict[Any, List[int]] = {}
    for i, g in enumerate(leaves):
        groups.setdefault(jnp.dtype(g.dtype), []).append(i)

    for dt, idxs in groups.items():
        if trigger_paths:
            buckets, cur = [], []
            for i in idxs:
                cur.append(i)
                if paths[i] in trigger_paths:
                    buckets.append(cur)
                    cur = []
            if cur:
                buckets.append(cur)
        else:
            buckets = [idxs]
        for bucket in buckets:
            n = sum(int(leaves[i].size) for i in bucket)
            comm_dt = jnp.dtype(jnp.float32) if allreduce_always_fp32 \
                else dt
            acct = _bucket_wire_accounting(
                n, comm_dt, topo, ici, compress, message_size,
                delay_allreduce, bool(trigger_paths))
            plan.append({
                "dtype": str(dt), "comm_dtype": str(comm_dt),
                "leaves": len(bucket), "elements": n,
                "chunks": acct["chunks"], "cause": acct["cause"],
                "topology": acct["topology"],
                "ici_size": ici, "dcn_size": dcn,
                "wire_elements": acct["wire_elements"],
                "padded_elements": acct["padded_elements"],
                "wire_bytes": acct["bytes"],
                "ici_wire_bytes": acct["ici_wire_bytes"],
                "dcn_wire_bytes": acct["dcn_wire_bytes"],
                "dcn_comm_dtype": acct["dcn_comm_dtype"],
                "eqns": acct["eqns"],
                "eqn_payload_bytes": acct["eqn_payload_bytes"]})
    return plan


def plan_collective_expectations(plan: List[dict],
                                 extra_psums: int = 0,
                                 extra_psum_bytes: int = 0) -> dict:
    """Fold a :func:`allreduce_comm_plan` into the ``collectives``
    expectation dict the analysis rule consumes: exact per-primitive
    eqn counts, the total on-wire payload, and the per-primitive
    payload split — which IS the ici-vs-dcn distinction at graph level
    (under the hierarchical topology the bucket's psum — or compressed
    bf16 all_gather — payload is exactly the DCN hop).

    ``extra_psums`` / ``extra_psum_bytes`` account for the step's
    scalar psums outside the grad reduction (the axis-size scalar
    ``gradient_average`` divides by, the loss pmean)."""
    counts: Counter = Counter()
    by_prim: Counter = Counter()
    total = 0
    for b in plan:
        for prim, k in b["eqns"].items():
            counts[prim] += k
        for prim, by in b["eqn_payload_bytes"].items():
            by_prim[prim] += by
        total += b["wire_bytes"]
    if extra_psums:
        counts["psum"] += extra_psums
        by_prim["psum"] += extra_psum_bytes
    return {"counts": dict(counts),
            "payload_bytes": total + extra_psum_bytes,
            "payload_bytes_by_primitive": dict(by_prim)}


def plan_resharding_expectations(plan: List[dict],
                                 budget: Optional[Dict[str, int]] = None
                                 ) -> dict:
    """Fold a comm plan (:func:`allreduce_comm_plan` buckets, or
    ``overlap_comm_schedule()["buckets"]``) into the ``resharding``
    expectation the census rule consumes: the exact per-eqn payload
    list of every *placement-changing* collective the plan issues.

    Unlike :func:`plan_collective_expectations` (which pins totals),
    the census needs per-eqn payloads so it can match graph eqns one by
    one and name the unexplained gather.  Per bucket:

    - ``reduce_scatter``: one eqn, the full padded bucket.
    - ``all_gather``: the in-slice gather-back of the 1/ici shard;
      under bf16 compression the DCN reduce is itself an all_gather of
      ``dcn_wire_bytes``, so the bucket contributes two payloads —
      ``[dcn_wire_bytes, total - dcn_wire_bytes]``.

    ``budget`` declares per-primitive counts of *additional* resharding
    eqns the entry point is allowed beyond the plan (default: none —
    any unplanned gather is an error finding)."""
    planned: Dict[str, List[int]] = {}
    for b in plan:
        eqns = b.get("eqns", {})
        payload = b.get("eqn_payload_bytes", {})
        for prim in ("all_gather", "all_to_all", "reduce_scatter",
                     "pgather"):
            k = int(eqns.get(prim, 0))
            if not k:
                continue
            total = int(payload.get(prim, 0))
            if prim == "all_gather" and k == 2:
                dcn = int(b.get("dcn_wire_bytes", 0))
                pays = [dcn, total - dcn]
            elif k == 1:
                pays = [total]
            else:
                pays = [total // k] * k
                pays[0] += total - sum(pays)
            planned.setdefault(prim, []).extend(pays)
    exp: Dict[str, Any] = {"planned": planned}
    if budget:
        exp["budget"] = {k: int(v) for k, v in budget.items()}
    return exp


def zero_update_comm_plan(params: Any, *, zero_stage: int,
                          world: int, ici_size: Optional[int] = None,
                          zero_compress_bf16: bool = False
                          ) -> List[dict]:
    """Static comm plan of one ZeRO-sharded optimizer step
    (``amp.AmpOptimizer.step`` with a ``zero_axis`` layout), in the
    same bucket schema as :func:`allreduce_comm_plan` so
    :func:`plan_collective_expectations` and
    :func:`plan_resharding_expectations` fold it unchanged — the
    analysis rules pin the ZeRO collective structure from the same
    source the runtime derives it from.  Buckets, by ``role``:

    - ``grad_reduce`` — the gradient reduction.  Stage 1: one
      full-axis ``reduce_scatter`` of the padded flat buffer (flat
      accounting: every byte crosses the slowest link).  Stages 2/3:
      the in-slice ``reduce_scatter`` plus the DCN reduce of the
      1/ici shard (a ``psum``, or a bf16 ``all_gather`` when
      compressed) — stage 3's scatter is the *transpose* of the
      just-in-time parameter gather, but it is the same eqn with the
      same payload, so the plan does not care who emitted it.
    - ``param_gather`` (stages 1/2, one bucket per gathered dtype) —
      the updated-shard all_gather back to full params: the half
      model copy, plus the fp32 copy only when some float leaf stays
      fp32 (``amp`` skips that gather otherwise, and so does the
      plan).
    - ``jit_gather`` (stage 3) — the ``zero_gather_params`` collectives
      in the forward and again in the ``jax.checkpoint`` replay: the
      half-dtype shard all_gather plus, when some float leaf stays
      fp32, the tiny fp32 aux gather of the exact elements (one fp32
      all_gather total when the layout has no half dtype).  Stage 3 has
      NO param_gather buckets: the master shard is the parameter store.

    ``params`` is the model parameter tree (shapes/dtypes only — the
    plan is static)."""
    from ..amp._process_optimizer import (_FlatLayout,
                                          _validate_zero_knobs)
    _validate_zero_knobs(zero_stage, ici_size, zero_compress_bf16)
    layout = _FlatLayout(params)
    n = layout.total
    isz = 4                                    # grads reduce in fp32
    if zero_stage >= 2:
        ici = int(ici_size)
        _topology.hierarchical_axis_groups(int(world), ici)
        dcn = int(world) // ici
        pop = ici
        topo = "hierarchical"
    else:
        pop = ici = int(world)
        dcn = 1
        topo = "flat"
    n_pad = n + ((-n) % pop)
    m = n_pad // pop
    half = layout.half_dtype
    any_fp32 = any(f and d == "float32" for f, d in
                   zip(layout.is_float, layout.dtypes))
    n_float = sum(1 for f in layout.is_float if f)

    def bucket(role, dtype, comm_dtype, leaves, elements, padded,
               eqns, payload, ici_bytes, dcn_bytes, dcn_dt):
        return {"role": role, "zero_stage": int(zero_stage),
                "dtype": str(dtype), "comm_dtype": str(comm_dtype),
                "leaves": leaves, "elements": elements,
                "chunks": 1, "cause": "zero", "topology": topo,
                "ici_size": ici, "dcn_size": dcn,
                "wire_elements": elements + padded,
                "padded_elements": padded,
                "wire_bytes": sum(payload.values()),
                "ici_wire_bytes": ici_bytes,
                "dcn_wire_bytes": dcn_bytes,
                "dcn_comm_dtype": str(jnp.dtype(dcn_dt)),
                "eqns": eqns, "eqn_payload_bytes": payload}

    plan: List[dict] = []
    if zero_stage >= 2:
        if zero_compress_bf16:
            eqns = {"reduce_scatter": 1, "all_gather": 1}
            payload = {"reduce_scatter": n_pad * isz,
                       "all_gather": m * 2}
            dcn_bytes, dcn_dt = m * 2, jnp.bfloat16
        else:
            eqns = {"reduce_scatter": 1, "psum": 1}
            payload = {"reduce_scatter": n_pad * isz, "psum": m * isz}
            dcn_bytes, dcn_dt = m * isz, jnp.float32
        plan.append(bucket("grad_reduce", jnp.float32, jnp.float32,
                           n_float, n, n_pad - n, eqns, payload,
                           n_pad * isz, dcn_bytes, dcn_dt))
    else:
        plan.append(bucket("grad_reduce", jnp.float32, jnp.float32,
                           n_float, n, n_pad - n,
                           {"reduce_scatter": 1},
                           {"reduce_scatter": n_pad * isz},
                           n_pad * isz, n_pad * isz, jnp.float32))
    if zero_stage == 3:
        # the jit gather runs at the model half dtype when the layout
        # has one (zero_gather_params): the half all_gather plus a tiny
        # fp32 aux gather for the exact (non-half) elements; all-fp32
        # layouts gather once in fp32.  Both appear twice: forward +
        # remat replay (zero_gather_checkpoint_policy re-gathers in the
        # backward instead of keeping the full model live).
        if half is not None:
            from ..amp._process_optimizer import _zero3_gather_tables
            _, _, n32, m32 = _zero3_gather_tables(layout, ici)
            hsz = jnp.dtype(half).itemsize
            gathers = [(half, {"all_gather": m * hsz}, m * hsz)]
            if n32:
                gathers.append((jnp.float32,
                                {"all_gather": max(m32, 1) * isz},
                                max(m32, 1) * isz))
        else:
            gathers = [(jnp.float32, {"all_gather": m * isz}, m * isz)]
        for _ in range(2):                     # forward + remat replay
            for dt, payload, ici_bytes in gathers:
                plan.append(bucket(
                    "jit_gather", dt, dt, n_float,
                    payload["all_gather"] // jnp.dtype(dt).itemsize, 0,
                    {"all_gather": 1}, dict(payload),
                    ici_bytes, 0, dt))
    else:
        gathers = []
        if any_fp32 or half is None:
            gathers.append((jnp.float32, 4,
                            sum(1 for f, d in zip(layout.is_float,
                                                  layout.dtypes)
                                if f and d == "float32")))
        if half is not None:
            gathers.append((half, jnp.dtype(half).itemsize,
                            sum(1 for f, d in zip(layout.is_float,
                                                  layout.dtypes)
                                if f and d == str(half))))
        for dt, dsz, leaves in gathers:
            b = m * dsz
            plan.append(bucket(
                "param_gather", dt, dt, leaves, m, 0,
                {"all_gather": 1}, {"all_gather": b},
                b, b if zero_stage == 1 else 0, dt))
    return plan


def _stamp_stage_labels(records: List[dict], stage: int,
                        issue_start: int) -> int:
    """Stamp one stage's bucket records (plan buckets OR runtime
    ``comm_stats``/``numerics_out`` dicts) with their place in the
    overlap schedule: ``stage`` (which forward stage owns the bucket)
    and ``issue_order`` (global position in the issue sequence).  ONE
    implementation shared by :func:`overlap_comm_schedule` and the
    runtime path, so a schedule change cannot relabel one side only.
    Returns the next free issue index."""
    for i, rec in enumerate(records):
        rec["stage"] = int(stage)
        rec["issue_order"] = issue_start + i
    return issue_start + len(records)


def staged_grads(stage_fns: Sequence[Callable], loss_head: Callable,
                 stage_params: Sequence[Any], x: Any,
                 reduce_stage: Optional[Callable] = None,
                 overlap: bool = True) -> Tuple[jax.Array, List[Any]]:
    """Manual chain rule over a sequential stage decomposition — the
    comm/compute-overlap engine (ROADMAP item 2; reference DDP's
    arrival-order bucket drain, distributed.py:378-398, expressed as
    program order).

    ``stage_fns[i](stage_params[i], act) -> act`` compose the forward;
    ``loss_head(act) -> scalar`` closes over labels.  The forward runs
    every stage under :func:`jax.vjp`; the backward then walks stages
    in :func:`topology.overlap_issue_order` (back-to-front — reverse
    AD makes the LAST stage's gradients first).  With ``overlap=True``
    each stage's ``reduce_stage(stage, issue_idx, grads)`` is called
    the moment that stage's gradients exist, BEFORE the next stage's
    VJP runs — so in the traced jaxpr the first bucket's
    psum_scatter/DCN-reduce/all_gather chain sits ahead of the earlier
    layers' grad eqns and a latency-hiding scheduler can run them
    concurrently (statically pinned by the collective lint rule's
    interleaving check).  With ``overlap=False`` the same reductions
    are issued in the same order but only AFTER the whole backward —
    the reduce-after-backward baseline the overlapped schedule is
    numerically pinned against (identical buckets, identical
    collectives, only the issue positions differ; grads match at fp32
    rtol 1e-6 in tests/test_overlap.py).

    Returns ``(loss, [per-stage grads])`` with grads in STAGE order
    (``grads[i]`` matches ``stage_params[i]``), reduced when
    ``reduce_stage`` is given."""
    n = len(stage_fns)
    if n != len(stage_params):
        raise ValueError(f"{n} stage fns vs {len(stage_params)} stage "
                         f"param trees")
    order = _topology.overlap_issue_order(n)
    act = x
    vjps = []
    for fn, p in zip(stage_fns, stage_params):
        act, vjp = jax.vjp(fn, p, act)
        vjps.append(vjp)
    loss, loss_vjp = jax.vjp(loss_head, act)
    (ct,) = loss_vjp(jnp.ones_like(loss))
    grads: List[Any] = [None] * n
    for issue, s in enumerate(order):
        g, ct = vjps[s](ct)
        if overlap and reduce_stage is not None:
            g = reduce_stage(s, issue, g)
        grads[s] = g
    if not overlap and reduce_stage is not None:
        # reduce-after-backward: SAME buckets, SAME issue order, issued
        # only once the full backward has been emitted
        for issue, s in enumerate(order):
            grads[s] = reduce_stage(s, issue, grads[s])
    return loss, grads


def overlap_comm_schedule(stage_trees: Sequence[Any],
                          message_size: int = 10_000_000,
                          allreduce_always_fp32: bool = False,
                          comm_topology: str = "flat",
                          allreduce_compress_bf16: bool = False,
                          ici_size: Optional[int] = None,
                          world: Optional[int] = None,
                          nproc: Optional[int] = None,
                          overlap: bool = True,
                          zero_stage: Optional[int] = None
                          ) -> Dict[str, Any]:
    """The static overlap schedule: :func:`allreduce_comm_plan`
    extended with WHEN each bucket's reduction is issued, computed from
    shapes alone.  Returns::

        {"overlap_mode": "overlapped" | "reduce_after_backward",
         "n_stages": S,
         "issue_order": [S-1, ..., 0],        # stage-level issue order
         "buckets": [...]}                    # plan buckets + stage/
                                              #   issue_order labels

    Every bucket dict is an :func:`allreduce_comm_plan` bucket — same
    shared :func:`_bucket_wire_accounting`, so per-level wire bytes are
    UNCHANGED by overlapping (the schedule moves issue positions, not
    payloads) — stamped by the same :func:`_stamp_stage_labels` the
    runtime uses.  Bucket order in ``buckets`` IS issue order, which is
    also the order ``comm_stats``/``numerics_out`` records arrive in at
    trace time; ``tests/test_overlap.py`` pins the two sides equal.
    The collective lint rule derives its expectations (census, per-
    primitive payloads, AND the static interleaving property) from this
    schedule via :func:`overlap_collective_expectations`.

    ``zero_stage=2`` describes the ZeRO-2 fused staged step
    (:meth:`DistributedDataParallel.staged_zero2_allreduce_grads`):
    per-stage wire accounting is IDENTICAL to the plain hierarchical
    schedule — the in-slice all_gather carries the *updated params*
    instead of the reduced grads, same shard, same payload, same
    fabric level — so the buckets are unchanged and the schedule is
    merely tagged (requires ``comm_topology='hierarchical'``)."""
    if zero_stage is not None:
        if zero_stage != 2:
            raise ValueError(
                f"overlap_comm_schedule composes with ZeRO stage 2 "
                f"only (stage 3's gather lives in the forward, not "
                f"the grad schedule); got zero_stage={zero_stage!r}")
        if comm_topology != "hierarchical":
            raise ValueError(
                "the fused ZeRO-2 staged schedule shards over the ICI "
                "slice; comm_topology must be 'hierarchical'")
    order = _topology.overlap_issue_order(len(stage_trees))
    buckets: List[dict] = []
    issue = 0
    for s in order:
        stage_buckets = allreduce_comm_plan(
            stage_trees[s], message_size=message_size,
            allreduce_always_fp32=allreduce_always_fp32,
            comm_topology=comm_topology,
            allreduce_compress_bf16=allreduce_compress_bf16,
            ici_size=ici_size, world=world, nproc=nproc)
        issue = _stamp_stage_labels(stage_buckets, s, issue)
        buckets.extend(stage_buckets)
    return {"overlap_mode": ("overlapped" if overlap
                             else "reduce_after_backward"),
            "n_stages": len(stage_trees),
            "issue_order": order,
            "zero_stage": zero_stage,
            "buckets": buckets}


def overlap_schedule_fields(schedule: Optional[Dict[str, Any]]
                            ) -> Dict[str, Any]:
    """The schedule fields a bench/attribution record carries
    (``exporters.OVERLAP_SCHEDULE_FIELDS``): mode, stage count, and
    stage-level issue order.  ``None`` describes a classic
    un-staged step — one stage, reduced after backward — so every
    attribution record can say which schedule it measured."""
    if schedule is None:
        return {"overlap_mode": "reduce_after_backward",
                "n_stages": 1, "issue_order": [0]}
    out = {"overlap_mode": schedule["overlap_mode"],
           "n_stages": int(schedule["n_stages"]),
           "issue_order": [int(s) for s in schedule["issue_order"]]}
    if schedule.get("zero_stage") is not None:
        out["zero_stage"] = int(schedule["zero_stage"])
    return out


def overlap_collective_expectations(schedule: Dict[str, Any],
                                    extra_psums: int = 0,
                                    extra_psum_bytes: int = 0) -> dict:
    """Fold an :func:`overlap_comm_schedule` into the collective rule's
    expectation dict: the exact census/payloads of
    :func:`plan_collective_expectations` over the schedule's buckets,
    PLUS — for the overlapped mode — the static interleaving pin: the
    first issued bucket's reduction eqns must appear in the jaxpr
    BEFORE the last layers' grad (conv/dot) eqns, not trail the whole
    backward.  ``min_payload_bytes`` separates grad-bucket collectives
    from the step's scalar psums (axis size, loss pmean): it is the
    smallest per-level hop any bucket puts on the wire, which a real
    gradient bucket always clears and a 4-byte scalar never does."""
    exp = plan_collective_expectations(schedule["buckets"],
                                       extra_psums=extra_psums,
                                       extra_psum_bytes=extra_psum_bytes)
    if schedule["overlap_mode"] == "overlapped" and schedule["buckets"]:
        min_hop = min(
            min(b["dcn_wire_bytes"], b["ici_wire_bytes"])
            for b in schedule["buckets"])
        # every bucket of every stage except the LAST-issued one (stage
        # 0 — reverse AD drains back-to-front) is emitted before that
        # stage's VJP, hence before the last grad matmul: each of its
        # eqns clears min_payload_bytes (every per-eqn payload is at
        # least its bucket's smaller fabric hop, which is at least the
        # global min_hop), so the schedule implies an exact FLOOR on
        # how many reductions precede the last matmul — the static
        # proof that the overlap did not silently collapse to
        # reduce-after-backward for all but one stage
        last_stage = schedule["issue_order"][-1]
        n_before = sum(sum(b["eqns"].values())
                       for b in schedule["buckets"]
                       if b["stage"] != last_stage)
        exp["interleaving"] = {
            "min_payload_bytes": max(int(min_hop), 16),
            "min_matmuls_after": 1,
            "min_collectives_before_last_matmul": int(n_before)}
    return exp


def _broadcast0(flat: jax.Array, axis_name: str,
                axis_index_groups=None) -> jax.Array:
    """Broadcast from rank 0 expressed as a masked psum (XLA lowers this
    to a collective-broadcast-shaped pattern over ICI).  psum runs in the
    leaf's own dtype — an fp32 round-trip would corrupt integer leaves
    beyond 2^24 (e.g. PRNG keys)."""
    comm = flat.astype(jnp.int32) if flat.dtype == jnp.bool_ else flat
    src = jnp.where(lax.axis_index(axis_name) == 0, comm,
                    jnp.zeros_like(comm))
    return lax.psum(src, axis_name,
                    axis_index_groups=axis_index_groups).astype(flat.dtype)


def flat_dist_call(tree: Any, axis_name: str = "data", op: str = "psum",
                   axis_index_groups=None) -> Any:
    """apply_flat_dist_call parity (distributed.py:36-49): one collective
    per dtype group over the flattened tree."""
    reducer = {"psum": lax.psum, "pmean": lax.pmean, "pmax": lax.pmax,
               "pmin": lax.pmin, "broadcast": _broadcast0}[op]
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    groups: Dict[Any, List[int]] = {}
    for i, g in enumerate(leaves):
        groups.setdefault(jnp.dtype(g.dtype), []).append(i)
    out: List[Any] = [None] * len(leaves)
    for dt, idxs in groups.items():
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        red = reducer(flat, axis_name, axis_index_groups=axis_index_groups)
        off = 0
        for i in idxs:
            sz = leaves[i].size
            out[i] = red[off:off + sz].reshape(leaves[i].shape)
            off += sz
    return jax.tree_util.tree_unflatten(treedef, out)


class DistributedDataParallel:
    """Model wrapper with the reference's constructor surface
    (distributed.py:129-171)."""

    def __init__(self, module=None, message_size: int = 10_000_000,
                 delay_allreduce: bool = False,
                 shared_param: Optional[bool] = None,
                 allreduce_trigger_params: Optional[list] = None,
                 retain_allreduce_buffers: bool = False,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 gradient_predivide_factor: float = 1.0,
                 axis_name: str = "data",
                 adasum: bool = False,
                 comm_topology: str = "flat",
                 allreduce_compress_bf16: bool = False,
                 ici_size: Optional[int] = None,
                 overlap: bool = False,
                 zero_stage: Optional[int] = None):
        if shared_param is not None:
            raise ValueError("shared_param is deprecated (reference "
                             "distributed.py:176-180)")
        self.module = module
        self.message_size = int(message_size)
        self.delay_allreduce = delay_allreduce
        self.allreduce_trigger_params = allreduce_trigger_params
        self.retain_allreduce_buffers = retain_allreduce_buffers
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.gradient_predivide_factor = gradient_predivide_factor
        self.axis_name = axis_name
        # topology knobs (allreduce_grads_tree): where the gradient
        # bytes travel — validated eagerly so a typo fails at
        # construction, not at first trace
        _validate_topology_knobs(comm_topology, allreduce_compress_bf16)
        self.comm_topology = comm_topology
        self.allreduce_compress_bf16 = allreduce_compress_bf16
        self.ici_size = ici_size
        # adasum=True swaps the psum for the adaptive-summation
        # butterfly (parallel/adasum.py, arXiv:2006.02924) — a
        # beyond-reference combiner for conflict-aware large-batch DP.
        # It REPLACES the sum-then-average pipeline wholesale, so the
        # psum-shaping knobs are meaningless with it: reject loudly
        # instead of silently ignoring them.  comm_topology DOES
        # compose: hierarchical adasum averages within the ICI slice
        # and runs the butterfly across slices (the paper's
        # average-within-node recipe) — see adasum_grads(ici_size=).
        self.adasum = adasum
        if adasum:
            clashes = [name for name, bad in (
                ("delay_allreduce", delay_allreduce),
                ("allreduce_trigger_params",
                 bool(allreduce_trigger_params)),
                ("retain_allreduce_buffers", retain_allreduce_buffers),
                ("allreduce_always_fp32", allreduce_always_fp32),
                ("allreduce_compress_bf16", allreduce_compress_bf16),
                ("gradient_average=False", not gradient_average),
                ("gradient_predivide_factor",
                 gradient_predivide_factor != 1.0)) if bad]
            if clashes:
                raise ValueError(
                    f"adasum=True replaces the psum pipeline; these "
                    f"options have no effect with it: {clashes}")
        # overlap=True selects the overlapped bucket schedule for
        # staged_allreduce_grads: each stage's reduction is issued
        # while earlier stages' gradients are still being computed.
        # It contradicts delay_allreduce (ONE fused reduce after
        # backward is the opposite schedule) and allreduce_trigger_
        # params (stage boundaries ARE the bucket boundaries in the
        # staged world); adasum's butterfly replaces the bucket
        # pipeline wholesale, so staging it is not wired.  Topology /
        # compression / predivide all compose — the per-bucket
        # reduction is the unchanged hierarchical chain, only its
        # issue position moves.
        self.overlap = bool(overlap)
        if self.overlap:
            clashes = [name for name, bad in (
                ("delay_allreduce", delay_allreduce),
                ("allreduce_trigger_params",
                 bool(allreduce_trigger_params)),
                ("adasum", adasum)) if bad]
            if clashes:
                raise ValueError(
                    f"overlap=True issues per-stage bucket reductions "
                    f"inside the backward; these options contradict "
                    f"that schedule: {clashes}")
        # zero_stage=2 arms the fused ZeRO-2 staged path
        # (staged_zero2_allreduce_grads): per-stage scatter-reduce to
        # the 1/ici shard, shard update, in-slice gather of the
        # UPDATED params — state sharding composed with the overlap
        # schedule.  Stages 1/3 shard inside amp.AmpOptimizer (the
        # step owns the flat master buffer), not here.
        if zero_stage is not None:
            if zero_stage != 2:
                raise ValueError(
                    f"DistributedDataParallel composes with ZeRO "
                    f"stage 2 only (stages 1/3 live in "
                    f"amp.AmpOptimizer's flat-buffer step); got "
                    f"zero_stage={zero_stage!r}")
            if comm_topology != "hierarchical":
                raise ValueError(
                    "zero_stage=2 shards the update over the ICI "
                    "slice; comm_topology must be 'hierarchical'")
            if adasum:
                raise ValueError("zero_stage=2 does not compose with "
                                 "adasum (the butterfly replaces the "
                                 "reduce-scatter the shard rides on)")
        self.zero_stage = zero_stage
        self.allreduce_buffers: list = []
        # trace-time comm accounting (observability): one record per
        # bucket of the most recently traced allreduce — see
        # allreduce_grads_tree(comm_stats=...)
        self.last_comm_stats: list = []
        # the most recently traced overlap schedule
        # (staged_allreduce_grads): overlap_mode / n_stages /
        # issue_order / stage-stamped bucket records — None until a
        # staged step traces, or when the compute twin elides comm
        self.last_overlap_schedule: Optional[dict] = None
        # numerics observability (PR 9): the most recently FLUSHED
        # gradient-health summary — host-side plain python, set by
        # record_numerics() after the step's NumericsMonitor.flush()
        # (the in-step device stats ride the carry, never this attr)
        self.last_numerics: dict = {}
        # comm_enabled=False builds the COMPUTE TWIN of a step for
        # step-time attribution (observability.steptime): the gradient
        # collectives are elided while the local average a psum would
        # have applied stays, so the twin's per-element work matches
        # the full step minus the wire.  Numerically it trains on
        # local gradients — a measurement device, not a training mode.
        self.comm_enabled = True

    # -- forward passthrough (wrapper parity) ------------------------------
    def __call__(self, *args, **kwargs):
        return self.module(*args, **kwargs)

    def apply(self, *args, **kwargs):
        return self.module.apply(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.module, name)

    # -- the hot path ------------------------------------------------------
    def allreduce_grads(self, grads: Any,
                        axis_index_groups: Optional[List[List[int]]] = None,
                        numerics_out: Optional[list] = None) -> Any:
        if self.zero_stage is not None:
            raise ValueError(
                "zero_stage=2 shards the update — a full-gradient "
                "allreduce would gather bytes the shard update never "
                "reads; use staged_zero2_allreduce_grads (or "
                "amp.AmpOptimizer's zero_axis step)")
        if not self.comm_enabled:
            self.last_comm_stats = []
            if self.gradient_average and not self.adasum:
                # static axis size, NOT _axis_size (a psum): the twin
                # must trace to a collective-free graph or the
                # decomposition measures comm it claims to elide
                world = int(lax.axis_size(self.axis_name))
                grads = jax.tree_util.tree_map(
                    lambda g: g / jnp.asarray(world, g.dtype)
                    if jnp.issubdtype(g.dtype, jnp.floating) else g,
                    grads)
            return grads
        if self.adasum:
            from .adasum import adasum_grads, adasum_comm_plan
            if axis_index_groups is not None:
                raise NotImplementedError(
                    "adasum over axis_index_groups is not wired")
            topo, _ = _resolve_topology(self.comm_topology, False)
            world = int(lax.axis_size(self.axis_name))
            ici = 1
            if topo == "hierarchical":
                ici = (int(self.ici_size) if self.ici_size is not None
                       else _topology.default_ici_size(world))
            # TRUE exchanged bytes from the static plan (the cost side
            # of the VERDICT "justify Adasum" experiment): log2(slices)
            # full-buffer fp32 ppermute stages + the in-slice pmean —
            # per-leaf accounting under-reported this by the stage
            # count before PR 9
            (plan_b,) = adasum_comm_plan(grads, world=world,
                                         ici_size=ici)
            self.last_comm_stats = [{**plan_b, "topology": topo}]
            self._record_comm_stats()
            return adasum_grads(grads, self.axis_name, ici_size=ici)
        retain = [] if self.retain_allreduce_buffers else None
        triggers = (set(self.allreduce_trigger_params)
                    if self.allreduce_trigger_params else None)
        comm_stats: list = []
        out = allreduce_grads_tree(
            grads, axis_name=self.axis_name, message_size=self.message_size,
            allreduce_always_fp32=self.allreduce_always_fp32,
            gradient_average=self.gradient_average,
            gradient_predivide_factor=self.gradient_predivide_factor,
            delay_allreduce=self.delay_allreduce,
            axis_index_groups=axis_index_groups,
            retain_buffers=retain, trigger_paths=triggers,
            comm_stats=comm_stats,
            comm_topology=self.comm_topology,
            allreduce_compress_bf16=self.allreduce_compress_bf16,
            ici_size=self.ici_size,
            numerics_out=numerics_out)
        if retain is not None:
            self.allreduce_buffers = retain
        self.last_comm_stats = comm_stats
        self._record_comm_stats()
        return out

    def staged_allreduce_grads(self, stage_fns: Sequence[Callable],
                               loss_head: Callable,
                               stage_params: Sequence[Any], x: Any,
                               numerics_out: Optional[list] = None
                               ) -> Tuple[jax.Array, List[Any]]:
        """The overlapped train-step hot path: forward + backward over
        a sequential stage decomposition with each stage's gradient
        bucket reduced on arrival (``self.overlap=True``) or after the
        full backward (``False`` — the pinned baseline schedule).  See
        :func:`staged_grads`; the per-stage reduction is
        :func:`allreduce_grads_tree` under this wrapper's knobs, so
        topology / compression / predivide / fp32-comm all behave
        exactly as in :meth:`allreduce_grads` — the schedule moves
        WHEN buckets are issued, never what they carry.

        The axis-size scalar is psum'd ONCE and shared across stages
        (``world_scalar=``), keeping the census at one scalar psum +
        whatever the plan budgets per bucket.  ``comm_stats`` /
        ``numerics_out`` records arrive stamped with
        ``stage``/``issue_order`` in exactly
        :func:`overlap_comm_schedule` bucket order (the plan-order
        contract PR 9's per-bucket scalars ride on), and
        ``self.last_overlap_schedule`` keeps the traced schedule.

        ``comm_enabled=False`` builds the compute twin: the SAME staged
        backward with every collective elided and the local 1/world
        average kept (static axis size), for step-time attribution."""
        if self.adasum:
            raise ValueError("staged_allreduce_grads does not compose "
                             "with adasum (the butterfly replaces the "
                             "bucket pipeline)")
        if self.zero_stage is not None:
            raise ValueError(
                "zero_stage=2 replaces the per-stage gather-back of "
                "grads with a gather of updated params; use "
                "staged_zero2_allreduce_grads")
        if self.delay_allreduce or self.allreduce_trigger_params:
            raise ValueError(
                "staged_allreduce_grads: stage boundaries define the "
                "buckets; delay_allreduce / allreduce_trigger_params "
                "contradict the staged schedule")
        if not self.comm_enabled:
            self.last_comm_stats = []
            self.last_overlap_schedule = None
            loss, grads = staged_grads(stage_fns, loss_head,
                                       stage_params, x,
                                       reduce_stage=None,
                                       overlap=self.overlap)
            if self.gradient_average:
                # static axis size, like allreduce_grads: the twin
                # must trace collective-free
                world = int(lax.axis_size(self.axis_name))
                grads = [jax.tree_util.tree_map(
                    lambda g: g / jnp.asarray(world, g.dtype)
                    if jnp.issubdtype(g.dtype, jnp.floating) else g,
                    gs) for gs in grads]
            return loss, grads
        world_static = int(lax.axis_size(self.axis_name))
        world_scalar = _axis_size(self.axis_name)
        retain = [] if self.retain_allreduce_buffers else None
        comm_stats: list = []
        issue_state = {"comm": 0, "num": 0}

        def reduce_stage(stage, issue, grads_s):
            cs: list = []
            nout: Optional[list] = \
                [] if numerics_out is not None else None
            out = allreduce_grads_tree(
                grads_s, axis_name=self.axis_name,
                message_size=self.message_size,
                allreduce_always_fp32=self.allreduce_always_fp32,
                gradient_average=self.gradient_average,
                gradient_predivide_factor=self.gradient_predivide_factor,
                retain_buffers=retain,
                comm_stats=cs,
                comm_topology=self.comm_topology,
                allreduce_compress_bf16=self.allreduce_compress_bf16,
                ici_size=self.ici_size,
                numerics_out=nout,
                world_scalar=world_scalar)
            issue_state["comm"] = _stamp_stage_labels(
                cs, stage, issue_state["comm"])
            comm_stats.extend(cs)
            if nout is not None:
                issue_state["num"] = _stamp_stage_labels(
                    nout, stage, issue_state["num"])
                numerics_out.extend(nout)
            return out

        loss, grads = staged_grads(stage_fns, loss_head, stage_params,
                                   x, reduce_stage=reduce_stage,
                                   overlap=self.overlap)
        if retain is not None:
            self.allreduce_buffers = retain
        self.last_comm_stats = comm_stats
        self.last_overlap_schedule = {
            "overlap_mode": ("overlapped" if self.overlap
                             else "reduce_after_backward"),
            "n_stages": len(stage_fns),
            "issue_order": _topology.overlap_issue_order(len(stage_fns)),
            "buckets": comm_stats,
            "world": world_static}
        self._record_comm_stats()
        return loss, grads

    def staged_zero2_allreduce_grads(
            self, stage_fns: Sequence[Callable], loss_head: Callable,
            stage_params: Sequence[Any], x: Any,
            update_shard: Callable) -> Tuple[jax.Array, List[Any]]:
        """The fused ZeRO-2 overlapped step (requires
        ``zero_stage=2``): the staged backward of
        :meth:`staged_allreduce_grads`, but each stage's arrival-order
        reduction is the *sharded weight update* instead of a plain
        allreduce —

        1. the stage's flat gradient bucket is scatter-reduced to its
           1/ici shard (``psum_scatter`` within the ICI slice + the
           DCN reduce, :func:`_hier_scatter_reduce` — the same eqns,
           payloads and fabric levels as the hierarchical allreduce's
           first two hops);
        2. ``update_shard(stage, param_shard, grad_shard)`` applies
           the optimizer to the local 1/ici window of the stage's
           params — shard-sized math, one fused kernel launch when the
           caller dispatches to the Pallas optimizer kernels;
        3. the in-slice ``all_gather`` carries the UPDATED param shard
           back (same payload the plain schedule spends gathering
           reduced grads — ZeRO-2 costs nothing extra on the wire).

        All three are issued the moment the stage's grads exist
        (``overlap=True``), so by the time the backward reaches stage
        0, the later stages' params for the next step are already in
        flight — update/backward overlap on top of comm/backward
        overlap.  With ``overlap=False`` the same chain runs after the
        full backward (the pinned baseline).

        Returns ``(loss, new_stage_params)`` — NOT grads: the update
        already happened.  The traced schedule lands in
        ``last_overlap_schedule`` tagged ``zero_stage=2``; bucket wire
        accounting is byte-identical to
        ``overlap_comm_schedule(..., zero_stage=2)``."""
        if self.zero_stage != 2:
            raise ValueError(
                "staged_zero2_allreduce_grads requires "
                "DistributedDataParallel(zero_stage=2, "
                "comm_topology='hierarchical')")
        if not self.comm_enabled:
            raise ValueError(
                "the ZeRO-2 compute twin is not wired: eliding the "
                "scatter-reduce would update each shard with local "
                "grads and the gathered params would diverge")
        world_static = int(lax.axis_size(self.axis_name))
        ici = (int(self.ici_size) if self.ici_size is not None
               else _topology.default_ici_size(world_static))
        ici_groups, dcn_groups = _topology.hierarchical_axis_groups(
            world_static, ici)
        compress = self.allreduce_compress_bf16
        world_scalar = _axis_size(self.axis_name)
        comm_stats: list = []
        issue_state = {"comm": 0}

        def reduce_stage(stage, issue, grads_s):
            leaves, treedef = jax.tree_util.tree_flatten(grads_s)
            dts = {jnp.dtype(l.dtype) for l in leaves}
            if len(dts) != 1:
                raise ValueError(
                    f"stage {stage} mixes gradient dtypes {dts}: the "
                    f"fused shard update runs on ONE flat buffer per "
                    f"stage — cast the stage params to a single dtype")
            (dt,) = dts
            flat = (leaves[0].reshape(-1) if len(leaves) == 1 else
                    jnp.concatenate([l.reshape(-1) for l in leaves]))
            comm = (flat.astype(jnp.float32)
                    if self.allreduce_always_fp32 else flat)
            pre, post = predivide_factors(
                world_scalar, self.gradient_predivide_factor)
            if pre != 1.0:
                comm = comm / jnp.asarray(pre, comm.dtype)
            n = comm.shape[0]
            g_shard, _ = _hier_scatter_reduce(
                comm, self.axis_name, ici_groups, dcn_groups, compress)
            if self.gradient_average:
                g_shard = g_shard / post.astype(g_shard.dtype)
            g_shard = g_shard.astype(dt)
            m = g_shard.shape[0]
            # the local window of the CURRENT params at the shard's
            # offset — a static-offset slice, no communication
            p_leaves = jax.tree_util.tree_leaves(stage_params[stage])
            flat_par = (p_leaves[0].reshape(-1) if len(p_leaves) == 1
                        else jnp.concatenate(
                            [l.reshape(-1) for l in p_leaves]))
            flat_par = jnp.pad(flat_par, (0, m * ici - n))
            idx = lax.axis_index(self.axis_name) % ici
            p_shard = lax.dynamic_slice_in_dim(flat_par, idx * m, m)
            new_shard = update_shard(stage, p_shard, g_shard)
            full = _hier_gather(new_shard, self.axis_name, ici_groups,
                                n)
            out, off = [], 0
            for l in leaves:
                sz = int(l.size)
                out.append(full[off:off + sz].reshape(l.shape))
                off += sz
            acct = _bucket_wire_accounting(
                n, comm.dtype, "hierarchical", ici, compress,
                self.message_size, False, False)
            rec = {"dtype": str(dt), "comm_dtype": str(comm.dtype),
                   "leaves": len(leaves), "elements": int(n),
                   **{k: v for k, v in acct.items()
                      if k not in ("eqns", "eqn_payload_bytes")}}
            issue_state["comm"] = _stamp_stage_labels(
                [rec], stage, issue_state["comm"])
            comm_stats.append(rec)
            return jax.tree_util.tree_unflatten(treedef, out)

        loss, new_params = staged_grads(stage_fns, loss_head,
                                        stage_params, x,
                                        reduce_stage=reduce_stage,
                                        overlap=self.overlap)
        self.last_comm_stats = comm_stats
        self.last_overlap_schedule = {
            "overlap_mode": ("overlapped" if self.overlap
                             else "reduce_after_backward"),
            "n_stages": len(stage_fns),
            "issue_order": _topology.overlap_issue_order(len(stage_fns)),
            "zero_stage": 2,
            "buckets": comm_stats,
            "world": world_static}
        self._record_comm_stats()
        return loss, new_params

    def _record_comm_stats(self):
        """Fold the per-bucket accounting into the process observability
        registry: per-(dtype, cause) bucket counts and per-dtype bytes.
        Runs at TRACE time — totals count compiled traces, not executed
        steps (per-step totals = these x steps on that executable); the
        adaptive-summation / cross-replica sharding comm work in
        PAPERS.md plans against exactly this per-bucket record."""
        from ..observability import get_registry
        reg = get_registry()
        buckets = reg.counter(
            "ddp_allreduce_buckets_total",
            help="gradient allreduce buckets per compiled trace")
        bts = reg.counter(
            "ddp_allreduce_bytes_total",
            help="one replica's communicated gradient bytes per trace")
        lvl = reg.counter(
            "ddp_allreduce_level_bytes_total",
            help="one replica's gradient bytes per fabric level (ici = "
                 "fast in-slice interconnect, dcn = cross-host) per "
                 "trace; flat psums count fully on both levels")
        for b in self.last_comm_stats:
            buckets.labels(dtype=b["comm_dtype"], cause=b["cause"]).inc()
            bts.labels(dtype=b["comm_dtype"]).inc(b["bytes"])
            lvl.labels(level="ici", dtype=b["comm_dtype"]).inc(
                b.get("ici_wire_bytes", b["bytes"]))
            lvl.labels(level="dcn", dtype=b["comm_dtype"]).inc(
                b.get("dcn_wire_bytes", b["bytes"]))

    def supervisor_signals(self) -> Dict[str, Any]:
        """The wrapper's host-side signal bundle for a training-run
        supervisor (``observability.supervisor.RunSupervisor``): the
        trace-time comm accounting and the last flushed numerics
        summary.  Everything here is plain python the wrapper already
        holds — feeding it to ``observe_step(comm_stats=...,
        numerics=...)`` costs no device traffic, which is the whole
        supervisor contract."""
        return {"comm_stats": list(self.last_comm_stats),
                "numerics": dict(self.last_numerics)}

    def record_numerics(self, flushed: Dict[str, Any]) -> Dict[str, Any]:
        """Fold a flushed ``NumericsMonitor`` summary into the wrapper's
        observability surface: ``ddp.last_numerics`` (the
        ``Engine.stats()``-style host view) plus the per-bucket
        compression-error gauges in the process registry — what the
        PR 5 bf16 DCN hop actually loses on the wire, next to the
        byte counters that say what it saves."""
        self.last_numerics = dict(flushed)
        from ..observability import get_registry
        reg = get_registry()
        for b in flushed.get("buckets", ()):
            reg.gauge(
                "ddp_allreduce_compression_sq_error",
                help="squared bf16 quantization error of one replica's "
                     "DCN-hop shard, accumulated over observed steps"
            ).labels(bucket=b["label"]).set(
                b.get("compression_sq_error", 0.0))
            reg.counter(
                "ddp_allreduce_bucket_nonfinite_total",
                help="nonfinite gradient elements seen per comm bucket"
            ).labels(bucket=b["label"]).set_total(b["nonfinite"])
        return self.last_numerics

    def broadcast_params(self, params: Any) -> Any:
        """Rank-0 parameter broadcast (reference DDP does this at
        construction, distributed.py:234).  Under shard_map replicated
        in_specs make it implicit; call this explicitly when ranks may
        have diverged (e.g. after independent init under multi-process)."""
        return flat_dist_call(params, self.axis_name, "broadcast")

    # -- whole-step builder for the common 1-D data-parallel mesh ---------
    def make_step(self, step_fn: Callable, mesh: Optional[Mesh] = None,
                  donate_state: bool = True,
                  steps_per_call: int = 1,
                  state_specs: Any = None) -> Callable:
        """shard_map ``step_fn(state..., batch) -> (state..., aux)`` over a
        1-D mesh: replicated state, batch sharded on axis 0.  ``step_fn``
        runs per-device and should call ``self.allreduce_grads`` on its
        gradient tree (param broadcast from rank 0 is implicit: replicated
        inputs to shard_map stay replicated, the analogue of the init-time
        broadcast at distributed.py:234).

        ``state_specs``: PartitionSpec pytree for the state when parts of
        it are NOT replicated — e.g. a ZeRO-sharded optimizer state
        (``(P(), P(), amp.zero_optimizer_specs(...))``) or TP-sharded
        params (``tensor_parallel.partition_specs``).  Defaults to fully
        replicated (``P()``), the plain-DDP contract.

        ``steps_per_call > 1`` wraps ``step_fn`` in a ``lax.scan`` over a
        leading micro-batch axis (batch shaped ``(K, per_step...)``) so
        one dispatch runs K optimizer steps — amortizes host→device
        dispatch latency, which on tunneled TPU runtimes is ~ms-scale.
        The aux output then carries the K per-step values."""
        if mesh is None:
            mesh = Mesh(jax.devices(), (self.axis_name,))
        an = self.axis_name
        K = int(steps_per_call)
        if K < 1:
            raise ValueError(f"steps_per_call must be >= 1, got {K}")
        if state_specs is None:
            state_specs = P()

        if K == 1:
            wrapped = step_fn
        else:
            def wrapped(state, batch):
                lead = {l.shape[0] for l in jax.tree_util.tree_leaves(batch)}
                if lead != {K}:
                    raise ValueError(
                        f"steps_per_call={K} needs every batch leaf shaped "
                        f"(K, per_step...); got leading dims {sorted(lead)}")
                return lax.scan(step_fn, state, batch)

        # batch sharded on the data axis: micro-batch axis (if any) first
        bspec = P(an) if K == 1 else P(None, an)
        mapped = jax.shard_map(
            wrapped, mesh=mesh,
            in_specs=(state_specs, bspec),
            out_specs=(state_specs, P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(0,) if donate_state else ())


class Reducer:
    """Manual allreduce helper, parity with apex.parallel.Reducer
    (distributed.py:89-126): call ``reduce(tree)`` inside a mapped context
    to sum (and average) a pytree across the axis, and
    ``broadcast_params(tree)`` for the construction-time rank-0 parameter
    broadcast the reference performs (distributed.py:100-104) — in the
    functional world construction has no params in hand, so the broadcast
    is an explicit call at the top of the first step (or skipped when
    params are replicated by shard_map, which is the common case)."""

    def __init__(self, module_or_tree=None, axis_name: str = "data",
                 gradient_average: bool = True):
        self.module = module_or_tree
        self.axis_name = axis_name
        self.gradient_average = gradient_average

    def reduce(self, tree: Any) -> Any:
        red = flat_dist_call(tree, self.axis_name, "psum")
        if self.gradient_average:
            world = _axis_size(self.axis_name)
            red = jax.tree_util.tree_map(
                lambda x: x / world.astype(x.dtype), red)
        return red

    def broadcast_params(self, tree: Any) -> Any:
        """Every rank gets rank 0's values (reference init broadcast,
        distributed.py:100-104 / DDP :234)."""
        return flat_dist_call(tree, self.axis_name, "broadcast")
