"""Pipeline parallelism: GPipe-style microbatched stage pipelining over a
mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.3 — its
inventory is data-parallel only); this is the TPU-native completion of
the parallelism surface: homogeneous stages laid out along a ``pp`` mesh
axis, microbatches streamed through with ``lax.ppermute`` neighbor
exchanges (ICI hops), the whole schedule expressed as one ``lax.scan``
XLA can pipeline — no host-side scheduler process the way GPipe/
PipeDream builds one, because under SPMD every device runs the same
compiled loop.

Scheme (the classic M-microbatch, S-stage wavefront):

- stage parameters are STACKED on a leading axis: ``init_stacked`` gives
  a (S, ...) tree, sharded ``P("pp")`` so each device holds one stage's
  slice (squeezed inside the loop body);
- the scan runs ``M + S - 1`` ticks; at tick t, stage 0 feeds
  microbatch t (zeros once the real ones run out), every stage applies
  its block to its current input and ``ppermute``-shifts the result to
  stage s+1;
- the last stage scatters each finished microbatch into an output
  buffer; a masked psum with identity-backward
  (``reduce_from_model_parallel``) replicates the buffer without the
  axis-size gradient inflation a plain psum transpose would cause.

Autodiff: ppermute transposes to the inverse permutation, scan to a
reverse-time scan — so backward is automatically the reverse wavefront
(activations rematerialized per jax defaults; wrap ``block`` in
``jax.checkpoint`` for GPipe's activation-recompute memory profile).

Composes with data parallelism on a second mesh axis (shard the
microbatch batch dim over ``data``) and with tensor parallelism inside
the block (``tensor_parallel`` layers over a third axis).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.module import Module
from .sync_batchnorm import _axis_in_scope
from .tensor_parallel import (copy_to_model_parallel,
                              reduce_from_model_parallel)

__all__ = ["init_stacked", "stacked_specs", "pipeline_apply",
           "pipeline_1f1b_grads", "bubble_fraction"]

DEFAULT_AXIS = "pp"


def init_stacked(block: Module, key: jax.Array, n_stages: int):
    """(S, ...) stacked params for ``n_stages`` copies of ``block``
    (independent init per stage, like S separately-initialized layers)."""
    keys = jax.random.split(key, n_stages)
    trees = [block.init(k)[0] for k in keys]
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *trees)


def stacked_specs(stacked_params: Any,
                  axis_name: str = DEFAULT_AXIS) -> Any:
    """PartitionSpec tree sharding the stage axis: ``P(axis_name)`` on
    every leaf's leading dim."""
    return jax.tree_util.tree_map(
        lambda l: P(axis_name, *([None] * (l.ndim - 1))), stacked_params)


def pipeline_apply(block: Module, stacked_params: Any, x: jax.Array,
                   axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """Run microbatches ``x: (M, B, ...)`` through the S-stage pipeline.

    Call inside ``shard_map`` with ``stacked_params`` sharded by
    :func:`stacked_specs` (each device sees a (1, ...) slice) and ``x``
    replicated along ``axis_name``.  Returns the (M, B, ...) outputs,
    replicated.  Outside any mesh, applies the S stages sequentially —
    the single-device degradation.
    """
    if not _axis_in_scope(axis_name):
        # single-device degradation: apply the S stages sequentially,
        # vmapped over the microbatch axis
        out = x
        S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        for s in range(S):
            p = jax.tree_util.tree_map(lambda l: l[s], stacked_params)
            out = jax.vmap(lambda mb, p=p: block(p, mb))(out)
        return out

    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # f-collective on the input: x's cotangent accumulates only on the
    # stage-0 device (the injection path); the psum-backward makes the
    # replicated-input gradient actually replicated, so upstream layers
    # (embeddings etc.) train identically on every pp rank
    x = copy_to_model_parallel(x, axis_name)
    M = x.shape[0]
    local_p = jax.tree_util.tree_map(lambda l: l[0], stacked_params)
    zero_in = jnp.zeros_like(x[0])
    out_buf = jnp.zeros((M,) + x.shape[1:], x.dtype)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        recv, out_buf = carry
        # stage 0 injects microbatch t (zeros during the drain phase);
        # other stages consume what the previous tick delivered
        mb = lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), 0,
                                      keepdims=False)
        inp = jnp.where(idx == 0,
                        jnp.where(t < M, mb, zero_in), recv)
        y = block(local_p, inp)
        # the last stage finished microbatch t - (S - 1) this tick
        done_t = t - (S - 1)
        is_last = idx == S - 1
        valid = jnp.logical_and(done_t >= 0, is_last)
        out_buf = lax.cond(
            valid,
            lambda b: lax.dynamic_update_index_in_dim(
                b, y, jnp.maximum(done_t, 0), 0),
            lambda b: b, out_buf)
        nxt = lax.ppermute(y, axis_name, perm)
        return (nxt, out_buf), None

    (_, out_buf), _ = lax.scan(tick, (zero_in, out_buf),
                               jnp.arange(M + S - 1))
    # replicate the last stage's buffer; identity-backward psum so the
    # replicated downstream loss doesn't inflate gradients S-fold
    mask = (idx == S - 1).astype(out_buf.dtype)
    return reduce_from_model_parallel(out_buf * mask, axis_name)


def bubble_fraction(n_stages: int, n_micro: int,
                    schedule: str = "1f1b") -> float:
    """Idle fraction of the pipeline schedule under the lockstep SPMD
    cost model (every tick, every device executes the same compiled
    graph; a stage with no scheduled work that tick burns the tick).

    - ``"gpipe"`` (:func:`pipeline_apply` + autodiff): forward scan of
      ``M + S - 1`` F-ticks then a transposed backward scan of
      ``M + S - 1`` B-ticks; each phase wastes ``S - 1`` wavefront
      ticks -> bubble ``(S - 1) / (M + S - 1)``.
    - ``"1f1b"`` (:func:`pipeline_1f1b_grads`): ONE scan of
      ``M + 2(S - 1)`` combined ticks (each executes the F-unit and the
      B-unit); the warmup/drain wavefronts waste ``2(S - 1)`` ticks ->
      bubble ``2(S - 1) / (M + 2(S - 1))``.

    For the same M the fractions are equal — lockstep SPMD cannot buy
    wall-clock with schedule order the way a MIMD host scheduler can
    (there is no per-device program to reorder).  What 1F1B buys here is
    PEAK MEMORY: its activation stash is bounded by ``min(M, 2S - 1)``
    microbatches regardless of M, while GPipe's transposed scan stashes
    all ``M`` (see ``pipeline_1f1b_grads``).  Driving the bubble itself
    down means raising M — which GPipe pays for in activation memory
    and 1F1B does not.
    """
    S, M = n_stages, n_micro
    if schedule == "gpipe":
        return (S - 1) / (M + S - 1)
    if schedule == "1f1b":
        return 2 * (S - 1) / (M + 2 * (S - 1))
    raise ValueError(f"unknown schedule {schedule!r}")


def pipeline_1f1b_grads(block: Module, loss_fn, stacked_params: Any,
                        x: jax.Array, targets: jax.Array,
                        axis_name: str = DEFAULT_AXIS):
    """One fused forward+backward pipeline pass under a 1F1B schedule:
    returns ``(loss, stacked_grads)`` with the activation stash bounded
    by ``min(M, 2S - 1)`` microbatches instead of GPipe's ``M``.

    ``x``/``targets`` are ``(M, B, ...)`` microbatches (replicated over
    ``axis_name``); ``loss_fn(y, target) -> scalar`` scores one
    microbatch of last-stage outputs; ``loss`` is the mean over the M
    microbatches, replicated; ``stacked_grads`` mirrors
    ``stacked_params`` (grads of the SUM-scaled-to-mean loss, each
    device computing exactly its stage's slice — shard with
    :func:`stacked_specs` in/out).

    Why not ``jax.grad(pipeline_apply)``?  Autodiff of the GPipe scan
    stashes every tick's residuals — O(M) microbatch activations per
    stage — and runs a second, transposed scan.  Megatron's 1F1B
    (PipeDream-flush) bounds in-flight microbatches at O(S) by starting
    backwards as soon as the first microbatch clears the last stage.
    This is that schedule, expressed the SPMD way: ONE ``lax.scan`` of
    ``M + 2(S - 1)`` ticks where every tick runs an F-unit (forward of
    one microbatch) and a B-unit (VJP of an earlier microbatch):

    - F(s, m) fires at tick ``s + m``; activations hop to s+1 via
      ``ppermute`` (forward ICI ring);
    - B(s, m) fires at tick ``2(S-1) - s + m``; cotangents hop to s-1
      via the reverse ring; the last stage seeds them from
      ``loss_fn``'s gradient the same tick its forward finishes;
    - between its F and its B, a microbatch's VJP residuals wait in a
      rotating ``min(M, 2S-1)``-slot stash — residuals are extracted as
      arrays with ``jax.closure_convert`` (the closure itself cannot
      cross a scan boundary), and the tick-invariant parameter
      residuals are identified by tracer identity and passed live
      rather than stashed K times;
    - per-stage grads accumulate in fp32 across microbatches and cast
      back to the param dtype at the end.

    See :func:`bubble_fraction` for the honest cost model: same bubble
    as GPipe under lockstep SPMD, O(S) not O(M) activation memory —
    i.e. the same reason Megatron prefers it (memory, not bubble; its
    bubble win needs the interleaved variant + a MIMD scheduler).

    Like :func:`pipeline_apply`, the block must be shape-homogeneous
    (output shape == input shape).  Call inside ``shard_map``; outside
    any mesh it degrades to the sequential forward + plain autodiff.
    The reference toolkit has no pipeline story (SURVEY.md §2.3); the
    schedule itself follows Narayanan et al.'s PipeDream-flush as used
    by Megatron-LM.
    """
    if not _axis_in_scope(axis_name):
        S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        M = x.shape[0]

        def seq_loss(p):
            out = x
            for s in range(S):
                ps = jax.tree_util.tree_map(lambda l: l[s], p)
                out = jax.vmap(lambda mb, ps=ps: block(ps, mb))(out)
            per_mb = jax.vmap(loss_fn)(out, targets)
            return jnp.mean(per_mb)

        loss, grads = jax.value_and_grad(seq_loss)(stacked_params)
        return loss, grads

    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    M = x.shape[0]
    T = M + 2 * (S - 1)
    K = min(M, 2 * S - 1)
    local_p = jax.tree_util.tree_map(lambda l: l[0], stacked_params)

    def block_fn(p, xin):
        return block(p, xin)

    # an amp-cast block may compute in a narrower dtype than the fed
    # x (O2 casts inputs to bf16 at its top); the scan carries must use
    # the block's OUTPUT dtype or the y/dy rings won't typecheck
    y_shape = jax.eval_shape(block_fn, local_p, x[0])
    x = x.astype(y_shape.dtype)

    # one abstract vjp to fix the residual structure; the value-level
    # computation below is dead code XLA removes — only `conv` (a
    # closed jaxpr) and the residual shapes/param-identity split are
    # used.  Param residuals are recognized by tracer identity (stable
    # across traces of the same function, pinned in tests).
    y0, vjp0 = jax.vjp(block_fn, local_p, x[0])
    conv, res0 = jax.closure_convert(vjp0, y0)
    p_ids = {id(l) for l in jax.tree_util.tree_leaves(local_p)}
    stash_i = [i for i, r in enumerate(res0) if id(r) not in p_ids]
    stash0 = [jnp.zeros((K,) + res0[i].shape, res0[i].dtype)
              for i in stash_i]

    # static schedule tables: microbatch handled by (tick, stage), -1
    # = idle.  Computed in numpy at trace time — S, M are static.
    t_idx = np.arange(T)[:, None]
    s_idx = np.arange(S)[None, :]
    fwd = t_idx - s_idx
    fwd_tab = jnp.asarray(np.where((fwd >= 0) & (fwd < M), fwd, -1),
                          jnp.int32)
    bwd = t_idx - (2 * (S - 1) - s_idx)
    bwd_tab = jnp.asarray(np.where((bwd >= 0) & (bwd < M), bwd, -1),
                          jnp.int32)

    perm_f = [(i, (i + 1) % S) for i in range(S)]
    perm_b = [(i, (i - 1) % S) for i in range(S)]
    g0 = jax.tree_util.tree_map(
        lambda l: jnp.zeros(l.shape, jnp.float32), local_p)

    def tick(carry, t):
        recv_y, recv_dy, stash, gacc, lacc = carry
        mb_f = jnp.take(lax.dynamic_index_in_dim(fwd_tab, t, 0, False),
                        idx)
        mb_b = jnp.take(lax.dynamic_index_in_dim(bwd_tab, t, 0, False),
                        idx)

        # --- F-unit: forward one microbatch, stash its residuals -----
        x_inj = lax.dynamic_index_in_dim(x, jnp.clip(mb_f, 0, M - 1),
                                         0, False)
        xin = jnp.where(idx == 0, x_inj, recv_y)
        y, vjp = jax.vjp(block_fn, local_p, xin)
        _, res = jax.closure_convert(vjp, y)
        # residual-drift canary: the stash indices and the param/
        # activation split are computed from the OUTER trace (res0) and
        # applied positionally here — a jax upgrade that reorders
        # closure_convert's extraction would silently corrupt grads, so
        # compare the full (shape, dtype) signature, not just the count
        sig = [(tuple(r.shape), r.dtype) for r in res]
        sig0 = [(tuple(r.shape), r.dtype) for r in res0]
        if sig != sig0:
            raise RuntimeError(
                "closure_convert residual structure changed between "
                f"traces ({sig} vs {sig0})")
        # idle F-ticks scatter out-of-bounds -> dropped, so a drain
        # tick can't clobber a slot still awaiting its backward
        slot_w = jnp.where(mb_f >= 0, jnp.clip(mb_f, 0, M - 1) % K, K)
        stash = [s.at[slot_w].set(res[i], mode="drop")
                 for s, i in zip(stash, stash_i)]

        # --- B-unit: VJP of an earlier microbatch from the stash -----
        tgt = lax.dynamic_index_in_dim(targets,
                                       jnp.clip(mb_b, 0, M - 1), 0,
                                       False)
        # last stage: this tick's forward IS microbatch mb_b (the
        # schedule aligns them), so its loss gradient seeds the chain
        lval, dy_loss = jax.value_and_grad(
            lambda yy: loss_fn(yy, tgt))(y)
        is_last = idx == S - 1
        dy = jnp.where(is_last, dy_loss / M, recv_dy)
        slot_r = jnp.clip(mb_b, 0, M - 1) % K
        res_b = list(res)               # param residuals ride live
        for s, i in zip(stash, stash_i):
            res_b[i] = lax.dynamic_index_in_dim(s, slot_r, 0, False)
        # the last stage's residuals for mb_b were stashed THIS tick
        # (read-after-write above), so the gather sees them
        dp, dxin = conv(dy, *res_b)
        b_valid = mb_b >= 0
        gacc = jax.tree_util.tree_map(
            lambda g, d: g + jnp.where(b_valid, d, 0).astype(g.dtype),
            gacc, dp)
        lacc = lacc + jnp.where(jnp.logical_and(b_valid, is_last),
                                lval, 0.0) / M

        # --- neighbor exchanges (both rings ride ICI) ----------------
        y_nxt = lax.ppermute(y, axis_name, perm_f)
        dy_nxt = lax.ppermute(dxin, axis_name, perm_b)
        return (y_nxt, dy_nxt, stash, gacc, lacc), None

    zero_y = jnp.zeros_like(x[0])
    carry0 = (zero_y, zero_y, stash0, g0, jnp.float32(0.0))
    (_, _, _, gacc, lacc), _ = lax.scan(tick, carry0, jnp.arange(T))

    loss = lax.psum(jnp.where(idx == S - 1, lacc, 0.0), axis_name)
    grads = jax.tree_util.tree_map(
        lambda g, l: g.astype(l.dtype)[None], gacc, local_p)
    return loss, grads
