"""Pipeline parallelism: GPipe-style microbatched stage pipelining over a
mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.3 — its
inventory is data-parallel only); this is the TPU-native completion of
the parallelism surface: homogeneous stages laid out along a ``pp`` mesh
axis, microbatches streamed through with ``lax.ppermute`` neighbor
exchanges (ICI hops), the whole schedule expressed as one ``lax.scan``
XLA can pipeline — no host-side scheduler process the way GPipe/
PipeDream builds one, because under SPMD every device runs the same
compiled loop.

Scheme (the classic M-microbatch, S-stage wavefront):

- stage parameters are STACKED on a leading axis: ``init_stacked`` gives
  a (S, ...) tree, sharded ``P("pp")`` so each device holds one stage's
  slice (squeezed inside the loop body);
- the scan runs ``M + S - 1`` ticks; at tick t, stage 0 feeds
  microbatch t (zeros once the real ones run out), every stage applies
  its block to its current input and ``ppermute``-shifts the result to
  stage s+1;
- the last stage scatters each finished microbatch into an output
  buffer; a masked psum with identity-backward
  (``reduce_from_model_parallel``) replicates the buffer without the
  axis-size gradient inflation a plain psum transpose would cause.

Autodiff: ppermute transposes to the inverse permutation, scan to a
reverse-time scan — so backward is automatically the reverse wavefront
(activations rematerialized per jax defaults; wrap ``block`` in
``jax.checkpoint`` for GPipe's activation-recompute memory profile).

Composes with data parallelism on a second mesh axis (shard the
microbatch batch dim over ``data``) and with tensor parallelism inside
the block (``tensor_parallel`` layers over a third axis).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.module import Module
from .sync_batchnorm import _axis_in_scope
from .tensor_parallel import (copy_to_model_parallel,
                              reduce_from_model_parallel)

__all__ = ["init_stacked", "stacked_specs", "pipeline_apply"]

DEFAULT_AXIS = "pp"


def init_stacked(block: Module, key: jax.Array, n_stages: int):
    """(S, ...) stacked params for ``n_stages`` copies of ``block``
    (independent init per stage, like S separately-initialized layers)."""
    keys = jax.random.split(key, n_stages)
    trees = [block.init(k)[0] for k in keys]
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *trees)


def stacked_specs(stacked_params: Any,
                  axis_name: str = DEFAULT_AXIS) -> Any:
    """PartitionSpec tree sharding the stage axis: ``P(axis_name)`` on
    every leaf's leading dim."""
    return jax.tree_util.tree_map(
        lambda l: P(axis_name, *([None] * (l.ndim - 1))), stacked_params)


def pipeline_apply(block: Module, stacked_params: Any, x: jax.Array,
                   axis_name: str = DEFAULT_AXIS) -> jax.Array:
    """Run microbatches ``x: (M, B, ...)`` through the S-stage pipeline.

    Call inside ``shard_map`` with ``stacked_params`` sharded by
    :func:`stacked_specs` (each device sees a (1, ...) slice) and ``x``
    replicated along ``axis_name``.  Returns the (M, B, ...) outputs,
    replicated.  Outside any mesh, applies the S stages sequentially —
    the single-device degradation.
    """
    if not _axis_in_scope(axis_name):
        # single-device degradation: apply the S stages sequentially,
        # vmapped over the microbatch axis
        out = x
        S = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        for s in range(S):
            p = jax.tree_util.tree_map(lambda l: l[s], stacked_params)
            out = jax.vmap(lambda mb, p=p: block(p, mb))(out)
        return out

    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # f-collective on the input: x's cotangent accumulates only on the
    # stage-0 device (the injection path); the psum-backward makes the
    # replicated-input gradient actually replicated, so upstream layers
    # (embeddings etc.) train identically on every pp rank
    x = copy_to_model_parallel(x, axis_name)
    M = x.shape[0]
    local_p = jax.tree_util.tree_map(lambda l: l[0], stacked_params)
    zero_in = jnp.zeros_like(x[0])
    out_buf = jnp.zeros((M,) + x.shape[1:], x.dtype)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        recv, out_buf = carry
        # stage 0 injects microbatch t (zeros during the drain phase);
        # other stages consume what the previous tick delivered
        mb = lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), 0,
                                      keepdims=False)
        inp = jnp.where(idx == 0,
                        jnp.where(t < M, mb, zero_in), recv)
        y = block(local_p, inp)
        # the last stage finished microbatch t - (S - 1) this tick
        done_t = t - (S - 1)
        is_last = idx == S - 1
        valid = jnp.logical_and(done_t >= 0, is_last)
        out_buf = lax.cond(
            valid,
            lambda b: lax.dynamic_update_index_in_dim(
                b, y, jnp.maximum(done_t, 0), 0),
            lambda b: b, out_buf)
        nxt = lax.ppermute(y, axis_name, perm)
        return (nxt, out_buf), None

    (_, out_buf), _ = lax.scan(tick, (zero_in, out_buf),
                               jnp.arange(M + S - 1))
    # replicate the last stage's buffer; identity-backward psum so the
    # replicated downstream loss doesn't inflate gradients S-fold
    mask = (idx == S - 1).astype(out_buf.dtype)
    return reduce_from_model_parallel(out_buf * mask, axis_name)
