"""Mesh construction helpers: ICI/DCN-aware device meshes.

The reference's topology story is one line — NCCL over however many GPUs
the launcher spawned.  On TPU the mesh layout decides which collectives
ride ICI (fast intra-slice interconnect) and which cross DCN (inter-host
network), so apex_tpu gives it a first-class helper:

    mesh = make_mesh(data=-1)                      # pure DP over all chips
    mesh = make_mesh(data=-1, sp=4)                # DP x sequence-parallel
    mesh = make_mesh(data=-1, tp=8)                # DP over hosts, TP in-slice

Axes are listed outermost-first; one axis may be -1 (inferred).  On
multi-host runs the outermost axis is laid out across hosts (its
collectives cross DCN — put data parallelism there, it communicates once
per step) while inner axes stay within a slice on ICI (put tensor/sequence
parallelism there, they communicate per layer).  This is the standard
sharding recipe; ``jax.experimental.mesh_utils`` supplies the
topology-aware device orderings underneath.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_info"]


def make_mesh(devices: Optional[list] = None, **axes: int) -> Mesh:
    """Build a Mesh from ``axis_name=size`` kwargs (outermost first).

    One axis may be -1: it absorbs the remaining devices.  Raises if the
    product does not cover the device count exactly.
    """
    if not axes:
        axes = {"data": -1}
    devs = list(jax.devices()) if devices is None else list(devices)
    n = len(devs)
    names = list(axes.keys())
    sizes = list(axes.values())
    if sum(1 for s in sizes if s == -1) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if fixed <= 0 or n % fixed != 0:
        raise ValueError(
            f"axis sizes {dict(zip(names, sizes))} do not divide "
            f"{n} devices")
    sizes = [n // fixed if s == -1 else s for s in sizes]
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"axis sizes {dict(zip(names, sizes))} != {n} devices")

    try:
        from jax.experimental import mesh_utils
        nproc = jax.process_count()
        if nproc > 1:
            # outermost axis spans hosts (its collectives cross DCN),
            # inner axes stay within a slice (ICI)
            if sizes[0] % nproc != 0:
                raise ValueError(
                    f"outermost axis {names[0]}={sizes[0]} must be "
                    f"divisible by the process count {nproc}")
            per_slice = (sizes[0] // nproc,) + tuple(sizes[1:])
            dcn = (nproc,) + (1,) * (len(sizes) - 1)
            arr = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=devs)
        else:
            arr = mesh_utils.create_device_mesh(tuple(sizes), devices=devs)
    except ValueError:
        raise
    except Exception:
        # host-platform CPUs (tests) have no topology; plain reshape
        arr = np.array(devs).reshape(sizes)
    return Mesh(arr, tuple(names))


def mesh_info(mesh: Mesh) -> str:
    """One-line human description of a mesh, for startup logging."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    plat = mesh.devices.flat[0].platform
    return (f"mesh {shape} over {mesh.devices.size} {plat} device(s), "
            f"{jax.process_count()} process(es)")
