"""Mesh construction helpers: ICI/DCN-aware device meshes.

The reference's topology story is one line — NCCL over however many GPUs
the launcher spawned.  On TPU the mesh layout decides which collectives
ride ICI (fast intra-slice interconnect) and which cross DCN (inter-host
network), so apex_tpu gives it a first-class helper:

    mesh = make_mesh(data=-1)                      # pure DP over all chips
    mesh = make_mesh(data=-1, sp=4)                # DP x sequence-parallel
    mesh = make_mesh(data=-1, tp=8)                # DP over hosts, TP in-slice

Axes are listed outermost-first; one axis may be -1 (inferred).  On
multi-host runs the outermost axis is laid out across hosts (its
collectives cross DCN — put data parallelism there, it communicates once
per step) while inner axes stay within a slice on ICI (put tensor/sequence
parallelism there, they communicate per layer).  This is the standard
sharding recipe; ``jax.experimental.mesh_utils`` supplies the
topology-aware device orderings underneath.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "mesh_info", "hierarchical_axis_groups",
           "default_ici_size", "auto_comm_topology",
           "overlap_issue_order", "collective_rank_groups"]


def make_mesh(devices: Optional[list] = None, **axes: int) -> Mesh:
    """Build a Mesh from ``axis_name=size`` kwargs (outermost first).

    One axis may be -1: it absorbs the remaining devices.  Raises if the
    product does not cover the device count exactly.
    """
    if not axes:
        axes = {"data": -1}
    devs = list(jax.devices()) if devices is None else list(devices)
    n = len(devs)
    names = list(axes.keys())
    sizes = list(axes.values())
    if sum(1 for s in sizes if s == -1) > 1:
        raise ValueError("at most one axis may be -1")
    fixed = int(np.prod([s for s in sizes if s != -1]))
    if fixed <= 0 or n % fixed != 0:
        raise ValueError(
            f"axis sizes {dict(zip(names, sizes))} do not divide "
            f"{n} devices")
    sizes = [n // fixed if s == -1 else s for s in sizes]
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"axis sizes {dict(zip(names, sizes))} != {n} devices")

    try:
        from jax.experimental import mesh_utils
        nproc = jax.process_count()
        if nproc > 1:
            # outermost axis spans hosts (its collectives cross DCN),
            # inner axes stay within a slice (ICI)
            if sizes[0] % nproc != 0:
                raise ValueError(
                    f"outermost axis {names[0]}={sizes[0]} must be "
                    f"divisible by the process count {nproc}")
            per_slice = (sizes[0] // nproc,) + tuple(sizes[1:])
            dcn = (nproc,) + (1,) * (len(sizes) - 1)
            arr = mesh_utils.create_hybrid_device_mesh(
                per_slice, dcn, devices=devs)
        else:
            arr = mesh_utils.create_device_mesh(tuple(sizes), devices=devs)
    except ValueError:
        raise
    except Exception:
        # host-platform CPUs (tests) have no topology; plain reshape
        arr = np.array(devs).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_ici_size(world: int, nproc: Optional[int] = None) -> int:
    """Devices per process along a host-spanning mesh axis — the size of
    the ICI (fast-fabric) level of a two-level reduction.  ``make_mesh``
    lays the outermost axis out with the DCN factor leading, so each
    process's devices are one *contiguous* block of ``world / nproc``
    ranks; that block is the inner level."""
    nproc = jax.process_count() if nproc is None else int(nproc)
    if nproc < 1 or world % nproc != 0:
        raise ValueError(
            f"axis size {world} is not divisible by the process count "
            f"{nproc}; pass ici_size explicitly")
    return world // nproc


def auto_comm_topology(nproc: Optional[int] = None) -> str:
    """The ``comm_topology='auto'`` heuristic: a data axis only crosses
    DCN when it spans more than one process (make_mesh puts the DCN
    factor on the outermost axis), so multi-process runs get the
    hierarchical two-level reduction and single-process runs keep the
    flat psum — there is no slow fabric to economize on."""
    nproc = jax.process_count() if nproc is None else int(nproc)
    return "hierarchical" if nproc > 1 else "flat"


def hierarchical_axis_groups(world: int, ici_size: int
                             ) -> Tuple[List[List[int]], List[List[int]]]:
    """``(ici_groups, dcn_groups)`` for a two-level reduction over an
    axis laid out like ``make_mesh``'s multi-host ordering: consecutive
    blocks of ``ici_size`` ranks share the fast fabric (one slice), and
    ranks at the same offset within their block talk across DCN.

        world=8, ici_size=4 ->  ici: [[0,1,2,3], [4,5,6,7]]
                                dcn: [[0,4], [1,5], [2,6], [3,7]]

    Used as ``axis_index_groups`` for the in-slice psum_scatter /
    all_gather (ici) and the cross-slice reduce on the 1/ici shard
    (dcn)."""
    if ici_size < 1 or world % ici_size != 0:
        raise ValueError(
            f"ici_size {ici_size} must be >= 1 and divide the axis "
            f"size {world}")
    n_slices = world // ici_size
    ici_groups = [list(range(s * ici_size, (s + 1) * ici_size))
                  for s in range(n_slices)]
    dcn_groups = [[j + s * ici_size for s in range(n_slices)]
                  for j in range(ici_size)]
    return ici_groups, dcn_groups


def overlap_issue_order(n_stages: int) -> List[int]:
    """Stage issue order for the overlapped gradient-communication
    schedule: reverse-mode AD produces gradients back-to-front, so the
    LAST forward stage's bucket is ready first and its reduction is the
    first one issued — ``[S-1, ..., 1, 0]``.  This is the ONE place the
    ordering lives: ``distributed.staged_grads`` walks stages in this
    order at trace time and ``distributed.overlap_comm_schedule``
    stamps plan buckets in the same order, so the runtime graph and the
    static schedule cannot disagree about who goes first."""
    n = int(n_stages)
    if n < 1:
        raise ValueError(f"need at least one stage, got {n_stages}")
    return list(range(n - 1, -1, -1))


def collective_rank_groups(axis_sizes: Dict[str, int],
                           axes,
                           axis_index_groups: Optional[Sequence[Sequence[int]]]
                           = None) -> List[Tuple[int, ...]]:
    """Flattened-rank participant groups for a collective over named mesh
    axes.

    ``axis_sizes`` is the mesh shape as an ordered ``{name: size}`` dict
    (outermost first); ranks are row-major indices into the mesh's device
    array, so this is the ONE place the jaxpr-level ``axis_index_groups``
    (positions along a single named axis) are translated into concrete
    device ranks.  Without explicit groups, each group holds every rank
    that shares its coordinates on the *unnamed* axes, ordered row-major
    over the named axes — exactly the set a ``psum``/``all_gather`` over
    ``axes`` mixes.  With explicit groups (only legal over a single named
    axis, as in JAX), each listed index tuple is instantiated once per
    combination of unnamed-axis coordinates, preserving the listed order
    (gather/scatter position is meaningful).

    The static sharding propagator (``analysis.sharding``) consumes this
    to model which ranks a collective makes agree."""
    names = list(axis_sizes)
    if isinstance(axes, str):
        axes = (axes,)
    axes = tuple(axes)
    for a in axes:
        if a not in axis_sizes:
            raise KeyError(f"axis {a!r} not in mesh axes {names}")
    strides: Dict[str, int] = {}
    s = 1
    for n in reversed(names):
        strides[n] = s
        s *= int(axis_sizes[n])
    other = [n for n in names if n not in axes]
    other_ranges = [range(int(axis_sizes[n])) for n in other]

    def rank(coords: Dict[str, int]) -> int:
        return sum(coords[n] * strides[n] for n in names)

    groups: List[Tuple[int, ...]] = []
    if axis_index_groups is not None:
        if len(axes) != 1:
            raise ValueError(
                "axis_index_groups only apply to a single named axis")
        ax = axes[0]
        for combo in itertools.product(*other_ranges):
            coords = dict(zip(other, combo))
            for g in axis_index_groups:
                groups.append(tuple(rank({**coords, ax: int(i)})
                                    for i in g))
    else:
        named_ranges = [range(int(axis_sizes[a])) for a in axes]
        for combo in itertools.product(*other_ranges):
            coords = dict(zip(other, combo))
            groups.append(tuple(
                rank({**coords, **dict(zip(axes, named))})
                for named in itertools.product(*named_ranges)))
    return groups


def mesh_info(mesh: Mesh) -> str:
    """One-line human description of a mesh, for startup logging."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    plat = mesh.devices.flat[0].platform
    return (f"mesh {shape} over {mesh.devices.size} {plat} device(s), "
            f"{jax.process_count()} process(es)")
