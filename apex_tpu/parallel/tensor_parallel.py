"""Tensor (model) parallelism: Megatron-style column/row-parallel layers.

The reference toolkit predates tensor parallelism (SURVEY.md §2.3: its
parallelism inventory is data-parallel only), but a TPU-native framework
scales BERT-large-class models across a mesh axis as a matter of course —
the mesh + collectives design (SURVEY.md §2.4) makes TP a module-level
concern rather than a runtime fork the way Megatron-LM's mpu is.

Pattern (Megatron-LM "Efficient Large-Scale Language Model Training",
applied the JAX way):

- ``ColumnParallelLinear`` — weight rows (output features) sharded over
  the ``model`` axis; forward is a local matmul producing the local slice
  of the output features.  No communication (optionally ``gather_output``
  all_gathers the feature axis).
- ``RowParallelLinear`` — weight columns (input features) sharded; each
  device contracts its input slice and the partial products are summed
  with ONE ``psum`` over the axis.  Bias is added after the reduction.
- ``ParallelMLP`` — Column(4E) -> activation -> Row(E): one psum per MLP.
- ``ParallelSelfAttention`` — q/k/v column-parallel with HEADS as the
  shard unit (contiguous head blocks, so a dim-0 split is exact), local
  flash/dense attention on the device's heads, row-parallel output
  projection: one psum per attention block.

How params flow (idiomatic GSPMD, not Megatron's per-rank allocation):
``init`` builds FULL-SIZE weights; :func:`partition_specs` walks the
module tree and returns a matching PartitionSpec pytree.  Jitting the
train step with ``jax.shard_map(..., in_specs=(specs, ...))`` (or
pjit-style sharding constraints) hands each device its local shard, and
the SAME forward code runs unmodified: inside shard_map the local
weight shard is simply a smaller array.  Outside any mesh (unit tests,
single device) the full weight is present and the psum no-ops via the
axis-in-scope check — the world_size==1 passthrough the reference's DDP
applies (apex/parallel/distributed.py world_size==1 branches).

Gradients: column/row shards receive local grads from the matmul
transposes; the replicated-activation psum transposes are inserted by
jax automatically.  Under a (data, model) mesh, DDP's
``allreduce_grads(axis_name="data")`` sums ONLY over the data axis, so
TP shards never get mixed across the model axis.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..nn.layers import Linear
from ..nn.module import Module, current_context
from ..nn import functional as F
from .sync_batchnorm import _axis_in_scope

__all__ = [
    "ColumnParallelLinear", "RowParallelLinear", "ParallelMLP",
    "ParallelSelfAttention", "VocabParallelEmbedding",
    "vocab_parallel_cross_entropy", "partition_specs",
    "local_shape", "sharded_optimizer_specs",
]

DEFAULT_AXIS = "model"


def _axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name) if _axis_in_scope(axis_name) else 1


# -- Megatron's conjugate f/g collectives -------------------------------
#
# Inside shard_map the loss is computed (identically) on every device of
# the model axis, so a plain ``psum`` at the row-parallel output would
# have its transpose re-sum the (already replicated) cotangent — every
# gradient upstream of it comes out axis_size times too large.  The
# correct pair (Megatron-LM's f/g):
#
#   g = reduce_from_model_parallel: psum forward, IDENTITY backward
#       (the cotangent of the replicated output is already replicated)
#   f = copy_to_model_parallel: identity forward, psum backward
#       (a replicated activation's gradient is the SUM of each shard's
#       local contribution)

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _g_reduce(x, axis_name):
    return lax.psum(x, axis_name)


def _g_reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _g_reduce_bwd(axis_name, _res, ct):
    return (ct,)


_g_reduce.defvjp(_g_reduce_fwd, _g_reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _f_copy(x, axis_name):
    return x


def _f_copy_fwd(x, axis_name):
    return x, None


def _f_copy_bwd(axis_name, _res, ct):
    return (lax.psum(ct, axis_name),)


_f_copy.defvjp(_f_copy_fwd, _f_copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _gather_last(x, axis_name):
    return lax.all_gather(x, axis_name, axis=x.ndim - 1, tiled=True)


def _gather_last_fwd(x, axis_name):
    return _gather_last(x, axis_name), x.shape[-1]


def _gather_last_bwd(axis_name, block, ct):
    # the replicated cotangent's transpose is SPLIT (take this device's
    # feature slice), not reduce-scatter — the all_gather transpose
    # would sum the identical replicated cotangents axis_size times
    idx = lax.axis_index(axis_name)
    return (lax.dynamic_slice_in_dim(ct, idx * block, block,
                                     axis=ct.ndim - 1),)


_gather_last.defvjp(_gather_last_fwd, _gather_last_bwd)


def reduce_from_model_parallel(x, axis_name: str = DEFAULT_AXIS):
    """psum forward / identity backward (Megatron's g)."""
    return _g_reduce(x, axis_name) if _axis_in_scope(axis_name) else x


def copy_to_model_parallel(x, axis_name: str = DEFAULT_AXIS):
    """identity forward / psum backward (Megatron's f)."""
    return _f_copy(x, axis_name) if _axis_in_scope(axis_name) else x


def gather_from_model_parallel(x, axis_name: str = DEFAULT_AXIS):
    """all_gather (last dim) forward / split backward."""
    return _gather_last(x, axis_name) if _axis_in_scope(axis_name) else x


class ColumnParallelLinear(Linear):
    """Linear whose OUTPUT features are sharded over ``axis_name``.

    Forward needs no collective: each device computes its slice of the
    output features from the (replicated) input.  ``gather_output=True``
    all_gathers the slices into the full feature dim (Megatron's
    gather_output flag) — leave False when a RowParallelLinear consumes
    the parallel activations directly.
    """

    def __init__(self, in_features: int, out_features: int,
                 bias: bool = True, gather_output: bool = False,
                 input_grad_reduce: bool = True,
                 axis_name: str = DEFAULT_AXIS):
        super().__init__(in_features, out_features, bias=bias)
        self.gather_output = gather_output
        # the f collective on the (replicated) input; blocks that feed
        # one activation into SEVERAL column layers (q/k/v) set this
        # False and apply copy_to_model_parallel once at block entry
        self.input_grad_reduce = input_grad_reduce
        self.axis_name = axis_name

    def param_specs(self) -> Dict[str, P]:
        s = {"weight": P(self.axis_name, None)}
        if self.use_bias:
            s["bias"] = P(self.axis_name)
        return s

    def forward(self, params, x):
        if self.input_grad_reduce:
            x = copy_to_model_parallel(x, self.axis_name)
        y = F.linear(x, params["weight"], params.get("bias"))
        if self.gather_output:
            y = gather_from_model_parallel(y, self.axis_name)
        return y


class RowParallelLinear(Linear):
    """Linear whose INPUT features are sharded over ``axis_name``.

    Each device contracts its input slice against its weight columns;
    the partial results are combined with one psum.  Bias (replicated)
    is added after the reduction so it is counted once.
    ``input_is_parallel=False`` first slices a replicated input down to
    this device's feature block (Megatron's scatter path).
    """

    def __init__(self, in_features: int, out_features: int,
                 bias: bool = True, input_is_parallel: bool = True,
                 axis_name: str = DEFAULT_AXIS):
        super().__init__(in_features, out_features, bias=bias)
        self.input_is_parallel = input_is_parallel
        self.axis_name = axis_name

    def param_specs(self) -> Dict[str, P]:
        s = {"weight": P(None, self.axis_name)}
        if self.use_bias:
            s["bias"] = P()
        return s

    def forward(self, params, x):
        in_scope = _axis_in_scope(self.axis_name)
        if not self.input_is_parallel and in_scope:
            # replicated input: each device slices its feature block; f
            # first, so the input's grad psums the zero-padded pieces
            # back into the full dense gradient
            x = copy_to_model_parallel(x, self.axis_name)
            tp = lax.axis_size(self.axis_name)
            idx = lax.axis_index(self.axis_name)
            block = self.in_features // tp
            x = lax.dynamic_slice_in_dim(x, idx * block, block,
                                         axis=x.ndim - 1)
        y = F.linear(x, params["weight"], None)
        # g: psum forward, identity backward — a plain psum's transpose
        # would re-sum the replicated cotangent (axis_size x grads)
        y = reduce_from_model_parallel(y, self.axis_name)
        b = params.get("bias")
        return y if b is None else y + b


class ParallelMLP(Module):
    """Column(hidden) -> activation -> Row(out): the Megatron MLP block,
    one psum per call."""

    def __init__(self, in_features: int, hidden_features: int,
                 activation: str = "gelu", bias: bool = True,
                 axis_name: str = DEFAULT_AXIS):
        super().__init__()
        self.fc_in = ColumnParallelLinear(in_features, hidden_features,
                                          bias=bias, axis_name=axis_name)
        self.fc_out = RowParallelLinear(hidden_features, in_features,
                                        bias=bias, axis_name=axis_name)
        self.activation = activation

    def forward(self, params, x):
        h = self.fc_in(params["fc_in"], x)
        h = getattr(F, self.activation)(h)
        return self.fc_out(params["fc_out"], h)


class ParallelSelfAttention(Module):
    """Self-attention with HEADS sharded over the model axis.

    q/k/v are separate column-parallel projections (contiguous head
    blocks shard exactly under a dim-0 split — a fused qkv matrix would
    interleave q/k/v inside one shard), the softmax(qk)v runs entirely
    locally on the device's heads via the same policy-aware
    ``dot_product_attention`` the single-device stack uses (flash kernel
    on TPU), and the output projection is row-parallel: ONE psum per
    attention block, the Megatron communication pattern.

    ``num_heads`` must divide by the axis size at run time.

    ``num_kv_heads < num_heads`` (GQA) shards the compact K/V
    projections over the same axis (``num_kv_heads % tp == 0``) and
    repeats them per local query-head group; ``rope_theta`` applies
    rotary position embeddings to q/k before attention (position-only,
    so head sharding is transparent) — together these are the Llama
    tensor-parallel block.
    """

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = True, causal: bool = False,
                 attn_dropout: float = 0.0,
                 axis_name: str = DEFAULT_AXIS,
                 num_kv_heads: Optional[int] = None,
                 rope_theta: Optional[float] = None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError(f"num_heads ({num_heads}) must divide "
                             f"embed_dim ({embed_dim})")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.num_kv_heads = (num_kv_heads if num_kv_heads is not None
                             else num_heads)
        if (self.num_kv_heads < 1
                or num_heads % self.num_kv_heads):
            raise ValueError(
                f"num_kv_heads={self.num_kv_heads} must be a positive "
                f"divisor of num_heads={num_heads}")
        self.head_dim = embed_dim // num_heads
        self.rope_theta = rope_theta
        self.causal = causal
        self.dropout_rate = dropout
        self.attn_dropout = attn_dropout    # attention-probs dropout
        self.axis_name = axis_name
        kv_dim = self.num_kv_heads * self.head_dim
        # one f at block entry instead of three: x feeds all three
        # projections, so input_grad_reduce is applied once in forward
        self.q = ColumnParallelLinear(embed_dim, embed_dim, bias=bias,
                                      input_grad_reduce=False,
                                      axis_name=axis_name)
        self.k = ColumnParallelLinear(embed_dim, kv_dim, bias=bias,
                                      input_grad_reduce=False,
                                      axis_name=axis_name)
        self.v = ColumnParallelLinear(embed_dim, kv_dim, bias=bias,
                                      input_grad_reduce=False,
                                      axis_name=axis_name)
        self.out = RowParallelLinear(embed_dim, embed_dim, bias=bias,
                                     axis_name=axis_name)

    def forward(self, params, x, mask: Optional[jax.Array] = None):
        from ..transformer.attention import dot_product_attention
        x = copy_to_model_parallel(x, self.axis_name)
        B, T, _ = x.shape
        tp = _axis_size(self.axis_name)
        if self.num_heads % tp or self.num_kv_heads % tp:
            raise ValueError(f"num_heads={self.num_heads} / num_kv_heads="
                             f"{self.num_kv_heads} not divisible by "
                             f"tensor-parallel size {tp}")
        h_local = self.num_heads // tp
        kv_local = self.num_kv_heads // tp
        q = self.q(params["q"], x).reshape(B, T, h_local, self.head_dim)
        k = self.k(params["k"], x).reshape(B, T, kv_local, self.head_dim)
        v = self.v(params["v"], x).reshape(B, T, kv_local, self.head_dim)
        q, k, v = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
        if self.rope_theta is not None:
            from ..models.llama import apply_rope
            q, k = apply_rope(q, k, jnp.arange(T), self.rope_theta)
        if kv_local != h_local:
            k = jnp.repeat(k, h_local // kv_local, axis=1)
            v = jnp.repeat(v, h_local // kv_local, axis=1)
        if (mask is not None and mask.ndim == 4
                and mask.shape[1] == self.num_heads and tp > 1):
            # per-head mask: take this device's head block, like the
            # weight shards (head-broadcast masks pass through untouched)
            idx = lax.axis_index(self.axis_name)
            mask = lax.dynamic_slice_in_dim(mask, idx * h_local, h_local,
                                            axis=1)
        attn_rng = None
        actx0 = current_context()
        if (self.attn_dropout > 0.0 and actx0 is not None and actx0.train):
            attn_rng = actx0.make_rng()
            if _axis_in_scope(self.axis_name):
                # independent attention-probs masks per head block
                attn_rng = jax.random.fold_in(
                    attn_rng, lax.axis_index(self.axis_name))
        ctx = dot_product_attention(
            q, k, v, mask=mask, causal=self.causal,
            dropout_rate=self.attn_dropout if attn_rng is not None else 0.0,
            dropout_rng=attn_rng)
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(B, T, h_local * self.head_dim)
        actx = current_context()
        if self.dropout_rate > 0.0 and actx is not None and actx.train:
            key = actx.make_rng()
            if _axis_in_scope(self.axis_name):
                # decorrelate the mask across model-axis shards — the
                # apply-rng is replicated, and an identical mask on
                # every head/feature block is a different (stronger)
                # regularizer than the dense equivalent (same fix as
                # ulysses.py / ring_attention.py)
                key = jax.random.fold_in(key,
                                         lax.axis_index(self.axis_name))
            ctx = F.dropout(ctx, self.dropout_rate, key)
        return self.out(params["out"], ctx)


class VocabParallelEmbedding(Module):
    """Embedding with the VOCAB dimension sharded over the model axis —
    the largest single weight in BERT-class models (vocab x hidden).

    Each device holds a contiguous vocab block; a lookup masks ids
    outside its block to a local zero row, gathers, and the g-collective
    psum combines the one-hot contributions (exactly one device is
    nonzero per id).  Megatron's VocabParallelEmbedding as mesh
    collectives.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 axis_name: str = DEFAULT_AXIS, init_std: float = 1.0):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.axis_name = axis_name
        self.init_std = init_std

    def create_params(self, key):
        return {"weight": self.init_std * jax.random.normal(
            key, (self.num_embeddings, self.embedding_dim), jnp.float32)}

    def param_specs(self) -> Dict[str, P]:
        return {"weight": P(self.axis_name, None)}

    def forward(self, params, ids):
        w = params["weight"]
        if not _axis_in_scope(self.axis_name):
            return F.embedding(ids, w)
        tp = lax.axis_size(self.axis_name)
        if self.num_embeddings % tp:
            raise ValueError(f"num_embeddings={self.num_embeddings} not "
                             f"divisible by tensor-parallel size {tp}")
        idx = lax.axis_index(self.axis_name)
        # derive the block from the actual local shard so a manually
        # padded table stays consistent with the mask math
        block = w.shape[0]
        start = idx * block
        local = ids - start
        in_block = (local >= 0) & (local < block)
        # F.embedding (not a raw take): an int8-quantized table
        # (quantization.QTensor) then gathers quantized rows and
        # dequantizes only those
        rows = F.embedding(jnp.where(in_block, local, 0), w)
        rows = jnp.where(in_block[..., None], rows, 0.0)
        return reduce_from_model_parallel(rows, self.axis_name)


def vocab_parallel_cross_entropy(local_logits: jax.Array,
                                 labels: jax.Array,
                                 axis_name: str = DEFAULT_AXIS,
                                 ignore_index: int = -100) -> jax.Array:
    """Cross-entropy over VOCAB-SHARDED logits without gathering them.

    ``local_logits``: (..., V/tp) — this device's vocab block (e.g. the
    output of a ColumnParallelLinear LM head with gather_output=False).
    The softmax statistics are combined with two scalar-per-token
    collectives (pmax for the stable max, psum for the normalizer) and
    the label's logit is picked out by the one device owning it —
    communication O(tokens), not O(tokens x vocab), Megatron's
    _VocabParallelCrossEntropy.  Masked tokens (``ignore_index``)
    contribute zero, mean over the rest.
    """
    f32 = local_logits.astype(jnp.float32)
    if _axis_in_scope(axis_name):
        tp = lax.axis_size(axis_name)
        idx = lax.axis_index(axis_name)
    else:
        tp, idx = 1, 0     # same masked math, degenerate collectives
    block = local_logits.shape[-1]
    start = idx * block
    # stable log-sum-exp across the sharded vocab; the max shift cancels
    # analytically, so its gradient path (incl. pmax) is cut explicitly
    local_max = lax.stop_gradient(jnp.max(f32, axis=-1))
    gmax = (lax.pmax(local_max, axis_name) if tp > 1 else local_max)
    sumexp = jnp.sum(jnp.exp(f32 - gmax[..., None]), axis=-1)
    # the partial-sum psum and the label-logit psum are both linear with
    # device-disjoint/identical-sum structure; plain psum would re-sum
    # the replicated cotangent in backward (the f/g issue), so both ride
    # the g-collective
    gsum = reduce_from_model_parallel(sumexp, axis_name)
    local_lbl = labels - start
    in_block = (local_lbl >= 0) & (local_lbl < block)
    picked = jnp.take_along_axis(
        f32, jnp.where(in_block, local_lbl, 0)[..., None], axis=-1)[..., 0]
    picked = jnp.where(in_block, picked, 0.0)
    label_logit = reduce_from_model_parallel(picked, axis_name)
    nll = jnp.log(gsum) + gmax - label_logit
    valid = labels != ignore_index
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return jnp.sum(nll) / denom


def partition_specs(module: Module, params: Optional[Any] = None,
                    key: Optional[jax.Array] = None) -> Any:
    """PartitionSpec pytree matching ``module.init(...)[0]``.

    TP layers contribute their ``param_specs``; every other leaf is
    replicated (``P()``).  Pass the real ``params`` tree when you have
    it; otherwise the structure is derived shape-only via
    ``jax.eval_shape`` (no FLOPs, no memory).

    Use as the param entry of ``shard_map``'s in/out_specs, e.g.::

        specs = tensor_parallel.partition_specs(model)
        train = jax.jit(jax.shard_map(step, mesh=mesh,
                        in_specs=((specs, P(), P()), P("data")),
                        out_specs=((specs, P(), P()), P())))
    """
    if params is None:
        key = key if key is not None else jax.random.PRNGKey(0)
        params = jax.eval_shape(lambda k: module.init(k)[0], key)

    def build(mod: Module, p: Any) -> Any:
        if not isinstance(p, dict):
            return P()
        own = mod.param_specs() if hasattr(mod, "param_specs") else {}
        out = {}
        children = dict(mod.named_children())
        for name, sub in p.items():
            if name in own:
                out[name] = own[name]
            elif name in children:
                out[name] = build(children[name], sub)
            else:
                out[name] = jax.tree_util.tree_map(lambda _: P(), sub)
        return out

    return build(module, params)


def local_shape(shape, spec, mesh):
    """Per-device shape of a global array sharded by ``spec`` — via
    NamedSharding, which also rejects non-divisible dims with a clear
    error instead of silently floor-dividing.

    Public because it is the ONE global→local shape rule: the TP entry
    point derives its shard_map operand shapes through it and the
    static sharding propagator (``analysis.sharding``) owes its
    local-bytes accounting to the same arithmetic."""
    from jax.sharding import NamedSharding
    return NamedSharding(mesh, spec if spec is not None else P()
                         ).shard_shape(tuple(shape))


_local_shape = local_shape


def sharded_optimizer_specs(optimizer, params: Any, param_specs: Any,
                            mesh, axis_name: str = DEFAULT_AXIS) -> Any:
    """PartitionSpec tree for ``optimizer.init(params)``-shaped state
    under tensor-parallel sharding.

    Optimizer state must be built from the LOCAL param shards (the amp
    O2 wrapper keeps masters/moments as one flat buffer whose length is
    the per-device param count), so both ``init`` and ``step`` run
    inside ``shard_map`` — this derives the matching out/in specs:

    - a leaf whose local shape equals its global shape is replicated
      (scalars: step counters, loss scale);
    - a 1-D leaf that shrank is a flat per-device buffer — device-
      concat layout, ``P(axis_name)``;
    - a multi-dim leaf that shrank mirrors a sharded param (tree-state
      optimizers): the shrunken dims get ``axis_name``.

    Usage::

        ospecs = tp.sharded_optimizer_specs(opt, params, specs, mesh)
        opt_state = jax.jit(jax.shard_map(
            opt.init, mesh=mesh, in_specs=(specs,), out_specs=ospecs,
            check_vma=False))(params)
    """
    flat_params = jax.tree_util.tree_flatten_with_path(params)[0]
    spec_of = {jax.tree_util.keystr(p): s for p, s in
               jax.tree_util.tree_flatten_with_path(
                   param_specs, is_leaf=lambda x: isinstance(x, P))[0]}
    # spec inference for MIRRORED state leaves attributes every shrunken
    # dim to axis_name, so param_specs may only shard over that one axis
    # (the tensor-parallel case this helper exists for) — reject other
    # axes loudly rather than mis-shard silently
    for k, s in spec_of.items():
        for names in (s or ()):
            for n in (names if isinstance(names, tuple)
                      else (names,) if names is not None else ()):
                if n != axis_name:
                    raise ValueError(
                        f"param spec at {k} shards over axis {n!r}; "
                        f"sharded_optimizer_specs only supports specs "
                        f"over the single axis {axis_name!r}")
    local_params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params),
        [jax.ShapeDtypeStruct(
            _local_shape(l.shape,
                         spec_of.get(jax.tree_util.keystr(p)),  # None ok
                         mesh), l.dtype)
         for p, l in flat_params])

    glob = jax.eval_shape(optimizer.init, params)
    loc = jax.eval_shape(optimizer.init, local_params)

    def leaf_spec(g, l):
        if tuple(g.shape) == tuple(l.shape):
            return P()
        if l.ndim == 1:
            return P(axis_name)
        return P(*[axis_name if gs != ls else None
                   for gs, ls in zip(g.shape, l.shape)])

    # pair leaves positionally and unflatten on the LOCAL treedef: the
    # amp wrapper's FlatMasters node carries its layout (shapes/offsets)
    # as pytree aux data, which differs between the global and local
    # trees — a tree_map across the two would reject the mismatch, and
    # shard_map's out_specs must match the structure the mapped init
    # actually returns (the local one)
    gl = jax.tree_util.tree_leaves(glob)
    ll, ldef = jax.tree_util.tree_flatten(loc)
    if len(gl) != len(ll):
        raise ValueError(
            f"optimizer state leaf count differs between global "
            f"({len(gl)}) and local ({len(ll)}) init — cannot infer "
            f"sharded state specs for this optimizer")
    return jax.tree_util.tree_unflatten(
        ldef, [leaf_spec(g, l) for g, l in zip(gl, ll)])
