"""Manual master-parameter toolkit (reference: apex/fp16_utils/fp16util.py).

Functional equivalents of the reference helpers: master copies are new
pytrees rather than cloned torch Parameters, and "convert network to half
keeping BatchNorm fp32" operates on the (module tree, params tree) pair via
amp.cast_param_tree — same invariant as convert_network
(fp16util.py:60-70).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "prep_param_lists", "model_grads_to_master_grads",
    "master_params_to_model_params", "network_to_half", "convert_network",
    "FP16Model", "tofp16", "BN_convert_float", "clip_grad_norm",
]


def prep_param_lists(model_params: Any, flat_master: bool = False
                     ) -> Tuple[Any, Any]:
    """Return (model_params, fp32 master copy).  With ``flat_master`` the
    master is a single fused fp32 vector (fp16util.py:90-133); params must
    then share one dtype."""
    if flat_master:
        leaves = jax.tree_util.tree_leaves(model_params)
        dtypes = {jnp.dtype(l.dtype) for l in leaves}
        if len(dtypes) > 1:
            raise TypeError("flat_master requires a single param dtype "
                            f"(got {sorted(map(str, dtypes))})")
        master = jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in leaves])
        return model_params, master
    master = jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32), model_params)
    return model_params, master


def model_grads_to_master_grads(model_grads: Any, master: Any,
                                flat_master: bool = False) -> Any:
    """Cast half grads into the master's fp32 structure
    (fp16util.py:136-155)."""
    if flat_master:
        leaves = jax.tree_util.tree_leaves(model_grads)
        return jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in leaves])
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32), model_grads)


def master_params_to_model_params(master: Any, model_params: Any,
                                  flat_master: bool = False) -> Any:
    """Copy master values back into the model's dtypes/shapes
    (fp16util.py:158-172)."""
    if flat_master:
        leaves, treedef = jax.tree_util.tree_flatten(model_params)
        out, off = [], 0
        for l in leaves:
            n = int(l.size)
            out.append(master[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype), master, model_params)


def tofp16(params: Any, half_dtype=jnp.float16) -> Any:
    """Cast every float leaf to half (fp16util.py:22-27)."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(half_dtype)
        if jnp.issubdtype(jnp.result_type(p), jnp.floating) else p, params)


def BN_convert_float(module, params: Any) -> Any:
    """Restore fp32 for BatchNorm params within a half tree
    (fp16util.py:30-42)."""
    from ..amp._initialize import cast_param_tree

    def walk(mod, p):
        if not isinstance(p, dict):
            return p
        out = {}
        for k, v in p.items():
            child = mod._children.get(k)
            if child is not None and getattr(child, "fp32_params", False):
                out[k] = jax.tree_util.tree_map(
                    lambda x: x.astype(jnp.float32), v)
            elif child is not None:
                out[k] = walk(child, v)
            else:
                out[k] = v
        return out
    return walk(module, params)


def network_to_half(module, params: Any, half_dtype=jnp.float16) -> Any:
    """Half params with fp32 BatchNorm — convert_network parity
    (fp16util.py:60-84)."""
    return convert_network(module, params, half_dtype)


def convert_network(module, params: Any, dtype=jnp.float16) -> Any:
    from ..amp._initialize import cast_param_tree
    return cast_param_tree(module, params, dtype, keep_batchnorm_fp32=True)


class FP16Model:
    """Wrapper running a module in half precision with half-cast inputs
    (fp16util.py:44-58)."""

    def __init__(self, module, half_dtype=jnp.float16):
        self.module = module
        self.half_dtype = half_dtype

    def init(self, key):
        params, state = self.module.init(key)
        return convert_network(self.module, params, self.half_dtype), state

    def apply(self, params, *args, **kwargs):
        from .. import nn
        args = jax.tree_util.tree_map(
            lambda x: x.astype(self.half_dtype)
            if isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
                jnp.result_type(x), jnp.floating) else x, args)
        return nn.apply(self.module, params, *args, **kwargs)

    __call__ = apply


def clip_grad_norm(grads: Any, max_norm: float, norm_type: float = 2.0
                   ) -> Tuple[Any, jax.Array]:
    """Clip a gradient tree by global norm; returns (clipped, total_norm)
    (reference alias fp16util.py:182-187)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == 2.0:
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in leaves))
    elif norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g.astype(jnp.float32))) for g in leaves]))
    else:
        total = sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
                    for g in leaves) ** (1.0 / norm_type)
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * coef).astype(g.dtype), grads)
    return clipped, total
