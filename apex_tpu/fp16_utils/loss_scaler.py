"""Legacy LossScaler / DynamicLossScaler (reference:
apex/fp16_utils/loss_scaler.py).

Stateful classes with the reference's attribute surface for scripts written
against the legacy API; new code should use apex_tpu.amp.LossScaler's
functional state.  Overflow detection mirrors the reference's inf/nan probe
(:84-110), here one fused jnp check instead of a per-tensor sum."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


class LossScaler:
    """Static scaler (reference :10-45)."""

    def __init__(self, scale: float = 1.0):
        self.cur_scale = float(scale)

    @property
    def loss_scale(self) -> float:
        return self.cur_scale

    def has_overflow(self, params: Any) -> bool:
        return False

    def update_scale(self, overflow: bool) -> None:
        pass

    def scale_gradient(self, grads: Any) -> Any:
        return jax.tree_util.tree_map(lambda g: g * self.loss_scale, grads)

    def backward(self, loss_fn, params, *args):
        scaled = lambda p: loss_fn(p, *args) * self.loss_scale
        return jax.value_and_grad(scaled)(params)


class DynamicLossScaler(LossScaler):
    """Dynamic scaler (reference :46-121): halve on overflow, double every
    ``scale_window`` clean iterations."""

    def __init__(self, init_scale: float = 2 ** 32, scale_factor: float = 2.,
                 scale_window: int = 1000):
        super().__init__(init_scale)
        self.cur_iter = 0
        self.last_overflow_iter = -1
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def has_overflow(self, grads: Any) -> bool:
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return False
        bad = jnp.any(jnp.stack(
            [jnp.any(~jnp.isfinite(g.astype(jnp.float32))) for g in leaves]))
        return bool(bad)

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.cur_scale = max(self.cur_scale / self.scale_factor, 1.0)
            self.last_overflow_iter = self.cur_iter
        elif (self.cur_iter - self.last_overflow_iter) % self.scale_window == 0:
            self.cur_scale *= self.scale_factor
        self.cur_iter += 1
