"""apex_tpu.fp16_utils — manual mixed-precision toolkit
(reference: apex/fp16_utils/__init__.py:1-16)."""

from .fp16util import (
    BN_convert_float, FP16Model, clip_grad_norm, convert_network,
    master_params_to_model_params, model_grads_to_master_grads,
    network_to_half, prep_param_lists, tofp16)
from .fp16_optimizer import FP16_Optimizer
from .loss_scaler import LossScaler, DynamicLossScaler
