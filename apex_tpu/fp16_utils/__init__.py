"""apex_tpu.fp16_utils — manual mixed-precision toolkit
(reference: apex/fp16_utils/__init__.py:1-16)."""

from .fp16util import (
    BN_convert_float, FP16Model, clip_grad_norm, convert_network,
    master_params_to_model_params, model_grads_to_master_grads,
    network_to_half, prep_param_lists, tofp16)
from .fp16_optimizer import FP16_Optimizer
from .loss_scaler import LossScaler, DynamicLossScaler


class Fused_Weight_Norm:
    """Working equivalent of the reference's *dangling* export: apex's
    reparameterization imports ``Fused_Weight_Norm`` from fp16_utils, but
    the reference snapshot no longer defines it (weight_norm.py:3 vs
    fp16_utils/__init__.py:1-16 — SURVEY.md §2.1 flags the breakage).
    Here the fused norm exists: w = g * v / ||v|| computed in fp32 in one
    XLA fusion (apex_tpu.reparameterization.compute_weight)."""

    @staticmethod
    def apply(v, g, dim: int = 0):
        from ..reparameterization import compute_weight
        return compute_weight(g, v, dim)

    def __call__(self, v, g, dim: int = 0):
        return self.apply(v, g, dim)
