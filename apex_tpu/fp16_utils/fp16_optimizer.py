"""General FP16_Optimizer: the legacy 2-line master-weight wrapper.

Equivalent of apex/fp16_utils/fp16_optimizer.py (643 lines): wraps *any*
apex_tpu Optimizer, owns loss scaling (``backward``), exposes
``update_master_grads`` / ``clip_master_grads`` / overflow-skipping
``step`` with closure support, and checkpoints fp32 masters separately from
model weights ("option 2", reference :298-359).

This is the stateful/eager flavor for legacy-script parity; it drives the
same functional pieces the jitted path uses (LossScaler state machine,
multi_tensor unscale).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .fp16util import (clip_grad_norm, master_params_to_model_params,
                       model_grads_to_master_grads, prep_param_lists)
from ..amp.scaler import LossScaler as _FunctionalScaler
from ..amp._amp_state import maybe_print

__all__ = ["FP16_Optimizer"]


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = True):
        self.optimizer = init_optimizer
        if dynamic_loss_scale:
            self.loss_scaler = _FunctionalScaler(
                "dynamic", **(dynamic_loss_args or {}))
        else:
            self.loss_scaler = _FunctionalScaler(static_loss_scale)
        self.verbose = verbose
        self.overflow = False
        self.first_closure_call_this_step = True
        self._params = None
        self._masters = None
        self._inner_state = None
        self._scaler_state = self.loss_scaler.init_state()
        self._master_grads = None
        self._scaled_grads = None

    # -- binding -----------------------------------------------------------
    def setup(self, params: Any) -> None:
        """Attach model params (half or fp32); builds fp32 masters."""
        self._params, self._masters = prep_param_lists(params)
        self._inner_state = self.optimizer.init(self._masters)

    @property
    def params(self) -> Any:
        return self._params

    @property
    def loss_scale(self) -> float:
        return float(self._scaler_state.loss_scale)

    # -- the reference's 4-call protocol ----------------------------------
    def zero_grad(self) -> None:
        self._master_grads = None
        self._scaled_grads = None

    def backward(self, loss_fn: Callable, *args,
                 update_master_grads: bool = True):
        """Scale the loss, compute grads w.r.t. model params
        (reference :462-523).  ``loss_fn(params, *args) -> scalar``.
        Returns the unscaled loss."""
        if self._params is None:
            raise RuntimeError("call setup(params) first")
        scale = self._scaler_state.loss_scale

        def scaled(p):
            return loss_fn(p, *args).astype(jnp.float32) * scale

        scaled_loss, grads = jax.value_and_grad(scaled)(self._params)
        if self._scaled_grads is None:
            self._scaled_grads = grads
        else:  # accumulate across backward calls (reference :497-510)
            self._scaled_grads = jax.tree_util.tree_map(
                jnp.add, self._scaled_grads, grads)
        if update_master_grads:
            self.update_master_grads()
        return scaled_loss / scale

    def update_master_grads(self) -> None:
        """Unscale accumulated grads into fp32 master grads with fused
        overflow check (reference :525-579)."""
        if self._scaled_grads is None:
            return
        grads32, found = self.loss_scaler.unscale(
            self._scaled_grads, self._scaler_state)
        self.overflow = bool(found > 0)
        self._master_grads = grads32
        self._scaled_grads = None

    def clip_master_grads(self, max_norm: float, norm_type: float = 2.0):
        """Clip master grads by global norm (reference :274-296); returns
        the pre-clip norm (-1 convention not used here: overflow is already
        tracked separately)."""
        if self._master_grads is None:
            raise RuntimeError("no master grads; call backward first")
        self._master_grads, total = clip_grad_norm(
            self._master_grads, max_norm, norm_type)
        return total

    def step(self, closure: Optional[Callable] = None):
        """Skip on overflow, else inner step on masters + master->model
        copy (reference :361-460, incl. closure support)."""
        if closure is not None:
            return self._step_with_closure(closure)
        old_scale = float(self._scaler_state.loss_scale)
        found = jnp.asarray(1.0 if self.overflow else 0.0, jnp.float32)
        self._scaler_state = self.loss_scaler.update(self._scaler_state, found)
        if self.overflow:
            maybe_print(
                f"OVERFLOW! Skipping step. Attempted loss scale: "
                f"{old_scale}, reducing to "
                f"{float(self._scaler_state.loss_scale)}")
            self.zero_grad()
            self.overflow = False
            return None
        self._masters, self._inner_state = self.optimizer.update(
            self._master_grads, self._inner_state, self._masters)
        self._params = master_params_to_model_params(
            self._masters, self._params)
        self.zero_grad()
        return None

    def _step_with_closure(self, closure: Callable):
        # re-evaluate until a non-overflowed step applies (reference :423-460)
        while True:
            loss = closure()
            if not self.overflow:
                break
            # closure path: scaler already updated inside step recursion
            found = jnp.ones((), jnp.float32)
            self._scaler_state = self.loss_scaler.update(
                self._scaler_state, found)
            maybe_print("OVERFLOW within closure! Retrying with loss scale "
                        f"{float(self._scaler_state.loss_scale)}")
            self.zero_grad()
            self.overflow = False
        self.step()
        return loss

    # -- checkpoint: masters separate from model weights (:298-359) --------
    def state_dict(self) -> dict:
        return {"loss_scaler": self._scaler_state._asdict(),
                "overflow": self.overflow,
                "first_closure_call_this_step":
                    self.first_closure_call_this_step,
                "optimizer_state": self._inner_state,
                "fp32_from_fp16": self._masters}

    def load_state_dict(self, sd: dict) -> None:
        from ..amp.scaler import ScalerState
        self._scaler_state = ScalerState(
            **{k: jnp.asarray(v) for k, v in sd["loss_scaler"].items()})
        self.overflow = sd["overflow"]
        self._inner_state = sd["optimizer_state"]
        self._masters = sd["fp32_from_fp16"]
        if self._params is not None:
            self._params = master_params_to_model_params(
                self._masters, self._params)
