"""Rule engine: findings, the rule registry, and ``analyze``.

A :class:`Rule` looks at one entry point's traced/lowered graph and
returns :class:`Finding`s.  Rules are data-driven: each entry point
carries an ``expect`` dict (see :mod:`.entry_points`) and a rule only
applies where its expectation key is present (except the always-on
host-transfer rule).  Findings are machine-readable and export as
schema-versioned JSONL records through ``observability.exporters`` —
tests, the CI gate (tests/ci/graph_lint.py), and the CLI
(``python -m apex_tpu.analysis``) all consume the same objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["Finding", "Rule", "RULES", "register_rule", "get_rule",
           "analyze", "analyze_entry_point", "findings_to_records",
           "run_lint", "ERROR", "WARNING"]

ERROR = "error"
WARNING = "warning"


@dataclass
class Finding:
    """One violated invariant in one entry point's graph."""
    rule: str
    entry_point: str
    message: str
    severity: str = ERROR
    detail: Dict[str, Any] = field(default_factory=dict)

    def to_record(self) -> Dict[str, Any]:
        """Flat JSONL payload (enriched with schema_version/host/stale
        by the exporter)."""
        rec = {"kind": "graph_lint", "rule": self.rule,
               "severity": self.severity, "entry_point": self.entry_point,
               "message": self.message}
        if self.detail:
            rec["detail"] = self.detail
        return rec

    def __str__(self):
        return (f"[{self.severity}] {self.entry_point}: "
                f"{self.rule}: {self.message}")


class Rule:
    """Base class: subclasses set ``name``/``expect_key`` and implement
    ``check``.  ``expect_key`` is the entry-point expectation that opts
    a graph into the rule; ``None`` means the rule is unconditional."""

    name: str = "?"
    expect_key: Optional[str] = None

    def applies(self, entry_point) -> bool:
        if self.expect_key is None:
            return True
        return self.expect_key in entry_point.expect

    def check(self, entry_point, graph) -> List[Finding]:
        raise NotImplementedError

    def finding(self, entry_point, message: str, severity: str = ERROR,
                **detail) -> Finding:
        return Finding(rule=self.name, entry_point=entry_point.name,
                       message=message, severity=severity, detail=detail)


RULES: Dict[str, Rule] = {}


def register_rule(rule_cls):
    """Class decorator: instantiate and register a rule by its name."""
    rule = rule_cls()
    if rule.name in RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    RULES[rule.name] = rule
    return rule_cls


def get_rule(name: str) -> Rule:
    try:
        return RULES[name]
    except KeyError:
        raise KeyError(f"unknown rule {name!r}; known: {sorted(RULES)}")


def analyze_entry_point(entry_point,
                        rules: Optional[Iterable] = None
                        ) -> List[Finding]:
    """Run every applicable rule (objects or names) over one entry
    point's graph."""
    out: List[Finding] = []
    graph = entry_point.graph()
    if rules is None:
        rules = list(RULES.values())
    rules = [get_rule(r) if isinstance(r, str) else r for r in rules]
    for rule in rules:
        if rule.applies(entry_point):
            out.extend(rule.check(entry_point, graph))
    return out


def analyze(entry_points=None, rules=None, names=None, tags=None
            ) -> List[Finding]:
    """Run the analyzer: ``entry_points`` (objects) or ``names``/``tags``
    select from the registry; ``rules`` (names or objects) defaults to
    all registered rules."""
    from .entry_points import select
    if entry_points is None:
        entry_points = select(names=names, tags=tags)
    if rules is not None:
        rules = [get_rule(r) if isinstance(r, str) else r for r in rules]
    findings: List[Finding] = []
    for ep in entry_points:
        findings.extend(analyze_entry_point(ep, rules=rules))
    return findings


def findings_to_records(findings: Iterable[Finding]) -> List[Dict[str, Any]]:
    return [f.to_record() for f in findings]


def run_lint(entry_points=None, rules=None, emit=None,
             skip_runtime_errors: bool = False, on_skip=None,
             progress=None) -> Dict[str, Any]:
    """Drive the analyzer end to end — the shared core of the CLI
    (``python -m apex_tpu.analysis``), the CI gate and ``bench.py
    --graph-lint``, so severity tallies and the summary-record shape
    cannot drift between consumers.

    ``emit(record)`` receives one RAW (un-enriched) JSONL payload per
    finding plus the final ``graph_lint_summary`` — callers route it
    through their exporter.  ``skip_runtime_errors`` skips entry points
    whose builders raise RuntimeError (the device-count gate) after
    calling ``on_skip(ep, exc)``; ``progress(ep, findings, seconds)``
    fires after each analyzed entry point.  Returns the summary dict.
    """
    import time as _time
    from .entry_points import select
    if entry_points is None:
        entry_points = select()
    if rules is not None:
        rules = [get_rule(r) if isinstance(r, str) else r for r in rules]
    n_err = n_warn = n_run = n_skip = 0
    t_start = _time.perf_counter()
    for ep in entry_points:
        t0 = _time.perf_counter()
        try:
            findings = analyze_entry_point(ep, rules=rules)
        except RuntimeError as e:
            if not skip_runtime_errors:
                raise
            n_skip += 1
            if on_skip is not None:
                on_skip(ep, e)
            continue
        n_run += 1
        for f in findings:
            if f.severity == ERROR:
                n_err += 1
            else:
                n_warn += 1
            if emit is not None:
                emit(f.to_record())
        if progress is not None:
            progress(ep, findings, _time.perf_counter() - t0)
    summary: Dict[str, Any] = {
        "kind": "graph_lint_summary", "entry_points": n_run,
        "rules": len(rules) if rules is not None else len(RULES),
        "findings": n_err + n_warn, "errors": n_err,
        "warnings": n_warn,
        "elapsed_seconds": round(_time.perf_counter() - t_start, 2)}
    if n_skip:
        summary["skipped_entry_points"] = n_skip
    if emit is not None:
        emit(summary)
    return summary
