"""Static partition-spec propagation over ``shard_map`` jaxprs.

The collective rule counts psums and the memory rule counts live bytes,
but neither can *see* placement: which arrays are replicated across the
mesh, which are sharded, and whether a refactor smuggled an unplanned
all-gather into the hot path.  This module closes that hole statically —
no device execution, no mocks, just the traced jaxpr.

The model is a **partition of ranks**: every intermediate value is
assigned a partition of the flattened device ranks (row-major over the
mesh axes) such that ranks in the same cell are *guaranteed* to hold
bit-identical values.  Fully replicated = one cell; fully varying =
singleton cells.  The replication factor of an array is
``world / n_cells``, and the deletable bytes are
``local_bytes * (world - n_cells)`` — exactly the fp32 master/optimizer
state ZeRO-2/3 (ROADMAP item 2) will shard away.

Propagation rules (validated against the jax 0.4.37 jaxprs the entry
points actually trace):

- ``shard_map`` body inputs: partition keyed by each rank's coordinates
  along the axes named in ``in_names`` (``{}`` -> replicated).
- default eqn: outputs get the meet (common refinement) of the input
  partitions — sound for any deterministic op (same inputs, same
  outputs).
- ``psum``/``pmax``/``pmin``: two ranks agree afterwards iff their
  participant groups reduce equal multisets — groups merge iff their
  *count-vectors* over input cells match.
- ``all_gather``: groups merge iff their members are element-wise in the
  same input cells (this is what makes the hierarchical
  psum_scatter(ici) -> psum(dcn) -> all_gather(ici) chain resolve to
  fully replicated).
- ``reduce_scatter`` (``psum_scatter``): output cell = (count-vector
  class of the group, position within the group).
- ``all_to_all``: output cell = (element-wise cell tuple of the group,
  position).  ``ppermute``: each destination inherits its source's cell;
  untargeted ranks share a "zero" cell.  ``axis_index``: cell = the
  coordinate along the axis.
- control flow: ``scan``/``while`` run the body to a fixpoint on the
  carry partitions (finite lattice — converges in <= world steps);
  ``while`` additionally meets the carry with the predicate partition
  (rank-varying trip counts de-replicate everything they touch);
  ``cond`` meets all branch outputs with the predicate.
- unknown higher-order prims: recursed when the sub-jaxpr arity matches;
  otherwise outputs are conservatively *varying* if the body contains
  collectives or ``axis_index``, else the meet of the inputs.

Consumers (wired through :mod:`.rules` and the exporters):

- :func:`entry_point_sharding_record` — the **replication ledger**, a
  schema-v13 ``kind: sharding`` record per train entry point so
  ``check_bench_trend`` can ratchet ``replicated_bytes`` down as
  ZeRO-2/3 stages land.
- :func:`check_shard_map_specs` — spec-vs-mesh consistency (axis-name
  existence, divisibility, replicated-output claims the propagated
  partition contradicts; ``check_vma=False`` means XLA never checks the
  latter at runtime).
- :func:`collective_sites` — the resharding census the
  ``resharding-census`` rule matches against
  ``allreduce_comm_plan``/``overlap_comm_schedule``.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.extend.core  # noqa: F401  (jax.extend is not auto-imported)

from . import graphs
from ..parallel.topology import collective_rank_groups

__all__ = [
    "Partition", "ArgSharding", "CollectiveSite", "ShardMapAnalysis",
    "RESHARD_PRIMS", "shard_map_eqns", "analyze_shard_map",
    "analyze_sharding", "check_shard_map_specs",
    "divergent_output_claims", "entry_point_sharding_record",
]

# collectives that change *placement* (vs psum/pmax/pmin which only
# reduce): the census rule requires every one of these in a hot graph to
# be explained by the comm plan or a declared budget
RESHARD_PRIMS = ("all_gather", "all_to_all", "reduce_scatter", "pgather")

_REDUCE_PRIMS = ("psum", "pmax", "pmin")


# -- the partition lattice ------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Partition:
    """A partition of the flattened mesh ranks into agreement cells:
    ranks in the same cell are guaranteed to hold identical values.
    ``cells[r]`` is rank r's cell id, canonicalized by first
    occurrence so equal partitions compare equal."""

    cells: Tuple[int, ...]

    @staticmethod
    def from_keys(keys: Sequence[Any]) -> "Partition":
        ids: Dict[Any, int] = {}
        out = []
        for k in keys:
            if k not in ids:
                ids[k] = len(ids)
            out.append(ids[k])
        return Partition(tuple(out))

    @staticmethod
    def replicated(world: int) -> "Partition":
        return Partition((0,) * world)

    @staticmethod
    def varying(world: int) -> "Partition":
        return Partition(tuple(range(world)))

    @property
    def world(self) -> int:
        return len(self.cells)

    @property
    def n_cells(self) -> int:
        return max(self.cells) + 1 if self.cells else 0

    @property
    def is_replicated(self) -> bool:
        return self.n_cells <= 1

    def replication_factor(self) -> float:
        f = self.world / max(1, self.n_cells)
        return int(f) if float(f).is_integer() else f

    def meet(self, other: "Partition") -> "Partition":
        """Common refinement: same cell afterwards iff same cell in
        BOTH inputs (the sound combine for multi-input ops)."""
        return Partition.from_keys(tuple(zip(self.cells, other.cells)))


def _meet_all(parts: Sequence[Partition], world: int) -> Partition:
    if not parts:
        return Partition.replicated(world)
    return functools.reduce(lambda a, b: a.meet(b), parts)


class _MeshCtx:
    """Rank bookkeeping for one mesh: coordinates, collective groups."""

    def __init__(self, axis_sizes: Dict[str, int]):
        self.axis_sizes = {k: int(v) for k, v in axis_sizes.items()}
        self.names = list(self.axis_sizes)
        sizes = [self.axis_sizes[n] for n in self.names]
        self.world = int(np.prod(sizes)) if sizes else 1
        import itertools
        self.coords = list(itertools.product(*[range(s) for s in sizes]))
        self._pos = {n: i for i, n in enumerate(self.names)}

    def groups(self, axes, axis_index_groups=None) -> List[Tuple[int, ...]]:
        return collective_rank_groups(self.axis_sizes, axes,
                                      axis_index_groups)

    def coord_partition(self, axes: Sequence[str]) -> Partition:
        """Partition keyed by each rank's coordinates along ``axes`` —
        the input partition of an array sharded over those axes, and
        the output of ``axis_index``."""
        idxs = [self._pos[a] for a in axes]
        return Partition.from_keys(
            [tuple(c[i] for i in idxs) for c in self.coords])

    def names_partition(self, names_dict: Dict[int, Tuple[str, ...]]
                        ) -> Partition:
        axes = sorted({a for t in names_dict.values() for a in t})
        if not axes:
            return Partition.replicated(self.world)
        return self.coord_partition(axes)

    def varies_along(self, part: Partition, axis: str) -> bool:
        """True if two ranks differing only in their ``axis`` coordinate
        can hold different values."""
        i = self._pos[axis]
        seen: Dict[Tuple, int] = {}
        for r, c in enumerate(self.coords):
            key = c[:i] + c[i + 1:]
            if key in seen and part.cells[seen[key]] != part.cells[r]:
                return True
            seen.setdefault(key, r)
        return False

    def spec_str(self, part: Partition) -> str:
        if part.is_replicated:
            return "replicated"
        axes = [a for a in self.names if self.varies_along(part, a)]
        if axes:
            return "varies(" + ",".join(axes) + ")"
        return f"varies({part.n_cells} cells)"


# -- collective transfer functions ----------------------------------------

def _reduce_part(p: Partition, groups, world: int) -> Partition:
    keys: List[Any] = [("solo", r) for r in range(world)]
    for g in groups:
        cnt: Dict[int, int] = {}
        for r in g:
            cnt[p.cells[r]] = cnt.get(p.cells[r], 0) + 1
        k = tuple(sorted(cnt.items()))
        for r in g:
            keys[r] = k
    return Partition.from_keys(keys)


def _gather_part(p: Partition, groups, world: int) -> Partition:
    keys: List[Any] = [("solo", r) for r in range(world)]
    for g in groups:
        k = tuple(p.cells[m] for m in g)
        for r in g:
            keys[r] = k
    return Partition.from_keys(keys)


def _scatter_part(p: Partition, groups, world: int) -> Partition:
    keys: List[Any] = [("solo", r) for r in range(world)]
    for g in groups:
        cnt: Dict[int, int] = {}
        for r in g:
            cnt[p.cells[r]] = cnt.get(p.cells[r], 0) + 1
        base = tuple(sorted(cnt.items()))
        for idx, r in enumerate(g):
            keys[r] = (base, idx)
    return Partition.from_keys(keys)


def _all_to_all_part(p: Partition, groups, world: int) -> Partition:
    keys: List[Any] = [("solo", r) for r in range(world)]
    for g in groups:
        base = tuple(p.cells[m] for m in g)
        for idx, r in enumerate(g):
            keys[r] = (base, idx)
    return Partition.from_keys(keys)


def _ppermute_part(p: Partition, groups, perm, world: int) -> Partition:
    keys: List[Any] = [("solo", r) for r in range(world)]
    src_of = {int(d): int(s) for s, d in perm}
    for g in groups:
        for idx, r in enumerate(g):
            if idx in src_of:
                keys[r] = ("v", p.cells[g[src_of[idx]]])
            else:
                keys[r] = ("zero",)
    return Partition.from_keys(keys)


# -- the propagator -------------------------------------------------------

@dataclasses.dataclass
class CollectiveSite:
    """One collective eqn inside a shard_map body, with the statically
    inferred placement of its operand *before* the op — the name the
    census rule prints when a gather is unplanned."""

    primitive: str
    payload_bytes: int
    shape: Tuple[int, ...]
    dtype: str
    spec: str          # inferred operand placement ("replicated", ...)
    axes: Tuple[str, ...]

    def describe(self) -> str:
        return (f"{self.primitive} over {self.axes} on "
                f"{self.dtype}{list(self.shape)} "
                f"({self.payload_bytes} B/replica, operand {self.spec})")


def _is_jaxpr(x) -> bool:
    return isinstance(x, (jax.extend.core.Jaxpr,
                          jax.extend.core.ClosedJaxpr))


def _sub_jaxprs(params: Dict[str, Any]) -> List[Any]:
    subs = []
    for v in params.values():
        for leaf in jax.tree_util.tree_leaves(v, is_leaf=_is_jaxpr):
            if _is_jaxpr(leaf):
                subs.append(leaf)
    return subs


def _contains_rank_dependence(jaxpr) -> bool:
    names = graphs.COLLECTIVE_PRIMS | {"axis_index"}
    jx = jaxpr.jaxpr if isinstance(jaxpr, jax.extend.core.ClosedJaxpr) \
        else jaxpr
    return any(e.primitive.name in names for e in graphs.walk_jaxpr(jx))


def _aval_bytes(aval) -> int:
    return int(np.prod(aval.shape)) * aval.dtype.itemsize


def _axes_param(params: Dict[str, Any]):
    axes = params.get("axes", params.get("axis_name"))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(axes) if axes is not None else ()


class _Propagator:
    def __init__(self, ctx: _MeshCtx,
                 sites: Optional[List[CollectiveSite]] = None):
        self.ctx = ctx
        self.sites = sites

    def run(self, jaxpr, in_parts: Sequence[Partition],
            const_parts: Optional[Sequence[Partition]] = None
            ) -> List[Partition]:
        """Propagate partitions through an (open or closed) jaxpr.
        Returns the outvar partitions."""
        closed_consts = None
        if isinstance(jaxpr, jax.extend.core.ClosedJaxpr):
            closed_consts = jaxpr.consts
            jaxpr = jaxpr.jaxpr
        w = self.ctx.world
        env: Dict[Any, Partition] = {}
        if const_parts is None:
            const_parts = [Partition.replicated(w)] * len(jaxpr.constvars)
        for v, p in zip(jaxpr.constvars, const_parts):
            env[v] = p
        if len(in_parts) != len(jaxpr.invars):
            raise ValueError(
                f"arity mismatch: {len(in_parts)} partitions for "
                f"{len(jaxpr.invars)} invars")
        for v, p in zip(jaxpr.invars, in_parts):
            env[v] = p

        def read(atom) -> Partition:
            if isinstance(atom, jax.extend.core.Literal):
                return Partition.replicated(w)
            return env.get(atom, Partition.replicated(w))

        for eqn in jaxpr.eqns:
            outs = self._eqn(eqn, [read(a) for a in eqn.invars])
            for v, p in zip(eqn.outvars, outs):
                env[v] = p
        return [read(a) for a in jaxpr.outvars]

    # one eqn -> outvar partitions
    def _eqn(self, eqn, in_parts: List[Partition]) -> List[Partition]:
        ctx, w = self.ctx, self.ctx.world
        name = eqn.primitive.name
        params = eqn.params

        if name in _REDUCE_PRIMS or name in RESHARD_PRIMS \
                or name in ("ppermute",):
            axes = _axes_param(params)
            try:
                groups = ctx.groups(axes, params.get("axis_index_groups"))
            except (KeyError, ValueError):
                # malformed axis reference: spec rule reports it; stay
                # sound here
                return [Partition.varying(w) for _ in eqn.outvars]
            if self.sites is not None and name in graphs.COLLECTIVE_PRIMS:
                op = _meet_all(in_parts, w)
                aval = eqn.invars[0].aval
                self.sites.append(CollectiveSite(
                    primitive=name,
                    payload_bytes=graphs.eqn_payload_bytes(eqn),
                    shape=tuple(aval.shape),
                    dtype=str(aval.dtype),
                    spec=ctx.spec_str(op),
                    axes=axes))
            if name in _REDUCE_PRIMS:
                return [_reduce_part(p, groups, w) for p in in_parts]
            if name == "all_gather":
                return [_gather_part(p, groups, w) for p in in_parts]
            if name == "reduce_scatter":
                return [_scatter_part(p, groups, w) for p in in_parts]
            if name == "all_to_all":
                return [_all_to_all_part(p, groups, w) for p in in_parts]
            if name == "ppermute":
                return [_ppermute_part(p, groups, params["perm"], w)
                        for p in in_parts]
            # pgather etc.: placement semantics not modeled — varying
            return [Partition.varying(w) for _ in eqn.outvars]

        if name == "axis_index":
            axes = _axes_param(params)
            try:
                return [ctx.coord_partition(list(axes))]
            except KeyError:
                return [Partition.varying(w)]

        if name == "scan":
            return self._scan(eqn, in_parts)
        if name == "while":
            return self._while(eqn, in_parts)
        if name == "cond":
            return self._cond(eqn, in_parts)
        if name == "pjit":
            return self.run(params["jaxpr"], in_parts)

        subs = _sub_jaxprs(params)
        if len(subs) == 1:
            sub = subs[0]
            jx = sub.jaxpr if isinstance(
                sub, jax.extend.core.ClosedJaxpr) else sub
            if len(jx.invars) == len(eqn.invars):
                try:
                    outs = self.run(sub, in_parts)
                    if len(outs) == len(eqn.outvars):
                        return outs
                except ValueError:
                    pass
        if subs and any(_contains_rank_dependence(s) for s in subs):
            return [Partition.varying(w) for _ in eqn.outvars]
        meet = _meet_all(in_parts, w)
        return [meet for _ in eqn.outvars]

    def _scan(self, eqn, in_parts: List[Partition]) -> List[Partition]:
        params = eqn.params
        nc, nk = params["num_consts"], params["num_carry"]
        consts, carry = in_parts[:nc], list(in_parts[nc:nc + nk])
        xs = in_parts[nc + nk:]
        quiet = _Propagator(self.ctx, sites=None)
        body = params["jaxpr"]
        for _ in range(4 * self.ctx.world + 4):
            outs = quiet.run(body, consts + carry + xs)
            new = [c.meet(o) for c, o in zip(carry, outs[:nk])]
            if new == carry:
                break
            carry = new
        # final pass with the sound carry, recording sites once
        outs = self.run(body, consts + carry + xs)
        return list(carry) + list(outs[nk:])

    def _while(self, eqn, in_parts: List[Partition]) -> List[Partition]:
        params = eqn.params
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cconsts = in_parts[:cn]
        bconsts = in_parts[cn:cn + bn]
        carry = list(in_parts[cn + bn:])
        quiet = _Propagator(self.ctx, sites=None)
        for _ in range(4 * self.ctx.world + 4):
            pred = quiet.run(params["cond_jaxpr"], cconsts + carry)[0]
            outs = quiet.run(params["body_jaxpr"], bconsts + carry)
            new = [c.meet(o).meet(pred) for c, o in zip(carry, outs)]
            if new == carry:
                break
            carry = new
        self.run(params["body_jaxpr"], bconsts + carry)  # record sites
        return carry

    def _cond(self, eqn, in_parts: List[Partition]) -> List[Partition]:
        pred, ops = in_parts[0], in_parts[1:]
        outs = None
        for branch in eqn.params["branches"]:
            b_outs = self.run(branch, ops)
            outs = b_outs if outs is None else [
                a.meet(b) for a, b in zip(outs, b_outs)]
        return [o.meet(pred) for o in (outs or [])]


# -- shard_map analysis ---------------------------------------------------

@dataclasses.dataclass
class ArgSharding:
    """Static placement of one shard_map body argument."""

    index: int
    shape: Tuple[int, ...]          # LOCAL (per-device block) shape
    dtype: str
    local_bytes: int
    n_cells: int
    replication_factor: float
    spec: str

    def replicated_bytes(self, world: int) -> int:
        return self.local_bytes * (world - self.n_cells)


@dataclasses.dataclass
class ShardMapAnalysis:
    """Everything the ledger and the two sharding rules need from one
    shard_map eqn: per-arg placement, the propagated output partitions,
    and the collective census with inferred operand specs."""

    world: int
    mesh_axes: Dict[str, int]
    args: List[ArgSharding]
    out_parts: List[Partition]
    out_names: Tuple[Dict[int, Tuple[str, ...]], ...]
    sites: List[CollectiveSite]

    @property
    def argument_bytes(self) -> int:
        return sum(a.local_bytes for a in self.args)

    @property
    def replicated_bytes(self) -> int:
        return sum(a.replicated_bytes(self.world) for a in self.args)

    @property
    def unique_bytes(self) -> int:
        return sum(a.local_bytes * a.n_cells for a in self.args)

    def replicated_bytes_by_dtype(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for a in self.args:
            b = a.replicated_bytes(self.world)
            if b:
                out[a.dtype] = out.get(a.dtype, 0) + b
        return out

    def resharding_eqns(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.sites:
            if s.primitive in RESHARD_PRIMS:
                out[s.primitive] = out.get(s.primitive, 0) + 1
        return out


def _names_spec_str(names_dict: Dict[int, Tuple[str, ...]]) -> str:
    if not names_dict:
        return "replicated"
    return "sharded(" + ", ".join(
        f"dim{d}->{'*'.join(names_dict[d])}"
        for d in sorted(names_dict)) + ")"


def shard_map_eqns(jaxpr) -> List[Any]:
    """Every shard_map eqn anywhere in a (closed) jaxpr, including under
    pjit wrapper layers."""
    return graphs.prim_eqns(jaxpr, ("shard_map",))


def analyze_shard_map(eqn) -> ShardMapAnalysis:
    """Propagate partitions through one shard_map eqn's body.

    Body input partitions come from ``in_names`` alone (shard_map
    semantics: the names say how the global operand is laid out across
    the mesh, independent of outer context); captured consts are
    replicated."""
    params = eqn.params
    mesh = params["mesh"]
    axis_sizes = dict(mesh.shape)
    ctx = _MeshCtx(axis_sizes)
    body = params["jaxpr"]                       # open Jaxpr, LOCAL shapes
    in_names = params["in_names"]
    out_names = params["out_names"]

    in_parts = []
    for nm in in_names:
        try:
            in_parts.append(ctx.names_partition(dict(nm)))
        except KeyError:
            # axis name not in the mesh — spec rule reports it
            in_parts.append(Partition.varying(ctx.world))

    sites: List[CollectiveSite] = []
    prop = _Propagator(ctx, sites=sites)
    const_parts = [Partition.replicated(ctx.world)] * len(body.constvars)
    out_parts = prop.run(body, in_parts, const_parts=const_parts)

    args = []
    for i, (v, part) in enumerate(zip(body.invars, in_parts)):
        args.append(ArgSharding(
            index=i,
            shape=tuple(v.aval.shape),
            dtype=str(v.aval.dtype),
            local_bytes=_aval_bytes(v.aval),
            n_cells=part.n_cells,
            replication_factor=part.replication_factor(),
            spec=_names_spec_str(dict(in_names[i]))))
    for j, v in enumerate(body.constvars):
        args.append(ArgSharding(
            index=len(in_parts) + j,
            shape=tuple(v.aval.shape),
            dtype=str(v.aval.dtype),
            local_bytes=_aval_bytes(v.aval),
            n_cells=1,
            replication_factor=ctx.world,
            spec="replicated(const)"))

    return ShardMapAnalysis(
        world=ctx.world, mesh_axes=dict(ctx.axis_sizes), args=args,
        out_parts=out_parts, out_names=tuple(dict(n) for n in out_names),
        sites=sites)


def analyze_sharding(closed_jaxpr) -> List[ShardMapAnalysis]:
    """Analyze every shard_map in an entry point's traced jaxpr."""
    return [analyze_shard_map(e) for e in shard_map_eqns(closed_jaxpr)]


# -- spec-consistency checks ----------------------------------------------

def check_shard_map_specs(eqn,
                          expected_mesh_axes: Optional[Dict[str, int]]
                          = None,
                          analysis: Optional[ShardMapAnalysis] = None
                          ) -> List[str]:
    """Static spec-vs-mesh consistency for one shard_map eqn.  Returns
    human-readable problem strings (empty = consistent):

    - the eqn's mesh axes must match ``expected_mesh_axes`` (the mesh
      ``topology.make_mesh`` was asked for) when given;
    - every axis named in in/out specs must exist on the mesh;
    - globally, every sharded dim must divide evenly across its axes
      (outer eqn operands carry GLOBAL shapes).

    Output specs that *over-claim* agreement are a separate, declared
    count — see :func:`divergent_output_claims`.
    """
    params = eqn.params
    mesh = params["mesh"]
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    problems: List[str] = []

    if expected_mesh_axes is not None and \
            axis_sizes != {k: int(v) for k, v in expected_mesh_axes.items()}:
        problems.append(
            f"shard_map mesh axes {axis_sizes} != expected "
            f"{dict(expected_mesh_axes)}")

    def _check_names(kind, names, vars_, global_shapes: bool):
        for i, (nm, v) in enumerate(zip(names, vars_)):
            nm = dict(nm)
            for d, axes in nm.items():
                missing = [a for a in axes if a not in axis_sizes]
                if missing:
                    problems.append(
                        f"{kind}[{i}] names unknown mesh axis "
                        f"{missing} (mesh has {list(axis_sizes)})")
                    continue
                factor = int(np.prod([axis_sizes[a] for a in axes]))
                shape = tuple(v.aval.shape)
                if global_shapes:
                    if d >= len(shape) or shape[d] % factor != 0:
                        dim = shape[d] if d < len(shape) else "<missing>"
                        problems.append(
                            f"{kind}[{i}] dim {d} (= {dim}) not divisible "
                            f"by axes {tuple(axes)} (x{factor})")

    _check_names("in_specs", params["in_names"], eqn.invars, True)
    _check_names("out_specs", params["out_names"], eqn.outvars, True)
    return problems


def divergent_output_claims(eqn,
                            analysis: Optional[ShardMapAnalysis] = None
                            ) -> List[str]:
    """Outputs whose spec claims MORE agreement than the propagated body
    partition guarantees (e.g. ``out_specs`` says replicated, the body
    value still varies across the data axis).  With ``check_vma=False``
    the runtime silently keeps one replica's value, so this is the
    silent-wrong-answer class — but it is also how non-synced BatchNorm
    running stats intentionally behave on the DDP entry points, so the
    rule pins a *declared count* per entry point instead of flat-zero.

    One message per divergent output."""
    params = eqn.params
    axis_sizes = {k: int(v) for k, v in dict(params["mesh"].shape).items()}
    if analysis is None:
        analysis = analyze_shard_map(eqn)
    ctx = _MeshCtx(axis_sizes)
    claims: List[str] = []
    for i, (nm, part) in enumerate(zip(analysis.out_names,
                                       analysis.out_parts)):
        claimed_axes = sorted({a for t in nm.values() for a in t})
        try:
            claimed = ctx.names_partition(nm) if nm else \
                Partition.replicated(ctx.world)
        except KeyError:
            continue  # unknown axis: check_shard_map_specs reports it
        # sound iff the claim refines what the body guarantees: every
        # pair of ranks the claim merges must be merged by the
        # propagated partition too
        rep: Dict[int, int] = {}
        for r in range(ctx.world):
            c = claimed.cells[r]
            if c in rep:
                if part.cells[rep[c]] != part.cells[r]:
                    out_v = eqn.outvars[i] if i < len(eqn.outvars) else None
                    what = (f"{out_v.aval.dtype}{list(out_v.aval.shape)}"
                            if out_v is not None and
                            hasattr(out_v, "aval") else f"output {i}")
                    claim = ("replicated" if not nm else
                             f"sharded over {claimed_axes}")
                    claims.append(
                        f"out_specs[{i}] claims {what} is {claim} but the "
                        f"propagated body value {ctx.spec_str(part)} — "
                        f"a collective is missing before the return "
                        f"(check_vma=False hides this at runtime)")
                    break
            else:
                rep[c] = r
    return claims


# -- the replication ledger ----------------------------------------------

def entry_point_sharding_record(ep, top_n: int = 8) -> Dict[str, Any]:
    """The replication ledger for one entry point, as a schema-v13
    ``kind: sharding`` record.

    ``argument_bytes`` counts the shard_map body's LOCAL operands (incl.
    captured consts) — the same accounting as
    ``memory.jaxpr_live_bytes``'s ``argument_bytes``, so the two planes
    cross-check.  ``replicated_bytes`` is the world-total of deletable
    duplicate bytes: ``sum(local_bytes * (world - n_cells))``; the
    identity ``unique_bytes + replicated_bytes == world *
    argument_bytes`` is enforced by ``validate_sharding_record``.

    Entry points that trace no shard_map (the serving engines) raise a
    bare ``RuntimeError`` — the documented CLI skip-gate class.
    """
    graph = ep.graph()
    eqns = shard_map_eqns(graph.jaxpr)
    if not eqns:
        raise RuntimeError(
            f"entry point {ep.name!r} traces no shard_map; the "
            f"replication ledger does not apply")
    analyses = [analyze_shard_map(e) for e in eqns]
    worlds = {a.world for a in analyses}
    if len(worlds) != 1:
        raise ValueError(
            f"entry point {ep.name!r} mixes shard_map worlds {worlds}")
    world = worlds.pop()
    mesh_axes = analyses[0].mesh_axes

    by_dtype: Dict[str, int] = {}
    resharding: Dict[str, int] = {}
    all_args: List[Tuple[ArgSharding, int]] = []
    for a in analyses:
        for dt, b in a.replicated_bytes_by_dtype().items():
            by_dtype[dt] = by_dtype.get(dt, 0) + b
        for prim, n in a.resharding_eqns().items():
            resharding[prim] = resharding.get(prim, 0) + n
        for arg in a.args:
            all_args.append((arg, arg.replicated_bytes(world)))

    all_args.sort(key=lambda t: t[1], reverse=True)
    top = [{
        "index": arg.index,
        "shape": list(arg.shape),
        "dtype": arg.dtype,
        "local_bytes": arg.local_bytes,
        "replication_factor": arg.replication_factor,
        "spec": arg.spec,
    } for arg, b in all_args[:top_n] if b > 0]

    argument_bytes = sum(a.argument_bytes for a in analyses)
    replicated = sum(a.replicated_bytes for a in analyses)
    unique = sum(a.unique_bytes for a in analyses)
    # schema v15: zero EPs name their stage in the registry name
    # (ddp_resnet18_o2_zero3, ddp_mlp_overlap_zero2) — stamp it so the
    # ledger says which stage its replicated_bytes claim measured
    zero_m = re.search(r"zero([123])", ep.name)
    rec = {
        "kind": "sharding",
        "entry_point": ep.name,
        "source": "jaxpr",
        "world": world,
        "mesh_axes": {k: int(v) for k, v in mesh_axes.items()},
        "shard_maps": len(analyses),
        "argument_bytes": argument_bytes,
        "unique_bytes": unique,
        "replicated_bytes": replicated,
        "replicated_bytes_by_dtype": by_dtype,
        "replicated_fraction": (
            replicated / (world * argument_bytes)
            if argument_bytes else 0.0),
        "top_replicated": top,
        "resharding_eqns": resharding,
    }
    if zero_m:
        rec["zero_stage"] = int(zero_m.group(1))
    return rec
