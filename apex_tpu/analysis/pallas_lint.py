"""Static precondition lint over the Pallas kernel family.

The TPU kernels in :mod:`apex_tpu.ops` all follow the same discipline:
pad the operand to a whole number of ``(BLOCK_ROWS, LANES)`` tiles,
launch a 1-D (or small N-D) grid over them, and alias the in-place
operands onto their outputs.  Every one of those conventions has a
silent failure mode — a block shape that does not divide the padded
operand reads garbage rows, an index map that steps past the last
block writes out of bounds (interpret mode masks this; hardware does
not), and a double-aliased output is two kernels racing one buffer.

This module checks the conventions *statically*: it intercepts
``pl.pallas_call`` while tracing each kernel's public wrapper on tiny
operands, records every call's grid/specs/aliases as a
:class:`KernelSite`, and lints the sites without ever executing the
kernel on hardware.  It is the net under ROADMAP item 1a's
paged-attention kernel — that kernel will be the first one written
against these checks (tests/test_pallas_lint.py runs them tier-1).

Checks per site:

- **block divisibility**: every blocked operand's (padded) shape must
  divide by its ``BlockSpec`` block shape — the kernels pre-pad via
  ``to_2d``/``_pad2`` exactly so this holds, and a refactor that drops
  the pad reads partial tiles;
- **index-map bounds**: the block index the spec's ``index_map``
  returns at every grid corner must stay within
  ``[0, shape[d] // block[d])`` for every dim;
- **aliasing declared exactly once**: ``input_output_aliases`` maps
  distinct inputs to distinct outputs, indices in range, and the
  aliased pair agrees on shape + dtype (donating a buffer of the
  wrong size is a lowering error on TPU and silent corruption in
  interpret mode).
"""

from __future__ import annotations

import contextlib
import functools
import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["KernelSite", "capture_kernel_sites", "check_site",
           "collect_kernel_sites", "lint_pallas_kernels"]


@dataclass
class KernelSite:
    """One recorded ``pl.pallas_call`` launch: the static spec plus the
    operand shapes it was invoked with."""
    name: str
    grid: Tuple[int, ...]
    in_specs: List[Any]
    out_specs: List[Any]
    in_shapes: List[Tuple[Tuple[int, ...], str]]
    out_shapes: List[Tuple[Tuple[int, ...], str]]
    input_output_aliases: Dict[int, int] = field(default_factory=dict)

    def describe(self) -> str:
        return (f"{self.name}: grid={self.grid}, "
                f"{len(self.in_shapes)} in / {len(self.out_shapes)} out, "
                f"aliases={dict(self.input_output_aliases)}")


def _as_seq(x) -> List[Any]:
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _kernel_name(fn) -> str:
    while isinstance(fn, functools.partial):
        fn = fn.func
    return getattr(fn, "__name__", repr(fn))


@contextlib.contextmanager
def capture_kernel_sites(into: List[KernelSite]) -> Iterator[None]:
    """Patch ``pallas.pallas_call`` so every launch traced inside the
    context appends a :class:`KernelSite` to ``into``, then delegates
    to the real implementation.  The ops modules all bind the *module*
    (``from jax.experimental import pallas as pl``), so one patch
    covers every kernel file.  Callers must clear the jitted wrappers'
    trace caches first or a warm cache skips the trace entirely —
    :func:`collect_kernel_sites` does both."""
    from jax.experimental import pallas as pallas_mod
    real = pallas_mod.pallas_call

    def record(kernel, *call_args, **kw):
        inner = real(kernel, *call_args, **kw)

        def run(*args):
            into.append(KernelSite(
                name=_kernel_name(kernel),
                grid=tuple(int(g) for g in _as_seq(kw.get("grid"))),
                in_specs=_as_seq(kw.get("in_specs")),
                out_specs=_as_seq(kw.get("out_specs")),
                in_shapes=[(tuple(int(d) for d in a.shape),
                            str(a.dtype)) for a in args],
                out_shapes=[(tuple(int(d) for d in s.shape),
                             str(np.dtype(s.dtype)))
                            for s in _as_seq(kw.get("out_shape"))],
                input_output_aliases=dict(
                    kw.get("input_output_aliases") or {})))
            return inner(*args)
        return run

    pallas_mod.pallas_call = record
    try:
        yield
    finally:
        pallas_mod.pallas_call = real


def _block_shape(spec) -> Optional[Tuple[int, ...]]:
    bs = getattr(spec, "block_shape", None)
    if bs is None:
        return None
    return tuple(int(b) for b in bs)


def _check_operand(site: KernelSite, kind: str, i: int, spec,
                   shape: Tuple[int, ...], problems: List[str]):
    block = _block_shape(spec)
    if block is None:
        return                       # scalar/SMEM spec: nothing blocked
    if len(block) != len(shape):
        problems.append(
            f"{site.name}: {kind}[{i}] block shape {block} rank != "
            f"operand shape {shape}")
        return
    n_blocks = []
    for d, (s, b) in enumerate(zip(shape, block)):
        if b < 1:
            problems.append(
                f"{site.name}: {kind}[{i}] block dim {d} is {b}")
            return
        if s % b != 0:
            problems.append(
                f"{site.name}: {kind}[{i}] dim {d} (= {s}) not "
                f"divisible by block {b} — the kernel reads/writes "
                f"partial tiles (missing pad?)")
        n_blocks.append(max(1, s // b))
    index_map = getattr(spec, "index_map", None)
    if index_map is None or not site.grid:
        return
    # evaluate the index map at every grid corner: the extremes bound
    # the affine maps these kernels use, so a step past the last block
    # shows up at a corner
    corners = itertools.product(
        *(sorted({0, g - 1}) for g in site.grid))
    for corner in corners:
        try:
            idx = index_map(*corner)
        except Exception as e:       # a map that cannot even evaluate
            problems.append(
                f"{site.name}: {kind}[{i}] index_map failed at grid "
                f"point {corner}: {e}")
            return
        idx = tuple(int(v) for v in _as_seq(idx))
        if len(idx) != len(block):
            problems.append(
                f"{site.name}: {kind}[{i}] index_map returns "
                f"{len(idx)} indices for a rank-{len(block)} block")
            return
        for d, (v, n) in enumerate(zip(idx, n_blocks)):
            if not (0 <= v < n):
                problems.append(
                    f"{site.name}: {kind}[{i}] index_map at grid "
                    f"point {corner} returns block index {v} for dim "
                    f"{d} — out of [0, {n}) (shape {shape}, block "
                    f"{block})")


def check_site(site: KernelSite) -> List[str]:
    """Lint one recorded launch; returns problem strings (empty =
    clean)."""
    problems: List[str] = []
    for i, (spec, (shape, _)) in enumerate(zip(site.in_specs,
                                               site.in_shapes)):
        _check_operand(site, "in_specs", i, spec, shape, problems)
    for i, (spec, (shape, _)) in enumerate(zip(site.out_specs,
                                               site.out_shapes)):
        _check_operand(site, "out_specs", i, spec, shape, problems)
    if len(site.in_specs) != len(site.in_shapes):
        problems.append(
            f"{site.name}: {len(site.in_specs)} in_specs for "
            f"{len(site.in_shapes)} operands")
    if len(site.out_specs) != len(site.out_shapes):
        problems.append(
            f"{site.name}: {len(site.out_specs)} out_specs for "
            f"{len(site.out_shapes)} outputs")
    # aliasing: each output donated to at most ONE input, indices in
    # range, shape/dtype agreement on the pair
    seen_out: Dict[int, int] = {}
    for in_idx, out_idx in site.input_output_aliases.items():
        in_idx, out_idx = int(in_idx), int(out_idx)
        if not (0 <= in_idx < len(site.in_shapes)):
            problems.append(
                f"{site.name}: alias input index {in_idx} out of "
                f"range (kernel has {len(site.in_shapes)} inputs)")
            continue
        if not (0 <= out_idx < len(site.out_shapes)):
            problems.append(
                f"{site.name}: alias output index {out_idx} out of "
                f"range (kernel has {len(site.out_shapes)} outputs)")
            continue
        if out_idx in seen_out:
            problems.append(
                f"{site.name}: output {out_idx} aliased twice "
                f"(inputs {seen_out[out_idx]} and {in_idx}) — two "
                f"refs racing one buffer")
            continue
        seen_out[out_idx] = in_idx
        in_shape, in_dt = site.in_shapes[in_idx]
        out_shape, out_dt = site.out_shapes[out_idx]
        if in_shape != out_shape or in_dt != out_dt:
            problems.append(
                f"{site.name}: alias {in_idx}->{out_idx} shape/dtype "
                f"mismatch ({in_dt}{list(in_shape)} vs "
                f"{out_dt}{list(out_shape)})")
    return problems


# -- driving the real kernel family ---------------------------------------

def _clear_jit_caches(*modules):
    """Defeat ``jax.jit``'s trace cache on every wrapper in the given
    modules: a warm cache means ``pallas_call`` never re-runs and the
    recorder sees nothing."""
    for mod in modules:
        for v in vars(mod).values():
            clear = getattr(v, "clear_cache", None)
            if callable(clear):
                try:
                    clear()
                except Exception:
                    pass


def collect_kernel_sites() -> List[KernelSite]:
    """Trace every public kernel wrapper in ``ops/pallas_*.py`` on tiny
    operands and return the recorded launch sites.  Runs in interpret
    mode on CPU (the kernels already route there off-TPU), so this is
    cheap enough for a tier-1 test."""
    import jax
    import jax.numpy as jnp
    from ..ops import (pallas_adam, pallas_common, pallas_flash_attention,
                       pallas_lamb, pallas_layer_norm,
                       pallas_multi_tensor, pallas_syncbn)

    _clear_jit_caches(pallas_adam, pallas_flash_attention, pallas_lamb,
                      pallas_layer_norm, pallas_multi_tensor,
                      pallas_syncbn)
    sites: List[KernelSite] = []
    rng = np.random.RandomState(18)
    f32 = lambda *s: jnp.asarray(rng.randn(*s), jnp.float32)
    with capture_kernel_sites(sites):
        # fused Adam, fp32-only and with the fused half write-out (the
        # two out_specs arities)
        n = 1000
        p, m, v, g = f32(n), np.abs(f32(n)), np.abs(f32(n)), f32(n)
        pallas_adam.fused_adam(p, m, v, g, 1e-3, 1.0, 0.9, 0.999, 1e-8,
                               False, 0.0)
        pallas_adam.fused_adam(p, m, v, g, 1e-3, 1.0, 0.9, 0.999, 1e-8,
                               False, 0.01, half_dtype=jnp.bfloat16)
        # LAMB, both stages (stage1 aliases 2 of 3 outputs, stage2 1/1)
        pallas_lamb.lamb_stage1(g, p, m, v, jnp.float32(1.0),
                                jnp.float32(1.0), jnp.float32(1.0),
                                0.9, 0.999, 1.0, 1e-6, 0.01, True)
        pallas_lamb.lamb_stage2(p, g, jnp.ones_like(p),
                                jnp.float32(1e-3))
        # layer norm fwd + bwd (column-stat specs next to row blocks)
        x2 = f32(8, 32)
        w, b = f32(32), f32(32)
        y, mean, inv = pallas_layer_norm.forward(x2, w, b, 1e-5)
        pallas_layer_norm.backward(f32(8, 32), x2, w, b, mean, inv)
        # multi-tensor family (SMEM scalar + finite-flag accumulators)
        tree = {"a": f32(300), "b": f32(40)}
        pallas_multi_tensor.multi_tensor_scale(tree, 2.0)
        pallas_multi_tensor.multi_tensor_axpby(1.0, 2.0, tree, tree)
        pallas_multi_tensor.multi_tensor_l2norm(tree)
        # fused BN apply fwd + bwd (NCHW rows, per-row stat columns)
        x4 = f32(2, 4, 6, 6)
        mean4, var4 = f32(4), np.abs(f32(4)) + 0.5
        w4, b4 = f32(4), f32(4)
        jax.grad(lambda xx: jnp.sum(
            pallas_syncbn.batch_norm_apply_fused(
                xx, mean4, var4, w4, b4, 1e-5)))(x4)
        # flash attention fwd + bwd (the 3-kernel family with its
        # blocked T x D streaming)
        q = f32(1, 2, 128, 64)
        k = f32(1, 2, 128, 64)
        vv = f32(1, 2, 128, 64)
        jax.grad(lambda a: jnp.sum(
            pallas_flash_attention.flash_attention(a, k, vv,
                                                   causal=True)))(q)
    return sites


def lint_pallas_kernels() -> Tuple[List[KernelSite], List[str]]:
    """Collect every launch site and lint them all.  Returns
    ``(sites, problems)`` — tests assert sites are non-trivial AND
    problems empty, so a refactor that silently stops launching
    kernels fails as loudly as one that breaks a precondition."""
    sites = collect_kernel_sites()
    problems: List[str] = []
    for s in sites:
        problems.extend(check_site(s))
    return sites, problems
