"""The core rule set: every hot-path invariant the repo has paid to
learn, pinned mechanically.

Expectation schema (per entry point, all keys optional — a rule only
runs where its key is present):

``host_transfer`` (always on; opt out with ``allow_host_transfers``)
    No host-boundary primitive may appear in a jitted hot graph.

``donation``::

    {"expect_donated": ("ids", "cache", "keys"),   # must be aliased
     "forbid_donated": ("temps",),                 # extra local bans
     "min_aliased": None}                          # default: donated leaf count

    The global blocklist (``serving.DONATION_BLOCKLIST``: per-slot
    length vectors ``cur_len``/``n_new``) is enforced on every donation
    entry point — donating that argnum class corrupted executables
    reloaded from the persistent XLA:CPU compile cache (PR 2).

``amp``::

    {"opt_level": "O2", "conv_dtype": "bfloat16", "dot_dtype": "bfloat16",
     "min_convs": 40, "min_dots": 0, "dot_min_elems": 256}

    ``conv_dtype``/``dot_dtype`` of ``None`` skips that op family.  The
    ``min_*`` floors keep the rule non-vacuous: an empty graph is a
    finding, not a pass.

``layout``::

    {"min_activation_elems": 12288, "allowed_6d_rearranges": 0}

    No transpose on activation-sized tensors in channels-last graphs;
    the 6-D block rearrange inside space_to_depth is the one sanctioned
    exception (budgeted, not open-ended).

``flops``::

    {"expected_flops": 3.8e6, "rtol": 0.05,
     "max_fp32_matmul_fraction": 0.02, "min_matmul_flops": 1e6}

    Analytic FLOP accounting (``observability.costmodel``):
    ``expected_flops`` pins the whole-graph count within ``rtol`` (an
    unexplained delta means the graph grew or lost work nobody
    budgeted); ``max_fp32_matmul_fraction`` caps the share of dot/conv
    FLOPs running on fp32 operands — under a bf16 compute policy a
    silent upcast moves flops into fp32 exactly where the arithmetic
    is, even when each individual op dodges the amp-dtype rule's
    element thresholds.  ``min_matmul_flops`` is the vacuity floor.

``memory``::

    {"budget_bytes": 500_000_000,
     "max_live_to_argument_ratio": 4.0,
     "temp_budget_bytes_by_dtype": {"float32": 250_000_000}}

    Analytic peak-live-bytes budgets (``observability.memory.
    jaxpr_live_bytes`` — a static last-use scan, no compile on the
    lint path).  ``budget_bytes`` caps the absolute peak;
    ``max_live_to_argument_ratio`` caps peak live bytes relative to
    the graph's argument+const bytes (portable across model sizes: a
    train step that suddenly holds a second copy of everything doubles
    the ratio no matter the model); the per-dtype temp budgets catch
    an fp32 upcast doubling fp32 temp bytes under O2 while the bf16
    peak is unchanged.

``collectives``::

    {"counts": {"psum": 4}, "payload_bytes": 40038408,
     "payload_bytes_by_primitive": {"psum": 40038408},
     "interleaving": {"min_payload_bytes": 1056,
                      "min_matmuls_after": 1}}

    Exact comm accounting: any collective primitive not named in
    ``counts`` is budgeted at zero, and the total on-wire payload must
    match to the byte (``payload_tolerance`` relaxes it when needed).
    ``payload_bytes_by_primitive`` (optional) additionally pins the
    per-primitive split — for the hierarchical DDP topology that is the
    fabric-level split: the bucket psum (or compressed bf16 all_gather)
    payload is exactly the DCN hop, so a bucket sneaking a full-size
    cross-host psum flags even if the total happens to balance.
    ``parallel.plan_collective_expectations`` derives all three fields
    from ``allreduce_comm_plan``.

    ``interleaving`` (optional) is the overlapped-schedule pin (PR 14):
    in jaxpr program order, the FIRST gradient-bucket collective (the
    first collective eqn moving at least ``min_payload_bytes`` — which
    separates grad buckets from the step's 4-byte scalar psums) must
    appear BEFORE the last conv/dot eqn, with at least
    ``min_matmuls_after`` matmul eqns after it.  A reduce-after-
    backward schedule has identical counts and payloads — only eqn
    POSITIONS distinguish it — so this is the one check that can tell
    the two apart statically.
    ``parallel.overlap_collective_expectations`` derives it (and the
    census) from ``overlap_comm_schedule``.

``numerics``::

    {"baseline": "ddp_resnet18_o2", "enabled": True,
     "extra_collectives": {"psum": 1}, "extra_payload_bytes": 520}

    The numerics-instrumentation pin (PR 9): enabled ⇒ zero host
    transfers + collective census exactly the baseline's plus the
    digest plan's delta; disabled ⇒ the step traces to the
    byte-identical jaxpr of the baseline (no residue).

``supervisor``::

    {"baseline": "ddp_resnet18_o2", "enabled": True}

    The operational-plane pin (PR 10): a run-supervised step must
    trace to the BYTE-IDENTICAL jaxpr of its unsupervised baseline
    and contain zero host-transfer primitives — enabled or disabled,
    because the supervisor consumes host-side flush points only and
    ``RunSupervisor.wrap_step`` is an identity by contract.

``sharding``::

    {"mesh_axes": {"data": 8},
     "divergent_outputs": 40,            # default 0
     "max_replicated_bytes": None}       # optional budget

    Spec-vs-mesh consistency (PR 18): the traced ``shard_map``'s mesh
    axes must be exactly what ``topology.make_mesh`` was asked for,
    every axis named in in/out specs must exist, every sharded dim
    must divide across its axes, and the number of outputs whose spec
    claims MORE agreement than ``analysis.sharding``'s propagated
    partition guarantees is pinned (``divergent_outputs`` — 40 on the
    resnet DDP entry points: two unsynced BatchNorm running stats per
    BN layer, the documented non-SyncBN semantics; any OTHER count,
    up or down, is a finding, so a new missing collective flags and a
    fixed sync forces a ratchet).  ``max_replicated_bytes`` caps the
    replication ledger's world-total duplicate bytes — the budget
    ZeRO-2/3 stages (ROADMAP item 2) will ratchet down.

``resharding``::

    {"planned": {"reduce_scatter": [38400, 22344088],
                 "all_gather": [9600, 5586022]},
     "budget": {"all_gather": 0}}        # extra eqns allowed, default 0

    The resharding census (PR 18): every placement-changing collective
    (``all_gather``/``all_to_all``/``reduce_scatter``/``pgather``) in
    the hot graph must be explained — matched one-for-one by payload
    against the comm plan's per-eqn list
    (``parallel.plan_resharding_expectations`` derives it from
    ``allreduce_comm_plan`` / ``overlap_comm_schedule``) or covered by
    a declared per-primitive ``budget``.  An unplanned gather (the
    classic "XLA silently replicated my shard") is an error naming the
    culprit operand's shape, dtype, payload, and statically inferred
    spec; a planned payload missing from the graph flags too
    (plan/graph desync).  psum/pmax/pmin stay the collective rule's
    business — a reduce changes values, not placement, which is why
    an unplanned all-gather can hide behind an identical psum census.
"""

from __future__ import annotations

from collections import Counter
from typing import List

from .core import Rule, Finding, register_rule
from . import graphs as G

__all__ = ["HostTransferRule", "DonationRule", "AmpDtypeRule",
           "LayoutRule", "CollectiveRule", "FlopAccountingRule",
           "MemoryBudgetRule", "NumericsRule", "SupervisorRule",
           "SpecConsistencyRule", "ReshardingCensusRule"]


@register_rule
class HostTransferRule(Rule):
    """No device_get/callback/transfer primitives inside jitted hot
    graphs — each one is a per-dispatch host round-trip."""

    name = "host-transfer"
    expect_key = None                        # unconditional

    def applies(self, ep):
        return not ep.expect.get("allow_host_transfers", False)

    def check(self, ep, graph) -> List[Finding]:
        hits = Counter(e.primitive.name
                       for e in G.host_transfer_eqns(graph.jaxpr))
        return [self.finding(
            ep, f"host-transfer primitive {prim!r} appears {n}x in the "
                f"jitted graph — a per-dispatch host sync",
            primitive=prim, count=n) for prim, n in sorted(hits.items())]


@register_rule
class DonationRule(Rule):
    """Every buffer the entry point promises to donate is actually
    aliased in the lowered module; blocklisted per-slot length vectors
    are never donated; no donated buffer is shared (double donation)."""

    name = "donation"
    expect_key = "donation"

    def check(self, ep, graph) -> List[Finding]:
        from ..serving import DONATION_BLOCKLIST
        want = ep.expect["donation"]
        out: List[Finding] = []
        if graph.arg_names is None:
            return [self.finding(
                ep, "donation expectation without arg_names — cannot "
                    "map donated buffers to arguments")]
        donated, partial = G.donated_arg_names(graph.lowered,
                                               graph.arg_names)
        for name in want.get("expect_donated", ()):
            if name not in donated:
                out.append(self.finding(
                    ep, f"argument {name!r} must be donated (multi-GB "
                        f"buffer mutated every dispatch) but the "
                        f"lowering does not alias it", argument=name))
        forbid = tuple(want.get("forbid_donated", ())) + \
            tuple(DONATION_BLOCKLIST)
        for name in forbid:
            if name in donated:
                blocked = name in DONATION_BLOCKLIST
                out.append(self.finding(
                    ep, f"argument {name!r} is donated but "
                        + ("is on the donation blocklist (per-slot "
                           "length vectors corrupt executables reloaded "
                           "from the persistent XLA compile cache — "
                           "PR 2 gotcha)" if blocked else
                           "this entry point forbids donating it"),
                    argument=name, blocklisted=blocked))
        for name in partial:
            out.append(self.finding(
                ep, f"argument {name!r} is only partially donated — "
                    f"some leaves alias, some keep a second copy alive",
                argument=name))
        # the lowering must honor every requested donation
        import jax
        args_info, _ = graph.lowered.args_info
        n_donated = sum(bool(i.donated)
                        for i in jax.tree_util.tree_leaves(args_info))
        min_aliased = want.get("min_aliased")
        if min_aliased is None:
            min_aliased = n_donated
        n_aliased = G.aliased_output_count(graph.stablehlo)
        if n_aliased < min_aliased:
            out.append(self.finding(
                ep, f"lowering aliases {n_aliased} buffers but "
                    f"{min_aliased} donations were requested — XLA "
                    f"silently dropped some (both copies stay alive)",
                aliased=n_aliased, requested=min_aliased))
        if graph.example_args is not None:
            for dup in G.duplicate_donated_leaves(
                    graph.lowered, graph.arg_names, graph.example_args):
                out.append(self.finding(
                    ep, f"double donation: {dup} — XLA rejects donating "
                        f"one buffer twice (per-layer cache allocation "
                        f"required; no dict(layer) shallow copies)",
                    duplicate=dup))
        return out


@register_rule
class AmpDtypeRule(Rule):
    """Conv/matmul operand dtypes match the O-level policy — forward,
    dgrad, and wgrad.  A single silently-upcast fp32 conv halves MXU
    rate and doubles HBM traffic on that op; fp32 accumulation belongs
    in ``preferred_element_type``, not operand upcasts."""

    name = "amp-dtype"
    expect_key = "amp"

    def check(self, ep, graph) -> List[Finding]:
        want = ep.expect["amp"]
        out: List[Finding] = []
        lvl = want.get("opt_level", "?")

        conv_dtype = want.get("conv_dtype")
        if conv_dtype is not None:
            convs = G.conv_eqns(graph.jaxpr)
            floor = want.get("min_convs", 1)
            if len(convs) < floor:
                out.append(self.finding(
                    ep, f"vacuous check: expected >= {floor} convs "
                        f"(fwd+dgrad+wgrad) in the {lvl} step, traced "
                        f"{len(convs)}", convs=len(convs), floor=floor))
            bad = Counter(
                (str(e.invars[0].aval.dtype), str(e.invars[1].aval.dtype))
                for e in convs
                if not all(str(v.aval.dtype) == conv_dtype
                           for v in e.invars[:2]))
            for (lhs, rhs), n in sorted(bad.items()):
                out.append(self.finding(
                    ep, f"{n} conv(s) with ({lhs}, {rhs}) operands in "
                        f"the {lvl} step — policy requires {conv_dtype} "
                        f"(silent upcast)",
                    lhs=lhs, rhs=rhs, count=n, expected=conv_dtype))

        dot_dtype = want.get("dot_dtype")
        if dot_dtype is not None:
            dots = G.large_dot_eqns(graph.jaxpr,
                                    want.get("dot_min_elems", 256))
            floor = want.get("min_dots", 1)
            if len(dots) < floor:
                out.append(self.finding(
                    ep, f"vacuous check: expected >= {floor} large dots "
                        f"in the {lvl} step, traced {len(dots)}",
                    dots=len(dots), floor=floor))
            bad = Counter(
                tuple(str(v.aval.dtype) for v in e.invars) for e in dots
                if not all(str(v.aval.dtype) == dot_dtype
                           for v in e.invars))
            for dts, n in sorted(bad.items()):
                out.append(self.finding(
                    ep, f"{n} large dot(s) with {dts} operands in the "
                        f"{lvl} step — policy requires {dot_dtype}",
                    operands=list(dts), count=n, expected=dot_dtype))
        return out


@register_rule
class LayoutRule(Rule):
    """Channels-last graphs stay transpose-free on activation-sized
    tensors — the whole point of the NHWC mode; a layout leak pays a
    relayout on every step."""

    name = "layout"
    expect_key = "layout"

    def check(self, ep, graph) -> List[Finding]:
        want = ep.expect["layout"]
        min_elems = want["min_activation_elems"]
        out: List[Finding] = []
        big = G.transpose_eqns(graph.jaxpr, min_elems)
        # the 6-D block rearrange inside F.space_to_depth is the one
        # sanctioned activation transpose (forward-only); it gets a
        # budget, not a blanket pass
        six_d = [e for e in big if e.invars[0].aval.ndim == 6]
        other = [e for e in big if e.invars[0].aval.ndim != 6]
        for e in other:
            out.append(self.finding(
                ep, f"activation-sized transpose "
                    f"{tuple(e.invars[0].aval.shape)} "
                    f"(permutation {e.params.get('permutation')}) in a "
                    f"channels-last graph — layout leak",
                shape=list(map(int, e.invars[0].aval.shape)),
                permutation=list(e.params.get("permutation", ()))))
        budget = want.get("allowed_6d_rearranges", 0)
        if len(six_d) > budget:
            out.append(self.finding(
                ep, f"{len(six_d)} 6-D block rearranges, budget is "
                    f"{budget} (space_to_depth runs forward-only; a "
                    f"second copy means gradient flows through the "
                    f"rearrange)", count=len(six_d), budget=budget))
        return out


@register_rule
class FlopAccountingRule(Rule):
    """The analytic FLOP count stays explained: totals within a pinned
    tolerance, and under a reduced-precision policy no meaningful
    share of matmul flops runs in fp32.  This is the flops-weighted
    twin of the amp-dtype rule: that one counts *ops*, this one counts
    *work* — a single upcast conv carrying half the step's FLOPs flags
    here even if 39 other convs are clean."""

    name = "flop-accounting"
    expect_key = "flops"

    def check(self, ep, graph) -> List[Finding]:
        from ..observability import costmodel
        want = ep.expect["flops"]
        out: List[Finding] = []
        cost = ep.cost() if hasattr(ep, "cost") \
            else costmodel.jaxpr_cost(graph.jaxpr)
        expected = want.get("expected_flops")
        if expected is not None:
            rtol = want.get("rtol", 0.05)
            if expected <= 0:
                out.append(self.finding(
                    ep, f"expected_flops must be > 0, got {expected}"))
            elif abs(cost.flops - expected) > rtol * expected:
                out.append(self.finding(
                    ep, f"unexplained FLOP delta: analytic count is "
                        f"{cost.flops:.4g}, expected {expected:.4g} "
                        f"(+/- {rtol:.0%}) — the graph gained or lost "
                        f"arithmetic nobody budgeted",
                    flops=cost.flops, expected_flops=expected,
                    rtol=rtol))
        cap = want.get("max_fp32_matmul_fraction")
        if cap is not None:
            floor = want.get("min_matmul_flops", 1.0)
            if cost.matmul_flops < floor:
                out.append(self.finding(
                    ep, f"vacuous check: expected >= {floor:.4g} "
                        f"dot/conv FLOPs, traced {cost.matmul_flops:.4g}",
                    matmul_flops=cost.matmul_flops, floor=floor))
            frac = cost.fp32_matmul_fraction()
            if frac > cap:
                fp32 = cost.matmul_flops_by_dtype.get("float32", 0.0)
                out.append(self.finding(
                    ep, f"{frac:.1%} of dot/conv FLOPs "
                        f"({fp32:.4g} of {cost.matmul_flops:.4g}) run "
                        f"on fp32 operands — cap is {cap:.1%} (silent "
                        f"upcast where the work is)",
                    fp32_matmul_fraction=frac, cap=cap,
                    fp32_matmul_flops=fp32,
                    matmul_flops=cost.matmul_flops))
        return out


@register_rule
class MemoryBudgetRule(Rule):
    """Peak live bytes stay within budget — the static early-warning
    for ROADMAP item 4's "pin peak-memory in bench": a refactor that
    keeps a dead copy of the cache, un-donates a buffer upstream, or
    upcasts a temp tree to fp32 moves the analytic liveness peak long
    before anyone reruns the hardware bench."""

    name = "memory-budget"
    expect_key = "memory"

    def check(self, ep, graph) -> List[Finding]:
        from ..observability import memory
        want = ep.expect["memory"]
        out: List[Finding] = []
        lb = memory.jaxpr_live_bytes(graph.jaxpr)
        peak = lb["peak_live_bytes"]
        budget = want.get("budget_bytes")
        if budget is not None and peak > budget:
            out.append(self.finding(
                ep, f"analytic peak live bytes {peak:,} exceed the "
                    f"{budget:,}-byte budget",
                peak_live_bytes=peak, budget_bytes=budget))
        ratio_cap = want.get("max_live_to_argument_ratio")
        if ratio_cap is not None:
            args = max(lb["argument_bytes"], 1)
            ratio = peak / args
            if ratio > ratio_cap:
                out.append(self.finding(
                    ep, f"peak live bytes are {ratio:.2f}x the "
                        f"argument bytes ({peak:,} vs {args:,}); "
                        f"budget is {ratio_cap}x — the graph is "
                        f"holding duplicate state",
                    peak_live_bytes=peak, argument_bytes=args,
                    ratio=round(ratio, 3), cap=ratio_cap))
        for dt, cap in sorted(
                want.get("temp_budget_bytes_by_dtype", {}).items()):
            got = lb["peak_temp_bytes_by_dtype"].get(dt, 0)
            if got > cap:
                out.append(self.finding(
                    ep, f"peak {dt} temp bytes {got:,} exceed the "
                        f"{cap:,}-byte budget — e.g. an fp32 upcast "
                        f"materializing a second activation tree",
                    dtype=dt, peak_temp_bytes=got, budget_bytes=cap))
        return out


@register_rule
class NumericsRule(Rule):
    """Numerics instrumentation is free where enabled and ABSENT where
    disabled (PR 9's audit pin).  Expectation::

        {"baseline": "ddp_resnet18_o2",      # name or EntryPoint/Graph
         "enabled": True,
         "extra_collectives": {"psum": 1},   # the divergence digest
         "extra_payload_bytes": 520}

    Enabled: the instrumented step must contain ZERO host-transfer
    primitives (the accounting is device-resident; ``flush()`` is the
    one fetch, outside the step) and its collective census must be
    EXACTLY the baseline's plus the planned delta
    (``numerics.digest_comm_plan`` derives it) — an instrumentation
    change that sneaks an extra collective or callback into the hot
    loop flags here before any profiler sees it.  Disabled: the step
    must trace to the byte-identical jaxpr of the baseline — the
    off-switch leaves no residue."""

    name = "numerics"
    expect_key = "numerics"

    @staticmethod
    def _baseline_graph(want):
        base = want.get("baseline")
        if base is None:
            return None
        if isinstance(base, str):
            from .entry_points import get as _get_ep
            return _get_ep(base).graph()
        return base.graph() if hasattr(base, "graph") else base

    def check(self, ep, graph) -> List[Finding]:
        want = ep.expect["numerics"]
        out: List[Finding] = []
        base = self._baseline_graph(want)
        if not want.get("enabled", True):
            if base is None:
                return [self.finding(
                    ep, "a disabled-numerics expectation needs a "
                        "baseline to compare against")]
            ours, theirs = str(graph.jaxpr), str(base.jaxpr)
            if ours != theirs:
                n_eq = sum(1 for _ in G.walk_jaxpr(graph.jaxpr))
                n_eq_b = sum(1 for _ in G.walk_jaxpr(base.jaxpr))
                out.append(self.finding(
                    ep, f"numerics residue: the disabled-numerics step "
                        f"traces to a different jaxpr than the "
                        f"uninstrumented baseline ({n_eq} vs {n_eq_b} "
                        f"eqns) — the off-switch must be free",
                    eqns=n_eq, baseline_eqns=n_eq_b))
            return out
        hits = Counter(e.primitive.name
                       for e in G.host_transfer_eqns(graph.jaxpr))
        for prim, n in sorted(hits.items()):
            out.append(self.finding(
                ep, f"numerics-instrumented step contains "
                    f"host-transfer primitive {prim!r} {n}x — the "
                    f"accounting must accumulate device-resident "
                    f"(flush() is the one host fetch, outside the "
                    f"step)", primitive=prim, count=n))
        if base is not None:
            got = Counter(e.primitive.name
                          for e in G.collective_eqns(graph.jaxpr))
            base_counts = Counter(
                e.primitive.name for e in G.collective_eqns(base.jaxpr))
            extra = dict(want.get("extra_collectives", {}))
            for prim in sorted(set(got) | set(base_counts) | set(extra)):
                w = base_counts.get(prim, 0) + extra.get(prim, 0)
                g = got.get(prim, 0)
                if g != w:
                    out.append(self.finding(
                        ep, f"expected {w} {prim} eqn(s) (baseline "
                            f"{base_counts.get(prim, 0)} + planned "
                            f"numerics delta {extra.get(prim, 0)}), "
                            f"instrumented graph has {g}",
                        primitive=prim, expected=w, got=g,
                        baseline=base_counts.get(prim, 0)))
            if "extra_payload_bytes" in want:
                ours = sum(G.eqn_payload_bytes(e)
                           for e in G.collective_eqns(graph.jaxpr))
                theirs = sum(G.eqn_payload_bytes(e)
                             for e in G.collective_eqns(base.jaxpr))
                delta, w = ours - theirs, want["extra_payload_bytes"]
                if delta != w:
                    out.append(self.finding(
                        ep, f"numerics adds {delta} collective payload "
                            f"bytes over the baseline, the digest plan "
                            f"budgets exactly {w}",
                        payload_delta=delta, expected_delta=w))
        return out


@register_rule
class SupervisorRule(Rule):
    """A run-supervised step is the UNSUPERVISED step, to the byte
    (PR 10's operational-plane pin).  Expectation::

        {"baseline": "ddp_resnet18_o2", "enabled": True}

    Unlike the numerics monitor — device-resident state that is free
    only when *disabled* — the supervisor holds no device state at
    all: it consumes signals the host already fetched at existing
    flush points, and ``RunSupervisor.wrap_step`` returns the step
    function unchanged.  So the pinned property is the same in BOTH
    directions: the supervised step's jaxpr must be byte-identical to
    the baseline's and contain zero host-transfer primitives, enabled
    or disabled.  A supervisor change that instruments the step —
    smuggles a callback to read the loss per step, adds a collective,
    threads extra carry state — flags here before any profiler sees
    the regression (mutation-tested both ways in
    tests/test_analysis.py)."""

    name = "supervisor"
    expect_key = "supervisor"

    def check(self, ep, graph) -> List[Finding]:
        want = ep.expect["supervisor"]
        out: List[Finding] = []
        hits = Counter(e.primitive.name
                       for e in G.host_transfer_eqns(graph.jaxpr))
        for prim, n in sorted(hits.items()):
            out.append(self.finding(
                ep, f"supervised step contains host-transfer "
                    f"primitive {prim!r} {n}x — the supervisor reads "
                    f"existing host flush points, it never instruments "
                    f"the jitted step", primitive=prim, count=n))
        base = NumericsRule._baseline_graph(want)
        if base is None:
            out.append(self.finding(
                ep, "a supervisor expectation needs a baseline to "
                    "compare against"))
            return out
        ours, theirs = str(graph.jaxpr), str(base.jaxpr)
        if ours != theirs:
            n_eq = sum(1 for _ in G.walk_jaxpr(graph.jaxpr))
            n_eq_b = sum(1 for _ in G.walk_jaxpr(base.jaxpr))
            state = ("enabled" if want.get("enabled", True)
                     else "disabled")
            out.append(self.finding(
                ep, f"supervisor residue: the {state}-supervisor step "
                    f"traces to a different jaxpr than the "
                    f"unsupervised baseline ({n_eq} vs {n_eq_b} eqns) "
                    f"— wrap_step must be an identity in both "
                    f"directions", eqns=n_eq, baseline_eqns=n_eq_b))
        return out


@register_rule
class CollectiveRule(Rule):
    """The comm pattern is exactly what the algorithm assumes: expected
    psum/all-gather eqn counts and on-wire payload bytes in DDP/TP/ZeRO
    graphs.  A missing psum is a wrong answer; an extra one is a
    regression the profiler would surface weeks later."""

    name = "collective"
    expect_key = "collectives"

    def check(self, ep, graph) -> List[Finding]:
        want = ep.expect["collectives"]
        out: List[Finding] = []
        eqns = G.collective_eqns(graph.jaxpr)
        got = Counter(e.primitive.name for e in eqns)
        expected = dict(want.get("counts", {}))
        for prim in sorted(set(got) | set(expected)):
            g, w = got.get(prim, 0), expected.get(prim, 0)
            if g != w:
                out.append(self.finding(
                    ep, f"expected {w} {prim} eqn(s), graph has {g}",
                    primitive=prim, expected=w, got=g))
        if "payload_bytes" in want:
            total = sum(G.eqn_payload_bytes(e) for e in eqns)
            w = want["payload_bytes"]
            tol = want.get("payload_tolerance", 0)
            if abs(total - w) > tol:
                out.append(self.finding(
                    ep, f"collective payload is {total} bytes on the "
                        f"wire, expected {w}"
                        + (f" (+/- {tol})" if tol else ""),
                    payload_bytes=total, expected_bytes=w))
        if "payload_bytes_by_primitive" in want:
            got_by = Counter()
            for e in eqns:
                got_by[e.primitive.name] += G.eqn_payload_bytes(e)
            want_by = dict(want["payload_bytes_by_primitive"])
            tol = want.get("payload_tolerance", 0)
            # only a hierarchical plan (it budgets a reduce_scatter per
            # bucket) makes the per-primitive split a fabric-level
            # statement — don't point a flat-plan mismatch at ICI/DCN
            hier = "reduce_scatter" in want.get("counts", want_by)
            for prim in sorted(set(got_by) | set(want_by)):
                g, w = got_by.get(prim, 0), want_by.get(prim, 0)
                if abs(g - w) > tol:
                    out.append(self.finding(
                        ep, f"{prim} payload is {g} bytes on the wire, "
                            f"expected {w}"
                            + (f" (+/- {tol})" if tol else "")
                            + (" — the per-primitive split is the "
                               "fabric-level split under a "
                               "hierarchical comm plan (the psum hop "
                               "is the DCN payload)" if hier else ""),
                        primitive=prim, payload_bytes=g,
                        expected_bytes=w))
        inter = want.get("interleaving")
        if inter:
            out.extend(self._check_interleaving(ep, graph, inter))
        return out

    def _check_interleaving(self, ep, graph, inter) -> List[Finding]:
        """The overlapped-schedule position pin: the first issued
        gradient bucket's reduction must sit AHEAD of the tail of the
        backward in jaxpr program order — a reduce-after-backward
        graph (every collective trailing every matmul) has the exact
        same census and payloads, so only the eqn positions can flag
        it.  Scalar psums (axis size, loss pmean) are excluded by the
        ``min_payload_bytes`` threshold, which
        ``parallel.overlap_collective_expectations`` derives as the
        smallest per-level hop any planned bucket puts on the wire."""
        out: List[Finding] = []
        thresh = int(inter.get("min_payload_bytes", 16))
        ordered = list(G.walk_jaxpr(graph.jaxpr))
        first_coll = None
        coll_pos: List[int] = []
        matmul_pos: List[int] = []
        for i, e in enumerate(ordered):
            name = e.primitive.name
            if (name in G.COLLECTIVE_PRIMS
                    and G.eqn_payload_bytes(e) >= thresh):
                if first_coll is None:
                    first_coll = i
                coll_pos.append(i)
            if name in ("dot_general", "conv_general_dilated"):
                matmul_pos.append(i)
        if first_coll is None:
            return [self.finding(
                ep, f"vacuous interleaving check: no collective eqn "
                    f"moves >= {thresh} bytes — there is no gradient "
                    f"bucket reduction to position",
                min_payload_bytes=thresh)]
        if not matmul_pos:
            return [self.finding(
                ep, "vacuous interleaving check: the graph has no "
                    "conv/dot eqns to interleave the reduction with")]
        last_mm = matmul_pos[-1]
        if first_coll > last_mm:
            out.append(self.finding(
                ep, f"reduce-after-backward schedule: the first "
                    f"gradient-bucket collective (eqn #{first_coll}) "
                    f"trails the last matmul (eqn #{last_mm}) — the "
                    f"overlapped schedule must issue the first "
                    f"bucket's reduction while later stages' backward "
                    f"is still being emitted",
                first_collective_eqn=first_coll,
                last_matmul_eqn=last_mm))
            return out
        after = sum(1 for i in matmul_pos if i > first_coll)
        floor = int(inter.get("min_matmuls_after", 1))
        if after < floor:
            out.append(self.finding(
                ep, f"only {after} matmul eqn(s) follow the first "
                    f"gradient-bucket collective (eqn #{first_coll}); "
                    f"the overlap schedule budgets >= {floor} — "
                    f"nothing is left for the reduction to overlap "
                    f"with", matmuls_after=after, floor=floor,
                first_collective_eqn=first_coll))
        # the per-stage pin: one bucket sneaking ahead of the last
        # matmul satisfies the first-collective check even if every
        # OTHER stage's reduction collapsed to reduce-after-backward.
        # The schedule knows exactly how many bucket eqns belong to
        # stages issued before the last one, so it declares a floor on
        # qualifying collectives preceding the last matmul
        # (parallel.overlap_collective_expectations).
        coll_floor = inter.get("min_collectives_before_last_matmul")
        if coll_floor is not None:
            before = sum(1 for i in coll_pos if i < last_mm)
            if before < int(coll_floor):
                out.append(self.finding(
                    ep, f"only {before} gradient-bucket collective(s) "
                        f"precede the last matmul (eqn #{last_mm}); "
                        f"the overlap schedule issues "
                        f">= {int(coll_floor)} before the final "
                        f"stage's backward — the staged overlap "
                        f"partially collapsed to "
                        f"reduce-after-backward",
                    collectives_before=before,
                    floor=int(coll_floor),
                    last_matmul_eqn=last_mm))
        return out


# a declared max_replicated_bytes budget whose measured ledger value
# sits below this fraction of it is "stale": the deterministic
# propagation means real headroom never exceeds the declaration slack
# (entry points declare ~1.05x measured), so >25% slack is a budget
# that outlived a ZeRO-stage (or sharding) change and must ratchet down
RATCHET_FRACTION = 0.75


@register_rule
class SpecConsistencyRule(Rule):
    """``shard_map`` specs are consistent with the mesh and with what
    the body actually computes: axes exist, sharded dims divide, the
    mesh is the one ``topology.make_mesh`` was asked for, and the
    number of outputs claiming more agreement than the propagated
    partition guarantees is exactly the declared count.  With
    ``check_vma=False`` (how every train entry point runs) NOTHING at
    runtime checks the last property — a replicated out-spec over a
    still-varying value silently keeps one replica's answer."""

    name = "sharding"
    expect_key = "sharding"

    def check(self, ep, graph) -> List[Finding]:
        from . import sharding as S
        want = ep.expect["sharding"]
        out: List[Finding] = []
        eqns = S.shard_map_eqns(graph.jaxpr)
        if not eqns:
            return [self.finding(
                ep, "a sharding expectation is declared but the graph "
                    "traces no shard_map eqn")]
        analyses = [S.analyze_shard_map(e) for e in eqns]
        divergent: List[str] = []
        for eqn, a in zip(eqns, analyses):
            for msg in S.check_shard_map_specs(
                    eqn, want.get("mesh_axes"), analysis=a):
                out.append(self.finding(ep, msg))
            divergent.extend(S.divergent_output_claims(eqn, a))
        declared = int(want.get("divergent_outputs", 0))
        if len(divergent) != declared:
            sample = "; ".join(divergent[:3])
            if len(divergent) > declared:
                out.append(self.finding(
                    ep, f"{len(divergent)} output spec(s) claim more "
                        f"agreement than the propagated partitions "
                        f"guarantee; {declared} are declared (the "
                        f"non-synced BatchNorm stats class) — a "
                        f"collective went missing before a return. "
                        f"First undeclared: {sample}",
                    divergent=len(divergent), declared=declared))
            else:
                out.append(self.finding(
                    ep, f"only {len(divergent)} divergent output "
                        f"claim(s) but {declared} are declared — "
                        f"ratchet divergent_outputs down",
                    divergent=len(divergent), declared=declared))
        budget = want.get("max_replicated_bytes")
        if budget is not None:
            repl = sum(a.replicated_bytes for a in analyses)
            if repl > int(budget):
                worst = max(
                    (arg for a in analyses for arg in a.args),
                    key=lambda g: g.replicated_bytes(analyses[0].world))
                out.append(self.finding(
                    ep, f"replication ledger reports {repl:,} "
                        f"world-total duplicate bytes, budget is "
                        f"{int(budget):,} — largest contributor: "
                        f"{worst.dtype}{list(worst.shape)} x"
                        f"{worst.replication_factor} ({worst.spec})",
                    replicated_bytes=repl, budget_bytes=int(budget)))
            elif repl < int(int(budget) * RATCHET_FRACTION):
                # the ratchet-both-ways contract: a ZeRO stage that
                # collapses the replicated state must tighten the
                # declared budget with it, or the budget silently
                # stops guarding anything (a later regression back to
                # full replication would still "pass")
                out.append(self.finding(
                    ep, f"replication budget is stale: the ledger "
                        f"reports {repl:,} world-total duplicate "
                        f"bytes but {int(budget):,} are budgeted "
                        f"(> {100 - int(RATCHET_FRACTION * 100)}% "
                        f"headroom) — ratchet max_replicated_bytes "
                        f"down to the measured value",
                    replicated_bytes=repl, budget_bytes=int(budget)))
        return out


@register_rule
class ReshardingCensusRule(Rule):
    """Every placement-changing collective in the hot graph is
    explained by the comm plan or a declared budget.  The collective
    rule pins counts and payload totals — but an unplanned all-gather
    introduced while a planned one is dropped can leave both intact.
    This rule matches graph eqns against the plan's per-eqn payload
    list one by one, and names the operand (shape, dtype, inferred
    spec) of anything unexplained — the "XLA silently replicated my
    shard" failure, caught statically."""

    name = "resharding-census"
    expect_key = "resharding"

    def check(self, ep, graph) -> List[Finding]:
        from . import sharding as S
        want = ep.expect["resharding"]
        out: List[Finding] = []
        eqns = S.shard_map_eqns(graph.jaxpr)
        if not eqns:
            return [self.finding(
                ep, "a resharding expectation is declared but the "
                    "graph traces no shard_map eqn")]
        sites = [s for e in eqns for s in S.analyze_shard_map(e).sites
                 if s.primitive in S.RESHARD_PRIMS]
        planned = {prim: list(pays)
                   for prim, pays in want.get("planned", {}).items()}
        budget = {k: int(v) for k, v in want.get("budget", {}).items()}
        unplanned: dict = {}
        for s in sites:
            pool = planned.get(s.primitive, [])
            if s.payload_bytes in pool:
                pool.remove(s.payload_bytes)
            else:
                unplanned.setdefault(s.primitive, []).append(s)
        for prim in sorted(unplanned):
            extra = unplanned[prim]
            allowed = budget.get(prim, 0)
            if len(extra) <= allowed:
                continue
            for s in extra:
                out.append(self.finding(
                    ep, f"unplanned {s.describe()} — not in the comm "
                        f"plan's {prim} payload list and beyond the "
                        f"declared budget of {allowed}; an unexplained "
                        f"resharding in the hot path",
                    primitive=s.primitive,
                    payload_bytes=s.payload_bytes,
                    shape=list(map(int, s.shape)), dtype=s.dtype,
                    spec=s.spec, budget=allowed))
        for prim in sorted(planned):
            left = planned[prim]
            if left:
                out.append(self.finding(
                    ep, f"comm plan schedules {len(left)} {prim} "
                        f"eqn(s) of {sorted(left)} bytes that the "
                        f"traced graph never issues — plan/graph "
                        f"desync",
                    primitive=prim, missing=len(left),
                    payloads=sorted(int(x) for x in left)))
        return out
