"""CLI: ``python -m apex_tpu.analysis`` — lint the hot graphs.

Stdout is pure schema-versioned JSONL (the bench.py contract): one
``graph_lint`` record per finding plus one ``graph_lint_summary``
record, all enriched by ``observability.exporters.JsonlExporter`` and
validated by ``tests/ci/check_bench_schema.py``.  Human-readable
progress goes to stderr.  Exit status: 0 = clean, 1 = any
error-severity finding (the CI gate tests/ci/graph_lint.py relies on
this), 2 = bad usage.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List


def _force_virtual_mesh():
    """Mirror tests/conftest.py: the DDP/TP entry points trace an
    8-device mesh, so force the virtual CPU mesh before the first
    backend initialization.  Jaxpr properties are backend-independent
    — the CPU trace pins what the TPU executable will see.  Set
    APEX_TPU_ANALYSIS_BACKEND=native to lint on the ambient backend
    instead."""
    if os.environ.get("APEX_TPU_ANALYSIS_BACKEND") == "native":
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    # jax is already imported (we live inside the package), so flip the
    # platform via jax.config — effective as long as no backend has
    # been initialized yet (tests/conftest.py's strategy)
    import jax
    jax.config.update("jax_platforms", "cpu")


def main(argv: List[str] = None) -> int:
    _force_virtual_mesh()
    p = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="Static graph lint over the hot entry points.")
    p.add_argument("--entry-points", default=None,
                   help="comma-separated entry-point names "
                        "(default: all registered)")
    p.add_argument("--tags", default=None,
                   help="comma-separated tags to select entry points "
                        "(e.g. training,serving)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule names (default: all)")
    p.add_argument("--entry", default=None, metavar="SUBSTR",
                   help="substring filter on entry-point names — the "
                        "rule-author iteration loop (the warm full "
                        "registry is ~16-21s; --entry=paged runs just "
                        "the paged engine EPs).  Composes with "
                        "--entry-points/--tags and filters --list")
    p.add_argument("--rule", default=None, metavar="SUBSTR",
                   help="substring filter on rule names (e.g. "
                        "--rule=shard matches sharding + "
                        "resharding-census).  Composes with --rules "
                        "and filters --list")
    p.add_argument("--list", action="store_true",
                   help="list entry points and rules, run nothing")
    p.add_argument("--memory", action="store_true",
                   help="emit one `kind: memory` record per entry "
                        "point (analytic FLOPs/bytes + the compiled "
                        "memory plan) instead of linting.  Compiles "
                        "each selected entry point — combine with "
                        "--entry-points/--tags to bound the cost")
    p.add_argument("--sharding", action="store_true",
                   help="emit one `kind: sharding` record (the "
                        "replication ledger: per-dtype replicated "
                        "bytes, top replicated arrays, resharding "
                        "census) per entry point instead of linting. "
                        "Entry points that trace no shard_map "
                        "(serving engines) are skipped")
    p.add_argument("--out", default=None,
                   help="append JSONL findings to this path instead of "
                        "stdout")
    args = p.parse_args(argv)

    from . import ENTRY_POINTS, RULES, get_rule, run_lint, select

    def _ep_match(name):
        return args.entry is None or args.entry in name

    def _rule_match(name):
        return args.rule is None or args.rule in name

    from ..observability.exporters import JsonlExporter

    if args.list:
        for ep in ENTRY_POINTS.values():
            if _ep_match(ep.name):
                print(f"{ep.name:32s} [{', '.join(sorted(ep.tags))}] "
                      f"{ep.description}")
        print("rules: " + ", ".join(
            r for r in sorted(RULES) if _rule_match(r)))
        return 0

    try:
        eps = select(
            names=args.entry_points.split(",")
            if args.entry_points else None,
            tags=args.tags.split(",") if args.tags else None)
        rules = ([get_rule(r) for r in args.rules.split(",")]
                 if args.rules else None)
    except KeyError as e:
        print(f"graph lint: {e.args[0]}", file=sys.stderr)
        return 2
    eps = [ep for ep in eps if _ep_match(ep.name)]
    if args.rule is not None:
        rules = [r for r in (rules if rules is not None
                             else RULES.values())
                 if _rule_match(r.name)]
        if not rules:
            print(f"no rules match --rule={args.rule}", file=sys.stderr)
            return 2
    if not eps:
        print("no entry points selected", file=sys.stderr)
        return 2

    def progress(ep, findings, dt):
        for f in findings:
            print(f"  {f}", file=sys.stderr)
        print(f"{ep.name:32s} {len(findings)} finding(s) [{dt:.1f}s]",
              file=sys.stderr)

    exp = JsonlExporter(path=args.out) if args.out \
        else JsonlExporter(stream=sys.stdout)

    if args.memory:
        # per-entry-point memory/FLOP dump: the analytic cost model
        # (free: reuses the cached trace) plus the compiled memory
        # plan (pays one compile per entry point, cached per process).
        # Same stdout contract as lint: pure schema-valid JSONL,
        # check_bench_schema.py validates the stream.
        from .entry_points import entry_point_memory_record
        failed = 0
        with exp:
            for ep in eps:
                t0 = time.perf_counter()
                try:
                    rec = entry_point_memory_record(ep)
                except RuntimeError as e:
                    # only the bare-RuntimeError device-count gate is a
                    # skip; jaxlib's XlaRuntimeError SUBCLASSES
                    # RuntimeError, and a real compile failure must
                    # fail the gate, not read as "skipped"
                    if type(e) is not RuntimeError:
                        failed += 1
                        print(f"{ep.name:32s} FAILED: {e}",
                              file=sys.stderr)
                        continue
                    print(f"{ep.name:32s} skipped: {e}",
                          file=sys.stderr)
                    continue
                except Exception as e:
                    failed += 1
                    print(f"{ep.name:32s} FAILED: {e}", file=sys.stderr)
                    continue
                exp.emit(rec)
                print(f"{ep.name:32s} flops={rec['flops']:.4g} "
                      f"peak_bytes={rec['peak_bytes']:,} "
                      f"[{time.perf_counter() - t0:.1f}s]",
                      file=sys.stderr)
        return 1 if failed else 0

    if args.sharding:
        # per-entry-point replication ledger: statically derived from
        # the traced jaxpr (free: reuses the cached trace, never
        # compiles).  Same stdout contract as lint: pure schema-valid
        # JSONL.  Two skip classes ride the bare-RuntimeError gate:
        # the device-count gate (hierarchical EPs on a 1-device host)
        # and "traces no shard_map" (serving engines) — jaxlib's
        # XlaRuntimeError SUBCLASSES RuntimeError, so a real trace
        # failure still fails the run.
        from .sharding import entry_point_sharding_record
        failed = 0
        with exp:
            for ep in eps:
                t0 = time.perf_counter()
                try:
                    rec = entry_point_sharding_record(ep)
                except RuntimeError as e:
                    if type(e) is not RuntimeError:
                        failed += 1
                        print(f"{ep.name:32s} FAILED: {e}",
                              file=sys.stderr)
                        continue
                    print(f"{ep.name:32s} skipped: {e}",
                          file=sys.stderr)
                    continue
                except Exception as e:
                    failed += 1
                    print(f"{ep.name:32s} FAILED: {e}", file=sys.stderr)
                    continue
                exp.emit(rec)
                print(f"{ep.name:32s} "
                      f"replicated={rec['replicated_bytes']:,} "
                      f"({rec['replicated_fraction']:.1%} of world "
                      f"bytes) [{time.perf_counter() - t0:.1f}s]",
                      file=sys.stderr)
        return 1 if failed else 0
    t0 = time.perf_counter()
    with exp:
        summary = run_lint(entry_points=eps, rules=rules,
                           emit=exp.emit, progress=progress)
    print(f"graph lint: {summary['entry_points']} entry point(s), "
          f"{summary['errors']} error(s), {summary['warnings']} "
          f"warning(s) in {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)
    return 1 if summary["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
