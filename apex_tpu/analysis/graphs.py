"""Graph plumbing for the static analyzer: jaxpr walking, primitive
taxonomies, and compat helpers over lowered StableHLO modules.

Everything here is *description*, not judgement: these helpers surface
what a traced/lowered graph contains (host-transfer primitives,
convolution operands, collective payloads, donation aliasing) and the
rules in :mod:`.rules` decide whether that violates an entry point's
expectations.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

import jax
import jax.extend.core  # noqa: F401  (jax.extend is not auto-imported)

__all__ = [
    "HOST_TRANSFER_PRIMS", "COLLECTIVE_PRIMS",
    "walk_jaxpr", "prim_eqns", "host_transfer_eqns", "conv_eqns",
    "large_dot_eqns", "transpose_eqns", "collective_eqns",
    "eqn_payload_bytes", "lowered_text", "aliased_output_count",
    "donated_arg_names", "duplicate_donated_leaves", "Graph",
]

# primitives that move data across the host boundary: any of these
# inside a jitted hot graph means a per-dispatch host round-trip — the
# exact cost the device-resident scaler, telemetry, and the serving
# decode window exist to avoid (pinned since PR 1 by
# tests/test_step_graph_audit.py)
HOST_TRANSFER_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outfeed", "infeed", "device_put",
})

# cross-replica communication primitives the accounting rule budgets
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "ppermute", "pgather",
})


def _as_jaxpr(jaxpr):
    if isinstance(jaxpr, jax.extend.core.ClosedJaxpr):
        return jaxpr.jaxpr
    return jaxpr


def walk_jaxpr(jaxpr) -> Iterator[Any]:
    """Yield every eqn in a (closed) jaxpr, recursing into sub-jaxprs
    (scan/while/cond bodies, shard_map, pjit calls, custom-vjp …)."""
    jaxpr = _as_jaxpr(jaxpr)
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in jax.tree_util.tree_leaves(
                    v, is_leaf=lambda x: isinstance(
                        x, (jax.extend.core.Jaxpr,
                            jax.extend.core.ClosedJaxpr))):
                if isinstance(sub, (jax.extend.core.Jaxpr,
                                    jax.extend.core.ClosedJaxpr)):
                    yield from walk_jaxpr(sub)


def prim_eqns(jaxpr, names: Iterable[str]) -> List[Any]:
    names = frozenset(names)
    return [e for e in walk_jaxpr(jaxpr) if e.primitive.name in names]


def host_transfer_eqns(jaxpr) -> List[Any]:
    return prim_eqns(jaxpr, HOST_TRANSFER_PRIMS)


def conv_eqns(jaxpr) -> List[Any]:
    return prim_eqns(jaxpr, ("conv_general_dilated",))


def large_dot_eqns(jaxpr, min_elems: int = 256) -> List[Any]:
    """dot_general eqns whose operands are all activation/param sized
    (>= ``min_elems`` elements) — the matmuls that hit the MXU; tiny
    bookkeeping dots (scalars, index math) are exempt from dtype
    policy."""
    return [e for e in prim_eqns(jaxpr, ("dot_general",))
            if all(int(np.prod(v.aval.shape)) >= min_elems
                   for v in e.invars)]


def transpose_eqns(jaxpr, min_elems: int = 0) -> List[Any]:
    return [e for e in prim_eqns(jaxpr, ("transpose",))
            if int(np.prod(e.invars[0].aval.shape)) >= min_elems]


def collective_eqns(jaxpr) -> List[Any]:
    return prim_eqns(jaxpr, COLLECTIVE_PRIMS)


def eqn_payload_bytes(eqn) -> int:
    """Bytes of operand data an eqn moves (sum over invars) — for a
    psum/all_gather this is the on-wire payload of one replica."""
    return sum(int(np.prod(v.aval.shape)) * v.aval.dtype.itemsize
               for v in eqn.invars
               if hasattr(v, "aval") and hasattr(v.aval, "shape"))


# -- lowered-module helpers ----------------------------------------------

def lowered_text(lowered, debug_info: bool = False) -> str:
    """`Lowered.as_text()` across the jax API drift: jax >= 0.5 takes
    ``debug_info=`` directly; 0.4.x needs the MLIR module's
    ``get_asm(enable_debug_info=True)`` to see scope/name metadata
    (named nvtx ranges, arg locations)."""
    if not debug_info:
        return lowered.as_text()
    try:
        return lowered.as_text(debug_info=True)
    except TypeError:
        mod = lowered.compiler_ir("stablehlo")
        return mod.operation.get_asm(enable_debug_info=True)


def aliased_output_count(stablehlo_text: str) -> int:
    """Number of input buffers the lowered module aliases to an output
    (``tf.aliasing_output`` entry-function attributes) — i.e. how many
    donations XLA actually honored.  Donation that is requested but not
    aliased silently keeps both copies alive."""
    return stablehlo_text.count("tf.aliasing_output")


def donated_arg_names(lowered, arg_names: Tuple[str, ...]):
    """Map ``Lowered.args_info`` donation flags back to the wrapped
    function's parameter names.

    Returns ``(donated, partial)``: names with at least one donated
    leaf, and the subset of those whose leaves are only *partially*
    donated (a donation hole inside one logical argument)."""
    args_info, _kwargs_info = lowered.args_info
    if len(args_info) != len(arg_names):
        raise ValueError(
            f"arg_names has {len(arg_names)} entries but the lowering "
            f"has {len(args_info)} positional args")
    donated, partial = [], []
    for name, info in zip(arg_names, args_info):
        flags = [bool(i.donated) for i in jax.tree_util.tree_leaves(info)]
        if any(flags):
            donated.append(name)
            if not all(flags):
                partial.append(name)
    return donated, partial


def duplicate_donated_leaves(lowered, arg_names: Tuple[str, ...],
                             example_args: Tuple[Any, ...]) -> List[str]:
    """Donated leaves that are the *same buffer object* appearing more
    than once in the donated argument set.  XLA rejects this at compile
    time ("Attempt to donate the same buffer twice"), and the classic
    way to ship it is a cache init that shares one zeros buffer across
    layers (the ``dict(layer)`` shallow copy PR 2 hit in
    ``gpt.init_cache``).  Returns a description per duplicated buffer."""
    donated, _ = donated_arg_names(lowered, arg_names)
    seen = {}
    dups = []
    for name, arg in zip(arg_names, example_args):
        if name not in donated:
            continue
        for path, leaf in jax.tree_util.tree_flatten_with_path(arg)[0]:
            key = id(leaf)
            where = f"{name}{jax.tree_util.keystr(path)}"
            if key in seen:
                dups.append(f"{where} shares a buffer with {seen[key]}")
            else:
                seen[key] = where
    return dups


class Graph:
    """One traced entry point: the jaxpr and (lazily) the lowered
    StableHLO module, plus the metadata the donation rule needs to name
    arguments."""

    def __init__(self,
                 trace: Optional[Callable[[], Any]] = None,
                 lower: Optional[Callable[[], Any]] = None,
                 arg_names: Optional[Tuple[str, ...]] = None,
                 example_args: Optional[Tuple[Any, ...]] = None):
        self._trace = trace
        self._lower = lower
        self.arg_names = arg_names
        self.example_args = example_args
        self._jaxpr = None
        self._lowered = None
        self._lowered_text = None
        self._compiled = None

    @property
    def jaxpr(self):
        if self._jaxpr is None:
            if self._trace is None:
                raise ValueError("entry point has no jaxpr tracer")
            self._jaxpr = self._trace()
        return self._jaxpr

    @property
    def lowered(self):
        if self._lowered is None:
            if self._lower is None:
                raise ValueError("entry point has no lowering")
            self._lowered = self._lower()
        return self._lowered

    @property
    def stablehlo(self) -> str:
        if self._lowered_text is None:
            self._lowered_text = self.lowered.as_text()
        return self._lowered_text

    @property
    def has_lowering(self) -> bool:
        return self._lower is not None or self._lowered is not None

    @property
    def compiled(self):
        """The compiled executable for memory/cost analysis.

        Entry points with a real lowering compile it (donation aliasing
        and all); trace-only entry points compile an ``eval_jaxpr``
        re-staging of the traced graph — structurally identical compute,
        but no donation, so alias_bytes reads 0 there.  Cached: the
        compile is paid once per process like the trace."""
        if self._compiled is None:
            if self.has_lowering:
                self._compiled = self.lowered.compile()
            else:
                closed = self.jaxpr
                args = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                        for v in closed.jaxpr.invars]
                fn = jax.jit(lambda *xs: jax.core.eval_jaxpr(
                    closed.jaxpr, closed.consts, *xs))
                self._compiled = fn.lower(*args).compile()
        return self._compiled
