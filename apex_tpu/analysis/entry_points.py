"""Registry of HOT entry points: the real graphs bench.py, the examples
and the serving engines execute, traced for the rule engine.

Each entry point builds the same step the production path dispatches —
DDP ResNet train steps across O0–O3 (telemetry on/off, channels-last
variants), the transformer-family O2 steps, the serving engines' jitted
mutators, and the tensor-parallel step — and carries the expectations
the rules check.  Expectations are *derived from the subsystems that
own them* wherever possible: conv/matmul dtypes from
``amp.compute_dtype``, DDP psum counts and on-wire bytes from
``parallel.allreduce_comm_plan``, donation names/blocklist from
``serving``'s constants.  Jaxpr properties are backend-independent, so
tracing on the CPU mesh pins what the TPU executable will see.

Builders run lazily and cache: registering is free, ``ep.graph()`` pays
the trace once per process (tests, the CI gate and the CLI share it).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .graphs import Graph

__all__ = ["EntryPoint", "ENTRY_POINTS", "register_entry_point", "get",
           "select", "names", "entry_point_memory_record"]


def entry_point_memory_record(ep: "EntryPoint") -> Dict[str, Any]:
    """One ``kind: memory`` JSONL payload for an entry point: the
    analytic cost (``ep.cost()``) merged with the compiled memory plan
    (``ep.memory_plan()``).  Shared by ``python -m apex_tpu.analysis
    --memory`` and tests so the record shape cannot drift from
    ``exporters.validate_memory_record``."""
    cost = ep.cost()
    rec = {"kind": "memory", "entry_point": ep.name,
           "source": "compiled", **cost.to_record(), **ep.memory_plan()}
    dt = cost.dominant_matmul_dtype
    if dt is not None:
        rec["dominant_matmul_dtype"] = dt
    return rec


class EntryPoint:
    """One hot graph: ``build(ep)`` returns a :class:`Graph` and may
    fill derived expectations into ``ep.expect`` before rules run."""

    def __init__(self, name: str, build: Callable[["EntryPoint"], Graph],
                 tags: Iterable[str] = (),
                 expect: Optional[Dict[str, Any]] = None,
                 description: str = ""):
        self.name = name
        self.tags = frozenset(tags)
        self.expect: Dict[str, Any] = dict(expect or {})
        self.description = description
        self._build = build
        self._graph: Optional[Graph] = None
        self._cost = None
        self._memory_plan: Optional[Dict[str, Any]] = None

    def graph(self) -> Graph:
        if self._graph is None:
            # leak barrier: amp.initialize(O1) installs a PROCESS-WIDE
            # cast policy (the reference's monkey-patch analogue) and
            # nothing uninstalls it — without this restore, building
            # the O1 entry point would silently re-dtype every graph
            # built after it (tests only dodge this via conftest's
            # autouse _reset_amp_policy).  Builders that need a policy
            # at trace time scope it explicitly via _scoped().
            from ..amp import policy as amp_policy
            base = amp_policy.current_policy()
            # same discipline for the thread-local mesh context: a
            # builder that raises mid-``with mesh:`` (the device-count
            # skip gate fires INSIDE some builders) or that forgets to
            # exit would otherwise leak a physical mesh into every
            # graph traced after it — which silently changes what the
            # sharding propagator sees as the ambient mesh
            try:
                from jax.interpreters import pxla
                mesh_env = pxla.thread_resources.env
            except Exception:        # pragma: no cover - jax internals
                pxla = mesh_env = None
            try:
                self._graph = self._build(self)
            finally:
                amp_policy.set_policy(base)
                if mesh_env is not None:
                    try:
                        pxla.thread_resources.env = mesh_env
                    except Exception:   # pragma: no cover
                        pass
        return self._graph

    def cost(self):
        """Analytic :class:`observability.costmodel.Cost` of the traced
        graph (honest mode: scan bodies times trip count).  Cached per
        process like ``graph()`` — the FlopAccountingRule, the CLI
        ``--memory`` dump and tests share one count."""
        if self._cost is None:
            from ..observability import costmodel
            self._cost = costmodel.jaxpr_cost(self.graph().jaxpr)
        return self._cost

    def memory_plan(self) -> Dict[str, Any]:
        """Compiled memory plan (``Compiled.memory_analysis()``) plus
        the analytic liveness estimate.  Unlike ``cost()`` this pays a
        compile on first call (cached after); the lint rules use only
        the analytic fields, so plain lint never compiles."""
        if self._memory_plan is None:
            from ..observability import memory
            plan = memory.memory_plan(self.graph().compiled)
            lb = memory.jaxpr_live_bytes(self.graph().jaxpr)
            plan["analytic_live_bytes"] = lb["peak_live_bytes"]
            plan["analytic_temp_bytes"] = lb["peak_temp_bytes"]
            plan["analytic_temp_bytes_by_dtype"] = \
                lb["peak_temp_bytes_by_dtype"]
            self._memory_plan = plan
        return self._memory_plan

    def __repr__(self):
        return f"EntryPoint({self.name!r}, tags={sorted(self.tags)})"


ENTRY_POINTS: Dict[str, EntryPoint] = {}


def register_entry_point(name: str, tags: Iterable[str] = (),
                         expect: Optional[Dict[str, Any]] = None,
                         description: str = ""):
    def deco(build):
        if name in ENTRY_POINTS:
            raise ValueError(f"duplicate entry point {name!r}")
        ENTRY_POINTS[name] = EntryPoint(name, build, tags=tags,
                                        expect=expect,
                                        description=description)
        return build
    return deco


def get(name: str) -> EntryPoint:
    try:
        return ENTRY_POINTS[name]
    except KeyError:
        raise KeyError(f"unknown entry point {name!r}; known: "
                       f"{sorted(ENTRY_POINTS)}")


def names() -> List[str]:
    return list(ENTRY_POINTS)


def select(names: Optional[Iterable[str]] = None,
           tags: Optional[Iterable[str]] = None) -> List[EntryPoint]:
    if names is not None:
        return [get(n) for n in names]
    eps = list(ENTRY_POINTS.values())
    if tags is not None:
        tags = frozenset(tags)
        eps = [ep for ep in eps if ep.tags & tags]
    return eps


def _scoped(pol, fn):
    """Defer ``fn`` under the amp cast-policy environment the builder
    intends — traces run lazily, long after the builder's global policy
    state has been restored by the EntryPoint.graph() leak barrier."""
    def run():
        from ..amp import policy as amp_policy
        with amp_policy.use_policy(pol):
            return fn()
    return run


def _no_policy():
    from ..amp import policy as amp_policy
    return amp_policy.NoPolicy()


def _require_devices(n: int):
    import jax
    if len(jax.devices()) < n:
        raise RuntimeError(
            f"this entry point traces an {n}-device mesh; run under "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"(the CLI and tests/ci/graph_lint.py set this before the "
            f"backend initializes)")


# -- DDP ResNet train steps (O0-O3, layouts, telemetry) -------------------

# activation threshold for the layout rule: one NHWC input batch
# (4, 32, 32, 3) on the 8-way mesh — anything that size or bigger being
# transposed is a relayout of real data, not index bookkeeping
_RESNET_ACT_ELEMS = 4 * 3 * 32 * 32


def _ddp_resnet_graph(ep, opt_level, channels_last=False,
                      input_format="NCHW", stem="conv7",
                      telemetry=False, B=8, image=32,
                      comm_topology="flat", compress=False,
                      ici_size=None, numerics=None, supervised=None,
                      world=None):
    """Trace the REAL DDP train step — shard_map over the 8-device CPU
    mesh with the grad allreduce inside — the same graph bench.py's
    headline and examples/imagenet execute.  ``telemetry=True`` threads
    a DeviceMetrics state through the step carry (the fully
    instrumented shape of the hot loop).  ``numerics="on"`` threads a
    NumericsMonitor through the carry — per-layer grad health from
    ``opt.step(grad_health=...)``, per-bucket stats from
    ``allreduce_grads(numerics_out=...)``, and the one-psum divergence
    digest over the updated params; ``numerics="off"`` runs the SAME
    step code with a disabled monitor, which must trace byte-identical
    to the uninstrumented baseline (the numerics rule pins both).
    ``supervised="on"``/``"off"`` routes the step through
    ``RunSupervisor.wrap_step`` with an enabled/disabled supervisor —
    which must be an IDENTITY both ways: the supervisor consumes
    host-side flush points only, and the supervisor rule pins the
    wrapped step's jaxpr byte-identical to the baseline's.
    ``world=N`` traces over a SUB-mesh of the first N ambient devices
    — the post-recovery shrunk-world step (fleet.recovery): the
    collective expectations are re-derived from ``allreduce_comm_plan``
    at that world, which is exactly the contract the elastic trainer's
    re-jit relies on."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from .. import amp, observability, optimizers, parallel, models
    from ..nn import functional as F
    from ..observability import numerics as obs_numerics

    model, opt = amp.initialize(
        models.resnet18(num_classes=10, channels_last=channels_last,
                        input_format=input_format, stem=stem),
        optimizers.FusedAdam(1e-3), opt_level=opt_level, verbosity=0)
    ddp = parallel.DistributedDataParallel(
        model, comm_topology=comm_topology,
        allreduce_compress_bf16=compress, ici_size=ici_size)
    params, bn = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    rng = np.random.RandomState(0)
    shape = (B, 3, image, image) if input_format == "NCHW" \
        else (B, image, image, 3)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, B), jnp.int32)
    dm = observability.DeviceMetrics(
        counters=("steps", "overflows"),
        gauges=("loss_scale", "grad_norm")) if telemetry else None
    ndev = world if world is not None else len(jax.devices())
    if world is not None:
        _require_devices(world)
    if ici_size is not None and (ndev < ici_size
                                 or ndev % ici_size):
        # bare RuntimeError = the device-count skip gate (run_lint's
        # skip_runtime_errors): a 1-device smoke host cannot trace a
        # 2-level mesh, and the old ValueError from the group builder
        # crashed bench --graph-lint instead of skipping the EP
        raise RuntimeError(
            f"this entry point needs an axis of a multiple of "
            f"ici_size={ici_size} devices; ambient mesh has {ndev}")
    nm = None
    digest_plan = []
    if numerics is not None:
        grad_plan = parallel.allreduce_comm_plan(
            params, comm_topology=comm_topology,
            allreduce_compress_bf16=compress, ici_size=ici_size,
            world=ndev, nproc=1)
        digest_plan = obs_numerics.digest_comm_plan(params)
        nm = obs_numerics.NumericsMonitor(
            params, half_dtype="bfloat16",
            bucket_labels=obs_numerics.bucket_labels(grad_plan),
            digest=True, axis_name="data",
            enabled=(numerics == "on"))

    def step(state, batch):
        if telemetry:
            params, bn, ost, tele = state
        elif nm is not None:
            params, bn, ost, ntele = state
        else:
            params, bn, ost = state
        xb, yb = batch

        def loss_fn(p):
            out, nb = model.apply(p, xb, state=bn, train=True)
            return F.cross_entropy(out, yb), nb

        loss, nb, g = amp.scaled_grad(loss_fn, params, ost, has_aux=True)
        if nm is not None and nm.enabled:
            nout: list = []
            g = ddp.allreduce_grads(g, numerics_out=nout)
            params, ost2, info = opt.step(params, ost, g,
                                          grad_health=nm)
            ntele = nm.update(ntele, grad_stats=info["grad_health"],
                              bucket_stats=nout,
                              found_inf=info["found_inf"],
                              loss_scale=info["loss_scale"],
                              sync_tree=params)
            return (params, nb, ost2, ntele), jax.lax.pmean(loss, "data")
        g = ddp.allreduce_grads(g)
        params, ost2, info = opt.step(params, ost, g)
        if telemetry:
            tele = dm.inc(tele, "steps")
            tele = dm.inc(tele, "overflows", info["found_inf"])
            tele = dm.set(tele, "loss_scale", info["loss_scale"])
            tele = dm.set(tele, "grad_norm", info["grad_norm"])
            return (params, nb, ost2, tele), jax.lax.pmean(loss, "data")
        if nm is not None:
            # disabled monitor: ntele is an empty pytree and update is
            # an identity — zero extra leaves, zero extra eqns, so the
            # trace is byte-identical to the uninstrumented baseline
            ntele = nm.update(ntele)
            return (params, nb, ost2, ntele), jax.lax.pmean(loss, "data")
        return (params, nb, ost2), jax.lax.pmean(loss, "data")

    # divergent-output ledger (spec-consistency rule): the seed's
    # intended non-SyncBN semantics — every rank updates its BN running
    # stats from LOCAL batch statistics, so each floating BN-state leaf
    # (2 stats x 20 BN layers = 40) diverges across ranks despite the
    # replicated out_spec.  The ENABLED numerics monitor adds 3 carry
    # leaves derived from rank-local bucket stats before their flush.
    divergent = sum(
        1 for leaf in jax.tree_util.tree_leaves(bn)
        if np.issubdtype(np.asarray(leaf).dtype, np.floating))
    if numerics == "on":
        divergent += 3
    _fill_ddp_expectations(ep, opt_level, params,
                           comm_topology=comm_topology,
                           compress=compress, ici_size=ici_size,
                           extra_plan=digest_plan if (
                               numerics == "on") else None,
                           world=ndev, divergent_outputs=divergent)
    if numerics is not None:
        ep.expect.setdefault("numerics", {
            "baseline": "ddp_resnet18_o2",
            "enabled": numerics == "on",
            "extra_collectives": {"psum": 1} if numerics == "on" else {},
            "extra_payload_bytes": (digest_plan[0]["wire_bytes"]
                                    if numerics == "on" else 0)})
    if supervised is not None:
        # the operational-plane contract (PR 10): attaching a run
        # supervisor changes NOTHING in the jitted step — wrap_step is
        # an identity whether the supervisor is enabled or not, and
        # the supervisor rule verifies the traced jaxpr stays
        # byte-identical to the unsupervised baseline
        sup = observability.RunSupervisor(
            f"ep_{ep.name}", enabled=(supervised == "on"))
        step = sup.wrap_step(step)
        ep.expect.setdefault("supervisor", {
            "baseline": "ddp_resnet18_o2",
            "enabled": supervised == "on"})
    state = (params, bn, ost) \
        + ((dm.init(),) if telemetry else ()) \
        + ((nm.init(),) if nm is not None else ())
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("data",))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), (P("data"), P("data"))),
                           out_specs=(P(), P()), check_vma=False)
    # O1's op-boundary casts consult the policy amp.initialize just
    # installed; capture it for the deferred trace (O0/O2/O3 see the
    # clean base policy thanks to the graph() leak barrier)
    from ..amp import policy as amp_policy
    pol = amp_policy.current_policy()
    return Graph(trace=_scoped(
        pol, lambda: jax.make_jaxpr(mapped)(state, (x, y))))


def _fill_ddp_expectations(ep, opt_level, params, comm_topology="flat",
                           compress=False, ici_size=None,
                           extra_plan=None, world=None,
                           divergent_outputs=0):
    """Derive the amp + collective expectations for a DDP train step.

    Comm accounting: the step's collective population is exactly the
    grad buckets of ``allreduce_comm_plan`` under the SAME topology
    knobs the step's DDP wrapper carries — one psum per bucket for the
    flat topology; reduce_scatter + DCN reduce + all_gather per bucket
    for the hierarchical one, per-level payloads included — folded by
    ``plan_collective_expectations``, plus two fp32 scalars: the
    axis-size psum ``gradient_average`` divides by, and the
    ``pmean(loss)`` the step returns.  Grad dtypes equal the amp-cast
    param dtypes (``scaled_grad`` differentiates wrt the cast tree), so
    the plan over ``params`` IS the plan over the grads.
    """
    from .. import amp, parallel
    import jax
    dt = str(np.dtype(amp.compute_dtype(opt_level)))
    ep.expect.setdefault("amp", {
        # resnet18 fwd has 20 convs; backward adds dgrad+wgrad per conv
        # minus the input dgrad — 40 is a sanity floor, not a census
        "opt_level": opt_level, "conv_dtype": dt, "min_convs": 40,
        # the fc head forward dot; dgrad/wgrad have a (B, 10)-sized
        # operand below the large-dot threshold
        "dot_dtype": dt, "min_dots": 1})
    plan = parallel.allreduce_comm_plan(
        params, comm_topology=comm_topology,
        allreduce_compress_bf16=compress, ici_size=ici_size,
        world=world if world is not None else len(jax.devices()),
        nproc=1)
    # ``extra_plan``: additional planned collectives beyond the grad
    # reduction — the numerics divergence digest's one psum
    # (numerics.digest_comm_plan) folds in here so the collective
    # rule's expectations stay exact on instrumented steps
    ep.expect.setdefault(
        "collectives",
        parallel.plan_collective_expectations(
            plan + list(extra_plan or []),
            extra_psums=2, extra_psum_bytes=2 * 4))
    # cost/memory accounting (PR 8): under a bf16 compute policy no
    # measurable share of dot/conv FLOPs may run in fp32 (the silent
    # upcast halves MXU rate exactly where the flops are), and the
    # step's peak live bytes stay within a fixed multiple of its
    # argument bytes (~2.6x today: params + fp32 masters/moments +
    # activations; 4x flags a graph suddenly holding a second copy of
    # everything).  Resnet18's train step traces ~126 MFLOP of matmul
    # work — the floor keeps the fraction check non-vacuous.
    if np.dtype(amp.compute_dtype(opt_level)) != np.dtype(np.float32):
        ep.expect.setdefault("flops", {"max_fp32_matmul_fraction": 0.02,
                                       "min_matmul_flops": 1e6})
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 4.0})
    # sharding plane (PR 18): the mesh the step maps over, plus the
    # DECLARED divergent-output count — the spec-consistency rule
    # re-derives the count from the partition propagator and flags any
    # drift in either direction (see _ddp_resnet_graph for what the
    # declared leaves are).  The resharding census is plan-derived like
    # the collective census: the hierarchical buckets' reduce_scatter /
    # all_gather payloads are the ONLY sanctioned reshards, and the
    # flat plan sanctions none (psums never reshard).
    ep.expect.setdefault("sharding", {
        "mesh_axes": {"data": world if world is not None
                      else len(jax.devices())},
        "divergent_outputs": divergent_outputs})
    ep.expect.setdefault(
        "resharding",
        parallel.plan_resharding_expectations(
            plan + list(extra_plan or [])))


for _lvl in ("O0", "O1", "O2", "O3"):
    register_entry_point(
        f"ddp_resnet18_{_lvl.lower()}", tags=("training", "ddp", "amp"),
        description=f"DDP resnet18 {_lvl} train step, NCHW, 8-way mesh")(
        lambda ep, lvl=_lvl: _ddp_resnet_graph(ep, lvl))

register_entry_point(
    "ddp_resnet18_o2_telemetry", tags=("training", "ddp", "amp",
                                       "telemetry"),
    description="DDP resnet18 O2 step with DeviceMetrics threaded "
                "through the carry — must stay host-transfer-free")(
    lambda ep: _ddp_resnet_graph(ep, "O2", telemetry=True))

# numerics observability (PR 9): the SAME O2 step with a
# NumericsMonitor threaded through the carry — per-layer grad health
# (amp's grad_health hook), per-bucket stats riding the allreduce
# bucket structure, and the one-psum cross-replica divergence digest.
# The numerics rule pins the contract both ways: the "on" variant adds
# zero host transfers and EXACTLY the digest plan's collective delta
# over the uninstrumented baseline; the "off" variant (same step code,
# disabled monitor) must trace to the byte-identical jaxpr.
register_entry_point(
    "ddp_resnet18_o2_numerics", tags=("training", "ddp", "amp",
                                      "numerics", "telemetry"),
    description="DDP resnet18 O2 step with device-resident numerics "
                "accounting (grad health + bucket stats + divergence "
                "digest) — zero host transfers, plan-exact collectives")(
    lambda ep: _ddp_resnet_graph(ep, "O2", numerics="on"))

register_entry_point(
    "ddp_resnet18_o2_numerics_off", tags=("training", "ddp",
                                          "numerics"),
    description="DDP resnet18 O2 step with numerics DISABLED — must "
                "lower byte-identical to the uninstrumented step")(
    lambda ep: _ddp_resnet_graph(ep, "O2", numerics="off"))

# operational plane (PR 10): the SAME O2 step routed through
# RunSupervisor.wrap_step.  The supervisor is host-side by contract —
# it consumes already-flushed signals — so BOTH the enabled and the
# disabled variant must trace to the byte-identical jaxpr of the
# uninstrumented baseline with zero host transfers (the supervisor
# rule; mutation-tested both ways in tests/test_analysis.py like the
# numerics rule).
register_entry_point(
    "ddp_resnet18_o2_supervised", tags=("training", "ddp", "amp",
                                        "supervisor", "telemetry"),
    description="DDP resnet18 O2 step under an ENABLED run supervisor "
                "— must stay byte-identical to the bare step (the "
                "supervisor reads host flush points only)")(
    lambda ep: _ddp_resnet_graph(ep, "O2", supervised="on"))

register_entry_point(
    "ddp_resnet18_o2_supervised_off", tags=("training", "ddp",
                                            "supervisor"),
    description="DDP resnet18 O2 step under a DISABLED run supervisor "
                "— byte-identical to the bare step")(
    lambda ep: _ddp_resnet_graph(ep, "O2", supervised="off"))

register_entry_point(
    "ddp_resnet18_o2_nhwc", tags=("training", "ddp", "amp", "layout"),
    expect={"layout": {"min_activation_elems": _RESNET_ACT_ELEMS,
                       "allowed_6d_rearranges": 0}},
    description="DDP resnet18 O2 channels-last step — transpose-free")(
    lambda ep: _ddp_resnet_graph(ep, "O2", channels_last=True,
                                 input_format="NHWC"))

# hierarchical two-level gradient communication (ICI/DCN): the same O2
# step with comm_topology="hierarchical" over a virtual 2-slice mesh
# (ici_size=4 on the 8-device CPU mesh — jaxpr properties are
# backend-independent, so the group structure pins what a real
# 2-host x 4-chip run communicates).  The collective expectations are
# DERIVED from allreduce_comm_plan under the same knobs: per-bucket
# reduce_scatter/psum/all_gather counts and the per-primitive payload
# split, where the bucket psum payload IS the DCN hop — 1/ici_size of
# the flat payload.
register_entry_point(
    "ddp_resnet18_o2_hier", tags=("training", "ddp", "amp", "hier"),
    description="DDP resnet18 O2 step, hierarchical ICI/DCN allreduce "
                "(ici_size=4 on the 8-way mesh)")(
    lambda ep: _ddp_resnet_graph(ep, "O2", comm_topology="hierarchical",
                                 ici_size=4))

register_entry_point(
    "ddp_resnet18_o2_hier_bf16", tags=("training", "ddp", "amp", "hier"),
    description="DDP resnet18 O2 step, hierarchical allreduce with "
                "bf16-compressed DCN hop")(
    lambda ep: _ddp_resnet_graph(ep, "O2", comm_topology="hierarchical",
                                 ici_size=4, compress=True))

# elastic recovery (PR 11): the POST-SHRINK step.  When a replica dies
# mid-run, fleet.recovery.ElasticTrainer re-jits the train step on the
# surviving world (here 8 → 4, ici_size 4 → 2: losing a host halves
# the slice, the same placement at half the fabric) — this entry point
# pins that the shrunk step lints clean with collective expectations
# RE-DERIVED from allreduce_comm_plan at the new world size: per-
# bucket reduce_scatter/psum/all_gather counts and per-level payloads
# all recomputed, the axis-size psum and the loss pmean still exactly
# two fp32 scalars.  predivide_factors needs no pinning beyond this:
# it divides by the mapped axis size, which IS the new world.
register_entry_point(
    "ddp_resnet18_o2_hier_world4", tags=("training", "ddp", "amp",
                                         "hier", "recovery"),
    description="DDP resnet18 O2 step re-jitted on the shrunk 4-device "
                "world (ici_size=2) — the post-recovery step, "
                "plan-derived expectations at world 4")(
    lambda ep: _ddp_resnet_graph(ep, "O2", comm_topology="hierarchical",
                                 ici_size=2, world=4))

register_entry_point(
    "ddp_resnet18_o2_nhwc_s2d", tags=("training", "ddp", "amp", "layout"),
    # the 6-D block rearrange inside F.space_to_depth is the ONE
    # legitimate activation transpose (forward-only: the input is a
    # constant, so no gradient flows back through it)
    expect={"layout": {"min_activation_elems": _RESNET_ACT_ELEMS,
                       "allowed_6d_rearranges": 1}},
    description="DDP resnet18 O2 NHWC space-to-depth stem step")(
    lambda ep: _ddp_resnet_graph(ep, "O2", channels_last=True,
                                 input_format="NHWC",
                                 stem="space_to_depth"))


# -- overlapped gradient communication (PR 14) ----------------------------

def _staged_mlp_graph(ep, overlap=True, comm_topology="hierarchical",
                      compress=False, ici_size=4, stages=4, hidden=32,
                      B=8):
    """The overlapped DDP train step (ROADMAP item 2): a sequential
    ``stages``-deep MLP whose backward runs stage-by-stage through
    ``DistributedDataParallel.staged_allreduce_grads`` — with
    ``overlap=True`` each stage's bucket reduction is ISSUED while the
    earlier stages' gradients are still being computed, which is a
    *position* property of the jaxpr: the collective census and
    payloads are byte-identical to the reduce-after-backward schedule,
    and only the interleaving check (derived from
    ``overlap_comm_schedule`` like every other expectation here) can
    tell them apart.  ``overlap=False`` builds that baseline schedule
    from the SAME staged step — the mutation tests lint it under the
    overlap expectations and require the position check to flag."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from .. import parallel

    ndev = len(jax.devices())
    if ici_size is not None and (ndev < ici_size or ndev % ici_size):
        # bare RuntimeError = the device-count skip gate (see
        # _ddp_resnet_graph): a 1-device smoke host cannot trace the
        # 2-level mesh
        raise RuntimeError(
            f"this entry point needs an axis of a multiple of "
            f"ici_size={ici_size} devices; ambient mesh has {ndev}")
    rng = np.random.RandomState(14)
    stage_params = [
        {"w": jnp.asarray(rng.randn(hidden, hidden) * 0.1, jnp.float32),
         "b": jnp.zeros((hidden,), jnp.float32)}
        for _ in range(stages)]
    x = jnp.asarray(rng.randn(B, hidden), jnp.float32)
    y = jnp.asarray(rng.randn(B, hidden), jnp.float32)
    stage_fns = [lambda p, a: jnp.tanh(a @ p["w"] + p["b"])] * stages
    ddp = parallel.DistributedDataParallel(
        comm_topology=comm_topology, allreduce_compress_bf16=compress,
        ici_size=ici_size, overlap=overlap)

    def step(params_list, batch):
        xb, yb = batch
        loss, grads = ddp.staged_allreduce_grads(
            stage_fns, lambda a: jnp.mean((a - yb) ** 2), params_list,
            xb)
        new = [jax.tree_util.tree_map(lambda w, g: w - 0.1 * g, p, g)
               for p, g in zip(params_list, grads)]
        return new, lax.pmean(loss, "data")

    schedule = parallel.overlap_comm_schedule(
        stage_params, comm_topology=comm_topology,
        allreduce_compress_bf16=compress, ici_size=ici_size,
        world=ndev, nproc=1, overlap=overlap)
    # census/payloads from the schedule (the same per-bucket accounting
    # allreduce_comm_plan uses) + 2 fp32 scalars: the ONE shared
    # axis-size psum (world_scalar=) and the loss pmean; overlapped
    # mode additionally pins the interleaving position property
    ep.expect.setdefault(
        "collectives",
        parallel.overlap_collective_expectations(
            schedule, extra_psums=2, extra_psum_bytes=2 * 4))
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 4.0})
    # sharding plane: params replicated, batch sharded over data, and
    # every output provably agrees (the per-stage allreduce chains
    # resolve to replicated); the census sanctions exactly the
    # schedule's per-bucket reduce_scatter/all_gather payloads
    ep.expect.setdefault("sharding", {"mesh_axes": {"data": ndev},
                                      "divergent_outputs": 0})
    ep.expect.setdefault(
        "resharding",
        parallel.plan_resharding_expectations(schedule["buckets"]))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), (P("data"), P("data"))),
                           out_specs=(P(), P()), check_vma=False)
    return Graph(trace=_scoped(
        _no_policy(),
        lambda: jax.make_jaxpr(mapped)(stage_params, (x, y))))


register_entry_point(
    "ddp_mlp_overlap_flat", tags=("training", "ddp", "overlap"),
    description="staged 4-stage MLP DDP step, OVERLAPPED flat "
                "allreduce — per-stage psums interleaved with the "
                "backward, position-pinned")(
    lambda ep: _staged_mlp_graph(ep, comm_topology="flat",
                                 ici_size=None))

register_entry_point(
    "ddp_mlp_overlap_hier", tags=("training", "ddp", "overlap", "hier"),
    description="staged 4-stage MLP DDP step, OVERLAPPED hierarchical "
                "ICI/DCN allreduce (ici_size=4) — bucket i's "
                "reduce_scatter/DCN-psum/all_gather chain issued while "
                "bucket i-1's grads are still in backward")(
    lambda ep: _staged_mlp_graph(ep))

register_entry_point(
    "ddp_mlp_overlap_hier_bf16", tags=("training", "ddp", "overlap",
                                       "hier"),
    description="staged 4-stage MLP DDP step, overlapped hierarchical "
                "allreduce with bf16-compressed DCN hop")(
    lambda ep: _staged_mlp_graph(ep, compress=True))


# -- ZeRO weight-update sharding (PR 20) ----------------------------------

def _zero_collective_expectations(plan, parallel):
    """Fold a ``zero_update_comm_plan`` into the collectives
    expectation: the plan's buckets plus the step's three scalar
    collectives OUTSIDE the plan — the grad-norm psum (full-axis for
    stage 1, in-slice for stages 2/3: one eqn either way), the loss
    pmean, and the ``pmax(found_inf)`` the loss scaler syncs skips
    with (ZeRO shards must overflow-skip together or the master
    shards diverge)."""
    exp = parallel.plan_collective_expectations(
        plan, extra_psums=2, extra_psum_bytes=2 * 4)
    exp["counts"]["pmax"] = exp["counts"].get("pmax", 0) + 1
    exp["payload_bytes"] += 4
    by = exp["payload_bytes_by_primitive"]
    by["pmax"] = by.get("pmax", 0) + 4
    return exp


def _zero_resnet_graph(ep, zero_stage, compress=False, ici_size=4,
                       B=8, image=32):
    """The ZeRO train step over the 8-device mesh: the SAME O2 resnet18
    forward/backward as ``ddp_resnet18_o2`` but with NO separate grad
    allreduce — ``AmpOptimizer.step`` owns the reduction, and what it
    issues depends on the stage:

    - stage 1: full-axis reduce_scatter of the flat fp32 grads, shard
      update, full-axis all_gather of the updated half params.
    - stage 2: in-slice reduce_scatter (ici groups) + DCN reduce of
      the 1/ici shard, shard update against the DCN-replicated
      optimizer state, in-slice all_gather back.
    - stage 3: the fp32 master shard IS the parameter store —
      ``zero_gather_params`` all-gathers each slice's params
      just-in-time in the forward (and its ``jax.checkpoint`` replay
      re-gathers in the backward), the cotangent arrives as the flat
      in-slice grad shard via the gather's transpose
      (reduce_scatter), and the step updates the shard with NO
      gathers of its own.

    Every collective/resharding expectation is derived from
    ``parallel.zero_update_comm_plan`` under the same knobs — the
    static plan the runtime documentation, bench ``--comm`` legs and
    this census all share."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from .. import amp, optimizers, parallel, models
    from ..nn import functional as F

    world = 8
    _require_devices(world)
    isz = ici_size if zero_stage >= 2 else None
    if isz is not None and world % isz:
        raise RuntimeError(
            f"this entry point needs an axis of a multiple of "
            f"ici_size={isz} devices; ambient mesh has {world}")
    model, opt = amp.initialize(
        models.resnet18(num_classes=10),
        optimizers.FusedAdam(1e-3), opt_level="O2", verbosity=0)
    params, bn = model.init(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, 3, image, image), jnp.float32)
    y = jnp.asarray(rng.randint(0, 10, B), jnp.int32)
    mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
    ospecs = amp.zero_optimizer_specs(
        opt, params, "data", zero_stage=zero_stage, zero_ici_size=isz,
        zero_compress_bf16=compress)
    ost = jax.jit(jax.shard_map(
        lambda p: opt.init(p, zero_axis="data", zero_stage=zero_stage,
                           zero_ici_size=isz,
                           zero_compress_bf16=compress),
        mesh=mesh, in_specs=(P(),), out_specs=ospecs,
        check_vma=False))(params)

    if zero_stage == 3:
        # masters ARE the params: the carry holds no model param tree,
        # and the loss differentiates wrt the flat fp32 shard through
        # the just-in-time gather.  The forward is wrapped under the
        # named-checkpoint policy: activations stay saved, but the
        # gathered parameter buffer is rematerialized — the backward
        # RE-GATHERS the slice params instead of holding the full
        # model live across the step, which is the ZeRO-3 memory/wire
        # trade the plan's two jit_gather buckets account for
        def step(state, batch):
            bn, ost = state
            xb, yb = batch

            def fwd(m):
                p = amp.zero_gather_params(m)
                out, nb = model.apply(p, xb, state=bn, train=True)
                return F.cross_entropy(out, yb), nb

            loss_fn = jax.checkpoint(
                fwd, policy=amp.zero_gather_checkpoint_policy())

            loss, nb, g = amp.scaled_grad(loss_fn, ost.masters, ost,
                                          has_aux=True)
            _, ost2, _ = opt.step((), ost, g)
            return (nb, ost2), jax.lax.pmean(loss, "data")

        state = (bn, ost)
        in_state = (P(), ospecs)
    else:
        def step(state, batch):
            params, bn, ost = state
            xb, yb = batch

            def loss_fn(p):
                out, nb = model.apply(p, xb, state=bn, train=True)
                return F.cross_entropy(out, yb), nb

            loss, nb, g = amp.scaled_grad(loss_fn, params, ost,
                                          has_aux=True)
            # no ddp.allreduce_grads: step() reduce-scatters the grads
            # and gathers the updated params internally
            params, ost2, _ = opt.step(params, ost, g)
            return (params, nb, ost2), jax.lax.pmean(loss, "data")

        state = (params, bn, ost)
        in_state = (P(), P(), ospecs)

    plan = parallel.zero_update_comm_plan(
        params, zero_stage=zero_stage, world=world, ici_size=isz,
        zero_compress_bf16=compress)
    dt = str(np.dtype(amp.compute_dtype("O2")))
    ep.expect.setdefault("amp", {
        "opt_level": "O2", "conv_dtype": dt, "min_convs": 40,
        "dot_dtype": dt, "min_dots": 1})
    ep.expect.setdefault("collectives",
                         _zero_collective_expectations(plan, parallel))
    ep.expect.setdefault("flops", {"max_fp32_matmul_fraction": 0.02,
                                   "min_matmul_flops": 1e6})
    # measured jaxpr_live_bytes on the 8-device CPU mesh, declared at
    # ~1.05x so a regression (an un-donated buffer, a second fp32
    # activation tree) trips the budget while trace noise does not:
    #   zero1  live/args 3.283  temps {bf16 22.5M, f32 89.5M, bool 2.8M}
    #   zero2  live/args 2.599  temps {bf16 22.5M, f32 89.5M, bool 5.6M}
    #   zero3  live/args 2.658  temps {bf16 22.5M, f32 55.9M, bool 5.6M}
    # (stage 3's fp32 temp peak is ~37% below stage 1/2: the half-dtype
    # jit gather + custom-vjp grad pack never materialize the fp32
    # full model)
    mem_budget = {
        1: {"max_live_to_argument_ratio": 3.45,
            "temp_budget_bytes_by_dtype": {
                dt: 23_700_000, "float32": 94_000_000,
                "bool": 2_950_000, "int32": 128}},
        2: {"max_live_to_argument_ratio": 2.73,
            "temp_budget_bytes_by_dtype": {
                dt: 23_700_000, "float32": 94_000_000,
                "bool": 5_900_000, "int32": 128}},
        3: {"max_live_to_argument_ratio": 2.80,
            "temp_budget_bytes_by_dtype": {
                dt: 23_700_000, "float32": 58_700_000,
                "bool": 5_900_000, "int32": 128}},
    }[zero_stage]
    ep.expect.setdefault("memory", mem_budget)
    divergent = sum(
        1 for leaf in jax.tree_util.tree_leaves(bn)
        if np.issubdtype(np.asarray(leaf).dtype, np.floating))
    if zero_stage == 2:
        # stage 2's gather-back is IN-SLICE: each returned param leaf
        # is provably equal only within its ICI slice, and the
        # cross-slice agreement rests on the DCN-replicated optimizer
        # state (P("data") in-specs can't express that), so the
        # partition propagator reports varies(data) for every param
        # output despite the replicated out-spec — the same declared
        # class as the non-synced BN stats, one per param leaf
        divergent += len(jax.tree_util.tree_leaves(params))
    # measured replication ledger (entry_point_sharding_record):
    # stages 1/2 keep the bf16 model replicated (156.9 MB world-total
    # duplicates); stage 3's only replicated bytes are the BN state,
    # scaler scalars and the gather index tables (1.27 MB) — the fp32
    # optimizer state's replicated fraction collapses 0.875 -> 0.005
    # vs ddp_resnet18_o2.  ~1.05x measured: the ratchet-down check
    # fires on stale over-declarations (RATCHET_FRACTION)
    ep.expect.setdefault("sharding", {
        "mesh_axes": {"data": world},
        "divergent_outputs": divergent,
        "max_replicated_bytes": (1_333_000 if zero_stage == 3
                                 else 164_800_000)})
    ep.expect.setdefault(
        "resharding", parallel.plan_resharding_expectations(plan))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(in_state,
                                     (P("data"), P("data"))),
                           out_specs=(in_state, P()), check_vma=False)
    from ..amp import policy as amp_policy
    pol = amp_policy.current_policy()
    return Graph(trace=_scoped(
        pol, lambda: jax.make_jaxpr(mapped)(state, (x, y))))


register_entry_point(
    "ddp_resnet18_o2_zero1", tags=("training", "ddp", "amp", "zero"),
    description="O2 resnet18 ZeRO-1 step — optimizer state sharded "
                "1/world, full-axis reduce_scatter + all_gather owned "
                "by the optimizer (the memory baseline the zero2/3 "
                "budgets ratchet against)")(
    lambda ep: _zero_resnet_graph(ep, 1))

register_entry_point(
    "ddp_resnet18_o2_zero2", tags=("training", "ddp", "amp", "zero",
                                   "hier"),
    description="O2 resnet18 ZeRO-2 step on the hierarchical fabric "
                "(ici_size=4): in-slice grad reduce_scatter + DCN "
                "shard reduce, DCN-replicated optimizer state, "
                "in-slice gather-back")(
    lambda ep: _zero_resnet_graph(ep, 2))

register_entry_point(
    "ddp_resnet18_o2_zero3", tags=("training", "ddp", "amp", "zero",
                                   "hier"),
    description="O2 resnet18 ZeRO-3 step: fp32 master shard is the "
                "parameter store, just-in-time in-slice param gather "
                "in forward + checkpoint re-gather in backward, grads "
                "arrive pre-scattered via the gather's transpose")(
    lambda ep: _zero_resnet_graph(ep, 3))


def _staged_mlp_zero2_graph(ep, compress=False, ici_size=4, stages=4,
                            hidden=32, B=8):
    """ZeRO-2 fused with the OVERLAPPED staged schedule (the tentpole
    composition): each stage's backward hands its flat grads to
    ``staged_zero2_allreduce_grads``, which reduce-scatters in-slice,
    DCN-reduces the 1/ici shard, updates the stage's PARAM SHARD in
    place, and gathers the updated params back — all issued while
    earlier stages' grads are still in backward.  Wire accounting is
    byte-identical to the plain hierarchical staged schedule (the
    gather carries updated params instead of grads), so the
    expectations come from ``overlap_comm_schedule(zero_stage=2)``
    exactly like the non-ZeRO overlap entry points — including the
    interleaving floor ``min_collectives_before_last_matmul`` that
    pins the overlap as a POSITION property."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P
    from .. import parallel

    ndev = len(jax.devices())
    if ndev < ici_size or ndev % ici_size:
        # bare RuntimeError = the device-count skip gate (see
        # _ddp_resnet_graph)
        raise RuntimeError(
            f"this entry point needs an axis of a multiple of "
            f"ici_size={ici_size} devices; ambient mesh has {ndev}")
    rng = np.random.RandomState(20)
    stage_params = [
        {"w": jnp.asarray(rng.randn(hidden, hidden) * 0.1, jnp.float32),
         "b": jnp.zeros((hidden,), jnp.float32)}
        for _ in range(stages)]
    x = jnp.asarray(rng.randn(B, hidden), jnp.float32)
    y = jnp.asarray(rng.randn(B, hidden), jnp.float32)
    stage_fns = [lambda p, a: jnp.tanh(a @ p["w"] + p["b"])] * stages
    ddp = parallel.DistributedDataParallel(
        comm_topology="hierarchical", allreduce_compress_bf16=compress,
        ici_size=ici_size, overlap=True, zero_stage=2)

    def step(params_list, batch):
        xb, yb = batch
        loss, new = ddp.staged_zero2_allreduce_grads(
            stage_fns, lambda a: jnp.mean((a - yb) ** 2), params_list,
            xb, lambda stage, p_sh, g_sh: p_sh - 0.1 * g_sh)
        return new, lax.pmean(loss, "data")

    schedule = parallel.overlap_comm_schedule(
        stage_params, comm_topology="hierarchical",
        allreduce_compress_bf16=compress, ici_size=ici_size,
        world=ndev, nproc=1, overlap=True, zero_stage=2)
    ep.expect.setdefault(
        "collectives",
        parallel.overlap_collective_expectations(
            schedule, extra_psums=2, extra_psum_bytes=2 * 4))
    # measured jaxpr_live_bytes: live/args 2.293, temps {f32 22,180,
    # int32 12, bool 1} — declared at ~1.05x (see _zero_resnet_graph)
    ep.expect.setdefault("memory", {
        "max_live_to_argument_ratio": 2.41,
        "temp_budget_bytes_by_dtype": {"float32": 23_300,
                                       "int32": 16, "bool": 4}})
    # every returned stage param came back through the IN-SLICE gather
    # of a shard updated against the slice-local window — cross-slice
    # agreement is real (the DCN reduce equalized the grads) but not
    # propagator-provable, so all 8 param leaves land in the declared
    # divergent class (see _zero_resnet_graph stage 2).  Replicated
    # ledger measures 118,272 bytes (the replicated activations/loss).
    ep.expect.setdefault("sharding", {
        "mesh_axes": {"data": ndev},
        "divergent_outputs": len(jax.tree_util.tree_leaves(
            stage_params)),
        "max_replicated_bytes": 124_000})
    ep.expect.setdefault(
        "resharding",
        parallel.plan_resharding_expectations(schedule["buckets"]))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), (P("data"), P("data"))),
                           out_specs=(P(), P()), check_vma=False)
    return Graph(trace=_scoped(
        _no_policy(),
        lambda: jax.make_jaxpr(mapped)(stage_params, (x, y))))


register_entry_point(
    "ddp_mlp_overlap_zero2", tags=("training", "ddp", "overlap", "hier",
                                   "zero"),
    description="staged 4-stage MLP, OVERLAPPED hierarchical ZeRO-2 "
                "fused update: per-stage in-slice reduce_scatter + DCN "
                "shard reduce + shard update + in-slice gather-back, "
                "issued while earlier stages are still in backward")(
    lambda ep: _staged_mlp_zero2_graph(ep))


# -- transformer-family O2 train steps ------------------------------------

def _transformer_graph(ep, family):
    """The real O2 DDP train step (fused-head loss) for a tiny
    transformer config over the 8-device CPU mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from .. import amp, optimizers, parallel, models

    if family == "gpt":
        net = models.GPT(models.GPTConfig(
            vocab_size=97, block_size=16, n_layer=2, n_head=4,
            n_embd=32, dropout=0.0))
    else:
        net = models.Llama(models.LlamaConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=16,
            tie_word_embeddings=True))
    model, opt = amp.initialize(net, optimizers.FusedAdam(1e-3),
                                opt_level="O2", verbosity=0)
    ddp = parallel.DistributedDataParallel(model)
    params, _ = model.init(jax.random.PRNGKey(0))
    ost = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 97, (8, 16)))

    def step(state, batch):
        params, ost = state
        (ids_b,) = batch

        def loss_fn(p):
            return model.loss(p, ids_b), ()

        loss, _, g = amp.scaled_grad(loss_fn, params, ost, has_aux=True)
        g = ddp.allreduce_grads(g)
        params, ost2, _ = opt.step(params, ost, g)
        return (params, ost2), jax.lax.pmean(loss, "data")

    dt = str(np.dtype(amp.compute_dtype("O2")))
    ep.expect.setdefault("amp", {
        # qkv/attention/MLP/fused-head dots, fwd and bwd
        "opt_level": "O2", "dot_dtype": dt, "min_dots": 10})
    plan = parallel.allreduce_comm_plan(params)
    ep.expect.setdefault(
        "collectives",
        parallel.plan_collective_expectations(
            plan, extra_psums=2, extra_psum_bytes=2 * 4))
    ep.expect.setdefault("flops", {"max_fp32_matmul_fraction": 0.02,
                                   "min_matmul_flops": 1e6})
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 4.0})
    # sharding plane: flat DDP — the plan sanctions NO reshards (every
    # bucket is a bare psum), so any all_gather that creeps into the
    # transformer step is an immediate census finding
    ep.expect.setdefault("sharding", {
        "mesh_axes": {"data": len(jax.devices())},
        "divergent_outputs": 0})
    ep.expect.setdefault("resharding",
                         parallel.plan_resharding_expectations(plan))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(P(), (P("data"),)),
                           out_specs=(P(), P()), check_vma=False)
    from ..amp import policy as amp_policy
    pol = amp_policy.current_policy()
    return Graph(trace=_scoped(
        pol, lambda: jax.make_jaxpr(mapped)((params, ost), (ids,))))


register_entry_point(
    "gpt_o2_train_step", tags=("training", "ddp", "amp", "transformer"),
    description="GPT O2 DDP train step (fused-head loss)")(
    lambda ep: _transformer_graph(ep, "gpt"))

register_entry_point(
    "llama_o2_train_step", tags=("training", "ddp", "amp", "transformer"),
    description="Llama O2 DDP train step (GQA, tied embeddings)")(
    lambda ep: _transformer_graph(ep, "llama"))


# -- serving engines ------------------------------------------------------

def _tiny_engine():
    import jax
    from .. import models, serving
    m = models.GPT(models.GPTConfig(vocab_size=64, block_size=32,
                                    n_layer=2, n_head=4, n_embd=32,
                                    dropout=0.0, n_kv_head=2))
    params, _ = m.init(jax.random.PRNGKey(0))
    return serving.Engine(m, params, slots=2, buf_len=32, window=8)


def _engine_step_k_graph(ep):
    import jax
    from .. import serving
    eng = _tiny_engine()
    args = (eng.ids, eng.cur_len, eng.cache, eng._slot_keys,
            eng._slot_temp, eng.limit, eng._eos)
    n_cache = len(jax.tree_util.tree_leaves(eng.cache))
    ep.expect.setdefault("donation", {
        # the big mutated window inputs — ids, the KV cache tree, the
        # RNG keys — must alias; the per-slot length vector cur_len is
        # covered by serving.DONATION_BLOCKLIST (PR 2 compile-cache
        # gotcha), and limit/eos are read-only scheduler state
        "expect_donated": ("ids", "cache", "keys"),
        "forbid_donated": ("temps", "limit", "eos"),
        "min_aliased": n_cache + 2})
    # a K-tick decode window mutates in place: live bytes stay O(cache
    # + params); a second cache copy materializing mid-window flags
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 2.5})
    return Graph(trace=_scoped(
                     _no_policy(),
                     lambda: jax.make_jaxpr(eng._step_k)(*args)),
                 lower=_scoped(_no_policy(),
                               lambda: eng._step_k.lower(*args)),
                 arg_names=serving.STEP_K_ARG_NAMES, example_args=args)


register_entry_point(
    "engine_step_k", tags=("serving", "donation"),
    description="Engine._step_k: the K-tick jitted decode window")(
    _engine_step_k_graph)


def _engine_prefill_graph(ep):
    import jax
    import jax.numpy as jnp
    from .. import serving
    eng = _tiny_engine()
    args = (eng.ids, eng.cache, None, 0, jnp.zeros((32,), jnp.int32))
    n_cache = len(jax.tree_util.tree_leaves(eng.cache))
    ep.expect.setdefault("donation", {
        # admission-path mutator: the cache row is scattered in place
        "expect_donated": ("ids", "cache"),
        "forbid_donated": ("slot", "row"),
        "min_aliased": n_cache + 1})
    # admission runs a full-buffer forward: activations push live bytes
    # to ~1.5x (params + cache); 2.5x budgets real headroom, not a leak
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 2.5})
    return Graph(trace=_scoped(
                     _no_policy(),
                     lambda: jax.make_jaxpr(eng._prefill_slot)(*args)),
                 lower=_scoped(_no_policy(),
                               lambda: eng._prefill_slot.lower(*args)),
                 arg_names=serving.PREFILL_SLOT_ARG_NAMES,
                 example_args=args)


register_entry_point(
    "engine_prefill_slot", tags=("serving", "donation"),
    description="Engine._prefill_slot: per-slot admission prefill")(
    _engine_prefill_graph)


def _tiny_paged_engine():
    import jax
    from .. import models, serving
    m = models.GPT(models.GPTConfig(vocab_size=64, block_size=32,
                                    n_layer=2, n_head=4, n_embd=32,
                                    dropout=0.0, n_kv_head=2))
    params, _ = m.init(jax.random.PRNGKey(0))
    return serving.PagedEngine(m, params, slots=2, buf_len=32,
                               block_size=8, prefill_chunk=8, window=8)


def _paged_step_k_graph(ep):
    import jax
    from .. import serving
    eng = _tiny_paged_engine()
    pending = eng._stage_pending()
    args = (eng.ids, eng.cur_len, eng.kv_len, eng.pool,
            eng._slot_keys, eng._slot_temp, eng.limit, eng._eos,
            eng.tables, eng.n_blk, eng.free_stack, eng.free_top,
            pending)
    n_pool = len(jax.tree_util.tree_leaves(eng.pool))
    ep.expect.setdefault("donation", {
        # the block pool is THE multi-GB resident and must alias in
        # place through the whole K-tick scan (gather/compute/scatter
        # per tick); ids and the RNG keys ride along.  cur_len /
        # kv_len / n_blk are per-slot length vectors covered by
        # serving.DONATION_BLOCKLIST (PR 2 compile-cache corruption
        # class), and the scheduler vectors (tables, free stack,
        # pending pack) are read-mostly
        "expect_donated": ("ids", "pool", "keys"),
        "forbid_donated": ("temps", "limit", "eos", "tables",
                           "free_stack", "free_top", "pending"),
        "min_aliased": n_pool + 2})
    # the dense per-slot gather materializes a pool-sized temporary
    # per tick next to the donated pool itself — ~2x pool + params is
    # the honest working set; 4x budgets headroom, not a leak
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 4.0})
    return Graph(trace=_scoped(
                     _no_policy(),
                     lambda: jax.make_jaxpr(eng._paged_step_k)(*args)),
                 lower=_scoped(_no_policy(),
                               lambda: eng._paged_step_k.lower(*args)),
                 arg_names=serving.PAGED_STEP_K_ARG_NAMES,
                 example_args=args)


register_entry_point(
    "paged_step_k", tags=("serving", "donation", "paged"),
    description="PagedEngine._paged_step_k: K continuous-batching "
                "ticks (chunked prefill + decode + in-graph block "
                "recycling + iteration-boundary admission)")(
    _paged_step_k_graph)


def _paged_admit_graph(ep):
    import jax
    import jax.numpy as jnp
    from .. import serving
    eng = _tiny_paged_engine()
    args = (eng.ids, eng.cur_len, eng.kv_len, eng.limit, eng._eos,
            eng._slot_keys, eng._slot_temp, eng.tables, eng.n_blk,
            eng.free_stack, eng.free_top, jnp.int32(0),
            jnp.zeros((32,), jnp.int32), jnp.int32(3), jnp.int32(8),
            jnp.int32(-1), jax.random.PRNGKey(1), jnp.float32(0.0),
            jnp.int32(1))
    ep.expect.setdefault("donation", {
        # admission is a scheduler-row seed, NOT a prefill: it writes
        # the ids row + key and pops block ids — there is no KV
        # argument to donate, and the blocklisted length vectors
        # (cur_len/kv_len/n_blk) must never alias
        "expect_donated": ("ids", "keys"),
        "forbid_donated": ("limit", "eos", "temps", "tables",
                           "free_stack", "free_top", "slot", "row"),
        "min_aliased": 2})
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 2.5})
    return Graph(trace=_scoped(
                     _no_policy(),
                     lambda: jax.make_jaxpr(eng._paged_admit)(*args)),
                 lower=_scoped(_no_policy(),
                               lambda: eng._paged_admit.lower(*args)),
                 arg_names=serving.PAGED_ADMIT_ARG_NAMES,
                 example_args=args)


register_entry_point(
    "paged_admit", tags=("serving", "donation", "paged"),
    description="PagedEngine._paged_admit: window-boundary block "
                "reservation + scheduler-row seed (no prefill)")(
    _paged_admit_graph)


def _seq2seq_step_k_graph(ep):
    import jax
    from .. import models, serving
    t5 = models.T5(models.T5Config(
        vocab_size=64, d_model=32, d_kv=8, d_ff=64, num_layers=1,
        num_heads=4, dropout_rate=0.0, relative_attention_num_buckets=8,
        relative_attention_max_distance=16))
    t5p, _ = t5.init(jax.random.PRNGKey(0))
    eng = serving.Seq2SeqEngine(t5, t5p, slots=2, src_len=8,
                                max_new_cap=8, window=4)
    args = (eng.state, eng.out, eng.n_new, eng.s_limit, eng._eos)
    ep.expect.setdefault("donation", {
        # slot state + the output buffer mutate every window; n_new is
        # the per-slot length vector (global blocklist)
        "expect_donated": ("state", "out"),
        "forbid_donated": ("limit", "eos")})
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 2.5})
    return Graph(trace=_scoped(
                     _no_policy(),
                     lambda: jax.make_jaxpr(eng._step_k)(*args)),
                 lower=_scoped(_no_policy(),
                               lambda: eng._step_k.lower(*args)),
                 arg_names=serving.SEQ2SEQ_STEP_K_ARG_NAMES,
                 example_args=args)


register_entry_point(
    "seq2seq_step_k", tags=("serving", "donation", "seq2seq"),
    description="Seq2SeqEngine._step_k: K decoder ticks in-graph")(
    _seq2seq_step_k_graph)


# -- tensor parallel ------------------------------------------------------

def _tp_train_step_graph(ep):
    """2x4 (data, model) mesh ParallelMLP train step: Megatron comm
    pattern — ONE row-parallel psum forward, ONE f-copy psum backward,
    plus the DDP grad bucket + axis-size scalar over data."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from .. import parallel
    from ..parallel import tensor_parallel as tp
    from ..nn import functional as F

    _require_devices(8)
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "model"))
    mlp = tp.ParallelMLP(8, 32, activation="relu")
    params, _ = mlp.init(jax.random.PRNGKey(6))
    specs = tp.partition_specs(mlp, params)
    ddp = parallel.DistributedDataParallel(mlp)
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(8, 8), jnp.float32)
    y = jnp.asarray(rng.randn(8, 8), jnp.float32)

    def step(p, xb, yb):
        def loss_fn(pp):
            return F.mse_loss(mlp(pp, xb), yb)
        grads = jax.grad(loss_fn)(p)
        grads = ddp.allreduce_grads(grads)     # data axis only
        return jax.tree_util.tree_map(lambda w, g: w - 0.1 * g, p, grads)

    # comm accounting, derived: ONE model-axis psum — the row-parallel
    # forward output, (B/2, 8) fp32 rows per device (the f-copy
    # backward psum computes dL/dx, which nothing consumes, so DCE
    # removes it); DDP over data contributes one psum per comm-plan
    # bucket over the LOCAL param shards (specs divide the model-axis
    # dims by 4) plus the axis-size scalar gradient_average divides by
    local = [
        jax.ShapeDtypeStruct(tp.local_shape(leaf.shape, spec, mesh),
                             leaf.dtype)
        for leaf, spec in zip(jax.tree_util.tree_leaves(params),
                              jax.tree_util.tree_leaves(
                                  specs, is_leaf=lambda s:
                                  isinstance(s, P)))]
    plan = parallel.allreduce_comm_plan(local)
    act_bytes = (x.shape[0] // mesh.shape["data"]) * 8 * 4
    ep.expect.setdefault(
        "collectives",
        parallel.plan_collective_expectations(
            plan, extra_psums=2, extra_psum_bytes=act_bytes + 4))
    ep.expect.setdefault("memory", {"max_live_to_argument_ratio": 4.0})
    # sharding plane: the 2x4 mesh, ONE declared divergent output — a
    # precision limit of the static propagator, not a real divergence:
    # DDP concatenates all local grad shards into one flat bundle
    # before the data-axis psum, and the partition model cannot see
    # through the concat/slice round trip, so the second bias's grad
    # conservatively reports varies(model) even though the psum made
    # the whole bundle agree along data and nothing mixed model ranks
    ep.expect.setdefault("sharding", {
        "mesh_axes": {"data": 2, "model": 4},
        "divergent_outputs": 1})
    ep.expect.setdefault("resharding",
                         parallel.plan_resharding_expectations(plan))
    mapped = jax.shard_map(step, mesh=mesh,
                           in_specs=(specs, P("data"), P("data")),
                           out_specs=specs, check_vma=False)
    return Graph(trace=_scoped(
        _no_policy(), lambda: jax.make_jaxpr(mapped)(params, x, y)))


register_entry_point(
    "tp_mlp_train_step", tags=("training", "tp"),
    description="DP x TP (2x4) ParallelMLP train step — Megatron "
                "psum pattern + DDP grad bucket")(
    _tp_train_step_graph)
