"""apex_tpu.analysis — static analysis over jaxprs and lowered
StableHLO that pins every hot-path invariant.

Apex's value is that mixed precision and data parallelism stay correct
*by construction* — a single silently-upcast conv or a botched buffer
donation erases the win the framework exists to deliver.  This package
enforces those invariants mechanically:

- a **rule engine** (:mod:`.core`, :mod:`.rules`): host-transfer,
  donation (incl. the per-slot length-vector blocklist and
  double-donation of shared buffers), amp dtype policy, channels-last
  layout, and collective accounting;
- an **entry-point registry** (:mod:`.entry_points`) tracing the real
  graphs bench.py, the examples and the serving engines execute;
- machine-readable findings exported as schema-versioned JSONL through
  ``observability.exporters`` — shared by the tests
  (tests/test_step_graph_audit.py), the CI gate
  (tests/ci/graph_lint.py) and the CLI::

      python -m apex_tpu.analysis            # lint every entry point
      python -m apex_tpu.analysis --list     # what would run
      python -m apex_tpu.analysis --tags serving --rules donation

See docs/analysis.md for the rule catalogue and how to add a rule.
"""

from .core import (Finding, Rule, RULES, register_rule, get_rule,
                   analyze, analyze_entry_point, findings_to_records,
                   run_lint, ERROR, WARNING)
from .graphs import (HOST_TRANSFER_PRIMS, COLLECTIVE_PRIMS, Graph,
                     walk_jaxpr, prim_eqns, host_transfer_eqns,
                     conv_eqns, large_dot_eqns, transpose_eqns,
                     collective_eqns, eqn_payload_bytes, lowered_text,
                     aliased_output_count, donated_arg_names,
                     duplicate_donated_leaves)
from .entry_points import (EntryPoint, ENTRY_POINTS,
                           register_entry_point, get, select,
                           entry_point_memory_record)
from .sharding import (Partition, ArgSharding, CollectiveSite,
                       ShardMapAnalysis, RESHARD_PRIMS, shard_map_eqns,
                       analyze_shard_map, analyze_sharding,
                       check_shard_map_specs, divergent_output_claims,
                       entry_point_sharding_record)
from .pallas_lint import (KernelSite, capture_kernel_sites, check_site,
                          collect_kernel_sites, lint_pallas_kernels)
from . import rules  # noqa: F401  (registers the core rule set)
from . import core
from . import graphs
from . import entry_points
from . import sharding
from . import pallas_lint

__all__ = [
    "Finding", "Rule", "RULES", "register_rule", "get_rule",
    "analyze", "analyze_entry_point", "findings_to_records",
    "run_lint", "ERROR", "WARNING",
    "HOST_TRANSFER_PRIMS", "COLLECTIVE_PRIMS", "Graph",
    "walk_jaxpr", "prim_eqns", "host_transfer_eqns", "conv_eqns",
    "large_dot_eqns", "transpose_eqns", "collective_eqns",
    "eqn_payload_bytes", "lowered_text", "aliased_output_count",
    "donated_arg_names", "duplicate_donated_leaves",
    "EntryPoint", "ENTRY_POINTS", "register_entry_point", "get",
    "select", "rules", "core", "graphs", "entry_points",
    "Partition", "ArgSharding", "CollectiveSite", "ShardMapAnalysis",
    "RESHARD_PRIMS", "shard_map_eqns", "analyze_shard_map",
    "analyze_sharding", "check_shard_map_specs",
    "divergent_output_claims", "entry_point_sharding_record",
    "sharding",
    "KernelSite", "capture_kernel_sites", "check_site",
    "collect_kernel_sites", "lint_pallas_kernels", "pallas_lint",
]
