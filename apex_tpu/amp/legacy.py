"""Legacy amp API: ``amp.init()`` handles and the deprecated OptimWrapper.

The reference keeps two generations of amp alive: the modern
``amp.initialize`` path and the original handle-based API —
``handle = amp.init(enabled=...)``, ``handle.wrap_optimizer(opt)``,
``with handle.scale_loss(loss, opt): ...`` (reference apex/amp/handle.py:
169-280 AmpHandle/NoOpHandle, apex/amp/opt.py:9-103 OptimWrapper).  Users
migrating from the reference may still hold handle-shaped code, so the
same surface exists here, built on the modern pieces: ``AmpHandle``
installs the O1 ``CastPolicy`` globally; ``OptimWrapper`` drives a
``BoundOptimizer`` under the covers.
"""

from __future__ import annotations

import contextlib
import warnings
from typing import Any, Callable

import jax.numpy as jnp

from . import policy as _policy

__all__ = ["init", "AmpHandle", "NoOpHandle", "OptimWrapper"]


class AmpHandle:
    """Handle returned by the legacy ``amp.init(enabled=True)``."""

    def __init__(self, enable_caching: bool = True, verbose: bool = False,
                 half_dtype=jnp.bfloat16):
        self._enable_caching = enable_caching  # accepted for API parity;
        # XLA CSEs repeated casts, so no cache object exists
        self._verbose = verbose
        self._all_wrappers = []
        self._is_active = True
        _policy.set_policy(_policy.CastPolicy(half_dtype))

    def is_active(self) -> bool:
        return self._is_active

    def wrap_optimizer(self, optimizer, num_loss: int = 1):
        """Returns the deprecated OptimWrapper (reference handle.py:222)."""
        self._all_wrappers.append(optimizer)
        return OptimWrapper(optimizer, self, num_loss)

    @contextlib.contextmanager
    def scale_loss(self, loss_fn: Callable, optimizer):
        """Legacy two-arg scale_loss; ``optimizer`` is an apex_tpu
        optimizer previously bound via ``amp.stateful.bind`` or an
        OptimWrapper from ``wrap_optimizer``."""
        if isinstance(optimizer, OptimWrapper):
            with optimizer.scale_loss(loss_fn) as scaled:
                yield scaled
            return
        from .handle import scale_loss as _modern
        with _modern(loss_fn, optimizer) as scaled:
            yield scaled

    def _deactivate(self) -> None:
        self._is_active = False
        _policy.set_policy(_policy.NoPolicy())


class NoOpHandle:
    """Handle returned by ``amp.init(enabled=False)`` — everything passes
    through untouched (reference handle.py:262-280)."""

    def is_active(self) -> bool:
        return False

    def wrap_optimizer(self, optimizer, num_loss: int = 1):
        return OptimWrapper(optimizer, self, num_loss)

    @contextlib.contextmanager
    def scale_loss(self, loss_fn: Callable, optimizer):
        yield loss_fn

    def _deactivate(self) -> None:
        pass


def init(enabled: bool = True, enable_caching: bool = True,
         verbose: bool = False, allow_banned: bool = False,
         half_dtype=jnp.bfloat16):
    """The original amp entry point (reference apex/amp/amp.py:68).
    Prefer ``amp.initialize``; this exists for migration parity."""
    if not enabled:
        return NoOpHandle()
    return AmpHandle(enable_caching, verbose, half_dtype)


class OptimWrapper:
    """Deprecated per-optimizer wrapper with per-loss scalers (reference
    apex/amp/opt.py:9-103)."""

    def __init__(self, optimizer, amp_handle, num_loss: int = 1):
        warnings.warn("OptimWrapper is deprecated; use amp.initialize + "
                      "amp.scaled_grad (or amp.scale_loss)",
                      DeprecationWarning, stacklevel=2)
        self._optimizer = optimizer
        self._amp_handle = amp_handle
        self._num_loss = num_loss
        self._loss_idx = 0
        self._bound = None  # bound in setup()

    # the reference requires params registered before use; here binding
    # happens through amp.stateful so state lives functionally
    def setup(self, params: Any) -> None:
        from . import stateful
        # the per-loss scalers live in the bound optimizer's state; make
        # sure it carries num_loss of them (reference opt.py:14-16)
        if getattr(self._optimizer, "num_losses", 1) < self._num_loss:
            self._optimizer.num_losses = self._num_loss
        self._bound = stateful.bind(self._optimizer, params)

    @property
    def params(self):
        return self._bound.params if self._bound else None

    @contextlib.contextmanager
    def scale_loss(self, loss_fn: Callable):
        if self._bound is None:
            raise RuntimeError("call OptimWrapper.setup(params) first")
        if self._loss_idx >= self._num_loss:
            raise RuntimeError(
                f"Unable to scale {self._num_loss + 1} losses — "
                f"OptimWrapper was constructed with num_loss={self._num_loss}"
                " (reference opt.py raises the same way)")
        loss_id = self._loss_idx

        class _Scaled:
            def __init__(self, bound):
                self._bound = bound

            def backward(self):
                self._bound._backward(loss_fn, loss_id)

            def __float__(self):
                return float(self._bound._eval_scaled_loss(loss_fn, loss_id))

        yield _Scaled(self._bound)
        self._bound._post_backward(loss_id)
        self._loss_idx += 1

    def step(self, closure=None):
        if closure is not None:
            raise NotImplementedError(
                "OptimWrapper does not support closures (reference "
                "opt.py:79-81)")
        self._loss_idx = 0
        self._bound.step()

    def zero_grad(self) -> None:
        self._bound.zero_grad()

    @property
    def loss_scale(self) -> float:
        return self._bound.loss_scale

    def state_dict(self) -> dict:
        from . import state_dict as _sd
        return _sd(self._bound.opt_state)

    def __getattr__(self, name):
        return getattr(self._optimizer, name)
