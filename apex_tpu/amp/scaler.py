"""Loss scaling: static and dynamic, as functional device-resident state.

State machine semantics are copied exactly from the reference
(apex/amp/scaler.py:39-72,190-210): dynamic scale starts at 2**16, halves on
overflow (clamped to ``min_loss_scale``), doubles after ``scale_window=2000``
consecutive clean steps (clamped to ``max_loss_scale``), and an overflowed
step is skipped.  The differences are deliberate TPU-isms:

- ``found_inf`` is a device fp32 scalar produced by the fused
  scale+finite-check (multi_tensor_scale), and ``update()`` is pure jnp, so
  the whole scaler lives inside jit with **zero host syncs per step** — the
  reference forces one D2H per iteration (scaler.py:192-193).
- Skipping a step is a ``lax.cond`` in the optimizer wrapper rather than a
  monkey-patched ``optimizer.step`` (reference handle.py:137-152).

With bfloat16 (the TPU-native half type) overflow is essentially impossible
(8 exponent bits, like fp32), so O2's default loss scale under bf16 is 1.0
static; fp16 keeps "dynamic" for behavioral parity.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..multi_tensor_apply import (multi_tensor_scale, multi_tensor_axpby)

__all__ = ["ScalerState", "LossScaler"]


class ScalerState(NamedTuple):
    loss_scale: jax.Array    # fp32 scalar
    unskipped: jax.Array     # int32 clean-step counter
    steps_skipped: jax.Array  # int32 total skipped (observability)


class LossScaler:
    """Configuration + pure transition functions over ScalerState."""

    def __init__(self, loss_scale: Any = "dynamic",
                 init_scale: float = 2.0 ** 16, scale_factor: float = 2.0,
                 scale_window: int = 2000, min_loss_scale: float = None,
                 max_loss_scale: float = 2.0 ** 24):
        if loss_scale == "dynamic":
            self.dynamic = True
            self._init_scale = init_scale
        else:
            self.dynamic = False
            self._init_scale = float(loss_scale)
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.min_loss_scale = min_loss_scale
        self.max_loss_scale = max_loss_scale

    # -- state ------------------------------------------------------------
    def init_state(self) -> ScalerState:
        return ScalerState(
            loss_scale=jnp.asarray(self._init_scale, jnp.float32),
            unskipped=jnp.zeros((), jnp.int32),
            steps_skipped=jnp.zeros((), jnp.int32))

    def loss_scale(self, state: ScalerState) -> jax.Array:
        return state.loss_scale

    # -- ops --------------------------------------------------------------
    def scale_loss(self, loss: jax.Array, state: ScalerState) -> jax.Array:
        return loss.astype(jnp.float32) * state.loss_scale

    def unscale(self, scaled_grads: Any, state: ScalerState,
                out_dtype=jnp.float32) -> Tuple[Any, jax.Array]:
        """grads/scale with fused overflow check; out cast to ``out_dtype``
        (the master-grad materialization of apex/amp/scaler.py:95-123)."""
        cast = jax.tree_util.tree_map(
            lambda g: g.astype(out_dtype), scaled_grads)
        return multi_tensor_scale(cast, 1.0 / state.loss_scale)

    def unscale_with_stashed(self, scaled_grads: Any, stashed: Any,
                             state: ScalerState) -> Tuple[Any, jax.Array]:
        """out = grads/scale + stashed — gradient accumulation across
        backward passes (apex/amp/scaler.py:149-182, multi_tensor_axpby)."""
        return multi_tensor_axpby(1.0 / state.loss_scale, 1.0,
                                  scaled_grads, stashed, arg_to_check=0)

    def update(self, state: ScalerState, found_inf: jax.Array) -> ScalerState:
        """Pure transition matching apex/amp/scaler.py:190-210."""
        if not self.dynamic:
            return state._replace(
                steps_skipped=state.steps_skipped + found_inf.astype(jnp.int32))
        overflow = found_inf > 0
        halved = state.loss_scale / self.scale_factor
        if self.min_loss_scale is not None:
            halved = jnp.maximum(halved, self.min_loss_scale)
        unskipped = jnp.where(overflow, 0, state.unskipped + 1)
        grow = unskipped >= self.scale_window
        grown = jnp.minimum(state.loss_scale * self.scale_factor,
                            self.max_loss_scale)
        new_scale = jnp.where(overflow, halved,
                              jnp.where(grow, grown, state.loss_scale))
        unskipped = jnp.where(grow, 0, unskipped)
        return ScalerState(
            loss_scale=new_scale,
            unskipped=unskipped.astype(jnp.int32),
            steps_skipped=state.steps_skipped + overflow.astype(jnp.int32))
