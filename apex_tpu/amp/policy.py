"""The dtype-cast policy consulted by apex_tpu.nn ops.

The reference implements O1 by monkey-patching ~200 torch entry points with
cast wrappers (apex/amp/amp.py:68-177, wrap.py:73-216).  JAX functions are
pure and cannot be patched per-handle, so the policy lives in a context the
framework's own functional ops consult at trace time.  Under ``jax.jit``
the casts are traced once and fused by XLA; the reference's casted-weight
cache (apex/amp/utils.py:87-119) is unnecessary because XLA CSEs repeated
casts of the same array.

Policies:

- ``NoPolicy``      — O0/O2/O3: ops execute in their inputs' dtypes (for
  O2/O3 the *parameters* were cast instead, see _initialize.py).
- ``CastPolicy``    — O1: whitelist ops cast args to the half dtype,
  blacklist ops to fp32, promote ops to the widest floating dtype of their
  args; banned ops raise with the reference's actionable message
  (functional_overrides.py:68-78).

``disable_casts()`` reproduces apex's escape hatch
(apex/amp/handle.py:162-166).
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from . import lists

__all__ = [
    "Policy", "NoPolicy", "CastPolicy", "current_policy", "set_policy",
    "use_policy", "disable_casts", "cast_op_args", "half_function",
    "float_function", "promote_function",
]

_FLOAT_DTYPES = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def _is_float_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
        jnp.result_type(x), jnp.floating)


def _cast_leaf(x: Any, dtype) -> Any:
    if _is_float_array(x) and jnp.result_type(x) != dtype:
        return x.astype(dtype)
    return x


def _cast_tree(tree: Any, dtype) -> Any:
    return jax.tree_util.tree_map(lambda x: _cast_leaf(x, dtype), tree)


def _widest_dtype(args: Sequence[Any]):
    widest = None
    order = {jnp.dtype(jnp.float16): 1, jnp.dtype(jnp.bfloat16): 1,
             jnp.dtype(jnp.float32): 2, jnp.dtype(jnp.float64): 3}

    def visit(x):
        nonlocal widest
        if _is_float_array(x):
            d = jnp.result_type(x)
            if widest is None or order.get(jnp.dtype(d), 0) > order.get(
                    jnp.dtype(widest), 0):
                widest = d
    jax.tree_util.tree_map(visit, list(args))
    return widest


class Policy:
    """Base: identity policy (no casting)."""

    enabled = False

    def cast_args(self, op_name: str, args: tuple, kwargs: dict):
        return args, kwargs


class NoPolicy(Policy):
    pass


class CastPolicy(Policy):
    """O1 whitelist/blacklist/promote casting, driven by amp.lists tables."""

    enabled = True

    def __init__(self, half_dtype=jnp.bfloat16, verbose: bool = False):
        self.half_dtype = jnp.dtype(half_dtype)
        self.verbose = verbose

    def _log(self, op_name: str, action: str) -> None:
        if self.verbose:
            from ._amp_state import maybe_print
            maybe_print(f"amp: {action} args of {op_name}")

    def cast_args(self, op_name: str, args: tuple, kwargs: dict):
        kind = lists.classify(op_name)
        if kind == "banned":
            raise NotImplementedError(lists.BANNED_MSG)
        if kind == "half":
            self._log(op_name, f"casting to {self.half_dtype.name}")
            return _cast_tree(args, self.half_dtype), _cast_tree(
                kwargs, self.half_dtype)
        if kind == "float":
            self._log(op_name, "casting to float32")
            return _cast_tree(args, jnp.float32), _cast_tree(
                kwargs, jnp.float32)
        if kind in ("promote", "sequence"):
            widest = _widest_dtype(list(args) + list(kwargs.values()))
            if widest is not None:
                self._log(op_name, f"promoting to {jnp.dtype(widest).name}")
                return _cast_tree(args, widest), _cast_tree(kwargs, widest)
        return args, kwargs


class _PolicyState(threading.local):
    def __init__(self):
        self.stack = [NoPolicy()]
        self.casts_disabled = 0


_STATE = _PolicyState()


def current_policy() -> Policy:
    if _STATE.casts_disabled:
        return _NO_POLICY
    return _STATE.stack[-1]


_NO_POLICY = NoPolicy()


def set_policy(policy: Policy) -> None:
    """Install ``policy`` as the process-wide default (what amp.initialize
    does for O1 — mirrors the global effect of apex's monkey-patching)."""
    _STATE.stack[0] = policy


@contextlib.contextmanager
def use_policy(policy: Policy):
    _STATE.stack.append(policy)
    try:
        yield policy
    finally:
        _STATE.stack.pop()


@contextlib.contextmanager
def disable_casts():
    """Temporarily run ops in their incoming dtypes (handle.py:162-166)."""
    _STATE.casts_disabled += 1
    try:
        yield
    finally:
        _STATE.casts_disabled -= 1


def cast_op_args(op_name: str, args: tuple, kwargs: dict):
    """Entry point used by apex_tpu.nn.functional at every op dispatch."""
    return current_policy().cast_args(op_name, args, kwargs)


def _wrap_with(cast: Callable, fn: Callable) -> Callable:
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        pol = current_policy()
        if not pol.enabled:
            return fn(*args, **kwargs)
        args, kwargs = cast(pol, args, kwargs)
        return fn(*args, **kwargs)
    return wrapper


def half_function(fn: Callable) -> Callable:
    """Decorator: run ``fn`` with float args cast to the policy half dtype
    (reference: apex/amp/amp.py:30-33)."""
    return _wrap_with(
        lambda p, a, k: (_cast_tree(a, p.half_dtype), _cast_tree(k, p.half_dtype)),
        fn)


def float_function(fn: Callable) -> Callable:
    """Decorator: run ``fn`` with float args cast to fp32 (amp.py:35-38)."""
    return _wrap_with(
        lambda p, a, k: (_cast_tree(a, jnp.float32), _cast_tree(k, jnp.float32)),
        fn)


def promote_function(fn: Callable) -> Callable:
    """Decorator: run ``fn`` with float args promoted to the widest incoming
    float dtype (amp.py:40-42)."""
    def cast(_p, a, k):
        widest = _widest_dtype(list(a) + list(k.values()))
        if widest is None:
            return a, k
        return _cast_tree(a, widest), _cast_tree(k, widest)
    return _wrap_with(cast, fn)
