"""apex_tpu.amp — automatic mixed precision for JAX on TPU.

Public surface mirrors apex.amp (reference apex/amp/__init__.py):

    model, optimizer = amp.initialize(model, optimizer, opt_level="O2")
    loss, scaled_grads = amp.scaled_grad(loss_fn, params, opt_state)
    params, opt_state, info = optimizer.step(params, opt_state, scaled_grads)

plus the eager API-parity path (amp.scale_loss / backward / step) and the
O1 registries (register_half_function etc.).
"""

from .frontend import (initialize, Properties, opt_levels, O0, O1, O2, O3,
                       compute_dtype, scaler_state, current_loss_scale,
                       steps_skipped, amp_stats, record_scaler)
from .handle import (scale_loss, scaled_grad, scaled_grad_accum,
                     disable_casts)
from .scaler import LossScaler, ScalerState
from ._process_optimizer import (AmpOptimizer, AmpOptState,
                                 zero_optimizer_specs,
                                 zero_gather_params,
                                 zero_gather_checkpoint_policy)
from ._initialize import AmpModel, cast_param_tree
from ._amp_state import master_params, maybe_print
from .policy import (CastPolicy, NoPolicy, current_policy, set_policy,
                     use_policy, half_function, float_function,
                     promote_function)
from .lists import (register_half_function, register_float_function,
                    register_promote_function)
from . import stateful
from . import lists
from . import policy
from .legacy import init, AmpHandle, NoOpHandle, OptimWrapper


def state_dict(bound_or_opt_state) -> dict:
    """Checkpoint the amp state (loss scalers) — the amp.state_dict the
    reference lacked in this snapshot (SURVEY.md §5 checkpoint gap)."""
    from .stateful import BoundOptimizer
    if isinstance(bound_or_opt_state, BoundOptimizer):
        opt_state = bound_or_opt_state.opt_state
    else:
        opt_state = bound_or_opt_state
    return {"scalers": [s._asdict() for s in opt_state.scalers]}


def load_state_dict(opt_state, sd: dict):
    import jax.numpy as jnp
    from .scaler import ScalerState
    scalers = tuple(ScalerState(**{k: jnp.asarray(v) for k, v in d.items()})
                    for d in sd["scalers"])
    return opt_state._replace(scalers=scalers)
