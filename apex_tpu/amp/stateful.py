"""Eager/stateful driver: torch-shaped training loops over the functional core.

Binds (params, opt_state) to an AmpOptimizer so user code can look like the
reference's examples (examples/imagenet/main_amp.py:335-351):

    model, optimizer = amp.initialize(model, optimizer, opt_level="O2")
    params, bn_state = model.init(key)
    bound = amp.stateful.bind(optimizer, params)

    def loss_fn(p):
        out, _ = model.apply(p, x)
        return criterion(out, y)

    with amp.scale_loss(loss_fn, optimizer) as scaled_loss:
        scaled_loss.backward()
    optimizer.step()        # == bound.step()

Grad accumulation across multiple backward() calls within one step uses
``unscale_with_stashed`` (axpby), matching apex/amp/scaler.py:149-182.
This path is for scripts and parity tests; the jit'd functional path is the
performance path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ._amp_state import maybe_print
from ._process_optimizer import AmpOptimizer, AmpOptState

__all__ = ["bind", "BoundOptimizer"]


class BoundOptimizer:
    def __init__(self, optimizer: AmpOptimizer, params: Any):
        self.optimizer = optimizer
        self.params = params
        self.opt_state: AmpOptState = optimizer.init(params)
        self._grads32: Optional[Any] = None      # unscaled accumulated grads
        self._found_inf = jnp.zeros((), jnp.float32)
        self._skip_next = False
        self._last_scale = None

    # -- driven by amp.scale_loss ------------------------------------------
    def _eval_scaled_loss(self, loss_fn: Callable, loss_id: int):
        scale = self.opt_state.scalers[loss_id].loss_scale
        return loss_fn(self.params) * scale

    def _backward(self, loss_fn: Callable, loss_id: int) -> None:
        scaler = self.optimizer.scaler
        sstate = self.opt_state.scalers[loss_id]
        scale = sstate.loss_scale
        grads = jax.grad(
            lambda p: loss_fn(p).astype(jnp.float32) * scale)(self.params)
        if self._grads32 is None:
            grads32, found = scaler.unscale(grads, sstate)
        else:
            grads32, found = scaler.unscale_with_stashed(
                jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), grads),
                self._grads32, sstate)
        self._grads32 = grads32
        self._found_inf = jnp.maximum(self._found_inf, found)

    def _post_backward(self, loss_id: int, delay_unscale: bool = False,
                       delay_overflow_check: bool = False) -> None:
        if delay_unscale or delay_overflow_check:
            return  # grads stay stashed for the next backward in this step
        scaler = self.optimizer.scaler
        sstate = self.opt_state.scalers[loss_id]
        old_scale = float(sstate.loss_scale)
        new_sstate = scaler.update(sstate, self._found_inf)
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(self.opt_state.scalers))
        self.opt_state = self.opt_state._replace(scalers=scalers)
        if bool(self._found_inf > 0):
            self._skip_next = True
            maybe_print(
                f"Gradient overflow.  Skipping step, loss scaler {loss_id} "
                f"reducing loss scale to {float(new_sstate.loss_scale)}")

    # -- torch-shaped methods ----------------------------------------------
    def zero_grad(self) -> None:
        self._grads32 = None
        self._found_inf = jnp.zeros((), jnp.float32)

    def step(self) -> None:
        if self._grads32 is None:
            raise RuntimeError("step() called before backward()")
        if self._skip_next:
            self._skip_next = False
            self.zero_grad()
            return
        inner = self.optimizer.inner
        ost = self.opt_state
        from ._process_optimizer import FlatMasters
        if isinstance(ost.masters, FlatMasters):
            lay = ost.masters.layout
            new_buf, new_inner, half = self.optimizer._flat_inner_step(
                ost.masters, ost.inner, lay.pack(self._grads32))
            self.params = lay.rebuild(
                new_buf, half, jax.tree_util.tree_leaves(self.params))
            self.opt_state = ost._replace(
                masters=FlatMasters(new_buf, lay), inner=new_inner)
        elif ost.masters is not None:
            new_masters, new_inner = inner.update(
                self._grads32, ost.inner, ost.masters)
            self.params = jax.tree_util.tree_map(
                lambda m, p: m.astype(p.dtype), new_masters, self.params)
            self.opt_state = ost._replace(masters=new_masters,
                                          inner=new_inner)
        else:
            grads = jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), self._grads32, self.params)
            self.params, new_inner = inner.update(grads, ost.inner, self.params)
            self.opt_state = ost._replace(inner=new_inner)
        self.zero_grad()

    @property
    def loss_scale(self) -> float:
        return float(self.opt_state.scalers[0].loss_scale)


def bind(optimizer: AmpOptimizer, params: Any) -> BoundOptimizer:
    """Attach (params, fresh opt_state) to ``optimizer`` for eager use."""
    bound = BoundOptimizer(optimizer, params)
    optimizer._bound = bound
    return bound
