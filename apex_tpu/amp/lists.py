"""Op-classification tables for the O1 cast policy.

The reference maintains three monkey-patch lists — fp16 whitelist (gemms and
convolutions), fp32 blacklist (transcendentals, reductions, losses, norms),
and type-promote ops — in apex/amp/lists/torch_overrides.py:7-61,83-105 and
functional_overrides.py:29-78.  Here the same classification is expressed as
*op categories* that the apex_tpu.nn functional layer consults at dispatch
time (JAX primitives cannot be safely monkey-patched per-handle; a policy
lookup at our own op boundary is the idiomatic equivalent).

User extension mirrors apex.amp's registries (apex/amp/amp.py:30-64):
``register_half_function`` / ``register_float_function`` /
``register_promote_function`` move an op name between categories, and the
``@half_function`` / ``@float_function`` / ``@promote_function`` decorators
wrap arbitrary user callables with the corresponding cast behavior.
"""

from __future__ import annotations

from typing import Callable, Set

# Ops that run fastest and safest in half precision on the MXU: dense
# matmuls and convolutions (reference: torch_overrides.py:7-27).
FP16_FUNCS: Set[str] = {
    "conv1d", "conv2d", "conv3d",
    "conv_transpose1d", "conv_transpose2d", "conv_transpose3d",
    "conv_tbc",
    "linear", "matmul", "mm", "mv", "bmm",
    "addmm", "addmv", "addr", "addbmm", "baddbmm",
    "prelu",
    # attention inner matmuls route through matmul; kept explicit for clarity
    "dot_product_attention",
}

# Ops numerically fragile in fp16/bf16: transcendentals, norms, reductions,
# losses, softmax (reference: torch_overrides.py:29-61,
# functional_overrides.py:29-66).
FP32_FUNCS: Set[str] = {
    "acos", "asin", "cosh", "erfinv", "exp", "expm1", "log", "log10",
    "log2", "log1p", "reciprocal", "rsqrt", "sinh", "tan", "pow",
    "softplus", "gelu", "erf",
    "cumprod", "cumsum", "dist", "mean", "norm", "prod", "std", "sum",
    "var", "renorm", "logsumexp",
    "softmax", "log_softmax", "softmin",
    "layer_norm", "group_norm", "batch_norm", "instance_norm", "normalize",
    "cosine_similarity", "pdist",
    "cross_entropy", "nll_loss", "l1_loss", "mse_loss", "smooth_l1_loss",
    "kl_div", "multilabel_margin_loss", "soft_margin_loss",
    "binary_cross_entropy_with_logits", "poisson_nll_loss",
    "cosine_embedding_loss", "hinge_embedding_loss", "margin_ranking_loss",
    "triplet_margin_loss", "multi_margin_loss",
}

# Multi-arg ops whose float args must agree: promote to the widest type
# (reference: torch_overrides.py:83-105).
PROMOTE_FUNCS: Set[str] = {
    "add", "sub", "mul", "div", "addcdiv", "addcmul", "atan2",
    "cross", "bilinear", "dot", "equal", "eq", "ne", "lt", "gt", "le", "ge",
    "min", "max", "fmod", "remainder",
}

# Sequence ops: promote every element of the tensor-sequence argument
# (reference: torch_overrides.py:109-112).
SEQUENCE_PROMOTE_FUNCS: Set[str] = {"cat", "stack", "concatenate"}

# Banned in half precision with an actionable error (reference:
# functional_overrides.py:68-78 — binary_cross_entropy after a sigmoid
# under-/overflows in fp16; users must switch to the fused logits form).
BANNED_FUNCS: Set[str] = {"binary_cross_entropy"}

BANNED_MSG = (
    "amp does not work out-of-the-box with `binary_cross_entropy` on half "
    "inputs: a sigmoid followed by BCE is numerically unsafe in half "
    "precision. Use `binary_cross_entropy_with_logits` (it fuses the "
    "sigmoid in fp32), or wrap your call with "
    "apex_tpu.amp.disable_casts() if you know what you're doing."
)


def classify(op_name: str) -> str:
    """Return one of 'half', 'float', 'promote', 'sequence', 'banned', 'none'."""
    if op_name in BANNED_FUNCS:
        return "banned"
    if op_name in FP16_FUNCS:
        return "half"
    if op_name in FP32_FUNCS:
        return "float"
    if op_name in PROMOTE_FUNCS:
        return "promote"
    if op_name in SEQUENCE_PROMOTE_FUNCS:
        return "sequence"
    return "none"


def _move(op_name: str, dest: Set[str]) -> None:
    for s in (FP16_FUNCS, FP32_FUNCS, PROMOTE_FUNCS, SEQUENCE_PROMOTE_FUNCS,
              BANNED_FUNCS):
        s.discard(op_name)
    dest.add(op_name)


def register_half_function(op_name: str) -> None:
    """Treat ``op_name`` as an fp16/bf16-whitelist op from now on."""
    _move(op_name, FP16_FUNCS)


def register_float_function(op_name: str) -> None:
    """Treat ``op_name`` as an fp32-blacklist op from now on."""
    _move(op_name, FP32_FUNCS)


def register_promote_function(op_name: str) -> None:
    """Treat ``op_name`` as a widest-type-promote op from now on."""
    _move(op_name, PROMOTE_FUNCS)
