"""Model/optimizer ingestion for amp.initialize.

The reference casts the model in place and patches its forward to cast
inputs/outputs (apex/amp/_initialize.py:150-268).  Functionally, the model
wrapper owns that behavior: ``AmpModel.init`` produces params already in
the opt-level's dtype (keeping batchnorm fp32 per keep_batchnorm_fp32, like
convert_network, apex/fp16_utils/fp16util.py:60-70), and ``AmpModel.apply``
casts inputs on entry / outputs on exit and installs the O1 cast policy for
the duration of the trace.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from . import policy as _policy
from ._amp_state import maybe_print
from ._process_optimizer import AmpOptimizer
from .frontend import Properties
from .scaler import LossScaler

# NOTE: apex_tpu.nn is imported lazily inside functions — nn.functional
# consults amp.policy at import time, so a module-level import here would
# be circular.

__all__ = ["AmpModel", "AmpOptimizer", "_initialize", "cast_param_tree"]


def cast_param_tree(module, params: dict, dtype,
                    keep_batchnorm_fp32: Optional[bool]) -> dict:
    """Cast a params tree to ``dtype``, skipping fp32-pinned modules
    (BatchNorm/LayerNorm) when keep_batchnorm_fp32 is truthy."""
    keep = bool(keep_batchnorm_fp32)

    def walk(mod, p: Any) -> Any:
        if not isinstance(p, dict):
            if keep and getattr(mod, "fp32_params", False):
                return p
            if jnp.issubdtype(jnp.result_type(p), jnp.floating):
                return p.astype(dtype)
            return p
        out = {}
        for k, v in p.items():
            child = mod._children.get(k)
            out[k] = walk(child, v) if child is not None else walk(mod, v)
        return out

    return walk(module, params)


def _cast_floats(tree: Any, dtype) -> Any:
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype)
        if isinstance(x, (jax.Array, jnp.ndarray)) and jnp.issubdtype(
            jnp.result_type(x), jnp.floating) else x, tree)


class AmpModel:
    """Policy-applying functional wrapper around an apex_tpu.nn.Module."""

    def __init__(self, module, properties: Properties,
                 disabled: bool = False):
        self.module = module
        self.properties = properties
        self.disabled = disabled

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> Tuple[dict, dict]:
        params, state = self.module.init(key)
        return self.cast_params(params), state

    def cast_params(self, params: dict) -> dict:
        props = self.properties
        ct = props.options.get("cast_model_type")
        if self.disabled or ct is None:
            return params
        if jnp.dtype(ct) == jnp.dtype(jnp.float32):
            return _cast_floats(params, jnp.float32)
        return cast_param_tree(self.module, params, ct,
                               props.keep_batchnorm_fp32)

    # -- forward -----------------------------------------------------------
    def _make_policy(self) -> _policy.Policy:
        if self.disabled or not self.properties.patch_torch_functions:
            return _policy.NoPolicy()
        return _policy.CastPolicy(self.properties.half_jnp_dtype)

    def apply(self, params: dict, *args, state: Optional[dict] = None,
              train: bool = False, rng: Optional[jax.Array] = None,
              mutable: bool = True, **kwargs):
        props = self.properties
        ct = None if self.disabled else props.options.get("cast_model_type")
        if ct is not None and jnp.dtype(ct) != jnp.dtype(jnp.float32):
            args = _cast_floats(args, ct)
            kwargs = _cast_floats(kwargs, ct)
        from ..nn import module as _nn_module
        with _policy.use_policy(self._make_policy()):
            out, new_state = _nn_module.apply(
                self.module, params, *args, state=state, train=train,
                rng=rng, mutable=mutable, **kwargs)
        co = None if self.disabled else props.options.get("cast_model_outputs")
        if co is not None:
            out = _cast_floats(out, co)
        elif ct is not None and jnp.dtype(ct) != jnp.dtype(jnp.float32):
            # O2/O3 cast model outputs back to fp32 (reference
            # _initialize.py:197-208) so losses run in fp32.
            out = _cast_floats(out, jnp.float32)
        return out, new_state

    __call__ = apply

    def __getattr__(self, name):
        return getattr(self.module, name)


def _wrap_optimizer(opt, props: Properties,
                    disabled: bool) -> AmpOptimizer:
    if isinstance(opt, AmpOptimizer):
        raise RuntimeError("amp.initialize should be called only once; "
                           "received an already-wrapped optimizer.")
    if disabled:
        scaler = LossScaler(1.0)
        return AmpOptimizer(opt, scaler, master_weights=False,
                            num_losses=props.num_losses)
    scaler = LossScaler(
        props.loss_scale if props.loss_scale is not None else "dynamic",
        min_loss_scale=props.min_loss_scale,
        max_loss_scale=props.max_loss_scale)
    master = bool(props.master_weights)
    return AmpOptimizer(opt, scaler, master_weights=master,
                        num_losses=props.num_losses)


def _initialize(model, optimizers, properties: Properties,
                disabled: bool = False):
    from ..nn.module import Module as _Module
    single_model = not isinstance(model, (list, tuple))
    models = [model] if single_model else list(model)
    for m in models:
        if isinstance(m, AmpModel):
            raise RuntimeError("amp.initialize should be called only once; "
                               "received an already-wrapped model.")
        if not isinstance(m, _Module):
            raise TypeError(
                f"amp.initialize expected an apex_tpu.nn.Module, got "
                f"{type(m).__name__}")

    wrapped_models = [AmpModel(m, properties, disabled) for m in models]

    if properties.patch_torch_functions and not disabled:
        # install the process-wide O1 policy, the analogue of amp.init()'s
        # monkey-patching (apex/amp/amp.py:68-177)
        _policy.set_policy(_policy.CastPolicy(properties.half_jnp_dtype))

    if optimizers is None:
        out_opt: Any = None
    else:
        single_opt = not isinstance(optimizers, (list, tuple))
        opts = [optimizers] if single_opt else list(optimizers)
        wrapped = [_wrap_optimizer(o, properties, disabled) for o in opts]
        out_opt = wrapped[0] if single_opt else wrapped

    out_model = wrapped_models[0] if single_model else wrapped_models
    if out_opt is None:
        return out_model
    return out_model, out_opt
