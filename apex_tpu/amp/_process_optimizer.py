"""AmpOptimizer: master weights, unscale, overflow-skip — functionally.

The reference performs in-place surgery on torch optimizers
(apex/amp/_process_optimizer.py): clones fp16 params to fp32 masters and
swaps them into param_groups (:13-73), patches ``step`` to copy masters
back to the model (:286-296), and installs pre/post-backward hooks that the
``scale_loss`` context drives (:76-239).  Here the same observable behavior
is a pure wrapper: masters are optimizer *state*, unscale+overflow-check is
the fused multi_tensor_scale, and a skipped step is a ``lax.cond`` that
leaves (params, masters, inner state) untouched — the whole thing lives
inside jit with no host sync.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .scaler import LossScaler, ScalerState
from ..optimizers.base import Optimizer

__all__ = ["AmpOptState", "AmpOptimizer"]


class AmpOptState(NamedTuple):
    inner: Any                     # wrapped optimizer's state
    masters: Any                   # fp32 master pytree, or None
    scalers: Tuple[ScalerState, ...]  # one per loss (num_losses)


def _to_fp32(tree):
    return jax.tree_util.tree_map(
        lambda p: p.astype(jnp.float32) if jnp.issubdtype(
            jnp.result_type(p), jnp.floating) else p, tree)


def _cast_like(tree, like):
    return jax.tree_util.tree_map(
        lambda x, l: x.astype(l.dtype) if jnp.issubdtype(
            jnp.result_type(l), jnp.floating) else x, tree, like)


class AmpOptimizer(Optimizer):
    """Wraps a base Optimizer with loss scaling and optional fp32 masters."""

    def __init__(self, inner: Optimizer, scaler: LossScaler,
                 master_weights: bool, num_losses: int = 1):
        self.inner = inner
        self.scaler = scaler
        self.master_weights = bool(master_weights)
        self.num_losses = int(num_losses)
        # eager/stateful-mode fields (see amp/stateful.py)
        self._bound = None

    # -- functional API ----------------------------------------------------
    def init(self, params: Any) -> AmpOptState:
        masters = _to_fp32(params) if self.master_weights else None
        inner_state = self.inner.init(masters if masters is not None else params)
        scalers = tuple(self.scaler.init_state()
                        for _ in range(self.num_losses))
        return AmpOptState(inner=inner_state, masters=masters,
                           scalers=scalers)

    def loss_scale(self, opt_state: AmpOptState, loss_id: int = 0):
        return opt_state.scalers[loss_id].loss_scale

    def step(self, params: Any = None, opt_state: AmpOptState = None,
             scaled_grads: Any = None, loss_id: int = 0,
             found_inf_extra: Optional[jax.Array] = None
             ) -> Tuple[Any, AmpOptState, dict]:
        """Unscale grads, update the scaler, apply-or-skip the inner update.

        ``scaled_grads`` are gradients of ``loss * loss_scale`` w.r.t. the
        *model* params.  ``found_inf_extra`` lets callers merge additional
        overflow sources (e.g. a pre-computed grad norm).
        Returns (new_params, new_opt_state, info).

        Called with no arguments in eager mode (after amp.stateful.bind +
        scale_loss/backward), it steps the bound state like torch's
        ``optimizer.step()``.
        """
        if params is None:
            if self._bound is None:
                raise RuntimeError("step() without arguments requires a "
                                   "bound optimizer (amp.stateful.bind)")
            return self._bound.step()
        sstate = opt_state.scalers[loss_id]
        grads32, found_inf = self.scaler.unscale(scaled_grads, sstate)
        if found_inf_extra is not None:
            found_inf = jnp.maximum(found_inf, found_inf_extra)
        new_sstate = self.scaler.update(sstate, found_inf)
        scalers = tuple(new_sstate if i == loss_id else s
                        for i, s in enumerate(opt_state.scalers))

        if opt_state.masters is not None:
            def do_update(operand):
                p, masters, inner = operand
                new_masters, new_inner = self.inner.update(
                    grads32, inner, masters)
                # master -> model copy (the reference's
                # _master_params_to_model_params, _process_optimizer.py:242-253)
                new_p = _cast_like(new_masters, p)
                return new_p, new_masters, new_inner
        else:
            def do_update(operand):
                p, masters, inner = operand
                new_p, new_inner = self.inner.update(
                    _cast_like(grads32, p), inner, p)
                return new_p, masters, new_inner

        def skip_update(operand):
            return operand

        new_params, new_masters, new_inner = jax.lax.cond(
            found_inf > 0, skip_update, do_update,
            (params, opt_state.masters, opt_state.inner))

        info = {"found_inf": found_inf,
                "loss_scale": new_sstate.loss_scale,
                "steps_skipped": new_sstate.steps_skipped}
        return new_params, AmpOptState(inner=new_inner, masters=new_masters,
                                       scalers=scalers), info

    # -- checkpoint (the amp.state_dict gap called out in SURVEY §5) -------
    def state_dict(self, opt_state: AmpOptState) -> dict:
        return {"scalers": [s._asdict() for s in opt_state.scalers]}

    def load_state_dict(self, opt_state: AmpOptState, sd: dict) -> AmpOptState:
        scalers = tuple(ScalerState(**{k: jnp.asarray(v) for k, v in d.items()})
                        for d in sd["scalers"])
        return opt_state._replace(scalers=scalers)

    # -- stateful-mode conveniences (amp/stateful.py fills these in) -------
    @property
    def masters(self):
        if self._bound is None:
            return None
        return self._bound.opt_state.masters
